"""Mask R-CNN (ref: S:dllib/models/maskrcnn — MaskRCNN.scala composing
the resnet backbone, FPN.scala, RegionProposal.scala, BoxHead.scala,
MaskHead.scala; SURVEY.md §2.3 model-zoo row calls it the zoo's hardest
model).

TPU-first formulation: a functional params-dict model (like
bigdl_tpu.llm.models) with **static shapes end-to-end** — fixed
proposal/detection counts with validity masks instead of the reference's
dynamic per-image tensors, so the whole inference path jits into one XLA
program. The detection ops (roi_align, nms, box codecs, anchors) live in
``bigdl_tpu.nn.layers.detection`` as reusable layers.

Layout NHWC (channels on the TPU lane dim). Scope: full inference path
(backbone → FPN → RPN proposals → box head → class-aware NMS → mask
head); training losses/sampling are out of scope this round (the
reference trains on COCO via its Spark mains — documented gap).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Dict, List, Sequence, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.layers.detection import (
    clip_boxes, decode_boxes, generate_anchors, nms, roi_align)


@dataclasses.dataclass
class MaskRCNNConfig:
    num_classes: int = 81                 # COCO: 80 + background
    image_size: int = 224                 # square input (static)
    backbone_channels: Tuple[int, ...] = (64, 128, 256, 512)
    fpn_channels: int = 64
    anchor_ratios: Tuple[float, ...] = (0.5, 1.0, 2.0)
    anchor_size_per_stride: float = 4.0   # anchor size = stride * this
    pre_nms_top_n: int = 256
    post_nms_top_n: int = 64
    rpn_nms_thresh: float = 0.7
    box_score_thresh: float = 0.05
    box_nms_thresh: float = 0.5
    detections_per_img: int = 16
    box_pool: int = 7
    mask_pool: int = 14
    mask_size: int = 28

    @classmethod
    def tiny(cls) -> "MaskRCNNConfig":
        return cls(num_classes=5, image_size=64,
                   backbone_channels=(8, 16, 32, 64), fpn_channels=16,
                   pre_nms_top_n=32, post_nms_top_n=8,
                   detections_per_img=4)

    @property
    def strides(self):
        return (4, 8, 16, 32)


# ---------------------------------------------------------------------------
# params
# ---------------------------------------------------------------------------

def _conv_p(key, k, c_in, c_out, scale=None):
    scale = scale or float(np.sqrt(2.0 / (k * k * c_in)))
    return {"w": jax.random.normal(key, (c_out, c_in, k, k),
                                   jnp.float32) * scale,
            "b": jnp.zeros((c_out,), jnp.float32)}


def _fc_p(key, n_in, n_out):
    return {"w": jax.random.normal(key, (n_out, n_in), jnp.float32)
            * float(np.sqrt(1.0 / n_in)),
            "b": jnp.zeros((n_out,), jnp.float32)}


def init_params(cfg: MaskRCNNConfig, seed: int = 0) -> Dict[str, Any]:
    key = jax.random.PRNGKey(seed)
    ks = iter(jax.random.split(key, 64))
    chans = cfg.backbone_channels
    f = cfg.fpn_channels
    a = len(cfg.anchor_ratios)
    params: Dict[str, Any] = {
        "stem": _conv_p(next(ks), 7, 3, chans[0]),
        "stages": [],
        "fpn_lateral": [], "fpn_out": [],
        "rpn": {"conv": _conv_p(next(ks), 3, f, f),
                "cls": _conv_p(next(ks), 1, f, a),
                "reg": _conv_p(next(ks), 1, f, a * 4)},
    }
    c_in = chans[0]
    for c in chans:
        params["stages"].append({
            "conv1": _conv_p(next(ks), 3, c_in, c),
            "conv2": _conv_p(next(ks), 3, c, c)})
        c_in = c
    for c in chans:
        params["fpn_lateral"].append(_conv_p(next(ks), 1, c, f))
        params["fpn_out"].append(_conv_p(next(ks), 3, f, f))
    p = cfg.box_pool
    params["box_head"] = {
        "fc1": _fc_p(next(ks), f * p * p, 4 * f),
        "fc2": _fc_p(next(ks), 4 * f, 4 * f),
        "cls": _fc_p(next(ks), 4 * f, cfg.num_classes),
        "reg": _fc_p(next(ks), 4 * f, cfg.num_classes * 4)}
    params["mask_head"] = {
        "convs": [_conv_p(next(ks), 3, f, f) for _ in range(4)],
        "deconv": _conv_p(next(ks), 2, f, f),
        "logits": _conv_p(next(ks), 1, f, cfg.num_classes)}
    return params


# ---------------------------------------------------------------------------
# building blocks (NHWC functional convs)
# ---------------------------------------------------------------------------

def _conv(p, x, stride=1, padding="SAME"):
    y = jax.lax.conv_general_dilated(
        x, p["w"].astype(x.dtype), (stride, stride), padding,
        dimension_numbers=("NHWC", "OIHW", "NHWC"))
    return y + p["b"].astype(x.dtype)


def _backbone(params, x) -> List[jnp.ndarray]:
    """stem(s2)+pool(s2) then 4 stages -> [C2(s4), C3(s8), C4(s16), C5(s32)]."""
    x = jax.nn.relu(_conv(params["stem"], x, stride=2))
    x = jax.lax.reduce_window(x, -jnp.inf, jax.lax.max, (1, 3, 3, 1),
                              (1, 2, 2, 1), "SAME")
    feats = []
    for i, sp in enumerate(params["stages"]):
        stride = 1 if i == 0 else 2
        x = jax.nn.relu(_conv(sp["conv1"], x, stride=stride))
        x = jax.nn.relu(_conv(sp["conv2"], x))
        feats.append(x)
    return feats


def _fpn(params, feats) -> List[jnp.ndarray]:
    """Top-down pathway with lateral 1x1s (ref FPN.scala) -> [P2..P5]."""
    lats = [_conv(lp, f) for lp, f in zip(params["fpn_lateral"], feats)]
    outs = [None] * len(lats)
    top = lats[-1]
    outs[-1] = _conv(params["fpn_out"][-1], top)
    for i in range(len(lats) - 2, -1, -1):
        b, h, w, c = lats[i].shape
        up = jax.image.resize(top, (b, h, w, c), method="nearest")
        top = lats[i] + up
        outs[i] = _conv(params["fpn_out"][i], top)
    return outs


def _fc(p, x):
    return x @ p["w"].T.astype(x.dtype) + p["b"].astype(x.dtype)


# ---------------------------------------------------------------------------
# heads
# ---------------------------------------------------------------------------

def _rpn_proposals(params, cfg: MaskRCNNConfig, pyramid, anchors_np):
    """Per-image fixed-size proposals from all FPN levels."""
    b = pyramid[0].shape[0]
    all_scores, all_deltas = [], []
    for feat in pyramid:
        t = jax.nn.relu(_conv(params["rpn"]["conv"], feat))
        cls = _conv(params["rpn"]["cls"], t)                 # (B,H,W,A)
        reg = _conv(params["rpn"]["reg"], t)                 # (B,H,W,A*4)
        all_scores.append(cls.reshape(b, -1))
        all_deltas.append(reg.reshape(b, -1, 4))
    scores = jnp.concatenate(all_scores, axis=1)             # (B, Na)
    deltas = jnp.concatenate(all_deltas, axis=1)             # (B, Na, 4)
    anchors = jnp.asarray(anchors_np)

    def per_image(sc, dl):
        k = min(cfg.pre_nms_top_n, sc.shape[0])
        top_sc, top_i = jax.lax.top_k(sc, k)
        boxes = decode_boxes(anchors[top_i], dl[top_i])
        boxes = clip_boxes(boxes, cfg.image_size, cfg.image_size)
        keep, valid = nms(boxes, top_sc, cfg.rpn_nms_thresh,
                          cfg.post_nms_top_n)
        return boxes[keep], jnp.where(valid, top_sc[keep], -jnp.inf), valid

    return jax.vmap(per_image)(scores, deltas)   # (B,P,4),(B,P),(B,P)


def _assign_levels(boxes: jnp.ndarray, n_levels: int) -> jnp.ndarray:
    """FPN level per box by sqrt(area) (ref Pooler level mapper)."""
    area = jnp.maximum(boxes[:, 2] - boxes[:, 0], 1.0) \
        * jnp.maximum(boxes[:, 3] - boxes[:, 1], 1.0)
    lvl = jnp.floor(2.0 + jnp.log2(jnp.sqrt(area) / 56.0))
    return jnp.clip(lvl, 0, n_levels - 1).astype(jnp.int32)


def _pyramid_roi_align(pyramid, cfg, boxes, batch_idx, out_size):
    """ROIAlign from the assigned FPN level (computed on every level,
    selected per box — static-shape formulation of the ref Pooler)."""
    lvl = _assign_levels(boxes, len(pyramid))
    pooled = None
    for i, feat in enumerate(pyramid):
        p_i = roi_align(feat, boxes, batch_idx, out_size,
                        spatial_scale=1.0 / cfg.strides[i])
        sel = (lvl == i).astype(p_i.dtype)[:, None, None, None]
        pooled = p_i * sel if pooled is None else pooled + p_i * sel
    return pooled


def forward(params: Dict[str, Any], cfg: MaskRCNNConfig,
            images: jnp.ndarray) -> Dict[str, jnp.ndarray]:
    """Inference: images (B, S, S, 3) → dict of fixed-shape detections:
    boxes (B, D, 4), scores (B, D), labels (B, D) int32 (0 = background /
    invalid slot), masks (B, D, M, M) sigmoid probabilities."""
    b = images.shape[0]
    feats = _backbone(params, images)
    pyramid = _fpn(params, feats)

    anchors_np = np.concatenate([
        generate_anchors(cfg.image_size // s, cfg.image_size // s, s,
                         [s * cfg.anchor_size_per_stride],
                         cfg.anchor_ratios)
        for s in cfg.strides])
    props, prop_scores, prop_valid = _rpn_proposals(params, cfg, pyramid,
                                                    anchors_np)

    # ---- box head over all images' proposals at once ----------------------
    P = props.shape[1]
    flat_boxes = props.reshape(-1, 4)
    flat_batch = jnp.repeat(jnp.arange(b, dtype=jnp.int32), P)
    pooled = _pyramid_roi_align(pyramid, cfg, flat_boxes, flat_batch,
                                cfg.box_pool)
    x = pooled.reshape(pooled.shape[0], -1)
    x = jax.nn.relu(_fc(params["box_head"]["fc1"], x))
    x = jax.nn.relu(_fc(params["box_head"]["fc2"], x))
    cls_logits = _fc(params["box_head"]["cls"], x)           # (BP, K)
    reg = _fc(params["box_head"]["reg"], x).reshape(
        -1, cfg.num_classes, 4)

    probs = jax.nn.softmax(cls_logits, axis=-1)
    # best non-background class per proposal
    fg = probs[:, 1:]
    best_c = jnp.argmax(fg, axis=1) + 1                      # (BP,)
    best_p = jnp.max(fg, axis=1)
    best_deltas = jnp.take_along_axis(
        reg, best_c[:, None, None], axis=1)[:, 0]
    det_boxes = clip_boxes(decode_boxes(flat_boxes, best_deltas),
                           cfg.image_size, cfg.image_size)
    det_boxes = det_boxes.reshape(b, P, 4)
    det_scores = jnp.where(prop_valid, best_p.reshape(b, P), -jnp.inf)
    det_labels = best_c.reshape(b, P)

    def per_image(boxes, sc, labels):
        # class-aware NMS: offset boxes by label so classes never suppress
        # each other (the standard batched-NMS trick)
        off = labels.astype(jnp.float32)[:, None] * (2.0 * cfg.image_size)
        keep, valid = nms(boxes + off, sc, cfg.box_nms_thresh,
                          cfg.detections_per_img)
        valid &= sc[keep] > cfg.box_score_thresh
        return (boxes[keep], jnp.where(valid, sc[keep], 0.0),
                jnp.where(valid, labels[keep], 0), valid)

    f_boxes, f_scores, f_labels, f_valid = jax.vmap(per_image)(
        det_boxes, det_scores, det_labels)

    # ---- mask head on the final detections --------------------------------
    D = f_boxes.shape[1]
    m_boxes = f_boxes.reshape(-1, 4)
    m_batch = jnp.repeat(jnp.arange(b, dtype=jnp.int32), D)
    mp = _pyramid_roi_align(pyramid, cfg, m_boxes, m_batch, cfg.mask_pool)
    for cp in params["mask_head"]["convs"]:
        mp = jax.nn.relu(_conv(cp, mp))
    # 2x deconv (ref: ConvTranspose 2x2 s2)
    mp = jax.lax.conv_transpose(
        mp, jnp.transpose(params["mask_head"]["deconv"]["w"],
                          (2, 3, 1, 0)).astype(mp.dtype),
        (2, 2), "SAME", dimension_numbers=("NHWC", "HWIO", "NHWC"))
    mp = jax.nn.relu(mp + params["mask_head"]["deconv"]["b"]
                     .astype(mp.dtype))
    mask_logits = _conv(params["mask_head"]["logits"], mp)   # (BD,M,M,K)
    lab = f_labels.reshape(-1)
    mask = jnp.take_along_axis(
        mask_logits, lab[:, None, None, None], axis=3)[..., 0]
    masks = jax.nn.sigmoid(mask).reshape(b, D, cfg.mask_size,
                                         cfg.mask_size)
    return {"boxes": f_boxes, "scores": f_scores,
            "labels": f_labels.astype(jnp.int32) * f_valid,
            "masks": masks}


class MaskRCNN:
    """Facade (ref API: models.maskrcnn.MaskRCNN(resolution=...))."""

    def __init__(self, cfg: MaskRCNNConfig = None, seed: int = 0):
        self.config = cfg or MaskRCNNConfig()
        self.params = init_params(self.config, seed)
        import functools
        self._fwd = jax.jit(functools.partial(forward, cfg=self.config))

    def __call__(self, images) -> Dict[str, np.ndarray]:
        out = self._fwd(self.params, images=jnp.asarray(images))
        return {k: np.asarray(v) for k, v in out.items()}

"""BERT on the bigdl_tpu nn stack (ref: BASELINE config 4 — Orca
Estimator BERT-base fine-tune; the reference runs HF BERT through torch
on Spark workers, P:orca/learn/pytorch/. Here BERT is a first-class nn
model so the SAME DistriOptimizer/mesh path that trains LeNet/ResNet
fine-tunes BERT on TPU — closing round 1's "Orca BERT never touches the
accelerator" gap).

Checkpoint interop: ``load_hf_bert_weights`` maps a HF
``bert-base-uncased``-family safetensors checkpoint onto this module tree.
"""

from __future__ import annotations

import dataclasses
from typing import Optional

import jax.numpy as jnp
import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.layers.attention import TransformerEncoderLayer
from bigdl_tpu.nn.module import Module
from bigdl_tpu.utils.table import Table


@dataclasses.dataclass
class BertConfig:
    vocab_size: int = 30522
    hidden_size: int = 768
    num_hidden_layers: int = 12
    num_attention_heads: int = 12
    intermediate_size: int = 3072
    max_position_embeddings: int = 512
    type_vocab_size: int = 2
    hidden_dropout_prob: float = 0.1
    layer_norm_eps: float = 1e-12

    @classmethod
    def base(cls) -> "BertConfig":
        return cls()

    @classmethod
    def tiny(cls, vocab: int = 64) -> "BertConfig":
        return cls(vocab_size=vocab, hidden_size=32, num_hidden_layers=2,
                   num_attention_heads=4, intermediate_size=64,
                   max_position_embeddings=64, hidden_dropout_prob=0.0)


def _split_bert_input(x):
    """token_ids | Table/tuple(token_ids[, segment_ids[, mask]])."""
    if isinstance(x, Table):
        vals = list(x.values())
    elif isinstance(x, (tuple, list)):
        vals = list(x)
    else:
        vals = [x]
    ids = vals[0]
    segs = vals[1] if len(vals) > 1 else None
    mask = vals[2] if len(vals) > 2 else None
    return ids, segs, mask


class BertEmbeddings(Module):
    def __init__(self, cfg: BertConfig, name: Optional[str] = None):
        super().__init__(name)
        self.cfg = cfg
        self._modules["word"] = nn.Embedding(cfg.vocab_size,
                                             cfg.hidden_size)
        self._modules["position"] = nn.Embedding(
            cfg.max_position_embeddings, cfg.hidden_size)
        self._modules["token_type"] = nn.Embedding(cfg.type_vocab_size,
                                                   cfg.hidden_size)
        self._modules["norm"] = nn.LayerNorm(cfg.hidden_size,
                                             eps=cfg.layer_norm_eps)
        self._modules["drop"] = nn.Dropout(cfg.hidden_dropout_prob)

    def _apply(self, params, states, x, *, training, rng):
        ids, segs, _ = _split_bert_input(x)
        b, t = ids.shape
        if segs is None:
            segs = jnp.zeros((b, t), jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(t, dtype=jnp.int32), (b, t))
        run, finalize = self.child_runner(params, states,
                                          training=training, rng=rng)
        h = run("word", ids) + run("position", pos) + run("token_type",
                                                          segs)
        h = run("drop", run("norm", h))
        return h, finalize()


class BertModel(Module):
    """Encoder + pooler. Output: Table(sequence_output, pooled_output)."""

    def __init__(self, cfg: BertConfig, name: Optional[str] = None):
        super().__init__(name)
        self.cfg = cfg
        self._modules["embeddings"] = BertEmbeddings(cfg)
        for i in range(cfg.num_hidden_layers):
            self._modules[f"layer{i}"] = TransformerEncoderLayer(
                cfg.hidden_size, cfg.num_attention_heads,
                cfg.intermediate_size, dropout=cfg.hidden_dropout_prob)
        self._modules["pooler"] = nn.Linear(cfg.hidden_size,
                                            cfg.hidden_size)
        self._modules["pooler_act"] = nn.Tanh()

    def _apply(self, params, states, x, *, training, rng):
        ids, segs, mask = _split_bert_input(x)
        run, finalize = self.child_runner(params, states,
                                          training=training, rng=rng)
        h = run("embeddings", x)
        for i in range(self.cfg.num_hidden_layers):
            h = run(f"layer{i}", (h, mask) if mask is not None else h)
        pooled = run("pooler_act", run("pooler", h[:, 0]))
        return Table(output=h, pooled=pooled), finalize()


class BertForSequenceClassification(Module):
    """BERT + classifier head; emits log-probs so ClassNLLCriterion (the
    canonical training loss here) applies directly."""

    def __init__(self, cfg: BertConfig, num_labels: int,
                 name: Optional[str] = None):
        super().__init__(name)
        self.cfg = cfg
        self.num_labels = num_labels
        self._modules["bert"] = BertModel(cfg)
        self._modules["drop"] = nn.Dropout(cfg.hidden_dropout_prob)
        self._modules["classifier"] = nn.Linear(cfg.hidden_size, num_labels)

    def _apply(self, params, states, x, *, training, rng):
        import jax

        run, finalize = self.child_runner(params, states,
                                          training=training, rng=rng)
        pooled = run("bert", x)["pooled"]
        logits = run("classifier", run("drop", pooled))
        return jax.nn.log_softmax(logits.astype(jnp.float32), -1), finalize()


def build_classifier(cfg: Optional[BertConfig] = None,
                     num_labels: int = 2) -> BertForSequenceClassification:
    return BertForSequenceClassification(cfg or BertConfig.base(),
                                         num_labels)


# ---------------------------------------------------------------------------
# HF checkpoint interop
# ---------------------------------------------------------------------------

_HF_LAYER_MAP = {
    "attention.self.query": ("attention", "q"),
    "attention.self.key": ("attention", "k"),
    "attention.self.value": ("attention", "v"),
    "attention.output.dense": ("attention", "out"),
    "attention.output.LayerNorm": ("attn_norm",),
    "intermediate.dense": ("ffn1",),
    "output.dense": ("ffn2",),
    "output.LayerNorm": ("ffn_norm",),
}


def load_hf_bert_weights(model: BertModel, path: str) -> BertModel:
    """Map a HF BERT safetensors checkpoint onto a :class:`BertModel`
    (names per transformers' bert-base; prefix-tolerant)."""
    import glob
    import os

    from safetensors import safe_open

    tensors: dict = {}
    for fname in sorted(glob.glob(os.path.join(path, "*.safetensors"))):
        with safe_open(fname, framework="numpy") as f:
            for k in f.keys():
                tensors[k.removeprefix("bert.")] = f.get_tensor(k)

    def get(name):
        return jnp.asarray(np.asarray(tensors[name], np.float32))

    p = model.parameters_dict()
    emb = p["embeddings"]
    emb["word"]["weight"] = get("embeddings.word_embeddings.weight")
    emb["position"]["weight"] = get(
        "embeddings.position_embeddings.weight")
    emb["token_type"]["weight"] = get(
        "embeddings.token_type_embeddings.weight")
    emb["norm"]["weight"] = get("embeddings.LayerNorm.weight")
    emb["norm"]["bias"] = get("embeddings.LayerNorm.bias")
    for i in range(model.cfg.num_hidden_layers):
        layer = p[f"layer{i}"]
        for hf_name, ours in _HF_LAYER_MAP.items():
            dst = layer
            for seg in ours:
                dst = dst[seg]
            base = f"encoder.layer.{i}.{hf_name}"
            dst["weight"] = get(f"{base}.weight")
            dst["bias"] = get(f"{base}.bias")
    p["pooler"]["weight"] = get("pooler.dense.weight")
    p["pooler"]["bias"] = get("pooler.dense.bias")
    model.load_parameters_dict(p)
    return model

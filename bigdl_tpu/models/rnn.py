"""Character/word-level RNN LM (ref: .../dllib/models/rnn/PTBModel.scala &
SimpleRNN example — LookupTable → Recurrent(cell) → TimeDistributed Linear
→ LogSoftMax)."""

from __future__ import annotations

import bigdl_tpu.nn as nn


def build_model(input_size: int = 100, hidden_size: int = 40,
                output_size: int = 100, cell: str = "rnn",
                num_layers: int = 1) -> nn.Sequential:
    cells = {"rnn": nn.RnnCell, "lstm": nn.LSTM, "gru": nn.GRU}
    if cell not in cells:
        raise ValueError(f"unknown cell {cell!r}")
    model = (nn.Sequential()
             .add(nn.LookupTable(input_size, hidden_size)))
    in_dim = hidden_size
    for _ in range(num_layers):
        mk = cells[cell]
        c = mk(in_dim, hidden_size) if cell != "rnn" else \
            mk(in_dim, hidden_size, "tanh")
        model.add(nn.Recurrent(c, return_sequences=True))
        in_dim = hidden_size
    return (model
            .add(nn.Linear(hidden_size, output_size))
            .add(nn.LogSoftMax()))

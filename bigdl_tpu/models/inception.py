"""Inception-v1 / GoogLeNet (ref: .../dllib/models/inception/Inception_v1.scala
— the BigDL paper's headline scaling benchmark model, BASELINE config 2).

Inception module = nn.Concat over four towers (1x1 / 1x1→3x3 / 1x1→5x5 /
pool→1x1), channel-concatenated — identical composition to the reference;
XLA fuses the towers."""

from __future__ import annotations

import bigdl_tpu.nn as nn


def _tower(*mods) -> nn.Sequential:
    seq = nn.Sequential()
    for m in mods:
        seq.add(m)
    return seq


def _conv(n_in, n_out, k, stride=1, pad=0) -> nn.Sequential:
    return (nn.Sequential()
            .add(nn.SpatialConvolution(n_in, n_out, k, k, stride, stride,
                                       pad, pad))
            .add(nn.ReLU()))


def inception_module(n_in: int, c1: int, c3r: int, c3: int, c5r: int,
                     c5: int, pool_proj: int) -> nn.Concat:
    """ref: Inception_Layer_v1(inputSize, config, namePrefix)."""
    return (nn.Concat(2)
            .add(_conv(n_in, c1, 1))
            .add(_tower(_conv(n_in, c3r, 1), _conv(c3r, c3, 3, 1, 1)))
            .add(_tower(_conv(n_in, c5r, 1), _conv(c5r, c5, 5, 1, 2)))
            .add(_tower(nn.SpatialMaxPooling(3, 3, 1, 1, 1, 1),
                        _conv(n_in, pool_proj, 1))))


def inception_v1(class_num: int = 1000) -> nn.Sequential:
    """GoogLeNet main trunk (no aux heads; ref Inception_v1_NoAuxClassifier)."""
    return (nn.Sequential()
            .add(_conv(3, 64, 7, 2, 3))
            .add(nn.SpatialMaxPooling(3, 3, 2, 2, -1, -1))
            .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
            .add(_conv(64, 64, 1))
            .add(_conv(64, 192, 3, 1, 1))
            .add(nn.SpatialCrossMapLRN(5, 0.0001, 0.75))
            .add(nn.SpatialMaxPooling(3, 3, 2, 2, -1, -1))
            .add(inception_module(192, 64, 96, 128, 16, 32, 32))    # 3a: 256
            .add(inception_module(256, 128, 128, 192, 32, 96, 64))  # 3b: 480
            .add(nn.SpatialMaxPooling(3, 3, 2, 2, -1, -1))
            .add(inception_module(480, 192, 96, 208, 16, 48, 64))   # 4a: 512
            .add(inception_module(512, 160, 112, 224, 24, 64, 64))  # 4b
            .add(inception_module(512, 128, 128, 256, 24, 64, 64))  # 4c
            .add(inception_module(512, 112, 144, 288, 32, 64, 64))  # 4d: 528
            .add(inception_module(528, 256, 160, 320, 32, 128, 128))  # 4e: 832
            .add(nn.SpatialMaxPooling(3, 3, 2, 2, -1, -1))
            .add(inception_module(832, 256, 160, 320, 32, 128, 128))  # 5a
            .add(inception_module(832, 384, 192, 384, 48, 128, 128))  # 5b:1024
            .add(nn.GlobalAveragePooling2D())
            .add(nn.Dropout(0.4))
            .add(nn.Linear(1024, class_num))
            .add(nn.LogSoftMax()))


build_model = inception_v1

"""bigdl_tpu — a TPU-native re-architecture of BigDL (yctai/BigDL).

A from-scratch framework on jax/XLA/pjit/Pallas providing the reference's
capabilities (see SURVEY.md):

- ``bigdl_tpu.tensor``  — Tensor facade over ``jax.Array``
  (ref: scala/dllib .../tensor/DenseTensor.scala).
- ``bigdl_tpu.nn``      — module contract + layer zoo + criterions
  (ref: scala/dllib .../nn/; hand-written backwards replaced by jax autodiff).
- ``bigdl_tpu.optim``   — Local/Distri optimizers, OptimMethods, Triggers,
  ValidationMethods (ref: .../optim/DistriOptimizer.scala, AllReduceParameter
  replaced by XLA collectives compiled into the SPMD step).
- ``bigdl_tpu.feature`` — DataSet/Sample/MiniBatch/transformers
  (ref: .../feature/dataset/).
- ``bigdl_tpu.keras``   — Keras-style API (ref: .../dllib/keras/).
- ``bigdl_tpu.models``  — model zoo (ref: .../dllib/models/).
- ``bigdl_tpu.orca``    — scale-out Estimator runtime (ref: python/orca).
- ``bigdl_tpu.chronos`` — time-series toolkit (ref: python/chronos).
- ``bigdl_tpu.llm``     — low-bit LLM inference (ref: python/llm, ggml kernels
  replaced by Pallas INT4/INT8 kernels).
- ``bigdl_tpu.parallel``— mesh / collectives / ring-attention building blocks
  (no reference equivalent: BigDL is DP-only; see SURVEY.md §2.5).
- ``bigdl_tpu.observability`` — metric registry (Prometheus exposition)
  + trace spans (Chrome-trace export); see docs/OBSERVABILITY.md.
- ``bigdl_tpu.reliability`` — fault-injection sites + retry/deadline/
  breaker/health policies behind the SoCC'19 survive-failures claim;
  see docs/RELIABILITY.md.
"""

from bigdl_tpu.version import __version__
from bigdl_tpu.utils.engine import Engine, init_engine, get_mesh
from bigdl_tpu.utils.table import Table, T

__all__ = [
    "__version__",
    "Engine",
    "init_engine",
    "get_mesh",
    "Table",
    "T",
]

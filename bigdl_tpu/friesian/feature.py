"""FeatureTable (ref: P:friesian/feature/table.py — a pyspark-DataFrame
wrapper with recsys feature engineering verbs; here the frame substrate is
pandas, the verbs keep the reference names/semantics)."""

from __future__ import annotations

from typing import Dict, List, Optional, Sequence, Tuple, Union

import numpy as np
import pandas as pd


def _as_list(c):
    return [c] if isinstance(c, str) else list(c)


class StringIndex:
    """Category → id mapping (ref: friesian StringIndex table)."""

    def __init__(self, mapping: pd.DataFrame, col_name: str):
        self.df = mapping          # columns: [col_name, "id"]
        self.col_name = col_name

    def to_dict(self) -> Dict:
        return dict(zip(self.df[self.col_name], self.df["id"]))


class FeatureTable:
    def __init__(self, df: pd.DataFrame):
        self.df = df.copy()

    # -- io ------------------------------------------------------------------
    @classmethod
    def read_csv(cls, path: str, **kwargs) -> "FeatureTable":
        return cls(pd.read_csv(path, **kwargs))

    @classmethod
    def read_parquet(cls, path: str, **kwargs) -> "FeatureTable":
        return cls(pd.read_parquet(path, **kwargs))

    def write_parquet(self, path: str):
        self.df.to_parquet(path)
        return self

    # -- basic verbs ---------------------------------------------------------
    def select(self, *cols) -> "FeatureTable":
        return FeatureTable(self.df[list(cols)])

    def drop(self, *cols) -> "FeatureTable":
        return FeatureTable(self.df.drop(columns=list(cols)))

    def rename(self, mapping: Dict[str, str]) -> "FeatureTable":
        return FeatureTable(self.df.rename(columns=mapping))

    def filter(self, condition) -> "FeatureTable":
        return FeatureTable(self.df[condition(self.df)])

    def fillna(self, value, columns: Union[str, Sequence[str], None]
               = None) -> "FeatureTable":
        df = self.df.copy()
        cols = _as_list(columns) if columns else df.columns
        df[cols] = df[cols].fillna(value)
        return FeatureTable(df)

    def dropna(self, columns=None) -> "FeatureTable":
        return FeatureTable(self.df.dropna(
            subset=_as_list(columns) if columns else None))

    def distinct(self) -> "FeatureTable":
        return FeatureTable(self.df.drop_duplicates())

    def size(self) -> int:
        return len(self.df)

    def to_pandas(self) -> pd.DataFrame:
        return self.df.copy()

    # -- recsys feature engineering (ref verbs) ------------------------------
    def encode_string(self, columns: Union[str, Sequence[str]],
                      indices: Optional[Sequence[StringIndex]] = None
                      ) -> Tuple["FeatureTable", List[StringIndex]]:
        """Map string categories to 1-based int ids (ref: encode_string —
        id 0 is reserved for OOV/missing)."""
        cols = _as_list(columns)
        df = self.df.copy()
        out_indices = []
        for i, c in enumerate(cols):
            if indices is not None:
                mapping = indices[i].to_dict()
            else:
                cats = pd.unique(df[c].dropna())
                mapping = {v: j + 1 for j, v in enumerate(cats)}
                out_indices.append(StringIndex(
                    pd.DataFrame({c: list(mapping), "id":
                                  list(mapping.values())}), c))
            df[c] = df[c].map(mapping).fillna(0).astype(np.int64)
        return FeatureTable(df), (list(indices) if indices is not None
                                  else out_indices)

    def category_encode(self, columns) -> Tuple["FeatureTable",
                                                List[StringIndex]]:
        return self.encode_string(columns)

    def cross_columns(self, crossed_columns: Sequence[Sequence[str]],
                      bucket_sizes: Sequence[int]) -> "FeatureTable":
        """Hash-cross column tuples into buckets (ref: cross_columns)."""
        df = self.df.copy()
        for cols, bucket in zip(crossed_columns, bucket_sizes):
            name = "_".join(cols)
            key = df[cols[0]].astype(str)
            for c in cols[1:]:
                key = key + "_" + df[c].astype(str)
            df[name] = key.map(lambda s: hash(s) % bucket)
        return FeatureTable(df)

    def min_max_scale(self, columns) -> Tuple["FeatureTable", Dict]:
        cols = _as_list(columns)
        df = self.df.copy()
        stats = {}
        for c in cols:
            lo, hi = float(df[c].min()), float(df[c].max())
            rng = (hi - lo) or 1.0
            df[c] = (df[c] - lo) / rng
            stats[c] = (lo, hi)
        return FeatureTable(df), stats

    def add_negative_samples(self, item_size: int, item_col: str = "item",
                             label_col: str = "label",
                             neg_num: int = 1,
                             seed: int = 0) -> "FeatureTable":
        """For each positive row, append neg_num rows with random items and
        label 0 (ref: add_negative_samples; items are 1-based)."""
        rs = np.random.RandomState(seed)
        df = self.df.copy()
        df[label_col] = 1
        negs = df.loc[df.index.repeat(neg_num)].copy()
        negs[item_col] = rs.randint(1, item_size + 1, len(negs))
        negs[label_col] = 0
        out = pd.concat([df, negs], ignore_index=True)
        return FeatureTable(out)

    def gen_hist_seq(self, user_col: str, cols: Union[str, Sequence[str]],
                     sort_col: Optional[str] = None,
                     min_len: int = 1, max_len: int = 10) -> "FeatureTable":
        """Per-user rolling history of past items (ref: gen_his_seq)."""
        cols = _as_list(cols)
        df = self.df.sort_values(
            [user_col] + ([sort_col] if sort_col else []))
        rows = []
        for _, g in df.groupby(user_col, sort=False):
            vals = {c: g[c].tolist() for c in cols}
            for i in range(len(g)):
                if i < min_len:
                    continue
                rec = g.iloc[i].to_dict()
                for c in cols:
                    rec[f"{c}_hist_seq"] = vals[c][max(0, i - max_len):i]
                rows.append(rec)
        return FeatureTable(pd.DataFrame(rows))

    def pad(self, columns, seq_len: int = 10,
            mask_token: int = 0) -> "FeatureTable":
        cols = _as_list(columns)
        df = self.df.copy()
        for c in cols:
            df[c] = df[c].map(
                lambda s: (list(s)[:seq_len]
                           + [mask_token] * max(0, seq_len - len(s))))
        return FeatureTable(df)

    def apply(self, in_col: str, out_col: str, fn) -> "FeatureTable":
        df = self.df.copy()
        df[out_col] = df[in_col].map(fn)
        return FeatureTable(df)

    def join(self, other: "FeatureTable", on: Union[str, Sequence[str]],
             how: str = "inner") -> "FeatureTable":
        return FeatureTable(self.df.merge(other.df, on=on, how=how))

    def group_by(self, columns, agg: Dict[str, str]) -> "FeatureTable":
        out = self.df.groupby(_as_list(columns)).agg(agg).reset_index()
        out.columns = ["_".join(c) if isinstance(c, tuple) else c
                       for c in out.columns]
        return FeatureTable(out)

"""bigdl_tpu.friesian — recommender toolkit (ref: python/friesian offline
FeatureTable + scala online recall/ranking services)."""

from bigdl_tpu.friesian.feature import FeatureTable
from bigdl_tpu.friesian.recall import BruteForceRecall
from bigdl_tpu.friesian.serving import (
    FeatureService, RankingService, RecallService, RecommenderService,
    ServiceClient)

__all__ = ["FeatureTable", "BruteForceRecall", "FeatureService",
           "RankingService", "RecallService", "RecommenderService",
           "ServiceClient"]

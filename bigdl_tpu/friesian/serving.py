"""Friesian online serving services (ref: scala friesian serving —
recall / feature / ranking / recommender gRPC services, SURVEY.md §2.8;
round 1 had only the recall index).

Transport: the same data-only length-prefixed wire format as the FL layer
(``bigdl_tpu.ppml.protocol`` — JSON structure + raw numpy buffers; the
gRPC/protobuf role without code-execution-on-decode). Each service runs as
a threaded TCP server and also exposes its logic in-process, so the
recommender can compose services either over sockets (the reference's
deployment shape) or directly (tests / single-host).

Pipeline (ref recommender flow):
  user id → FeatureService (user features + history)
          → RecallService (candidate item ids)
          → FeatureService (item features)
          → RankingService (InferenceModel scores)
          → top-k item ids
"""

from __future__ import annotations

import socket
import threading
from typing import Any, Callable, Dict, List, Optional, Sequence

import numpy as np

from bigdl_tpu.ppml.protocol import recv_msg, send_msg


# ---------------------------------------------------------------------------
# service base: threaded TCP endpoint over the safe wire format
# ---------------------------------------------------------------------------

class _TcpService:
    """Request/response server: one message in, one message out."""

    def __init__(self, host: str = "127.0.0.1", port: int = 0):
        self._sock = socket.socket(socket.AF_INET, socket.SOCK_STREAM)
        self._sock.setsockopt(socket.SOL_SOCKET, socket.SO_REUSEADDR, 1)
        self._sock.bind((host, port))
        self.host, self.port = self._sock.getsockname()[:2]
        self._stop = threading.Event()
        self._accept_thread: Optional[threading.Thread] = None
        self._conn_lock = threading.Lock()
        #: live (thread, socket) handler pairs — stop() severs the
        #: sockets so handlers blocked in recv_msg actually exit
        self._conns: List[tuple] = []

    def start(self):
        self._sock.listen()
        self._accept_thread = threading.Thread(target=self._accept_loop,
                                               daemon=True)
        self._accept_thread.start()
        return self

    def _accept_loop(self):
        while not self._stop.is_set():
            try:
                conn, _ = self._sock.accept()
            except OSError:
                return
            t = threading.Thread(target=self._serve, args=(conn,),
                                 daemon=True)
            t.start()
            # sweep finished handlers so a long-lived service doesn't
            # accumulate one dead pair per past connection
            with self._conn_lock:
                self._conns = [(c, s) for c, s in self._conns
                               if c.is_alive()]
                self._conns.append((t, conn))

    def _serve(self, conn: socket.socket):
        try:
            while not self._stop.is_set():
                try:
                    msg = recv_msg(conn)
                except (ValueError, TypeError, KeyError) as e:
                    send_msg(conn, {"status": "error",
                                    "error": f"malformed message: {e}"})
                    return
                try:
                    send_msg(conn, {"status": "ok",
                                    **self.handle(msg)})
                except Exception as e:
                    send_msg(conn, {"status": "error", "error": repr(e)})
        except (ConnectionError, OSError):
            pass
        finally:
            conn.close()

    def handle(self, msg: dict) -> dict:
        raise NotImplementedError

    def stop(self):
        self._stop.set()
        try:
            # shutdown BEFORE close: on Linux, close() alone does not
            # wake a thread blocked in accept(); shutdown() does
            self._sock.shutdown(socket.SHUT_RDWR)
        except OSError:
            pass
        try:
            self._sock.close()
        except OSError:
            pass
        if self._accept_thread is not None:
            self._accept_thread.join(timeout=5.0)
        with self._conn_lock:
            pending, self._conns = self._conns, []
        for t, conn in pending:
            # sever the client socket first: a handler blocked in
            # recv_msg on an idle-but-connected client only notices
            # _stop between messages — without this every join below
            # would burn its full timeout and leak the thread
            try:
                conn.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                conn.close()
            except OSError:
                pass
        for t, _ in pending:
            t.join(timeout=1.0)

    @property
    def target(self) -> str:
        return f"{self.host}:{self.port}"


class ServiceClient:
    """Blocking client for any :class:`_TcpService`."""

    def __init__(self, target: str):
        host, port = target.rsplit(":", 1)
        self._sock = socket.create_connection((host, int(port)))
        self._lock = threading.Lock()

    def call(self, msg: dict) -> dict:
        with self._lock:
            send_msg(self._sock, msg)
            resp = recv_msg(self._sock)
        if resp.get("status") != "ok":
            raise RuntimeError(f"service error: {resp.get('error')}")
        return resp

    def close(self):
        self._sock.close()


# ---------------------------------------------------------------------------
# feature service (ref: friesian feature service over redis kv)
# ---------------------------------------------------------------------------

class FeatureService(_TcpService):
    """In-memory kv feature store keyed by entity id (the redis role)."""

    def __init__(self, **kw):
        super().__init__(**kw)
        self._user: Dict[int, np.ndarray] = {}
        self._item: Dict[int, np.ndarray] = {}

    def load_user_features(self, ids: Sequence[int],
                           feats: np.ndarray):
        for i, f in zip(ids, np.asarray(feats)):
            self._user[int(i)] = np.asarray(f, np.float32)
        return self

    def load_item_features(self, ids: Sequence[int], feats: np.ndarray):
        for i, f in zip(ids, np.asarray(feats)):
            self._item[int(i)] = np.asarray(f, np.float32)
        return self

    def get_features(self, kind: str, ids: Sequence[int]) -> np.ndarray:
        if kind not in ("user", "item"):
            raise ValueError(f"unknown feature kind {kind!r} "
                             f"(expected 'user' or 'item')")
        store = self._user if kind == "user" else self._item
        return np.stack([store[int(i)] for i in ids])

    def handle(self, msg: dict) -> dict:
        ids = np.asarray(msg["ids"]).ravel().tolist()
        return {"features": self.get_features(msg["kind"], ids)}


# ---------------------------------------------------------------------------
# recall service (faiss role — over the BruteForceRecall index)
# ---------------------------------------------------------------------------

class RecallService(_TcpService):
    def __init__(self, dim: int, metric: str = "ip", **kw):
        super().__init__(**kw)
        from bigdl_tpu.friesian.recall import BruteForceRecall
        self.index = BruteForceRecall(dim, metric=metric)

    def add_items(self, embeddings: np.ndarray):
        self.index.add(np.asarray(embeddings, np.float32))
        return self

    def recall(self, query: np.ndarray, k: int) -> np.ndarray:
        _, idx = self.index.search(np.asarray(query, np.float32)[None], k)
        return idx[0]

    def handle(self, msg: dict) -> dict:
        return {"ids": self.recall(msg["query"], int(msg["k"]))}


# ---------------------------------------------------------------------------
# ranking service (InferenceModel scoring role)
# ---------------------------------------------------------------------------

class RankingService(_TcpService):
    """Scores (user, item) feature pairs with a compiled InferenceModel."""

    def __init__(self, inference_model=None,
                 score_fn: Optional[Callable] = None, **kw):
        super().__init__(**kw)
        if (inference_model is None) == (score_fn is None):
            raise ValueError("pass exactly one of inference_model/score_fn")
        self.model = inference_model
        self.score_fn = score_fn

    def rank(self, user_feat: np.ndarray,
             item_feats: np.ndarray) -> np.ndarray:
        n = item_feats.shape[0]
        x = np.concatenate(
            [np.broadcast_to(user_feat, (n,) + user_feat.shape),
             item_feats], axis=-1).astype(np.float32)
        if self.score_fn is not None:
            scores = self.score_fn(x)
        else:
            scores = self.model.do_predict(x)
        return np.asarray(scores).reshape(n)

    def handle(self, msg: dict) -> dict:
        return {"scores": self.rank(np.asarray(msg["user"]),
                                    np.asarray(msg["items"]))}


# ---------------------------------------------------------------------------
# recommender (orchestrates the pipeline)
# ---------------------------------------------------------------------------

class RecommenderService(_TcpService):
    """recall → features → rank → top-k (the reference's recommender
    service composing the three backends over gRPC)."""

    def __init__(self, feature: "FeatureService | str",
                 recall: "RecallService | str",
                 ranking: "RankingService | str",
                 item_ids: Optional[Sequence[int]] = None, **kw):
        super().__init__(**kw)
        self._feature = (ServiceClient(feature)
                         if isinstance(feature, str) else feature)
        self._recall = (ServiceClient(recall)
                        if isinstance(recall, str) else recall)
        self._ranking = (ServiceClient(ranking)
                         if isinstance(ranking, str) else ranking)
        # recall returns positional indices; map to item ids when given
        self._item_ids = (None if item_ids is None
                          else np.asarray(item_ids, np.int64))

    # -- backend dispatch (in-proc object or remote client) ------------------
    def _get_feats(self, kind, ids):
        if isinstance(self._feature, ServiceClient):
            return np.asarray(self._feature.call(
                {"kind": kind, "ids": np.asarray(ids)})["features"])
        return self._feature.get_features(kind, ids)

    def _do_recall(self, query, k):
        if isinstance(self._recall, ServiceClient):
            return np.asarray(self._recall.call(
                {"query": np.asarray(query), "k": k})["ids"])
        return self._recall.recall(query, k)

    def _do_rank(self, user, items):
        if isinstance(self._ranking, ServiceClient):
            return np.asarray(self._ranking.call(
                {"user": user, "items": items})["scores"])
        return self._ranking.rank(user, items)

    def recommend(self, user_id: int, k: int = 10,
                  candidate_num: int = 50) -> List[int]:
        user_feat = self._get_feats("user", [user_id])[0]
        cand_idx = self._do_recall(user_feat, candidate_num)
        cand_ids = (cand_idx if self._item_ids is None
                    else self._item_ids[cand_idx])
        item_feats = self._get_feats("item", cand_ids)
        scores = self._do_rank(user_feat, item_feats)
        order = np.argsort(-scores)[:k]
        return [int(i) for i in np.asarray(cand_ids)[order]]

    def handle(self, msg: dict) -> dict:
        return {"ids": np.asarray(self.recommend(
            int(msg["user_id"]), int(msg.get("k", 10)),
            int(msg.get("candidate_num", 50))), np.int64)}

"""Vector recall (ref: friesian online recall service — faiss similarity
search behind gRPC). TPU-native design: brute-force inner-product top-k
IS the fast path on the MXU — a (batch, dim) x (dim, n_items) matmul +
jax.lax.top_k beats an IVF index for corpus sizes that fit HBM, with
exact results."""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


class BruteForceRecall:
    def __init__(self, dim: int, metric: str = "ip"):
        self.dim = dim
        self.metric = metric
        self._items = None
        self._search = None

    def add(self, embeddings: np.ndarray):
        emb = jnp.asarray(np.asarray(embeddings, np.float32))
        if self.metric == "l2":
            self._sq = jnp.sum(emb * emb, axis=1)
        if self.metric == "cosine":
            emb = emb / (jnp.linalg.norm(emb, axis=1, keepdims=True)
                         + 1e-12)
        self._items = emb

        metric = self.metric
        sq = getattr(self, "_sq", None)
        from functools import partial

        @partial(jax.jit, static_argnums=1)
        def search(q, k):
            if metric == "l2":
                scores = -(sq[None, :]
                           - 2 * (q @ emb.T)
                           + jnp.sum(q * q, axis=1, keepdims=True))
            else:
                qq = q
                if metric == "cosine":
                    qq = q / (jnp.linalg.norm(q, axis=1, keepdims=True)
                              + 1e-12)
                scores = qq @ emb.T
            return jax.lax.top_k(scores, k)

        self._search_fn = search
        return self

    def search(self, queries: np.ndarray,
               k: int = 10) -> Tuple[np.ndarray, np.ndarray]:
        """Returns (scores (B, k), indices (B, k))."""
        if self._items is None:
            raise RuntimeError("add() embeddings first")
        q = jnp.asarray(np.atleast_2d(np.asarray(queries, np.float32)))
        scores, idx = self._search_fn(q, k)
        return np.asarray(scores), np.asarray(idx)

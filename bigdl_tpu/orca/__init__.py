"""bigdl_tpu.orca — scale-out runtime (ref: python/orca).

The reference's Orca turns a Spark/Ray cluster into a scale-out substrate
for foreign frameworks: ``init_orca_context`` builds the cluster,
``XShards`` partitions data across it, per-backend ``Estimator``s run each
framework's training loop on the workers (SURVEY.md §2.7). Here the
substrate is the jax device mesh: ``init_orca_context`` wires
``Engine.init`` (host process ↔ TPU chips), XShards partitions map onto
the ``data`` mesh axis, and the Estimator backends are:

- ``bigdl`` — our nn/keras models through DistriOptimizer (SPMD);
- ``torch`` — foreign-framework hosting: a real torch (CPU) train loop
  driven shard-by-shard, mirroring the reference's TorchRunner-per-
  partition design (torch has no TPU backend here; parity, not perf).
"""

from bigdl_tpu.orca.common import (
    OrcaContext, init_orca_context, stop_orca_context)
from bigdl_tpu.orca.data import XShards
from bigdl_tpu.orca.ray_pool import (
    RayContext, RemoteError, init_ray_on_spark)

__all__ = ["init_orca_context", "stop_orca_context", "OrcaContext",
           "XShards", "RayContext", "RemoteError", "init_ray_on_spark"]

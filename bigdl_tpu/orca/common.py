"""init_orca_context (ref: P:orca/common/__init__.py — creates the
SparkContext (+Ray) for cluster_mode local/yarn/k8s; here: Engine/mesh)."""

from __future__ import annotations

import logging
from typing import Optional

logger = logging.getLogger("bigdl_tpu.orca")

_context: Optional["OrcaContext"] = None


class OrcaContext:
    def __init__(self, cluster_mode: str, cores: Optional[int],
                 num_nodes: int):
        import jax

        from bigdl_tpu.utils.engine import Engine

        self.cluster_mode = cluster_mode
        engine_type = "cpu" if cluster_mode == "local-cpu" else None
        Engine.init(engine_type=engine_type)
        self.mesh = Engine.mesh()
        self.num_devices = len(jax.devices())
        self.num_nodes = num_nodes
        self.cores = cores

    def __repr__(self):
        return (f"OrcaContext(mode={self.cluster_mode}, "
                f"devices={self.num_devices})")


def init_orca_context(cluster_mode: str = "local", cores: Optional[int]
                      = None, num_nodes: int = 1, memory: str = "2g",
                      init_ray_on_spark: bool = False,
                      **kwargs) -> OrcaContext:
    """ref signature kept; Spark/Ray-only kwargs accepted and ignored with
    a log line (memory, conda archives, extra python libs...)."""
    global _context
    if kwargs:
        logger.info("orca: ignoring Spark/Ray-specific kwargs %s",
                    sorted(kwargs))
    _context = OrcaContext(cluster_mode, cores, num_nodes)
    return _context


def get_orca_context() -> OrcaContext:
    if _context is None:
        raise RuntimeError("call init_orca_context() first")
    return _context


def stop_orca_context():
    global _context
    _context = None

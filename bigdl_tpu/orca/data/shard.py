"""XShards (ref: P:orca/data/shard.py — SparkXShards: an RDD of
dict-of-numpy partitions with transform_shard/repartition/collect).

Here a shard list lives in the driver process and partitions map onto the
mesh ``data`` axis at fit time (the reference pins partitions to Spark
executors; we pin them to chips via batch sharding)."""

from __future__ import annotations

from typing import Any, Callable, Dict, List, Sequence, Union

import numpy as np


class XShards:
    """List of partitions; each partition is a dict of numpy arrays,
    a pandas DataFrame, or an arbitrary python object."""

    def __init__(self, partitions: List[Any]):
        self._parts = list(partitions)

    # -- construction --------------------------------------------------------
    @staticmethod
    def partition(data: Union[Dict[str, np.ndarray], np.ndarray, tuple],
                  num_shards: int = 4) -> "XShards":
        """ref: XShards.partition — split dict-of-numpy along dim 0."""
        def split(arr):
            return np.array_split(np.asarray(arr), num_shards)

        if isinstance(data, dict):
            pieces = {k: split(v) for k, v in data.items()}
            parts = [{k: pieces[k][i] for k in data}
                     for i in range(num_shards)]
        elif isinstance(data, tuple):
            cols = [split(v) for v in data]
            parts = [tuple(c[i] for c in cols) for i in range(num_shards)]
        else:
            parts = split(data)
        return XShards(parts)

    # -- transformations -----------------------------------------------------
    def transform_shard(self, fn: Callable, *args) -> "XShards":
        return XShards([fn(p, *args) for p in self._parts])

    def repartition(self, num_partitions: int) -> "XShards":
        """Best effort: re-split dict-of-numpy / array shards evenly."""
        first = self._parts[0]
        if isinstance(first, dict):
            merged = {k: np.concatenate([np.asarray(p[k])
                                         for p in self._parts])
                      for k in first}
            return XShards.partition(merged, num_partitions)
        merged = np.concatenate([np.asarray(p) for p in self._parts])
        return XShards.partition(merged, num_partitions)

    # -- access --------------------------------------------------------------
    def collect(self) -> List[Any]:
        return list(self._parts)

    def num_partitions(self) -> int:
        return len(self._parts)

    def __len__(self):
        return len(self._parts)

    def merged(self):
        """Concatenate all partitions (driver-side)."""
        first = self._parts[0]
        if isinstance(first, dict):
            return {k: np.concatenate([np.asarray(p[k])
                                       for p in self._parts])
                    for k in first}
        if isinstance(first, tuple):
            n = len(first)
            return tuple(np.concatenate([np.asarray(p[i])
                                         for p in self._parts])
                         for i in range(n))
        return np.concatenate([np.asarray(p) for p in self._parts])


def read_csv(path: str, num_shards: int = 4, **kwargs) -> XShards:
    """ref: orca.data.pandas.read_csv → shards of DataFrames."""
    import glob

    import pandas as pd

    files = sorted(glob.glob(path)) or [path]
    dfs = [pd.read_csv(f, **kwargs) for f in files]
    df = pd.concat(dfs, ignore_index=True)
    return XShards(_split_df(df, num_shards))


def read_parquet(path: str, num_shards: int = 4, **kwargs) -> XShards:
    import glob

    import pandas as pd

    files = sorted(glob.glob(path)) or [path]
    df = pd.concat([pd.read_parquet(f, **kwargs) for f in files],
                   ignore_index=True)
    return XShards(_split_df(df, num_shards))


def _split_df(df, num_shards: int):
    """Row-range split (np.array_split on a DataFrame coerces to ndarray
    on pandas 3.x)."""
    bounds = np.linspace(0, len(df), num_shards + 1, dtype=int)
    return [df.iloc[a:b].reset_index(drop=True)
            for a, b in zip(bounds[:-1], bounds[1:])]

from bigdl_tpu.orca.data.shard import XShards, read_csv, read_parquet

__all__ = ["XShards", "read_csv", "read_parquet"]

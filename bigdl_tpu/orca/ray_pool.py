"""RayContext — the RayOnSpark architectural role on stdlib processes
(ref: P:orca/ray/raycontext.py; SURVEY §2.7 row 49. VERDICT r3 missing
#6: the substrate — Spark executors hosting Ray workers — is absent
from this environment, but the ROLE, a multi-process worker pool under
one orchestrator dispatching pickled tasks, is exactly reproducible
with ``multiprocessing`` spawn workers).

API shape follows Ray's surface the way the reference uses it:

    ctx = RayContext(num_workers=4).start()
    ref = ctx.remote(fn)(args)        # -> ObjectRef
    ctx.get(ref)                      # block for the result
    ctx.map(fn, items)                # parallel map
    ctx.stop()

Workers are **spawned** (never forked — a forked TPU client would share
the parent's device state) and pin themselves to the CPU backend before
any user code runs; task payloads travel as cloudpickle blobs so
closures and lambdas work like Ray remotes.
"""

from __future__ import annotations

import itertools
import multiprocessing as mp
import os
import threading
import traceback
from typing import Any, Callable, Dict, Iterable, List, Optional

import cloudpickle


def _worker_main(task_q, result_q):
    os.environ["XLA_FLAGS"] = (os.environ.get("XLA_FLAGS", "")
                               + " --xla_force_host_platform_device_count=1"
                               ).strip()
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:           # noqa: BLE001 — jax-less tasks still run
        pass
    while True:
        item = task_q.get()
        if item is None:
            return
        task_id, blob = item
        try:
            fn, args, kwargs = cloudpickle.loads(blob)
            out = fn(*args, **kwargs)
            result_q.put((task_id, True, cloudpickle.dumps(out)))
        except BaseException as e:   # noqa: BLE001 — report, don't die
            result_q.put((task_id, False,
                          cloudpickle.dumps(
                              (type(e).__name__, str(e),
                               traceback.format_exc()))))


class ObjectRef:
    def __init__(self, task_id: int):
        self.task_id = task_id
        self._event = threading.Event()
        self._ok: Optional[bool] = None
        self._blob: Optional[bytes] = None


class RemoteError(RuntimeError):
    pass


class _RemoteFn:
    def __init__(self, ctx: "RayContext", fn: Callable):
        self._ctx = ctx
        self._fn = fn

    def __call__(self, *args, **kwargs) -> ObjectRef:
        return self._ctx._submit(self._fn, args, kwargs)

    remote = __call__       # ray spelling: f.remote(...)


class RayContext:
    def __init__(self, num_workers: int = 2):
        self.num_workers = num_workers
        self._mp = mp.get_context("spawn")
        self._task_q = self._mp.Queue()
        self._result_q = self._mp.Queue()
        self._procs: List[Any] = []
        self._refs: Dict[int, ObjectRef] = {}
        self._ids = itertools.count()
        self._lock = threading.Lock()
        self._collector: Optional[threading.Thread] = None
        self._stopped = threading.Event()

    # -- lifecycle -----------------------------------------------------------
    def start(self) -> "RayContext":
        import sys

        # spawn children re-import the parent's __main__ from
        # __main__.__file__; a stdin/REPL parent ('<stdin>') has no
        # importable main and the child dies in bootstrap. Hide the
        # phantom path during start — task payloads never need it
        # (cloudpickle serializes __main__ functions by value).
        main = sys.modules.get("__main__")
        saved = getattr(main, "__file__", None)
        if (main is not None and saved is not None
                and not os.path.exists(saved)):
            del main.__file__
        try:
            for _ in range(self.num_workers):
                p = self._mp.Process(target=_worker_main,
                                     args=(self._task_q, self._result_q),
                                     daemon=True)
                p.start()
                self._procs.append(p)
        finally:
            if saved is not None and not hasattr(main, "__file__"):
                main.__file__ = saved
        self._collector = threading.Thread(target=self._collect,
                                           daemon=True)
        self._collector.start()
        return self

    def stop(self):
        self._stopped.set()
        for _ in self._procs:
            self._task_q.put(None)
        for p in self._procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
        self._procs = []
        if self._collector is not None:
            # exits within its 0.2 s result-queue poll of _stopped
            self._collector.join(timeout=5.0)
            self._collector = None

    def _collect(self):
        while not self._stopped.is_set():
            try:
                task_id, ok, blob = self._result_q.get(timeout=0.2)
            except Exception:        # noqa: BLE001 — queue timeout
                continue
            with self._lock:
                ref = self._refs.pop(task_id, None)
            if ref is not None:
                ref._ok, ref._blob = ok, blob
                ref._event.set()

    # -- task API ------------------------------------------------------------
    def remote(self, fn: Callable) -> _RemoteFn:
        return _RemoteFn(self, fn)

    def _submit(self, fn, args, kwargs) -> ObjectRef:
        if not self._procs:
            raise RuntimeError("RayContext not started")
        task_id = next(self._ids)
        ref = ObjectRef(task_id)
        with self._lock:
            self._refs[task_id] = ref
        self._task_q.put((task_id, cloudpickle.dumps((fn, args, kwargs))))
        return ref

    def get(self, ref, timeout: Optional[float] = None):
        if isinstance(ref, (list, tuple)):
            return [self.get(r, timeout) for r in ref]
        if not ref._event.wait(timeout):
            raise TimeoutError(f"task {ref.task_id} still running")
        if not ref._ok:
            name, msg, tb = cloudpickle.loads(ref._blob)
            raise RemoteError(f"{name}: {msg}\n--- worker traceback ---\n"
                              f"{tb}")
        return cloudpickle.loads(ref._blob)

    def map(self, fn: Callable, items: Iterable,
            timeout: Optional[float] = None) -> list:
        refs = [self._submit(fn, (it,), {}) for it in items]
        return self.get(refs, timeout)

    def __enter__(self):
        return self.start()

    def __exit__(self, *exc):
        self.stop()


def init_ray_on_spark(num_workers: int = 2, **_ignored) -> RayContext:
    """Reference-named entry (init_ray_on_spark / RayContext.init)."""
    return RayContext(num_workers).start()

from bigdl_tpu.orca.learn.estimator import Estimator

__all__ = ["Estimator"]

"""Orca Estimator facade (ref: P:orca/learn/*/estimator.py — one Estimator
per backend: bigdl (JVM DLlib), torch_distributed/spark (torch DDP), tf2).

Backends here:
- ``Estimator.from_bigdl``  — our nn/keras model through DistriOptimizer:
  the SPMD path, data sharded over the mesh (this is the TPU-native
  translation of "Spark partition → executor model replica").
- ``Estimator.from_torch`` — foreign-framework hosting (the reference's
  flagship Orca path, BASELINE config 4 BERT fine-tune): a genuine torch
  training loop driven shard-by-shard on host CPU, mirroring
  TorchRunner's creator-function API. torch has no TPU backend in this
  image, so this is capability parity; the perf path is from_bigdl.
- ``Estimator.from_keras`` (backend="tf2") — the reference's Orca TF2
  estimator (P:orca/learn/tf2): a creator-function-built tf.keras model
  trained with an explicit tf.GradientTape loop driven shard-by-shard
  (the role TF2Estimator's per-worker strategy loop plays upstream).
"""

from __future__ import annotations

from typing import Callable, Optional, Sequence

import numpy as np

from bigdl_tpu.orca.data import XShards


def _xy_from_data(data, label_cols=None, feature_cols=None):
    if isinstance(data, dict) and "x" in data and "y" in data:
        return data["x"], data["y"]
    if isinstance(data, XShards):
        merged = data.merged()
        if isinstance(merged, dict):
            if "x" in merged and "y" in merged:
                return merged["x"], merged["y"]
            if feature_cols and label_cols:
                x = np.stack([merged[c] for c in feature_cols], axis=-1)
                y = np.stack([merged[c] for c in label_cols], axis=-1)
                return x, y
            raise ValueError("dict shards need x/y keys or feature/label "
                             "cols")
        return merged
    return data


class BigDLEstimator:
    def __init__(self, model, loss, optimizer, metrics):
        from bigdl_tpu.keras.objectives import to_criterion
        from bigdl_tpu.keras.optimizers import to_optim_method
        from bigdl_tpu.keras.metrics import to_validation_methods

        # keras-API models carry their own module
        self.model = getattr(model, "module", model)
        self.criterion = to_criterion(loss) if loss is not None else None
        self.optim_method = to_optim_method(optimizer) \
            if optimizer is not None else None
        self.metrics = to_validation_methods(metrics or [])

    def fit(self, data, epochs: int = 1, batch_size: int = 32,
            feature_cols=None, label_cols=None, validation_data=None):
        from bigdl_tpu.optim.optimizer import Optimizer
        from bigdl_tpu.optim.trigger import Trigger

        x, y = _xy_from_data(data, label_cols, feature_cols)
        opt = Optimizer(self.model, (np.asarray(x), np.asarray(y)),
                        self.criterion, batch_size=batch_size,
                        end_trigger=Trigger.max_epoch(epochs))
        if self.optim_method is not None:
            opt.set_optim_method(self.optim_method)
        if validation_data is not None and self.metrics:
            vx, vy = _xy_from_data(validation_data, label_cols,
                                   feature_cols)
            opt.set_validation(Trigger.every_epoch(),
                               (np.asarray(vx), np.asarray(vy)),
                               self.metrics, batch_size)
        opt.optimize()
        return self

    def predict(self, data, batch_size: int = 128, feature_cols=None):
        from bigdl_tpu.optim.optimizer import Predictor

        if isinstance(data, XShards):
            merged = data.merged()
            x = merged["x"] if isinstance(merged, dict) else merged
        else:
            x = data
        return Predictor(self.model, batch_size).predict(np.asarray(x))

    def evaluate(self, data, batch_size: int = 128, feature_cols=None,
                 label_cols=None):
        from bigdl_tpu.optim.optimizer import Evaluator

        x, y = _xy_from_data(data, label_cols, feature_cols)
        return Evaluator(self.model).evaluate(
            (np.asarray(x), np.asarray(y)), self.metrics, batch_size)

    def get_model(self):
        return self.model

    def save(self, path: str):
        self.model.save_module(path)
        return self

    def load(self, path: str):
        from bigdl_tpu.nn.module import Module

        self.model = Module.load_module(path)
        return self


class TorchEstimator:
    """ref: P:orca/learn/pytorch — creator-function API; the training loop
    is torch's own (TorchRunner.train_epochs), driven per shard."""

    def __init__(self, model_creator: Callable,
                 optimizer_creator: Callable, loss_creator: Callable,
                 config: Optional[dict] = None):
        import torch

        self.config = config or {}
        self.model = model_creator(self.config)
        self.optimizer = optimizer_creator(self.model, self.config)
        loss = loss_creator(self.config) if loss_creator else None
        self.loss_fn = loss
        self._torch = torch

    def fit(self, data, epochs: int = 1, batch_size: int = 32):
        torch = self._torch
        self.model.train()
        stats = []
        for _ in range(epochs):
            shards = data.collect() if isinstance(data, XShards) else [data]
            for shard in shards:
                if isinstance(shard, dict):
                    x, y = shard["x"], shard["y"]
                else:
                    x, y = shard
                n = len(x)
                for i in range(0, n, batch_size):
                    xb = torch.as_tensor(np.asarray(x[i:i + batch_size]))
                    yb = torch.as_tensor(np.asarray(y[i:i + batch_size]))
                    self.optimizer.zero_grad()
                    out = self.model(xb)
                    if hasattr(out, "logits"):   # HF-style outputs
                        out = out.logits
                    loss = self.loss_fn(out, yb)
                    loss.backward()
                    self.optimizer.step()
                stats.append(float(loss.detach()))
        return stats

    def predict(self, data, batch_size: int = 128) -> np.ndarray:
        torch = self._torch
        self.model.eval()
        if isinstance(data, XShards):
            merged = data.merged()
            x = merged["x"] if isinstance(merged, dict) else merged
        else:
            x = data
        outs = []
        with torch.no_grad():
            for i in range(0, len(x), batch_size):
                out = self.model(torch.as_tensor(np.asarray(
                    x[i:i + batch_size])))
                if hasattr(out, "logits"):
                    out = out.logits
                outs.append(out.numpy())
        return np.concatenate(outs, 0)

    def evaluate(self, data, batch_size: int = 128) -> dict:
        x, y = _xy_from_data(data)
        pred = self.predict(x, batch_size)
        if pred.ndim > 1 and pred.shape[-1] > 1:
            acc = float((pred.argmax(-1) == np.asarray(y)).mean())
            return {"Accuracy": acc}
        diff = pred.squeeze() - np.asarray(y).squeeze()
        return {"MSE": float(np.mean(diff ** 2))}

    def get_model(self):
        return self.model


class TF2Estimator:
    """ref: P:orca/learn/tf2/estimator.py — creator-function API over a
    host tf.keras model; the train loop is an explicit GradientTape step
    per batch (the hosted analog of TF2Estimator's per-worker
    MultiWorkerMirroredStrategy loop), driven shard-by-shard."""

    def __init__(self, model_creator: Callable,
                 config: Optional[dict] = None,
                 compile_args_creator: Optional[Callable] = None):
        import tensorflow as tf

        self._tf = tf
        self.config = config or {}
        self.model = model_creator(self.config)
        if compile_args_creator is not None:
            self.model.compile(**compile_args_creator(self.config))
        if self.model.optimizer is None:
            raise ValueError("model_creator must compile the model or a "
                             "compile_args_creator must be given")

    def fit(self, data, epochs: int = 1, batch_size: int = 32):
        tf = self._tf
        model = self.model
        loss_fn = model.loss
        if isinstance(loss_fn, str):
            loss_fn = tf.keras.losses.get(loss_fn)
        opt = model.optimizer
        stats = []

        @tf.function
        def train_step(xb, yb):
            with tf.GradientTape() as tape:
                out = model(xb, training=True)
                loss = loss_fn(yb, out)
            grads = tape.gradient(loss, model.trainable_variables)
            opt.apply_gradients(zip(grads, model.trainable_variables))
            return loss

        for _ in range(epochs):
            shards = data.collect() if isinstance(data, XShards) else [data]
            for shard in shards:
                if isinstance(shard, dict):
                    x, y = shard["x"], shard["y"]
                else:
                    x, y = shard
                x, y = np.asarray(x), np.asarray(y)
                for i in range(0, len(x), batch_size):
                    loss = train_step(x[i:i + batch_size],
                                      y[i:i + batch_size])
                stats.append(float(loss))
        return stats

    def predict(self, data, batch_size: int = 128) -> np.ndarray:
        if isinstance(data, XShards):
            merged = data.merged()
            x = merged["x"] if isinstance(merged, dict) else merged
        else:
            x = data
        return np.asarray(self.model.predict(np.asarray(x),
                                             batch_size=batch_size,
                                             verbose=0))

    def evaluate(self, data, batch_size: int = 128) -> dict:
        x, y = _xy_from_data(data)
        pred = self.predict(x, batch_size)
        if pred.ndim > 1 and pred.shape[-1] > 1:
            acc = float((pred.argmax(-1)
                         == np.asarray(y).squeeze()).mean())
            return {"Accuracy": acc}
        diff = pred.squeeze() - np.asarray(y).squeeze()
        return {"MSE": float(np.mean(diff ** 2))}

    def get_model(self):
        return self.model

    def save(self, path: str):
        self.model.save_weights(path)
        return self

    def load(self, path: str):
        self.model.load_weights(path)
        return self


class Estimator:
    """Facade (ref: each backend module exposes Estimator.from_*)."""

    @staticmethod
    def from_bigdl(*, model, loss=None, optimizer=None, metrics=None,
                   **_ignored) -> BigDLEstimator:
        return BigDLEstimator(model, loss, optimizer, metrics)

    @staticmethod
    def from_torch(*, model_creator, optimizer_creator, loss_creator=None,
                   config=None, backend: str = "spark",
                   workers_per_node: int = 1, **_ignored) -> TorchEstimator:
        # backend spark|ray|torch_distributed all collapse to the hosted
        # loop here (no Spark/Ray substrate; documented capability gap)
        return TorchEstimator(model_creator, optimizer_creator,
                              loss_creator, config)

    @staticmethod
    def from_keras(*, model_creator=None, config=None,
                   compile_args_creator=None, backend: str = "tf2",
                   model=None, loss=None, optimizer=None, metrics=None,
                   **_ignored):
        """backend="tf2" hosts a foreign tf.keras model (creator-fn API,
        ref P:orca/learn/tf2); backend="bigdl" trains one of OUR keras-API
        models through DistriOptimizer."""
        if backend == "bigdl" or model is not None:
            return BigDLEstimator(model, loss, optimizer, metrics)
        if backend != "tf2":
            raise ValueError(
                f"unknown from_keras backend {backend!r}: this build "
                "hosts 'tf2' (single-process tf.GradientTape loop) and "
                "'bigdl'; the reference's spark/ray/horovod substrates "
                "are absent from this environment")
        if model_creator is None:
            raise ValueError("tf2 backend needs model_creator")
        return TF2Estimator(model_creator, config, compile_args_creator)

from bigdl_tpu.orca.automl.auto_estimator import AutoEstimator
from bigdl_tpu.orca.automl.hp import hp

__all__ = ["AutoEstimator", "hp"]

"""Search-space primitives (ref: P:orca/automl/hp.py — thin wrappers over
Ray Tune sample spaces; here self-contained samplers)."""

from __future__ import annotations

import random
from typing import Any, List, Sequence


class _Space:
    def sample(self, rng: random.Random) -> Any:
        raise NotImplementedError


class _Choice(_Space):
    def __init__(self, options: Sequence[Any]):
        self.options = list(options)

    def sample(self, rng):
        return rng.choice(self.options)


class _Uniform(_Space):
    def __init__(self, lo: float, hi: float):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.uniform(self.lo, self.hi)


class _LogUniform(_Space):
    def __init__(self, lo: float, hi: float):
        import math
        self.lo, self.hi = math.log(lo), math.log(hi)

    def sample(self, rng):
        import math
        return math.exp(rng.uniform(self.lo, self.hi))


class _RandInt(_Space):
    def __init__(self, lo: int, hi: int):
        self.lo, self.hi = lo, hi

    def sample(self, rng):
        return rng.randint(self.lo, self.hi - 1)


class hp:
    """ref API: hp.choice / hp.uniform / hp.loguniform / hp.randint /
    hp.grid_search."""

    @staticmethod
    def choice(options):
        return _Choice(options)

    @staticmethod
    def uniform(lo, hi):
        return _Uniform(lo, hi)

    @staticmethod
    def loguniform(lo, hi):
        return _LogUniform(lo, hi)

    @staticmethod
    def randint(lo, hi):
        return _RandInt(lo, hi)

    @staticmethod
    def grid_search(options):
        g = _Choice(options)
        g.grid = True
        return g


def sample_config(space: dict, rng: random.Random) -> dict:
    return {k: (v.sample(rng) if isinstance(v, _Space) else v)
            for k, v in space.items()}


def grid_axes(space: dict) -> List[str]:
    return [k for k, v in space.items() if getattr(v, "grid", False)]

"""AutoEstimator (ref: P:orca/automl/auto_estimator.py — HPO driver that
Ray-Tunes a model_creator over a search space; here a sequential
random/grid search with the same creator-function contract — on a single
host the chip is the scarce resource, so trials run serially on it)."""

from __future__ import annotations

import itertools
import logging
import random
from typing import Callable, Optional

import numpy as np

from bigdl_tpu.orca.automl.hp import _Choice, grid_axes, sample_config

logger = logging.getLogger("bigdl_tpu.orca.automl")


class AutoEstimator:
    def __init__(self, model_builder: Callable[[dict], object],
                 metric: str = "mse", mode: str = "min"):
        """model_builder(config) -> object with fit(data, ...) and
        evaluate(data, metrics=[metric]) -> [value]."""
        self.model_builder = model_builder
        self.metric = metric
        self.mode = mode
        self.best_config: Optional[dict] = None
        self.best_model = None
        self.best_score: Optional[float] = None
        self.trials = []

    def fit(self, data, validation_data=None, search_space: dict = None,
            n_sampling: int = 8, epochs: int = 3, batch_size: int = 32,
            seed: int = 0):
        rng = random.Random(seed)
        grids = grid_axes(search_space)
        if grids:
            grid_values = [search_space[k].options for k in grids]
            combos = list(itertools.product(*grid_values))
            configs = []
            for combo in combos:
                cfg = sample_config(
                    {k: v for k, v in search_space.items()
                     if k not in grids}, rng)
                cfg.update(dict(zip(grids, combo)))
                configs.append(cfg)
        else:
            configs = [sample_config(search_space, rng)
                       for _ in range(n_sampling)]

        val = validation_data if validation_data is not None else data
        better = (lambda a, b: a < b) if self.mode == "min" \
            else (lambda a, b: a > b)
        for i, cfg in enumerate(configs):
            model = self.model_builder(dict(cfg))
            model.fit(data, epochs=epochs, batch_size=batch_size)
            score = float(model.evaluate(val, metrics=[self.metric])[0])
            self.trials.append({"config": cfg, self.metric: score})
            logger.info("trial %d/%d %s=%.6f %s", i + 1, len(configs),
                        self.metric, score, cfg)
            if self.best_score is None or better(score, self.best_score):
                self.best_score = score
                self.best_config = cfg
                self.best_model = model
        return self

    def get_best_model(self):
        return self.best_model

    def get_best_config(self) -> dict:
        return self.best_config

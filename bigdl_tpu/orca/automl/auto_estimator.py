"""AutoEstimator (ref: P:orca/automl/auto_estimator.py — HPO driver that
Ray-Tunes a model_creator over a search space, with the same
creator-function contract).

Round-4 depth (VERDICT r3 weak #7): trials can run (a) in PARALLEL
across a :class:`bigdl_tpu.orca.ray_pool.RayContext` worker pool — the
RayOnSpark execution shape — and (b) under an ASHA-style
successive-halving scheduler (``scheduler="asha"``): every config gets
``grace_epochs``, only the top ``1/reduction_factor`` advance to the
next rung with ``reduction_factor×`` the budget, repeated until one
rung fits within ``epochs`` — Ray Tune's default scheduler lineage."""

from __future__ import annotations

import itertools
import logging
import random
from typing import Callable, Optional

import numpy as np

from bigdl_tpu.orca.automl.hp import _Choice, grid_axes, sample_config

logger = logging.getLogger("bigdl_tpu.orca.automl")


class AutoEstimator:
    def __init__(self, model_builder: Callable[[dict], object],
                 metric: str = "mse", mode: str = "min"):
        """model_builder(config) -> object with fit(data, ...) and
        evaluate(data, metrics=[metric]) -> [value]."""
        self.model_builder = model_builder
        self.metric = metric
        self.mode = mode
        self.best_config: Optional[dict] = None
        self.best_model = None
        self.best_score: Optional[float] = None
        self.trials = []

    def fit(self, data, validation_data=None, search_space: dict = None,
            n_sampling: int = 8, epochs: int = 3, batch_size: int = 32,
            seed: int = 0, ray_ctx=None, scheduler: Optional[str] = None,
            grace_epochs: int = 1, reduction_factor: int = 2):
        rng = random.Random(seed)
        grids = grid_axes(search_space)
        if grids:
            grid_values = [search_space[k].options for k in grids]
            combos = list(itertools.product(*grid_values))
            configs = []
            for combo in combos:
                cfg = sample_config(
                    {k: v for k, v in search_space.items()
                     if k not in grids}, rng)
                cfg.update(dict(zip(grids, combo)))
                configs.append(cfg)
        else:
            configs = [sample_config(search_space, rng)
                       for _ in range(n_sampling)]

        val = validation_data if validation_data is not None else data
        if scheduler == "asha":
            if ray_ctx is not None:
                logger.warning(
                    "scheduler='asha' runs trials serially (rung models "
                    "keep incremental state in-driver); ray_ctx is "
                    "ignored — drop the scheduler for pool-parallel "
                    "trials")
            self._fit_asha(configs, data, val, epochs, batch_size,
                           grace_epochs, reduction_factor)
        elif ray_ctx is not None:
            self._fit_parallel(configs, data, val, epochs, batch_size,
                               ray_ctx)
        else:
            self._fit_serial(configs, data, val, epochs, batch_size)
        return self

    def _better(self):
        return (lambda a, b: a < b) if self.mode == "min" \
            else (lambda a, b: a > b)

    def _record(self, cfg, score, model=None):
        self.trials.append({"config": cfg, self.metric: score})
        better = self._better()
        if self.best_score is None or better(score, self.best_score):
            self.best_score = score
            self.best_config = cfg
            if model is not None:
                self.best_model = model

    def _fit_serial(self, configs, data, val, epochs, batch_size):
        for i, cfg in enumerate(configs):
            model = self.model_builder(dict(cfg))
            model.fit(data, epochs=epochs, batch_size=batch_size)
            score = float(model.evaluate(val, metrics=[self.metric])[0])
            logger.info("trial %d/%d %s=%.6f %s", i + 1, len(configs),
                        self.metric, score, cfg)
            self._record(cfg, score, model)

    def _fit_parallel(self, configs, data, val, epochs, batch_size,
                      ray_ctx):
        """One cloudpickled trial per pool task (Ray-Tune shape: workers
        return scores, not models; the winner retrains in-driver so
        get_best_model() keeps its contract)."""
        builder, metric = self.model_builder, self.metric

        def trial(cfg):
            model = builder(dict(cfg))
            model.fit(data, epochs=epochs, batch_size=batch_size)
            return float(model.evaluate(val, metrics=[metric])[0])

        scores = ray_ctx.map(trial, configs)
        for cfg, score in zip(configs, scores):
            self._record(cfg, score)
        best = self.model_builder(dict(self.best_config))
        best.fit(data, epochs=epochs, batch_size=batch_size)
        self.best_model = best

    def _fit_asha(self, configs, data, val, epochs, batch_size,
                  grace_epochs, reduction_factor):
        """Successive halving: rung budgets grow by reduction_factor,
        survivors are the top 1/reduction_factor of each rung. Models
        keep training incrementally (fit() continues on the same
        object), so total epochs spent is far below len(configs) *
        epochs."""
        better = self._better()
        live = [(dict(cfg), self.model_builder(dict(cfg)), 0)
                for cfg in configs]
        budget = grace_epochs
        rung = 0
        while live:
            scored = []
            for cfg, model, done in live:
                add = min(budget, epochs) - done
                if add > 0:
                    model.fit(data, epochs=add, batch_size=batch_size)
                score = float(model.evaluate(
                    val, metrics=[self.metric])[0])
                scored.append((score, cfg, model, min(budget, epochs)))
            scored.sort(key=lambda t: t[0],
                        reverse=(self.mode == "max"))
            logger.info("asha rung %d (budget %d): %d trials, best "
                        "%s=%.6f", rung, min(budget, epochs),
                        len(scored), self.metric, scored[0][0])
            # a trial is recorded exactly ONCE, at its FINAL evaluation
            # (elimination or last rung) — recording every rung let
            # best_model be captured early and then mutated by later
            # incremental fit() calls, and duplicated trials entries
            # (ADVICE r4)
            if budget >= epochs or len(scored) == 1:
                for score, cfg, model, done in scored:
                    self._record(cfg, score, model)
                break
            keep = max(1, len(scored) // reduction_factor)
            for score, cfg, model, done in scored[keep:]:
                self._record(cfg, score, model)   # eliminated: final state
            live = [(cfg, model, done)
                    for score, cfg, model, done in scored[:keep]]
            budget *= reduction_factor
            rung += 1

    def get_best_model(self):
        return self.best_model

    def get_best_config(self) -> dict:
        return self.best_config

"""Federated GBDT across two parties (ref: PPML FGBoost quickstart):
each party holds half the rows; only aggregated histograms cross the
wire; both end with identical ensembles."""

import threading

import numpy as np


def main(smoke: bool = False):
    from bigdl_tpu.ppml import FGBoostRegression, FLClient, FLServer

    srv = FLServer(client_num=2, port=0).build().start()
    rs = np.random.RandomState(0)
    X = rs.randn(400, 5)
    y = np.sin(X[:, 0]) + X[:, 1] * X[:, 2] + 0.1 * rs.randn(400)
    preds = {}

    def party(i):
        cli = FLClient(f"party{i}", f"127.0.0.1:{srv.port}")
        model = FGBoostRegression(cli, n_estimators=4 if smoke else 12,
                                  max_depth=3)
        model.fit(X[i::2], y[i::2])
        preds[i] = model.predict(X)
        cli.close()

    ts = [threading.Thread(target=party, args=(i,)) for i in range(2)]
    [t.start() for t in ts]
    [t.join() for t in ts]
    srv.stop()
    agree = np.allclose(preds[0], preds[1])
    mse = float(np.mean((preds[0] - y) ** 2))
    print(f"parties agree: {agree}; train MSE {mse:.4f} "
          f"(var {float(np.var(y)):.4f})")
    assert agree, "federated parties diverged — protocol regression"
    return mse


if __name__ == "__main__":
    main()

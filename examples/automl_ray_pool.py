"""Parallel hyperparameter search over the RayContext process pool +
ASHA successive halving (ref: orca.automl's Ray-Tune lineage and the
RayOnSpark worker-pool role)."""

import numpy as np


class _Ridge:
    def __init__(self, config):
        self.lam = config["lam"]
        self.w = None

    def fit(self, data, epochs=1, batch_size=32):
        x, y = data
        a = x.T @ x + self.lam * np.eye(x.shape[1])
        self.w = np.linalg.solve(a, x.T @ y)

    def evaluate(self, data, metrics=("mse",)):
        x, y = data
        return [float(np.mean((x @ self.w - y) ** 2))]


def main(smoke: bool = False):
    from bigdl_tpu.orca import RayContext
    from bigdl_tpu.orca.automl import hp
    from bigdl_tpu.orca.automl.auto_estimator import AutoEstimator

    rs = np.random.RandomState(0)
    x = rs.rand(256, 6).astype(np.float32)
    y = (x @ rs.randn(6, 1)).astype(np.float32)

    est = AutoEstimator(lambda cfg: _Ridge(cfg), metric="mse",
                        mode="min")
    with RayContext(num_workers=2) as ctx:
        est.fit((x, y), search_space={
            "lam": hp.grid_search([10.0, 0.1, 1e-5])}, ray_ctx=ctx)
    print("parallel grid best:", est.get_best_config(),
          "mse:", est.best_score)

    est2 = AutoEstimator(lambda cfg: _Ridge(cfg), metric="mse",
                         mode="min")
    est2.fit((x, y), search_space={
        "lam": hp.choice([10.0, 1.0, 0.1, 1e-5])}, n_sampling=4,
        scheduler="asha", epochs=4)
    print("asha best:", est2.get_best_config())
    return est.get_best_config()


if __name__ == "__main__":
    main()

"""LeNet-5 training (ref: S:dllib/models/lenet — BASELINE config 1).

Trains on real MNIST when the IDX files are present (see
bigdl_tpu.feature.mnist), synthetic digits otherwise. Keras-style API
over the SPMD optimizer.

``--trace-out PATH`` dumps the run's trace spans (per-step/per-epoch
timing from the instrumented optimizer loop) as Chrome-trace JSON —
open it at https://ui.perfetto.dev or chrome://tracing, or summarize it
with ``python tools/telemetry_report.py PATH``.
"""

import numpy as np


def main(smoke: bool = False, trace_out: str = None):
    import bigdl_tpu.keras as K
    from bigdl_tpu.nn.module import set_seed

    set_seed(0)
    n, epochs = (256, 1) if smoke else (60000, 5)
    from bigdl_tpu.feature.mnist import load_mnist
    x, y = load_mnist(train=True)          # IDX files or learnable
    x = x.reshape(-1, 1, 28, 28).astype(np.float32)[:n]   # synthetic digits
    y = np.asarray(y, np.int32)[:n]

    m = K.Sequential()
    m.add(K.Convolution2D(6, 5, 5, activation="tanh",
                          input_shape=(1, 28, 28)))
    m.add(K.MaxPooling2D())
    m.add(K.Convolution2D(12, 5, 5, activation="tanh"))
    m.add(K.MaxPooling2D())
    m.add(K.Flatten())
    m.add(K.Dense(100, activation="tanh"))
    m.add(K.Dense(10, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(x, y, batch_size=64, nb_epoch=epochs)
    results = m.evaluate(x, y, batch_size=256)
    print("train-set metrics:", results)
    if trace_out:
        from bigdl_tpu import observability as obs
        obs.export_chrome_trace(trace_out)
        print(f"trace written to {trace_out} "
              f"({len(obs.TRACE)} spans; load in Perfetto or run "
              f"tools/telemetry_report.py on it)")
    return results


if __name__ == "__main__":
    import argparse

    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--smoke", action="store_true",
                    help="tiny subset, one epoch")
    ap.add_argument("--trace-out", default=None, metavar="PATH",
                    help="write Chrome-trace JSON of the training run")
    args = ap.parse_args()
    main(smoke=args.smoke, trace_out=args.trace_out)

"""LeNet-5 training (ref: S:dllib/models/lenet — BASELINE config 1).

Trains on real MNIST when the IDX files are present (see
bigdl_tpu.feature.mnist), synthetic digits otherwise. Keras-style API
over the SPMD optimizer.
"""

import numpy as np


def main(smoke: bool = False):
    import bigdl_tpu.keras as K
    from bigdl_tpu.nn.module import set_seed

    set_seed(0)
    n, epochs = (256, 1) if smoke else (60000, 5)
    from bigdl_tpu.feature.mnist import load_mnist
    x, y = load_mnist(train=True)          # IDX files or learnable
    x = x.reshape(-1, 1, 28, 28).astype(np.float32)[:n]   # synthetic digits
    y = np.asarray(y, np.int32)[:n]

    m = K.Sequential()
    m.add(K.Convolution2D(6, 5, 5, activation="tanh",
                          input_shape=(1, 28, 28)))
    m.add(K.MaxPooling2D())
    m.add(K.Convolution2D(12, 5, 5, activation="tanh"))
    m.add(K.MaxPooling2D())
    m.add(K.Flatten())
    m.add(K.Dense(100, activation="tanh"))
    m.add(K.Dense(10, activation="softmax"))
    m.compile(optimizer="adam", loss="sparse_categorical_crossentropy",
              metrics=["accuracy"])
    m.fit(x, y, batch_size=64, nb_epoch=epochs)
    results = m.evaluate(x, y, batch_size=256)
    print("train-set metrics:", results)
    return results


if __name__ == "__main__":
    main()

"""Paged-KV LLM serving behind the FastChat-style HTTP worker (ref:
bigdl-llm's FastChat integration — a worker process serving
/worker_generate over the continuous-batching engine)."""

import http.client
import json

import numpy as np


def main(smoke: bool = False):
    from bigdl_tpu.llm.models.llama import LlamaConfig
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.llm.transformers import AutoModelForCausalLM
    from bigdl_tpu.llm.worker import LLMWorker

    model = AutoModelForCausalLM.from_pretrained(
        LlamaConfig.tiny(), load_in_4bit=True, max_cache_len=64)
    # paged KV cache: HBM proportional to tokens in flight
    srv = LLMServer(model, max_batch=2, max_seq_len=32,
                    page_size=16).start()
    worker = LLMWorker(srv, model_name="demo-llm").start()
    try:
        conn = http.client.HTTPConnection(*worker.address, timeout=300)
        conn.request("POST", "/worker_generate",
                     json.dumps({"prompt_ids": [1, 2, 3],
                                 "max_new_tokens": 6}),
                     {"Content-Type": "application/json"})
        out = json.loads(conn.getresponse().read())
        print("worker_generate:", out)
        conn.request("GET", "/worker_get_status")
        print("status:", json.loads(conn.getresponse().read()))
        conn.close()
        assert len(out["output_ids"]) == 6
        return out
    finally:
        worker.stop()
        srv.stop()


if __name__ == "__main__":
    main()

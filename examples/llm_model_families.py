"""All five ggml model families generating under INT4 (ref: the
reference ships per-family demos under P:llm/ggml/model/ — llama,
gptneox, bloom, starcoder, chatglm; SURVEY.md §2.8 row 65). With a
``model_path`` the family is dispatched from config.json's model_type
through AutoModelForCausalLM; without one, demo-sized random weights
exercise each architecture's distinct machinery (ALiBi, MQA, parallel
residual, interleaved partial rotary)."""

import numpy as np


def main(smoke: bool = False, model_path: str = None):
    if model_path:
        from bigdl_tpu.llm.transformers import AutoModelForCausalLM
        model = AutoModelForCausalLM.from_pretrained(model_path,
                                                     load_in_4bit=True)
        out = model.generate(np.array([[1, 2, 3, 4]], np.int32),
                             max_new_tokens=8)
        print(type(model).__name__, out[0].tolist())
        return

    import dataclasses
    from bigdl_tpu.llm.models import (
        BloomConfig, BloomForCausalLM, GptNeoXConfig, GptNeoXForCausalLM,
        LlamaConfig, LlamaForCausalLM, StarCoderConfig,
        StarCoderForCausalLM)

    ids = np.array([[1, 2, 3, 4]], np.int32)
    demos = [
        ("llama", LlamaForCausalLM, LlamaConfig.tiny()),
        ("chatglm/glm (interleaved partial rotary)", LlamaForCausalLM,
         LlamaConfig.tiny_glm()),
        ("gptneox (parallel residual)", GptNeoXForCausalLM,
         GptNeoXConfig.tiny()),
        ("bloom (ALiBi)", BloomForCausalLM,
         dataclasses.replace(BloomConfig.tiny(), hidden_size=256,
                             num_attention_heads=2)),
        ("starcoder (MQA)", StarCoderForCausalLM,
         dataclasses.replace(StarCoderConfig.tiny(), hidden_size=256,
                             intermediate_size=256,
                             num_attention_heads=2)),
    ]
    for name, cls, cfg in demos:
        model = cls.from_config(cfg, seed=0, load_in_low_bit="sym_int4",
                                max_cache_len=32)
        out = model.generate(ids, max_new_tokens=4)
        print(f"{name}: {out[0].tolist()}")


if __name__ == "__main__":
    main()

"""Mask R-CNN inference (ref: S:dllib/models/maskrcnn demo): one jitted
program from image batch to boxes/labels/masks."""

import numpy as np


def main(smoke: bool = False):
    from bigdl_tpu.models.maskrcnn import MaskRCNN, MaskRCNNConfig

    cfg = MaskRCNNConfig.tiny() if smoke else MaskRCNNConfig(
        num_classes=81, image_size=224)
    model = MaskRCNN(cfg, seed=0)
    imgs = np.random.RandomState(0).rand(
        1, cfg.image_size, cfg.image_size, 3).astype(np.float32)
    det = model(imgs)
    kept = int((det["scores"][0] > 0).sum())
    print(f"detections: {kept} / {cfg.detections_per_img} slots; "
          f"mask grid {det['masks'].shape[-2:]}")
    return det


if __name__ == "__main__":
    main(smoke=True)

"""Llama INT4 generate + continuous-batching serving (ref: bigdl-llm
README demo — AutoModelForCausalLM(load_in_4bit=True).generate, and the
fastchat-worker analog LLMServer)."""

import numpy as np


def main(smoke: bool = False, model_path: str = None):
    from bigdl_tpu.llm.models.llama import LlamaConfig
    from bigdl_tpu.llm.serving import LLMServer
    from bigdl_tpu.llm.transformers import AutoModelForCausalLM

    if model_path:
        model = AutoModelForCausalLM.from_pretrained(model_path,
                                                     load_in_4bit=True)
    else:  # demo-sized random weights
        model = AutoModelForCausalLM.from_pretrained(
            LlamaConfig.tiny(), load_in_4bit=True, max_cache_len=64)

    ids = np.array([[1, 2, 3, 4]], np.int32)
    out = model.generate(ids, max_new_tokens=8)
    print("generate:", out[0].tolist())

    srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
    try:
        reqs = [srv.submit(np.array(p, np.int32), max_new_tokens=4)
                for p in ([5, 6], [7, 8, 9], [1])]
        for r in reqs:
            print("served:", r.get(timeout=300))
    finally:
        srv.stop()
    return out


if __name__ == "__main__":
    main()

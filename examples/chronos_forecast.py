"""Chronos forecasting (ref: chronos quickstarts): TSDataset roll ->
TCN + Autoformer forecasters -> evaluate."""

import numpy as np


def main(smoke: bool = False):
    from bigdl_tpu.chronos.forecaster import (AutoformerForecaster,
                                              TCNForecaster)

    t = np.arange(800, dtype=np.float32)
    series = (np.sin(2 * np.pi * t / 24)
              + 0.1 * np.random.RandomState(0).randn(800))
    L, H = 48, 8
    xs = np.stack([series[i:i + L] for i in range(700)])[..., None]
    ys = np.stack([series[i + L:i + L + H] for i in range(700)])[..., None]
    split = 600
    epochs = 1 if smoke else 10
    results = {}
    for name, f in [("tcn", TCNForecaster(L, H, 1, 1)),
                    ("autoformer", AutoformerForecaster(L, H, 1, 1,
                                                        d_model=16))]:
        f.fit((xs[:split], ys[:split]), epochs=epochs, batch_size=64)
        mse = float(np.mean((f.predict(xs[split:]) - ys[split:]) ** 2))
        results[name] = mse
        print(f"{name}: test MSE {mse:.4f}")
    return results


if __name__ == "__main__":
    main()

"""Orca foreign-framework hosting (ref: orca quickstarts): the same
XShards feed a torch estimator and a tf.keras (tf2) estimator."""

import numpy as np


def main(smoke: bool = False):
    from bigdl_tpu.orca.data import XShards
    from bigdl_tpu.orca.learn.estimator import Estimator

    rs = np.random.RandomState(0)
    x = rs.randn(200, 8).astype(np.float32)
    y = (x[:, 0] > 0).astype(np.int64)
    shards = XShards.partition({"x": x, "y": y}, num_shards=4)
    out = {}

    try:
        import torch

        def model_creator(config):
            torch.manual_seed(0)
            return torch.nn.Sequential(
                torch.nn.Linear(8, 16), torch.nn.ReLU(),
                torch.nn.Linear(16, 2))

        est = Estimator.from_torch(
            model_creator=model_creator,
            optimizer_creator=lambda m, c: torch.optim.Adam(
                m.parameters(), lr=c["lr"]),
            loss_creator=lambda c: torch.nn.CrossEntropyLoss(),
            config={"lr": 1e-2})
        est.fit(shards, epochs=1 if smoke else 5, batch_size=32)
        out["torch"] = est.evaluate({"x": x, "y": y})
        print("torch estimator:", out["torch"])
    except ImportError:
        pass

    try:
        import tensorflow as tf

        def keras_creator(config):
            tf.keras.utils.set_random_seed(0)
            m = tf.keras.Sequential([
                tf.keras.layers.Dense(16, activation="relu"),
                tf.keras.layers.Dense(2, activation="softmax")])
            m.compile(optimizer="adam",
                      loss=tf.keras.losses
                      .SparseCategoricalCrossentropy())
            return m

        est = Estimator.from_keras(model_creator=keras_creator,
                                   backend="tf2")
        est.fit(shards, epochs=1 if smoke else 5, batch_size=32)
        out["tf2"] = est.evaluate({"x": x, "y": y})
        print("tf2 estimator:", out["tf2"])
    except ImportError:
        pass
    return out


if __name__ == "__main__":
    main()

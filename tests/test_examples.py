"""Every examples/ script must run end-to-end in smoke mode (the
reference's examples double as CI smoke tests, SURVEY.md §4)."""

import importlib.util
import os
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(__file__), "..", "examples")


def _load(name):
    spec = importlib.util.spec_from_file_location(
        f"example_{name}", os.path.join(EXAMPLES, f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


@pytest.mark.parametrize("name", [
    "lenet_mnist", "llama_int4_generate", "chronos_forecast",
    "fgboost_federated", "maskrcnn_inference", "orca_estimators",
    "llm_http_worker", "automl_ray_pool", "llm_model_families"])
def test_example_smoke(name):
    mod = _load(name)
    mod.main(smoke=True)

"""Module-contract and layer-numerics tests.

Mirrors the reference's per-layer specs (e.g. nn/LinearSpec.scala,
nn/SpatialConvolutionSpec.scala) and its torch-parity pattern (SURVEY.md §4):
golden numerics are checked against independent numpy implementations.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.utils.table import T, Table


class TestModuleContract:
    def test_forward_backward_linear(self, rng):
        layer = nn.Linear(4, 3)
        x = rng.randn(2, 4).astype(np.float32)
        y = layer.forward(x)
        assert y.shape == (2, 3)
        w = np.asarray(layer.parameters_dict()["weight"])
        b = np.asarray(layer.parameters_dict()["bias"])
        np.testing.assert_allclose(np.asarray(y), x @ w.T + b, rtol=1e-5)

        # backward = vjp: gradInput of y = xW^T+b wrt x is g @ W
        g = rng.randn(2, 3).astype(np.float32)
        gi = layer.backward(x, g)
        np.testing.assert_allclose(np.asarray(gi), g @ w, rtol=1e-5)

        # accGradParameters accumulated
        _, grads = layer.parameters()
        assert any(np.abs(np.asarray(gr)).sum() > 0 for gr in grads)

    def test_parameters_dict_roundtrip(self):
        model = nn.Sequential().add(nn.Linear(4, 8)).add(nn.ReLU()).add(
            nn.Linear(8, 2))
        params = model.parameters_dict()
        zeroed = jax.tree_util.tree_map(jnp.zeros_like, params)
        model.load_parameters_dict(zeroed)
        for leaf in jax.tree_util.tree_leaves(model.parameters_dict()):
            assert float(jnp.abs(leaf).sum()) == 0.0

    def test_save_load_module(self, tmp_path, rng):
        model = nn.Sequential().add(nn.Linear(4, 8)).add(nn.Tanh()).add(
            nn.Linear(8, 2))
        x = rng.randn(3, 4).astype(np.float32)
        y1 = np.asarray(model.forward(x))
        path = str(tmp_path / "model.bigdl")
        model.save_module(path)
        loaded = nn.Module.load_module(path)
        y2 = np.asarray(loaded.forward(x))
        np.testing.assert_allclose(y1, y2, rtol=1e-6)

    def test_training_eval_modes(self):
        model = nn.Sequential().add(nn.Linear(4, 4)).add(nn.Dropout(0.5))
        model.evaluate()
        assert not model[1].is_training()
        model.training()
        assert model[1].is_training()


class TestLayers:
    def test_spatial_convolution_golden(self, rng):
        # 1x1 input channel, known kernel — verify against direct correlation
        conv = nn.SpatialConvolution(1, 1, 3, 3, with_bias=True)
        x = rng.randn(1, 1, 5, 5).astype(np.float32)
        y = np.asarray(conv.forward(x))
        w = np.asarray(conv.parameters_dict()["weight"])[0, 0]
        b = float(np.asarray(conv.parameters_dict()["bias"])[0])
        expected = np.zeros((3, 3), np.float32)
        for i in range(3):
            for j in range(3):
                expected[i, j] = np.sum(x[0, 0, i:i + 3, j:j + 3] * w) + b
        np.testing.assert_allclose(y[0, 0], expected, rtol=1e-4, atol=1e-5)

    def test_conv_same_padding(self, rng):
        conv = nn.SpatialConvolution(3, 8, 3, 3, pad_w=-1, pad_h=-1)
        x = rng.randn(2, 3, 7, 7).astype(np.float32)
        assert conv.forward(x).shape == (2, 8, 7, 7)

    def test_conv_groups(self, rng):
        conv = nn.SpatialConvolution(4, 8, 3, 3, n_group=2)
        x = rng.randn(2, 4, 5, 5).astype(np.float32)
        assert conv.forward(x).shape == (2, 8, 3, 3)

    def test_max_pooling(self, rng):
        pool = nn.SpatialMaxPooling(2, 2)
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        y = np.asarray(pool.forward(x))
        np.testing.assert_allclose(y[0, 0], [[5, 7], [13, 15]])

    def test_avg_pooling(self):
        pool = nn.SpatialAveragePooling(2, 2)
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        y = np.asarray(pool.forward(x))
        np.testing.assert_allclose(y[0, 0], [[2.5, 4.5], [10.5, 12.5]])

    def test_batchnorm_train_and_eval(self, rng):
        bn = nn.SpatialBatchNormalization(3)
        x = rng.randn(4, 3, 5, 5).astype(np.float32) * 2 + 1
        y = np.asarray(bn.forward(x))
        # normalized over batch+spatial per channel
        np.testing.assert_allclose(y.mean(axis=(0, 2, 3)), 0.0, atol=1e-5)
        np.testing.assert_allclose(y.std(axis=(0, 2, 3)), 1.0, atol=1e-3)
        # running stats moved off init
        rm = np.asarray(bn.states_dict()["running_mean"])
        assert np.abs(rm).sum() > 0
        bn.evaluate()
        y2 = bn.forward(x)
        assert y2.shape == x.shape

    def test_dropout_train_vs_eval(self, rng):
        drop = nn.Dropout(0.5)
        x = np.ones((10, 100), np.float32)
        y_train = np.asarray(drop.forward(x))
        assert (y_train == 0).mean() > 0.2
        drop.evaluate()
        y_eval = np.asarray(drop.forward(x))
        np.testing.assert_allclose(y_eval, x)

    def test_logsoftmax_nll_pair(self, rng):
        x = rng.randn(4, 10).astype(np.float32)
        lsm = nn.LogSoftMax()
        y = np.asarray(lsm.forward(x))
        np.testing.assert_allclose(np.exp(y).sum(-1), 1.0, rtol=1e-5)
        crit = nn.ClassNLLCriterion()
        target = np.array([1, 2, 3, 10], np.float32)  # 1-based
        loss = crit.forward(y, target)
        expected = -np.mean([y[i, int(t) - 1] for i, t in enumerate(target)])
        np.testing.assert_allclose(loss, expected, rtol=1e-5)

    def test_lstm_gru_scan(self, rng):
        x = rng.randn(2, 7, 4).astype(np.float32)
        for cell in (nn.LSTM(4, 6), nn.GRU(4, 6), nn.RnnCell(4, 6)):
            rec = nn.Recurrent(cell)
            y = rec.forward(x)
            assert y.shape == (2, 7, 6)

    def test_birecurrent(self, rng):
        x = rng.randn(2, 5, 4).astype(np.float32)
        bi = nn.BiRecurrent(nn.LSTM(4, 3), nn.LSTM(4, 3))
        assert bi.forward(x).shape == (2, 5, 6)

    def test_lookup_table_1based(self):
        lt = nn.LookupTable(10, 4)
        idx = np.array([[1, 2], [10, 1]], np.float32)
        y = np.asarray(lt.forward(idx))
        w = np.asarray(lt.parameters_dict()["weight"])
        np.testing.assert_allclose(y[0, 0], w[0], rtol=1e-6)
        np.testing.assert_allclose(y[1, 0], w[9], rtol=1e-6)

    def test_temporal_convolution(self, rng):
        conv = nn.TemporalConvolution(8, 16, 3)
        x = rng.randn(2, 10, 8).astype(np.float32)
        assert conv.forward(x).shape == (2, 8, 16)

    def test_full_convolution_upsamples(self, rng):
        deconv = nn.SpatialFullConvolution(3, 2, 2, 2, 2, 2)
        x = rng.randn(1, 3, 4, 4).astype(np.float32)
        assert deconv.forward(x).shape == (1, 2, 8, 8)

    def test_layernorm_rmsnorm(self, rng):
        x = rng.randn(2, 5, 16).astype(np.float32)
        ln = nn.LayerNorm(16)
        y = np.asarray(ln.forward(x))
        np.testing.assert_allclose(y.mean(-1), 0.0, atol=1e-5)
        rms = nn.RMSNorm(16)
        y2 = np.asarray(rms.forward(x))
        ms = (y2 ** 2).mean(-1)
        np.testing.assert_allclose(ms, (x ** 2).mean(-1) /
                                   (x ** 2).mean(-1), rtol=1e-2)

    def test_lrn(self, rng):
        lrn = nn.SpatialCrossMapLRN(5, 0.0001, 0.75, 1.0)
        x = rng.randn(1, 8, 4, 4).astype(np.float32)
        assert lrn.forward(x).shape == x.shape


class TestContainers:
    def test_concat(self, rng):
        c = nn.Concat(2).add(nn.Linear(4, 3)).add(nn.Linear(4, 5))
        x = rng.randn(2, 4).astype(np.float32)
        assert c.forward(x).shape == (2, 8)

    def test_concat_table_and_cadd(self, rng):
        model = nn.Sequential() \
            .add(nn.ConcatTable().add(nn.Linear(4, 4)).add(nn.Identity())) \
            .add(nn.CAddTable())
        x = rng.randn(2, 4).astype(np.float32)
        y = model.forward(x)
        assert y.shape == (2, 4)

    def test_parallel_table(self, rng):
        pt = nn.ParallelTable().add(nn.Linear(4, 2)).add(nn.Linear(3, 2))
        x = T(jnp.asarray(rng.randn(2, 4).astype(np.float32)),
              jnp.asarray(rng.randn(2, 3).astype(np.float32)))
        y = pt.forward(x)
        assert isinstance(y, Table)
        assert y[1].shape == (2, 2) and y[2].shape == (2, 2)

    def test_join_split(self, rng):
        x = rng.randn(2, 6).astype(np.float32)
        split = nn.SplitTable(2)
        parts = split.forward(x)
        assert len(parts) == 6
        join = nn.JoinTable(1, 1)
        back = join.forward(parts)
        assert back.shape == (12,) or back.shape == (2 * 6,)

    def test_nested_sequential_grad(self, rng):
        model = nn.Sequential() \
            .add(nn.Linear(4, 8)) \
            .add(nn.Sequential().add(nn.ReLU()).add(nn.Linear(8, 3))) \
            .add(nn.LogSoftMax())
        x = rng.randn(2, 4).astype(np.float32)
        y = model.forward(x)
        gi = model.backward(x, np.ones((2, 3), np.float32))
        assert gi.shape == (2, 4)


class TestCriterions:
    @pytest.mark.parametrize("crit_cls", [
        nn.MSECriterion, nn.AbsCriterion, nn.SmoothL1Criterion])
    def test_regression_criteria(self, crit_cls, rng):
        crit = crit_cls()
        x = rng.randn(4, 3).astype(np.float32)
        t = rng.randn(4, 3).astype(np.float32)
        loss = crit.forward(x, t)
        assert loss >= 0
        gi = crit.backward(x, t)
        assert gi.shape == x.shape

    def test_mse_golden(self):
        crit = nn.MSECriterion()
        x = np.array([[1.0, 2.0]], np.float32)
        t = np.array([[0.0, 0.0]], np.float32)
        np.testing.assert_allclose(crit.forward(x, t), 2.5)

    def test_cross_entropy_matches_nll_logsoftmax(self, rng):
        x = rng.randn(4, 5).astype(np.float32)
        t = np.array([1, 2, 3, 4], np.float32)
        ce = nn.CrossEntropyCriterion().forward(x, t)
        lsm = np.asarray(nn.LogSoftMax().forward(x))
        nll = nn.ClassNLLCriterion().forward(lsm, t)
        np.testing.assert_allclose(ce, nll, rtol=1e-5)

    def test_bce(self):
        crit = nn.BCECriterion()
        x = np.array([[0.8], [0.2]], np.float32)
        t = np.array([[1.0], [0.0]], np.float32)
        expected = -np.mean([np.log(0.8), np.log(0.8)])
        np.testing.assert_allclose(crit.forward(x, t), expected, rtol=1e-5)

    def test_parallel_criterion(self, rng):
        pc = nn.ParallelCriterion() \
            .add(nn.MSECriterion(), 0.5) \
            .add(nn.MSECriterion(), 2.0)
        x = T(jnp.ones((2, 2)), jnp.zeros((2, 2)))
        t = T(jnp.zeros((2, 2)), jnp.ones((2, 2)))
        np.testing.assert_allclose(pc.forward(x, t), 0.5 * 1.0 + 2.0 * 1.0)


class TestJitCompatibility:
    def test_pure_apply_under_jit_and_grad(self, rng):
        """The pure path must jit and grad — the whole framework depends on it."""
        model = nn.Sequential() \
            .add(nn.SpatialConvolution(1, 4, 3, 3)) \
            .add(nn.ReLU()) \
            .add(nn.SpatialMaxPooling(2, 2)) \
            .add(nn.Reshape([4 * 5 * 5])) \
            .add(nn.Linear(100, 10)) \
            .add(nn.LogSoftMax())
        crit = nn.ClassNLLCriterion()
        params = model.parameters_dict()
        states = model.states_dict()
        x = jnp.asarray(rng.randn(8, 1, 12, 12).astype(np.float32))
        t = jnp.asarray(rng.randint(1, 11, (8,)).astype(np.float32))
        key = jax.random.PRNGKey(0)

        @jax.jit
        def step(p):
            def loss_fn(p):
                y, _ = model.apply(p, states, x, training=True, rng=key)
                return crit.apply_loss(y, t)
            return jax.value_and_grad(loss_fn)(p)

        loss, grads = step(p=params)
        assert np.isfinite(float(loss))
        flat = jax.tree_util.tree_leaves(grads)
        assert all(np.all(np.isfinite(np.asarray(g))) for g in flat)


class TestReviewRegressions:
    """Regressions for the round-1 code-review findings."""

    def test_dropout_backward_uses_forward_mask(self, rng):
        model = nn.Sequential().add(nn.Identity()).add(nn.Dropout(0.5))
        x = np.ones((4, 50), np.float32)
        y = np.asarray(model.forward(x))
        gi = np.asarray(model.backward(x, np.ones_like(x)))
        # grad flows exactly where forward kept units
        np.testing.assert_allclose((gi != 0), (y != 0))

    def test_avg_pooling_ceil_mode(self):
        pool = nn.SpatialAveragePooling(2, 2, 2, 2, ceil_mode=True)
        x = np.ones((1, 1, 5, 5), np.float32)
        assert pool.forward(x).shape == (1, 1, 3, 3)
        # floor mode drops the remainder
        pool_f = nn.SpatialAveragePooling(2, 2, 2, 2)
        assert pool_f.forward(x).shape == (1, 1, 2, 2)

    def test_avg_pooling_same(self):
        pool = nn.SpatialAveragePooling(2, 2, 2, 2, pad_w=-1, pad_h=-1)
        x = np.ones((1, 1, 5, 5), np.float32)
        assert pool.forward(x).shape == (1, 1, 3, 3)

    def test_reverse_last_step_has_full_context(self, rng):
        x = rng.randn(1, 6, 4).astype(np.float32)
        cell = nn.LSTM(4, 3)
        seq = nn.Recurrent(cell, reverse=True)
        full = np.asarray(seq.forward(x))
        last_only = nn.Recurrent(cell, return_sequences=False, reverse=True)
        last_only._modules["cell"] = cell
        last = np.asarray(last_only.forward(x))
        # backward RNN's full-context state is at time index 0 of the
        # re-reversed sequence
        np.testing.assert_allclose(last, full[:, 0], rtol=1e-5)

    def test_bilinear_accepts_list(self, rng):
        bl = nn.Bilinear(4, 3, 2)
        a = rng.randn(2, 4).astype(np.float32)
        b = rng.randn(2, 3).astype(np.float32)
        y1 = np.asarray(bl.forward([a, b]))
        y2 = np.asarray(bl.forward(T(jnp.asarray(a), jnp.asarray(b))))
        np.testing.assert_allclose(y1, y2, rtol=1e-6)

    def test_table_ordering_past_ten(self):
        t = T(*[jnp.full((1,), i) for i in range(12)])
        lst = t.to_list()
        vals = [float(v[0]) for v in lst]
        assert vals == list(range(12))
        leaves = jax.tree_util.tree_leaves(t)
        assert [float(v[0]) for v in leaves] == list(range(12))

    def test_set_seed_reproducible_despite_forward(self, rng):
        nn.set_seed(123)
        m1 = nn.Linear(4, 4)
        m1.forward(np.ones((1, 4), np.float32))
        m2 = nn.Linear(4, 4)
        w2a = np.asarray(m2.parameters_dict()["weight"])
        nn.set_seed(123)
        _ = nn.Linear(4, 4)
        m2b = nn.Linear(4, 4)
        np.testing.assert_allclose(
            w2a, np.asarray(m2b.parameters_dict()["weight"]))

    def test_tensor_squeeze_never_aliases(self):
        from bigdl_tpu.tensor import Tensor
        t = Tensor.ones(2, 3)
        s = t.squeeze(1)  # size != 1 → no-op copy
        s.fill(0)
        assert float(t.data.sum()) == 6.0


class TestCheckpointContainer:
    def test_remat_matches_plain(self):
        """nn.Checkpoint must be numerically transparent (same forward,
        same gradients) — it only changes what is saved for backward."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn.module import set_seed

        def build(wrap):
            set_seed(0)
            inner = (nn.Sequential()
                     .add(nn.Linear(8, 16)).add(nn.ReLU())
                     .add(nn.Linear(16, 4)))
            return nn.Sequential().add(
                nn.Checkpoint(inner) if wrap else inner)

        plain, remat = build(False), build(True)
        x = jnp.asarray(np.random.RandomState(0).randn(3, 8), jnp.float32)

        def loss(model, params, x):
            y, _ = model.apply(params, model.states_dict(), x,
                               training=True, rng=jax.random.PRNGKey(0))
            return jnp.sum(y * y)

        p_plain = plain.parameters_dict()
        p_remat = remat.parameters_dict()
        l1, g1 = jax.value_and_grad(
            lambda p: loss(plain, p, x))(p_plain)
        l2, g2 = jax.value_and_grad(
            lambda p: loss(remat, p, x))(p_remat)
        assert abs(float(l1) - float(l2)) < 1e-5
        f1 = jax.tree_util.tree_leaves(g1)
        f2 = jax.tree_util.tree_leaves(g2)
        for a, b in zip(f1, f2):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_batchnorm_single_pass_stats(self):
        """New m2-mean BN form must match the two-pass definition."""
        import jax
        import jax.numpy as jnp
        import numpy as np
        import bigdl_tpu.nn as nn

        bn = nn.SpatialBatchNormalization(8, format="NHWC")
        x = np.random.RandomState(1).randn(4, 5, 5, 8).astype(np.float32)
        params = bn.parameters_dict()
        states = bn.states_dict()
        y, new_states = bn.apply(params, states, jnp.asarray(x),
                                 training=True, rng=None)
        mean = x.mean(axis=(0, 1, 2))
        var = x.var(axis=(0, 1, 2))
        ref = (x - mean) / np.sqrt(var + bn.eps)
        np.testing.assert_allclose(np.asarray(y), ref, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(np.asarray(new_states["running_mean"]),
                                   0.1 * mean, rtol=1e-4, atol=1e-5)


class TestQuantizedLayers:
    """nn.quantized INT8 inference (ref: S:dllib/nn/quantized + BigQuant)."""

    def test_quantized_linear_close_to_float(self):
        import jax.numpy as jnp
        import numpy as np
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn.module import set_seed

        set_seed(0)
        lin = nn.Linear(32, 16)
        qlin = nn.quantized.Linear.from_float(lin)
        x = np.random.RandomState(0).randn(4, 32).astype(np.float32)
        y = np.asarray(lin.forward(x))
        yq = np.asarray(qlin.forward(x))
        rel = np.abs(yq - y).max() / (np.abs(y).max() + 1e-6)
        assert rel < 0.03, rel

    def test_quantized_conv_close_to_float(self):
        import numpy as np
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn.module import set_seed

        set_seed(0)
        conv = nn.SpatialConvolution(3, 8, 3, 3, 1, 1, 1, 1)
        qconv = nn.quantized.SpatialConvolution.from_float(conv)
        x = np.random.RandomState(1).randn(2, 3, 8, 8).astype(np.float32)
        y = np.asarray(conv.forward(x))
        yq = np.asarray(qconv.forward(x))
        rel = np.abs(yq - y).max() / (np.abs(y).max() + 1e-6)
        assert rel < 0.03, rel
        assert np.asarray(qconv._states["q"]).dtype == np.int8

    def test_quantize_model_surgery(self):
        import numpy as np
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn.module import set_seed
        from bigdl_tpu.nn.quantized import quantize_model

        set_seed(0)
        model = (nn.Sequential()
                 .add(nn.SpatialConvolution(3, 4, 3, 3, 1, 1, 1, 1))
                 .add(nn.ReLU())
                 .add(nn.Flatten())
                 .add(nn.Linear(4 * 6 * 6, 10)))
        x = np.random.RandomState(2).randn(2, 3, 6, 6).astype(np.float32)
        y = np.asarray(model.forward(x))
        quantize_model(model)
        kinds = [type(m).__module__ + "." + type(m).__name__
                 for m in model.modules()]
        assert any("quantized.SpatialConvolution" in k for k in kinds)
        assert any("quantized.Linear" in k for k in kinds)
        yq = np.asarray(model.forward(x))
        rel = np.abs(yq - y).max() / (np.abs(y).max() + 1e-6)
        assert rel < 0.05, rel

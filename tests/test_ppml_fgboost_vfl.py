"""FGBoost federated GBDT + VFL linear/logistic — 2-client convergence
tests over the real TCP FLServer (the reference's FGBoost/VFL test
pattern: multi-party training on one host; SURVEY.md §2.8 PPML row)."""

import threading

import numpy as np
import pytest

from bigdl_tpu.ppml import (
    FGBoostClassification, FGBoostRegression, FLClient, FLServer,
    VFLLinearRegression, VFLLogisticRegression)


def _run_parties(fns):
    """Run one callable per party on threads; re-raise any failure."""
    errs = []
    results = [None] * len(fns)

    def runner(i, fn):
        try:
            results[i] = fn()
        except Exception as e:  # noqa: BLE001
            errs.append(e)

    ts = [threading.Thread(target=runner, args=(i, f))
          for i, f in enumerate(fns)]
    for t in ts:
        t.start()
    for t in ts:
        t.join(timeout=120)
    if errs:
        raise errs[0]
    return results


@pytest.fixture()
def server():
    srv = FLServer(client_num=2, port=0).build().start()
    yield srv
    srv.stop()


class TestFGBoost:
    def test_regression_converges_and_parties_agree(self, server):
        rs = np.random.RandomState(0)
        X = rs.randn(400, 5)
        y = (np.sin(X[:, 0]) + 0.5 * X[:, 1] ** 2
             + X[:, 2] * X[:, 3] + 0.1 * rs.randn(400))
        shards = [(X[:200], y[:200]), (X[200:], y[200:])]
        target = str(f"127.0.0.1:{server.port}")

        def party(i):
            cli = FLClient(f"c{i}", target)
            model = FGBoostRegression(cli, n_estimators=8, max_depth=3,
                                      n_bins=16)
            model.fit(*shards[i])
            pred = model.predict(X)
            cli.close()
            return pred

        p0, p1 = _run_parties([lambda: party(0), lambda: party(1)])
        # both parties hold the identical ensemble
        np.testing.assert_allclose(p0, p1, rtol=1e-10, atol=1e-10)
        base_mse = np.mean((y - y.mean()) ** 2)
        mse = np.mean((p0 - y) ** 2)
        assert mse < 0.5 * base_mse, (mse, base_mse)

    def test_classification_accuracy(self, server):
        rs = np.random.RandomState(1)
        X = rs.randn(400, 4)
        logits = X[:, 0] - 0.8 * X[:, 1] + X[:, 2] * X[:, 3]
        y = (logits > 0).astype(np.float64)
        shards = [(X[:200], y[:200]), (X[200:], y[200:])]
        target = f"127.0.0.1:{server.port}"

        def party(i):
            cli = FLClient(f"c{i}", target)
            model = FGBoostClassification(cli, n_estimators=10,
                                          max_depth=3, n_bins=16)
            model.fit(*shards[i])
            acc = float((model.predict(X) == y).mean())
            cli.close()
            return acc

        accs = _run_parties([lambda: party(0), lambda: party(1)])
        assert min(accs) > 0.85, accs


class TestVFL:
    def test_linear_regression_converges(self, server):
        rs = np.random.RandomState(2)
        n = 300
        Xa, Xb = rs.randn(n, 3), rs.randn(n, 2)
        w_true = np.array([1.0, -2.0, 0.5, 3.0, -1.0])
        y = np.concatenate([Xa, Xb], 1) @ w_true + 0.7
        target = f"127.0.0.1:{server.port}"

        def party_a():
            cli = FLClient("a", target)
            m = VFLLinearRegression(cli, 3, has_labels=True,
                                    learning_rate=0.1)
            m.fit(Xa, y, epochs=120)
            pred = m.predict(Xa)
            cli.close()
            return m, pred

        def party_b():
            cli = FLClient("b", target)
            m = VFLLinearRegression(cli, 2, has_labels=False,
                                    learning_rate=0.1)
            m.fit(Xb, epochs=120)
            pred = m.predict(Xb)
            cli.close()
            return m, pred

        (ma, pa), (mb, pb) = _run_parties([party_a, party_b])
        assert ma.history[-1] < 0.05 * ma.history[0]
        np.testing.assert_allclose(pa, pb)          # same summed logits
        np.testing.assert_allclose(pa, y, atol=0.5)
        np.testing.assert_allclose(
            np.concatenate([ma.w, mb.w]), w_true, atol=0.15)

    def test_logistic_regression_accuracy(self, server):
        rs = np.random.RandomState(3)
        n = 400
        Xa, Xb = rs.randn(n, 2), rs.randn(n, 3)
        w_true = np.array([2.0, -1.0, 1.5, 0.5, -2.0])
        y = ((np.concatenate([Xa, Xb], 1) @ w_true) > 0).astype(np.float64)
        target = f"127.0.0.1:{server.port}"

        def party(i):
            X = Xa if i == 0 else Xb
            cli = FLClient(f"p{i}", target)
            m = VFLLogisticRegression(cli, X.shape[1], has_labels=(i == 0),
                                      learning_rate=0.3)
            m.fit(X, y if i == 0 else None, epochs=150)
            proba = m.predict(X)
            cli.close()
            return m, proba

        (ma, pa), (mb, pb) = _run_parties(
            [lambda: party(0), lambda: party(1)])
        np.testing.assert_allclose(pa, pb)
        acc = float(((pa >= 0.5) == y).mean())
        assert acc > 0.93, acc
        assert ma.history[-1] < 0.5 * ma.history[0]

"""OpenAI-compatible serving gateway tests (ISSUE 20).

Layers under test, cheapest first:

- wire/unit: SSE framing grammar, incremental stop matching, the byte
  tokenizer, chat templates, OpenAI error objects;
- translation: request-body edge cases against a fake backend (no
  engine, no HTTP);
- live worker: ``/v1/*`` on an api-enabled ``LLMWorker`` — parity with
  the native ``/worker_generate``, stream grammar + usage, shed → 429,
  client-disconnect abort freeing slot + KV pages, gate-off 404;
- live router: the SSE relay over the failover journal — bit-identical
  to ``model.generate`` through two workers, with the router's SLO
  sketches stamping every streamed token exactly once.
"""

import http.client
import io
import json
import socket
import struct
import time

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu import reliability as rel
from bigdl_tpu.llm.api import (ByteTokenizer, InvalidRequestError,
                               OpenAIGateway, RateLimitError, StopMatcher,
                               UpstreamError, apply_chat_template,
                               build_tokenizer, parse_sse, sse_done,
                               sse_event)
from bigdl_tpu.llm.api.errors import error_for_status
from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import LLMServer
from bigdl_tpu.llm.worker import LLMRouter, LLMWorker

pytestmark = pytest.mark.api

MODEL_ID = "bigdl-tpu-llm"


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=128)


def _generate(model, p, n):
    return [int(t) for t in
            model.generate(np.asarray(p)[None], max_new_tokens=n)
            [0, len(p):]]


def _req(addr, method, path, body=None, headers=None, timeout=120):
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        payload = json.dumps(body) if body is not None else None
        conn.request(method, path, payload,
                     dict(headers or {},
                          **({"Content-Type": "application/json"}
                             if body is not None else {})))
        r = conn.getresponse()
        data = json.loads(r.read().decode())
        return r.status, data, dict(r.getheaders())
    finally:
        conn.close()


def _stream(addr, path, body, timeout=120):
    """POST with ``stream=true`` → (status, [chunks], headers)."""
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        conn.request("POST", path, json.dumps(dict(body, stream=True)),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        if r.status != 200:
            return r.status, json.loads(r.read().decode()), \
                dict(r.getheaders())
        return 200, list(parse_sse(r)), dict(r.getheaders())
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# SSE framing
# ---------------------------------------------------------------------------

class TestSSEFraming:
    def test_event_grammar(self):
        assert sse_event({"a": 1}) == b'data: {"a": 1}\n\n'
        assert sse_done() == b"data: [DONE]\n\n"

    def test_parse_roundtrip_stops_at_done(self):
        wire = sse_event({"i": 0}) + sse_event({"i": 1}) + sse_done() \
            + b"data: after-done-is-ignored\n\n"
        got = list(parse_sse(io.BytesIO(wire)))
        assert got == [{"i": 0}, {"i": 1}]

    def test_parse_requires_terminal_done(self):
        with pytest.raises(ValueError, match="DONE"):
            list(parse_sse(io.BytesIO(sse_event({"i": 0}))))

    def test_parse_rejects_foreign_lines(self):
        with pytest.raises(ValueError, match="data line"):
            list(parse_sse(io.BytesIO(b"event: ping\n\n")))


# ---------------------------------------------------------------------------
# stop matching
# ---------------------------------------------------------------------------

class TestStopMatcher:
    def test_text_stop_split_across_chunks(self):
        m = StopMatcher(["XY"])
        assert m.feed("aX") == ("a", False)   # "X" held back
        assert m.feed("Yb") == ("", True)     # match cut exactly
        assert m.hit and m.flush() is None

    def test_text_no_match_flushes_tail(self):
        m = StopMatcher(["ZZ"])
        assert m.feed("aZ") == ("a", False)
        assert m.flush() == "Z"

    def test_earliest_stop_wins(self):
        m = StopMatcher(["cd", "b"])
        emit, done = m.feed("abcd")
        assert (emit, done) == ("a", True)

    def test_token_stop_sequences(self):
        m = StopMatcher([[5, 6]])
        emit, done = m.feed([1, 5])
        assert (list(emit), done) == ([1], False)
        emit, done = m.feed([6, 7])
        assert (list(emit), done) == ([], True)

    def test_no_stops_passthrough(self):
        m = StopMatcher([])
        assert m.feed("anything") == ("anything", False)


# ---------------------------------------------------------------------------
# tokenizer + chat templates
# ---------------------------------------------------------------------------

class TestTemplates:
    def test_byte_tokenizer_roundtrip(self):
        tok = ByteTokenizer()
        ids = tok.encode("héllo")
        assert all(0 <= t < 256 for t in ids)
        assert tok.decode(ids) == "héllo"

    def test_build_tokenizer_knob(self):
        assert build_tokenizer("") is None
        assert isinstance(build_tokenizer("byte"), ByteTokenizer)
        with pytest.raises(ValueError, match="byte"):
            build_tokenizer("sentencepiece")

    def test_families(self):
        msgs = [{"role": "system", "content": "be terse"},
                {"role": "user", "content": "hi"},
                {"role": "assistant", "content": "hello"},
                {"role": "user", "content": "bye"}]
        plain = apply_chat_template("plain", msgs)
        assert "### Human: hi" in plain and plain.endswith(
            "### Assistant:")
        llama = apply_chat_template("llama", msgs)
        assert "<<SYS>>" in llama and "[INST] bye [/INST]" in llama
        glm = apply_chat_template("chatglm", msgs)
        assert "[Round 0]\n问：hi" in glm and glm.endswith("答：")

    @pytest.mark.parametrize("messages", [
        [],
        [{"role": "user", "content": "hi"},
         {"role": "assistant", "content": "yo"}],   # must end on user
        [{"role": "tool", "content": "x"}],
        [{"role": "user", "content": 7}],
        "not a list",
    ])
    def test_bad_messages_rejected(self, messages):
        with pytest.raises(InvalidRequestError) as ei:
            apply_chat_template("plain", messages)
        assert ei.value.param == "messages"


# ---------------------------------------------------------------------------
# OpenAI error objects
# ---------------------------------------------------------------------------

class TestErrors:
    def test_shed_maps_to_429_rate_limit(self):
        e = error_for_status(503, "queue full", retry_after="7")
        assert isinstance(e, RateLimitError)
        assert e.status == 429
        assert dict(e.headers())["Retry-After"] == "7"
        err = e.body()["error"]
        assert err["type"] == "rate_limit_error"
        assert err["code"] == "rate_limit_exceeded"

    def test_4xx_keeps_status_as_invalid_request(self):
        e = error_for_status(422, "bad shape")
        assert isinstance(e, InvalidRequestError) and e.status == 422
        assert e.body()["error"]["type"] == "invalid_request_error"

    def test_5xx_is_api_error(self):
        e = error_for_status(504, "deadline")
        assert isinstance(e, UpstreamError) and e.status == 504
        assert e.body()["error"]["type"] == "api_error"


# ---------------------------------------------------------------------------
# translation edges (fake backend, no engine)
# ---------------------------------------------------------------------------

class _FakeBackend:
    model_name = MODEL_ID
    request_timeout = 5.0

    def sampling(self):
        return (0.0, 0)

    def generate(self, prompt_ids, max_new_tokens, priority, deadline,
                 on_delta):
        raise AssertionError("translation tests never dispatch")


class _ScriptedBackend(_FakeBackend):
    """Feeds scripted token groups through on_delta — the unit harness
    for stop matching + emission without an engine."""

    def __init__(self, groups):
        self.groups = [list(g) for g in groups]

    def generate(self, prompt_ids, max_new_tokens, priority, deadline,
                 on_delta):
        out = []
        for g in self.groups:
            out.extend(g)
            if on_delta is not None:
                on_delta(list(g))
        return out, "length"


class TestTranslation:
    def gw(self, tokenizer="byte"):
        return OpenAIGateway(_FakeBackend(),
                             tokenizer=build_tokenizer(tokenizer))

    def translate(self, body, headers=None, chat=False,
                  tokenizer="byte"):
        return self.gw(tokenizer)._translate(body, headers or {},
                                             chat=chat)

    def test_token_prompt_is_native(self):
        t = self.translate({"prompt": [1, 2, 3]}, tokenizer="")
        assert t.prompt_ids == [1, 2, 3] and t.max_tokens == 16
        assert t.n == 1 and not t.stream and t.priority is None

    def test_model_mismatch_404(self):
        with pytest.raises(InvalidRequestError) as ei:
            self.translate({"model": "gpt-4", "prompt": [1]})
        assert ei.value.status == 404
        assert ei.value.code == "model_not_found"

    @pytest.mark.parametrize("body,param", [
        ({"prompt": [1], "max_tokens": 0}, "max_tokens"),
        ({"prompt": [1], "max_tokens": "lots"}, "max_tokens"),
        ({"prompt": [1], "n": 0}, "n"),
        ({"prompt": [1], "n": 9}, "n"),
        ({"prompt": [1], "temperature": 0.7}, "temperature"),
        ({"prompt": [1], "top_k": 40}, "top_k"),
        ({"prompt": [1], "top_p": 0.9}, "top_p"),
        ({"prompt": [1], "stop": ["a", "b", "c", "d", "e"]}, "stop"),
        ({"prompt": [1], "stop": 7}, "stop"),
        ({"prompt": [1], "stop": [[1], "x"]}, "stop"),
        ({"prompt": []}, "prompt"),
        ({"prompt": [1, True, 3]}, "prompt"),
        ({}, "prompt"),
    ])
    def test_invalid_bodies(self, body, param):
        with pytest.raises(InvalidRequestError) as ei:
            self.translate(body)
        assert ei.value.param == param

    def test_matching_sampling_params_accepted(self):
        t = self.translate({"prompt": [1], "temperature": 0.0,
                            "top_k": 0, "top_p": 1.0})
        assert t.prompt_ids == [1]

    def test_stop_normalization(self):
        t = self.translate({"prompt": [1], "stop": "ab"})
        assert t.stops_text == ["ab"] and t.stops_tokens == []
        t = self.translate({"prompt": [1], "stop": [5, 6]})
        assert t.stops_tokens == [[5, 6]] and t.stops_text == []
        t = self.translate({"prompt": [1], "stop": [[5], [6, 7]]})
        assert t.stops_tokens == [[5], [6, 7]]

    def test_text_needs_tokenizer(self):
        with pytest.raises(InvalidRequestError) as ei:
            self.translate({"prompt": "hello"}, tokenizer="")
        assert ei.value.param == "prompt"
        with pytest.raises(InvalidRequestError) as ei:
            self.translate({"prompt": [1], "stop": "x"}, tokenizer="")
        assert ei.value.param == "stop"
        t = self.translate({"prompt": "hi"})
        assert t.prompt_ids == ByteTokenizer().encode("hi")

    def test_chat_templating_into_tokens(self):
        t = self.translate(
            {"messages": [{"role": "user", "content": "hi"}]},
            chat=True)
        want = ByteTokenizer().encode(apply_chat_template(
            "plain", [{"role": "user", "content": "hi"}]))
        assert t.prompt_ids == want and t.rid.startswith("chatcmpl-")

    def test_priority_header_and_user_passthrough(self):
        t = self.translate({"prompt": [1]},
                           headers={"X-BigDL-Priority": "batch"})
        assert t.priority == "batch"
        t = self.translate({"prompt": [1], "user": "interactive"})
        assert t.priority == "interactive"
        t = self.translate({"prompt": [1], "user": "alice"})
        assert t.priority is None    # opaque user ids are not classes

    def test_run_choice_text_stop_held_back(self):
        # "W" then "XY" arrives split across groups: the held-back "X"
        # never leaks and the stream cuts exactly at the match
        tok = ByteTokenizer()
        gw = OpenAIGateway(
            _ScriptedBackend([tok.encode("aX"), tok.encode("Yb")]),
            tokenizer=tok)
        treq = gw._translate({"prompt": "p", "stop": "XY"}, {},
                             chat=False)
        emitted = []
        generated, finish = gw._run_choice(
            treq, lambda ids, txt: emitted.append(txt))
        assert finish == "stop"
        assert "".join(emitted) == "a"

    def test_run_choice_token_stop(self):
        gw = OpenAIGateway(_ScriptedBackend([[1, 5], [6, 7]]),
                           tokenizer=None)
        treq = gw._translate({"prompt": [9], "stop": [5, 6]}, {},
                             chat=False)
        emitted = []
        _, finish = gw._run_choice(
            treq, lambda ids, txt: emitted.append(ids))
        assert finish == "stop"
        assert [t for g in emitted for t in g] == [1]


# ---------------------------------------------------------------------------
# live worker surface
# ---------------------------------------------------------------------------

class TestWorkerGateway:
    @pytest.fixture(scope="class")
    def served(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8, kvcache=True).start()
        worker = LLMWorker(srv, api=True,
                           tokenizer=ByteTokenizer()).start()
        yield model, srv, worker
        worker.stop()
        srv.stop()

    def test_models_route(self, served):
        _, _, worker = served
        st, body, _ = _req(worker.address, "GET", "/v1/models")
        assert st == 200 and body["object"] == "list"
        assert [m["id"] for m in body["data"]] == [MODEL_ID]

    def test_blocking_parity_with_native(self, served):
        model, _, worker = served
        ids = [3, 1, 4, 1, 5]
        want = _generate(model, ids, 6)
        st, native, _ = _req(worker.address, "POST", "/worker_generate",
                             {"prompt_ids": ids, "max_new_tokens": 6})
        assert st == 200 and native["output_ids"] == want
        st, body, _ = _req(worker.address, "POST", "/v1/completions",
                           {"model": MODEL_ID, "prompt": ids,
                            "max_tokens": 6})
        assert st == 200, body
        choice = body["choices"][0]
        assert choice["token_ids"] == want
        assert choice["finish_reason"] == "length"
        assert body["usage"] == {"prompt_tokens": 5,
                                 "completion_tokens": 6,
                                 "total_tokens": 11}

    def test_stream_grammar_usage_and_parity(self, served):
        model, _, worker = served
        ids = [2, 7, 1, 8]
        want = _generate(model, ids, 6)
        st, chunks, hdrs = _stream(worker.address, "/v1/completions",
                                   {"model": MODEL_ID, "prompt": ids,
                                    "max_tokens": 6})
        assert st == 200
        assert hdrs["Content-Type"] == "text/event-stream"
        toks = [t for c in chunks
                for t in c["choices"][0].get("token_ids", [])]
        assert toks == want
        # exactly one terminal finish chunk, usage rides the last chunk
        finals = [c for c in chunks
                  if c["choices"][0]["finish_reason"] is not None]
        assert len(finals) == 1 and finals[0] is chunks[-1]
        assert chunks[-1]["usage"]["completion_tokens"] == 6
        rid = chunks[0]["id"]
        assert rid.startswith("cmpl-")
        assert all(c["id"] == rid for c in chunks)

    def test_stream_raw_wire_has_done_sentinel(self, served):
        _, _, worker = served
        conn = http.client.HTTPConnection(*worker.address, timeout=120)
        try:
            conn.request("POST", "/v1/completions",
                         json.dumps({"prompt": [1, 2, 3],
                                     "max_tokens": 2, "stream": True}),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            raw = r.read()     # http.client undoes the chunking
        finally:
            conn.close()
        events = [ln for ln in raw.split(b"\n\n") if ln]
        assert all(e.startswith(b"data: ") for e in events)
        assert events[-1] == b"data: [DONE]"

    def test_token_stop_sequence_live(self, served):
        model, _, worker = served
        ids = [3, 1, 4, 1, 5]
        want = _generate(model, ids, 6)
        stop_at = 2
        st, body, _ = _req(worker.address, "POST", "/v1/completions",
                           {"prompt": ids, "max_tokens": 6,
                            "stop": [want[stop_at]]})
        assert st == 200, body
        choice = body["choices"][0]
        assert choice["finish_reason"] == "stop"
        assert choice["token_ids"] == want[:stop_at]

    def test_n_two_choices_greedy_identical(self, served):
        model, _, worker = served
        ids = [5, 5, 2]
        want = _generate(model, ids, 4)
        st, body, _ = _req(worker.address, "POST", "/v1/completions",
                           {"prompt": ids, "max_tokens": 4, "n": 2})
        assert st == 200, body
        assert [c["index"] for c in body["choices"]] == [0, 1]
        for c in body["choices"]:
            assert c["token_ids"] == want
        assert body["usage"]["completion_tokens"] == 2 * len(want)

    def test_chat_completions_roundtrip(self, served):
        _, _, worker = served
        msgs = [{"role": "user", "content": "hi"}]
        st, body, _ = _req(worker.address, "POST",
                           "/v1/chat/completions",
                           {"model": MODEL_ID, "messages": msgs,
                            "max_tokens": 3})
        assert st == 200, body
        msg = body["choices"][0]["message"]
        assert msg["role"] == "assistant"
        assert isinstance(msg["content"], str)
        assert body["object"] == "chat.completion"
        want_prompt = ByteTokenizer().encode(
            apply_chat_template("plain", msgs))
        assert body["usage"]["prompt_tokens"] == len(want_prompt)

    def test_chat_stream_delta_grammar(self, served):
        _, _, worker = served
        st, chunks, _ = _stream(
            worker.address, "/v1/chat/completions",
            {"messages": [{"role": "user", "content": "go"}],
             "max_tokens": 3})
        assert st == 200
        assert chunks[0]["choices"][0]["delta"].get("role") \
            == "assistant"
        assert chunks[-1]["choices"][0]["delta"] == {}
        assert chunks[-1]["choices"][0]["finish_reason"] is not None
        assert chunks[0]["object"] == "chat.completion.chunk"

    def test_bad_bodies_answer_openai_error_objects(self, served):
        _, _, worker = served
        st, body, _ = _req(worker.address, "POST", "/v1/completions",
                           {"model": MODEL_ID})
        assert st == 400
        err = body["error"]
        assert err["type"] == "invalid_request_error"
        assert err["param"] == "prompt" and "message" in err
        st, body, _ = _req(worker.address, "POST", "/v1/completions",
                           {"model": "gpt-4o", "prompt": [1]})
        assert st == 404
        assert body["error"]["code"] == "model_not_found"

    def test_non_json_body_is_invalid(self, served):
        _, _, worker = served
        conn = http.client.HTTPConnection(*worker.address, timeout=60)
        try:
            conn.request("POST", "/v1/completions", b"not json{",
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            body = json.loads(r.read().decode())
        finally:
            conn.close()
        assert r.status == 400
        assert body["error"]["type"] == "invalid_request_error"

    def test_overload_sheds_as_429_with_retry_after(self, served,
                                                    monkeypatch):
        _, srv, worker = served

        def full(*a, **k):
            raise rel.OverloadError("queue full (max_queue=0)")
        monkeypatch.setattr(srv, "submit", full)
        st, body, hdrs = _req(worker.address, "POST",
                              "/v1/completions",
                              {"prompt": [1, 2], "max_tokens": 2})
        assert st == 429
        err = body["error"]
        assert err["type"] == "rate_limit_error"
        assert err["code"] == "rate_limit_exceeded"
        assert float(hdrs["Retry-After"]) >= 1.0

    def test_client_disconnect_aborts_and_frees_pages(self, served):
        model, srv, worker = served
        ids = [6, 2, 9, 4]
        st, _, _ = _req(worker.address, "POST", "/worker_generate",
                        {"prompt_ids": ids, "max_new_tokens": 12})
        assert st == 200
        kv = srv._kv
        pool = kv.pool
        # conservation baseline: every non-free page is indexed (the
        # radix legitimately keeps the aborted chain cached); a page
        # held by a dead slot would make the sum fall short
        page_sum = lambda: pool.free_pages() \
            + kv.index.indexed_pages()  # noqa: E731
        base_sum = page_sum()
        cancelled = lambda: obs.REGISTRY.sample_value(  # noqa: E731
            "bigdl_llm_requests_total", reason="cancelled") or 0.0
        before = cancelled()
        was = rel.enabled()
        if not was:
            rel.enable()
        plan = rel.FaultPlan(seed=0)
        plan.add("llm.step", "delay", times=None, delay=0.05)
        rel.set_plan(plan)
        try:
            conn = http.client.HTTPConnection(*worker.address,
                                              timeout=60)
            conn.request("POST", "/v1/completions",
                         json.dumps({"prompt": ids, "max_tokens": 12,
                                     "stream": True}),
                         {"Content-Type": "application/json"})
            r = conn.getresponse()
            first = r.readline()         # status is in; first event
            assert first.startswith(b"data: ")
            # a plain close() would keep the fd alive through the
            # response's makefile ref — no FIN ever reaches the server.
            # SO_LINGER(0) + closing both handles emits an RST, so the
            # next SSE write raises and the relay must abort.
            conn.sock.setsockopt(socket.SOL_SOCKET, socket.SO_LINGER,
                                 struct.pack("ii", 1, 0))
            r.close()
            conn.sock.close()
        finally:
            rel.set_plan(None)
            if not was:
                rel.disable()
        deadline = time.time() + 30
        while time.time() < deadline:
            if cancelled() > before and page_sum() >= base_sum:
                break
            time.sleep(0.05)
        assert cancelled() > before, \
            "disconnect never reached LLMServer.abort"
        assert page_sum() >= base_sum, \
            "aborted stream leaked KV pages"
        # the slot is reusable: a follow-up request answers correctly
        want = _generate(model, ids, 3)
        st, body, _ = _req(worker.address, "POST", "/v1/completions",
                           {"prompt": ids, "max_tokens": 3})
        assert st == 200 and body["choices"][0]["token_ids"] == want

    def test_api_counter_tracks_outcomes(self, served):
        _, _, worker = served
        if not obs.enabled():
            pytest.skip("observability disabled")
        val = lambda o: obs.REGISTRY.sample_value(  # noqa: E731
            "bigdl_api_requests_total", route="/v1/completions",
            outcome=o) or 0.0
        ok0, inv0 = val("ok"), val("invalid")
        _req(worker.address, "POST", "/v1/completions",
             {"prompt": [1, 2], "max_tokens": 2})
        _req(worker.address, "POST", "/v1/completions", {})
        assert val("ok") == ok0 + 1
        assert val("invalid") == inv0 + 1


class TestGateOff:
    def test_disabled_worker_404s_naming_the_gate(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        worker = LLMWorker(srv).start()
        before = set(obs.render().splitlines()) if obs.enabled() \
            else set()
        try:
            assert worker._api is None
            for method, path in (("GET", "/v1/models"),
                                 ("POST", "/v1/completions"),
                                 ("POST", "/v1/chat/completions")):
                st, body, _ = _req(worker.address, method, path,
                                   {} if method == "POST" else None)
                assert st == 404, (path, st, body)
                assert "bigdl.llm.api.enabled" in body["error"]
            # the native surface still works and grew no api series
            st, out, _ = _req(worker.address, "POST",
                              "/worker_generate",
                              {"prompt_ids": [1, 2],
                               "max_new_tokens": 2})
            assert st == 200 and len(out["output_ids"]) == 2
            if obs.enabled():
                new = set(obs.render().splitlines()) - before
                assert not [ln for ln in new if "bigdl_api_" in ln], \
                    "gate-off serving grew bigdl_api_* series"
        finally:
            worker.stop()
            srv.stop()

    def test_router_gateway_requires_failover(self, model):
        with pytest.raises(ValueError, match="failover"):
            LLMRouter([], [("127.0.0.1", 1)], start_prober=False,
                      api=True)


# ---------------------------------------------------------------------------
# live router: SSE relay over the failover journal
# ---------------------------------------------------------------------------

class TestRouterGateway:
    @pytest.fixture(scope="class")
    def fleet(self, model):
        servers = [LLMServer(model, max_batch=2, max_seq_len=64,
                             page_size=8, kvcache=True,
                             slo=True).start() for _ in range(2)]
        workers = [LLMWorker(s, role="decode").start() for s in servers]
        router = LLMRouter([], [w.address for w in workers],
                           failover=True, start_prober=False,
                           slo=True, api=True).start()
        yield model, servers, workers, router
        router.stop()
        for w in workers:
            w.stop()
        for s in servers:
            s.stop()

    def _slo(self):
        if not obs.enabled():
            return None
        reg = obs.REGISTRY
        return {
            "ttft": reg.sample_value("bigdl_router_ttft_seconds")
            or 0.0,
            "itl": reg.sample_value("bigdl_router_itl_seconds") or 0.0}

    def test_streams_bit_identical_with_one_slo_accounting(self, fleet):
        model, _, _, router = fleet
        prompts = [[3, 1, 4, 1, 5], [9, 2, 6], [5, 3, 5, 8, 9, 7]]
        want = [_generate(model, p, 5) for p in prompts]
        before = self._slo()
        got = []
        for p in prompts:
            st, chunks, _ = _stream(router.address, "/v1/completions",
                                    {"model": MODEL_ID, "prompt": p,
                                     "max_tokens": 5})
            assert st == 200, chunks
            got.append([t for c in chunks
                        for t in c["choices"][0].get("token_ids", [])])
            assert chunks[-1]["usage"]["completion_tokens"] == 5
        assert got == want
        after = self._slo()
        if after is not None:
            # the SSE relay and the router SLO sketches fire from the
            # same journal drain: requests stamped exactly once
            assert after["ttft"] - before["ttft"] == len(prompts)
            assert after["itl"] - before["itl"] == \
                sum(len(w) - 1 for w in want)

    def test_blocking_matches_native_route(self, fleet):
        model, _, _, router = fleet
        ids = [7, 7, 2, 1]
        want = _generate(model, ids, 4)
        st, native, _ = _req(router.address, "POST",
                             "/worker_generate",
                             {"prompt_ids": ids, "max_new_tokens": 4})
        assert st == 200 and native["output_ids"] == want
        st, body, _ = _req(router.address, "POST", "/v1/completions",
                           {"prompt": ids, "max_tokens": 4})
        assert st == 200 and body["choices"][0]["token_ids"] == want

    def test_models_route_on_router(self, fleet):
        _, _, _, router = fleet
        st, body, _ = _req(router.address, "GET", "/v1/models")
        assert st == 200
        assert body["data"][0]["id"] == MODEL_ID


# ---------------------------------------------------------------------------
# langchain base_url client helper (satellite)
# ---------------------------------------------------------------------------

class TestLangchainClient:
    @pytest.fixture(scope="class")
    def served(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8, kvcache=True).start()
        worker = LLMWorker(srv, api=True,
                           tokenizer=ByteTokenizer()).start()
        yield model, srv, worker
        worker.stop()
        srv.stop()

    def test_invoke_models_stream_and_chat(self, served):
        from bigdl_tpu.llm.langchain import BigdlTpuOpenAI
        _, _, worker = served
        host, port = worker.address
        llm = BigdlTpuOpenAI(f"http://{host}:{port}/v1",
                             max_tokens=4)
        assert llm.models() == [MODEL_ID]
        blocking = llm.invoke("hello")
        assert isinstance(blocking, str)
        streamed = "".join(llm.stream("hello"))
        assert streamed == blocking      # greedy: same text both ways
        answer = llm.chat([{"role": "user", "content": "hello"}])
        assert isinstance(answer, str)

    def test_base_url_parsing(self):
        from bigdl_tpu.llm.langchain import BigdlTpuOpenAI
        assert BigdlTpuOpenAI._parse("http://h:8000/v1") == ("h", 8000)
        assert BigdlTpuOpenAI._parse("h:8000") == ("h", 8000)
        with pytest.raises(ValueError):
            BigdlTpuOpenAI._parse("http://no-port/v1")

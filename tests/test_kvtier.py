"""Tiered KV cache (ISSUE 6): host-arena/migration/handoff units,
spill→reload greedy parity on the live engine, fetch-failure
degradation, disaggregated handoff + router, and the disabled-mode
structural-absence contract.

Engine tests run the migrator in SYNCHRONOUS mode
(``bigdl.llm.kvtier.sync``) unless they specifically exercise the
background thread — inline migration is the suite's fake clock: no
sleeps, deterministic landing order, tier-1 friendly."""

import http.client
import json

import numpy as np
import pytest

from bigdl_tpu.llm.kvtier import (HostArena, HostArenaError, KVTier,
                                  Migrator, deserialize_chain,
                                  serialize_chain)
from bigdl_tpu.llm.kvtier.handoff import HandoffError
from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import LLMServer
from bigdl_tpu.utils.conf import conf

pytestmark = pytest.mark.kvtier

PAGE = 8


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=128)


@pytest.fixture()
def sync_tier():
    """Inline migration for deterministic, sleep-free engine tests."""
    conf.set("bigdl.llm.kvtier.sync", "true")
    yield
    conf.unset("bigdl.llm.kvtier.sync")


def _generate(model, p, n):
    return model.generate(np.asarray(p)[None], max_new_tokens=n)[0, len(p):]


def _page(v, l=2, h=1, d=4):
    return np.full((l, h, PAGE, d), v, np.float32)


# ---------------------------------------------------------------------------
# host arena: slots, LRU, pins
# ---------------------------------------------------------------------------

class TestHostArena:
    def test_reserve_commit_lookup(self):
        a = HostArena(4, PAGE)
        key = tuple(range(PAGE))
        slot = a.reserve(key)
        # not ready yet: lookups must not serve an uncommitted slot
        assert a.lookup_chunks(range(PAGE + 4), 0, PAGE + 3) == []
        a.commit(slot, _page(1.0), _page(2.0))
        hits = a.lookup_chunks(range(PAGE + 4), 0, PAGE + 3)
        assert hits == [(key, slot)]
        k, v = a.read(slot)
        assert k[0, 0, 0, 0] == 1.0 and v[0, 0, 0, 0] == 2.0
        # consecutive-chunk walk stops at the first hole
        key2 = tuple(range(2 * PAGE))
        s2 = a.reserve(key2)
        a.commit(s2, _page(3.0), _page(3.0))
        toks = list(range(3 * PAGE))
        assert [s for _, s in a.lookup_chunks(toks, 0, 3 * PAGE - 1)] \
            == [slot, s2]

    def test_partial_key_rejected(self):
        a = HostArena(2, PAGE)
        with pytest.raises(HostArenaError, match="full pages"):
            a.reserve(tuple(range(PAGE - 1)))

    def test_lru_eviction_skips_pinned(self):
        a = HostArena(2, PAGE)
        s0 = a.reserve(tuple(range(PAGE)))
        a.commit(s0, _page(0), _page(0))
        s1 = a.reserve(tuple(range(100, 100 + PAGE)))
        a.commit(s1, _page(1), _page(1))
        a.lookup_chunks(range(PAGE), 0, PAGE)       # re-warm s0
        a.pin(s0)
        # full arena + a third key: the unpinned LRU (s1) must go even
        # though s0 is older by insertion
        s2 = a.reserve(tuple(range(200, 200 + PAGE)))
        assert s2 == s1
        assert a.host_evictions == 1
        a.commit(s2, _page(2), _page(2))
        assert a.lookup_chunks(range(100, 100 + PAGE), 0, PAGE) == []
        # everything pinned: reserve degrades to None (spill skipped)
        a.unpin(s0)
        a.pin(s0)
        a.pin(s2)
        assert a.reserve(tuple(range(300, 300 + PAGE))) is None

    def test_abort_removes_entry(self):
        a = HostArena(2, PAGE)
        key = tuple(range(PAGE))
        slot = a.reserve(key)
        a.abort(slot)
        assert a.used() == 0 and a.pinned() == 0
        # a re-reserve gets a fresh claim
        assert a.reserve(key) is not None


# ---------------------------------------------------------------------------
# migrator: spill/fetch round trip, failure hygiene
# ---------------------------------------------------------------------------

class TestMigrator:
    def test_sync_spill_then_fetch_roundtrip(self):
        import jax.numpy as jnp
        arena = HostArena(4, PAGE)
        mig = Migrator(arena, synchronous=True)
        key = tuple(range(PAGE))
        slot = arena.reserve(key)
        k_dev = jnp.asarray(_page(3.5))
        v_dev = jnp.asarray(_page(4.5))
        job = mig.submit_spill(key, slot, k_dev, v_dev)
        assert job.done.is_set() and job.ok
        arena.pin(slot)
        fj = mig.submit_fetch([(key, slot)])
        assert fj.ok and arena.pinned() == 0     # worker unpinned
        np.testing.assert_array_equal(np.asarray(fj.k_dev[0]),
                                      _page(3.5))
        np.testing.assert_array_equal(np.asarray(fj.v_dev[0]),
                                      _page(4.5))
        assert mig.spills_done == 1 and mig.fetches_done == 1

    def test_injected_spill_failure_aborts_entry(self):
        import jax.numpy as jnp
        from bigdl_tpu import reliability as rel
        arena = HostArena(4, PAGE)
        mig = Migrator(arena, synchronous=True)
        plan = rel.FaultPlan(seed=0)
        plan.add("kvtier.spill", "raise", times=1)
        rel.set_plan(plan)
        try:
            slot = arena.reserve(tuple(range(PAGE)))
            job = mig.submit_spill(tuple(range(PAGE)), slot,
                                   jnp.zeros((2, 1, PAGE, 4)),
                                   jnp.zeros((2, 1, PAGE, 4)))
        finally:
            rel.set_plan(None)
        assert not job.ok and mig.spill_failures == 1
        assert arena.used() == 0 and arena.pinned() == 0

    def test_injected_fetch_failure_unpins(self):
        from bigdl_tpu import reliability as rel
        arena = HostArena(4, PAGE)
        mig = Migrator(arena, synchronous=True)
        slot = arena.reserve(tuple(range(PAGE)))
        arena.commit(slot, _page(0), _page(0))
        plan = rel.FaultPlan(seed=0)
        plan.add("kvtier.fetch", "raise", times=1)
        rel.set_plan(plan)
        try:
            arena.pin(slot)
            job = mig.submit_fetch([(tuple(range(PAGE)), slot)])
        finally:
            rel.set_plan(None)
        assert not job.ok and mig.fetch_failures == 1
        assert arena.pinned() == 0               # pin released anyway


# ---------------------------------------------------------------------------
# handoff blobs
# ---------------------------------------------------------------------------

class TestHandoff:
    @pytest.mark.parametrize("dtype", ["float32", "bfloat16"])
    def test_roundtrip_bit_exact(self, dtype):
        import jax.numpy as jnp
        dt = jnp.dtype(dtype)
        rs = np.random.RandomState(0)
        pages = [rs.randn(2, 1, PAGE, 4).astype(dt) for _ in range(3)]
        toks = list(range(3 * PAGE))
        blob = serialize_chain(toks, pages, pages[::-1], PAGE)
        t2, k2, v2, hdr = deserialize_chain(blob)
        assert t2 == toks and hdr["dtype"] == dtype
        for a, b in zip(pages, k2):
            np.testing.assert_array_equal(np.asarray(a), b)
        for a, b in zip(pages[::-1], v2):
            np.testing.assert_array_equal(np.asarray(a), b)

    def test_malformed_blobs_rejected(self):
        with pytest.raises(HandoffError, match="magic"):
            deserialize_chain(b"nonsense")
        blob = serialize_chain(list(range(PAGE)), [_page(0)],
                               [_page(0)], PAGE)
        with pytest.raises(HandoffError, match="body holds"):
            deserialize_chain(blob[:-8])


# ---------------------------------------------------------------------------
# engine: spill -> reload parity (the acceptance matrix)
# ---------------------------------------------------------------------------

class TestSpillReloadParity:
    @pytest.mark.parametrize("depth", [1, 2])
    def test_eviction_hammer_reloads_from_host(self, model, depth,
                                               sync_tier):
        """A pool sized for ~2 of 4 chains: pass 1 seeds and spills,
        pass 2 re-adopts every evicted prefix FROM THE HOST ARENA —
        greedy outputs must match generate() exactly at both pipeline
        depths, and the budget/pin ledgers must come back whole."""
        rs = np.random.RandomState(17)
        groups = [rs.randint(0, 250, 16).astype(np.int32)
                  for _ in range(4)]
        prompts = []
        for rnd in range(2):
            for g in range(4):
                prompts.append(np.concatenate(
                    [groups[g], rs.randint(0, 250, 2 + (g + rnd) % 3)
                     .astype(np.int32)]))
        lens = [int(rs.randint(2, 5)) for _ in prompts]
        want = [_generate(model, p, n) for p, n in zip(prompts, lens)]
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, num_pages=9, kvcache=True,
                        kvtier=True, host_pages=32,
                        pipeline_depth=depth).start()
        try:
            got = [srv.submit(p, max_new_tokens=n).get(timeout=600)
                   for p, n in zip(prompts, lens)]
            spills, fetches = srv._tier.spills, srv._tier.fetches
            st = srv._kv.debug_stats()
        finally:
            srv.stop()
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        assert spills > 0 and fetches > 0   # the tier actually worked
        # refcount/pin invariants across migration: every grant
        # returned, nothing pinned, arena pins drained
        assert st["pages_pinned"] == 0
        assert st["budget_avail"] == 9 - 1
        assert st["tier"]["pinned"] == 0
        assert st["tier"]["fetch_failures"] == 0

    def test_async_migration_thread_parity(self, model):
        """Same workload through the REAL background migration thread
        (the default): landing order is now racy against admission —
        outputs must not care."""
        rs = np.random.RandomState(29)
        groups = [rs.randint(0, 250, 16).astype(np.int32)
                  for _ in range(4)]
        prompts = [np.concatenate(
            [groups[g % 4], rs.randint(0, 250, 2 + g % 3)
             .astype(np.int32)]) for g in range(8)]
        want = [_generate(model, p, 3) for p in prompts]
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, num_pages=9, kvcache=True,
                        kvtier=True, host_pages=32).start()
        try:
            got = [srv.submit(p, max_new_tokens=3).get(timeout=600)
                   for p in prompts]
            assert srv._tier.spills > 0
        finally:
            srv.stop()
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")

    def test_failed_fetch_degrades_to_miss(self, model, sync_tier):
        """kvtier.fetch raises: the admission must fall back to a full
        prefill with identical greedy output — a failed fetch is a
        cache miss, never a stall or a wrong token."""
        from bigdl_tpu import reliability as rel
        rs = np.random.RandomState(31)
        groups = [rs.randint(0, 250, 16).astype(np.int32)
                  for _ in range(4)]
        prompts = [np.concatenate(
            [groups[j % 4], rs.randint(0, 250, 2 + j % 3)
             .astype(np.int32)]) for j in range(8)]
        want = [_generate(model, p, 3) for p in prompts]
        plan = rel.FaultPlan(seed=3)
        plan.add("kvtier.fetch", "raise", times=None)  # EVERY fetch
        rel.set_plan(plan)
        try:
            srv = LLMServer(model, max_batch=2, max_seq_len=64,
                            page_size=PAGE, num_pages=9, kvcache=True,
                            kvtier=True, host_pages=32).start()
            try:
                got = [srv.submit(p, max_new_tokens=3).get(timeout=600)
                       for p in prompts]
                failures = srv._tier.fetch_failures
                st = srv._kv.debug_stats()
            finally:
                srv.stop()
        finally:
            rel.set_plan(None)
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        assert failures > 0                  # the fault really fired
        assert st["budget_avail"] == 9 - 1   # degraded charges returned
        assert st["pages_pinned"] == 0


# ---------------------------------------------------------------------------
# disaggregated handoff: engine-level and through the router
# ---------------------------------------------------------------------------

class TestHandoffEngine:
    def test_export_import_roundtrip_parity(self, model, sync_tier):
        """Prefill on server A, handoff, decode on server B: B's
        output must equal a single-server run, and B must have served
        the prompt from its host tier (fetches > 0)."""
        prompt = np.arange(1, 21, dtype=np.int32)    # 2 full pages
        want = _generate(model, prompt, 5)
        a = LLMServer(model, max_batch=2, max_seq_len=64,
                      page_size=PAGE, kvcache=True, kvtier=True).start()
        b = LLMServer(model, max_batch=2, max_seq_len=64,
                      page_size=PAGE, kvcache=True, kvtier=True).start()
        try:
            a.submit(prompt, max_new_tokens=1).get(timeout=600)
            blob = a.export_chain(prompt)
            assert a._tier.handoffs_out == 1
            n = b.import_chain(blob)
            assert n == len(prompt) // PAGE == 2
            got = b.submit(prompt, max_new_tokens=5).get(timeout=600)
            assert b._tier.fetches >= n
            assert b._tier.handoffs_in == 1
        finally:
            a.stop()
            b.stop()
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_import_rejects_mismatched_geometry(self, model, sync_tier):
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, kvcache=True, kvtier=True)
        try:
            blob = serialize_chain(list(range(16)),
                                   [np.zeros((1, 1, 16, 2), np.float32)],
                                   [np.zeros((1, 1, 16, 2), np.float32)],
                                   16)
            with pytest.raises(HandoffError, match="do not fit"):
                srv.import_chain(blob)
        finally:
            srv.stop()

    def test_handoff_needs_tier(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        page_size=PAGE, kvcache=True)
        try:
            with pytest.raises(RuntimeError, match="kvtier"):
                srv.export_chain(np.arange(8, dtype=np.int32))
            with pytest.raises(RuntimeError, match="kvtier"):
                srv.import_chain(b"BDKV1\n")
        finally:
            srv.stop()


def _req(addr, method, path, body=None, headers=None):
    conn = http.client.HTTPConnection(*addr, timeout=120)
    try:
        payload = json.dumps(body) if body is not None else None
        conn.request(method, path, payload,
                     dict(headers or {},
                          **({"Content-Type": "application/json"}
                             if body is not None else {})))
        r = conn.getresponse()
        data = json.loads(r.read().decode())
        return r.status, data, dict(r.getheaders())
    finally:
        conn.close()


class TestRouterDisaggregated:
    def test_prefill_decode_split_end_to_end(self, model, sync_tier):
        """The acceptance scenario: a prefill-role worker and a
        decode-role worker complete a request via KV handoff, the
        output is bit-identical to generate(), and the stitched trace
        shows spans from BOTH workers under one id."""
        from bigdl_tpu import observability as obs
        from bigdl_tpu.llm.worker import LLMRouter, LLMWorker
        prompt = list(range(1, 21))
        want = _generate(model, np.asarray(prompt, np.int32), 5)
        pf_srv = LLMServer(model, max_batch=2, max_seq_len=64,
                           page_size=PAGE, kvcache=True,
                           kvtier=True).start()
        de_srv = LLMServer(model, max_batch=2, max_seq_len=64,
                           page_size=PAGE, kvcache=True,
                           kvtier=True).start()
        pf = LLMWorker(pf_srv, role="prefill").start()
        de = LLMWorker(de_srv, role="decode").start()
        router = LLMRouter([pf.address], [de.address]).start()
        try:
            status, body, hdrs = _req(
                router.address, "POST", "/worker_generate",
                {"prompt_ids": prompt, "max_new_tokens": 5})
            assert status == 200, body
            np.testing.assert_array_equal(
                np.asarray(body["output_ids"]), want)
            assert de_srv._tier.handoffs_in == 1
            assert de_srv._tier.fetches > 0     # served from the tier
            assert router.handoffs_routed == 1
            # role gating: misrouted calls answer 403
            s403, _, _ = _req(pf.address, "POST", "/worker_generate",
                              {"prompt_ids": prompt})
            assert s403 == 403
            s403, _, _ = _req(de.address, "POST", "/worker_prefill",
                              {"prompt_ids": prompt})
            assert s403 == 403
            # stitched trace across router + both workers (same-process
            # ring): decode AND handoff-export spans under one id
            trace_id = hdrs.get(obs.TRACE_HEADER)
            if trace_id:                     # observability enabled
                st, tr, _ = _req(router.address, "GET",
                                 f"/debug/trace/{trace_id}")
                assert st == 200
                names = {s["name"] for s in tr["spans"]}
                assert "llm/handoff_export" in names
                assert "llm/handoff_import" in names
                assert "llm/decode" in names
                assert "llm/route" in names
            # router surfaces: healthz + status
            st, hz, _ = _req(router.address, "GET", "/healthz")
            assert st == 200 and hz["role"] == "router"
            st, ws, _ = _req(pf.address, "GET", "/worker_get_status")
            assert ws["role"] == "prefill"
        finally:
            router.stop()
            pf.stop()
            de.stop()
            pf_srv.stop()
            de_srv.stop()

    def test_import_chain_endpoint_direct(self, model, sync_tier):
        """`POST /worker_import_chain` exercised through the HTTP
        surface itself (the router path covers it indirectly): a blob
        exported engine-side lands via the worker and reports its page
        count; malformed payloads answer 400."""
        import base64

        from bigdl_tpu.llm.worker import LLMWorker
        prompt = np.arange(1, 21, dtype=np.int32)      # 2 full pages
        a = LLMServer(model, max_batch=2, max_seq_len=64,
                      page_size=PAGE, kvcache=True, kvtier=True).start()
        b_srv = LLMServer(model, max_batch=2, max_seq_len=64,
                          page_size=PAGE, kvcache=True,
                          kvtier=True).start()
        w = LLMWorker(b_srv, role="decode").start()
        try:
            a.submit(prompt, max_new_tokens=1).get(timeout=600)
            blob = a.export_chain(prompt)
            st, body, _ = _req(
                w.address, "POST", "/worker_import_chain",
                {"handoff": base64.b64encode(blob).decode()})
            assert st == 200, body
            assert body["imported_pages"] == len(prompt) // PAGE
            assert b_srv._tier.handoffs_in == 1
            st, body, _ = _req(w.address, "POST",
                               "/worker_import_chain",
                               {"handoff": "!!!not-base64"})
            assert st == 400
        finally:
            w.stop()
            a.stop()
            b_srv.stop()

    def test_router_relays_decode_shed_without_tripping_breaker(self):
        """A 503 from a decode backend is backpressure, not death: the
        router must relay it with Retry-After and keep the breaker
        closed (a tripped breaker would evict a healthy-but-busy
        worker from the pool)."""
        import json as _json
        from http.server import (BaseHTTPRequestHandler,
                                 ThreadingHTTPServer)
        import threading

        from bigdl_tpu.llm.worker import LLMRouter

        class Shedding(BaseHTTPRequestHandler):
            def log_message(self, *a):
                pass

            def do_POST(self):
                self.rfile.read(int(self.headers.get("Content-Length",
                                                     0)))
                body = _json.dumps({"error": "queue full"}).encode()
                self.send_response(503)
                self.send_header("Retry-After", "1")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        httpd = ThreadingHTTPServer(("127.0.0.1", 0), Shedding)
        threading.Thread(target=httpd.serve_forever,
                         daemon=True).start()
        router = LLMRouter([], [httpd.server_address],
                           breaker_threshold=2).start()
        try:
            for _ in range(4):   # > breaker_threshold sheds in a row
                status, body, hdrs = _req(
                    router.address, "POST", "/worker_generate",
                    {"prompt_ids": [1, 2, 3], "max_new_tokens": 2})
                assert status == 503, body
                assert hdrs.get("Retry-After") == "1"
            addr = router.decode_workers[0]
            assert router._breakers[addr].state == "closed"
        finally:
            router.stop()
            httpd.shutdown()
            httpd.server_close()

    def test_router_degrades_without_prefill_pool(self, model,
                                                  sync_tier):
        """Prefill stage down (no backends): the router routes straight
        to decode, which prefills itself — same tokens, one degraded
        counter."""
        from bigdl_tpu.llm.worker import LLMRouter, LLMWorker
        prompt = list(range(1, 15))
        want = _generate(model, np.asarray(prompt, np.int32), 4)
        de_srv = LLMServer(model, max_batch=2, max_seq_len=64,
                           page_size=PAGE, kvcache=True,
                           kvtier=True).start()
        de = LLMWorker(de_srv, role="decode").start()
        # a dead prefill backend address: breaker opens, router degrades
        router = LLMRouter([("127.0.0.1", 1)], [de.address],
                           breaker_threshold=1).start()
        try:
            status, body, _ = _req(
                router.address, "POST", "/worker_generate",
                {"prompt_ids": prompt, "max_new_tokens": 4})
            assert status == 200, body
            np.testing.assert_array_equal(
                np.asarray(body["output_ids"]), want)
            assert router.prefill_degraded == 1
        finally:
            router.stop()
            de.stop()
            de_srv.stop()


# ---------------------------------------------------------------------------
# microbench + chaos flows (kept out of tier-1 by the slow marker)
# ---------------------------------------------------------------------------

class TestTierFlows:
    @pytest.mark.perf
    @pytest.mark.slow
    def test_microbench_reports_savings(self, model):
        """tools/microbench_tier.py end-to-end: the tier-on replay must
        fetch from the arena and delete re-prefill tokens (latency
        values advisory on shared CI hosts)."""
        from tools.microbench_tier import run_tier_bench

        out = run_tier_bench(n_groups=4, shared_len=24, tail_len=4,
                             new_tokens=3, page_size=8, model=model)
        assert out["prefill_tokens_saved_vs_off"] > 0
        assert out["tier_on"]["fetches"] > 0
        assert out["tier_on"]["hit_rate"] > out["tier_off"]["hit_rate"]

    @pytest.mark.chaos
    @pytest.mark.slow
    def test_chaos_migration_faults_keep_parity(self):
        """tools/chaos_check.py --kvtier: delayed + failed spills and
        fetches must leave greedy outputs identical to the clean
        tier-on run."""
        from tools.chaos_check import run_kvtier_chaos

        out = run_kvtier_chaos(seed=0)
        assert out["match"] and out["clean_fetches"] > 0


# ---------------------------------------------------------------------------
# disabled mode: structurally absent
# ---------------------------------------------------------------------------

class TestDisabledMode:
    def test_no_tier_no_series_no_debug_block(self, model):
        from bigdl_tpu import observability as obs
        # the gate defaults off (gatecheck absence-test contract)
        assert conf.get_bool("bigdl.llm.kvtier.enabled", False) is False
        # registry is process-global (earlier enabled-mode tests minted
        # bigdl_kvtier_* series), so structural absence is a DELTA: a
        # tier-off server must declare nothing new
        before = len(obs.REGISTRY.collect())
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        page_size=PAGE, kvcache=True)
        assert srv._tier is None
        assert srv._kv.tier is None
        req = srv.submit(np.array([3, 1, 4], np.int32), max_new_tokens=3)
        while not req.done.is_set():
            srv._admit()
            srv._step()
        assert len(obs.REGISTRY.collect()) == before
        assert "tier" not in srv._kv.debug_stats()

    def test_tier_requires_prefix_cache(self, model):
        with pytest.raises(ValueError, match="kvcache"):
            LLMServer(model, max_batch=2, max_seq_len=32,
                      page_size=PAGE, kvcache=False, kvtier=True)

    def test_enabled_declares_series(self, model, sync_tier):
        from bigdl_tpu import observability as obs
        rs = np.random.RandomState(5)
        shared = rs.randint(0, 250, 16).astype(np.int32)
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, num_pages=9, kvcache=True,
                        kvtier=True, host_pages=32).start()
        try:
            for j in range(4):
                srv.submit(np.concatenate(
                    [shared, rs.randint(0, 250, 2 + j)
                     .astype(np.int32)]),
                    max_new_tokens=3).get(timeout=600)
        finally:
            srv.stop()
        text = obs.render()
        for name in ("bigdl_kvtier_spills_total",
                     "bigdl_kvtier_fetches_total",
                     "bigdl_kvtier_host_pages_used",
                     "bigdl_kvtier_host_pages"):
            assert name in text
        # the /debug/kvcache tier block carries occupancy + migrations
        st = srv._kv.debug_stats()["tier"]
        assert {"capacity", "used", "spills", "fetches",
                "inflight_migrations", "handoff_bytes"} <= set(st)

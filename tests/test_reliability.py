"""ISSUE 2 reliability layer: fault injection, policy primitives, atomic
checkpoints, preemption round-trip, serving backpressure.

Everything here is tier-1: retry/breaker schedules run on fake clocks
(zero real sleeping), training cases use tiny MLPs, and the HTTP cases
use the in-proc queue backend.
"""

import http.client
import json
import os
import sys
import threading
import time
import types

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu import reliability as rel
from bigdl_tpu.reliability.policies import (CircuitBreaker, Deadline,
                                            RetryPolicy)
from bigdl_tpu.utils import checkpoint as ckpt
from bigdl_tpu.utils.conf import conf


@pytest.fixture(autouse=True)
def _clean_reliability_state():
    """Each test starts enabled with no plan armed and no leftover
    health checks; counters reset so assertions are local."""
    rel.enable()
    rel.set_plan(None)
    for name in list(rel.health_checks()):
        rel.unregister_health(name)
    obs.reset()
    yield
    rel.enable()
    rel.set_plan(None)
    for name in list(rel.health_checks()):
        rel.unregister_health(name)
    obs.reset()


def _counter_value(_metric, **labels):
    m = obs.REGISTRY.get(_metric)
    if m is None:
        return 0.0
    child = m.labels(**labels) if labels else m
    return child.value


# ---------------------------------------------------------------------------
# policies: RetryPolicy / Deadline / CircuitBreaker (fake clocks, no sleeps)
# ---------------------------------------------------------------------------

class TestRetryPolicy:
    def test_backoff_schedule_exponential_and_capped(self):
        p = RetryPolicy(max_attempts=6, base_delay=0.1, max_delay=0.5,
                        multiplier=2.0, jitter=0.0, seed=0)
        delays = list(p.delays())
        assert delays == [0.1, 0.2, 0.4, 0.5, 0.5]

    def test_jitter_is_seeded_and_bounded(self):
        a = list(RetryPolicy(max_attempts=4, base_delay=0.1, jitter=0.5,
                             seed=7).delays())
        b = list(RetryPolicy(max_attempts=4, base_delay=0.1, jitter=0.5,
                             seed=7).delays())
        assert a == b                      # same seed, same schedule
        for base, d in zip([0.1, 0.2, 0.4], a):
            assert base <= d <= base * 1.5

    def test_call_retries_then_succeeds_without_sleeping(self):
        slept = []
        p = RetryPolicy(max_attempts=4, base_delay=0.1, jitter=0.0,
                        sleep=slept.append)
        calls = {"n": 0}

        def flaky():
            calls["n"] += 1
            if calls["n"] < 3:
                raise IOError("transient")
            return "ok"

        assert p.call(flaky, component="test") == "ok"
        assert calls["n"] == 3
        assert slept == [0.1, 0.2]
        assert _counter_value("bigdl_reliability_retries_total",
                              component="test") == 2

    def test_budget_exhausted_reraises_last_error(self):
        p = RetryPolicy(max_attempts=3, base_delay=0.01, jitter=0.0,
                        sleep=lambda s: None)
        calls = {"n": 0}

        def always():
            calls["n"] += 1
            raise ValueError("permanent")

        with pytest.raises(ValueError, match="permanent"):
            p.call(always)
        assert calls["n"] == 3             # attempts, not retries

    def test_deadline_cuts_retries_short(self):
        t = {"now": 0.0}
        d = Deadline(0.15, clock=lambda: t["now"])
        p = RetryPolicy(max_attempts=10, base_delay=0.1, jitter=0.0,
                        sleep=lambda s: t.__setitem__("now",
                                                      t["now"] + s))

        def always():
            raise IOError("down")

        # retry delays would sum past the deadline: raises the op error
        # (not DeadlineExceeded) once sleeping further would be pointless
        with pytest.raises(IOError):
            p.call(always, deadline=d)
        assert t["now"] <= 0.15


class TestDeadline:
    def test_expiry_on_fake_clock(self):
        t = {"now": 100.0}
        d = Deadline(0.5, clock=lambda: t["now"])
        assert not d.expired()
        assert 0.4 < d.remaining() <= 0.5
        t["now"] += 1.0
        assert d.expired()
        with pytest.raises(rel.DeadlineExceeded):
            d.check("unit test")
        assert _counter_value(
            "bigdl_reliability_deadline_expired_total") == 1

    def test_header_roundtrip(self):
        d = Deadline(1.0)
        ms = int(d.to_header())
        assert 0 < ms <= 1000
        d2 = Deadline.from_header(str(ms))
        assert d2 is not None and d2.remaining() <= 1.0
        assert Deadline.from_header(None) is None
        assert Deadline.from_header("garbage") is None


class TestCircuitBreaker:
    def test_state_machine(self):
        t = {"now": 0.0}
        br = CircuitBreaker("t", failure_threshold=3, reset_timeout=10.0,
                            clock=lambda: t["now"])
        assert br.state == "closed"
        for _ in range(2):
            br.record_failure()
        assert br.state == "closed"        # below threshold
        br.record_failure()
        assert br.state == "open"
        assert not br.allow()
        with pytest.raises(rel.CircuitOpenError):
            br.call(lambda: "never")
        t["now"] = 10.0                    # reset timeout elapses
        assert br.state == "half_open"
        assert br.allow()
        br.record_failure()                # probe fails -> reopen
        assert br.state == "open"
        t["now"] = 20.0
        assert br.call(lambda: "probe") == "probe"   # probe succeeds
        assert br.state == "closed"
        # trips and recoveries are visible on /metrics
        assert _counter_value(
            "bigdl_reliability_breaker_transitions_total",
            name="t", state="open") == 2
        assert _counter_value(
            "bigdl_reliability_breaker_transitions_total",
            name="t", state="closed") == 1


# ---------------------------------------------------------------------------
# fault injection registry
# ---------------------------------------------------------------------------

class TestFaultInjection:
    def test_noop_without_plan(self):
        assert rel.inject("checkpoint.write") is None
        assert rel.armed_sites() == []

    def test_plan_fires_deterministically_and_counts(self):
        plan = rel.FaultPlan(seed=3)
        plan.add("optimizer.step", "raise", after=1, times=1)
        rel.set_plan(plan)
        assert rel.inject("optimizer.step") is None     # after=1 skips
        with pytest.raises(rel.InjectedFault):
            rel.inject("optimizer.step")
        assert rel.inject("optimizer.step") is None     # times=1 spent
        assert plan.fired == [("optimizer.step", "raise")]
        assert _counter_value(
            "bigdl_reliability_injected_faults_total",
            site="optimizer.step", action="raise") == 1

    def test_glob_sites_and_corrupt_action(self):
        plan = rel.FaultPlan()
        plan.add("checkpoint.*", "corrupt", times=2)
        rel.set_plan(plan)
        assert rel.inject("checkpoint.write.arrays") == "corrupt"
        assert rel.inject("checkpoint.commit") == "corrupt"
        assert rel.inject("checkpoint.load") is None
        assert rel.armed_sites() == ["checkpoint.*"]

    def test_delay_action_sleeps(self):
        plan = rel.FaultPlan()
        plan.add("serving.batch", "delay", delay=0.02, times=1)
        rel.set_plan(plan)
        t0 = time.perf_counter()
        assert rel.inject("serving.batch") == "delay"
        assert time.perf_counter() - t0 >= 0.015

    def test_randomize_is_reproducible(self):
        sites_a = rel.FaultPlan(seed=5).randomize(6).sites()
        sites_b = rel.FaultPlan(seed=5).randomize(6).sites()
        assert sites_a == sites_b


# ---------------------------------------------------------------------------
# atomic checkpoints
# ---------------------------------------------------------------------------

def _tree():
    return {"w": np.arange(12, dtype=np.float32).reshape(3, 4), "step": 7}


class TestAtomicCheckpoint:
    def test_roundtrip_and_checksums(self, tmp_path):
        p = str(tmp_path / "optim.1.1")
        ckpt.save_checkpoint(p, _tree())
        assert ckpt.verify_checkpoint(p)
        tree, _ = ckpt.load_checkpoint(p, to_jax=False)
        np.testing.assert_array_equal(tree["w"], _tree()["w"])
        with open(os.path.join(p, "manifest.json")) as f:
            manifest = json.load(f)
        assert "arrays.safetensors" in manifest["files"]
        assert manifest["files"]["arrays.safetensors"]["sha256"]

    def test_writer_killed_between_arrays_and_manifest(self, tmp_path):
        """Satellite regression: the seed wrote arrays then manifest into
        the LIVE dir — a crash between the two left a half-checkpoint
        recovery would happily load. Now the partial write stays in a
        .tmp sibling: never loadable, never visible to latest()."""
        root = str(tmp_path)
        p = os.path.join(root, "optim.1.1")
        plan = rel.FaultPlan()
        plan.add("checkpoint.write.manifest", "raise", times=1)
        rel.set_plan(plan)
        with pytest.raises(rel.InjectedFault):
            ckpt.save_checkpoint(p, _tree())
        rel.set_plan(None)
        assert not os.path.exists(p)            # nothing published
        assert ckpt.latest(root) is None        # nothing to resume from
        with pytest.raises(Exception):
            ckpt.load_checkpoint(p)
        # and a crash during commit also publishes nothing
        plan = rel.FaultPlan()
        plan.add("checkpoint.commit", "raise", times=1)
        rel.set_plan(plan)
        with pytest.raises(rel.InjectedFault):
            ckpt.save_checkpoint(p, _tree())
        assert ckpt.latest(root) is None

    def test_injected_corruption_is_caught_and_quarantined(self, tmp_path):
        root = str(tmp_path)
        ckpt.save_checkpoint(os.path.join(root, "optim.1.1"), _tree())
        plan = rel.FaultPlan()
        plan.add("checkpoint.write.arrays", "corrupt", times=1)
        rel.set_plan(plan)
        p = os.path.join(root, "optim.1.2")
        ckpt.save_checkpoint(p, _tree())        # corrupted in flight
        rel.set_plan(None)
        assert not ckpt.verify_checkpoint(p)
        with pytest.raises(ckpt.CheckpointCorruptError):
            ckpt.load_checkpoint(p)
        # latest() must skip + quarantine the torn newest checkpoint and
        # hand recovery the older healthy one, never the garbage
        assert ckpt.latest(root) == "1.1"
        assert not os.path.exists(p)            # moved aside
        assert any(".corrupt-" in n for n in os.listdir(root))
        assert _counter_value(
            "bigdl_reliability_checkpoints_quarantined_total") == 1

    def test_overwrite_replaces_atomically(self, tmp_path):
        p = str(tmp_path / "optim.1.1")
        ckpt.save_checkpoint(p, _tree())
        ckpt.save_checkpoint(p, {"w": np.zeros(2, np.float32)})
        tree, _ = ckpt.load_checkpoint(p, to_jax=False)
        assert tree["w"].shape == (2,)
        assert ckpt.verify_checkpoint(p)

    def test_retention_prunes_old_tags_and_tmp_orphans(self, tmp_path):
        root = str(tmp_path)
        for ne in range(1, 6):
            ckpt.save_checkpoint(os.path.join(root, f"optim.1.{ne}"),
                                 _tree())
            ckpt.save_checkpoint(os.path.join(root, f"model.1.{ne}"),
                                 _tree())
        os.makedirs(os.path.join(root, "optim.1.9.tmp-123-dead"))
        pruned = ckpt.prune_checkpoints(root, keep=2)
        assert pruned == ["1.1", "1.2", "1.3"]
        left = sorted(os.listdir(root))
        assert left == ["model.1.4", "model.1.5", "optim.1.4",
                        "optim.1.5"]

    def test_legacy_manifest_without_checksums_still_loads(self, tmp_path):
        p = str(tmp_path / "legacy")
        ckpt.save_checkpoint(p, _tree())
        with open(os.path.join(p, "manifest.json")) as f:
            manifest = json.load(f)
        del manifest["files"]                   # PR-1 layout
        with open(os.path.join(p, "manifest.json"), "w") as f:
            json.dump(manifest, f)
        tree, _ = ckpt.load_checkpoint(p, to_jax=False)
        assert tree["step"] == 7
        assert ckpt.verify_checkpoint(p)


# ---------------------------------------------------------------------------
# recovery semantics: training
# ---------------------------------------------------------------------------

def _training_setup(tmp_path, epochs=4):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.feature.dataset import LocalDataSet
    from bigdl_tpu.nn.module import set_seed
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import Trigger

    set_seed(0)
    model = (nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU())
             .add(nn.Linear(16, 4)).add(nn.LogSoftMax()))
    rs = np.random.RandomState(0)
    x = rs.randn(64, 8).astype(np.float32)
    t = (rs.randint(0, 4, 64) + 1).astype(np.int32)
    opt = LocalOptimizer(model, LocalDataSet(x, t, shuffle=False),
                         nn.ClassNLLCriterion(), batch_size=16,
                         end_trigger=Trigger.max_epoch(epochs))
    opt.set_checkpoint(str(tmp_path), Trigger.every_epoch())
    return opt, x, t


class TestPreemptionRoundTrip:
    def test_sigterm_checkpoints_then_exits_and_resumes_exactly(
            self, tmp_path):
        import jax
        opt, x, t = _training_setup(tmp_path)
        hits = {"n": 0}
        orig = opt._check_preemption

        def hook(params, states, opt_state, state):
            hits["n"] += 1
            if hits["n"] == 5:
                # what the installed SIGTERM handler does: set the flag
                opt._preempt_requested = True
            return orig(params, states, opt_state, state)

        opt._check_preemption = hook
        with pytest.raises(rel.TrainingPreempted):
            opt.optimize()
        saved_neval = opt.state["neval"]
        assert _counter_value("bigdl_reliability_preemptions_total") == 1
        tag = ckpt.latest(str(tmp_path), paired_prefix="model.")
        assert tag is not None and tag.endswith(str(saved_neval))
        saved_params, _ = ckpt.load_checkpoint(
            str(tmp_path / f"model.{tag}"), to_jax=False)

        # fresh process: auto-resume at the exact saved iteration with
        # bit-identical params
        opt2, _, _ = _training_setup(tmp_path)
        resumed = {}
        orig_once = opt2._optimize_once

        def capture():
            resumed["neval"] = opt2.state["neval"]
            resumed["params"] = [
                np.asarray(p) for p in jax.tree_util.tree_leaves(
                    opt2.model.parameters_dict())]
            return orig_once()

        opt2._optimize_once = capture
        opt2.optimize()
        assert resumed["neval"] == saved_neval
        for a, b in zip(resumed["params"],
                        jax.tree_util.tree_leaves(saved_params["params"])):
            np.testing.assert_array_equal(a, b)   # bit-identical
        assert opt2.state["epoch"] > 4            # and training finished

    def test_signal_handler_installed_and_restored(self, tmp_path):
        import signal as sig
        opt, _, _ = _training_setup(tmp_path, epochs=1)
        seen = {}
        orig_once = opt._optimize_once

        def capture():
            seen["term"] = sig.getsignal(sig.SIGTERM)
            return orig_once()

        opt._optimize_once = capture
        before = sig.getsignal(sig.SIGTERM)
        opt.optimize()
        assert seen["term"] is not before      # installed during the run
        assert sig.getsignal(sig.SIGTERM) is before   # restored after

    def test_mid_iteration_crash_recovers_from_checkpoint(self, tmp_path):
        """Acceptance: injected mid-iteration crash + retry budget →
        training recovers automatically from the newest checkpoint."""
        opt, x, t = _training_setup(tmp_path)
        opt.set_max_retry(2)
        plan = rel.FaultPlan()
        plan.add("optimizer.step", "raise", after=6, times=1)
        rel.set_plan(plan)
        trained = opt.optimize()
        rel.set_plan(None)
        assert plan.fired == [("optimizer.step", "raise")]
        assert opt.state["epoch"] > 4
        assert _counter_value("bigdl_reliability_retries_total",
                              component="optimizer") == 1
        y = np.asarray(trained.evaluate().forward(x[:4]))
        assert y.shape == (4, 4)

    def test_corrupt_newest_checkpoint_quarantined_on_recovery(
            self, tmp_path):
        """Corrupt-checkpoint quarantine: recovery must skip a torn
        newest checkpoint and restore the older valid one."""
        opt, x, t = _training_setup(tmp_path)
        opt.set_max_retry(2)
        plan = rel.FaultPlan()
        # corrupt the arrays of one optimizer checkpoint write, then
        # crash a later step so recovery has to scan the dir
        plan.add("checkpoint.write.arrays", "corrupt", after=2, times=1)
        plan.add("optimizer.step", "raise", after=10, times=1)
        rel.set_plan(plan)
        opt.optimize()
        rel.set_plan(None)
        assert ("optimizer.step", "raise") in plan.fired
        assert ("checkpoint.write.arrays", "corrupt") in plan.fired
        assert opt.state["epoch"] > 4
        names = os.listdir(tmp_path)
        assert any(".corrupt-" in n for n in names)


# ---------------------------------------------------------------------------
# serving backpressure
# ---------------------------------------------------------------------------

def _post(addr, path, obj, headers=None):
    conn = http.client.HTTPConnection(*addr, timeout=30)
    body = json.dumps(obj)
    conn.request("POST", path, body=body,
                 headers={"Content-Type": "application/json",
                          **(headers or {})})
    r = conn.getresponse()
    out = (r.status, dict(r.getheaders()), json.loads(r.read() or b"{}"))
    conn.close()
    return out


def _get(addr, path):
    conn = http.client.HTTPConnection(*addr, timeout=30)
    conn.request("GET", path)
    r = conn.getresponse()
    out = (r.status, json.loads(r.read() or b"{}"))
    conn.close()
    return out


class TestFrontendBackpressure:
    def test_timeout_evicts_pending_entry(self):
        """Satellite regression: a timed-out /predict used to leave its
        event entry behind, so the late result accumulated forever."""
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        fe = ServingFrontend(stream_name="rel_evict",
                             result_timeout=0.05).start()
        try:
            # no serving job attached: every request times out
            status, _, _ = _post(fe.address, "/predict",
                                 {"inputs": {"x": [[1.0]]}})
            assert status == 504
            with fe._lock:
                assert fe._events == {}         # evicted on timeout
                assert fe._results == {}
            # a late result for the dead uri must be dropped, not stored
            fe._out._cache.clear()
        finally:
            fe.stop()

    def test_overload_sheds_503_with_retry_after(self):
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        fe = ServingFrontend(stream_name="rel_shed", result_timeout=0.5,
                             max_pending=0).start()   # everything sheds
        try:
            status, headers, body = _post(fe.address, "/predict",
                                          {"inputs": {"x": [[1.0]]}})
            assert status == 503
            assert headers.get("Retry-After") == "1"
            assert "overloaded" in body["error"]
            assert fe.shed == 1
            assert _counter_value("bigdl_reliability_shed_total",
                                  component="serving_frontend") == 1
        finally:
            fe.stop()

    def test_healthz_and_drain(self):
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        fe = ServingFrontend(stream_name="rel_hz",
                             result_timeout=0.2).start()
        try:
            status, body = _get(fe.address, "/healthz")
            assert status == 200 and body["status"] == "ok"
            assert any(k.startswith("serving_frontend:")
                       for k in body["checks"])
        finally:
            fe.stop()
        # stop() unregisters the instance's health check
        assert not any(k.startswith("serving_frontend:")
                       for k in rel.health_checks())

    def test_draining_frontend_sheds_new_work(self):
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        fe = ServingFrontend(stream_name="rel_drain",
                             result_timeout=0.2).start()
        try:
            fe._draining.set()
            status, headers, body = _post(fe.address, "/predict",
                                          {"inputs": {"x": [[1.0]]}})
            assert status == 503 and "draining" in body["error"]
        finally:
            fe.stop()

    def test_request_deadline_header_caps_wait(self):
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        fe = ServingFrontend(stream_name="rel_dl",
                             result_timeout=30.0).start()
        try:
            t0 = time.perf_counter()
            status, _, _ = _post(fe.address, "/predict",
                                 {"inputs": {"x": [[1.0]]}},
                                 headers={rel.DEADLINE_HEADER: "100"})
            took = time.perf_counter() - t0
            assert status == 504          # deadline, not the 30s timeout
            assert took < 5.0
        finally:
            fe.stop()

    def test_end_to_end_with_injected_backend_faults(self):
        """A full predict round-trip with delay faults armed on the
        queue backend: slower, but every request still completes."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.serving.cluster_serving import ClusterServing
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        from bigdl_tpu.serving.inference_model import InferenceModel

        model = nn.Sequential().add(nn.Linear(4, 2))
        im = InferenceModel().load_bigdl(model=model)
        plan = rel.FaultPlan(seed=11)
        plan.add("serving.backend.*", "delay", delay=0.01, times=4)
        rel.set_plan(plan)
        job = ClusterServing(im, stream_name="rel_e2e",
                             batch_size=4, batch_timeout=0.01).start()
        fe = ServingFrontend(stream_name="rel_e2e",
                             result_timeout=20.0).start()
        try:
            status, _, body = _post(
                fe.address, "/predict",
                {"inputs": {"x": [[1.0, 2.0, 3.0, 4.0]]}})
            assert status == 200
            assert np.asarray(body["result"]).shape == (1, 2)
            assert plan.fired   # faults really fired along the way
        finally:
            rel.set_plan(None)
            fe.stop()
            job.stop()


class TestRedisReconnect:
    def test_reconnect_with_backoff_behind_breaker(self, monkeypatch):
        """Acceptance: redis disconnect recovers automatically. The
        redis client lib is not in the image, so a fake module stands in
        — first N ops raise ConnectionError, then the backend must have
        reconnected and succeeded, counting its retries."""
        state = {"clients": 0, "fail_ops": 2}

        class FakeRedis:
            def __init__(self, host=None, port=None):
                state["clients"] += 1

            def ping(self):
                return True

            def rpush(self, stream, payload):
                if state["fail_ops"] > 0:
                    state["fail_ops"] -= 1
                    raise ConnectionError("connection reset")
                state.setdefault("pushed", []).append(payload)

            def blpop(self, streams, timeout=1):
                pushed = state.get("pushed", [])
                return ("q", pushed.pop(0)) if pushed else None

        fake = types.ModuleType("redis")
        fake.Redis = FakeRedis
        monkeypatch.setitem(sys.modules, "redis", fake)

        from bigdl_tpu.serving.cluster_serving import _RedisBackend
        be = _RedisBackend(
            "localhost", 6379,
            retry=RetryPolicy(max_attempts=5, base_delay=0.001,
                              jitter=0.0))
        be.push("q", b"payload")
        assert state["clients"] >= 3         # initial + 2 reconnects
        assert be.reconnects() == 2
        assert be.pop("q", timeout=0.1) == b"payload"
        assert be._breaker.state == "closed"
        assert _counter_value("bigdl_reliability_retries_total",
                              component="redis_backend") == 2

    def test_breaker_opens_when_queue_stays_down(self, monkeypatch):
        class DeadRedis:
            def __init__(self, host=None, port=None):
                pass

            def ping(self):
                return True

            def rpush(self, *a):
                raise ConnectionError("still down")

        fake = types.ModuleType("redis")
        fake.Redis = DeadRedis
        monkeypatch.setitem(sys.modules, "redis", fake)
        from bigdl_tpu.serving.cluster_serving import _RedisBackend
        be = _RedisBackend(
            "localhost", 6379,
            retry=RetryPolicy(max_attempts=2, base_delay=0.001,
                              jitter=0.0),
            breaker=CircuitBreaker("test_redis", failure_threshold=2,
                                   reset_timeout=60.0))
        for _ in range(2):
            with pytest.raises(ConnectionError):
                be.push("q", b"x")
        # breaker open: callers now fail fast without touching the socket
        with pytest.raises(rel.CircuitOpenError):
            be.push("q", b"x")


class TestLLMWorkerBackpressure:
    class _StubServer:
        """submit/queue surface of LLMServer without a model."""

        def __init__(self):
            self._queue = __import__("queue").Queue()
            self._thread = threading.Thread(target=lambda: time.sleep(30),
                                            daemon=True)
            self._thread.start()
            self._draining = threading.Event()
            self.steps = 0
            self.eos_token_id = None
            self.overloaded = False

        def submit(self, ids, max_new_tokens=32):
            if self.overloaded:
                raise rel.OverloadError("request queue full (2 waiting)")
            from bigdl_tpu.llm.serving import Request
            req = Request(np.asarray(ids, np.int32), max_new_tokens)
            req.tokens = [1, 2, 3]
            req.done.set()
            return req

    def test_queue_full_sheds_503_with_retry_after(self):
        from bigdl_tpu.llm.worker import LLMWorker
        srv = self._StubServer()
        worker = LLMWorker(srv).start()
        try:
            status, _, body = _post(worker.address, "/worker_generate",
                                    {"prompt_ids": [1, 2]})
            assert status == 200 and body["output_ids"] == [1, 2, 3]
            srv.overloaded = True
            status, headers, body = _post(worker.address,
                                          "/worker_generate",
                                          {"prompt_ids": [1, 2]})
            assert status == 503
            assert headers.get("Retry-After") == "1"
            assert "queue full" in body["error"]
        finally:
            worker.stop()

    def test_healthz_reports_engine_liveness(self):
        from bigdl_tpu.llm.worker import LLMWorker
        srv = self._StubServer()
        worker = LLMWorker(srv).start()
        try:
            status, body = _get(worker.address, "/healthz")
            assert status == 200
            assert body["engine_alive"] is True
            srv._draining.set()
            status, body = _get(worker.address, "/healthz")
            assert status == 503 and body["status"] == "draining"
        finally:
            worker.stop()

    def test_prefill_failure_releases_budget_and_fails_request(self):
        """Review regression: a raising prefill must restore the page
        budget (the resilient engine loop would otherwise shrink the
        admission pool forever) and unblock the client with the error
        instead of letting it hang to timeout."""
        from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
        from bigdl_tpu.llm.serving import LLMServer
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(vocab=64),
                                             seed=0, max_cache_len=64)
        srv = LLMServer(model, max_batch=1, max_seq_len=32)
        before_budget = srv._budget_avail
        before_pages = len(srv._free)

        def boom(i, req):
            raise RuntimeError("prefill exploded")

        srv._prefill_paged = boom
        req = srv.submit([1, 2, 3], max_new_tokens=2)
        with pytest.raises(RuntimeError, match="prefill exploded"):
            srv._admit()           # engine loop not started: call direct
        assert srv._budget_avail == before_budget
        assert len(srv._free) == before_pages
        assert srv._slots[0] is None
        with pytest.raises(RuntimeError, match="prefill exploded"):
            req.get(timeout=0.1)   # failed fast, not hung

    def test_llm_server_bounded_queue_and_drain(self):
        """Real LLMServer admission: with max_queue=1 and the engine
        loop not started, the second waiting submit is shed; draining
        rejects all new work."""
        from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(vocab=64),
                                             seed=0, max_cache_len=64)
        from bigdl_tpu.llm.serving import LLMServer
        srv = LLMServer(model, max_batch=1, max_seq_len=32, max_queue=1)
        srv.submit([1, 2, 3], max_new_tokens=2)     # fills the queue
        with pytest.raises(rel.OverloadError, match="queue full"):
            srv.submit([1, 2, 3], max_new_tokens=2)
        assert _counter_value("bigdl_reliability_shed_total",
                              component="llm_server") == 1
        srv._draining.set()
        srv._queue.get_nowait()
        with pytest.raises(rel.OverloadError, match="draining"):
            srv.submit([1, 2, 3], max_new_tokens=2)


# ---------------------------------------------------------------------------
# disabled mode: structurally absent, zero overhead
# ---------------------------------------------------------------------------

class TestDisabledMode:
    def test_disabled_is_structurally_absent(self, tmp_path):
        conf.set("bigdl.reliability.enabled", "false")
        try:
            assert not rel.enabled()
            # no plan can arm
            with pytest.raises(RuntimeError):
                rel.set_plan(rel.FaultPlan())
            assert rel.armed_sites() == []
            # inject is a pure no-op
            assert rel.inject("checkpoint.write") is None
            # health registrations are ignored
            rel.register_health("x", lambda: True)
            assert rel.health_checks() == {}
            # no signal handlers installed during training
            import signal as sig
            before = sig.getsignal(sig.SIGTERM)
            opt, x, t = _training_setup(tmp_path, epochs=1)
            seen = {}
            orig_once = opt._optimize_once

            def capture():
                seen["term"] = sig.getsignal(sig.SIGTERM)
                return orig_once()

            opt._optimize_once = capture
            opt.optimize()
            assert seen["term"] is before
            # checkpoint layout unchanged and loadable by the PR-1
            # reader (same two files + sidecar; extra manifest keys only)
            tag = ckpt.latest(str(tmp_path), paired_prefix="model.")
            assert tag is not None
            model_dir = str(tmp_path / f"model.{tag}")
            assert sorted(os.listdir(model_dir)) == [
                "arrays.safetensors", "manifest.json", "structure.pkl"]
            tree, _ = ckpt.load_checkpoint(model_dir, to_jax=False,
                                           verify=False)   # PR-1 path
            assert "params" in tree
            # zero reliability counters were minted along the way
            rendered = obs.render()
            assert "bigdl_reliability_" not in rendered
        finally:
            conf.unset("bigdl.reliability.enabled")
            assert rel.enabled()    # unset() restores the default=true

    def test_disabled_policies_work_but_mint_no_counters(self):
        """Review regression: policy objects keep functioning when the
        layer is disabled, but must mint ZERO bigdl_reliability_* series
        (the retry paths in the optimizer/serving loops run regardless)."""
        conf.set("bigdl.reliability.enabled", "false")
        try:
            p = RetryPolicy(max_attempts=3, base_delay=0.001, jitter=0.0,
                            sleep=lambda s: None)
            calls = {"n": 0}

            def flaky():
                calls["n"] += 1
                if calls["n"] < 2:
                    raise IOError("transient")
                return "ok"

            assert p.call(flaky, component="gated") == "ok"
            br = CircuitBreaker("gated", failure_threshold=1)
            br.record_failure()
            assert br.state == "open"      # machine still works
            assert "bigdl_reliability_" not in obs.render()
        finally:
            conf.unset("bigdl.reliability.enabled")

    def test_conf_toggle_roundtrip(self):
        conf.set("bigdl.reliability.enabled", "false")
        assert not rel.enabled()
        conf.set("bigdl.reliability.enabled", "true")
        assert rel.enabled()
        conf.unset("bigdl.reliability.enabled")
        assert rel.enabled()

    def test_retry_knobs_come_from_conf(self):
        conf.set("bigdl.reliability.retry.max.attempts", "7")
        conf.set("bigdl.reliability.retry.base.delay", "0.5")
        try:
            p = RetryPolicy(jitter=0.0)
            assert p.max_attempts == 7
            assert list(p.delays())[0] == 0.5
        finally:
            conf.unset("bigdl.reliability.retry.max.attempts")
            conf.unset("bigdl.reliability.retry.base.delay")


# ---------------------------------------------------------------------------
# chaos (seeded randomized injection; slow => outside the tier-1 gate)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
@pytest.mark.slow
@pytest.mark.parametrize("seed", [1, 7, 42])
def test_chaos_lenet_converges_like_clean_run(seed):
    """N seeded kill/corrupt/delay events over training + checkpointing:
    the run must recover automatically and land on the SAME final loss
    as an uninjected run (tools/chaos_check.py)."""
    from tools.chaos_check import run_chaos
    out = run_chaos(seed=seed, events=4, smoke=True)
    assert out["match"]
    assert out["events_fired"]        # the plan really fired something


class TestCheckpointKeepConf:
    def test_training_prunes_to_keep(self, tmp_path):
        conf.set("bigdl.checkpoint.keep", "2")
        try:
            opt, _, _ = _training_setup(tmp_path, epochs=4)
            opt.optimize()
            tags = ckpt.list_checkpoint_tags(str(tmp_path))
            assert len(tags) == 2          # retention enforced
            # and the survivors are the newest pair
            assert ckpt.latest(str(tmp_path),
                               paired_prefix="model.") == tags[-1]
        finally:
            conf.unset("bigdl.checkpoint.keep")

"""Round-4 layer-zoo tail + criterion tail (ref: S:dllib/nn one-file
rows; VERDICT r3 missing #2). Golden values are independent numpy."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import bigdl_tpu.nn as nn


def _run(layer, x, training=False):
    y, _ = layer.apply(layer.parameters_dict(), layer.states_dict(),
                       jnp.asarray(x), training=training,
                       rng=jax.random.PRNGKey(0))
    return np.asarray(y)


class TestActivationTail:
    def test_hard_soft_tanh_shrink_logsigmoid(self):
        x = np.array([[-2.0, -0.3, 0.0, 0.4, 1.5]], np.float32)
        np.testing.assert_allclose(
            _run(nn.HardShrink(0.5), x), np.where(np.abs(x) > 0.5, x, 0))
        np.testing.assert_allclose(
            _run(nn.SoftShrink(0.5), x),
            np.sign(x) * np.maximum(np.abs(x) - 0.5, 0))
        np.testing.assert_allclose(_run(nn.TanhShrink(), x),
                                   x - np.tanh(x), rtol=1e-5, atol=1e-7)
        np.testing.assert_allclose(
            _run(nn.LogSigmoid(), x), np.log(1 / (1 + np.exp(-x))),
            rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(
            _run(nn.BinaryThreshold(0.1), x), (x > 0.1).astype(np.float32))

    def test_spatial_dropout_1d_3d(self):
        rs = np.random.RandomState(0)
        x = rs.rand(4, 10, 8).astype(np.float32) + 1.0
        y = _run(nn.SpatialDropout1D(0.5), x, training=True)
        # whole channels dropped: each (b, :, c) column all-zero or scaled
        col_zero = (y == 0).all(axis=1)
        col_live = (y != 0).all(axis=1)
        assert ((col_zero | col_live)).all()
        assert col_zero.any() and col_live.any()
        x3 = rs.rand(2, 6, 3, 4, 5).astype(np.float32) + 1.0
        y3 = _run(nn.SpatialDropout3D(0.5), x3, training=True)
        vol = y3.reshape(2, 6, -1)
        assert (((vol == 0).all(axis=2)) | ((vol != 0).all(axis=2))).all()
        # inference = identity
        np.testing.assert_array_equal(_run(nn.SpatialDropout1D(0.5), x), x)

    def test_penalty_identities(self):
        x = np.array([[0.5, -1.0, 2.0]], np.float32)
        ar = nn.ActivityRegularization(l1=0.1, l2=0.01)
        np.testing.assert_array_equal(_run(ar, x, training=True), x)
        pen = float(ar.penalty_of(jnp.asarray(x)))
        assert abs(pen - (0.1 * 3.5 + 0.01 * 5.25)) < 1e-5
        ne = nn.NegativeEntropyPenalty(beta=1.0)
        p = np.array([[0.5, 0.5]], np.float32)
        np.testing.assert_array_equal(_run(ne, p, training=True), p)
        assert abs(float(ne.penalty_of(jnp.asarray(p)))
                   - (2 * 0.5 * np.log(0.5))) < 1e-5


class TestShapeTableTail:
    def test_cropping1d(self):
        x = np.arange(2 * 6 * 3, dtype=np.float32).reshape(2, 6, 3)
        np.testing.assert_array_equal(_run(nn.Cropping1D(1, 2), x),
                                      x[:, 1:4])

    def test_bifurcate_split(self):
        x = np.arange(12, dtype=np.float32).reshape(2, 6)
        m = nn.BifurcateSplitTable(dimension=2)
        lo, hi = m.apply(m.parameters_dict(), m.states_dict(),
                         jnp.asarray(x), training=False, rng=None)[0]
        np.testing.assert_array_equal(np.asarray(lo), x[:, :3])
        np.testing.assert_array_equal(np.asarray(hi), x[:, 3:])

    def test_masked_select_eager_and_jit_error(self):
        m = nn.MaskedSelect()
        x = np.array([[1.0, 2.0], [3.0, 4.0]], np.float32)
        mask = np.array([[1, 0], [0, 1]], np.float32)
        out = m.apply(m.parameters_dict(), m.states_dict(),
                      [jnp.asarray(x), jnp.asarray(mask)],
                      training=False, rng=None)[0]
        np.testing.assert_array_equal(np.asarray(out), [1.0, 4.0])
        with pytest.raises(Exception):
            jax.jit(lambda a, b: m.apply(
                {}, {}, [a, b], training=False, rng=None)[0])(
                    jnp.asarray(x), jnp.asarray(mask))

    def test_dense_to_sparse(self):
        m = nn.DenseToSparse()
        x = np.array([[0.0, 2.0], [3.0, 0.0]], np.float32)
        st = m.apply({}, {}, jnp.asarray(x), training=False, rng=None)[0]
        np.testing.assert_array_equal(np.asarray(st.to_dense()), x)

    def test_gaussian_sampler_stats(self):
        m = nn.GaussianSampler()
        mean = np.full((4096, 2), 3.0, np.float32)
        logv = np.full((4096, 2), np.log(0.25), np.float32)
        out = m.apply({}, {}, [jnp.asarray(mean), jnp.asarray(logv)],
                      training=True, rng=jax.random.PRNGKey(1))[0]
        out = np.asarray(out)
        assert abs(out.mean() - 3.0) < 0.05
        assert abs(out.std() - 0.5) < 0.05

    def test_input_identity(self):
        x = np.ones((2, 3), np.float32)
        np.testing.assert_array_equal(_run(nn.Input(), x), x)


class TestVisionTail:
    def test_resize_bilinear(self):
        x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
        y = _run(nn.ResizeBilinear(2, 2), x)
        assert y.shape == (1, 1, 2, 2)
        # downscale preserves mean approximately
        assert abs(y.mean() - x.mean()) < 1.0

    def test_roi_pooling_max_semantics(self):
        feats = np.zeros((1, 8, 8, 1), np.float32)
        feats[0, 2, 3, 0] = 5.0
        feats[0, 6, 6, 0] = 9.0
        rois = np.array([[0, 0, 0, 7, 7]], np.float32)
        m = nn.RoiPooling(pooled_h=2, pooled_w=2, spatial_scale=1.0)
        out = m.apply({}, {}, [jnp.asarray(feats), jnp.asarray(rois)],
                      training=False, rng=None)[0]
        out = np.asarray(out)    # (1, 2, 2, 1)
        assert out.max() == 9.0
        assert out[0, 0, 0, 0] == 5.0     # top-left quadrant max
        assert out[0, 1, 1, 0] == 9.0     # bottom-right quadrant max

    def test_spatial_convolution_map_masks_connections(self):
        table = [[1, 1], [2, 2]]     # plane i -> plane i only
        m = nn.SpatialConvolutionMap(table, 3, 3, pad_w=1, pad_h=1)
        x = np.zeros((1, 2, 5, 5), np.float32)
        x[0, 0] = 1.0                # only input plane 1 carries signal
        y = _run(m, x)
        p = m.parameters_dict()
        # weight mask: cross connections are zeroed in the effective kernel
        w = np.asarray(p["weight"]) * np.asarray(m._mask)
        assert (w[0, 1] == 0).all() and (w[1, 0] == 0).all()
        # output plane 2 sees no signal from input plane 1 beyond bias
        assert np.allclose(y[0, 1], y[0, 1].flat[0])

    def test_share_convolution_is_convolution(self):
        m = nn.SpatialShareConvolution(2, 3, 3, 3)
        x = np.random.RandomState(0).rand(1, 2, 6, 6).astype(np.float32)
        ref = nn.SpatialConvolution(2, 3, 3, 3)
        ref.load_parameters_dict(m.parameters_dict())
        np.testing.assert_allclose(_run(m, x), _run(ref, x), rtol=1e-5)

    def test_priorbox_and_anchor(self):
        x = np.zeros((1, 4, 2, 2), np.float32)
        pb = nn.PriorBox(min_sizes=[30.0], aspect_ratios=(2.0,),
                         img_h=300, img_w=300)
        out = np.asarray(_run(pb, x))
        # 2x2 cells x 3 anchors (min, ar2, ar1/2) x 4 coords
        assert out.shape == (1, 2, 2 * 2 * 3 * 4)
        anc = nn.Anchor(stride=16, sizes=(32.0,), ratios=(1.0,))
        a = np.asarray(_run(anc, x))
        assert a.shape == (2 * 2 * 1, 4)


class TestMultiRNNCell:
    def test_stacked_cells_in_recurrent(self):
        rs = np.random.RandomState(0)
        cell = nn.MultiRNNCell([nn.RnnCell(4, 8), nn.RnnCell(8, 6)])
        rec = nn.Recurrent(cell)
        x = rs.rand(3, 5, 4).astype(np.float32)
        y = _run(rec, x)
        assert y.shape == (3, 5, 6)   # return_sequences default
        assert np.isfinite(y).all()


class TestCriterionTail:
    def test_cosine_distance(self):
        x = np.array([[1.0, 0.0]], np.float32)
        t = np.array([[0.0, 1.0]], np.float32)
        c = nn.CosineDistanceCriterion()
        assert abs(c.forward(x, t) - 1.0) < 1e-6
        assert abs(c.forward(x, x) - 0.0) < 1e-6

    def test_dice(self):
        c = nn.DiceCoefficientCriterion(epsilon=0.0)
        x = np.array([[1.0, 1.0, 0.0, 0.0]], np.float32)
        assert abs(c.forward(x, x)) < 1e-6        # perfect overlap
        t = np.array([[0.0, 0.0, 1.0, 1.0]], np.float32)
        assert abs(c.forward(x, t) - 1.0) < 1e-6  # disjoint

    def test_kld_and_gaussian(self):
        mean = np.zeros((2, 3), np.float32)
        logv = np.zeros((2, 3), np.float32)
        kld = nn.KLDCriterion()
        assert abs(kld.forward([mean, logv], None)) < 1e-6  # N(0,1)||N(0,1)
        g = nn.GaussianCriterion()
        t = np.zeros((2, 3), np.float32)
        want = 3 * 0.5 * np.log(2 * np.pi)
        assert abs(g.forward([mean, logv], t) - want) < 1e-4

    def test_l1_hinge_embedding(self):
        c = nn.L1HingeEmbeddingCriterion(margin=2.0)
        x1 = np.array([[1.0, 2.0]], np.float32)
        x2 = np.array([[0.0, 0.5]], np.float32)   # L1 distance 2.5
        assert abs(c.forward([x1, x2], np.array([1.0])) - 2.5) < 1e-6
        assert abs(c.forward([x1, x2], np.array([-1.0])) - 0.0) < 1e-6

    def test_multilabel_margin(self):
        c = nn.MultiLabelMarginCriterion()
        x = np.array([[0.1, 0.2, 0.4, 0.8]], np.float32)
        t = np.array([[3, 0, 0, 0]], np.float32)  # class 3 (1-based)
        # torch golden: sum over non-target i of max(0,1-(x[2]-x[i]))/4
        want = (max(0, 1 - (0.4 - 0.1)) + max(0, 1 - (0.4 - 0.2))
                + max(0, 1 - (0.4 - 0.8))) / 4
        assert abs(c.forward(x, t) - want) < 1e-5
        # class 1 as a target must not be clobbered by the zero padding
        # that scatters to the same index (review r4 finding)
        t1 = np.array([[1, 0, 0, 0]], np.float32)
        want1 = (max(0, 1 - (0.1 - 0.2)) + max(0, 1 - (0.1 - 0.4))
                 + max(0, 1 - (0.1 - 0.8))) / 4
        assert abs(c.forward(x, t1) - want1) < 1e-5

    def test_multilabel_margin_stops_at_first_zero(self):
        """torch semantics: [3, 0, 2, 0] names ONLY class 3 — the list
        terminates at the first zero (ADVICE r4); golden vs torch."""
        import torch
        c = nn.MultiLabelMarginCriterion()
        x = np.array([[0.1, 0.2, 0.4, 0.8]], np.float32)
        t = np.array([[3, 0, 2, 0]], np.float32)
        want = float(torch.nn.MultiLabelMarginLoss()(
            torch.tensor(x), torch.tensor([[2, -1, 1, -1]])))
        assert abs(c.forward(x, t) - want) < 1e-5

    def test_resize_bilinear_align_corners_matches_torch(self):
        """align_corners=True is exact inclusive-grid lerp (ADVICE r4:
        previously silently fell back to half-pixel). False stays on
        jax.image.resize, whose antialiased downscale intentionally
        differs from torch — only the True path is a torch golden."""
        import torch
        rs = np.random.RandomState(0)
        x = rs.rand(2, 3, 5, 7).astype(np.float32)
        got = _run(nn.ResizeBilinear(9, 4, align_corners=True), x)
        want = torch.nn.functional.interpolate(
            torch.tensor(x), size=(9, 4), mode="bilinear",
            align_corners=True).numpy()
        np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
        # corner pixels map exactly to corner pixels
        up = _run(nn.ResizeBilinear(7, 9, align_corners=True), x)
        np.testing.assert_allclose(up[..., 0, 0], x[..., 0, 0], rtol=1e-6)
        np.testing.assert_allclose(up[..., -1, -1], x[..., -1, -1],
                                   rtol=1e-6)

    def test_class_simplex(self):
        c = nn.ClassSimplexCriterion(n_classes=3)
        goal = np.asarray(c._targets)
        # vertices are unit-norm, pairwise-equidistant
        np.testing.assert_allclose(np.linalg.norm(goal, axis=1), 1.0,
                                   rtol=1e-5)
        x = goal[0][None]
        assert abs(c.forward(x, np.array([1.0]))) < 1e-10

    def test_time_distributed_mask(self):
        base = nn.MSECriterion()
        c = nn.TimeDistributedMaskCriterion(base)
        x = np.ones((2, 3, 4), np.float32)
        labels = np.zeros((2, 3, 4), np.float32)
        mask = np.ones((2, 3), np.float32)
        # all steps live: equals plain per-step MSE = 1.0
        assert abs(c.forward(x, [labels, mask]) - 1.0) < 1e-6
        # masked SAMPLES contribute exactly zero (review r4 finding):
        # row 1's labels are garbage but row 1 is fully masked out
        labels2 = labels.copy()
        labels2[1] = 100.0
        mask2 = np.stack([np.ones(3), np.zeros(3)]).astype(np.float32)
        assert abs(c.forward(x, [labels2, mask2]) - 1.0) < 1e-6


class TestKerasTail:
    def test_new_keras_layers_shape_inference(self):
        from bigdl_tpu.keras.layers import (
            ActivityRegularization, Cropping3D, GlobalAveragePooling3D,
            GlobalMaxPooling3D, LocallyConnected2D, SReLU,
            SpatialDropout1D, SpatialDropout3D, ZeroPadding3D)
        from bigdl_tpu.keras.topology import Sequential

        rs = np.random.RandomState(0)
        m = Sequential()
        m.add(ZeroPadding3D((1, 1, 1), input_shape=(2, 3, 4, 5)))
        m.add(Cropping3D(((1, 1), (1, 1), (1, 1))))
        m.add(SpatialDropout3D(0.3))
        m.add(GlobalAveragePooling3D())
        out = m.predict(rs.rand(2, 2, 3, 4, 5).astype(np.float32))
        assert out.shape == (2, 2)

        m2 = Sequential()
        m2.add(SpatialDropout1D(0.3, input_shape=(6, 4)))
        m2.add(SReLU())
        m2.add(ActivityRegularization(l1=0.01))
        out2 = m2.predict(rs.rand(3, 6, 4).astype(np.float32))
        assert out2.shape == (3, 6, 4)

        m3 = Sequential()
        m3.add(LocallyConnected2D(6, 3, 3, input_shape=(2, 8, 8)))
        out3 = m3.predict(rs.rand(2, 2, 8, 8).astype(np.float32))
        assert out3.shape == (2, 6, 6, 6)

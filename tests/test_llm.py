"""bigdl-llm slice tests: quantization formats, INT4/INT8 kernels (golden
parity vs independent numpy impl, SURVEY.md §4), LowBitLinear surgery, and
Llama prefill/decode."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.llm.ggml.quantize import QK, dequantize, quantize
from bigdl_tpu.llm.kernels import (
    asym_int4_matmul, int4_matmul, int4_matmul_reference, int8_matmul,
    to_tpu_layout)
from bigdl_tpu.llm.models.llama import (
    LlamaConfig, LlamaForCausalLM, forward, init_cache, init_params,
    param_pspecs, quantize_params)
from bigdl_tpu.llm.transformers import (
    AutoModelForCausalLM, LowBitLinear, ggml_convert_low_bit)


class TestQuantize:
    @pytest.mark.parametrize("qtype,tol", [
        ("sym_int4", 0.10), ("asym_int4", 0.08), ("sym_int5", 0.05),
        ("sym_int8", 0.01), ("nf4", 0.13), ("fp4", 0.16),
    ])
    def test_roundtrip_error(self, qtype, tol):
        rs = np.random.RandomState(0)
        w = rs.randn(8, 128).astype(np.float32)
        deq = dequantize(quantize(w, qtype))
        assert deq.shape == w.shape
        rel = np.abs(deq - w).max() / np.abs(w).max()
        assert rel < tol, f"{qtype}: rel err {rel}"

    def test_q4_packing_layout(self):
        w = np.arange(-16, 16, dtype=np.float32).reshape(1, 32)
        qd = quantize(w, "sym_int4")
        assert qd["q"].shape == (1, 16) and qd["q"].dtype == np.uint8
        assert qd["scale"].shape == (1, 1)
        deq = dequantize(qd)
        # monotone ramp must stay monotone after q4 round-trip
        assert (np.diff(deq[0]) >= -1e-6).all()

    def test_zero_block_safe(self):
        w = np.zeros((4, 64), np.float32)
        for qt in ("sym_int4", "asym_int4", "sym_int8", "nf4"):
            deq = dequantize(quantize(w, qt))
            np.testing.assert_allclose(deq, 0.0)


class TestKernels:
    @pytest.mark.parametrize("m,k,n", [(1, 64, 48), (5, 96, 40),
                                       (17, 256, 130)])
    def test_int4_parity(self, m, k, n):
        rs = np.random.RandomState(1)
        x = rs.randn(m, k).astype(np.float32)
        w = rs.randn(n, k).astype(np.float32) * 0.1
        qd = quantize(w, "sym_int4")
        ref = int4_matmul_reference(x, qd["q"], qd["scale"])
        td = to_tpu_layout(qd)
        out = np.asarray(int4_matmul(
            jnp.asarray(x), jnp.asarray(td["q"]), jnp.asarray(td["scale"]),
            interpret=True, out_dtype=jnp.float32), np.float32)
        scale = max(np.abs(ref).max(), 1e-6)
        assert np.abs(out - ref).max() / scale < 0.02

    @pytest.mark.parametrize("mode", ["corr", "sub8"])
    def test_int4_modes_agree(self, mode):
        """Both zero-point strategies must produce the same numbers."""
        rs = np.random.RandomState(5)
        x = rs.randn(3, 128).astype(np.float32)
        w = rs.randn(32, 128).astype(np.float32) * 0.1
        td = to_tpu_layout(quantize(w, "sym_int4"))
        ref = int4_matmul_reference(x, quantize(w, "sym_int4")["q"],
                                    quantize(w, "sym_int4")["scale"])
        out = np.asarray(int4_matmul(
            jnp.asarray(x), jnp.asarray(td["q"]), jnp.asarray(td["scale"]),
            interpret=True, out_dtype=jnp.float32, mode=mode), np.float32)
        scale = max(np.abs(ref).max(), 1e-6)
        assert np.abs(out - ref).max() / scale < 0.02

    def test_asym_int4_parity(self):
        rs = np.random.RandomState(3)
        x = rs.randn(4, 96).astype(np.float32)
        w = rs.randn(24, 96).astype(np.float32) * 0.1 + 0.05
        qd = quantize(w, "asym_int4")
        ref = x @ dequantize(qd).T
        td = to_tpu_layout(qd)
        out = np.asarray(asym_int4_matmul(
            jnp.asarray(x), jnp.asarray(td["q"]), jnp.asarray(td["scale"]),
            jnp.asarray(td["zero"]), interpret=True,
            out_dtype=jnp.float32), np.float32)
        scale = max(np.abs(ref).max(), 1e-6)
        assert np.abs(out - ref).max() / scale < 0.02

    def test_int8_parity(self):
        rs = np.random.RandomState(2)
        x = rs.randn(5, 96).astype(np.float32)
        w = rs.randn(40, 96).astype(np.float32) * 0.1
        qd = quantize(w, "sym_int8")
        ref = x @ dequantize(qd).T
        td = to_tpu_layout(qd)
        out = np.asarray(int8_matmul(
            jnp.asarray(x), jnp.asarray(td["q"]), jnp.asarray(td["scale"]),
            interpret=True, out_dtype=jnp.float32), np.float32)
        scale = max(np.abs(ref).max(), 1e-6)
        assert np.abs(out - ref).max() / scale < 0.02


class TestLowBitLinear:
    def test_matches_dense(self):
        from bigdl_tpu.nn.module import set_seed
        set_seed(0)
        lin = nn.Linear(64, 32)
        low = LowBitLinear.from_linear(lin, "sym_int4")
        x = np.random.RandomState(3).randn(4, 64).astype(np.float32)
        y_dense = np.asarray(lin.forward(x))
        y_low = np.asarray(low.forward(x))
        rel = np.abs(y_low - y_dense).max() / (np.abs(y_dense).max() + 1e-6)
        assert rel < 0.15, rel

    def test_convert_model_surgery(self):
        from bigdl_tpu.nn.module import set_seed
        set_seed(0)
        model = (nn.Sequential()
                 .add(nn.Linear(32, 64).set_name("fc1"))
                 .add(nn.ReLU())
                 .add(nn.Linear(64, 8).set_name("lm_head")))
        ggml_convert_low_bit(model, "sym_int4",
                             modules_to_not_convert=["lm_head"])
        kinds = [type(m).__name__ for m in model.modules()]
        assert kinds.count("LowBitLinear") == 1
        assert kinds.count("Linear") == 1  # lm_head kept dense
        y = model.forward(np.random.rand(2, 32).astype(np.float32))
        assert y.shape == (2, 8)


class TestLlama:
    def test_prefill_decode_consistency(self):
        """Decoding token-by-token must agree with a single prefill."""
        cfg = LlamaConfig.tiny()
        params = init_params(cfg, seed=0)
        toks = np.array([[5, 9, 3, 7, 2]], np.int32)

        cache = init_cache(cfg, 1, 16)
        pos = jnp.arange(5)[None, :]
        logits_full, _ = forward(params, cfg, jnp.asarray(toks), cache, pos)

        cache = init_cache(cfg, 1, 16)
        outs = []
        for t in range(5):
            pos_t = jnp.asarray([[t]])
            lg, cache = forward(params, cfg, jnp.asarray(toks[:, t:t + 1]),
                                cache, pos_t)
            outs.append(np.asarray(lg[:, 0]))
        step_logits = np.stack(outs, axis=1)
        np.testing.assert_allclose(np.asarray(logits_full), step_logits,
                                   rtol=2e-2, atol=2e-2)

    def test_generate_greedy_deterministic(self):
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM.from_config(cfg, seed=0, max_cache_len=64)
        ids = np.array([[1, 2, 3]], np.int32)
        out1 = model.generate(ids, max_new_tokens=8)
        out2 = model.generate(ids, max_new_tokens=8)
        assert out1.shape == (1, 11)
        np.testing.assert_array_equal(out1, out2)
        np.testing.assert_array_equal(out1[:, :3], ids)

    def test_paged_decode_matches_dense_decode(self):
        """The round-5 default token loop (decode_scan_paged: dense
        prefill pageified into a pool, attention over live pages only)
        must be BIT-IDENTICAL to the dense ring-cache loop on greedy,
        sampled (shared rng stream) and EOS-chunked paths — for plain,
        GLM-rotary, sliding-window and MoE configs."""
        import dataclasses
        from bigdl_tpu.llm.models.llama import init_params
        for cfg in (LlamaConfig.tiny(), LlamaConfig.tiny_glm(),
                    dataclasses.replace(LlamaConfig.tiny(),
                                        sliding_window=24),
                    LlamaConfig.tiny_moe()):
            params = init_params(cfg, seed=0)
            dense = LlamaForCausalLM(cfg, params, max_cache_len=64,
                                     paged_decode=False)
            paged = LlamaForCausalLM(cfg, params, max_cache_len=64,
                                     paged_decode=True)
            ids = np.array([[3, 1, 4, 1, 5], [9, 2, 6, 5, 3]], np.int32)
            np.testing.assert_array_equal(
                dense.generate(ids, max_new_tokens=10),
                paged.generate(ids, max_new_tokens=10))
            np.testing.assert_array_equal(
                dense.generate(ids, max_new_tokens=6, do_sample=True,
                               top_k=5, seed=3),
                paged.generate(ids, max_new_tokens=6, do_sample=True,
                               top_k=5, seed=3))
            eos = int(dense.generate(ids, max_new_tokens=4)[0, -1])
            np.testing.assert_array_equal(
                dense.generate(ids, max_new_tokens=12, eos_token_id=eos,
                               decode_chunk=4),
                paged.generate(ids, max_new_tokens=12, eos_token_id=eos,
                               decode_chunk=4))

    def test_quantized_generate_close_to_dense(self):
        cfg = LlamaConfig.tiny()
        dense = LlamaForCausalLM.from_config(cfg, seed=0, max_cache_len=32)
        quant = LlamaForCausalLM(cfg, quantize_params(dense.params),
                                 max_cache_len=32)
        ids = np.array([[4, 8, 15]], np.int32)
        ld, _ = dense(jnp.asarray(ids))
        lq, _ = quant(jnp.asarray(ids))
        # logits correlate strongly even at 4 bits
        a = np.asarray(ld).ravel()
        b = np.asarray(lq).ravel()
        corr = np.corrcoef(a, b)[0, 1]
        assert corr > 0.95, corr

    def test_fused_projections_match_unfused(self):
        """qkv_proj / gate_up_proj fusion (4 weight streams per layer
        instead of 7) is a pure layout change: logits and greedy tokens
        must match the unfused path. tiny() is GQA (4 q heads, 2 kv), so
        the fused split boundaries are exercised."""
        from bigdl_tpu.llm.models.llama import fuse_decoder_params

        cfg = LlamaConfig.tiny()
        ids = np.array([[4, 8, 15, 16]], np.int32)

        # dense: fuse_decoder_params on bf16 stacked weights
        dense = LlamaForCausalLM.from_config(cfg, seed=0, max_cache_len=32)
        fused = LlamaForCausalLM(cfg, fuse_decoder_params(dense.params),
                                 max_cache_len=32)
        assert "qkv_proj" in fused.params["layers"]
        assert "q_proj" not in fused.params["layers"]
        ld, _ = dense(jnp.asarray(ids))
        lf, _ = fused(jnp.asarray(ids))
        np.testing.assert_allclose(np.asarray(ld), np.asarray(lf),
                                   rtol=1e-5, atol=1e-5)

        # quantized: quantize_params(fuse=True) vs fuse=False
        qu = LlamaForCausalLM(cfg, quantize_params(dense.params,
                                                   fuse=False),
                              max_cache_len=32)
        qf = LlamaForCausalLM(cfg, quantize_params(dense.params),
                              max_cache_len=32)
        assert "gate_up_proj" in qf.params["layers"]
        tu = qu.generate(ids, max_new_tokens=8)
        tf = qf.generate(ids, max_new_tokens=8)
        np.testing.assert_array_equal(tu, tf)

    def test_batched_generation_with_sampling(self):
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM.from_config(cfg, seed=1, max_cache_len=32)
        ids = np.array([[1, 2], [3, 4]], np.int32)
        out = model.generate(ids, max_new_tokens=4, do_sample=True,
                             temperature=0.8, top_k=10, seed=7)
        assert out.shape == (2, 6)
        assert (out < cfg.vocab_size).all()

    def test_auto_model_facade(self):
        model = AutoModelForCausalLM.from_pretrained(
            LlamaConfig.tiny(), load_in_4bit=True, max_cache_len=32)
        out = model.generate(np.array([[1, 2, 3]]), max_new_tokens=4)
        assert out.shape == (1, 7)

    def test_fused_projections_under_tensor_parallelism(self):
        """Fused qkv/gate_up shard over the model axis: the shard
        boundaries cross the fused segments (at and inside q/k/v), so
        greedy tokens must still match the unsharded model exactly —
        XLA reshards the post-matmul split if a boundary misaligns."""
        import dataclasses

        from jax.sharding import Mesh

        cfg = dataclasses.replace(LlamaConfig.tiny(), hidden_size=128,
                                  intermediate_size=256)
        dense = LlamaForCausalLM.from_config(cfg, seed=0,
                                             max_cache_len=32)
        q = quantize_params(dense.params)
        ids = np.array([[4, 8, 15, 16]], np.int32)
        want = LlamaForCausalLM(cfg, q, max_cache_len=32).generate(
            ids, max_new_tokens=6)
        for tp in (2, 4):    # boundary exactly at q|k vs inside q
            mesh = Mesh(np.asarray(jax.devices()[:tp]).reshape(tp),
                        ("model",))
            got = LlamaForCausalLM(cfg, q, max_cache_len=32).shard(
                mesh).generate(ids, max_new_tokens=6)
            np.testing.assert_array_equal(want, got, err_msg=f"tp={tp}")

    def test_tp_pspecs_cover_linears(self):
        cfg = LlamaConfig.tiny()
        params = init_params(cfg, seed=0)
        specs = param_pspecs(params)
        q_spec = specs["layers"]["q_proj"]["w"]
        assert q_spec[1] == "model"          # N dim sharded (after stack)
        o_spec = specs["layers"]["o_proj"]["w"]
        assert o_spec[2] == "model"          # K dim sharded
        assert specs["norm"] == jax.sharding.PartitionSpec()

    def test_tp_sharded_forward_matches(self, devices):
        from bigdl_tpu.parallel import create_mesh
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM.from_config(cfg, seed=0, max_cache_len=16)
        ids = np.array([[1, 2, 3, 4]], np.int32)
        ref, _ = model(jnp.asarray(ids))
        mesh = create_mesh({"data": 2, "model": 2})
        model.shard(mesh)
        sharded, _ = model(jnp.asarray(ids))
        # bf16 partial-sum reduction order differs under TP psum
        np.testing.assert_allclose(np.asarray(ref), np.asarray(sharded),
                                   rtol=8e-2, atol=8e-2)


class TestTorchCrossCheck:
    def test_matches_hf_llama_numerics(self):
        """Golden parity vs the independent HF torch implementation
        (the reference's Torch-parity test pattern, SURVEY.md §4)."""
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        hf_cfg = transformers.LlamaConfig(
            vocab_size=96, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            rms_norm_eps=1e-5, rope_theta=10000.0, attn_implementation="eager")
        torch.manual_seed(0)
        hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()

        from bigdl_tpu.llm.transformers.model import _hf_to_params
        from bigdl_tpu.llm.models.llama import LlamaConfig as Cfg

        cfg = Cfg.from_hf(hf_cfg)
        params = _hf_to_params(hf_model, cfg)
        # bf16 storage loses bits vs torch f32; recast to f32 for parity
        params = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32)
            if a.dtype == jnp.bfloat16 else a, params)

        ids = np.array([[3, 17, 42, 9, 61]], np.int32)
        with torch.no_grad():
            ref = hf_model(torch.tensor(ids, dtype=torch.long)) \
                .logits.numpy()

        cache = init_cache(cfg, 1, 16, dtype=jnp.float32)
        pos = jnp.arange(5)[None, :]
        ours, _ = forward(params, cfg, jnp.asarray(ids), cache, pos)
        ours = np.asarray(ours)

        scale = np.abs(ref).max()
        assert np.abs(ours - ref).max() / scale < 0.02, \
            np.abs(ours - ref).max() / scale


class TestAttentionMemoryPaths:
    """The blockwise / GQA / ring attention paths must agree with the
    single-pass dense path (VERDICT r1 weak #6: full-logits + KV repeat
    was the 4k-context memory wall)."""

    def _logits(self, cfg, seed=0, T=24, cache_len=None):
        # f32 params: parity between attention paths is exact math, not
        # bf16 accumulation-order noise
        params = init_params(cfg, seed=seed, dtype=jnp.float32)
        rs = np.random.RandomState(seed)
        toks = jnp.asarray(rs.randint(0, cfg.vocab_size, (2, T)), jnp.int32)
        cache = init_cache(cfg, 2, cache_len or T, dtype=jnp.float32)
        pos = jnp.broadcast_to(jnp.arange(T), (2, T))
        logits, new_cache = forward(params, cfg, toks, cache, pos)
        return np.asarray(logits), new_cache

    def test_blockwise_matches_dense(self):
        """Same model, cache window larger than attn_block_size → the
        blockwise online-softmax path must reproduce the one-shot path."""
        import dataclasses
        cfg_dense = LlamaConfig.tiny()               # block 1024 ≫ window
        cfg_block = dataclasses.replace(cfg_dense, attn_block_size=8)
        ref, _ = self._logits(cfg_dense, T=24, cache_len=40)
        blk, _ = self._logits(cfg_block, T=24, cache_len=40)
        np.testing.assert_allclose(ref, blk, rtol=1e-4, atol=1e-4)

    def test_blockwise_decode_matches(self):
        """Blockwise on the decode step (Tq=1) with a partly-filled cache."""
        import dataclasses
        cfg_d = LlamaConfig.tiny()
        cfg_b = dataclasses.replace(cfg_d, attn_block_size=8)
        params = init_params(cfg_d, seed=1)
        rs = np.random.RandomState(1)
        toks = jnp.asarray(rs.randint(0, cfg_d.vocab_size, (1, 5)), jnp.int32)
        outs = {}
        for name, cfg in (("dense", cfg_d), ("block", cfg_b)):
            cache = init_cache(cfg, 1, 20)
            pos = jnp.arange(5)[None, :]
            lg, cache = forward(params, cfg, toks, cache, pos)
            nxt = jnp.argmax(lg[:, -1], -1).astype(jnp.int32)[:, None]
            lg2, _ = forward(params, cfg, nxt, cache, jnp.asarray([[5]]))
            outs[name] = np.asarray(lg2)
        np.testing.assert_allclose(outs["dense"], outs["block"],
                                   rtol=2e-2, atol=2e-2)

    def test_gqa_grouping_matches_explicit_repeat(self):
        """GQA einsum grouping must equal the explicit KV-head repeat."""
        from bigdl_tpu.llm.models.llama import _attention
        cfg = LlamaConfig.tiny()                       # Hq=4, Hkv=2
        rs = np.random.RandomState(0)
        b, tq, s, hq, hkv, d = 2, 3, 12, 4, 2, 16
        q = jnp.asarray(rs.randn(b, tq, hq, d), jnp.float32)
        k = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
        v = jnp.asarray(rs.randn(b, s, hkv, d), jnp.float32)
        qpos = jnp.broadcast_to(jnp.arange(9, 9 + tq), (b, tq))
        valid = jnp.broadcast_to(jnp.arange(s) < 12, (b, s))
        out = np.asarray(_attention(q, k, v, qpos, valid, cfg))

        # independent reference with explicit repeat
        rep = hq // hkv
        k_r = np.repeat(np.asarray(k), rep, axis=2)
        v_r = np.repeat(np.asarray(v), rep, axis=2)
        logits = np.einsum("bqhd,bshd->bhqs", np.asarray(q), k_r) / np.sqrt(d)
        mask = (np.arange(s)[None, None, None, :]
                <= np.asarray(qpos)[:, None, :, None])
        logits = np.where(mask, logits, -1e30)
        p = np.exp(logits - logits.max(-1, keepdims=True))
        p /= p.sum(-1, keepdims=True)
        ref = np.einsum("bhqs,bshd->bqhd", p, v_r).reshape(b, tq, hq * d)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_ring_prefill_matches_dense(self, devices):
        """sequence_parallel prefill over the 8-device ring must agree
        with the single-device dense prefill, and decoding must continue
        correctly from the ring-built cache."""
        from bigdl_tpu.parallel import create_mesh
        cfg = LlamaConfig.tiny()
        params = init_params(cfg, seed=0, dtype=jnp.float32)
        model = LlamaForCausalLM(cfg, params, max_cache_len=64,
                                 cache_dtype=jnp.float32)
        rs = np.random.RandomState(3)
        ids = rs.randint(0, cfg.vocab_size, (2, 32)).astype(np.int32)
        ref_logits, ref_cache = model(jnp.asarray(ids))

        mesh = create_mesh({"seq": 8})
        model.sequence_parallel(mesh)
        ring_logits, ring_cache = model(jnp.asarray(ids))
        np.testing.assert_allclose(np.asarray(ref_logits),
                                   np.asarray(ring_logits),
                                   rtol=1e-4, atol=1e-4)
        # cache must be identical so decode continues seamlessly
        np.testing.assert_allclose(np.asarray(ref_cache["k"]),
                                   np.asarray(ring_cache["k"]),
                                   rtol=1e-4, atol=1e-4)
        nxt = jnp.argmax(ring_logits[:, -1], -1).astype(jnp.int32)[:, None]
        pos = jnp.full((2, 1), 32)
        lg_ring, _ = forward(model.params, cfg, nxt, ring_cache, pos)
        lg_ref, _ = forward(model.params, cfg, nxt, ref_cache, pos)
        np.testing.assert_allclose(np.asarray(lg_ref), np.asarray(lg_ring),
                                   rtol=1e-4, atol=1e-4)


class TestSlidingWindow:
    def test_window_geq_seq_equals_dense(self):
        import dataclasses
        cfg = LlamaConfig.tiny()
        cfg_w = dataclasses.replace(cfg, sliding_window=64)
        params = init_params(cfg, seed=0, dtype=jnp.float32)
        toks = jnp.asarray([[1, 5, 9, 3, 7, 2]], jnp.int32)
        pos = jnp.arange(6)[None, :]
        for c in (cfg, cfg_w):
            cache = init_cache(c, 1, 8, dtype=jnp.float32)
            lg, _ = forward(params, c, toks, cache, pos)
            if c is cfg:
                ref = np.asarray(lg)
        np.testing.assert_allclose(np.asarray(lg), ref, rtol=1e-5,
                                   atol=1e-6)

    def test_window_masks_old_positions(self):
        """With window=2, position p attends only {p-1, p}: perturbing a
        token >=2 positions back must not change the current logits."""
        import dataclasses
        cfg = dataclasses.replace(LlamaConfig.tiny(), sliding_window=2)
        params = init_params(cfg, seed=0, dtype=jnp.float32)
        t1 = np.array([[4, 8, 15, 16, 23]], np.int32)
        t2 = t1.copy()
        t2[0, 0] = 42   # outside the window of the last position
        pos = jnp.arange(5)[None, :]
        outs = []
        for toks in (t1, t2):
            cache = init_cache(cfg, 1, 8, dtype=jnp.float32)
            lg, _ = forward(params, cfg, jnp.asarray(toks), cache, pos)
            outs.append(np.asarray(lg[:, -1]))
        np.testing.assert_allclose(outs[0], outs[1], rtol=1e-5, atol=1e-6)
        # sanity: the dense model DOES depend on that token
        outs_d = []
        cfg_d = LlamaConfig.tiny()
        for toks in (t1, t2):
            cache = init_cache(cfg_d, 1, 8, dtype=jnp.float32)
            lg, _ = forward(params, cfg_d, jnp.asarray(toks), cache, pos)
            outs_d.append(np.asarray(lg[:, -1]))
        assert np.abs(outs_d[0] - outs_d[1]).max() > 1e-4

    def test_blockwise_window_matches_single_pass(self):
        import dataclasses
        base = dataclasses.replace(LlamaConfig.tiny(), sliding_window=6)
        blk = dataclasses.replace(base, attn_block_size=8)
        params = init_params(base, seed=2, dtype=jnp.float32)
        rs = np.random.RandomState(0)
        toks = jnp.asarray(rs.randint(0, base.vocab_size, (2, 20)),
                           jnp.int32)
        pos = jnp.broadcast_to(jnp.arange(20), (2, 20))
        outs = {}
        for name, c in (("one", base), ("blk", blk)):
            cache = init_cache(c, 2, 24, dtype=jnp.float32)
            lg, _ = forward(params, c, toks, cache, pos)
            outs[name] = np.asarray(lg)
        np.testing.assert_allclose(outs["one"], outs["blk"], rtol=1e-4,
                                   atol=1e-5)


class TestGptNeoX:
    def test_prefill_decode_consistency(self):
        from bigdl_tpu.llm.models.gptneox import (
            GptNeoXConfig, forward as nx_forward, init_cache as nx_cache,
            init_params as nx_params)
        cfg = GptNeoXConfig.tiny()
        params = nx_params(cfg, seed=0, dtype=jnp.float32)
        toks = np.array([[5, 9, 3, 7]], np.int32)
        cache = nx_cache(cfg, 1, 16, dtype=jnp.float32)
        pos = jnp.arange(4)[None, :]
        full, _ = nx_forward(params, cfg, jnp.asarray(toks), cache, pos)
        cache = nx_cache(cfg, 1, 16, dtype=jnp.float32)
        outs = []
        for t in range(4):
            lg, cache = nx_forward(params, cfg,
                                   jnp.asarray(toks[:, t:t + 1]), cache,
                                   jnp.asarray([[t]]))
            outs.append(np.asarray(lg[:, 0]))
        np.testing.assert_allclose(np.asarray(full), np.stack(outs, 1),
                                   rtol=1e-4, atol=1e-4)

    def test_matches_hf_gptneox_numerics(self, tmp_path):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        hf_cfg = transformers.GPTNeoXConfig(
            vocab_size=97, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64, rotary_pct=0.25,
            use_parallel_residual=True, tie_word_embeddings=False)
        torch.manual_seed(0)
        hf = transformers.GPTNeoXForCausalLM(hf_cfg)
        hf.eval()
        path = str(tmp_path / "tiny-neox")
        hf.save_pretrained(path, safe_serialization=True)

        from bigdl_tpu.llm.transformers import AutoModelForCausalLM
        model = AutoModelForCausalLM.from_pretrained(path, max_cache_len=32)
        from bigdl_tpu.llm.models.gptneox import GptNeoXForCausalLM
        assert isinstance(model, GptNeoXForCausalLM)

        ids = np.array([[3, 17, 42, 9, 60]], np.int64)
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.float().numpy()
        logits, _ = model(jnp.asarray(ids, jnp.int32))
        ours = np.asarray(logits)
        np.testing.assert_allclose(ours, ref, rtol=0.1, atol=0.1)
        assert (np.argmax(ours[:, -1], -1) == np.argmax(ref[:, -1], -1)).all()

    def test_quantized_load_generates(self, tmp_path):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        hf_cfg = transformers.GPTNeoXConfig(
            vocab_size=97, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            max_position_embeddings=64)
        torch.manual_seed(0)
        path = str(tmp_path / "tiny-neox-q")
        transformers.GPTNeoXForCausalLM(hf_cfg).save_pretrained(
            path, safe_serialization=True)
        from bigdl_tpu.llm.transformers import AutoModelForCausalLM
        model = AutoModelForCausalLM.from_pretrained(
            path, load_in_4bit=True, max_cache_len=32)
        lp = model.params["layers"]["q_proj"]
        assert "q" in lp and "scale" in lp
        out = model.generate(np.array([[1, 5, 9]], np.int32),
                             max_new_tokens=6)
        assert out.shape == (1, 9)


class TestMoE:
    """Switch-FFN MoE on the Llama stack (VERDICT r2 #9: the one empty
    parallelism axis). Capacity mode for training; no-drop dense mode
    (capacity_factor<=0) is exact and batch-independent."""

    def _cfg(self, **kw):
        import dataclasses
        return dataclasses.replace(LlamaConfig.tiny_moe(), **kw)

    def test_prefill_decode_consistency_dense_mode(self):
        import dataclasses
        cfg = self._cfg(expert_capacity_factor=0.0)
        params = init_params(cfg, seed=0, dtype=jnp.float32)
        toks = np.array([[5, 9, 3, 7]], np.int32)
        cache = init_cache(cfg, 1, 16, dtype=jnp.float32)
        pos = jnp.arange(4)[None, :]
        full, _ = forward(params, cfg, jnp.asarray(toks), cache, pos)
        cache = init_cache(cfg, 1, 16, dtype=jnp.float32)
        outs = []
        for t in range(4):
            lg, cache = forward(params, cfg, jnp.asarray(toks[:, t:t + 1]),
                                cache, jnp.asarray([[t]]))
            outs.append(np.asarray(lg[:, 0]))
        np.testing.assert_allclose(np.asarray(full), np.stack(outs, 1),
                                   rtol=2e-2, atol=2e-2)

    def test_capacity_mode_matches_dense_when_roomy(self):
        """With capacity >= S*K no slot can drop: the dispatch-based path
        must agree with the dense no-drop path."""
        cfg_cap = self._cfg(expert_capacity_factor=float(
            self._cfg().num_experts))   # C = S*K — roomy
        cfg_dense = self._cfg(expert_capacity_factor=0.0)
        params = init_params(cfg_cap, seed=1, dtype=jnp.float32)
        toks = np.array([[3, 1, 4, 1, 5]], np.int32)
        pos = jnp.arange(5)[None, :]
        outs = {}
        for name, cfg in (("cap", cfg_cap), ("dense", cfg_dense)):
            cache = init_cache(cfg, 1, 8, dtype=jnp.float32)
            lg, _ = forward(params, cfg, jnp.asarray(toks), cache, pos)
            outs[name] = np.asarray(lg)
        np.testing.assert_allclose(outs["cap"], outs["dense"],
                                   rtol=2e-2, atol=2e-2)

    def test_moe_generate(self):
        cfg = self._cfg()
        model = LlamaForCausalLM.from_config(cfg, seed=0, max_cache_len=32)
        out = model.generate(np.array([[1, 2, 3]], np.int32),
                             max_new_tokens=5)
        assert out.shape == (1, 8)
        assert (out < cfg.vocab_size).all()

    def test_ep_pspecs(self, devices):
        from bigdl_tpu.parallel import create_mesh
        from jax.sharding import NamedSharding
        cfg = self._cfg()
        params = init_params(cfg, seed=0)
        specs = param_pspecs(params, ep_axis="ep")
        gspec = specs["layers"]["gate_proj"]["w"]
        assert gspec[1] == "ep" and gspec[2] == "model"
        dspec = specs["layers"]["down_proj"]["w"]
        assert dspec[1] == "ep" and dspec[3] == "model"
        assert specs["layers"]["router"]["w"][1] == "ep"
        # place + run one sharded forward on a dp x ep x tp mesh
        mesh = create_mesh({"data": 2, "ep": 2, "model": 2})
        sharded = jax.tree_util.tree_map(
            lambda a, sp: jax.device_put(a, NamedSharding(mesh, sp)),
            params, specs)
        toks = np.array([[1, 2, 3, 4]] * 2, np.int32)
        cache = init_cache(cfg, 2, 8)
        lg, _ = forward(sharded, cfg, jnp.asarray(toks), cache,
                        jnp.broadcast_to(jnp.arange(4), (2, 4)))
        assert np.isfinite(np.asarray(lg, np.float32)).all()


class TestQwen2CrossCheck:
    def test_matches_hf_qwen2_numerics(self):
        """Golden parity vs HF torch Qwen2 — third cross-checked family
        (Llama block + GQA + q/k/v biases, the qwen lineage of the
        reference's model zoo)."""
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        hf_cfg = transformers.Qwen2Config(
            vocab_size=96, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            rms_norm_eps=1e-6, rope_theta=10000.0,
            tie_word_embeddings=False, use_sliding_window=False,
            attn_implementation="eager")
        torch.manual_seed(0)
        hf = transformers.Qwen2ForCausalLM(hf_cfg).eval()

        from bigdl_tpu.llm.models.llama import LlamaConfig as Cfg
        from bigdl_tpu.llm.transformers.model import _hf_to_params

        cfg = Cfg.from_hf(hf_cfg)
        assert cfg.attention_bias, "qwen2 must map to attention_bias"
        # HF Qwen2 default: sliding_window present but NOT applied
        # (use_sliding_window=False) — must not window-mask our layers
        assert cfg.sliding_window is None
        params = _hf_to_params(hf, cfg)
        assert "b" in params["layers"]["q_proj"]
        params = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32)
            if a.dtype == jnp.bfloat16 else a, params)

        ids = np.array([[3, 17, 42, 9, 61, 7, 25, 50]], np.int32)
        with torch.no_grad():
            ref = hf(torch.tensor(ids, dtype=torch.long)) \
                .logits.numpy()

        cache = init_cache(cfg, 1, 16, dtype=jnp.float32)
        pos = jnp.arange(ids.shape[1])[None, :]
        ours, _ = forward(params, cfg, jnp.asarray(ids), cache, pos)
        ours = np.asarray(ours)
        scale = np.abs(ref).max()
        assert np.abs(ours - ref).max() / scale < 0.02, \
            np.abs(ours - ref).max() / scale

    def test_quantized_bias_generate_and_fusion(self):
        """Quantized Qwen2: biases stay dense beside q4 planes, survive
        qkv fusion, and fused == unfused greedy tokens."""
        cfg = LlamaConfig.tiny_qwen2()
        dense = LlamaForCausalLM.from_config(cfg, seed=0, max_cache_len=32)
        assert "b" in dense.params["layers"]["q_proj"]
        qf = LlamaForCausalLM(cfg, quantize_params(dense.params),
                              max_cache_len=32)
        assert "b" in qf.params["layers"]["qkv_proj"]
        qu = LlamaForCausalLM(cfg, quantize_params(dense.params,
                                                   fuse=False),
                              max_cache_len=32)
        ids = np.array([[4, 8, 15]], np.int32)
        np.testing.assert_array_equal(
            qf.generate(ids, max_new_tokens=8),
            qu.generate(ids, max_new_tokens=8))

    def test_paged_server_serves_qwen2(self):
        """The paged server handles bias models (attention_qkv plumbs
        the fused bias through prefill and decode)."""
        from bigdl_tpu.llm.serving import LLMServer

        cfg = LlamaConfig.tiny_qwen2()
        # non-zero biases so a dropped bias would change tokens
        model = LlamaForCausalLM.from_config(cfg, seed=0, max_cache_len=64)
        key = jax.random.PRNGKey(9)
        lay = dict(model.params["layers"])
        for i, name in enumerate(("q_proj", "k_proj", "v_proj")):
            d = dict(lay[name])
            d["b"] = jax.random.normal(
                jax.random.fold_in(key, i), d["b"].shape,
                jnp.float32) * 0.3
            lay[name] = d
        model.params = dict(model.params, layers=lay)
        ids = np.array([3, 1, 4, 1, 5], np.int32)
        want = model.generate(ids[None], max_new_tokens=6)[0, 5:]
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        try:
            got = srv.submit(ids, max_new_tokens=6).get(timeout=300)
        finally:
            srv.stop()
        np.testing.assert_array_equal(np.asarray(got), want)


class TestMistralCrossCheck:
    def test_matches_hf_mistral_numerics(self):
        """Golden parity vs HF torch Mistral (sliding-window family) —
        the same independent-implementation pattern as the Llama and
        GPT-NeoX cross-checks (SURVEY.md §4)."""
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        hf_cfg = transformers.MistralConfig(
            vocab_size=96, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            rms_norm_eps=1e-5, rope_theta=10000.0, sliding_window=4,
            attn_implementation="eager")
        torch.manual_seed(0)
        hf = transformers.MistralForCausalLM(hf_cfg).eval()

        from bigdl_tpu.llm.models.llama import LlamaConfig as Cfg
        from bigdl_tpu.llm.transformers.model import _hf_to_params

        cfg = Cfg.from_hf(hf_cfg)
        assert cfg.sliding_window == 4
        params = _hf_to_params(hf, cfg)
        params = jax.tree_util.tree_map(
            lambda a: a.astype(jnp.float32)
            if a.dtype == jnp.bfloat16 else a, params)

        ids = np.array([[3, 17, 42, 9, 61, 7, 25, 50]], np.int32)
        with torch.no_grad():
            ref = hf(torch.tensor(ids, dtype=torch.long)) \
                .logits.numpy()

        cache = init_cache(cfg, 1, 16, dtype=jnp.float32)
        pos = jnp.arange(ids.shape[1])[None, :]
        ours, _ = forward(params, cfg, jnp.asarray(ids), cache, pos)
        ours = np.asarray(ours)
        scale = np.abs(ref).max()
        assert np.abs(ours - ref).max() / scale < 0.02, \
            np.abs(ours - ref).max() / scale

"""Paged KV-cache attention kernels (ref: the vLLM paged-attention row
of SURVEY.md §2.2/§2.8 — serving's ragged attention). Golden parity: the
Mosaic kernels (interpret mode on CPU) and the XLA references are both
checked against independent numpy softmaxes — decode here since PR 5,
the ISSUE 8 ragged paged-PREFILL kernel below
(:class:`TestRaggedPrefill`)."""

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu.llm.kernels.paged_attention import (
    LANE, paged_attention_decode, paged_attention_reference)
from bigdl_tpu.llm.kernels.ragged_prefill import (
    ragged_prefill_attention, ragged_prefill_reference)

pytestmark = pytest.mark.kernels


def _naive(q, k_pages, v_pages, bt, lens, bi, window=None):
    P, Hkv, page, D = k_pages.shape
    Hq = q.shape[1]
    maxp = bt.shape[1]
    s_max = maxp * page
    ks = k_pages[bt[bi]].transpose(0, 2, 1, 3).reshape(s_max, Hkv, D)
    vs = v_pages[bt[bi]].transpose(0, 2, 1, 3).reshape(s_max, Hkv, D)
    L = int(lens[bi])
    lo = max(0, L - window) if window else 0
    out = np.zeros((Hq, D))
    for h in range(Hq):
        kh, vh = ks[lo:L, h // (Hq // Hkv)], vs[lo:L, h // (Hq // Hkv)]
        sc = (q[bi, h] @ kh.T) / np.sqrt(D)
        w = np.exp(sc - sc.max())
        w /= w.sum()
        out[h] = w @ vh
    return out


def _setup(rs, B, Hq, Hkv, D, page, P, maxp):
    q = rs.randn(B, Hq, D).astype(np.float32)
    k_pages = rs.randn(P, Hkv, page, D).astype(np.float32)
    v_pages = rs.randn(P, Hkv, page, D).astype(np.float32)
    bt = rs.permutation(P)[:B * maxp].reshape(B, maxp).astype(np.int32)
    lens = rs.randint(1, maxp * page + 1, B).astype(np.int32)
    return q, k_pages, v_pages, bt, lens


class TestPagedAttention:
    @pytest.mark.parametrize("Hq,Hkv", [(8, 8), (8, 2)])
    def test_reference_matches_naive(self, Hq, Hkv):
        rs = np.random.RandomState(0)
        q, kp, vp, bt, lens = _setup(rs, 3, Hq, Hkv, 128, 16, 64, 16)
        ref = np.asarray(paged_attention_reference(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(lens)))
        for bi in range(3):
            np.testing.assert_allclose(ref[bi],
                                       _naive(q, kp, vp, bt, lens, bi),
                                       rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("page_major", [False, True])
    @pytest.mark.parametrize("Hq,Hkv", [(8, 8), (8, 2)])
    def test_kernel_interpret_matches_reference(self, Hq, Hkv,
                                                page_major):
        rs = np.random.RandomState(1)
        q, kp, vp, bt, lens = _setup(rs, 2, Hq, Hkv, 128, 16, 48, 16)
        args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(bt), jnp.asarray(lens))
        ker = np.asarray(paged_attention_decode(*args, page_size=16,
                                                interpret=True,
                                                page_major=page_major))
        ref = np.asarray(paged_attention_reference(*args))
        np.testing.assert_allclose(ker, ref, rtol=2e-3, atol=2e-3)

    def test_sliding_window(self):
        rs = np.random.RandomState(2)
        q, kp, vp, bt, lens = _setup(rs, 2, 4, 4, 128, 16, 48, 16)
        win = 40
        args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(bt), jnp.asarray(lens))
        ref = np.asarray(paged_attention_reference(
            *args, sliding_window=win))
        for bi in range(2):
            np.testing.assert_allclose(
                ref[bi], _naive(q, kp, vp, bt, lens, bi, window=win),
                rtol=2e-5, atol=2e-5)
        ker = np.asarray(paged_attention_decode(
            *args, page_size=16, interpret=True, sliding_window=win))
        np.testing.assert_allclose(ker, ref, rtol=2e-3, atol=2e-3)

    @pytest.mark.parametrize("interpret", [None, True])
    @pytest.mark.parametrize("window", [None, 24])
    def test_stats_merge_equals_write_then_attend(self, interpret, window):
        """The round-5 serving decode structure: stats over the existing
        ``lens`` tokens + merge of the current token's K/V must equal
        writing the token to its page first and attending over lens+1
        (what the python-loop decode did). interpret=None exercises the
        XLA reference-stats path, True the Mosaic kernel thunk."""
        from bigdl_tpu.llm.kernels.paged_attention import (
            merge_attention_partial, paged_attention_reference,
            paged_attention_stats)
        rs = np.random.RandomState(4)
        B, Hq, Hkv, D, page, P, maxp = 3, 8, 2, 128, 16, 64, 16
        q, kp, vp, bt, lens = _setup(rs, B, Hq, Hkv, D, page, P, maxp)
        lens = np.minimum(lens, maxp * page - 1)  # room for the new token
        k_new = rs.randn(B, Hkv, D).astype(np.float32)
        v_new = rs.randn(B, Hkv, D).astype(np.float32)

        acc, m, l = paged_attention_stats(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(lens), page_size=page,
            interpret=interpret,
            sliding_window=None if window is None else window - 1)
        got = np.asarray(merge_attention_partial(
            acc, m, l, jnp.asarray(q), jnp.asarray(k_new),
            jnp.asarray(v_new)))

        # golden: write the token at (bt[b, lens//page], lens%page), then
        # full attention over lens+1
        kp2, vp2 = kp.copy(), vp.copy()
        for bi in range(B):
            pid = bt[bi, lens[bi] // page]
            kp2[pid, :, lens[bi] % page] = k_new[bi]
            vp2[pid, :, lens[bi] % page] = v_new[bi]
        want = np.asarray(paged_attention_reference(
            jnp.asarray(q), jnp.asarray(kp2), jnp.asarray(vp2),
            jnp.asarray(bt), jnp.asarray(lens + 1),
            sliding_window=window))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_stats_empty_row_identity(self):
        """lens == 0 rows must return the combine identity so the merge
        yields pure self-attention (softmax of one element = v_new)."""
        from bigdl_tpu.llm.kernels.paged_attention import (
            merge_attention_partial, paged_attention_stats)
        rs = np.random.RandomState(5)
        B, Hq, Hkv, D, page, P, maxp = 2, 4, 4, 128, 16, 32, 8
        q, kp, vp, bt, _ = _setup(rs, B, Hq, Hkv, D, page, P, maxp)
        lens = np.zeros(B, np.int32)
        v_new = rs.randn(B, Hkv, D).astype(np.float32)
        k_new = rs.randn(B, Hkv, D).astype(np.float32)
        acc, m, l = paged_attention_stats(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(lens), page_size=page)
        np.testing.assert_allclose(np.asarray(l), 0.0)
        got = np.asarray(merge_attention_partial(
            acc, m, l, jnp.asarray(q), jnp.asarray(k_new),
            jnp.asarray(v_new)))
        np.testing.assert_allclose(got, np.repeat(v_new, Hq // Hkv, 1),
                                   rtol=1e-5, atol=1e-5)

    def test_lane_contract(self):
        rs = np.random.RandomState(3)
        q, kp, vp, bt, lens = _setup(rs, 2, 4, 4, 128, 16, 48, 12)
        with pytest.raises(ValueError, match="multiple"):
            # pages_max=12 is not a multiple of LANE//16 = 8
            paged_attention_decode(
                jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(bt), jnp.asarray(lens), page_size=16,
                interpret=True)
        assert LANE == 128

    def test_reference_gather_sliced_to_live_span(self):
        """The ISSUE 8 small fix: with concrete lengths the reference
        gathers only the live page span, not the padded table capacity
        — and the sliced result matches the full gather (to float
        rounding: the softmax reduction width shrinks with the slice)."""
        from bigdl_tpu.llm.kernels.paged_attention import _sliced_tables
        rs = np.random.RandomState(6)
        q, kp, vp, bt, lens = _setup(rs, 2, 4, 4, 128, 16, 64, 16)
        lens = np.minimum(lens, 40)        # live span: 3 of 16 pages
        sliced = _sliced_tables(jnp.asarray(bt), jnp.asarray(lens), 16)
        assert sliced.shape[1] == -(-int(lens.max()) // 16)
        args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp))
        full = np.asarray(paged_attention_reference(
            *args, jnp.asarray(bt), jnp.asarray(lens),
            max_live_tokens=16 * 16))      # bound = capacity: no slice
        got = np.asarray(paged_attention_reference(
            *args, jnp.asarray(bt), jnp.asarray(lens)))
        np.testing.assert_allclose(got, full, rtol=1e-6, atol=1e-6)
        # traced lengths keep the static shape (jit safety)
        import jax
        traced = jax.eval_shape(
            lambda t: _sliced_tables(jnp.asarray(bt), t, 16),
            jax.ShapeDtypeStruct(lens.shape, jnp.int32))
        assert traced.shape == bt.shape


# ---------------------------------------------------------------------------
# ragged paged-PREFILL kernel (ISSUE 8)
# ---------------------------------------------------------------------------

def _naive_ragged(q, k_suf, v_suf, kp, vp, bt, offs, lens, bi,
                  window=None):
    """Independent numpy golden: row ``bi``'s suffix queries attend the
    prefix gathered from pages (positions < offs) plus the dense suffix
    K/V, causally at positions offs + j."""
    P, Hkv, page, D = kp.shape
    B, Tq, Hq, _ = q.shape
    off, sl = int(offs[bi]), int(lens[bi])
    maxp = bt.shape[1]
    ks = kp[bt[bi]].transpose(0, 2, 1, 3).reshape(maxp * page, Hkv, D)
    vs = vp[bt[bi]].transpose(0, 2, 1, 3).reshape(maxp * page, Hkv, D)
    k_all = np.concatenate([ks[:off], k_suf[bi, :sl]], 0)
    v_all = np.concatenate([vs[:off], v_suf[bi, :sl]], 0)
    out = np.zeros((sl, Hq, D))
    for j in range(sl):
        qpos = off + j
        lo = max(0, qpos + 1 - window) if window else 0
        for h in range(Hq):
            hk = h // (Hq // Hkv)
            kh, vh = k_all[lo:qpos + 1, hk], v_all[lo:qpos + 1, hk]
            sc = (q[bi, j, h] @ kh.T) / np.sqrt(D)
            w = np.exp(sc - sc.max())
            w /= w.sum()
            out[j, h] = w @ vh
    return out


def _setup_ragged(rs, B, Tq, Hq, Hkv, D, page, P, maxp, offs, lens):
    q = rs.randn(B, Tq, Hq, D).astype(np.float32)
    k_suf = rs.randn(B, Tq, Hkv, D).astype(np.float32)
    v_suf = rs.randn(B, Tq, Hkv, D).astype(np.float32)
    kp = rs.randn(P, Hkv, page, D).astype(np.float32)
    vp = rs.randn(P, Hkv, page, D).astype(np.float32)
    bt = rs.permutation(P)[:B * maxp].reshape(B, maxp).astype(np.int32)
    args = tuple(jnp.asarray(a) for a in
                 (q, k_suf, v_suf, kp, vp, bt,
                  np.asarray(offs, np.int32), np.asarray(lens, np.int32)))
    return (q, k_suf, v_suf, kp, vp, bt, np.asarray(offs, np.int32),
            np.asarray(lens, np.int32)), args


class TestRaggedPrefill:
    # offsets mix a page boundary (32), mid-page (17) and zero (the
    # full-prefill case: no page block contributes); lens are ragged
    OFFS = (32, 17, 0)
    LENS = (12, 7, 9)

    def test_reference_matches_naive(self):
        # GQA (Hq=4, Hkv=2) subsumes the MHA head mapping in the naive
        # check; the kernel test below keeps both combos
        Hq, Hkv = 4, 2
        rs = np.random.RandomState(10)
        raw, args = _setup_ragged(rs, 3, 12, Hq, Hkv, 128, 16, 32, 8,
                                  self.OFFS, self.LENS)
        ref = np.asarray(ragged_prefill_reference(*args))
        for bi in range(3):
            sl = self.LENS[bi]
            np.testing.assert_allclose(
                ref[bi, :sl], _naive_ragged(*raw, bi),
                rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("Hq,Hkv", [(4, 4), (4, 2)])
    def test_kernel_interpret_matches_reference(self, Hq, Hkv):
        rs = np.random.RandomState(11)
        _, args = _setup_ragged(rs, 3, 12, Hq, Hkv, 128, 16, 32, 8,
                                self.OFFS, self.LENS)
        ker = np.asarray(ragged_prefill_attention(
            *args, page_size=16, interpret=True))
        ref = np.asarray(ragged_prefill_reference(*args))
        for bi in range(3):
            sl = self.LENS[bi]
            np.testing.assert_allclose(ker[bi, :sl], ref[bi, :sl],
                                       rtol=2e-3, atol=2e-3)

    def test_sliding_window(self):
        rs = np.random.RandomState(12)
        win = 24
        raw, args = _setup_ragged(rs, 2, 12, 4, 4, 128, 16, 32, 8,
                                  (48, 21), (12, 5))
        ref = np.asarray(ragged_prefill_reference(
            *args, sliding_window=win))
        for bi in range(2):
            sl = int(raw[7][bi])
            np.testing.assert_allclose(
                ref[bi, :sl], _naive_ragged(*raw, bi, window=win),
                rtol=2e-5, atol=2e-5)
        ker = np.asarray(ragged_prefill_attention(
            *args, page_size=16, interpret=True, sliding_window=win))
        for bi in range(2):
            sl = int(raw[7][bi])
            np.testing.assert_allclose(ker[bi, :sl], ref[bi, :sl],
                                       rtol=2e-3, atol=2e-3)

    def test_head_dim_padding(self):
        """d = 64 < 128: the kernel zero-pads the minor dim for the
        Mosaic DMA alignment and slices it back off."""
        rs = np.random.RandomState(13)
        raw, args = _setup_ragged(rs, 2, 8, 4, 2, 64, 16, 32, 8,
                                  (16, 5), (8, 3))
        ker = ragged_prefill_attention(*args, page_size=16,
                                       interpret=True)
        assert ker.shape[-1] == 64
        ref = np.asarray(ragged_prefill_reference(*args))
        for bi in range(2):
            sl = int(raw[7][bi])
            np.testing.assert_allclose(
                np.asarray(ker)[bi, :sl], ref[bi, :sl],
                rtol=2e-3, atol=2e-3)
            np.testing.assert_allclose(
                ref[bi, :sl], _naive_ragged(*raw, bi),
                rtol=2e-5, atol=2e-5)

    def test_all_masked_rows_finite(self):
        """Query rows past ``seq_lens`` (incl. a fully idle row with
        len 0) see every score masked — the contract is finite garbage,
        never NaN, so the engine can slice without sanitizing."""
        rs = np.random.RandomState(14)
        _, args = _setup_ragged(rs, 2, 8, 4, 4, 128, 16, 32, 8,
                                (32, 0), (3, 0))
        for out in (ragged_prefill_attention(*args, page_size=16,
                                             interpret=True),
                    ragged_prefill_reference(*args)):
            assert bool(jnp.all(jnp.isfinite(out)))

    def test_pages_max_contract(self):
        rs = np.random.RandomState(15)
        _, args = _setup_ragged(rs, 1, 8, 4, 4, 128, 16, 32, 6,
                                (16,), (8,))
        with pytest.raises(ValueError, match="multiple"):
            # pages_max=6 is not a multiple of LANE//16 = 8
            ragged_prefill_attention(*args, page_size=16,
                                     interpret=True)

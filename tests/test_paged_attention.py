"""Paged KV-cache attention kernel (ref: the vLLM paged-attention row of
SURVEY.md §2.2/§2.8 — serving's ragged attention). Golden parity: the
Mosaic kernel (interpret mode on CPU) and the XLA gather reference are
both checked against an independent numpy softmax."""

import numpy as np
import pytest

import jax.numpy as jnp

from bigdl_tpu.llm.kernels.paged_attention import (
    LANE, paged_attention_decode, paged_attention_reference)


def _naive(q, k_pages, v_pages, bt, lens, bi, window=None):
    P, Hkv, page, D = k_pages.shape
    Hq = q.shape[1]
    maxp = bt.shape[1]
    s_max = maxp * page
    ks = k_pages[bt[bi]].transpose(0, 2, 1, 3).reshape(s_max, Hkv, D)
    vs = v_pages[bt[bi]].transpose(0, 2, 1, 3).reshape(s_max, Hkv, D)
    L = int(lens[bi])
    lo = max(0, L - window) if window else 0
    out = np.zeros((Hq, D))
    for h in range(Hq):
        kh, vh = ks[lo:L, h // (Hq // Hkv)], vs[lo:L, h // (Hq // Hkv)]
        sc = (q[bi, h] @ kh.T) / np.sqrt(D)
        w = np.exp(sc - sc.max())
        w /= w.sum()
        out[h] = w @ vh
    return out


def _setup(rs, B, Hq, Hkv, D, page, P, maxp):
    q = rs.randn(B, Hq, D).astype(np.float32)
    k_pages = rs.randn(P, Hkv, page, D).astype(np.float32)
    v_pages = rs.randn(P, Hkv, page, D).astype(np.float32)
    bt = rs.permutation(P)[:B * maxp].reshape(B, maxp).astype(np.int32)
    lens = rs.randint(1, maxp * page + 1, B).astype(np.int32)
    return q, k_pages, v_pages, bt, lens


class TestPagedAttention:
    @pytest.mark.parametrize("Hq,Hkv", [(8, 8), (8, 2)])
    def test_reference_matches_naive(self, Hq, Hkv):
        rs = np.random.RandomState(0)
        q, kp, vp, bt, lens = _setup(rs, 3, Hq, Hkv, 128, 16, 64, 16)
        ref = np.asarray(paged_attention_reference(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(lens)))
        for bi in range(3):
            np.testing.assert_allclose(ref[bi],
                                       _naive(q, kp, vp, bt, lens, bi),
                                       rtol=2e-5, atol=2e-5)

    @pytest.mark.parametrize("page_major", [False, True])
    @pytest.mark.parametrize("Hq,Hkv", [(8, 8), (8, 2)])
    def test_kernel_interpret_matches_reference(self, Hq, Hkv,
                                                page_major):
        rs = np.random.RandomState(1)
        q, kp, vp, bt, lens = _setup(rs, 2, Hq, Hkv, 128, 16, 48, 16)
        args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(bt), jnp.asarray(lens))
        ker = np.asarray(paged_attention_decode(*args, page_size=16,
                                                interpret=True,
                                                page_major=page_major))
        ref = np.asarray(paged_attention_reference(*args))
        np.testing.assert_allclose(ker, ref, rtol=2e-3, atol=2e-3)

    def test_sliding_window(self):
        rs = np.random.RandomState(2)
        q, kp, vp, bt, lens = _setup(rs, 2, 4, 4, 128, 16, 48, 16)
        win = 40
        args = (jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(bt), jnp.asarray(lens))
        ref = np.asarray(paged_attention_reference(
            *args, sliding_window=win))
        for bi in range(2):
            np.testing.assert_allclose(
                ref[bi], _naive(q, kp, vp, bt, lens, bi, window=win),
                rtol=2e-5, atol=2e-5)
        ker = np.asarray(paged_attention_decode(
            *args, page_size=16, interpret=True, sliding_window=win))
        np.testing.assert_allclose(ker, ref, rtol=2e-3, atol=2e-3)

    @pytest.mark.parametrize("interpret", [None, True])
    @pytest.mark.parametrize("window", [None, 24])
    def test_stats_merge_equals_write_then_attend(self, interpret, window):
        """The round-5 serving decode structure: stats over the existing
        ``lens`` tokens + merge of the current token's K/V must equal
        writing the token to its page first and attending over lens+1
        (what the python-loop decode did). interpret=None exercises the
        XLA reference-stats path, True the Mosaic kernel thunk."""
        from bigdl_tpu.llm.kernels.paged_attention import (
            merge_attention_partial, paged_attention_reference,
            paged_attention_stats)
        rs = np.random.RandomState(4)
        B, Hq, Hkv, D, page, P, maxp = 3, 8, 2, 128, 16, 64, 16
        q, kp, vp, bt, lens = _setup(rs, B, Hq, Hkv, D, page, P, maxp)
        lens = np.minimum(lens, maxp * page - 1)  # room for the new token
        k_new = rs.randn(B, Hkv, D).astype(np.float32)
        v_new = rs.randn(B, Hkv, D).astype(np.float32)

        acc, m, l = paged_attention_stats(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(lens), page_size=page,
            interpret=interpret,
            sliding_window=None if window is None else window - 1)
        got = np.asarray(merge_attention_partial(
            acc, m, l, jnp.asarray(q), jnp.asarray(k_new),
            jnp.asarray(v_new)))

        # golden: write the token at (bt[b, lens//page], lens%page), then
        # full attention over lens+1
        kp2, vp2 = kp.copy(), vp.copy()
        for bi in range(B):
            pid = bt[bi, lens[bi] // page]
            kp2[pid, :, lens[bi] % page] = k_new[bi]
            vp2[pid, :, lens[bi] % page] = v_new[bi]
        want = np.asarray(paged_attention_reference(
            jnp.asarray(q), jnp.asarray(kp2), jnp.asarray(vp2),
            jnp.asarray(bt), jnp.asarray(lens + 1),
            sliding_window=window))
        np.testing.assert_allclose(got, want, rtol=2e-3, atol=2e-3)

    def test_stats_empty_row_identity(self):
        """lens == 0 rows must return the combine identity so the merge
        yields pure self-attention (softmax of one element = v_new)."""
        from bigdl_tpu.llm.kernels.paged_attention import (
            merge_attention_partial, paged_attention_stats)
        rs = np.random.RandomState(5)
        B, Hq, Hkv, D, page, P, maxp = 2, 4, 4, 128, 16, 32, 8
        q, kp, vp, bt, _ = _setup(rs, B, Hq, Hkv, D, page, P, maxp)
        lens = np.zeros(B, np.int32)
        v_new = rs.randn(B, Hkv, D).astype(np.float32)
        k_new = rs.randn(B, Hkv, D).astype(np.float32)
        acc, m, l = paged_attention_stats(
            jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
            jnp.asarray(bt), jnp.asarray(lens), page_size=page)
        np.testing.assert_allclose(np.asarray(l), 0.0)
        got = np.asarray(merge_attention_partial(
            acc, m, l, jnp.asarray(q), jnp.asarray(k_new),
            jnp.asarray(v_new)))
        np.testing.assert_allclose(got, np.repeat(v_new, Hq // Hkv, 1),
                                   rtol=1e-5, atol=1e-5)

    def test_lane_contract(self):
        rs = np.random.RandomState(3)
        q, kp, vp, bt, lens = _setup(rs, 2, 4, 4, 128, 16, 48, 12)
        with pytest.raises(ValueError, match="multiple"):
            # pages_max=12 is not a multiple of LANE//16 = 8
            paged_attention_decode(
                jnp.asarray(q), jnp.asarray(kp), jnp.asarray(vp),
                jnp.asarray(bt), jnp.asarray(lens), page_size=16,
                interpret=True)
        assert LANE == 128

"""Prefix-aware KV-cache subsystem (ISSUE 5): pool/radix units, engine
greedy parity with the cache on, COW forks, LRU eviction under
pressure, and the disabled-mode structural-absence contract."""

import json
import http.client

import numpy as np
import pytest

from bigdl_tpu.llm.kvcache import KVCacheManager
from bigdl_tpu.llm.kvcache.pool import PagePool, PagePoolError
from bigdl_tpu.llm.kvcache.radix import RadixIndex
from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import LLMServer

pytestmark = pytest.mark.kvcache

PAGE = 8


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=128)


def _generate(model, p, n):
    return model.generate(np.asarray(p)[None], max_new_tokens=n)[0, len(p):]


# ---------------------------------------------------------------------------
# pool: refcounts, COW, budget/pins
# ---------------------------------------------------------------------------

class TestPagePool:
    def test_seed_engine_allocation_order(self):
        """Disabled-mode bit-parity rests on this: ids pop low-first
        (page 1 first) and frees append — exactly the embedded
        free-list the pool replaced."""
        pool = PagePool(6, PAGE)
        assert [pool.take_free() for _ in range(5)] == [1, 2, 3, 4, 5]
        pool.decref(3)
        pool.decref(1)
        assert pool.take_free() == 1          # LIFO over the appends
        assert pool.take_free() == 3
        with pytest.raises(PagePoolError):
            pool.take_free()                  # pool drained
        pool.decref(5)
        assert pool.free_ids() == [5]

    def test_refcounts_free_only_at_zero(self):
        pool = PagePool(4, PAGE)
        p = pool.take_free()
        pool.incref(p)
        assert pool.decref(p) == 1
        assert pool.free_pages() == 2         # still held
        assert pool.decref(p) == 0
        assert pool.free_pages() == 3
        with pytest.raises(PagePoolError):
            pool.decref(p)                    # double free

    def test_budget_and_pins(self):
        """Pins charge ONE reservation per shared page regardless of
        adopter count, released on the last unpin."""
        pool = PagePool(6, PAGE)
        pool.charge(2)
        assert pool.budget_avail == 3
        p = pool.take_free()
        assert pool.pin_cost([p, p]) == 1     # dedup within one call
        pool.pin(p)
        pool.pin(p)                           # second adopter: no charge
        assert pool.budget_avail == 2
        pool.unpin(p)
        assert pool.budget_avail == 2         # still pinned once
        pool.unpin(p)
        assert pool.budget_avail == 3
        with pytest.raises(PagePoolError):
            pool.charge(4)                    # overdraft is a bug


# ---------------------------------------------------------------------------
# radix index: chunk walk, partial tails, LRU eviction
# ---------------------------------------------------------------------------

def _mk_index(num_pages=16, page=4):
    pool = PagePool(num_pages, page)
    return pool, RadixIndex(pool)


class TestRadixIndex:
    def test_insert_lookup_full_chunks(self):
        pool, idx = _mk_index()
        toks = list(range(10))                # 2 full pages + tail of 2
        pages = pool.alloc(3)
        idx.insert(toks, pages)
        m = idx.lookup(toks)
        assert m.matched_len == 10
        assert m.full_pages == pages[:2]
        assert m.tail_src == pages[2] and m.tail_len == 2
        # divergent mid-page: 1 full page + 2 shared slots of page 2
        m = idx.lookup([0, 1, 2, 3, 4, 5, 99, 99])
        assert m.matched_len == 6
        assert m.full_pages == pages[:1]
        assert m.tail_src == pages[1] and m.tail_len == 2
        # disjoint: nothing
        assert idx.lookup([7, 7, 7, 7]).matched_len == 0

    def test_duplicate_insert_keeps_existing_nodes(self):
        pool, idx = _mk_index()
        a = pool.alloc(2)
        idx.insert(list(range(8)), a)
        b = pool.alloc(2)
        taken = idx.insert(list(range(8)), b)
        assert taken == []                    # duplicates not adopted
        assert idx.lookup(list(range(8))).full_pages == a
        assert pool.refcount(b[0]) == 1       # still only the caller's

    def test_lru_eviction_leaf_first(self):
        pool, idx = _mk_index(num_pages=8)
        cold = pool.alloc(2)
        idx.insert([1, 1, 1, 1, 2, 2, 2, 2], cold)
        warm = pool.alloc(2)
        idx.insert([3, 3, 3, 3, 4, 4, 4, 4], warm)
        for p in cold + warm:
            pool.decref(p)                    # only the index holds them
        idx.lookup([1, 1, 1, 1])              # re-warm cold's FIRST page
        # LRU is per NODE: cold's untouched second page is the coldest
        # leaf, then the warm chain drains back-to-front, and the
        # re-warmed cold head survives longest
        assert idx.evict_lru(1) == [cold[1]]
        assert idx.evict_lru(2) == [warm[1], warm[0]]
        assert [n.page for n in idx._nodes] == [cold[0]]
        assert pool.free_pages() == (8 - 1) - 1

    def test_adopted_pages_are_not_evictable(self):
        pool, idx = _mk_index(num_pages=8)
        pages = pool.alloc(2)
        idx.insert(list(range(8)), pages)
        # "live request" keeps its own ref on page 0
        pool.decref(pages[1])
        assert idx.evict_lru(5) == [pages[1]]
        assert pool.refcount(pages[0]) == 2   # untouched


# ---------------------------------------------------------------------------
# manager: admission math
# ---------------------------------------------------------------------------

class TestManagerAdmission:
    def test_disabled_charges_full_worst_case(self):
        kv = KVCacheManager(9, PAGE, enabled=False)
        assert kv.index is None
        adm = kv.admit(np.arange(10), 6)      # ceil(16/8) = 2 pages
        assert adm.charge == 2 and adm.matched_len == 0
        assert kv.budget_avail == 6

    def test_enabled_charges_suffix_only(self):
        kv = KVCacheManager(17, PAGE, enabled=True)
        toks = list(range(20))
        pages = kv.alloc(3)
        kv.insert(toks, pages)
        kv.free_owned(pages)                  # index-only now
        # same prompt +4 new tokens: 2 full pages shared, tail forked
        adm = kv.admit(toks + [77, 78], 10)   # full = ceil(32/8) = 4
        assert adm.matched_len == 20
        assert adm.charge == 4 - 2            # suffix pages only
        # 2 shared pins + 1 transient tail pin + charge 2
        assert kv.budget_avail == 16 - 2 - 3
        kv.release_transient(adm)
        assert kv.budget_avail == 16 - 2 - 2
        kv.cancel(adm)
        assert kv.budget_avail == 16

    def test_fully_cached_prompt_leaves_one_suffix_token(self):
        kv = KVCacheManager(17, PAGE, enabled=True)
        toks = list(range(16))                # exactly 2 full pages
        pages = kv.alloc(2)
        kv.insert(toks, pages)
        kv.free_owned(pages)
        adm = kv.admit(toks, 4)
        assert adm.matched_len == 15          # >= 1 token must prefill
        assert adm.tail_src == pages[1] and adm.tail_len == PAGE - 1
        assert adm.shared_pages == pages[:1]
        kv.cancel(adm)


# ---------------------------------------------------------------------------
# engine parity: the acceptance matrix
# ---------------------------------------------------------------------------

class TestEngineParity:
    @pytest.mark.parametrize("depth", [1, 2])
    def test_disjoint_prompts(self, model, depth):
        """(a) no shareable prefixes: the cache must be a pure
        pass-through (all misses, zero tokens saved, exact outputs)."""
        rs = np.random.RandomState(3)
        prompts = [rs.randint(0, 250, rs.randint(2, 20)).astype(np.int32)
                   for _ in range(5)]
        lens = [3, 5, 2, 4, 3]
        want = [_generate(model, p, n) for p, n in zip(prompts, lens)]
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, kvcache=True,
                        pipeline_depth=depth).start()
        try:
            got = [r.get(timeout=300) for r in
                   [srv.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, lens)]]
        finally:
            srv.stop()
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        assert srv.prefix_tokens_saved == 0

    @pytest.mark.parametrize("depth", [1, 2])
    def test_shared_prefix_divergent_tails(self, model, depth):
        """(b) one system prompt, divergent user tails: later requests
        reuse the shared pages (COW fork on the partial tail) and stay
        token-identical to the cache-off engine."""
        rs = np.random.RandomState(11)
        shared = rs.randint(0, 250, 20).astype(np.int32)  # 2.5 pages
        prompts = [np.concatenate([shared,
                                   rs.randint(0, 250, 1 + j)
                                   .astype(np.int32)])
                   for j in range(4)]
        want = [_generate(model, p, 4) for p in prompts]
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, kvcache=True,
                        pipeline_depth=depth).start()
        try:
            got = [r.get(timeout=300) for r in
                   [srv.submit(p, max_new_tokens=4) for p in prompts]]
        finally:
            srv.stop()
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        assert srv._kv.hits > 0
        assert srv.prefix_tokens_saved >= 16   # >= the 2 full pages
        # all grants returned: budget whole, nothing pinned
        st = srv._kv.debug_stats()
        assert st["pages_pinned"] == 0
        assert st["budget_avail"] == srv._num_pages - 1

    @pytest.mark.parametrize("depth", [1, 2])
    def test_lru_eviction_hammer_mid_stream(self, model, depth):
        """(c) a pool too small to keep every chain warm: admission and
        decode must LRU-evict mid-stream and still produce exact greedy
        output for every request."""
        rs = np.random.RandomState(23)
        shared = rs.randint(0, 250, 12).astype(np.int32)
        prompts = []
        for j in range(10):
            tail = rs.randint(0, 250, rs.randint(1, 14)).astype(np.int32)
            base = shared if j % 2 == 0 else \
                rs.randint(0, 250, 12).astype(np.int32)
            prompts.append(np.concatenate([base, tail]))
        lens = [int(rs.randint(1, 6)) for _ in prompts]
        want = [_generate(model, p, n) for p, n in zip(prompts, lens)]
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, num_pages=11, kvcache=True,
                        pipeline_depth=depth).start()
        try:
            got = [r.get(timeout=600) for r in
                   [srv.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, lens)]]
        finally:
            srv.stop()
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        assert srv._kv.evictions > 0           # pressure actually hit

    def test_eviction_hammer_with_host_tier(self, model):
        """ISSUE 6: the hammer workload again, but with the host tier
        armed — evicted chains spill instead of dropping, re-requests
        re-adopt them FROM THE ARENA (fetches > 0), outputs stay exact,
        and the pool's refcount/pin/budget ledger survives the
        migrations. (Depth 1/2 spill-reload parity lives in
        tests/test_kvtier.py; this ties the tier into the kvcache
        suite's own acceptance matrix.)"""
        from bigdl_tpu.utils.conf import conf
        rs = np.random.RandomState(23)
        groups = [rs.randint(0, 250, 16).astype(np.int32)
                  for _ in range(4)]
        prompts = [np.concatenate(
            [groups[j % 4], rs.randint(0, 250, 1 + j % 4)
             .astype(np.int32)]) for j in range(8)]
        lens = [int(rs.randint(1, 5)) for _ in prompts]
        want = [_generate(model, p, n) for p, n in zip(prompts, lens)]
        conf.set("bigdl.llm.kvtier.sync", "true")
        try:
            srv = LLMServer(model, max_batch=2, max_seq_len=64,
                            page_size=PAGE, num_pages=9, kvcache=True,
                            kvtier=True, host_pages=32).start()
            try:
                got = [r.get(timeout=600) for r in
                       [srv.submit(p, max_new_tokens=n)
                        for p, n in zip(prompts, lens)]]
                spills, fetches = srv._tier.spills, srv._tier.fetches
                st = srv._kv.debug_stats()
            finally:
                srv.stop()
        finally:
            conf.unset("bigdl.llm.kvtier.sync")
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        assert spills > 0 and fetches > 0
        assert st["pages_pinned"] == 0
        assert st["budget_avail"] == 9 - 1
        assert st["tier"]["pinned"] == 0

    @pytest.mark.parametrize("family", ["gptneox", "starcoder"])
    def test_non_llama_families_share_prefixes(self, family):
        """Every paged family has a partial-prefill entry point: the
        facade families reuse shared prefixes with exact greedy
        parity too."""
        if family == "gptneox":
            from bigdl_tpu.llm.models.gptneox import (GptNeoXConfig as C,
                                                      GptNeoXForCausalLM
                                                      as M)
        else:
            from bigdl_tpu.llm.models.starcoder import (
                StarCoderConfig as C, StarCoderForCausalLM as M)
        fam_model = M.from_config(C.tiny(), seed=0, max_cache_len=64)
        rs = np.random.RandomState(1)
        shared = rs.randint(0, 250, 20).astype(np.int32)
        prompts = [np.concatenate([shared,
                                   rs.randint(0, 250, 3)
                                   .astype(np.int32)])
                   for _ in range(3)]
        want = [_generate(fam_model, p, 4) for p in prompts]
        srv = LLMServer(fam_model, max_batch=2, max_seq_len=48,
                        page_size=PAGE, kvcache=True).start()
        try:
            got = [srv.submit(p, max_new_tokens=4).get(timeout=300)
                   for p in prompts]
        finally:
            srv.stop()
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        assert srv._kv.hits > 0 and srv.prefix_tokens_saved > 0

    def test_multi_turn_chain_stays_warm(self, model):
        """EOS keeps prompt+output indexed: a follow-up whose prompt
        extends the previous conversation reuses those pages."""
        p1 = np.arange(1, 19, dtype=np.int32)          # 18 tokens
        out1 = _generate(model, p1, 6)
        p2 = np.concatenate([p1, out1.astype(np.int32),
                             np.array([9, 7], np.int32)])
        want2 = _generate(model, p2, 4)
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, kvcache=True).start()
        try:
            g1 = srv.submit(p1, max_new_tokens=6).get(timeout=300)
            saved0 = srv.prefix_tokens_saved
            g2 = srv.submit(p2, max_new_tokens=4).get(timeout=300)
        finally:
            srv.stop()
        np.testing.assert_array_equal(np.asarray(g1), out1)
        np.testing.assert_array_equal(np.asarray(g2), want2)
        # the whole first turn (prompt + generated, 24 tokens = 3 full
        # pages at least) came from the cache
        assert srv.prefix_tokens_saved - saved0 >= 3 * PAGE


# ---------------------------------------------------------------------------
# disabled mode: structurally absent
# ---------------------------------------------------------------------------

class TestDisabledMode:
    def test_no_index_no_series_same_pool_order(self, model):
        from bigdl_tpu import observability as obs
        before = len(obs.REGISTRY.collect())
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        page_size=PAGE)
        assert srv._kv.index is None
        assert srv._kv.enabled is False
        # seed free-list order preserved exactly
        assert srv._free == list(range(srv._num_pages - 1, 0, -1))
        req = srv.submit(np.array([3, 1, 4], np.int32), max_new_tokens=3)
        while not req.done.is_set():
            srv._admit()
            srv._step()
        # no new series minted, no lazily-declared kvcache instruments,
        # zero cache activity (the registry is process-global, so the
        # check is a delta — other tests may have enabled the cache)
        assert len(obs.REGISTRY.collect()) == before
        assert srv._kv._ins is None
        assert srv._kv.hits == srv._kv.misses == 0
        assert srv.prefix_tokens_saved == 0

    def test_enabled_declares_series(self, model):
        from bigdl_tpu import observability as obs
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        page_size=PAGE, kvcache=True)
        req = srv.submit(np.array([3, 1, 4], np.int32), max_new_tokens=3)
        while not req.done.is_set():
            srv._admit()
            srv._step()
        text = obs.render()
        for name in ("bigdl_kvcache_hits_total",
                     "bigdl_kvcache_misses_total",
                     "bigdl_kvcache_evictions_total",
                     "bigdl_kvcache_pool_occupancy"):
            assert name in text


# ---------------------------------------------------------------------------
# shed diagnostics (ISSUE 5 satellite) + debug endpoint
# ---------------------------------------------------------------------------

def _get(addr, path):
    conn = http.client.HTTPConnection(*addr, timeout=10)
    conn.request("GET", path)
    r = conn.getresponse()
    body = json.loads(r.read().decode())
    conn.close()
    return r.status, body


class TestHttpSurface:
    def test_queue_full_shed_reports_suffix_pages(self, model):
        from bigdl_tpu import reliability
        srv = LLMServer(model, max_batch=1, max_seq_len=32,
                        page_size=PAGE, max_queue=1, kvcache=True)
        srv.submit(np.arange(1, 11, dtype=np.int32), max_new_tokens=2)
        with pytest.raises(reliability.OverloadError,
                           match="queue full") as ei:
            srv.submit(np.arange(1, 11, dtype=np.int32),
                       max_new_tokens=2)
        # post-lookup suffix cost rides the exception for the worker's
        # Retry-After diagnostics
        assert ei.value.pages_needed == 2     # ceil(12/8), nothing cached
        assert ei.value.pages_free == srv._num_pages - 1
        assert "pages" in str(ei.value)

    def test_impossible_request_rejected_on_suffix_cost(self, model):
        srv = LLMServer(model, max_batch=1, max_seq_len=64,
                        page_size=PAGE, num_pages=3, kvcache=True)
        with pytest.raises(ValueError, match="uncached suffix"):
            srv.submit(np.arange(40, dtype=np.int32), max_new_tokens=8)

    def test_debug_kvcache_endpoint(self, model):
        from bigdl_tpu.llm.worker import LLMWorker
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        page_size=PAGE, kvcache=True).start()
        worker = LLMWorker(srv).start()
        try:
            status, body = _get(worker.address, "/debug/kvcache")
            assert status == 200
            assert body["enabled"] is True
            assert body["page_size"] == PAGE
            assert {"hits", "misses", "evictions", "index"} <= set(body)
        finally:
            worker.stop()
            srv.stop()

    def test_debug_kvcache_404_when_disabled(self, model):
        from bigdl_tpu.llm.worker import LLMWorker
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        page_size=PAGE).start()
        worker = LLMWorker(srv).start()
        try:
            status, _ = _get(worker.address, "/debug/kvcache")
            assert status == 404
        finally:
            worker.stop()
            srv.stop()


# ---------------------------------------------------------------------------
# prefix microbench (bench.py telemetry embed)
# ---------------------------------------------------------------------------

class TestPrefixMicrobench:
    @pytest.mark.perf
    def test_microbench_reports_savings(self, model):
        """tools/microbench_prefix.py end-to-end on the tiny model: the
        cache-on pass must save prefill tokens and report both TTFT
        numbers (latency values advisory — shared CI hosts)."""
        from tools.microbench_prefix import run_prefix_bench

        out = run_prefix_bench(n_requests=3, shared_len=24, tail_len=4,
                               new_tokens=3, page_size=8, model=model)
        assert out["prefill_tokens_saved"] > 0
        assert out["cache_on"]["prefill_tokens"] \
            < out["cache_off"]["prefill_tokens"]
        assert out["cache_off"]["ttft_ms"] > 0
        assert out["cache_on"]["ttft_ms"] > 0
        assert out["cache_on"]["hits"] > 0


# ---------------------------------------------------------------------------
# seeded eviction faults (chaos satellite, fast smoke)
# ---------------------------------------------------------------------------

class TestEvictionFaults:
    def test_injected_evict_faults_keep_greedy_parity(self, model):
        """kvcache.evict delays AND raises under pool pressure: raises
        surface before any state mutates, the engine loop retries, and
        every output still matches generate()."""
        from bigdl_tpu import reliability as rel
        rs = np.random.RandomState(5)
        shared = rs.randint(0, 250, 10).astype(np.int32)
        prompts = [np.concatenate([shared, rs.randint(0, 250, 2 + j)
                                   .astype(np.int32)]) for j in range(6)]
        want = [_generate(model, p, 4) for p in prompts]
        plan = rel.FaultPlan(seed=1)
        # first-match-wins: bounded raises first, delays on other passes
        plan.add("kvcache.evict", "raise", times=2, after=1)
        plan.add("kvcache.evict", "delay", times=None, delay=0.002)
        rel.set_plan(plan)
        try:
            srv = LLMServer(model, max_batch=2, max_seq_len=64,
                            page_size=PAGE, num_pages=7,
                            kvcache=True).start()
            try:
                # sequential: every request's chain lands before the
                # next admission, so warm chains reliably fill the tiny
                # pool and most admissions must reclaim — the fault
                # site fires on a deterministic-enough cadence for both
                # rules to trigger regardless of engine-thread timing
                got = [srv.submit(p, max_new_tokens=4).get(timeout=300)
                       for p in prompts]
            finally:
                srv.stop()
        finally:
            rel.set_plan(None)
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        assert ("kvcache.evict", "delay") in plan.fired
        # the raise path (retried admission/step) was exercised too
        assert ("kvcache.evict", "raise") in plan.fired

"""ISSUE 16: engine flight recorder + live roofline attribution —
ring semantics, typed decision events recorded at real engine decision
points, the trace-stitched ``/debug/explain/<request_id>`` timeline and
its one-line verdicts, the filterable ``/debug/flight`` ring surface,
the flight-gated utilization sampler (``bigdl_device_mfu`` /
``bigdl_device_hbm_bw_gbps`` / ``bigdl_device_bw_util`` + the roofline
table), and the disabled-mode structural-absence contract for
``bigdl.observability.flight.enabled``."""

import http.client
import json
import sys

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu.observability import compile_recorder, flight, utilization
from bigdl_tpu.observability import request_context as rc
from bigdl_tpu.utils.conf import conf

GATE = "bigdl.observability.flight.enabled"


@pytest.fixture(autouse=True)
def _flight_clean():
    """Observability on, the flight gate at its default (OFF), and an
    empty ring/sampler around every test; tests opt in via
    ``conf.set(GATE, "true")``. The global registry is NOT cleared (live
    modules hold instrument refs) — absence tests read render deltas."""
    was = obs.enabled()
    obs.enable()
    flight.reset()
    utilization.reset()
    yield
    for key in (GATE, "bigdl.observability.flight.capacity",
                "bigdl.device.peak.tflops", "bigdl.device.peak.gbps"):
        conf.unset(key)
    flight.reset()
    utilization.reset()
    if was:
        obs.enable()
    else:
        obs.disable()


def _get(addr, path):
    conn = http.client.HTTPConnection(*addr, timeout=120)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, json.loads(r.read().decode())
    finally:
        conn.close()


class TestFlightRing:
    def test_bounded_oldest_dropped(self):
        r = flight.FlightRing(4)
        for i in range(7):
            r.append({"seq": i, "kind": "queue"})
        assert [e["seq"] for e in r.events()] == [3, 4, 5, 6]
        assert r.dropped == 3 and len(r) == 4

    def test_filters_and_limit(self):
        r = flight.FlightRing(16)
        for i in range(6):
            r.append({"seq": i, "kind": "queue" if i % 2 else "admit",
                      "request": f"r{i % 3}"})
        assert all(e["kind"] == "queue" for e in r.events(kind="queue"))
        assert [e["seq"] for e in r.events(request_id="r1")] == [1, 4]
        assert [e["seq"] for e in r.events(limit=2)] == [4, 5]

    def test_set_capacity_keeps_newest(self):
        r = flight.FlightRing(8)
        for i in range(8):
            r.append({"seq": i, "kind": "queue"})
        r.set_capacity(3)
        assert [e["seq"] for e in r.events()] == [5, 6, 7]
        r.append({"seq": 8, "kind": "queue"})
        assert [e["seq"] for e in r.events()] == [6, 7, 8]


class TestGateStructuralAbsence:
    def test_default_off_record_is_noop_zero_registry_delta(self):
        assert conf.get_bool(GATE, False) is False
        assert flight.enabled is False
        lines = set(obs.render().splitlines())
        flight.record("shed", request_id="r1", component="x")
        flight.record("evict", pages=3)
        assert flight.ring() is None          # never constructed
        assert set(obs.render().splitlines()) == lines

    def test_endpoints_404_when_off(self):
        for path in ("/debug/flight", "/debug/explain/r1"):
            resp = flight.debug_endpoint(path)
            assert resp is not None and resp[0] == 404, \
                f"{path} must 404 while {GATE} is off"
        # unowned paths fall through to the next helper
        assert flight.debug_endpoint("/debug/traces") is None
        assert flight.debug_endpoint("/healthz") is None

    def test_runtime_toggle(self):
        conf.set(GATE, "true")
        assert flight.enabled
        flight.record("queue", request_id="r1")
        assert len(flight.ring()) == 1
        conf.set(GATE, "false")
        assert not flight.enabled
        flight.record("queue", request_id="r2")
        assert len(flight.ring()) == 1        # kept, not grown

    def test_capacity_conf_pokes_live_ring(self):
        conf.set(GATE, "true")
        for i in range(8):
            flight.record("queue", request_id=f"r{i}")
        conf.set("bigdl.observability.flight.capacity", "4")
        assert flight.ring().capacity == 4
        assert len(flight.ring()) == 4


class TestRecordExplain:
    def test_ambient_trace_detail_filter_and_counter(self):
        conf.set(GATE, "true")
        before = obs.REGISTRY.sample_value("bigdl_flight_events_total",
                                           kind="admit") or 0
        ctx = rc.new_trace()
        with rc.activate(ctx):
            flight.record("admit", request_id="req-1", slot=0,
                          matched_tokens=None)
        (ev,) = flight.ring().events()
        assert ev["trace"] == ctx.trace_id    # picked up from context
        assert ev["detail"] == {"slot": 0}    # None-valued keys dropped
        assert obs.REGISTRY.sample_value("bigdl_flight_events_total",
                                         kind="admit") == before + 1

    def test_explain_stitches_trace_and_orders_causally(self):
        """Acceptance: a request hitting radix miss + tier fetches +
        chunked admission + a mid-stream failover resume (recorded by
        the router under its own local id but the same trace) yields
        one causally ordered timeline and the composite verdict."""
        conf.set(GATE, "true")
        tid = "ab" * 16
        flight.record("queue", request_id="w-req", trace_id=tid,
                      prompt_tokens=96)
        flight.record("radix_miss", request_id="w-req", trace_id=tid,
                      prompt_tokens=96)
        flight.record("park", request_id="w-req", trace_id=tid, pages=3)
        flight.record("fetch", request_id="w-req", trace_id=tid,
                      pages=2, wait_ms=21.0, status="landed")
        flight.record("fetch", request_id="w-req", trace_id=tid,
                      pages=1, wait_ms=20.0, status="landed")
        flight.record("admit", request_id="w-req", trace_id=tid,
                      chunked=True)
        for c in (32, 32, 32):
            flight.record("chunk_charge", request_id="w-req",
                          trace_id=tid, chunk_tokens=c)
        flight.record("failover", request_id="router-7", trace_id=tid,
                      tokens_resumed=2, attempt=2)
        flight.record("finish", request_id="w-req", trace_id=tid,
                      tokens=8, ttft_ms=700.0)
        doc = flight.explain("w-req")
        assert doc["traces"] == [tid]
        seqs = [e["seq"] for e in doc["events"]]
        assert seqs == sorted(seqs)                   # causal order
        assert any(e.get("request") == "router-7"
                   for e in doc["events"])            # trace-stitched
        v = doc["verdict"]
        assert v.startswith("slow TTFT")              # 700 > 500 default
        assert "radix miss" in v
        assert "2 tier fetches parked 41 ms" in v
        assert "chunked admission, 3 chunks" in v
        assert "1 mid-stream failover resume" in v
        assert "TTFT 700 ms" in v

    def test_shed_verdict_and_ok_verdict(self):
        conf.set(GATE, "true")
        flight.record("shed", request_id="s1", component="llm_server",
                      reason="queue_full")
        assert flight.explain("s1")["verdict"] == "shed: queue_full"
        flight.record("radix_hit", request_id="h1", matched_tokens=64)
        flight.record("finish", request_id="h1", tokens=4, ttft_ms=12.0)
        v = flight.explain("h1")["verdict"]
        assert v.startswith("ok") and "radix hit (64 tokens reused)" in v

    def test_debug_flight_filters(self):
        conf.set(GATE, "true")
        for i in range(5):
            flight.record("queue" if i % 2 else "evict",
                          request_id=f"r{i}", pages=i)
        st, doc = flight.debug_endpoint("/debug/flight?kind=evict")
        assert st == 200 and doc["kinds"] == ["evict"]
        st, doc = flight.debug_endpoint("/debug/flight?request=r1")
        assert st == 200
        assert all(e["request"] == "r1" for e in doc["events"])
        st, doc = flight.debug_endpoint("/debug/flight?limit=2")
        assert st == 200 and len(doc["events"]) == 2

    def test_explain_unknown_request_404s(self):
        conf.set(GATE, "true")
        flight.record("queue", request_id="known")
        st, body = flight.debug_endpoint("/debug/explain/unknown")
        assert st == 404 and "unknown" in body["error"]


class TestServingEmission:
    def test_engine_decision_points_and_http_surfaces(self):
        """Live engine: a cold and then a warm admission through the
        prefix cache emit queue/admit/radix_miss/radix_hit/finish at
        the real decision points; the worker serves /debug/flight and
        /debug/explain over HTTP, and flipping the gate off turns both
        into 404s without restarting anything."""
        from bigdl_tpu.llm.models.llama import (LlamaConfig,
                                                LlamaForCausalLM)
        from bigdl_tpu.llm.serving import LLMServer
        from bigdl_tpu.llm.worker import LLMWorker

        conf.set(GATE, "true")
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                             max_cache_len=64)
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                        kvcache=True).start()
        worker = LLMWorker(srv).start()
        try:
            prompt = np.arange(1, 13, dtype=np.int32)
            r1 = srv.submit(prompt, max_new_tokens=3)
            r1.get(timeout=300)
            r2 = srv.submit(prompt, max_new_tokens=3)
            r2.get(timeout=300)
            kinds1 = {e["kind"]
                      for e in flight.ring().events(request_id=r1.id)}
            assert {"queue", "admit", "radix_miss", "finish"} <= kinds1
            kinds2 = {e["kind"]
                      for e in flight.ring().events(request_id=r2.id)}
            assert "radix_hit" in kinds2
            st, doc = _get(worker.address,
                           f"/debug/explain/{r2.id}")
            assert st == 200
            assert "radix hit" in doc["verdict"]
            assert "TTFT" in doc["verdict"]   # finish stamped ttft_ms
            st, ring_doc = _get(worker.address, "/debug/flight?kind=queue")
            assert st == 200 and ring_doc["kinds"] == ["queue"]
            # runtime off: same process, endpoints now 404
            conf.set(GATE, "false")
            st, _ = _get(worker.address, "/debug/flight")
            assert st == 404
            st, _ = _get(worker.address, f"/debug/explain/{r2.id}")
            assert st == 404
        finally:
            worker.stop()
            srv.stop(drain=False)


class TestUtilization:
    def test_window_math_gauges_and_roofline(self, monkeypatch):
        conf.set(GATE, "true")
        conf.set("bigdl.device.peak.tflops", "100")
        conf.set("bigdl.device.peak.gbps", "800")
        monkeypatch.setattr(compile_recorder, "latest_costs",
                            lambda: {"llm/decode_paged": (2e9, 4e8)})
        for _ in range(10):
            utilization.observe("llm/decode_paged", 0.001)
        snap = utilization.snapshot()
        assert snap["samples"] == 10
        assert snap["peak_tflops"] == 100.0
        assert snap["peak_gbps"] == 800.0
        # 4e8 bytes / 1e-3 s = 400 GB/s; mfu = 2e12/1e14; bw 400/800
        assert snap["hbm_bw_gbps"] == pytest.approx(400.0)
        assert snap["mfu"] == pytest.approx(0.02)
        assert snap["bw_util"] == pytest.approx(0.5)
        (row,) = snap["programs"]
        assert row["fn"] == "llm/decode_paged" and row["calls"] == 10
        # 5 flops/byte << the 125 flops/byte machine balance
        assert row["bound"] == "memory"
        assert obs.REGISTRY.sample_value("bigdl_device_hbm_bw_gbps") \
            == pytest.approx(400.0)
        assert obs.REGISTRY.sample_value("bigdl_device_mfu") \
            == pytest.approx(0.02)
        assert obs.REGISTRY.sample_value("bigdl_device_bw_util") \
            == pytest.approx(0.5)

    def test_compute_bound_classification(self, monkeypatch):
        conf.set(GATE, "true")
        conf.set("bigdl.device.peak.tflops", "100")
        conf.set("bigdl.device.peak.gbps", "800")
        # 2000 flops/byte >> 125: sits on the compute side
        monkeypatch.setattr(compile_recorder, "latest_costs",
                            lambda: {"llm/step_mixed": (2e12, 1e9)})
        utilization.observe("llm/step_mixed", 0.1)
        (row,) = utilization.roofline_table()
        assert row["bound"] == "compute"

    def test_unattributable_programs_excluded_from_window(
            self, monkeypatch):
        conf.set(GATE, "true")
        conf.set("bigdl.device.peak.gbps", "800")
        monkeypatch.setattr(compile_recorder, "latest_costs",
                            lambda: {"known": (0.0, 4e8)})
        utilization.observe("known", 0.001)
        utilization.observe("mystery", 10.0)  # no costs: not in ratio
        assert obs.REGISTRY.sample_value("bigdl_device_hbm_bw_gbps") \
            == pytest.approx(400.0)

    def test_gated_off_structurally_absent(self):
        assert not flight.enabled
        lines = set(obs.render().splitlines())
        utilization.observe("llm/decode_paged", 0.01)
        snap = utilization.snapshot()
        assert snap["samples"] == 0 and snap["programs"] == []
        assert "mfu" not in snap and "bw_util" not in snap
        assert set(obs.render().splitlines()) == lines

    def test_peaks_conf_override_and_unknown_platform(self):
        # CPU backend, no override: both axes unknown, gauges suppressed
        assert utilization.peaks() == (None, None)
        conf.set("bigdl.device.peak.tflops", "197")
        conf.set("bigdl.device.peak.gbps", "819")
        assert utilization.peaks() == (197e12, 819.0)

    def test_peak_flops_table_mirrors_bench(self):
        import bench
        bench_table = dict(bench._PEAK_BF16_FLOPS)
        for key, tflops, _gbps in utilization.PEAK_SPECS:
            assert bench_table.get(key) == pytest.approx(tflops * 1e12), \
                f"PEAK_SPECS[{key}] drifted from bench._PEAK_BF16_FLOPS"


class TestExplainTools:
    def _seed_events(self):
        tid = "cd" * 16
        flight.record("queue", request_id="w-1", trace_id=tid)
        flight.record("radix_miss", request_id="w-1", trace_id=tid)
        flight.record("failover", request_id="router-2", trace_id=tid,
                      tokens_resumed=1)
        flight.record("finish", request_id="w-1", trace_id=tid,
                      tokens=4, ttft_ms=40.0)

    def test_summarize_explain_from_ring_dump(self, tmp_path):
        conf.set(GATE, "true")
        self._seed_events()
        st, ring_doc = flight.debug_endpoint("/debug/flight")
        assert st == 200
        path = tmp_path / "flight.json"
        path.write_text(json.dumps(ring_doc))
        sys.path.insert(0, "tools")
        try:
            from telemetry_report import summarize_explain
        finally:
            sys.path.pop(0)
        out = summarize_explain("w-1", str(path))
        assert out["request"] == "w-1"
        assert any(e.get("request") == "router-2"
                   for e in out["events"])           # stitched offline too
        assert "failover" in out["verdict"]

    def test_summarize_explain_live_ring(self):
        conf.set(GATE, "true")
        self._seed_events()
        sys.path.insert(0, "tools")
        try:
            from telemetry_report import summarize_explain
        finally:
            sys.path.pop(0)
        out = summarize_explain("w-1")
        assert out["verdict"] == flight.explain("w-1")["verdict"]

    def test_explain_report_renders_timeline_and_roofline(
            self, capsys, monkeypatch):
        conf.set(GATE, "true")
        conf.set("bigdl.device.peak.tflops", "100")
        conf.set("bigdl.device.peak.gbps", "800")
        monkeypatch.setattr(compile_recorder, "latest_costs",
                            lambda: {"llm/decode_paged": (2e9, 4e8)})
        utilization.observe("llm/decode_paged", 0.001)
        self._seed_events()
        sys.path.insert(0, "tools")
        try:
            from explain_report import render
        finally:
            sys.path.pop(0)
        render(flight.explain("w-1"), roof=utilization.snapshot())
        text = capsys.readouterr().out
        assert "flight timeline: request w-1" in text
        assert "verdict:" in text
        assert "llm/decode_paged" in text and "roofline" in text


class TestFederationSnapshotRoofline:
    def test_roofline_rides_snapshot_only_when_sampled(self, monkeypatch):
        from bigdl_tpu.observability.federation import registry_snapshot
        doc = registry_snapshot(instance="w0")
        assert "roofline" not in doc          # gate off: no key at all
        conf.set(GATE, "true")
        monkeypatch.setattr(compile_recorder, "latest_costs",
                            lambda: {"llm/decode_paged": (2e9, 4e8)})
        utilization.observe("llm/decode_paged", 0.001)
        doc = registry_snapshot(instance="w0")
        assert doc["roofline"]["programs"][0]["fn"] == "llm/decode_paged"

"""SLO-class priority scheduling + lossless preemption (ISSUE 17):
class-model units, class-ordered admission on the live engine, the
headline preempt→fence-release→resume parity run (pipeline depth 4,
parked kvtier fetch, shared radix prefix), and the disabled-mode
structural-absence contract for ``bigdl.llm.priority.enabled``.

Engine tests run the tier migrator in SYNCHRONOUS mode
(``bigdl.llm.kvtier.sync``) — a host-arena hit still parks the
admission in ``_fetch_wait`` for a pass (the job just lands inline),
so the parked-fetch path is exercised without racy sleeps."""

import threading

import numpy as np
import pytest

from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import (CLASS_RETRY_WEIGHTS, PRIORITY_CLASSES,
                                   LLMServer, _PriorityScheduler,
                                   normalize_priority)
from bigdl_tpu.utils.conf import conf

pytestmark = pytest.mark.priority

PAGE = 8


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=128)


@pytest.fixture()
def sync_tier():
    """Inline migration for deterministic, sleep-free engine tests."""
    conf.set("bigdl.llm.kvtier.sync", "true")
    yield
    conf.unset("bigdl.llm.kvtier.sync")


def _generate(model, p, n):
    return model.generate(np.asarray(p)[None], max_new_tokens=n)[0, len(p):]


class _Stub:
    """Minimal request stand-in for scheduler units (the scheduler only
    reads .priority/.done/.resume_ids)."""

    def __init__(self, priority, resumed=False):
        self.priority = priority
        self.done = threading.Event()
        self.resume_ids = np.zeros(1, np.int32) if resumed else None


# ---------------------------------------------------------------------------
# class model: normalization, retry weights, heap ordering
# ---------------------------------------------------------------------------

class TestClassModel:
    def test_normalize_degrades_never_fails(self):
        # header values are client-controlled: unknown/missing classes
        # must degrade to "standard", never raise
        assert normalize_priority(None) == "standard"
        assert normalize_priority("interactive") == "interactive"
        assert normalize_priority("  BATCH ") == "batch"
        assert normalize_priority("Standard") == "standard"
        assert normalize_priority("p99-or-bust") == "standard"
        assert normalize_priority(7) == "standard"

    def test_retry_weights_order_backoff_by_class(self):
        # batch clients must back off harder than interactive under the
        # same backlog (the class-weighted Retry-After satellite)
        assert (CLASS_RETRY_WEIGHTS["interactive"]
                < CLASS_RETRY_WEIGHTS["standard"]
                < CLASS_RETRY_WEIGHTS["batch"])
        assert set(CLASS_RETRY_WEIGHTS) == set(PRIORITY_CLASSES)

    def test_scheduler_class_order_fifo_within_class(self):
        sched = _PriorityScheduler()
        b1, i1, s1, i2 = (_Stub("batch"), _Stub("interactive"),
                          _Stub("standard"), _Stub("interactive"))
        for r in (b1, i1, s1, i2):
            sched.push(r)
        order = []
        while len(sched):
            order.append(sched.pop_entry()[2])
        assert order == [i1, i2, s1, b1]

    def test_scheduler_reparked_head_keeps_its_place(self):
        sched = _PriorityScheduler()
        a, b = _Stub("standard"), _Stub("standard")
        sched.push(a)
        sched.push(b)
        ent = sched.pop_entry()          # budget-blocked head...
        sched.push_entry(ent)            # ...re-parks at the FRONT
        assert sched.pop_entry()[2] is a
        assert sched.pop_entry()[2] is b

    def test_scheduler_depths_and_parked(self):
        sched = _PriorityScheduler()
        sched.push(_Stub("interactive"))
        sched.push(_Stub("batch"))
        victim = _Stub("batch", resumed=True)   # preempted, awaiting resume
        sched.push(victim)
        finished = _Stub("standard")
        finished.done.set()
        sched.push(finished)
        assert sched.depths() == {"interactive": 1, "standard": 0,
                                  "batch": 2}
        assert sched.parked() == 1
        assert sched.live() == 3
        assert sched.best_rank() == 0


# ---------------------------------------------------------------------------
# loadgen: --priority-mix plumbing (pure units)
# ---------------------------------------------------------------------------

class TestLoadgenMix:
    def test_parse_and_assign_deterministic(self):
        from tools.loadgen import assign_classes, parse_priority_mix

        mix = parse_priority_mix("interactive:1,batch:2")
        assert mix == [("interactive", 1), ("batch", 2)]
        classes = assign_classes(6, mix)
        assert classes == ["interactive", "batch", "batch"] * 2
        assert assign_classes(6, mix) == classes   # stable across calls

    def test_parse_rejects_bad_specs(self):
        from tools.loadgen import parse_priority_mix

        with pytest.raises(ValueError):
            parse_priority_mix("interactive:0,batch:0")
        with pytest.raises(ValueError):
            parse_priority_mix("warp-speed:1")
        with pytest.raises(ValueError):
            parse_priority_mix("")


# ---------------------------------------------------------------------------
# engine: class-ordered admission
# ---------------------------------------------------------------------------

class TestClassOrderedAdmission:
    def test_backlog_served_in_class_order(self, model):
        """One slot, one long-running interactive request, then a
        batch→standard→interactive backlog submitted in REVERSE class
        order: first-token stamps must come out interactive, standard,
        batch — the heap, not arrival order, decides."""
        rs = np.random.RandomState(3)
        prompts = [rs.randint(0, 250, 6 + j).astype(np.int32)
                   for j in range(4)]
        srv = LLMServer(model, max_batch=1, max_seq_len=64,
                        page_size=PAGE, num_pages=12, kvcache=True,
                        priority=True).start()
        try:
            # rank-0 occupant: never a preemption victim for a rank-0
            # waiter (preemption needs a strictly better class)
            head = srv.submit(prompts[0], max_new_tokens=24,
                              priority="interactive")
            while not head.tokens and not head.done.is_set():
                pass
            rb = srv.submit(prompts[1], max_new_tokens=2,
                            priority="batch")
            rstd = srv.submit(prompts[2], max_new_tokens=2)  # standard
            ri = srv.submit(prompts[3], max_new_tokens=2,
                            priority="interactive")
            for r in (head, rb, rstd, ri):
                r.get(timeout=600)
            assert srv.preemptions_total == 0
        finally:
            srv.stop()
        assert ri.t_first_token < rstd.t_first_token < rb.t_first_token


# ---------------------------------------------------------------------------
# engine: the headline lossless-preemption run
# ---------------------------------------------------------------------------

class TestPreemptResume:
    def test_preempt_resume_parity_pipeline4_parked_fetch(self, model,
                                                          sync_tier):
        """The ISSUE 17 acceptance run: pipeline depth 4, batch decodes
        whose shared radix prefix re-admits through a parked kvtier
        fetch, an interactive burst that preempts in-flight victims —
        every output (victims included) must match generate() exactly,
        every preemption must resume, and the page/pin ledgers and
        host arena must come back idle."""
        rs = np.random.RandomState(11)
        shared = rs.randint(0, 250, 16).astype(np.int32)
        batch_prompts = [np.concatenate(
            [shared, rs.randint(0, 250, 2 + j).astype(np.int32)])
            for j in range(3)]
        fills = [rs.randint(0, 250, 24).astype(np.int32)
                 for _ in range(3)]
        inter_prompts = [rs.randint(0, 250, 6 + j).astype(np.int32)
                         for j in range(2)]
        n_batch, n_inter = 20, 3
        want_b = [_generate(model, p, n_batch) for p in batch_prompts]
        want_i = [_generate(model, p, n_inter) for p in inter_prompts]
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, num_pages=12, kvcache=True,
                        kvtier=True, host_pages=64, pipeline_depth=4,
                        priority=True).start()
        try:
            # pass 1: seed the shared-prefix chains, then evict them to
            # the host arena with distinct fill chains — the storm's
            # batch admissions must come back through a tier fetch
            for j, p in enumerate(batch_prompts):
                got = srv.submit(p, max_new_tokens=2,
                                 priority="batch").get(timeout=600)
                np.testing.assert_array_equal(np.asarray(got),
                                              want_b[j][:2])
            for f in fills:
                srv.submit(f, max_new_tokens=2).get(timeout=600)
            # storm: saturate both slots with long batch decodes...
            rb = [srv.submit(p, max_new_tokens=n_batch, priority="BATCH")
                  for p in batch_prompts]   # header casing is client-set
            deadline = [r for r in rb]
            while sum(1 for r in deadline if r.tokens) < 2:
                if all(r.done.is_set() for r in deadline):
                    break
                pass
            # ...then burst interactive: no free slot, strictly better
            # class → lossless preemption of an in-flight batch decode
            ri = [srv.submit(p, max_new_tokens=n_inter,
                             priority="interactive")
                  for p in inter_prompts]
            got_b = [r.get(timeout=600) for r in rb]
            got_i = [r.get(timeout=600) for r in ri]
            preempts = srv.preemptions_total
            resumes = srv.preempt_resumes_total
            fetches = srv._tier.fetches
            inflight = srv._tier.migrator.inflight()
            parked = srv.preempt_parked
            depths = srv.class_depths()
            leftover = srv._parked
            st = srv._kv.debug_stats()
        finally:
            srv.stop()
        for j, (g, w) in enumerate(zip(got_b, want_b)):
            np.testing.assert_array_equal(
                np.asarray(g), w, err_msg=f"batch request {j} lost "
                "tokens across preemption (resume must be lossless)")
        for j, (g, w) in enumerate(zip(got_i, want_i)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"interactive {j}")
        assert preempts >= 1            # the storm really preempted
        assert resumes == preempts      # every victim resumed
        assert fetches > 0              # parked-fetch path exercised
        assert parked == 0 and not leftover
        assert inflight == 0
        assert depths == {c: 0 for c in PRIORITY_CLASSES}
        # ledger/arena idle: every grant returned, nothing pinned
        assert st["pages_pinned"] == 0
        assert st["budget_avail"] == 12 - 1
        assert st["tier"]["pinned"] == 0
        assert st["tier"]["fetch_failures"] == 0

    @pytest.mark.chaos
    @pytest.mark.slow
    def test_chaos_priority_storm_keeps_parity(self):
        """tools/chaos_check.py --preempt: a priority storm under step
        delays and an injected llm.preempt fault must stay bit-identical
        to FIFO, reconcile counters with flight events, and beat the
        FIFO baseline's worst-case interactive TTFT."""
        from tools.chaos_check import run_preempt_chaos

        out = run_preempt_chaos(seed=0, smoke=True)
        assert out["match"] and out["preemptions"] >= 1
        assert out["lost_requests"] == 0


# ---------------------------------------------------------------------------
# disabled mode: structurally absent
# ---------------------------------------------------------------------------

class TestDisabledMode:
    def test_off_is_structurally_absent(self, model):
        from bigdl_tpu import observability as obs

        # the gate defaults off (gatecheck absence-test contract)
        assert conf.get_bool("bigdl.llm.priority.enabled",
                             False) is False
        before = len(obs.REGISTRY.collect())
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, num_pages=12,
                        kvcache=True).start()
        try:
            # no scheduler, no parked-blob map, no class-key surfaces
            assert srv._sched is None
            assert srv._parked is None
            assert srv.class_depths() is None
            assert srv.preempt_parked == 0
            # priority hints are inert metadata, not a scheduler
            r1 = srv.submit(np.array([3, 1, 4, 1, 5], np.int32),
                            max_new_tokens=3, priority="interactive")
            r2 = srv.submit(np.array([2, 7, 1, 8], np.int32),
                            max_new_tokens=3, priority="batch")
            r1.get(timeout=600)
            r2.get(timeout=600)
            assert srv.preemptions_total == 0
            assert srv.preempt_resumes_total == 0
            # Retry-After depth is the plain intake depth — the class
            # weighting must not apply when the scheduler is off
            assert (srv.retry_depth("batch")
                    == srv.retry_depth("interactive")
                    == srv.retry_depth())
        finally:
            srv.stop()
        # a priority-off server must declare no new series (registry is
        # process-global, so structural absence is a DELTA)
        assert len(obs.REGISTRY.collect()) == before

    def test_priority_requires_paged(self, model):
        with pytest.raises(ValueError, match="page-pool"):
            LLMServer(model, max_batch=2, max_seq_len=32, paged=False,
                      priority=True)

"""ISSUE 18: the in-process time-series plane — windowed store ring
semantics (fake clocks throughout), reset-aware counter windows across
worker restarts, stale/departed federation members excluded from
merged windows, sketch-snapshot subtraction with alpha-mismatch
passthrough, the empty-window NaN contract, retention eviction, the
declarative alert engine's pending/firing/resolved state machine with
flight-event reconciliation, and the disabled-mode structural-absence
contract for ``bigdl.observability.timeseries.enabled``."""

import math
import threading

import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu.observability import alerts, flight
from bigdl_tpu.observability import timeseries as ts
from bigdl_tpu.observability.sketch import QuantileSketch
from bigdl_tpu.utils.conf import conf

pytestmark = pytest.mark.timeseries

GATE = "bigdl.observability.timeseries.enabled"


@pytest.fixture(autouse=True)
def _ts_clean():
    """Observability on, the time-series gate at its default (OFF),
    and no live store/engine around every test; tests opt in via
    ``conf.set(GATE, "true")``. The global registry is NOT cleared
    (live modules hold instrument refs) — absence tests read render
    deltas."""
    was = obs.enabled()
    obs.enable()
    ts.reset()
    alerts.reset()
    flight.reset()
    yield
    for key in (GATE, "bigdl.observability.timeseries.interval",
                "bigdl.observability.timeseries.retention",
                "bigdl.observability.timeseries.slo.window",
                "bigdl.observability.alerts.rules",
                "bigdl.observability.flight.enabled",
                "bigdl.slo.objective"):
        conf.unset(key)
    ts.reset()
    alerts.reset()
    flight.reset()
    if was:
        obs.enable()
    else:
        obs.disable()


def _doc(counters=None, gauges=None, sketches=None):
    """A minimal federation wire snapshot: unlabeled series only."""
    metrics = []
    for name, v in (counters or {}).items():
        metrics.append({"name": name, "kind": "counter", "help": "",
                        "labelnames": [],
                        "series": [{"labels": [], "value": float(v)}]})
    for name, v in (gauges or {}).items():
        metrics.append({"name": name, "kind": "gauge", "help": "",
                        "labelnames": [],
                        "series": [{"labels": [], "value": float(v)}]})
    for name, snap in (sketches or {}).items():
        metrics.append({"name": name, "kind": "summary", "help": "",
                        "labelnames": [],
                        "series": [{"labels": [], "sketch": snap}]})
    return {"instance": "synthetic", "ts": 0.0, "metrics": metrics}


class _StubCollector:
    """Quacks like the federation collector's scrape cache: the store
    only reads ``snapshots()``, ``stale_instances()`` and
    ``include_self``."""

    def __init__(self, include_self="m1"):
        self.include_self = include_self
        self.snaps = {}
        self.stale = set()

    def snapshots(self):
        return dict(self.snaps)

    def stale_instances(self):
        return set(self.stale)


def _member_store(retention=600.0):
    st = ts.TimeSeriesStore(interval=1.0, retention=retention,
                            clock=lambda: 0.0)
    coll = _StubCollector()
    st.attach_collector(coll)
    return st, coll


# ---------------------------------------------------------------------------
# pure window primitives
# ---------------------------------------------------------------------------

class TestPrimitives:
    def test_counter_delta_reset_aware(self):
        # 5->9 (+4), 9->2 (restart: +2), 2->4 (+2)
        assert ts.counter_delta([5.0, 9.0, 2.0, 4.0]) == 8.0

    def test_counter_delta_empty_window_is_nan_not_zero(self):
        assert math.isnan(ts.counter_delta([]))
        assert math.isnan(ts.counter_delta([7.0]))

    def test_counter_rate(self):
        assert ts.counter_rate([(0.0, 0.0), (10.0, 40.0)]) == 4.0
        assert math.isnan(ts.counter_rate([(5.0, 1.0)]))
        assert math.isnan(ts.counter_rate([(5.0, 1.0), (5.0, 2.0)]))

    def test_gauge_stats_empty_all_nan(self):
        stats = ts.gauge_stats([])
        assert all(math.isnan(v) for v in stats.values())
        stats = ts.gauge_stats([2.0, 8.0, 5.0])
        assert (stats["avg"], stats["min"], stats["max"],
                stats["last"]) == (5.0, 2.0, 8.0, 5.0)

    def test_histogram_delta_and_restart_passthrough(self):
        first = {"bounds": [1.0], "cum": [2], "sum": 3.0, "count": 4}
        last = {"bounds": [1.0], "cum": [5], "sum": 9.0, "count": 8}
        d = ts.histogram_delta(first, last)
        assert (d["count"], d["sum"]) == (4.0, 6.0)
        # count drop = restart: last passes through whole
        d = ts.histogram_delta(last, first)
        assert (d["count"], d["sum"]) == (4.0, 3.0)

    def test_windowed_counter_per_member_semantics(self):
        wc = ts.WindowedCounter()
        assert wc.observe({"a": 5.0, "b": 3.0}) == 0.0   # first sight
        assert wc.observe({"a": 7.0, "b": 3.0}) == 2.0
        # a restarts (7 -> 1): ITS post-reset value is the delta; b's
        # growth is not swallowed by any summed clamp
        assert wc.observe({"a": 1.0, "b": 6.0}) == 4.0
        # b departs: no contribution, no phantom negative
        assert wc.observe({"a": 1.0}) == 0.0
        # b rejoins: first sight again, delta 0
        assert wc.observe({"a": 1.0, "b": 9.0}) == 0.0


class TestSketchWindow:
    def _snap(self, values, alpha=0.01):
        sk = QuantileSketch(alpha=alpha)
        for v in values:
            sk.observe(v)
        return sk.to_snapshot()

    def test_window_isolates_between_samples(self):
        before = self._snap([0.1] * 50)
        sk = QuantileSketch.from_snapshot(before)
        for _ in range(50):
            sk.observe(5.0)
        win = ts.sketch_window(before, sk.to_snapshot(), qs=(0.5,))
        assert abs(win[0.5] - 5.0) / 5.0 < 0.05

    def test_alpha_mismatch_passes_after_through(self):
        before = self._snap([0.1] * 20, alpha=0.01)
        after = self._snap([0.1] * 20 + [9.0] * 20, alpha=0.02)
        d = ts.sketch_delta(before, after)
        assert d == dict(after)          # no lying subtraction
        win = ts.sketch_window(before, after, qs=(0.5,))
        assert win[0.5] is not None      # quantiles of after, whole

    def test_count_drop_passes_after_through(self):
        before = self._snap([1.0] * 30)
        after = self._snap([2.0] * 10)   # restarted: fewer samples
        assert ts.sketch_delta(before, after) == dict(after)

    def test_empty_delta_yields_none_quantiles(self):
        snap = self._snap([1.0] * 10)
        win = ts.sketch_window(snap, snap, qs=(0.5, 0.99))
        assert win == {0.5: None, 0.99: None}


# ---------------------------------------------------------------------------
# the windowed store (fake clocks; synthetic federation members)
# ---------------------------------------------------------------------------

class TestStoreWindows:
    def test_counter_reset_across_worker_restart(self):
        # the member's counter drops mid-window (worker restart): the
        # post-reset value is new increase, never a negative delta
        st, coll = _member_store()
        for now, val in ((0.0, 100.0), (10.0, 130.0), (20.0, 5.0),
                         (30.0, 12.0)):
            coll.snaps = {"m1": _doc(counters={"x_total": val})}
            st.sample_now(now=now)
        assert st.query("x_total", "delta", window=30.0,
                        instance="m1", now=30.0) == 30.0 + 5.0 + 7.0
        assert st.query("x_total", "rate", window=30.0, instance="m1",
                        now=30.0) == pytest.approx(42.0 / 30.0)

    def test_empty_window_is_nan_never_zero(self):
        st, coll = _member_store()
        assert math.isnan(st.query("x_total", "delta", window=60.0))
        coll.snaps = {"m1": _doc(counters={"x_total": 9.0})}
        st.sample_now(now=0.0)
        # one point is not a window
        assert math.isnan(st.query("x_total", "delta", window=60.0,
                                   instance="m1", now=0.0))
        st.sample_now(now=10.0)
        # a series the window never saw is NaN, not 0
        assert math.isnan(st.query("nope_total", "delta", window=60.0,
                                   instance="m1", now=10.0))
        assert math.isnan(st.query("nope_gauge", "avg", window=60.0,
                                   instance="m1", now=10.0))

    def test_retention_evicts_old_samples(self):
        st, coll = _member_store(retention=30.0)
        coll.snaps = {"m1": _doc(counters={"x_total": 1.0})}
        for now in (0.0, 10.0, 20.0, 40.0):
            st.sample_now(now=now)
        # floor = 40 - 30 = 10: the t=0 sample is gone, t=10 survives
        assert len(st) == 3
        assert st.evicted == 1
        assert st._window(None, 40.0)[0][0] == 10.0

    def test_stale_members_excluded_at_sample_time(self):
        st, coll = _member_store()
        coll.snaps = {"m1": _doc(counters={"x_total": 1.0}),
                      "m2": _doc(counters={"x_total": 100.0})}
        st.sample_now(now=0.0)
        assert st.instances(now=0.0) == ["m1", "m2"]
        coll.stale = {"m2"}          # m2's scrape failed: cached copy
        coll.snaps["m1"] = _doc(counters={"x_total": 4.0})
        st.sample_now(now=10.0)
        assert st.instances(now=10.0) == ["m1"]
        # merged window only aggregates live members
        assert st.query("x_total", "delta", window=10.0, instance="*",
                        now=10.0) == 3.0

    def test_departed_members_leave_merged_windows(self):
        st, coll = _member_store()
        coll.snaps = {"m1": _doc(counters={"x_total": 10.0}),
                      "m2": _doc(counters={"x_total": 50.0})}
        st.sample_now(now=0.0)
        coll.snaps = {"m1": _doc(counters={"x_total": 12.0}),
                      "m2": _doc(counters={"x_total": 55.0})}
        st.sample_now(now=10.0)
        del coll.snaps["m2"]         # m2 left the pool
        coll.snaps["m1"] = _doc(counters={"x_total": 15.0})
        st.sample_now(now=20.0)
        # membership = the window's most recent sample
        assert st.instances(now=20.0) == ["m1"]
        assert st.query("x_total", "delta", window=20.0, instance="*",
                        now=20.0) == 5.0
        tl = st.timeline("x_total", window=20.0, now=20.0)
        assert [p[1] for p in tl["instances"]["m2"]] == [50.0, 55.0]
        assert tl["merged"][-1] == [20.0, 15.0]

    def test_merged_delta_resets_per_member(self):
        # m1 restarts while m2 grows: per-member reset detection means
        # m2's growth survives (the summed-trace clamp would eat it)
        st, coll = _member_store()
        coll.snaps = {"m1": _doc(counters={"x_total": 90.0}),
                      "m2": _doc(counters={"x_total": 10.0})}
        st.sample_now(now=0.0)
        coll.snaps = {"m1": _doc(counters={"x_total": 2.0}),
                      "m2": _doc(counters={"x_total": 30.0})}
        st.sample_now(now=10.0)
        assert st.query("x_total", "delta", window=10.0, instance="*",
                        now=10.0) == 2.0 + 20.0

    def test_gauge_window_and_timeline(self):
        st, coll = _member_store()
        for now, v1, v2 in ((0.0, 2.0, 4.0), (10.0, 4.0, 4.0)):
            coll.snaps = {"m1": _doc(gauges={"g": v1}),
                          "m2": _doc(gauges={"g": v2})}
            st.sample_now(now=now)
        assert st.query("g", "max", window=10.0, instance="*",
                        now=10.0) == 8.0
        assert st.query("g", "avg", window=10.0, instance="*",
                        now=10.0) == 7.0
        assert st.query("g", "last", window=10.0, instance="m1",
                        now=10.0) == 4.0

    def test_merged_sketch_skips_alpha_mismatched_member(self):
        def snap(values, alpha):
            sk = QuantileSketch(alpha=alpha)
            for v in values:
                sk.observe(v)
            return sk.to_snapshot()

        st, coll = _member_store()
        coll.snaps = {"m1": _doc(sketches={"lat": snap([1.0], 0.01)}),
                      "m2": _doc(sketches={"lat": snap([9.0], 0.05)})}
        st.sample_now(now=0.0)
        coll.snaps = {
            "m1": _doc(sketches={"lat": snap([1.0] * 40, 0.01)}),
            "m2": _doc(sketches={"lat": snap([9.0] * 40, 0.05)})}
        st.sample_now(now=10.0)
        # merged p50 uses m1 + whichever mates merge cleanly; the
        # alpha-mismatched m2 is skipped instead of poisoning the merge
        val = st.query("lat", "p50", window=10.0, instance="*",
                       now=10.0)
        assert not math.isnan(val)
        assert abs(val - 1.0) < 0.5

    def test_parse_series(self):
        name, labels = ts.parse_series(
            'bigdl_slo_requests_total{slo="ttft",verdict="ok"}')
        assert name == "bigdl_slo_requests_total"
        assert labels == {"slo": "ttft", "verdict": "ok"}
        assert ts.parse_series("plain_total") == ("plain_total", {})
        with pytest.raises(ValueError):
            ts.parse_series("bad{unclosed")


# ---------------------------------------------------------------------------
# the alert engine (fake clock: evaluate(now) on manual store ticks)
# ---------------------------------------------------------------------------

class TestAlertEngine:
    def _slo_member(self, st, coll, now, ok, violated):
        coll.snaps = {"m1": _doc(counters={})}
        doc = _doc()
        doc["metrics"].append({
            "name": "bigdl_slo_requests_total", "kind": "counter",
            "help": "", "labelnames": ["slo", "verdict"],
            "series": [
                {"labels": ["ttft", "ok"], "value": float(ok)},
                {"labels": ["ttft", "violated"],
                 "value": float(violated)}]})
        coll.snaps = {"m1": doc}
        st.sample_now(now=now)

    def test_burn_rate_fires_and_resolves(self):
        st, coll = _member_store()
        eng = alerts.AlertEngine(st, rules=[
            {"name": "fb", "kind": "burn_rate", "slo": "ttft",
             "short": 10.0, "long": 20.0, "factor": 5.0,
             "objective": 0.99}])
        self._slo_member(st, coll, 0.0, ok=10, violated=0)
        eng.evaluate(0.0)
        assert eng.firing() == []
        # 10 violated of 12 total in both windows: burn = .833/.01 = 83
        self._slo_member(st, coll, 10.0, ok=12, violated=10)
        eng.evaluate(10.0)
        assert eng.firing() == ["fb"]
        # windows drain past the storm: resolve
        self._slo_member(st, coll, 50.0, ok=20, violated=10)
        self._slo_member(st, coll, 55.0, ok=25, violated=10)
        eng.evaluate(55.0)
        assert eng.firing() == []
        state = eng.status()["rules"][0]
        assert state["state"] == "resolved"
        assert state["fired_count"] == 1

    def test_burn_rate_needs_both_windows(self):
        # short window hot but long window cold: no page (the
        # multi-window guard against one bad scrape)
        st, coll = _member_store()
        eng = alerts.AlertEngine(st, rules=[
            {"name": "fb", "kind": "burn_rate", "slo": "ttft",
             "short": 10.0, "long": 100.0, "factor": 5.0,
             "objective": 0.9}])
        self._slo_member(st, coll, 0.0, ok=1000, violated=0)
        self._slo_member(st, coll, 95.0, ok=2000, violated=0)
        self._slo_member(st, coll, 100.0, ok=2000, violated=30)
        eng.evaluate(100.0)
        assert eng.firing() == []

    def test_threshold_pending_for_then_firing(self):
        st, coll = _member_store()
        eng = alerts.AlertEngine(st, rules=[
            {"name": "qh", "kind": "threshold", "series": "q",
             "fn": "last", "window": 30.0, "op": ">", "value": 5.0,
             "for": 10.0}])
        coll.snaps = {"m1": _doc(gauges={"q": 9.0})}
        st.sample_now(now=0.0)
        eng.evaluate(0.0)
        assert eng.status()["rules"][0]["state"] == "pending"
        st.sample_now(now=5.0)
        eng.evaluate(5.0)
        assert eng.firing() == []          # held, not yet past `for`
        st.sample_now(now=12.0)
        eng.evaluate(12.0)
        assert eng.firing() == ["qh"]
        coll.snaps = {"m1": _doc(gauges={"q": 0.0})}
        st.sample_now(now=20.0)
        eng.evaluate(20.0)
        assert eng.firing() == []

    def test_pending_cancelled_when_condition_clears(self):
        st, coll = _member_store()
        eng = alerts.AlertEngine(st, rules=[
            {"name": "qh", "kind": "threshold", "series": "q",
             "fn": "last", "window": 30.0, "op": ">", "value": 5.0,
             "for": 10.0}])
        coll.snaps = {"m1": _doc(gauges={"q": 9.0})}
        st.sample_now(now=0.0)
        eng.evaluate(0.0)
        coll.snaps = {"m1": _doc(gauges={"q": 1.0})}
        st.sample_now(now=5.0)
        eng.evaluate(5.0)
        assert eng.status()["rules"][0]["state"] == "inactive"
        assert eng.status()["rules"][0]["fired_count"] == 0

    def test_absence_rule_scrape_hole_is_not_absence(self):
        st, coll = _member_store()
        eng = alerts.AlertEngine(st, rules=[
            {"name": "ab", "kind": "absence", "series": "heartbeat",
             "window": 30.0, "instance": "m1"}])
        eng.evaluate(0.0)              # empty store: a scrape hole
        assert eng.firing() == []
        coll.snaps = {"m1": _doc(gauges={"other": 1.0})}
        st.sample_now(now=10.0)
        eng.evaluate(10.0)             # samples exist, series absent
        assert eng.firing() == ["ab"]
        coll.snaps = {"m1": _doc(gauges={"heartbeat": 1.0})}
        st.sample_now(now=20.0)
        eng.evaluate(20.0)
        assert eng.firing() == []

    def test_transitions_reconcile_with_flight_events(self):
        conf.set("bigdl.observability.flight.enabled", "true")
        st, coll = _member_store()
        eng = alerts.AlertEngine(st, rules=[
            {"name": "fb", "kind": "burn_rate", "slo": "ttft",
             "short": 10.0, "long": 20.0, "factor": 5.0,
             "objective": 0.99}])

        def counts():
            evs = flight.ring().events() if flight.ring() else []
            reg = obs.REGISTRY
            return {
                "fire_ev": sum(1 for e in evs
                               if e["kind"] == "alert_fire"),
                "resolve_ev": sum(1 for e in evs
                                  if e["kind"] == "alert_resolve"),
                "fire_tr": reg.sample_value(
                    "bigdl_alerts_transitions_total", rule="fb",
                    state="firing") or 0.0,
                "resolve_tr": reg.sample_value(
                    "bigdl_alerts_transitions_total", rule="fb",
                    state="resolved") or 0.0,
            }

        before = counts()
        self._slo_member(st, coll, 0.0, ok=10, violated=0)
        eng.evaluate(0.0)
        self._slo_member(st, coll, 10.0, ok=12, violated=10)
        eng.evaluate(10.0)
        self._slo_member(st, coll, 50.0, ok=20, violated=10)
        self._slo_member(st, coll, 55.0, ok=25, violated=10)
        eng.evaluate(55.0)
        after = counts()
        delta = {k: after[k] - before[k] for k in after}
        # same call site: transitions and flight events move in lockstep
        assert delta == {"fire_ev": 1, "resolve_ev": 1,
                         "fire_tr": 1.0, "resolve_tr": 1.0}
        assert (obs.REGISTRY.sample_value("bigdl_alerts_firing")
                or 0.0) == 0.0

    def test_record_rule_publishes_gauge(self):
        st, coll = _member_store()
        eng = alerts.AlertEngine(st, rules=[
            {"name": "qdepth", "kind": "record", "series": "q",
             "fn": "last", "window": 30.0, "instance": "m1"}])
        coll.snaps = {"m1": _doc(gauges={"q": 7.0})}
        st.sample_now(now=0.0)
        eng.evaluate(0.0)
        assert eng.status()["rules"][0]["state"] == "recording"
        assert obs.REGISTRY.sample_value("bigdl_alerts_recorded",
                                         rule="qdepth") == 7.0

    def test_declarative_rules_override_and_fallback(self):
        conf.set("bigdl.observability.alerts.rules",
                 '[{"name": "only", "kind": "threshold", '
                 '"series": "q", "value": 1}]')
        assert [r["name"] for r in alerts.load_rules()] == ["only"]
        conf.set("bigdl.observability.alerts.rules", "{broken json")
        names = [r["name"] for r in alerts.load_rules()]
        assert names == [r["name"] for r in alerts.default_rules()]
        assert "slo-fast-burn-ttft" in names


# ---------------------------------------------------------------------------
# lifecycle + the structural-absence contract
# ---------------------------------------------------------------------------

class TestGateLifecycle:
    def test_disabled_is_structurally_absent(self):
        # bigdl.observability.timeseries.enabled defaults off
        assert not ts.enabled
        lines_before = set(obs.render().splitlines())
        assert ts.acquire() is None
        assert ts.store() is None
        assert alerts.engine() is None
        assert ts.sample_now(now=0.0) is None
        assert ts.slo_burn("ttft", "router") is None
        for path in ("/metrics/query?series=x_total&window=60",
                     "/fleet/timeline?series=x_total"):
            status, body = ts.debug_endpoint(path)
            assert status == 404
            assert body["gate"] == GATE
        status, body = alerts.debug_endpoint("/alerts")
        assert status == 404 and body["gate"] == GATE
        assert not [t for t in threading.enumerate()
                    if t.name == ts.TimeSeriesStore.THREAD_NAME]
        grown = set(obs.render().splitlines()) - lines_before
        assert not [g for g in grown if "bigdl_timeseries" in g
                    or "bigdl_alerts" in g]

    def test_acquire_release_refcount(self):
        conf.set(GATE, "true")
        conf.set("bigdl.observability.timeseries.interval", "3600")
        st = ts.acquire()
        assert st is ts.store() is ts.acquire()   # refcount 2
        assert alerts.engine() is not None
        assert [t for t in threading.enumerate()
                if t.name == ts.TimeSeriesStore.THREAD_NAME]
        ts.release()
        assert [t for t in threading.enumerate()
                if t.name == ts.TimeSeriesStore.THREAD_NAME]
        ts.release()                              # last ref: stop
        assert not [t for t in threading.enumerate()
                    if t.name == ts.TimeSeriesStore.THREAD_NAME]

    def test_conf_refresh_pokes_live_store(self):
        conf.set(GATE, "true")
        assert ts.enabled
        conf.set("bigdl.observability.timeseries.interval", "3600")
        st = ts.acquire()
        try:
            conf.set("bigdl.observability.timeseries.retention", "42")
            assert st.retention == 42.0
        finally:
            ts.release()
        conf.unset(GATE)
        assert not ts.enabled

    def test_query_endpoint_over_live_store(self):
        conf.set(GATE, "true")
        conf.set("bigdl.observability.timeseries.interval", "3600")
        st = ts.acquire()
        try:
            c = obs.counter("bigdl_timeseries_samples_total")
            del c                        # the instrument exists anyway
            st.sample_now()
            st.sample_now()
            status, body = ts.debug_endpoint(
                "/metrics/query?series=bigdl_timeseries_samples_total"
                "&window=600&fn=delta")
            assert status == 200
            assert body["value"] >= 1.0
            status, body = ts.debug_endpoint(
                "/fleet/timeline?series=bigdl_timeseries_samples_total"
                "&window=600")
            assert status == 200
            assert list(body["instances"]) == ["local"]
            assert len(body["merged"]) == 2
            status, body = ts.debug_endpoint(
                "/metrics/query?series=x&window=nope")
            assert status == 400
            status, body = ts.debug_endpoint("/metrics/query")
            assert status == 400
        finally:
            ts.release()

    def test_slo_burn_from_store_windows(self):
        conf.set(GATE, "true")
        conf.set("bigdl.observability.timeseries.interval", "3600")
        st = ts.acquire()
        try:
            reqs = obs.counter("bigdl_slo_requests_total",
                               labelnames=("slo", "verdict", "scope"))
            st.sample_now(now=0.0)
            reqs.labels(slo="ttft", verdict="ok",
                        scope="ts-test").inc(6)
            reqs.labels(slo="ttft", verdict="violated",
                        scope="ts-test").inc(2)
            st.sample_now(now=10.0)
            burn = ts.slo_burn("ttft", "ts-test", window=60.0,
                               now=10.0)
            assert burn == pytest.approx(0.25)
            # warm store, idle scope: 0.0 (None means "no plane")
            assert ts.slo_burn("ttft", "no-such-scope", window=60.0,
                              now=10.0) == 0.0
        finally:
            ts.release()

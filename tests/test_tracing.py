"""ISSUE 3: end-to-end request tracing + XLA compile/HBM flight
recorder — TraceContext propagation (headers, queue records, contextvar),
cross-process span stitching on both serving stacks, /debug/trace
assembly, latency exemplars, recompile detection, the self-describing
build-info series, and the disabled-mode no-surface contract."""

import http.client
import json
import sys
import time

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu import observability as obs
from bigdl_tpu.observability import request_context as rc
from bigdl_tpu.observability.tracing import ExemplarStore

sys.path.insert(0, "tools")
try:
    from trace_report import build_waterfall, render_waterfall, traces_in
finally:
    sys.path.pop(0)


@pytest.fixture(autouse=True)
def _obs_clean():
    """Enabled switch, empty trace ring and exemplar store per test; the
    global registry is NOT cleared (live modules hold instrument refs) —
    tests read deltas."""
    was = obs.enabled()
    obs.enable()
    obs.TRACE.clear()
    obs.EXEMPLARS.clear()
    yield
    obs.TRACE.clear()
    obs.EXEMPLARS.clear()
    if was:
        obs.enable()
    else:
        obs.disable()


def _request(addr, method, path, obj=None, headers=()):
    conn = http.client.HTTPConnection(*addr, timeout=120)
    hdrs = {"Content-Type": "application/json", **dict(headers)}
    conn.request(method, path, json.dumps(obj) if obj is not None
                 else None, hdrs)
    r = conn.getresponse()
    body = r.read()
    out_headers = {k: v for k, v in r.getheaders()}
    conn.close()
    try:
        body = json.loads(body)
    except ValueError:
        body = body.decode()
    return r.status, body, out_headers


class TestTraceContext:
    def test_ids_and_child(self):
        ctx = rc.new_trace()
        assert len(ctx.trace_id) == 32 and len(ctx.span_id) == 16
        child = ctx.child()
        assert child.trace_id == ctx.trace_id
        assert child.span_id != ctx.span_id
        assert child.parent_id == ctx.span_id

    def test_header_roundtrip_case_insensitive(self):
        ctx = rc.new_trace()
        pairs = rc.to_headers(ctx)
        assert dict(pairs)[rc.TRACE_HEADER] == ctx.trace_id
        # a client lowercasing every header name must still propagate
        lowered = {k.lower(): v for k, v in pairs}
        back = rc.from_headers(lowered)
        assert back is not None
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id   # arrives as parent-span
        # and SHOUTING-case too
        shouted = {k.upper(): v for k, v in pairs}
        assert rc.from_headers(shouted).trace_id == ctx.trace_id

    def test_wire_roundtrip(self):
        ctx = rc.new_trace()
        blob = rc.to_wire(ctx)
        back = rc.from_wire(blob)
        assert back.trace_id == ctx.trace_id
        assert back.span_id == ctx.span_id
        assert rc.from_wire(None) is None
        assert rc.from_wire({"nope": 1}) is None

    def test_disabled_emits_and_extracts_nothing(self):
        ctx = rc.new_trace()
        obs.disable()
        try:
            assert rc.to_headers(ctx) == []
            assert rc.to_wire(ctx) is None
            assert rc.from_headers({rc.TRACE_HEADER: "a" * 32}) is None
            assert rc.server_context({rc.TRACE_HEADER: "a" * 32}) is None
            with rc.activate(ctx):
                assert rc.current() is None
        finally:
            obs.enable()

    def test_server_context_mints_root_when_absent(self):
        ctx = rc.server_context({})
        assert ctx is not None and len(ctx.trace_id) == 32


class TestSpanTraceTagging:
    def test_nested_spans_stitch_under_context(self):
        ctx = rc.new_trace()
        with rc.activate(ctx):
            with obs.span("outer", stage="a"):
                with obs.span("inner", stage="b"):
                    pass
        inner, outer = obs.TRACE.spans()       # completion order
        assert inner["args"]["trace"] == ctx.trace_id
        assert outer["args"]["trace"] == ctx.trace_id
        # inner's parent span is outer's own span id; outer parents to
        # the activated context (the caller's open span)
        assert inner["args"]["parent_span"] == outer["args"]["span"]
        assert outer["args"]["parent_span"] == ctx.span_id
        # context restored after the block
        assert rc.current() is None

    def test_untraced_spans_have_no_trace_args(self):
        with obs.span("plain"):
            pass
        (span,) = obs.TRACE.spans()
        assert "trace" not in span["args"]

    def test_for_trace_and_assemble(self):
        tid = "d" * 32
        obs.add_complete("x", 100.0, 0.5, trace=tid, stage="s1")
        obs.add_complete("y", 100.6, 0.25, trace=tid, stage="s2")
        obs.add_complete("z", 100.0, 0.1, trace="e" * 32, stage="s1")
        spans = obs.TRACE.for_trace(tid)
        assert [s["name"] for s in spans] == ["x", "y"]
        asm = obs.assemble_trace(tid)
        assert asm["span_count"] == 2
        assert set(asm["stages"]) == {"s1", "s2"}
        assert asm["stages"]["s2"]["seconds"] == pytest.approx(0.25)


class TestFakeClockWaterfall:
    def test_three_stage_waterfall_math(self):
        """Frontend→queue→worker stitching verified against a fake
        clock: offsets, durations and stage rollup come out exactly."""
        tid = "f" * 32
        t0 = 1000.0
        obs.add_complete("serving/predict", t0, 0.5, trace=tid,
                         stage="frontend")
        obs.add_complete("serving/queue_wait", t0 + 0.01, 0.2,
                         trace=tid, stage="queue")
        obs.add_complete("serving/infer", t0 + 0.21, 0.25, trace=tid,
                         stage="cluster_serving")
        wf = build_waterfall(obs.TRACE.spans(), tid)
        assert wf["wall_ms"] == pytest.approx(500.0)
        assert [r["name"] for r in wf["rows"]] == \
            ["serving/predict", "serving/queue_wait", "serving/infer"]
        assert wf["rows"][1]["start_ms"] == pytest.approx(10.0)
        assert wf["rows"][1]["dur_ms"] == pytest.approx(200.0)
        assert wf["stages"]["queue"] == pytest.approx(200.0)
        assert wf["stages"]["cluster_serving"] == pytest.approx(250.0)
        text = render_waterfall(wf)
        assert "stage rollup" in text and "queue" in text

    def test_emit_record_trace_spans_fake_clock(self):
        from bigdl_tpu.serving.cluster_serving import \
            emit_record_trace_spans
        tid = "a1" * 16
        recs = [{"uri": "u1", "trace": {"trace_id": tid,
                                        "parent_span": "b" * 16},
                 "enqueued_at": 2000.0},
                {"uri": "u2", "data": {}}]        # untraced: skipped
        shipped = emit_record_trace_spans(recs, infer_start=2003.0,
                                          infer_dur=1.5)
        spans = obs.TRACE.for_trace(tid)
        by_name = {s["name"]: s for s in spans}
        assert set(by_name) == {"serving/queue_wait", "serving/infer"}
        qw = by_name["serving/queue_wait"]
        assert qw["dur"] == pytest.approx(3.0 * 1e6)
        assert qw["args"]["parent_span"] == "b" * 16
        assert by_name["serving/infer"]["dur"] == \
            pytest.approx(1.5 * 1e6)
        assert len(obs.TRACE.spans()) == 2    # untraced rec emitted none
        # the consumer ships its spans home for cross-process assembly
        assert set(shipped) == {"u1"}
        assert [s["name"] for s in shipped["u1"]] == \
            ["serving/queue_wait", "serving/infer"]

    def test_foreign_span_ingestion_by_pid(self):
        import os
        from bigdl_tpu.observability import tracing
        mine = tracing.make_complete("local", 1.0, 0.1, trace="x" * 32)
        foreign = dict(mine, pid=os.getpid() + 1, name="remote")
        tracing.ingest_foreign_spans([mine, foreign, None])
        names = [s["name"] for s in obs.TRACE.spans()]
        assert names == ["remote"]     # same-pid and junk skipped

    def test_result_record_carries_trace_spans_on_the_wire(self):
        """The output-queue record round-trips the consumer's spans
        through the wire protocol (the cross-process assembly path)."""
        from bigdl_tpu.serving.cluster_serving import (
            ClusterServing, InputQueue, OutputQueue)
        from bigdl_tpu.serving.inference_model import InferenceModel

        im = InferenceModel().load_bigdl(
            model=nn.Sequential().add(nn.Linear(4, 2)).add(nn.SoftMax()))
        stream = "trace_wire_stream"
        inq = InputQueue(stream)
        outq = OutputQueue(stream)
        job = ClusterServing(im, stream_name=stream).start()
        ctx = rc.new_trace()
        try:
            with rc.activate(ctx):
                uri = inq.enqueue(None, input=np.ones((1, 4), np.float32))
            deadline = time.time() + 30
            rec = None
            while rec is None and time.time() < deadline:
                rec = outq.dequeue_record(timeout=1.0)
            assert rec is not None and rec["uri"] == uri
            names = [s["name"] for s in rec.get("trace_spans", [])]
            assert "serving/infer" in names
            assert all(s["args"]["trace"] == ctx.trace_id
                       for s in rec["trace_spans"])
        finally:
            job.stop()


class TestFrontendTraceStitching:
    def test_predict_stitches_three_stages(self):
        """Acceptance: one request through ServingFrontend backed by
        ClusterServing yields a single stitched trace, retrievable via
        GET /debug/trace/<id>, covering ≥3 stages — with lowercased
        request headers (the casing satellite)."""
        from bigdl_tpu.serving.cluster_serving import ClusterServing
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        from bigdl_tpu.serving.inference_model import InferenceModel

        im = InferenceModel().load_bigdl(
            model=nn.Sequential().add(nn.Linear(4, 3)).add(nn.SoftMax()))
        job = ClusterServing(im, stream_name="trace_stream").start()
        fe = ServingFrontend(stream_name="trace_stream").start()
        tid = "ab" * 16
        try:
            code, out, headers = _request(
                fe.address, "POST", "/predict",
                {"inputs": {"input": [[1.0, 2.0, 3.0, 4.0]]}},
                headers={"x-bigdl-trace-id": tid,
                         "x-bigdl-parent-span": "cd" * 8})
            assert code == 200, out
            # response echoes the trace id for /debug/trace lookup
            assert headers.get(rc.TRACE_HEADER) == tid
            code, doc, _ = _request(fe.address, "GET",
                                    f"/debug/trace/{tid}")
            assert code == 200
            stages = set(doc["stages"])
            assert {"frontend", "queue", "cluster_serving"} <= stages
            assert doc["span_count"] >= 3
            # the frontend root span parents to the client's span header
            root = [s for s in doc["spans"]
                    if s["name"] == "serving/predict"][0]
            assert root["args"]["parent_span"] == "cd" * 8
            # exemplar retained and listed
            code, ex, _ = _request(fe.address, "GET", "/debug/traces")
            assert code == 200
            assert any(e["trace_id"] == tid for e in ex["exemplars"])
            # the tool renders its waterfall
            wf = build_waterfall(doc["spans"], tid)
            assert wf["wall_ms"] > 0 and len(wf["rows"]) >= 3
            assert "frontend" in wf["stages"]
        finally:
            fe.stop()
            job.stop()

    def test_request_without_headers_gets_fresh_trace(self):
        from bigdl_tpu.serving.cluster_serving import ClusterServing
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        from bigdl_tpu.serving.inference_model import InferenceModel

        im = InferenceModel().load_bigdl(
            model=nn.Sequential().add(nn.Linear(4, 2)).add(nn.SoftMax()))
        job = ClusterServing(im, stream_name="trace_fresh_stream").start()
        fe = ServingFrontend(stream_name="trace_fresh_stream").start()
        try:
            code, _, headers = _request(
                fe.address, "POST", "/predict",
                {"inputs": {"input": [[1.0, 2.0, 3.0, 4.0]]}})
            assert code == 200
            tid = headers.get(rc.TRACE_HEADER)
            assert tid and len(tid) == 32
            assert obs.TRACE.for_trace(tid)
        finally:
            fe.stop()
            job.stop()


class TestDeadlineHeaderCasing:
    def test_lowercase_deadline_header_caps_the_wait(self):
        """X-BigDL-Deadline-Ms must round-trip case-insensitively: a
        lowercased header on a request whose backend never answers must
        cap the wait at the deadline, not the 30s result timeout."""
        from bigdl_tpu.serving.http_frontend import ServingFrontend

        fe = ServingFrontend(stream_name="deadline_case_stream").start()
        try:
            t0 = time.monotonic()
            code, out, _ = _request(
                fe.address, "POST", "/predict",
                {"inputs": {"input": [[1.0, 2.0]]}},
                headers={"x-bigdl-deadline-ms": "300"})
            elapsed = time.monotonic() - t0
            assert code == 504 and "timeout" in out["error"]
            assert elapsed < 10.0    # not the 30s result_timeout
        finally:
            fe.stop()


class TestLLMTraceStitching:
    @pytest.fixture(scope="class")
    def served(self):
        from bigdl_tpu.llm.models.llama import (LlamaConfig,
                                                LlamaForCausalLM)
        from bigdl_tpu.llm.serving import LLMServer
        from bigdl_tpu.llm.worker import LLMWorker

        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                             max_cache_len=64)
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        worker = LLMWorker(srv).start()
        yield srv, worker
        worker.stop()
        srv.stop(drain=False)

    def test_generate_stitches_engine_stages(self, served):
        """Acceptance: LLMServer→LLMWorker yields one stitched trace
        (request → queue wait → prefill → decode) via /debug/trace."""
        srv, worker = served
        tid = "e1" * 16
        code, out, headers = _request(
            worker.address, "POST", "/worker_generate",
            {"prompt_ids": [1, 2, 3], "max_new_tokens": 3},
            headers={"x-bigdl-trace-id": tid})
        assert code == 200 and len(out["output_ids"]) == 3
        assert headers.get(rc.TRACE_HEADER) == tid
        code, doc, _ = _request(worker.address, "GET",
                                f"/debug/trace/{tid}")
        assert code == 200
        names = {s["name"] for s in doc["spans"]}
        assert {"llm/request", "llm/queue_wait", "llm/prefill",
                "llm/decode"} <= names
        assert {"llm_worker", "queue", "llm_server"} <= \
            set(doc["stages"])
        # decode span accounts the request's tokens
        decode = [s for s in doc["spans"]
                  if s["name"] == "llm/decode"][0]
        assert decode["args"]["tokens"] == 3
        # exemplar retained
        assert any(e["trace_id"] == tid
                   for e in obs.EXEMPLARS.items())

    def test_unknown_trace_404s(self, served):
        _, worker = served
        code, out, _ = _request(worker.address, "GET",
                                "/debug/trace/" + "0" * 32)
        assert code == 404


class TestCompileRecorder:
    def test_recompile_detected_exactly_once(self):
        import jax.numpy as jnp

        f = obs.compiled(lambda x: x * 3, name="test/recompile_unit")

        def series(metric):
            return obs.REGISTRY.sample_value(
                metric, fn="test/recompile_unit") or 0

        f(jnp.ones((4,)))
        f(jnp.ones((4,)))                 # same signature: cache hit
        assert series("bigdl_xla_compiles_total") == 1
        assert series("bigdl_xla_recompiles_total") == 0
        f(jnp.ones((2, 2)))               # changed shape
        assert series("bigdl_xla_compiles_total") == 2
        assert series("bigdl_xla_recompiles_total") == 1
        f(jnp.ones((2, 2)))               # seen again: no new compile
        assert series("bigdl_xla_recompiles_total") == 1
        stats = [s for s in obs.compile_stats()
                 if s["fn"] == "test/recompile_unit"][0]
        assert stats["compiles"] == 2 and stats["recompiles"] == 1
        # the triggering signature is recorded, human-readable
        assert stats["history"][1]["signature"] == "(float32[2,2])"
        # compile events land in the trace ring too
        assert any(s["name"] == "xla/compile"
                   and s["args"]["fn"] == "test/recompile_unit"
                   and s["args"]["recompile"]
                   for s in obs.TRACE.spans())

    def test_cost_and_memory_harvested(self):
        import jax.numpy as jnp

        f = obs.compiled(lambda x: x @ x, name="test/cost_unit")
        f(jnp.ones((8, 8)))
        flops = obs.REGISTRY.sample_value("bigdl_xla_flops_per_call",
                                          fn="test/cost_unit")
        assert flops and flops > 0
        assert obs.REGISTRY.sample_value(
            "bigdl_xla_bytes_accessed_per_call", fn="test/cost_unit") > 0
        assert obs.REGISTRY.sample_value(
            "bigdl_xla_peak_hbm_bytes", fn="test/cost_unit") > 0
        assert obs.REGISTRY.sample_value(
            "bigdl_xla_compile_seconds", fn="test/cost_unit") == 1

    def test_results_match_plain_jit(self):
        import jax.numpy as jnp

        f = obs.compiled(lambda x, y: x * 2 + y, name="test/value_unit")
        out = f(jnp.arange(4.0), y=jnp.ones((4,)))
        np.testing.assert_allclose(np.asarray(out),
                                   np.arange(4.0) * 2 + 1)

    def test_disabled_mode_no_series_no_signatures(self):
        import jax.numpy as jnp

        obs.disable()
        try:
            f = obs.compiled(lambda x: x + 1, name="test/disabled_unit")
            f(jnp.ones((4,)))
            f(jnp.ones((8,)))             # a "recompile", untracked
        finally:
            obs.enable()
        assert obs.REGISTRY.sample_value(
            "bigdl_xla_compiles_total", fn="test/disabled_unit") in \
            (None, 0)
        assert not [s for s in obs.compile_stats()
                    if s["fn"] == "test/disabled_unit"]
        assert len(obs.TRACE) == 0


class TestDisabledModeNoTraceSurface:
    def test_no_headers_no_spans_no_debug(self):
        """Acceptance: with observability disabled no trace headers are
        emitted and no new series/spans exist; /debug/trace is 404."""
        from bigdl_tpu.serving.cluster_serving import ClusterServing
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        from bigdl_tpu.serving.inference_model import InferenceModel

        obs.disable()
        im = InferenceModel().load_bigdl(
            model=nn.Sequential().add(nn.Linear(4, 2)).add(nn.SoftMax()))
        job = ClusterServing(im, stream_name="trace_off_stream").start()
        fe = ServingFrontend(stream_name="trace_off_stream").start()
        try:
            code, _, headers = _request(
                fe.address, "POST", "/predict",
                {"inputs": {"input": [[1.0, 2.0, 3.0, 4.0]]}},
                headers={"x-bigdl-trace-id": "aa" * 16})
            assert code == 200
            assert rc.TRACE_HEADER not in headers
            assert len(obs.TRACE) == 0
            assert obs.EXEMPLARS.items() == []
            code, _, _ = _request(fe.address, "GET",
                                  "/debug/trace/" + "aa" * 16)
            assert code == 404
            code, _, _ = _request(fe.address, "GET", "/debug/traces")
            assert code == 404
        finally:
            obs.enable()
            fe.stop()
            job.stop()


class TestExemplarStore:
    def test_slowest_n_retained(self):
        store = ExemplarStore(capacity=3)
        for i, dur in enumerate([0.1, 0.5, 0.2, 0.9, 0.05]):
            store.offer(f"trace{i}", dur, name="t")
        kept = [e["duration_s"] for e in store.items()]
        assert kept == [0.9, 0.5, 0.2]    # slowest first, capped at 3

    def test_same_trace_updates_in_place(self):
        store = ExemplarStore(capacity=3)
        store.offer("t1", 0.1)
        store.offer("t1", 0.4)
        assert len(store.items()) == 1
        assert store.items()[0]["duration_s"] == pytest.approx(0.4)

    def test_disabled_records_nothing(self):
        store = ExemplarStore(capacity=3)
        obs.disable()
        try:
            store.offer("t1", 1.0)
        finally:
            obs.enable()
        assert store.items() == []


class TestBuildInfo:
    def test_standard_series_on_render(self):
        from bigdl_tpu.observability import parse_prometheus
        from bigdl_tpu.version import __version__

        parsed = parse_prometheus(obs.render())
        info = parsed["bigdl_build_info"]
        (labels, value), = info.items()
        assert value == 1
        assert dict(labels)["version"] == __version__
        assert "jax_version" in dict(labels)
        assert parsed["process_start_time_seconds"][()] == \
            pytest.approx(obs.PROCESS_START_TIME)

    def test_absent_when_disabled(self):
        reg = obs.MetricRegistry()
        # the ensure hook writes to the GLOBAL registry only when
        # enabled; a disabled render must not mint the series fresh
        obs.disable()
        try:
            text = obs.render_prometheus(reg)
            assert "bigdl_build_info" not in text
        finally:
            obs.enable()


class TestBenchRegressTool:
    @staticmethod
    def _write_round(tmp_path, n, resnet, llama):
        ns = {"resnet_img_s": resnet,
              "llama_b1": {"v": llama, "unit": "tokens/sec"}}
        compact = {"metric": "resnet50_imagenet_train_throughput",
                   "value": resnet, "unit": "images/sec/chip",
                   "extra": {"northstar_summary": ns}}
        tail = json.dumps(compact)
        (tmp_path / f"BENCH_r{n:02d}.json").write_text(
            json.dumps({"n": n, "cmd": "bench", "rc": 0, "tail": tail}))

    def test_deltas_and_warn_threshold(self, tmp_path):
        sys.path.insert(0, "tools")
        try:
            from bench_regress import compare_latest
        finally:
            sys.path.pop(0)
        self._write_round(tmp_path, 1, resnet=2500.0, llama=30.0)
        self._write_round(tmp_path, 2, resnet=2550.0, llama=20.0)
        progress = tmp_path / "PROGRESS.jsonl"
        out = compare_latest(str(tmp_path), warn_pct=10.0,
                             progress_path=str(progress))
        assert out["base"] == "BENCH_r01.json"
        assert out["head"] == "BENCH_r02.json"
        d = out["deltas"]
        assert d["resnet_img_s"]["pct"] == pytest.approx(2.0)
        assert not d["resnet_img_s"]["warn"]
        assert d["llama_b1"]["warn"]          # -33%: past the threshold
        assert out["warned"] == ["llama_b1"]
        # compact breadcrumb appended
        line = json.loads(progress.read_text().strip())
        assert line["kind"] == "bench_regress"
        assert line["warned"] == ["llama_b1"]

    def test_fewer_than_two_rounds(self, tmp_path):
        sys.path.insert(0, "tools")
        try:
            from bench_regress import compare_latest
        finally:
            sys.path.pop(0)
        self._write_round(tmp_path, 1, resnet=1.0, llama=1.0)
        assert compare_latest(str(tmp_path)) is None


class TestTelemetryReportTraceFilter:
    def test_trace_filter_and_p95(self):
        sys.path.insert(0, "tools")
        try:
            from telemetry_report import summarize_trace
        finally:
            sys.path.pop(0)
        t1, t2 = "a" * 32, "b" * 32
        for i in range(10):
            obs.add_complete("phase/x", 100.0 + i, 0.01 * (i + 1),
                             trace=t1)
        obs.add_complete("phase/x", 200.0, 5.0, trace=t2)
        doc = {"traceEvents": obs.TRACE.spans()}
        all_spans = summarize_trace(doc)
        assert all_spans["spans"]["phase/x"]["count"] == 11
        assert "p95" in all_spans["spans"]["phase/x"]
        only_t1 = summarize_trace(doc, trace_id=t1)
        assert only_t1["trace_id"] == t1
        assert only_t1["spans"]["phase/x"]["count"] == 10
        assert only_t1["spans"]["phase/x"]["max"] == pytest.approx(0.1)

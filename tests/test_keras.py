"""Keras-API tests (ref pattern: keras layer specs + fit smoke tests,
SURVEY.md §4 'Keras-parity')."""

import numpy as np
import pytest

import bigdl_tpu.keras as K
from bigdl_tpu.nn.module import set_seed


class TestShapeInference:
    def test_dense_chain(self):
        m = K.Sequential()
        m.add(K.Dense(32, activation="relu", input_shape=(16,)))
        m.add(K.Dense(8))
        assert m.get_output_shape() == (8,)

    def test_conv_pool_flatten(self):
        m = K.Sequential()
        m.add(K.Convolution2D(6, 5, 5, input_shape=(1, 28, 28)))
        assert m.get_output_shape() == (6, 24, 24)
        m.add(K.MaxPooling2D((2, 2)))
        assert m.get_output_shape() == (6, 12, 12)
        m.add(K.Flatten())
        assert m.get_output_shape() == (6 * 12 * 12,)

    def test_same_padding(self):
        m = K.Sequential()
        m.add(K.Convolution2D(4, 3, 3, border_mode="same",
                              subsample=(2, 2), input_shape=(3, 32, 32)))
        assert m.get_output_shape() == (4, 16, 16)

    def test_first_layer_needs_shape(self):
        m = K.Sequential()
        with pytest.raises(ValueError):
            m.add(K.Dense(4))

    def test_rnn_shapes(self):
        m = K.Sequential()
        m.add(K.LSTM(7, return_sequences=True, input_shape=(5, 3)))
        assert m.get_output_shape() == (5, 7)
        m.add(K.GRU(4))
        assert m.get_output_shape() == (4,)

    def test_embedding_shape(self):
        m = K.Sequential()
        m.add(K.Embedding(100, 8, input_length=10))
        assert m.get_output_shape() == (10, 8)


class TestForwardShapes:
    @pytest.mark.parametrize("layer,shape", [
        (lambda: K.Convolution1D(4, 3), (5, 10, 6)),
        (lambda: K.MaxPooling1D(2), (5, 10, 6)),
        (lambda: K.AveragePooling1D(2), (5, 10, 6)),
        (lambda: K.GlobalMaxPooling1D(), (5, 10, 6)),
        (lambda: K.GlobalAveragePooling1D(), (5, 10, 6)),
        (lambda: K.ZeroPadding2D((1, 2)), (5, 3, 8, 8)),
        (lambda: K.UpSampling2D((2, 2)), (5, 3, 8, 8)),
        (lambda: K.BatchNormalization(), (5, 3, 8, 8)),
        (lambda: K.Permute((2, 1)), (5, 4, 6)),
        (lambda: K.RepeatVector(3), (5, 7)),
        (lambda: K.LeakyReLU(), (5, 7)),
        (lambda: K.Bidirectional(K.LSTM(4)), (5, 6, 3)),
        (lambda: K.TimeDistributed(K.Dense(4)), (5, 6, 3)),
    ])
    def test_forward_matches_inferred_shape(self, layer, shape):
        set_seed(0)
        lay = layer()
        mod = lay.build(shape[1:])
        out = mod.forward(np.random.rand(*shape).astype(np.float32))
        assert tuple(out.shape) == (shape[0],) + tuple(lay.output_shape), \
            f"{type(lay).__name__}: {out.shape} vs {lay.output_shape}"


class TestTraining:
    def test_mlp_fit_evaluate_predict(self):
        set_seed(1)
        rs = np.random.RandomState(0)
        x = rs.rand(256, 10).astype(np.float32)
        w = rs.randn(10, 3).astype(np.float32)
        y = (x @ w).argmax(1).astype(np.int32)  # zero-based labels

        from bigdl_tpu.optim.optim_method import Adam
        m = K.Sequential()
        m.add(K.Dense(32, activation="relu", input_shape=(10,)))
        m.add(K.Dense(3, activation="softmax"))
        m.compile(optimizer=Adam(learning_rate=0.01),
                  loss="sparse_categorical_crossentropy",
                  metrics=["accuracy"])
        m.fit(x, y, batch_size=32, nb_epoch=40, distributed=False)
        res = m.evaluate(x, y)
        assert res[0].result > 0.9, f"accuracy {res[0].result}"
        pred = m.predict(x[:7])
        assert pred.shape == (7, 3)
        np.testing.assert_allclose(pred.sum(1), 1.0, rtol=1e-4)
        cls = m.predict_classes(x[:7])
        assert cls.shape == (7,)

    def test_regression_mse(self):
        set_seed(2)
        rs = np.random.RandomState(1)
        x = rs.rand(128, 4).astype(np.float32)
        y = (x.sum(axis=1, keepdims=True) * 2).astype(np.float32)
        m = K.Sequential()
        m.add(K.Dense(16, activation="tanh", input_shape=(4,)))
        m.add(K.Dense(1))
        m.compile(optimizer="sgd", loss="mse")
        m.fit(x, y, batch_size=16, nb_epoch=80, distributed=False)
        pred = m.predict(x)
        assert float(np.mean((pred - y) ** 2)) < 0.05


class TestFunctionalModel:
    def test_two_branch_merge(self):
        set_seed(3)
        a = K.Input(shape=(8,))
        b = K.Input(shape=(6,))
        ha = K.Dense(4, activation="relu")(a)
        hb = K.Dense(4, activation="relu")(b)
        joined = K.merge([ha, hb], mode="concat")
        assert joined.shape == (8,)
        out = K.Dense(2)(joined)
        model = K.Model(input=[a, b], output=out)
        xa = np.random.rand(5, 8).astype(np.float32)
        xb = np.random.rand(5, 6).astype(np.float32)
        y = model.module.forward([xa, xb])
        assert tuple(y.shape) == (5, 2)

    def test_merge_sum_and_residual(self):
        set_seed(4)
        a = K.Input(shape=(6,))
        h = K.Dense(6, activation="relu")(a)
        s = K.merge([a, h], mode="sum")
        model = K.Model(input=a, output=s)
        x = np.random.rand(3, 6).astype(np.float32)
        y = model.module.forward(x)
        assert tuple(y.shape) == (3, 6)

    def test_graph_cycle_detection(self):
        from bigdl_tpu.nn.graph import Graph, Input as GInput, Node
        import bigdl_tpu.nn as nn
        a = GInput()
        lin = nn.Linear(4, 4)
        n1 = lin.inputs(a)
        n1.inputs.append(n1)  # malformed self-loop
        with pytest.raises(ValueError):
            Graph([a], [n1])


class TestWidenedKerasLayers:
    def test_conv3d_pipeline(self):
        import numpy as np
        import bigdl_tpu.keras as K
        from bigdl_tpu.nn.module import set_seed
        set_seed(0)
        m = K.Sequential()
        m.add(K.Convolution3D(4, 3, 3, 3, activation="relu",
                              input_shape=(2, 8, 8, 8)))
        m.add(K.MaxPooling3D())
        m.add(K.Flatten())
        m.add(K.Dense(5))
        x = np.random.RandomState(0).rand(2, 2, 8, 8, 8).astype(np.float32)
        y = m.predict(x)
        assert y.shape == (2, 5)
        assert np.isfinite(np.asarray(y)).all()

    def test_cropping_and_misc_wrappers(self):
        import numpy as np
        import bigdl_tpu.keras as K
        from bigdl_tpu.nn.module import set_seed
        set_seed(0)
        m = K.Sequential()
        m.add(K.Cropping2D(((1, 1), (2, 2)), input_shape=(3, 10, 12)))
        m.add(K.SpatialDropout2D(0.2))
        m.add(K.Flatten())
        m.add(K.Highway())
        m.add(K.Dense(4))
        x = np.random.RandomState(1).rand(2, 3, 10, 12).astype(np.float32)
        y = m.predict(x)
        assert y.shape == (2, 4)

    def test_locally_connected_and_crop1d(self):
        import numpy as np
        import bigdl_tpu.keras as K
        from bigdl_tpu.nn.module import set_seed
        set_seed(0)
        m = K.Sequential()
        m.add(K.Cropping1D((1, 2), input_shape=(12, 6)))
        m.add(K.LocallyConnected1D(8, 3, activation="tanh"))
        m.add(K.GlobalMaxPooling1D())
        m.add(K.Dense(3))
        x = np.random.RandomState(2).rand(2, 12, 6).astype(np.float32)
        y = m.predict(x)
        assert y.shape == (2, 3)

    def test_noise_layers_identity_at_eval(self):
        import numpy as np
        import bigdl_tpu.keras as K
        from bigdl_tpu.nn.module import set_seed
        set_seed(0)
        m = K.Sequential()
        m.add(K.GaussianNoise(0.5, input_shape=(6,)))
        m.add(K.GaussianDropout(0.3))
        m.add(K.Masking(0.0))
        x = np.random.RandomState(3).rand(4, 6).astype(np.float32)
        y = np.asarray(m.predict(x))
        np.testing.assert_allclose(y, x, rtol=1e-6)

"""Native C++ quant library tests: bit-parity against the pure-numpy
implementation (the golden-parity pattern, SURVEY.md §4)."""

import numpy as np
import pytest

from bigdl_tpu.native import (
    available, native_dequantize_q4_0, native_matmul_q4_0,
    native_quantize_q4_0, native_quantize_q8_0)

pytestmark = pytest.mark.skipif(not available(),
                                reason="native toolchain unavailable")


def _numpy_q4_0(w):
    """Independent reimplementation (not the production numpy path)."""
    n, k = w.shape
    blocks = w.reshape(n, k // 32, 32)
    amax = np.abs(blocks).max(axis=2)
    scale = (amax / 7.0).astype(np.float16)
    s = scale.astype(np.float32)[..., None]
    q = np.round(np.divide(blocks, s, out=np.zeros_like(blocks),
                           where=s > 0)).clip(-7, 7) + 8
    q = q.astype(np.uint8).reshape(n, -1)
    packed = (q[:, 0::2] | (q[:, 1::2] << 4)).astype(np.uint8)
    return packed, scale


class TestNativeQuant:
    def test_q4_0_bit_parity_with_numpy(self):
        rs = np.random.RandomState(0)
        w = (rs.randn(16, 256) * rs.uniform(0.01, 3)).astype(np.float32)
        native = native_quantize_q4_0(w)
        ref_q, ref_s = _numpy_q4_0(w)
        np.testing.assert_array_equal(native["scale"].view(np.uint16),
                                      ref_s.view(np.uint16))
        # rounding at exact .5 boundaries may differ (lround vs np.round
        # banker's rounding): tolerate ±1 code on a tiny fraction
        nq = native["q"]
        diff_lo = np.abs((nq & 0xF).astype(int) - (ref_q & 0xF).astype(int))
        diff_hi = np.abs((nq >> 4).astype(int) - (ref_q >> 4).astype(int))
        assert (diff_lo <= 1).all() and (diff_hi <= 1).all()
        frac = ((diff_lo > 0).mean() + (diff_hi > 0).mean()) / 2
        assert frac < 0.01, frac

    def test_q4_0_roundtrip_through_python_dequant(self):
        from bigdl_tpu.llm.ggml.quantize import dequantize

        rs = np.random.RandomState(1)
        w = rs.randn(8, 128).astype(np.float32)
        qd = native_quantize_q4_0(w)
        deq_py = dequantize(qd)
        deq_c = native_dequantize_q4_0(qd["q"], qd["scale"])
        np.testing.assert_allclose(deq_c, deq_py, atol=1e-6)
        rel = np.abs(deq_c - w).max() / np.abs(w).max()
        assert rel < 0.10

    def test_q8_0_matches_python(self):
        from bigdl_tpu.llm.ggml.quantize import dequantize

        rs = np.random.RandomState(2)
        w = rs.randn(4, 96).astype(np.float32)
        qd = native_quantize_q8_0(w)
        deq = dequantize(qd)
        rel = np.abs(deq - w).max() / np.abs(w).max()
        assert rel < 0.02

    def test_matmul_matches_dequant_matmul(self):
        from bigdl_tpu.llm.ggml.quantize import dequantize

        rs = np.random.RandomState(3)
        x = rs.randn(5, 128).astype(np.float32)
        w = rs.randn(24, 128).astype(np.float32) * 0.2
        qd = native_quantize_q4_0(w)
        ref = x @ dequantize(qd).T
        out = native_matmul_q4_0(x, qd["q"], qd["scale"])
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_production_quantize_uses_native(self):
        """quantize() must route sym_int4 through the native path and stay
        consistent with the Pallas kernel's layout expectations."""
        import jax.numpy as jnp

        from bigdl_tpu.llm.ggml.quantize import quantize
        from bigdl_tpu.llm.kernels import int4_matmul, to_tpu_layout

        rs = np.random.RandomState(4)
        x = rs.randn(4, 64).astype(np.float32)
        w = rs.randn(16, 64).astype(np.float32) * 0.3
        qd = quantize(w, "sym_int4")
        td = to_tpu_layout(qd)
        out = np.asarray(int4_matmul(
            jnp.asarray(x), jnp.asarray(np.asarray(td["q"])),
            jnp.asarray(np.asarray(td["scale"])),
            interpret=True, out_dtype=jnp.float32), np.float32)
        from bigdl_tpu.llm.ggml.quantize import dequantize
        ref = x @ dequantize(qd).T
        assert np.abs(out - ref).max() / np.abs(ref).max() < 0.02

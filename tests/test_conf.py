"""Layered config system (ref: SURVEY.md §5 config row — properties/
conf-file/env layering of the reference's Engine.createSparkConf)."""

import os

import pytest

from bigdl_tpu.utils.conf import BigDLConf, _env_key


class TestBigDLConf:
    def test_defaults(self):
        c = BigDLConf(conf_file="/nonexistent")
        assert c.get("bigdl.mesh.axes") == "data"
        assert c.get_bool("bigdl.llm.kvcache.enabled") is False
        assert c.get_int("bigdl.optimizer.max.retry") == 0

    def test_layering_file_env_set(self, tmp_path, monkeypatch):
        f = tmp_path / "bigdl-tpu.conf"
        f.write_text("# comment\nbigdl.mesh.axes=data,model\n"
                     "bigdl.optimizer.max.retry=3\n")
        c = BigDLConf(conf_file=str(f))
        assert c.get_list("bigdl.mesh.axes") == ["data", "model"]
        assert c.get_int("bigdl.optimizer.max.retry") == 3
        # env overrides file
        monkeypatch.setenv(_env_key("bigdl.optimizer.max.retry"), "5")
        assert c.get_int("bigdl.optimizer.max.retry") == 5
        # set() overrides env
        c.set("bigdl.optimizer.max.retry", 7)
        assert c.get_int("bigdl.optimizer.max.retry") == 7
        c.unset("bigdl.optimizer.max.retry")
        assert c.get_int("bigdl.optimizer.max.retry") == 5

    def test_typed_getters_validate(self):
        c = BigDLConf(conf_file="/nonexistent")
        c.set("bigdl.num.processes", "not-a-number")
        with pytest.raises(ValueError, match="not an int"):
            c.get_int("bigdl.num.processes")
        c.set("bigdl.train.prefetch", "maybe")
        with pytest.raises(ValueError, match="not a bool"):
            c.get_bool("bigdl.train.prefetch")

    def test_effective_view(self):
        c = BigDLConf(conf_file="/nonexistent")
        c.set("bigdl.engine.type", "cpu")
        eff = c.effective()
        assert eff["bigdl.engine.type"] == "cpu"
        assert "bigdl.mesh.axes" in eff

    def test_env_key_mapping(self):
        assert _env_key("bigdl.engine.type") == "BIGDL_TPU_ENGINE_TYPE"

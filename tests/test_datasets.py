"""Input pipelines: CIFAR binary reader, ImageNet folder reader, prefetch
overlap — feeding DistriOptimizer end-to-end (VERDICT r1 missing #10)."""

import os

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.feature.cifar import (
    cifar_dataset, load_cifar, normalizer)
from bigdl_tpu.feature.dataset import PrefetchDataSet, SampleToMiniBatch
from bigdl_tpu.feature.imagenet import (
    ImageFolderDataSet, synthetic_imagenet_dataset)


def _write_cifar10_bin(folder, n_per_file=20, seed=0):
    rs = np.random.RandomState(seed)
    os.makedirs(folder, exist_ok=True)
    all_labels, all_imgs = [], []
    for name in [f"data_batch_{i}.bin" for i in range(1, 6)]:
        labels = rs.randint(0, 10, n_per_file).astype(np.uint8)
        imgs = rs.randint(0, 256, (n_per_file, 3072)).astype(np.uint8)
        rec = np.concatenate([labels[:, None], imgs], axis=1)
        rec.tofile(os.path.join(folder, name))
        all_labels.append(labels)
        all_imgs.append(imgs)
    return (np.concatenate(all_imgs).reshape(-1, 3, 32, 32),
            np.concatenate(all_labels))


class TestCifarReader:
    def test_binary_format_roundtrip(self, tmp_path):
        folder = str(tmp_path / "cifar")
        imgs, labels = _write_cifar10_bin(folder)
        x, y = load_cifar(folder, train=True)
        assert x.shape == (100, 3, 32, 32)
        np.testing.assert_allclose(x, imgs.astype(np.float32) / 255.0)
        np.testing.assert_array_equal(y, labels.astype(np.float32) + 1)

    def test_augment_chain_shapes(self):
        ds = cifar_dataset(synthetic_size=16)
        batches = list(SampleToMiniBatch(8)(ds.data(train=True)))
        assert len(batches) == 2
        x = batches[0].get_input()
        assert x.shape == (8, 3, 32, 32)
        # normalized data should not be in [0,1] anymore
        assert x.min() < -0.5

    def test_feeds_distri_optimizer(self, devices):
        from bigdl_tpu.models import lenet  # noqa: F401  (pattern check)
        from bigdl_tpu.optim.optimizer import Optimizer
        from bigdl_tpu.optim.optim_method import Adam
        from bigdl_tpu.optim.trigger import Trigger
        from bigdl_tpu.nn.module import set_seed

        set_seed(0)
        ds = cifar_dataset(synthetic_size=256, classes=10).prefetch(16)
        model = (nn.Sequential()
                 .add(nn.Reshape((3 * 32 * 32,)))
                 .add(nn.Linear(3 * 32 * 32, 64)).add(nn.ReLU())
                 .add(nn.Linear(64, 10)).add(nn.LogSoftMax()))
        opt = Optimizer(model, ds, nn.ClassNLLCriterion(), batch_size=64,
                        end_trigger=Trigger.max_epoch(12), distributed=True)
        # 2e-4, not 2e-3: Adam's first steps are ~sign(g)*lr per
        # weight, so at 2e-3 the 3072-wide input layer shifts hidden
        # pre-activations by ~±6 in one step — the loss spikes to ~15,
        # the ReLU layer dies, and training parks at the uniform
        # ln(10)≈2.30 forever (acc 0.17, the long-standing tier-1
        # failure). At 2e-4 the same pipeline memorizes the synthetic
        # set to acc 1.0 in the same 12 epochs.
        opt.set_optim_method(Adam(learning_rate=2e-4))
        opt.optimize()
        x, y = load_cifar(synthetic_size=256)
        model.evaluate()
        import jax.numpy as jnp
        pred = np.asarray(model.forward(
            jnp.asarray(normalizer(x)))).argmax(-1) + 1
        acc = (pred == y).mean()
        assert acc > 0.6, f"synthetic CIFAR did not train: acc={acc}"


class TestImageFolderReader:
    @pytest.fixture()
    def image_tree(self, tmp_path):
        PIL = pytest.importorskip("PIL")
        from PIL import Image
        root = tmp_path / "imagenet" / "train"
        rs = np.random.RandomState(0)
        for cls in ["n01", "n02", "n03"]:
            d = root / cls
            d.mkdir(parents=True)
            for i in range(4):
                arr = rs.randint(0, 256, (40, 52, 3)).astype(np.uint8)
                Image.fromarray(arr).save(d / f"img{i}.JPEG")
        return str(root)

    def test_reads_and_labels(self, image_tree):
        ds = ImageFolderDataSet(image_tree, image_size=32, train=False)
        assert ds.size() == 12
        assert ds.class_names == ["n01", "n02", "n03"]
        samples = list(ds.data(train=False))
        assert len(samples) == 12
        assert samples[0].features[0].shape == (3, 32, 32)
        labels = sorted({float(s.labels[0]) for s in samples})
        assert labels == [1.0, 2.0, 3.0]   # 1-based

    def test_train_augment_randomized(self, image_tree):
        ds = ImageFolderDataSet(image_tree, image_size=32, train=True)
        a = next(iter(ds.data(train=True))).features[0]
        b = next(iter(ds.data(train=True))).features[0]
        assert a.shape == (3, 32, 32)
        assert not np.array_equal(a, b)   # crop/flip randomness

    def test_synthetic_imagenet_streams(self):
        ds = synthetic_imagenet_dataset(n=8, classes=5, image_size=16)
        batches = list(SampleToMiniBatch(4)(ds.data(train=False)))
        assert len(batches) == 2
        assert batches[0].get_input().shape == (4, 3, 16, 16)


class TestPrefetch:
    def test_order_and_completeness(self):
        from bigdl_tpu.feature.dataset import LocalDataSet

        x = np.arange(64, dtype=np.float32)[:, None]
        ds = LocalDataSet(x, x[:, 0], shuffle=False)
        plain = [float(s.features[0][0]) for s in ds.data(train=False)]
        pre = [float(s.features[0][0])
               for s in PrefetchDataSet(ds, depth=4).data(train=False)]
        assert plain == pre

    def test_propagates_producer_error(self):
        class Boom:
            def size(self):
                return 1

            def data(self, train=True):
                yield from ()
                raise RuntimeError("decode failed")

        class BoomReal(Boom):
            def data(self, train=True):
                raise RuntimeError("decode failed")
                yield  # pragma: no cover

        with pytest.raises(RuntimeError, match="decode failed"):
            list(PrefetchDataSet(BoomReal(), depth=2).data())

"""Chronos tests (ref pattern: chronos tests train tiny models on synthetic
series, SURVEY.md §4). BASELINE config 3 = TCN/Seq2Seq forecasters."""

import numpy as np
import pandas as pd
import pytest

from bigdl_tpu.chronos import (
    AEDetector, LSTMForecaster, NBeatsForecaster, Seq2SeqForecaster,
    TCNForecaster, ThresholdDetector, TSDataset)


def _sine_df(n=300, ids=None):
    t = np.arange(n)
    base = {"dt": pd.date_range("2025-01-01", periods=n, freq="h"),
            "value": np.sin(t * 0.3) + 0.05 * np.cos(t * 1.7),
            "extra": np.cos(t * 0.3)}
    if ids is None:
        return pd.DataFrame(base)
    dfs = []
    for i in ids:
        d = pd.DataFrame(base)
        d["id"] = i
        dfs.append(d)
    return pd.concat(dfs, ignore_index=True)


class TestTSDataset:
    def test_roll_shapes(self):
        ts = TSDataset.from_pandas(_sine_df(100), dt_col="dt",
                                   target_col="value",
                                   extra_feature_col="extra")
        x, y = ts.roll(lookback=12, horizon=3).to_numpy()
        assert x.shape == (100 - 12 - 3 + 1, 12, 2)
        assert y.shape == (100 - 12 - 3 + 1, 3, 1)

    def test_multi_id_roll_no_leakage(self):
        ts = TSDataset.from_pandas(_sine_df(50, ids=["a", "b"]),
                                   dt_col="dt", target_col="value",
                                   extra_feature_col="extra", id_col="id")
        x, y = ts.roll(lookback=10, horizon=2).to_numpy()
        # windows never cross id boundaries: (50-10-2+1) per id
        assert x.shape[0] == 2 * 39

    def test_impute_modes(self):
        df = _sine_df(30)
        df.loc[5, "value"] = np.nan
        df.loc[0, "extra"] = np.nan
        ts = TSDataset.from_pandas(df, "dt", "value", "extra")
        ts.impute("linear")
        assert not ts.df[["value", "extra"]].isna().any().any()

    def test_scale_roundtrip(self):
        ts = TSDataset.from_pandas(_sine_df(60), "dt", "value", "extra")
        orig = ts.df["value"].to_numpy().copy()
        ts.scale()
        assert abs(ts.df["value"].mean()) < 1e-6
        ts.unscale()
        np.testing.assert_allclose(ts.df["value"].to_numpy(), orig,
                                   atol=1e-9)

    def test_unscale_numpy_inverts_targets(self):
        ts = TSDataset.from_pandas(_sine_df(80), "dt", "value", "extra")
        ts.scale().roll(lookback=8, horizon=2)
        _, y = ts.to_numpy()
        y_un = ts.unscale_numpy(y)
        ts2 = TSDataset.from_pandas(_sine_df(80), "dt", "value", "extra")
        x2, y2 = ts2.roll(lookback=8, horizon=2).to_numpy()
        np.testing.assert_allclose(y_un, y2, atol=1e-5)

    def test_split_and_dt_features(self):
        tr, va, te = TSDataset.from_pandas(
            _sine_df(100), "dt", "value", with_split=True,
            val_ratio=0.2, test_ratio=0.2)
        assert len(tr.df) == 60 and len(va.df) == 20 and len(te.df) == 20
        tr.gen_dt_feature(["HOUR", "IS_WEEKEND"])
        assert "HOUR(dt)" in tr.feature_cols


class TestForecasters:
    @pytest.mark.parametrize("cls,kwargs", [
        (TCNForecaster, dict(num_channels=(16, 16))),
        (Seq2SeqForecaster, dict(lstm_hidden_dim=32)),
        (LSTMForecaster, dict(hidden_dim=32, future_seq_len=4)),
    ])
    def test_fit_improves_and_beats_persistence(self, cls, kwargs):
        ts = TSDataset.from_pandas(_sine_df(400), "dt", "value")
        x, y = ts.roll(lookback=24, horizon=4).to_numpy()
        f = cls(past_seq_len=24, future_seq_len=4, input_feature_num=1,
                output_feature_num=1, lr=5e-3, **{
                    k: v for k, v in kwargs.items()
                    if k != "future_seq_len"})
        f.fit((x, y), epochs=10, batch_size=32)
        mse = f.evaluate((x, y), metrics=["mse"])[0]
        persistence = float(np.mean((y - x[:, -1:, :1]) ** 2))
        assert mse < persistence, (mse, persistence)
        pred = f.predict(x[:5])
        assert pred.shape == (5, 4, 1)

    def test_nbeats_univariate(self):
        ts = TSDataset.from_pandas(_sine_df(300), "dt", "value")
        x, y = ts.roll(lookback=16, horizon=2).to_numpy()
        f = NBeatsForecaster(past_seq_len=16, future_seq_len=2,
                             nbeats_units=32, num_blocks=2, lr=5e-3)
        f.fit((x, y), epochs=10, batch_size=32)
        mse = f.evaluate((x, y), metrics=["mse", "smape"])[0]
        assert mse < 0.05, mse

    def test_save_load_roundtrip(self, tmp_path):
        ts = TSDataset.from_pandas(_sine_df(200), "dt", "value")
        x, y = ts.roll(lookback=12, horizon=2).to_numpy()
        f = LSTMForecaster(past_seq_len=12, input_feature_num=1,
                           output_feature_num=1, future_seq_len=2,
                           hidden_dim=16)
        f.fit((x, y), epochs=3)
        p1 = f.predict(x[:3])
        path = str(tmp_path / "model.bin")
        f.save(path)
        g = LSTMForecaster(past_seq_len=12, input_feature_num=1,
                           output_feature_num=1, future_seq_len=2,
                           hidden_dim=16)
        g.load(path)
        np.testing.assert_allclose(p1, g.predict(x[:3]), atol=1e-6)


class TestDetectors:
    def test_threshold_detector(self):
        rs = np.random.RandomState(0)
        y = np.sin(np.arange(500) * 0.1) + rs.randn(500) * 0.05
        y_pred = np.sin(np.arange(500) * 0.1)
        y[100] += 3.0
        y[400] -= 3.0
        d = ThresholdDetector().set_params(ratio=0.02)
        d.fit(np.delete(y, [100, 400]), np.delete(y_pred, [100, 400]))
        idx = d.anomaly_indexes(y, y_pred)
        assert 100 in idx and 400 in idx
        assert len(idx) < 30

    def test_ae_detector(self):
        rs = np.random.RandomState(1)
        y = np.sin(np.arange(400) * 0.2) + rs.randn(400) * 0.02
        y[200:204] += 2.5
        d = AEDetector(roll_len=16, ratio=0.05, epochs=60)
        d.fit(y)
        idx = d.anomaly_indexes(y)
        assert any(195 <= i <= 210 for i in idx)

    def test_dbscan_detector(self):
        y = np.concatenate([np.zeros(100), [10.0], np.zeros(100)])
        from bigdl_tpu.chronos.detector import DBScanDetector
        idx = DBScanDetector(eps=0.5, min_samples=5).anomaly_indexes(y)
        assert 100 in idx


class TestAutoformer:
    def test_fit_predict_beats_naive(self):
        from bigdl_tpu.chronos.forecaster import AutoformerForecaster

        rs = np.random.RandomState(0)
        t = np.arange(600, dtype=np.float32)
        series = np.sin(2 * np.pi * t / 24) + 0.05 * rs.randn(600)
        L, H = 48, 8
        xs = np.stack([series[i:i + L] for i in range(500)])[..., None]
        ys = np.stack([series[i + L:i + L + H]
                       for i in range(500)])[..., None]
        f = AutoformerForecaster(L, H, 1, 1, d_model=16, lr=3e-3)
        f.fit((xs[:400], ys[:400]), epochs=8, batch_size=64)
        pred = f.predict(xs[400:])
        mse = float(np.mean((pred - ys[400:]) ** 2))
        naive = float(np.mean((xs[400:, -1:, :] - ys[400:]) ** 2))
        assert pred.shape == (100, H, 1)
        assert mse < naive, (mse, naive)

    def test_series_decomp_recombines(self):
        from bigdl_tpu.chronos.forecaster.autoformer import _series_decomp
        import jax.numpy as jnp
        x = jnp.asarray(np.random.RandomState(1).randn(2, 32, 3),
                        jnp.float32)
        seas, trend = _series_decomp(x, 7)
        np.testing.assert_allclose(np.asarray(seas + trend),
                                   np.asarray(x), rtol=1e-5, atol=1e-5)


class TestDPGANSimulator:
    def test_fit_generate_shapes_and_stats(self):
        from bigdl_tpu.chronos.simulator import DPGANSimulator

        rs = np.random.RandomState(0)
        phase = rs.rand(256, 1, 1) * 2 * np.pi
        t = np.arange(24)[None, :, None]
        data = np.sin(2 * np.pi * t / 12 + phase).astype(np.float32) * 2.0
        sim = DPGANSimulator(seq_len=24, feature_num=1, seed=0)
        sim.fit(data, epochs=60, batch_size=64)
        out = sim.generate(32, seed=1)
        assert out.shape == (32, 24, 1)
        assert np.isfinite(out).all()
        # samples live in the data's scale, not at tanh saturation
        assert np.abs(out).max() <= 2.0 * 2.5 + 1e-3
        assert out.std() > 0.1

    def test_dp_mode_trains(self):
        from bigdl_tpu.chronos.simulator import DPGANSimulator

        data = np.sin(np.arange(16))[None].repeat(64, 0)[..., None] \
            .astype(np.float32)
        sim = DPGANSimulator(seq_len=16, feature_num=1, dp=True, seed=0)
        sim.fit(data, epochs=5, batch_size=16)
        assert len(sim.history) == 5
        assert all(np.isfinite(v) for pair in sim.history for v in pair)
        out = sim.generate(4)
        assert out.shape == (4, 16, 1) and np.isfinite(out).all()

"""Unified mixed prefill+decode dispatch (ISSUE 14): greedy bit-parity
vs the SPLIT engine and the plain ``generate`` golden across pipeline
depths × prefix cache on/off × chunked/unchunked admissions, COW
correctness when a chunked admission forks a radix tail while another
row live-decodes against the same prefix, the O(suffix-buckets)
compile-grid invariant over a mixed-prefix replay, the
shed-during-chunking ledger rollback, the ``llm.chunk`` fault contract,
the dense-escape-hatch interaction and the disabled-mode structural
absence of the gate.
"""

import numpy as np
import pytest

from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import LLMServer

pytestmark = pytest.mark.mixed

PAGE = 8
CHUNK = 8         # one page per chunk: every long prompt really chunks


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=128)


def _generate(model, p, n):
    return list(map(int, model.generate(
        np.asarray(p)[None], max_new_tokens=n)[0, len(p):]))


def _serve(model, prompts, lens, *, mixed, chunk_tokens=CHUNK,
           replay=1, max_seq_len=64, num_pages=None, **kw):
    srv = LLMServer(model, max_batch=2, max_seq_len=max_seq_len,
                    page_size=PAGE, ragged_prefill=True, mixed=mixed,
                    chunk_tokens=chunk_tokens, num_pages=num_pages,
                    **kw).start()
    try:
        for _ in range(replay):
            got = [list(map(int, r.get(timeout=600))) for r in
                   [srv.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, lens)]]
        return got, srv
    finally:
        srv.stop()


def _workload():
    """Long prompts (chunked at CHUNK=8) + short ones (unchunked),
    sharing a prefix so the cache-on matrix exercises adoption."""
    rs = np.random.RandomState(14)
    shared = rs.randint(0, 250, 20).astype(np.int32)     # 2.5 pages
    prompts = [np.concatenate(
        [shared, rs.randint(0, 250, 11 + 4 * j).astype(np.int32)])
        for j in range(3)]                               # 31/35/39 toks
    prompts.append(rs.randint(0, 250, 26).astype(np.int32))  # disjoint
    prompts.append(rs.randint(0, 250, 6).astype(np.int32))   # short
    return prompts, [4, 3, 5, 4, 4]


# goldens + the split-engine reference, computed once per cache mode
# (the split engine's own parity vs generate is PR 8's proven matrix)
_REF_CACHE = {}


def _references(model, kvcache):
    if kvcache not in _REF_CACHE:
        prompts, lens = _workload()
        golden = [_generate(model, p, n) for p, n in zip(prompts, lens)]
        split, srv = _serve(model, prompts, lens, mixed=False,
                            replay=2, kvcache=kvcache, pipeline_depth=1)
        assert srv.prefill_chunks_total == 0     # split never chunks
        _REF_CACHE[kvcache] = (golden, split)
    return _REF_CACHE[kvcache]


class TestEngineParity:
    """The acceptance matrix: unified outputs must be bit-identical to
    the split engine AND the generate golden, with chunking genuinely
    engaged (chunked) or genuinely absent (unchunked)."""

    @pytest.mark.parametrize("kvcache,depth", [
        pytest.param(True, 1), pytest.param(True, 2),
        pytest.param(True, 4), pytest.param(False, 1),
        pytest.param(False, 2), pytest.param(False, 4)])
    def test_chunked_parity_vs_split_and_golden(self, model, depth,
                                                kvcache):
        prompts, lens = _workload()
        want, split = _references(model, kvcache)
        got, srv = _serve(model, prompts, lens, mixed=True, replay=2,
                          kvcache=kvcache, pipeline_depth=depth)
        for j, (g, s, w) in enumerate(zip(got, split, want)):
            assert g == s, f"request {j}: unified vs split diverged"
            assert g == w, f"request {j}: unified vs golden diverged"
        assert srv.prefill_chunks_total > 0      # chunking engaged
        if kvcache:
            assert srv._kv.hits > 0
            # chunks fused with live decode rows actually happened
            assert srv.mixed_passes > 0

    def test_unchunked_gate_on_parity(self, model):
        """mixed ON but chunk_tokens above every suffix: the unified
        engine must route every admission through the split paths
        (zero chunks) and stay bit-identical."""
        prompts, lens = _workload()
        want, _split = _references(model, True)
        got, srv = _serve(model, prompts, lens, mixed=True,
                          chunk_tokens=64, kvcache=True,
                          pipeline_depth=2)
        assert got == want
        assert srv.prefill_chunks_total == 0
        assert srv.mixed_passes == 0

    # one facade family in tier-1 guards the hand-written NeoX mixed
    # composition (parallel residual, partial rotary); StarCoder (MQA,
    # learned wpe) rides the slow suite — same structure
    @pytest.mark.parametrize("family", [
        "gptneox", pytest.param("starcoder", marks=pytest.mark.slow)])
    def test_family_chunked_parity(self, family):
        if family == "gptneox":
            from bigdl_tpu.llm.models.gptneox import (
                GptNeoXConfig as C, GptNeoXForCausalLM as M)
        else:
            from bigdl_tpu.llm.models.starcoder import (
                StarCoderConfig as C, StarCoderForCausalLM as M)
        fam_model = M.from_config(C.tiny(), seed=0, max_cache_len=64)
        rs = np.random.RandomState(6)
        prompts = [rs.randint(0, 250, 26).astype(np.int32),
                   rs.randint(0, 250, 7).astype(np.int32)]
        lens = [4, 6]
        want = [_generate(fam_model, p, n)
                for p, n in zip(prompts, lens)]
        got, srv = _serve(fam_model, prompts, lens, mixed=True,
                          kvcache=True, pipeline_depth=2,
                          max_seq_len=48)
        assert got == want
        assert srv.prefill_chunks_total > 0

    def test_tier_prepaid_chunked_parity(self, model):
        """A host-tier admission (budget fully pre-charged at admit)
        whose landed suffix is still long chunk-DISPATCHES without
        touching the ledger again (the prepaid path) and stays
        bit-identical."""
        from bigdl_tpu.utils.conf import conf
        rs = np.random.RandomState(11)
        groups = [rs.randint(0, 250, 16).astype(np.int32)
                  for _ in range(4)]
        prompts = [np.concatenate(
            [groups[j % 4],
             rs.randint(0, 250, 10 + j % 3).astype(np.int32)])
            for j in range(8)]
        lens = [int(rs.randint(1, 5)) for _ in prompts]
        want = [_generate(model, p, n) for p, n in zip(prompts, lens)]
        conf.set("bigdl.llm.kvtier.sync", "true")
        try:
            got, srv = _serve(model, prompts, lens, mixed=True,
                              num_pages=11, kvcache=True, kvtier=True,
                              host_pages=32)
            assert srv._tier.spills > 0 and srv._tier.fetches > 0
        finally:
            conf.unset("bigdl.llm.kvtier.sync")
        assert got == want
        assert srv.prefill_chunks_total > 0

    def test_cow_fork_across_chunks_with_live_decode_row(self, model):
        """A chunked admission adopts a radix prefix whose tail page it
        must COW-fork at its FIRST chunk, while another request is
        live-decoding against the same shared pages: both streams must
        stay bit-identical to their goldens."""
        rs = np.random.RandomState(5)
        P = rs.randint(0, 250, 20).astype(np.int32)        # 2.5 pages
        B = np.concatenate([P, rs.randint(0, 250, 18).astype(np.int32)])
        want_a = _generate(model, P, 4)
        want_c = _generate(model, P, 24)
        want_b = _generate(model, B, 4)
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, ragged_prefill=True, mixed=True,
                        chunk_tokens=CHUNK, kvcache=True,
                        pipeline_depth=2).start()
        try:
            # A indexes P (+ its output tail page) at EOS
            ra = srv.submit(P, max_new_tokens=4)
            assert list(map(int, ra.get(timeout=600))) == want_a
            # C adopts the chain and keeps decoding while B arrives
            rc = srv.submit(P, max_new_tokens=24)
            while len(rc.tokens) < 2:
                pass
            rb = srv.submit(B, max_new_tokens=4)
            assert list(map(int, rb.get(timeout=600))) == want_b
            assert list(map(int, rc.get(timeout=600))) == want_c
            assert srv.prefill_chunks_total > 0    # B really chunked
            assert srv._kv.hits >= 2               # C and B both hit
        finally:
            srv.stop()


class TestChunkLedger:
    def test_shed_during_chunking_rolls_back_cleanly(self, model):
        """A chunked admission that cannot charge its next chunk within
        chunk_wait is SHED: every page and ledger charge of the partial
        chain returns, the request fails retriably, and a resubmission
        after pressure clears is bit-identical to the golden."""
        rs = np.random.RandomState(7)
        a_prompt = rs.randint(0, 250, 8).astype(np.int32)
        b_prompt = rs.randint(0, 250, 32).astype(np.int32)
        want_b = _generate(model, b_prompt, 8)
        # pool of 9 budget pages: A (prompt 8 + 40 new) charges 6, so B
        # (needs 5) admits its first chunks but stalls at the decode
        # top-up and must shed while A is still decoding
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, num_pages=10, kvcache=False,
                        ragged_prefill=True, mixed=True,
                        chunk_tokens=CHUNK, chunk_wait=0.01,
                        pipeline_depth=2).start()
        try:
            ra = srv.submit(a_prompt, max_new_tokens=40)
            rb = srv.submit(b_prompt, max_new_tokens=8)
            with pytest.raises(RuntimeError) as ei:
                rb.get(timeout=600)
            assert "retriable" in str(ei.value)
            assert "starved" in str(ei.value)
            # the partial chain's budget comes back at the next
            # in-flight fence (the deferred-release contract — pages a
            # live step may still read are never freed early): poll
            # briefly, then only A's charge may remain
            import time
            deadline = time.time() + 5
            while srv._budget_avail != 3 and time.time() < deadline:
                time.sleep(0.005)
            assert srv._budget_avail == 9 - 6
            assert ra.get(timeout=600) is not None
            # pressure gone: the resubmission chunks through unharmed
            rb2 = srv.submit(b_prompt, max_new_tokens=8)
            assert list(map(int, rb2.get(timeout=600))) == want_b
        finally:
            srv.stop()
        assert srv._budget_avail == 9          # idle ledger balanced
        assert srv.pages_in_use == 0

    def test_chunk_fault_rolls_back_and_retries_identically(self, model):
        """The llm.chunk fault site: a raise between chunks frees the
        partial chain, fails the request retriably, and the resubmitted
        request is bit-identical (the chaos_check --mixed contract,
        tier-1 sized)."""
        from bigdl_tpu import reliability as rel
        rs = np.random.RandomState(9)
        prompt = rs.randint(0, 250, 30).astype(np.int32)
        want = _generate(model, prompt, 4)
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, num_pages=24, kvcache=True,
                        ragged_prefill=True, mixed=True,
                        chunk_tokens=CHUNK, pipeline_depth=2).start()
        was = rel.enabled()
        if not was:
            rel.enable()
        try:
            plan = rel.FaultPlan(seed=0)
            plan.add("llm.chunk", "raise", times=1)
            rel.set_plan(plan)
            try:
                req = srv.submit(prompt, max_new_tokens=4)
                with pytest.raises(RuntimeError) as ei:
                    req.get(timeout=600)
                assert "retriable" in str(ei.value)
            finally:
                rel.set_plan(None)
            assert ("llm.chunk", "raise") in plan.fired
            retry = srv.submit(prompt, max_new_tokens=4)
            assert list(map(int, retry.get(timeout=600))) == want
        finally:
            if not was:
                rel.disable()
            srv.stop()
        assert srv._budget_avail == 23         # idle ledger balanced


class TestCompileGrid:
    def test_mixed_replay_compiles_zero_new_programs(self, model):
        """The unified step's compile grid is O(suffix-buckets): chunk
        sizes come from the same pow2 ladder as the ragged prefill, and
        offsets/tables/targets are runtime data — so a mixed-prefix
        replay (same chunk bucket, different prefix lengths and radix
        offsets) adds ZERO new programs once the buckets are warm
        (the PR 8 compile-recorder pattern)."""
        from bigdl_tpu import observability as obs
        from bigdl_tpu.llm import serving as sv
        rs = np.random.RandomState(42)
        chains = [rs.randint(0, 250, PAGE * (1 + j)).astype(np.int32)
                  for j in range(3)]

        def tails(seed):
            r2 = np.random.RandomState(seed)
            return [np.concatenate(
                [c, r2.randint(0, 250, 9 + r2.randint(0, 8))
                 .astype(np.int32)]) for c in chains]

        def keys(tag):
            return {k for k in sv._PAGED_STEP_CACHE if tag in k}

        def compiles(fn_name):
            return sum(s["compiles"] for s in obs.compile_stats()
                       if s["fn"] == fn_name)

        was = obs.enabled()
        obs.enable()
        mixed_before = keys("mixed")
        srv = LLMServer(model, max_batch=2, max_seq_len=96,
                        page_size=PAGE, num_pages=64, kvcache=True,
                        ragged_prefill=True, mixed=True,
                        chunk_tokens=CHUNK, pipeline_depth=2).start()
        try:
            # a long-running decode row keeps passes FUSED (the mixed
            # program, not just the solo ragged-chunk route)
            stream = srv.submit(rs.randint(0, 250, 6).astype(np.int32),
                                max_new_tokens=80)
            for p in list(chains) + tails(0):
                srv.submit(p, max_new_tokens=2).get(timeout=600)
            assert srv.mixed_passes > 0
            warm_keys = keys("mixed")
            warm_ragged = keys("prefill_ragged")
            warm_compiles = compiles("llm/step_mixed")
            # mixed-prefix replay: every chain length again, new tails,
            # shifting radix offsets — zero new programs allowed
            for seed in (1, 2, 3):
                for p in tails(seed):
                    srv.submit(p, max_new_tokens=2).get(timeout=600)
            assert keys("mixed") == warm_keys
            assert keys("prefill_ragged") == warm_ragged
            assert compiles("llm/step_mixed") == warm_compiles
            # the whole mixed grid is the chunk-bucket ladder: every
            # chunk here is <= CHUNK tokens -> ONE pow2 bucket
            assert len(warm_keys - mixed_before) <= 1
            stream.get(timeout=600)
        finally:
            srv.stop()
            if not was:
                obs.disable()


class TestGateAbsence:
    def test_disabled_mode_structural_absence(self, model):
        """``bigdl.llm.mixed.enabled`` defaults off and
        ``bigdl.llm.prefill.chunk_tokens`` is only read behind it: the
        default engine must be structurally split — no chunk state, no
        chunk dispatches, and none of the
        ``bigdl_llm_pass_rows_total`` / ``bigdl_llm_prefill_chunks_total``
        / ``bigdl_llm_pass_mix`` series even with observability on."""
        from bigdl_tpu import observability as obs
        rs = np.random.RandomState(3)
        prompts = [rs.randint(0, 250, 26).astype(np.int32),
                   rs.randint(0, 250, 7).astype(np.int32)]
        series_names = ("bigdl_llm_pass_rows_total",
                        "bigdl_llm_prefill_chunks_total",
                        "bigdl_llm_pass_mix")

        def samples(text, name):
            return sorted(l for l in text.splitlines()
                          if l.startswith(name + "{")
                          or l.startswith(name + " "))

        was = obs.enabled()
        obs.enable()
        try:
            before = obs.render()   # the registry is process-global:
            # other tests may have minted the series — the absence
            # contract here is a ZERO DELTA from this server
            srv = LLMServer(model, max_batch=2, max_seq_len=64,
                            page_size=PAGE, ragged_prefill=True,
                            kvcache=True).start()
            try:
                assert srv._mixed is False
                assert srv._mixed_active is False
                assert srv._chunk_state is None
                for p in prompts:
                    srv.submit(p, max_new_tokens=3).get(timeout=600)
                assert srv.prefill_chunks_total == 0
                assert srv.mixed_passes == 0
            finally:
                srv.stop()
            after = obs.render()
            for series in series_names:
                assert samples(after, series) == samples(before, series)
        finally:
            if not was:
                obs.disable()

    def test_dense_escape_hatch_forces_unchunked(self, model):
        """Chunking requires the ragged in-place prefill: under the
        ``bigdl.llm.prefill.ragged=false`` escape hatch the mixed gate
        is INERT (documented in docs/PERFORMANCE.md) — admissions
        prefill whole through the dense split paths and outputs stay
        correct."""
        rs = np.random.RandomState(4)
        prompt = rs.randint(0, 250, 26).astype(np.int32)
        want = _generate(model, prompt, 4)
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, ragged_prefill=False,
                        mixed=True, chunk_tokens=CHUNK,
                        kvcache=True).start()
        try:
            assert srv._mixed is True
            assert srv._mixed_active is False      # ragged off: inert
            got = list(map(int,
                           srv.submit(prompt, max_new_tokens=4)
                           .get(timeout=600)))
            assert got == want
            assert srv.prefill_chunks_total == 0
            assert srv.prefill_dense_staged_tokens > 0
        finally:
            srv.stop()

    def test_mixed_rejects_slot_static_engine(self, model):
        with pytest.raises(ValueError):
            LLMServer(model, max_batch=2, max_seq_len=32, paged=False,
                      mixed=True)

"""On-device sampling kernel edge cases (ISSUE 5 satellite).

llm/kernels/sampling.py was folded into every compiled decode step in
PR 4 and partial prefill (ISSUE 5) changes its call sites again — these
tests lock the kernel's boundary behaviors so those refactors cannot
silently shift sampling semantics:

- ``top_k >= vocab`` must be a no-op filter (identical draws to
  unfiltered sampling under the same key);
- ``top_k == 1`` must equal greedy argmax for ANY key (one unmasked
  logit survives);
- ``temperature ~ 0`` must stay numerically stable (the 1e-6 floor) and
  behave like argmax, never NaN.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.llm.kernels.sampling import (fence_token, make_sampled_step,
                                            sample_tokens)

VOCAB = 32


@pytest.fixture()
def logits(rng):
    return jnp.asarray(rng.randn(4, VOCAB).astype(np.float32))


class TestSampleTokens:
    def test_greedy_is_argmax(self, logits):
        toks = sample_tokens(logits, jax.random.PRNGKey(0))
        np.testing.assert_array_equal(
            np.asarray(toks), np.asarray(jnp.argmax(logits, -1)))
        assert toks.dtype == jnp.int32

    def test_top_k_geq_vocab_matches_unfiltered(self, logits):
        """The kth threshold is the global min when k >= vocab: masking
        removes nothing and the categorical draw must be bit-identical
        to top_k=0 under the same key."""
        key = jax.random.PRNGKey(7)
        for k in (VOCAB, VOCAB + 1, 10 * VOCAB):
            a = sample_tokens(logits, key, do_sample=True,
                              temperature=jnp.float32(0.8), top_k=k)
            b = sample_tokens(logits, key, do_sample=True,
                              temperature=jnp.float32(0.8), top_k=0)
            np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_top_k_1_equals_greedy_for_any_key(self, logits):
        """With one surviving logit the categorical is deterministic:
        every key must reproduce the greedy argmax."""
        want = np.asarray(jnp.argmax(logits, -1))
        for seed in range(5):
            got = sample_tokens(logits, jax.random.PRNGKey(seed),
                                do_sample=True,
                                temperature=jnp.float32(1.3), top_k=1)
            np.testing.assert_array_equal(np.asarray(got), want)

    @pytest.mark.parametrize("temp", [1e-9, 1e-6, 1e-4])
    def test_near_zero_temperature_is_stable_argmax(self, logits, temp):
        """temperature -> 0 sharpens to a point mass; the 1e-6 floor
        keeps the division finite, so the draw is the argmax — never a
        NaN-poisoned arbitrary index."""
        got = sample_tokens(logits, jax.random.PRNGKey(3),
                            do_sample=True,
                            temperature=jnp.float32(temp), top_k=0)
        arr = np.asarray(got)
        assert not np.any(np.isnan(arr.astype(np.float64)))
        np.testing.assert_array_equal(
            arr, np.asarray(jnp.argmax(logits, -1)))

    def test_temperature_is_runtime_not_trace_constant(self, logits):
        """Serving tunes temperature without a recompile: the jitted
        kernel must accept it as a traced scalar."""
        fn = jax.jit(lambda lg, key, t: sample_tokens(
            lg, key, do_sample=True, temperature=t, top_k=2))
        key = jax.random.PRNGKey(0)
        a = fn(logits, key, jnp.float32(0.7))
        b = fn(logits, key, jnp.float32(1.9))   # same compile, new temp
        assert a.shape == b.shape == (4,)


class TestFenceToken:
    def test_fence_depends_on_all_inputs_and_is_finite(self):
        out = fence_token(jnp.full((3,), jnp.inf),
                          jnp.array([np.nan, 1.0]),
                          jnp.array([2], jnp.int32))
        arr = np.asarray(out)
        assert arr.shape == (1,) and arr.dtype == np.int32

    def test_sampled_step_emits_fence_element(self):
        """The lifted step returns (B+1,) ids — B samples + the fence —
        and masks inactive rows to the trash page."""
        seen = {}

        def fam_step(params, cfg, kp, vp, bt, lens, toks, *, page):
            seen["bt"] = bt
            seen["lens"] = lens
            b = toks.shape[0]
            logits = jnp.zeros((b, VOCAB), jnp.float32)
            return logits, kp, vp

        step = make_sampled_step(fam_step)
        b = 2
        kp = vp = jnp.zeros((1, 2, 1, 4, 2), jnp.float32)
        bt = jnp.ones((b, 2), jnp.int32)
        lens = jnp.array([3, 5], jnp.int32)
        last = jnp.asarray(np.eye(b, VOCAB, dtype=np.float32))
        active = jnp.array([True, False])
        out, logits, kp, vp, new_lens, key = step(
            {}, None, kp, vp, bt, lens, last, active, jnp.float32(1.0),
            jax.random.PRNGKey(0), page=4)
        assert out.shape == (b + 1,)
        np.testing.assert_array_equal(np.asarray(out[:b]), [0, 1])
        # inactive rows: trash block table + zero length + no advance
        np.testing.assert_array_equal(np.asarray(seen["bt"]),
                                      [[1, 1], [0, 0]])
        np.testing.assert_array_equal(np.asarray(seen["lens"]), [3, 0])
        np.testing.assert_array_equal(np.asarray(new_lens), [4, 5])

"""Static-analysis suite tests (ISSUE 11 + 13): fixture-based per-rule
checks for each pass (known-bad snippets fire, known-good don't), the
def-use dataflow layer, the baseline round-trip, the lockwatch runtime
witness, the CLI exit-code contract (incl. --only/--sarif), the
no-jax-import + runtime-budget property, and the tier-1 repo gate
(zero unbaselined findings across all six passes)."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from bigdl_tpu import analysis
from bigdl_tpu.analysis import lockwatch
from bigdl_tpu.analysis.baseline import Baseline
from bigdl_tpu.analysis.concurrency import (lock_graph,
                                            run_concurrency_pass)
from bigdl_tpu.analysis.core import Finding, ProjectIndex
from bigdl_tpu.analysis.donation import run_donation_pass
from bigdl_tpu.analysis.gatecheck import run_gatecheck_pass
from bigdl_tpu.analysis.hotpath import run_hotpath_pass
from bigdl_tpu.analysis.httpdrift import run_httpdrift_pass
from bigdl_tpu.analysis.registrydrift import run_registry_pass

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_tree(tmp_path, files, subdirs=("bigdl_tpu",)):
    """Write {relpath: source} under tmp_path/bigdl_tpu (paths with a
    leading "tests/"/"tools/" land at the tree root) and index it."""
    roots = set()
    for rel, src in files.items():
        if rel.startswith(("tests/", "tools/", "examples/")):
            path = tmp_path / rel
            roots.add(rel.split("/", 1)[0])
        else:
            path = tmp_path / "bigdl_tpu" / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    subdirs = tuple(subdirs) + tuple(sorted(roots - set(subdirs)))
    return ProjectIndex.scan(str(tmp_path), subdirs)


def rules_fired(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# concurrency pass fixtures
# ---------------------------------------------------------------------------

BAD_LOCK_ORDER = '''
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
'''

GOOD_LOCK_ORDER = BAD_LOCK_ORDER.replace(
    "with self._b:\n            with self._a:",
    "with self._a:\n            with self._b:")

#: the cycle hides behind a call: two() holds b and CALLS a helper
#: that takes a — only the transitive edge sees it
BAD_LOCK_ORDER_INDIRECT = '''
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def _take_a(self):
        with self._a:
            pass

    def two(self):
        with self._b:
            self._take_a()
'''

BAD_UNLOCKED_WRITE = '''
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        self.count += 1

    def bump(self):
        self.count += 1

    def stop(self):
        self._thread.join()
'''

GOOD_LOCKED_WRITE = BAD_UNLOCKED_WRITE.replace(
    "    def _loop(self):\n        self.count += 1",
    "    def _loop(self):\n        with self._lock:\n"
    "            self.count += 1").replace(
    "    def bump(self):\n        self.count += 1",
    "    def bump(self):\n        with self._lock:\n"
    "            self.count += 1")

BAD_THREAD_NO_JOIN = '''
import threading

def fire():
    threading.Thread(target=print, daemon=True).start()
'''

GOOD_THREAD_JOINED = '''
import threading

class S:
    def start(self):
        self._thread = threading.Thread(target=print, daemon=True)
        self._thread.start()

    def stop(self):
        self._thread.join()
'''

BAD_BARE_ACQUIRE = '''
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def risky(self):
        self._lock.acquire()
        do_work()
        self._lock.release()
'''

GOOD_ACQUIRE_FINALLY = '''
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def safe(self):
        self._lock.acquire()
        try:
            do_work()
        finally:
            self._lock.release()
'''


class TestConcurrencyPass:
    def test_lock_order_inversion_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_LOCK_ORDER})
        hits = rules_fired(run_concurrency_pass(idx), "lock-order")
        assert len(hits) == 1
        assert "S._a" in hits[0].key and "S._b" in hits[0].key

    def test_consistent_order_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_LOCK_ORDER})
        assert rules_fired(run_concurrency_pass(idx), "lock-order") == []

    def test_lock_order_through_call_graph(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_LOCK_ORDER_INDIRECT})
        hits = rules_fired(run_concurrency_pass(idx), "lock-order")
        assert len(hits) == 1

    def test_unlocked_write_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_UNLOCKED_WRITE})
        hits = rules_fired(run_concurrency_pass(idx), "unlocked-write")
        assert [h.key for h in hits] == ["S.count"]

    def test_locked_write_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_LOCKED_WRITE})
        assert rules_fired(run_concurrency_pass(idx),
                           "unlocked-write") == []

    def test_init_writes_exempt(self, tmp_path):
        # the __init__ assignment of count never counts as a race side
        idx = make_tree(tmp_path, {"mod.py": GOOD_LOCKED_WRITE})
        findings = run_concurrency_pass(idx)
        assert all("__init__" not in f.message for f in findings)

    def test_thread_no_join_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_THREAD_NO_JOIN})
        assert len(rules_fired(run_concurrency_pass(idx),
                               "thread-no-join")) == 1

    def test_joined_thread_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_THREAD_JOINED})
        assert rules_fired(run_concurrency_pass(idx),
                           "thread-no-join") == []

    def test_bare_acquire_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_BARE_ACQUIRE})
        assert len(rules_fired(run_concurrency_pass(idx),
                               "bare-acquire")) == 1

    def test_acquire_with_finally_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_ACQUIRE_FINALLY})
        assert rules_fired(run_concurrency_pass(idx),
                           "bare-acquire") == []

    def test_lock_graph_names_sites(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_LOCK_ORDER})
        graph = lock_graph(idx)
        assert any("S._a" in k for k in graph)


# ---------------------------------------------------------------------------
# hot-path pass fixtures
# ---------------------------------------------------------------------------

HOT_SYNCS = '''
import jax
import numpy as np

class Engine:
    def _loop(self):
        while True:
            self._tick()

    def _tick(self):
        x = self._step()
        n = x.item()
        host = np.asarray(x)
        jax.block_until_ready(x)
        flag = float(x)
        return n, host, flag

    def _step(self):
        return 1
'''

HOT_CLEAN = '''
import jax
import jax.numpy as jnp

class Engine:
    def _loop(self):
        while True:
            self._tick()

    def _tick(self):
        dev = jnp.asarray([1, 2])      # host->device: async, allowed
        return dev

    def unreachable_sync(self, x):
        return x.item()                # NOT reachable from _loop
'''

BAD_COMPILED = '''
from bigdl_tpu import observability as obs

class Model:
    def _build(self):
        def step(params, x, flag):
            if flag:                    # traced-branch
                return params
            return self.scale * x       # compiled-self-ref
        return obs.compiled(step, name="m/step")
'''

GOOD_COMPILED = '''
from bigdl_tpu import observability as obs

class Model:
    def _build(self):
        cfg = self.cfg                  # the blessed idiom
        def step(params, x):
            return params + x * cfg.scale
        return obs.compiled(step, name="m/step")
'''

ROOTS = (("bigdl_tpu/mod.py", "Engine", "_loop"),)


class TestHotPathPass:
    def test_sync_rules_fire(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": HOT_SYNCS})
        findings = run_hotpath_pass(idx, roots=ROOTS)
        assert len(rules_fired(findings, "host-sync-item")) == 1
        # np.asarray + block_until_ready
        assert len(rules_fired(findings, "host-sync-transfer")) == 2
        assert len(rules_fired(findings, "host-sync-cast")) == 1

    def test_upload_and_unreachable_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": HOT_CLEAN})
        findings = run_hotpath_pass(idx, roots=ROOTS)
        # jnp.asarray is a host->device upload, not a sync; and the
        # .item() lives in a function the engine loop never reaches
        assert findings == []

    def test_compiled_fn_hazards_fire(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_COMPILED})
        findings = run_hotpath_pass(idx, roots=ROOTS)
        assert len(rules_fired(findings, "traced-branch")) == 1
        assert len(rules_fired(findings, "compiled-self-ref")) == 1

    def test_compiled_fn_good_idiom_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_COMPILED})
        assert run_hotpath_pass(idx, roots=ROOTS) == []


# ---------------------------------------------------------------------------
# registry-drift pass fixtures
# ---------------------------------------------------------------------------

REGISTRY_FIXTURE = '''
from bigdl_tpu.utils.conf import conf
from bigdl_tpu import observability as obs
from bigdl_tpu import reliability

def f():
    conf.get_bool("bigdl.nosuch.key", False)          # unregistered
    conf.get_int("bigdl.llm.pipeline_depth", 2)       # registered
    obs.counter("bigdl_nosuch_total", "bogus")        # unregistered
    reliability.inject("nosuch.site")                 # unregistered
    reliability.inject("llm.step")                    # registered
'''


class TestRegistryPass:
    def _run(self, tmp_path, files):
        idx = make_tree(tmp_path, files)
        return run_registry_pass(idx, usage_index=idx,
                                 root=str(tmp_path))

    def test_unregistered_literals_fire(self, tmp_path):
        findings = self._run(tmp_path, {"mod.py": REGISTRY_FIXTURE})
        assert [f.key for f in rules_fired(findings,
                                           "conf-unregistered")] == \
            ["bigdl.nosuch.key"]
        assert [f.key for f in rules_fired(findings,
                                           "metric-unregistered")] == \
            ["bigdl_nosuch_total"]
        assert [f.key for f in rules_fired(findings,
                                           "site-unregistered")] == \
            ["nosuch.site"]

    def test_registered_names_clean(self, tmp_path):
        findings = self._run(tmp_path, {"mod.py": REGISTRY_FIXTURE})
        bad_keys = {f.key for f in findings
                    if f.rule.endswith("unregistered")}
        assert "bigdl.llm.pipeline_depth" not in bad_keys
        assert "llm.step" not in bad_keys

    def test_source_drift_fires(self, tmp_path):
        files = {
            "mod.py": "x = 1\n",
            "utils/conf.py": '_DEFAULTS = {"bigdl.rogue.key": "1"}\n',
        }
        findings = self._run(tmp_path, files)
        assert [f.key for f in rules_fired(findings,
                                           "registry-source-drift")] == \
            ["conf:bigdl.rogue.key"]

    def test_marker_unregistered_fires(self, tmp_path):
        files = {"mod.py": "import pytest\n\n"
                           "@pytest.mark.bogusmark\n"
                           "def test_x():\n    pass\n"}
        findings = self._run(tmp_path, files)
        assert [f.key for f in rules_fired(findings,
                                           "marker-unregistered")] == \
            ["bogusmark"]

    def test_doc_brace_expansion(self):
        from bigdl_tpu.analysis.registrydrift import DocIndex
        di = DocIndex("counters `bigdl_kvcache_{hits,misses}_total` "
                      "and `bigdl_kvtier_host_pages{,_used}`")
        assert di.covers("bigdl_kvcache_hits_total")
        assert di.covers("bigdl_kvcache_misses_total")
        assert di.covers("bigdl_kvtier_host_pages")
        assert di.covers("bigdl_kvtier_host_pages_used")
        assert not di.covers("bigdl_kvcache_evictions_total")


# ---------------------------------------------------------------------------
# donation pass fixtures (ISSUE 13)
# ---------------------------------------------------------------------------

#: use-after-donate, straight-line: the pool is read after the donating
#: dispatch with no rebind
BAD_USE_AFTER_DONATE = '''
from bigdl_tpu import observability as obs

class Eng:
    def _build(self):
        def step(x, pool):
            return x
        self._step = obs.compiled(step, donate_argnums=(1,))

    def dispatch(self, x):
        out = self._step(x, self._pool)
        return self._pool.sum()
'''

GOOD_REBOUND_AFTER_DONATE = '''
from bigdl_tpu import observability as obs

class Eng:
    def _build(self):
        def step(x, pool):
            return x, pool
        self._step = obs.compiled(step, donate_argnums=(1,))

    def dispatch(self, x):
        out, self._pool = self._step(x, self._pool)
        return self._pool.sum()
'''

#: the donation is declared in a BUILDER method (value flow through the
#: call graph) and the post-donation read happens in a CALLEE
BAD_DONATE_THROUGH_CALLEE = '''
from bigdl_tpu import observability as obs

class Eng:
    def _build_step(self):
        def step(x, pool):
            return x
        return obs.compiled(step, donate_argnums=(1,))

    def setup(self):
        self._step = self._build_step()

    def dispatch(self, x):
        out = self._step(x, self._pool)
        self._drain()

    def _drain(self):
        return self._pool.sum()
'''

GOOD_CALLEE_AFTER_REBIND = BAD_DONATE_THROUGH_CALLEE.replace(
    "        out = self._step(x, self._pool)\n        self._drain()",
    "        self._pool = self._step(x, self._pool)\n        self._drain()")

#: loop back-edge: nothing in the loop rebinds the donated buffer
BAD_DONATE_IN_LOOP = '''
from bigdl_tpu import observability as obs

class Eng:
    def _build(self):
        def step(x, pool):
            return x
        self._step = obs.compiled(step, donate_argnums=(1,))

    def run(self, xs):
        for x in xs:
            out = self._step(x, self._pool)
'''

GOOD_DONATE_IN_LOOP = BAD_DONATE_IN_LOOP.replace(
    "            out = self._step(x, self._pool)",
    "            self._pool = self._step(x, self._pool)")

#: aliasing via a pool handle: `k = self._pool` then both positions
BAD_ALIASED_DONATE = '''
from bigdl_tpu import observability as obs

class Eng:
    def _build(self):
        def step(a, b):
            return a
        self._step2 = obs.compiled(step, donate_argnums=(1,))

    def dispatch(self):
        k = self._pool
        self._pool = self._step2(self._pool, k)
'''

GOOD_DISTINCT_DONATE = BAD_ALIASED_DONATE.replace(
    "        k = self._pool\n"
    "        self._pool = self._step2(self._pool, k)",
    "        k = self._other\n"
    "        self._pool = self._step2(self._pool, k)")

#: partial host fetch of a deferred (pipelined) dispatch record
BAD_UNFENCED_DRAIN = '''
import numpy as np
from bigdl_tpu import observability as obs

class Pipe:
    def _build(self):
        def step(x, pool):
            return x
        self._step = obs.compiled(step, donate_argnums=(1,))

    def dispatch(self, x):
        out = self._step(x, self._pool)
        self._pool = out
        self._inflight.append({"out": out, "slot": 1})

    def drain(self):
        rec = self._inflight.popleft()
        toks = np.asarray(rec["out"][0])
        return toks
'''

GOOD_FULL_FETCH_DRAIN = BAD_UNFENCED_DRAIN.replace(
    'toks = np.asarray(rec["out"][0])',
    'toks = np.asarray(rec["out"])')


class TestDonationPass:
    def test_use_after_donate_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_USE_AFTER_DONATE})
        hits = rules_fired(run_donation_pass(idx), "use-after-donate")
        assert len(hits) == 1
        assert "self._pool" in hits[0].key

    def test_rebound_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_REBOUND_AFTER_DONATE})
        assert rules_fired(run_donation_pass(idx),
                           "use-after-donate") == []

    def test_donation_through_callee_fires(self, tmp_path):
        """The ISSUE's fixture: donation declared in a builder (value
        flow through the call graph), the read in a callee."""
        idx = make_tree(tmp_path, {"mod.py": BAD_DONATE_THROUGH_CALLEE})
        hits = rules_fired(run_donation_pass(idx), "use-after-donate")
        assert len(hits) == 1
        assert "_drain" in hits[0].key

    def test_callee_after_rebind_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_CALLEE_AFTER_REBIND})
        assert rules_fired(run_donation_pass(idx),
                           "use-after-donate") == []

    def test_loop_backedge_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_DONATE_IN_LOOP})
        hits = rules_fired(run_donation_pass(idx), "use-after-donate")
        assert len(hits) == 1
        assert "@loop" in hits[0].key

    def test_loop_rebind_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_DONATE_IN_LOOP})
        assert rules_fired(run_donation_pass(idx),
                           "use-after-donate") == []

    def test_aliased_donate_via_handle_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_ALIASED_DONATE})
        hits = rules_fired(run_donation_pass(idx), "aliased-donate")
        assert len(hits) == 1
        assert "self._pool" in hits[0].key

    def test_distinct_buffers_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_DISTINCT_DONATE})
        assert rules_fired(run_donation_pass(idx), "aliased-donate") == []

    def test_unfenced_drain_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_UNFENCED_DRAIN})
        hits = rules_fired(run_donation_pass(idx), "unfenced-drain")
        assert len(hits) == 1
        assert "drain" in hits[0].key

    def test_full_record_fetch_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_FULL_FETCH_DRAIN})
        assert rules_fired(run_donation_pass(idx), "unfenced-drain") == []

    def test_barrier_stands_down(self, tmp_path):
        src = BAD_UNFENCED_DRAIN.replace(
            "        rec = self._inflight.popleft()",
            "        rec = self._inflight.popleft()\n"
            "        jax.block_until_ready(rec)")
        idx = make_tree(tmp_path, {"mod.py": src})
        assert rules_fired(run_donation_pass(idx), "unfenced-drain") == []

    def test_sibling_else_arm_clean(self, tmp_path):
        """A read in the OPPOSITE arm of an if/else never follows the
        donating call — linearized order must not fake an ordered
        pair (the fallback-dispatch shape)."""
        src = '''
from bigdl_tpu import observability as obs

class Eng:
    def _build(self):
        def step(x, pool):
            return x
        self._step = obs.compiled(step, donate_argnums=(1,))

    def dispatch(self, x, fast):
        if fast:
            out = self._step(x, self._pool)
            self._pool = out
        else:
            out = self._pool.mean()
        return out
'''
        idx = make_tree(tmp_path, {"mod.py": src})
        assert rules_fired(run_donation_pass(idx),
                           "use-after-donate") == []

    def test_sibling_arm_def_does_not_protect(self, tmp_path):
        """A rebind in the opposite arm must NOT silence a real
        post-donation read on the donating path."""
        src = '''
from bigdl_tpu import observability as obs

class Eng:
    def _build(self):
        def step(x, pool):
            return x
        self._step = obs.compiled(step, donate_argnums=(1,))

    def dispatch(self, x, fast):
        if fast:
            out = self._step(x, self._pool)
        else:
            self._pool = x
        return self._pool.mean()
'''
        idx = make_tree(tmp_path, {"mod.py": src})
        hits = rules_fired(run_donation_pass(idx), "use-after-donate")
        assert len(hits) == 1

    def test_comprehension_before_donation_clean(self, tmp_path):
        """An eager comprehension consumed BEFORE the dispatch holds no
        live reference — the clean rebind idiom must stay clean."""
        src = '''
from bigdl_tpu import observability as obs

class Eng:
    def _build(self):
        def step(x, pool):
            return x
        self._step = obs.compiled(step, donate_argnums=(1,))

    def dispatch(self, x):
        total = sum(p for p in self._pool)
        self._pool = self._step(x, self._pool)
        return total
'''
        idx = make_tree(tmp_path, {"mod.py": src})
        assert rules_fired(run_donation_pass(idx),
                           "use-after-donate") == []

    def test_swap_idiom_not_aliased(self, tmp_path):
        """Double-buffer swap: the handle was taken BEFORE the source
        was rebound, so the two positions are distinct objects."""
        src = '''
from bigdl_tpu import observability as obs

class Eng:
    def _build(self):
        def step(a, b):
            return a
        self._step2 = obs.compiled(step, donate_argnums=(1,))

    def dispatch(self):
        old = self._pool
        self._pool = self._alloc()
        self._pool = self._step2(self._pool, old)
'''
        idx = make_tree(tmp_path, {"mod.py": src})
        assert rules_fired(run_donation_pass(idx), "aliased-donate") == []

    def test_escape_to_thread_fires(self, tmp_path):
        """Donating a buffer a same-function thread holds: the escaped
        ref can read the donated buffer at any time."""
        src = '''
import threading
from bigdl_tpu import observability as obs

class Eng:
    def _build(self):
        def step(x, pool):
            return x
        self._step = obs.compiled(step, donate_argnums=(1,))

    def dispatch(self, x, fn):
        t = threading.Thread(target=fn, args=(self._pool,))
        t.start()
        out = self._step(x, self._pool)
        self._pool = out
        t.join()
'''
        idx = make_tree(tmp_path, {"mod.py": src})
        hits = rules_fired(run_donation_pass(idx), "use-after-donate")
        assert len(hits) == 1 and "@escape" in hits[0].key


# ---------------------------------------------------------------------------
# gatecheck pass fixtures (ISSUE 13)
# ---------------------------------------------------------------------------

GATED_PKG = '''
class GatedThing:
    def __init__(self):
        pass
'''

#: construction outside the package with no gate in sight
BAD_UNGUARDED_USER = '''
from bigdl_tpu.sub.gated import GatedThing

class Host:
    def setup(self):
        self._t = GatedThing()
'''

GOOD_GUARDED_USER = '''
from bigdl_tpu.sub.gated import GatedThing
from bigdl_tpu.utils.conf import conf

class Host:
    def setup(self):
        if conf.get_bool("bigdl.testsub.enabled", False):
            self._t = GatedThing()
'''

#: the gate is read in __init__, the construction guarded by the
#: derived attribute in ANOTHER method
GOOD_DERIVED_GUARD_USER = '''
from bigdl_tpu.sub.gated import GatedThing
from bigdl_tpu.utils.conf import conf

class Host:
    def __init__(self):
        self._enabled = conf.get_bool("bigdl.testsub.enabled", False)

    def setup(self):
        if self._enabled:
            self._t = GatedThing()
'''

#: a gate-false path reaching a thread start: the gated module starts a
#: thread at IMPORT time, which no gate can prevent
BAD_MODULE_THREAD = '''
import threading

def _loop():
    pass

threading.Thread(target=_loop, daemon=True).start()
'''

TEST_GATES = {"bigdl.testsub.enabled": {"package": "bigdl_tpu/sub"}}


class TestGatecheckPass:
    def _run(self, tmp_path, files, gates=TEST_GATES):
        idx = make_tree(tmp_path, files)
        return run_gatecheck_pass(idx, usage_index=idx,
                                  root=str(tmp_path), gates=gates)

    def test_unguarded_construction_fires(self, tmp_path):
        findings = self._run(tmp_path, {
            "sub/gated.py": GATED_PKG, "user.py": BAD_UNGUARDED_USER})
        hits = rules_fired(findings, "gate-unguarded-construction")
        assert len(hits) == 1
        assert "GatedThing" in hits[0].key

    def test_guarded_construction_clean(self, tmp_path):
        findings = self._run(tmp_path, {
            "sub/gated.py": GATED_PKG, "user.py": GOOD_GUARDED_USER})
        assert rules_fired(findings, "gate-unguarded-construction") == []

    def test_derived_attr_guard_clean(self, tmp_path):
        findings = self._run(tmp_path, {
            "sub/gated.py": GATED_PKG,
            "user.py": GOOD_DERIVED_GUARD_USER})
        assert rules_fired(findings, "gate-unguarded-construction") == []

    def test_module_level_thread_start_fires(self, tmp_path):
        """The ISSUE's fixture: a gate-false path reaching a thread
        start — import-time side effects defeat any gate."""
        findings = self._run(tmp_path, {
            "sub/gated.py": GATED_PKG + BAD_MODULE_THREAD,
            "user.py": GOOD_GUARDED_USER})
        hits = rules_fired(findings, "gate-module-side-effect")
        assert any("thread start" in h.key for h in hits)

    def test_method_thread_start_clean(self, tmp_path):
        """Thread starts inside gated-class METHODS are fine — the
        class only exists when the gate admitted its construction."""
        src = GATED_PKG + '''
import threading

class Runner:
    def start(self):
        self._t = threading.Thread(target=print, daemon=True)
        self._t.start()

    def stop(self):
        self._t.join()
'''
        findings = self._run(tmp_path, {
            "sub/gated.py": src, "user.py": GOOD_GUARDED_USER})
        assert rules_fired(findings, "gate-module-side-effect") == []

    def test_default_on_fires(self, tmp_path):
        files = {
            "sub/gated.py": GATED_PKG,
            "user.py": GOOD_GUARDED_USER,
            "utils/conf.py":
                '_DEFAULTS = {"bigdl.testsub.enabled": "true"}\n',
        }
        findings = self._run(tmp_path, files)
        assert [f.key for f in rules_fired(findings,
                                           "gate-default-on")] == \
            ["bigdl.testsub.enabled"]

    def test_default_off_clean(self, tmp_path):
        files = {
            "sub/gated.py": GATED_PKG,
            "user.py": GOOD_GUARDED_USER,
            "utils/conf.py":
                '_DEFAULTS = {"bigdl.testsub.enabled": "false"}\n',
        }
        findings = self._run(tmp_path, files)
        assert rules_fired(findings, "gate-default-on") == []

    def test_absence_test_checked(self, tmp_path):
        files = {
            "sub/gated.py": GATED_PKG,
            "user.py": GOOD_GUARDED_USER,
            "tests/test_other.py": "def test_x():\n    pass\n",
        }
        findings = self._run(tmp_path, files)
        assert [f.key for f in rules_fired(findings,
                                           "gate-no-absence-test")] == \
            ["bigdl.testsub.enabled"]
        files["tests/test_other.py"] = (
            'def test_absent():\n'
            '    assert not conf.get_bool("bigdl.testsub.enabled")\n')
        findings = self._run(tmp_path, files)
        assert rules_fired(findings, "gate-no-absence-test") == []


# ---------------------------------------------------------------------------
# httpdrift pass fixtures (ISSUE 13)
# ---------------------------------------------------------------------------

SURFACE = '''
class Handler:
    def do_GET(self):
        if self.path == "/things":
            self._json(200, {})
        elif self.path == "/gated":
            self._json(200, self.sub.stats())
        else:
            self._json(404, {"error": "unknown path"})
'''

SURFACE_GATED_OK = SURFACE.replace(
    '''        elif self.path == "/gated":
            self._json(200, self.sub.stats())''',
    '''        elif self.path == "/gated":
            if self.sub is None:
                self._json(404, {"error": "disabled"})
            else:
                self._json(200, self.sub.stats())''')

CLIENT = '''
import http.client

def fetch(addr):
    conn = http.client.HTTPConnection(*addr)
    conn.request("GET", "/things")
    return conn.getresponse()
'''

TEST_ENDPOINTS = {
    "/things": {"methods": ("GET",)},
    "/gated": {"methods": ("GET",),
               "gate": "bigdl.testsub.enabled"},
}


class TestHttpDriftPass:
    def _run(self, tmp_path, files, endpoints=TEST_ENDPOINTS):
        idx = make_tree(tmp_path, files)
        return run_httpdrift_pass(idx, usage_index=idx,
                                  root=str(tmp_path),
                                  endpoints=endpoints)

    def test_route_with_no_client_fires(self, tmp_path):
        findings = self._run(tmp_path, {"srv.py": SURFACE_GATED_OK})
        assert "/things" in {f.key for f in rules_fired(
            findings, "http-route-no-client")}

    def test_route_with_client_clean(self, tmp_path):
        findings = self._run(tmp_path, {"srv.py": SURFACE_GATED_OK,
                                        "cli.py": CLIENT})
        keys = {f.key for f in rules_fired(findings,
                                           "http-route-no-client")}
        assert "/things" not in keys

    def test_gated_endpoint_missing_404_fires(self, tmp_path):
        findings = self._run(tmp_path, {"srv.py": SURFACE,
                                        "cli.py": CLIENT})
        hits = rules_fired(findings, "http-gated-no-404")
        assert len(hits) == 1 and "/gated" in hits[0].key

    def test_gated_endpoint_with_404_clean(self, tmp_path):
        findings = self._run(tmp_path, {"srv.py": SURFACE_GATED_OK,
                                        "cli.py": CLIENT})
        assert rules_fired(findings, "http-gated-no-404") == []

    def test_conjunctive_gate_test_clean(self, tmp_path):
        src = SURFACE.replace(
            'elif self.path == "/gated":',
            'elif self.path == "/gated" and self.sub is not None:')
        findings = self._run(tmp_path, {"srv.py": src, "cli.py": CLIENT})
        assert rules_fired(findings, "http-gated-no-404") == []

    def test_unrelated_conjunct_still_fires(self, tmp_path):
        """`and req_ok` is request state, not gate state — it must not
        satisfy the 404-when-off contract."""
        src = SURFACE.replace(
            'elif self.path == "/gated":',
            'elif self.path == "/gated" and req_ok:')
        findings = self._run(tmp_path, {"srv.py": src, "cli.py": CLIENT})
        hits = rules_fired(findings, "http-gated-no-404")
        assert len(hits) == 1 and "/gated" in hits[0].key

    def test_unregistered_route_fires(self, tmp_path):
        findings = self._run(tmp_path, {"srv.py": SURFACE_GATED_OK},
                             endpoints={"/gated": TEST_ENDPOINTS["/gated"]})
        assert [f.key for f in rules_fired(findings,
                                           "route-unregistered")] == \
            ["/things"]

    def test_unserved_registry_entry_fires(self, tmp_path):
        eps = dict(TEST_ENDPOINTS)
        eps["/ghost"] = {"methods": ("GET",)}
        findings = self._run(tmp_path, {"srv.py": SURFACE_GATED_OK},
                             endpoints=eps)
        assert [f.key for f in rules_fired(findings,
                                           "route-unserved")] == \
            ["/ghost"]

    def test_client_unhandled_fires(self, tmp_path):
        src = CLIENT.replace('"/things"', '"/nothing"')
        findings = self._run(tmp_path, {"srv.py": SURFACE_GATED_OK,
                                        "cli.py": src})
        assert [f.key for f in rules_fired(findings,
                                           "http-client-unhandled")] == \
            ["/nothing"]

    def test_docs_and_tests_coverage_rules(self, tmp_path):
        """A route mentioned in README + tests is covered; one in
        neither fires both coverage rules."""
        (tmp_path / "README.md").write_text(
            "Call `/things` for things.\n")
        files = {"srv.py": SURFACE_GATED_OK, "cli.py": CLIENT,
                 "tests/test_api.py": 'THINGS = "/things"\n'}
        findings = self._run(tmp_path, files)
        undoc = {f.key for f in rules_fired(findings,
                                            "http-route-undocumented")}
        untested = {f.key for f in rules_fired(findings,
                                               "http-route-untested")}
        assert "/things" not in undoc and "/gated" in undoc
        assert "/things" not in untested and "/gated" in untested

    def test_early_return_neq_route_detected(self, tmp_path):
        """The `self.path != "/x": 404-return` idiom serves /x."""
        src = '''
class Handler:
    def do_POST(self):
        if self.path != "/predictish":
            self._json(404, {})
            return
        self._json(200, {})
'''
        findings = self._run(
            tmp_path, {"srv.py": src},
            endpoints={"/predictish": {"methods": ("POST",),
                                       "gate": "bigdl.testsub.enabled"}})
        # detected as served (no route-unregistered), and the negated
        # match counts as having the 404-when-off fall-through
        assert rules_fired(findings, "route-unregistered") == []
        assert rules_fired(findings, "route-unserved") == []
        assert rules_fired(findings, "http-gated-no-404") == []


# ---------------------------------------------------------------------------
# baseline engine
# ---------------------------------------------------------------------------

def _finding(key="k", rule="lock-order"):
    return Finding(rule=rule, file="bigdl_tpu/mod.py", line=3,
                   key=key, message="m")


class TestBaseline:
    def test_round_trip_suppresses(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        bl = Baseline(path=path)
        bl.add_findings([_finding("a"), _finding("b")], "triaged: ok")
        bl.save()
        loaded = Baseline.load(path)
        new, suppressed, stale = loaded.split(
            [_finding("a"), _finding("b"), _finding("c")])
        assert [f.key for f in new] == ["c"]
        assert len(suppressed) == 2 and stale == []

    def test_stale_entries_reported_and_prunable(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        bl = Baseline(path=path)
        bl.add_findings([_finding("gone")], "fixed since")
        bl.save()
        loaded = Baseline.load(path)
        _, _, stale = loaded.split([])
        assert stale == [_finding("gone").fingerprint]
        loaded.prune(stale)
        loaded.save()
        assert Baseline.load(path).entries == {}

    def test_justification_required(self, tmp_path):
        path = tmp_path / "baseline.json"
        path.write_text(json.dumps({"entries": [
            {"fingerprint": "lock-order::f::k", "justification": ""}]}))
        bl = Baseline.load(str(path))
        assert bl.entries == {}
        assert any("justification" in e for e in bl.errors)

    def test_fingerprint_is_line_free(self):
        a = Finding(rule="r", file="f", line=1, key="k", message="x")
        b = Finding(rule="r", file="f", line=99, key="k", message="y")
        assert a.fingerprint == b.fingerprint


# ---------------------------------------------------------------------------
# lockwatch runtime witness
# ---------------------------------------------------------------------------

class TestLockwatch:
    def test_disabled_structurally_absent(self):
        """Acceptance: off by default — stock factories, no series."""
        from bigdl_tpu import observability as obs
        from bigdl_tpu.utils.conf import conf
        assert conf.get_bool("bigdl.analysis.lockwatch") is False
        assert lockwatch.maybe_install() is False
        assert threading.Lock is lockwatch._ORIG_LOCK
        assert threading.RLock is lockwatch._ORIG_RLOCK
        assert not lockwatch.installed()
        assert "bigdl_lockwatch" not in obs.render()

    def test_inversion_detected(self):
        """The seeded A->B / B->A inversion the ISSUE asks for."""
        lockwatch.install()
        try:
            lockwatch.reset()
            a = threading.Lock()
            b = threading.Lock()
            assert type(a).__name__ == "_WatchedLock"
            with a:
                with b:
                    pass
            assert lockwatch.violations() == []
            with b:
                with a:
                    pass
            vio = lockwatch.violations()
            assert len(vio) == 1
            assert "test_analysis.py" in vio[0]["pair"][0]
        finally:
            lockwatch.uninstall()
            lockwatch.reset()
        assert threading.Lock is lockwatch._ORIG_LOCK

    def test_consistent_order_no_violation(self):
        lockwatch.install()
        try:
            lockwatch.reset()
            # one creation site per lock: site identity is file:line
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
            assert lockwatch.violations() == []
            assert len(lockwatch.observed_edges()) >= 1
        finally:
            lockwatch.uninstall()
            lockwatch.reset()

    def test_reentrant_rlock_no_edge(self):
        lockwatch.install()
        try:
            lockwatch.reset()
            r = threading.RLock()
            with r:
                with r:            # reentrant: no self-edge, balanced
                    pass
            assert lockwatch.violations() == []
            with r:                # still usable after full release
                pass
        finally:
            lockwatch.uninstall()
            lockwatch.reset()

    def test_watched_lock_backs_condition(self):
        lockwatch.install()
        try:
            lockwatch.reset()
            cv = threading.Condition(threading.RLock())
            hit = []

            def waiter():
                with cv:
                    cv.wait(timeout=5)
                    hit.append(1)

            t = threading.Thread(target=waiter)
            t.start()
            import time
            time.sleep(0.05)
            with cv:
                cv.notify_all()
            t.join(timeout=5)
            assert hit == [1]
        finally:
            lockwatch.uninstall()
            lockwatch.reset()


# ---------------------------------------------------------------------------
# the CI gate
# ---------------------------------------------------------------------------

class TestGate:
    def test_repo_gate_zero_unbaselined(self):
        """THE tier-1 gate: the analyzer over bigdl_tpu/ must report
        zero findings the checked-in baseline does not suppress."""
        out = analysis.check(REPO)
        # the gate spans all six passes (ISSUE 13 extended it)
        assert set(out["by_pass"]) == set(analysis.PASSES)
        assert out["baseline_errors"] == []
        assert out["new"] == [], (
            "unbaselined static-analysis findings — fix them or triage "
            "into bigdl_tpu/analysis/baseline.json:\n" +
            "\n".join(f"{f['rule']}: {f['file']}:{f['line']}: "
                      f"{f['message']}" for f in out["new"]))
        assert out["ok"]

    def test_repo_baseline_not_stale(self):
        """Every baseline entry still matches a live finding — the
        baseline only ever shrinks (prune when your fix lands)."""
        out = analysis.check(REPO)
        assert out["stale_baseline"] == []

    def _cli(self, *args):
        env = dict(os.environ)
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "check_static.py")] +
            list(args), capture_output=True, text=True, env=env,
            timeout=300)

    def test_cli_fixture_violations_exit_nonzero(self, tmp_path):
        """Acceptance: nonzero exit on each fixture violation, one per
        pass."""
        (tmp_path / "bigdl_tpu").mkdir()
        (tmp_path / "bigdl_tpu" / "mod.py").write_text(BAD_LOCK_ORDER)
        r = self._cli("--root", str(tmp_path), "--passes",
                      "concurrency")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "lock-order" in r.stdout

        (tmp_path / "bigdl_tpu" / "mod.py").write_text(BAD_COMPILED)
        r = self._cli("--root", str(tmp_path), "--passes", "hotpath")
        assert r.returncode == 1
        assert "traced-branch" in r.stdout

        (tmp_path / "bigdl_tpu" / "mod.py").write_text(
            'from bigdl_tpu.utils.conf import conf\n'
            'conf.get("bigdl.nosuch.key")\n')
        r = self._cli("--root", str(tmp_path), "--passes", "registry")
        assert r.returncode == 1
        assert "conf-unregistered" in r.stdout

        (tmp_path / "bigdl_tpu" / "mod.py").write_text(
            BAD_USE_AFTER_DONATE)
        r = self._cli("--root", str(tmp_path), "--only", "donation")
        assert r.returncode == 1
        assert "use-after-donate" in r.stdout

    def test_cli_only_rejects_unknown_pass(self, tmp_path):
        (tmp_path / "bigdl_tpu").mkdir()
        (tmp_path / "bigdl_tpu" / "mod.py").write_text("x = 1\n")
        r = self._cli("--root", str(tmp_path), "--only", "nosuchpass")
        assert r.returncode == 2
        assert "unknown pass" in r.stderr

    def test_cli_sarif_output(self, tmp_path):
        """--sarif: rule ids, file:line regions, stable fingerprints,
        and baseline justifications as suppressions."""
        (tmp_path / "bigdl_tpu").mkdir()
        (tmp_path / "bigdl_tpu" / "mod.py").write_text(
            BAD_USE_AFTER_DONATE)
        r = self._cli("--root", str(tmp_path), "--only", "donation",
                      "--sarif")
        assert r.returncode == 1
        doc = json.loads(r.stdout)
        assert doc["version"] == "2.1.0"
        run = doc["runs"][0]
        assert any(rule["id"] == "use-after-donate"
                   for rule in run["tool"]["driver"]["rules"])
        res = [x for x in run["results"]
               if x["ruleId"] == "use-after-donate"]
        assert len(res) == 1
        loc = res[0]["locations"][0]["physicalLocation"]
        assert loc["artifactLocation"]["uri"] == "bigdl_tpu/mod.py"
        assert loc["region"]["startLine"] > 0
        fp = res[0]["fingerprints"]["bigdlAnalysis/v1"]
        assert fp.startswith("use-after-donate::bigdl_tpu/mod.py::")
        assert res[0]["level"] == "warning"
        # baseline the finding -> it renders as a suppressed note
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps({"entries": [
            {"fingerprint": fp, "rule": "use-after-donate",
             "justification": "fixture: designed idiom"}]}))
        r = self._cli("--root", str(tmp_path), "--only", "donation",
                      "--baseline", str(bl), "--sarif")
        assert r.returncode == 0
        doc = json.loads(r.stdout)
        res = [x for x in doc["runs"][0]["results"]
               if x["ruleId"] == "use-after-donate"]
        assert res[0]["level"] == "note"
        assert res[0]["suppressions"][0]["justification"] == \
            "fixture: designed idiom"

    def test_gate_runs_without_jax_within_budget(self):
        """Acceptance: all six passes run standalone — jax poisoned at
        import — in under 10 s with zero unbaselined findings."""
        poison = (
            "import sys, runpy\n"
            "sys.modules['jax'] = None\n"          # `import jax` raises
            "sys.modules['jax.numpy'] = None\n"
            "sys.argv = ['check_static.py', '--json']\n"
            f"runpy.run_path({os.path.join(REPO, 'tools', 'check_static.py')!r},"
            " run_name='__main__')\n")
        t0 = time.perf_counter()
        r = subprocess.run([sys.executable, "-c", poison],
                           capture_output=True, text=True, timeout=60)
        elapsed = time.perf_counter() - t0
        assert r.returncode == 0, r.stdout + r.stderr
        out = json.loads(r.stdout)
        assert out["new"] == [] and out["baseline_errors"] == []
        assert set(out["by_pass"]) == set(analysis.PASSES)
        assert elapsed < 10.0, f"gate took {elapsed:.1f}s (budget 10s)"

    def test_cli_missing_justification_exit_2(self, tmp_path):
        (tmp_path / "bigdl_tpu").mkdir()
        (tmp_path / "bigdl_tpu" / "mod.py").write_text("x = 1\n")
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps({"entries": [
            {"fingerprint": "lock-order::f::k", "justification": ""}]}))
        r = self._cli("--root", str(tmp_path), "--passes",
                      "concurrency", "--baseline", str(bl))
        assert r.returncode == 2
        assert "BASELINE ERROR" in r.stdout

    @pytest.mark.slow
    def test_cli_repo_clean_exit_0(self):
        """Acceptance: `python tools/check_static.py` exits 0 on the
        repo (the in-process gate test covers the same contract; this
        one pins the CLI surface)."""
        r = self._cli()
        assert r.returncode == 0, r.stdout + r.stderr
        assert "gate clean" in r.stdout

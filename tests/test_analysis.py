"""Static-analysis suite tests (ISSUE 11): fixture-based per-rule
checks for each pass (known-bad snippets fire, known-good don't), the
baseline round-trip, the lockwatch runtime witness, the CLI exit-code
contract, and the tier-1 repo gate (zero unbaselined findings)."""

import json
import os
import subprocess
import sys
import threading

import pytest

from bigdl_tpu import analysis
from bigdl_tpu.analysis import lockwatch
from bigdl_tpu.analysis.baseline import Baseline
from bigdl_tpu.analysis.concurrency import (lock_graph,
                                            run_concurrency_pass)
from bigdl_tpu.analysis.core import Finding, ProjectIndex
from bigdl_tpu.analysis.hotpath import run_hotpath_pass
from bigdl_tpu.analysis.registrydrift import run_registry_pass

pytestmark = pytest.mark.analysis

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def make_tree(tmp_path, files):
    """Write {relpath: source} under tmp_path/bigdl_tpu and index it."""
    for rel, src in files.items():
        path = tmp_path / "bigdl_tpu" / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(src)
    return ProjectIndex.scan(str(tmp_path), ["bigdl_tpu"])


def rules_fired(findings, rule):
    return [f for f in findings if f.rule == rule]


# ---------------------------------------------------------------------------
# concurrency pass fixtures
# ---------------------------------------------------------------------------

BAD_LOCK_ORDER = '''
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def two(self):
        with self._b:
            with self._a:
                pass
'''

GOOD_LOCK_ORDER = BAD_LOCK_ORDER.replace(
    "with self._b:\n            with self._a:",
    "with self._a:\n            with self._b:")

#: the cycle hides behind a call: two() holds b and CALLS a helper
#: that takes a — only the transitive edge sees it
BAD_LOCK_ORDER_INDIRECT = '''
import threading

class S:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def one(self):
        with self._a:
            with self._b:
                pass

    def _take_a(self):
        with self._a:
            pass

    def two(self):
        with self._b:
            self._take_a()
'''

BAD_UNLOCKED_WRITE = '''
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()
        self.count = 0
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def _loop(self):
        self.count += 1

    def bump(self):
        self.count += 1

    def stop(self):
        self._thread.join()
'''

GOOD_LOCKED_WRITE = BAD_UNLOCKED_WRITE.replace(
    "    def _loop(self):\n        self.count += 1",
    "    def _loop(self):\n        with self._lock:\n"
    "            self.count += 1").replace(
    "    def bump(self):\n        self.count += 1",
    "    def bump(self):\n        with self._lock:\n"
    "            self.count += 1")

BAD_THREAD_NO_JOIN = '''
import threading

def fire():
    threading.Thread(target=print, daemon=True).start()
'''

GOOD_THREAD_JOINED = '''
import threading

class S:
    def start(self):
        self._thread = threading.Thread(target=print, daemon=True)
        self._thread.start()

    def stop(self):
        self._thread.join()
'''

BAD_BARE_ACQUIRE = '''
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def risky(self):
        self._lock.acquire()
        do_work()
        self._lock.release()
'''

GOOD_ACQUIRE_FINALLY = '''
import threading

class S:
    def __init__(self):
        self._lock = threading.Lock()

    def safe(self):
        self._lock.acquire()
        try:
            do_work()
        finally:
            self._lock.release()
'''


class TestConcurrencyPass:
    def test_lock_order_inversion_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_LOCK_ORDER})
        hits = rules_fired(run_concurrency_pass(idx), "lock-order")
        assert len(hits) == 1
        assert "S._a" in hits[0].key and "S._b" in hits[0].key

    def test_consistent_order_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_LOCK_ORDER})
        assert rules_fired(run_concurrency_pass(idx), "lock-order") == []

    def test_lock_order_through_call_graph(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_LOCK_ORDER_INDIRECT})
        hits = rules_fired(run_concurrency_pass(idx), "lock-order")
        assert len(hits) == 1

    def test_unlocked_write_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_UNLOCKED_WRITE})
        hits = rules_fired(run_concurrency_pass(idx), "unlocked-write")
        assert [h.key for h in hits] == ["S.count"]

    def test_locked_write_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_LOCKED_WRITE})
        assert rules_fired(run_concurrency_pass(idx),
                           "unlocked-write") == []

    def test_init_writes_exempt(self, tmp_path):
        # the __init__ assignment of count never counts as a race side
        idx = make_tree(tmp_path, {"mod.py": GOOD_LOCKED_WRITE})
        findings = run_concurrency_pass(idx)
        assert all("__init__" not in f.message for f in findings)

    def test_thread_no_join_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_THREAD_NO_JOIN})
        assert len(rules_fired(run_concurrency_pass(idx),
                               "thread-no-join")) == 1

    def test_joined_thread_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_THREAD_JOINED})
        assert rules_fired(run_concurrency_pass(idx),
                           "thread-no-join") == []

    def test_bare_acquire_fires(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_BARE_ACQUIRE})
        assert len(rules_fired(run_concurrency_pass(idx),
                               "bare-acquire")) == 1

    def test_acquire_with_finally_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_ACQUIRE_FINALLY})
        assert rules_fired(run_concurrency_pass(idx),
                           "bare-acquire") == []

    def test_lock_graph_names_sites(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_LOCK_ORDER})
        graph = lock_graph(idx)
        assert any("S._a" in k for k in graph)


# ---------------------------------------------------------------------------
# hot-path pass fixtures
# ---------------------------------------------------------------------------

HOT_SYNCS = '''
import jax
import numpy as np

class Engine:
    def _loop(self):
        while True:
            self._tick()

    def _tick(self):
        x = self._step()
        n = x.item()
        host = np.asarray(x)
        jax.block_until_ready(x)
        flag = float(x)
        return n, host, flag

    def _step(self):
        return 1
'''

HOT_CLEAN = '''
import jax
import jax.numpy as jnp

class Engine:
    def _loop(self):
        while True:
            self._tick()

    def _tick(self):
        dev = jnp.asarray([1, 2])      # host->device: async, allowed
        return dev

    def unreachable_sync(self, x):
        return x.item()                # NOT reachable from _loop
'''

BAD_COMPILED = '''
from bigdl_tpu import observability as obs

class Model:
    def _build(self):
        def step(params, x, flag):
            if flag:                    # traced-branch
                return params
            return self.scale * x       # compiled-self-ref
        return obs.compiled(step, name="m/step")
'''

GOOD_COMPILED = '''
from bigdl_tpu import observability as obs

class Model:
    def _build(self):
        cfg = self.cfg                  # the blessed idiom
        def step(params, x):
            return params + x * cfg.scale
        return obs.compiled(step, name="m/step")
'''

ROOTS = (("bigdl_tpu/mod.py", "Engine", "_loop"),)


class TestHotPathPass:
    def test_sync_rules_fire(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": HOT_SYNCS})
        findings = run_hotpath_pass(idx, roots=ROOTS)
        assert len(rules_fired(findings, "host-sync-item")) == 1
        # np.asarray + block_until_ready
        assert len(rules_fired(findings, "host-sync-transfer")) == 2
        assert len(rules_fired(findings, "host-sync-cast")) == 1

    def test_upload_and_unreachable_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": HOT_CLEAN})
        findings = run_hotpath_pass(idx, roots=ROOTS)
        # jnp.asarray is a host->device upload, not a sync; and the
        # .item() lives in a function the engine loop never reaches
        assert findings == []

    def test_compiled_fn_hazards_fire(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": BAD_COMPILED})
        findings = run_hotpath_pass(idx, roots=ROOTS)
        assert len(rules_fired(findings, "traced-branch")) == 1
        assert len(rules_fired(findings, "compiled-self-ref")) == 1

    def test_compiled_fn_good_idiom_clean(self, tmp_path):
        idx = make_tree(tmp_path, {"mod.py": GOOD_COMPILED})
        assert run_hotpath_pass(idx, roots=ROOTS) == []


# ---------------------------------------------------------------------------
# registry-drift pass fixtures
# ---------------------------------------------------------------------------

REGISTRY_FIXTURE = '''
from bigdl_tpu.utils.conf import conf
from bigdl_tpu import observability as obs
from bigdl_tpu import reliability

def f():
    conf.get_bool("bigdl.nosuch.key", False)          # unregistered
    conf.get_int("bigdl.llm.pipeline_depth", 2)       # registered
    obs.counter("bigdl_nosuch_total", "bogus")        # unregistered
    reliability.inject("nosuch.site")                 # unregistered
    reliability.inject("llm.step")                    # registered
'''


class TestRegistryPass:
    def _run(self, tmp_path, files):
        idx = make_tree(tmp_path, files)
        return run_registry_pass(idx, usage_index=idx,
                                 root=str(tmp_path))

    def test_unregistered_literals_fire(self, tmp_path):
        findings = self._run(tmp_path, {"mod.py": REGISTRY_FIXTURE})
        assert [f.key for f in rules_fired(findings,
                                           "conf-unregistered")] == \
            ["bigdl.nosuch.key"]
        assert [f.key for f in rules_fired(findings,
                                           "metric-unregistered")] == \
            ["bigdl_nosuch_total"]
        assert [f.key for f in rules_fired(findings,
                                           "site-unregistered")] == \
            ["nosuch.site"]

    def test_registered_names_clean(self, tmp_path):
        findings = self._run(tmp_path, {"mod.py": REGISTRY_FIXTURE})
        bad_keys = {f.key for f in findings
                    if f.rule.endswith("unregistered")}
        assert "bigdl.llm.pipeline_depth" not in bad_keys
        assert "llm.step" not in bad_keys

    def test_source_drift_fires(self, tmp_path):
        files = {
            "mod.py": "x = 1\n",
            "utils/conf.py": '_DEFAULTS = {"bigdl.rogue.key": "1"}\n',
        }
        findings = self._run(tmp_path, files)
        assert [f.key for f in rules_fired(findings,
                                           "registry-source-drift")] == \
            ["conf:bigdl.rogue.key"]

    def test_marker_unregistered_fires(self, tmp_path):
        files = {"mod.py": "import pytest\n\n"
                           "@pytest.mark.bogusmark\n"
                           "def test_x():\n    pass\n"}
        findings = self._run(tmp_path, files)
        assert [f.key for f in rules_fired(findings,
                                           "marker-unregistered")] == \
            ["bogusmark"]

    def test_doc_brace_expansion(self):
        from bigdl_tpu.analysis.registrydrift import DocIndex
        di = DocIndex("counters `bigdl_kvcache_{hits,misses}_total` "
                      "and `bigdl_kvtier_host_pages{,_used}`")
        assert di.covers("bigdl_kvcache_hits_total")
        assert di.covers("bigdl_kvcache_misses_total")
        assert di.covers("bigdl_kvtier_host_pages")
        assert di.covers("bigdl_kvtier_host_pages_used")
        assert not di.covers("bigdl_kvcache_evictions_total")


# ---------------------------------------------------------------------------
# baseline engine
# ---------------------------------------------------------------------------

def _finding(key="k", rule="lock-order"):
    return Finding(rule=rule, file="bigdl_tpu/mod.py", line=3,
                   key=key, message="m")


class TestBaseline:
    def test_round_trip_suppresses(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        bl = Baseline(path=path)
        bl.add_findings([_finding("a"), _finding("b")], "triaged: ok")
        bl.save()
        loaded = Baseline.load(path)
        new, suppressed, stale = loaded.split(
            [_finding("a"), _finding("b"), _finding("c")])
        assert [f.key for f in new] == ["c"]
        assert len(suppressed) == 2 and stale == []

    def test_stale_entries_reported_and_prunable(self, tmp_path):
        path = str(tmp_path / "baseline.json")
        bl = Baseline(path=path)
        bl.add_findings([_finding("gone")], "fixed since")
        bl.save()
        loaded = Baseline.load(path)
        _, _, stale = loaded.split([])
        assert stale == [_finding("gone").fingerprint]
        loaded.prune(stale)
        loaded.save()
        assert Baseline.load(path).entries == {}

    def test_justification_required(self, tmp_path):
        path = tmp_path / "baseline.json"
        path.write_text(json.dumps({"entries": [
            {"fingerprint": "lock-order::f::k", "justification": ""}]}))
        bl = Baseline.load(str(path))
        assert bl.entries == {}
        assert any("justification" in e for e in bl.errors)

    def test_fingerprint_is_line_free(self):
        a = Finding(rule="r", file="f", line=1, key="k", message="x")
        b = Finding(rule="r", file="f", line=99, key="k", message="y")
        assert a.fingerprint == b.fingerprint


# ---------------------------------------------------------------------------
# lockwatch runtime witness
# ---------------------------------------------------------------------------

class TestLockwatch:
    def test_disabled_structurally_absent(self):
        """Acceptance: off by default — stock factories, no series."""
        from bigdl_tpu import observability as obs
        from bigdl_tpu.utils.conf import conf
        assert conf.get_bool("bigdl.analysis.lockwatch") is False
        assert lockwatch.maybe_install() is False
        assert threading.Lock is lockwatch._ORIG_LOCK
        assert threading.RLock is lockwatch._ORIG_RLOCK
        assert not lockwatch.installed()
        assert "bigdl_lockwatch" not in obs.render()

    def test_inversion_detected(self):
        """The seeded A->B / B->A inversion the ISSUE asks for."""
        lockwatch.install()
        try:
            lockwatch.reset()
            a = threading.Lock()
            b = threading.Lock()
            assert type(a).__name__ == "_WatchedLock"
            with a:
                with b:
                    pass
            assert lockwatch.violations() == []
            with b:
                with a:
                    pass
            vio = lockwatch.violations()
            assert len(vio) == 1
            assert "test_analysis.py" in vio[0]["pair"][0]
        finally:
            lockwatch.uninstall()
            lockwatch.reset()
        assert threading.Lock is lockwatch._ORIG_LOCK

    def test_consistent_order_no_violation(self):
        lockwatch.install()
        try:
            lockwatch.reset()
            # one creation site per lock: site identity is file:line
            a = threading.Lock()
            b = threading.Lock()
            for _ in range(3):
                with a:
                    with b:
                        pass
            assert lockwatch.violations() == []
            assert len(lockwatch.observed_edges()) >= 1
        finally:
            lockwatch.uninstall()
            lockwatch.reset()

    def test_reentrant_rlock_no_edge(self):
        lockwatch.install()
        try:
            lockwatch.reset()
            r = threading.RLock()
            with r:
                with r:            # reentrant: no self-edge, balanced
                    pass
            assert lockwatch.violations() == []
            with r:                # still usable after full release
                pass
        finally:
            lockwatch.uninstall()
            lockwatch.reset()

    def test_watched_lock_backs_condition(self):
        lockwatch.install()
        try:
            lockwatch.reset()
            cv = threading.Condition(threading.RLock())
            hit = []

            def waiter():
                with cv:
                    cv.wait(timeout=5)
                    hit.append(1)

            t = threading.Thread(target=waiter)
            t.start()
            import time
            time.sleep(0.05)
            with cv:
                cv.notify_all()
            t.join(timeout=5)
            assert hit == [1]
        finally:
            lockwatch.uninstall()
            lockwatch.reset()


# ---------------------------------------------------------------------------
# the CI gate
# ---------------------------------------------------------------------------

class TestGate:
    def test_repo_gate_zero_unbaselined(self):
        """THE tier-1 gate: the analyzer over bigdl_tpu/ must report
        zero findings the checked-in baseline does not suppress."""
        out = analysis.check(REPO)
        assert out["baseline_errors"] == []
        assert out["new"] == [], (
            "unbaselined static-analysis findings — fix them or triage "
            "into bigdl_tpu/analysis/baseline.json:\n" +
            "\n".join(f"{f['rule']}: {f['file']}:{f['line']}: "
                      f"{f['message']}" for f in out["new"]))
        assert out["ok"]

    def test_repo_baseline_not_stale(self):
        """Every baseline entry still matches a live finding — the
        baseline only ever shrinks (prune when your fix lands)."""
        out = analysis.check(REPO)
        assert out["stale_baseline"] == []

    def _cli(self, *args):
        env = dict(os.environ)
        return subprocess.run(
            [sys.executable, os.path.join(REPO, "tools",
                                          "check_static.py")] +
            list(args), capture_output=True, text=True, env=env,
            timeout=300)

    def test_cli_fixture_violations_exit_nonzero(self, tmp_path):
        """Acceptance: nonzero exit on each fixture violation, one per
        pass."""
        (tmp_path / "bigdl_tpu").mkdir()
        (tmp_path / "bigdl_tpu" / "mod.py").write_text(BAD_LOCK_ORDER)
        r = self._cli("--root", str(tmp_path), "--passes",
                      "concurrency")
        assert r.returncode == 1, r.stdout + r.stderr
        assert "lock-order" in r.stdout

        (tmp_path / "bigdl_tpu" / "mod.py").write_text(BAD_COMPILED)
        r = self._cli("--root", str(tmp_path), "--passes", "hotpath")
        assert r.returncode == 1
        assert "traced-branch" in r.stdout

        (tmp_path / "bigdl_tpu" / "mod.py").write_text(
            'from bigdl_tpu.utils.conf import conf\n'
            'conf.get("bigdl.nosuch.key")\n')
        r = self._cli("--root", str(tmp_path), "--passes", "registry")
        assert r.returncode == 1
        assert "conf-unregistered" in r.stdout

    def test_cli_missing_justification_exit_2(self, tmp_path):
        (tmp_path / "bigdl_tpu").mkdir()
        (tmp_path / "bigdl_tpu" / "mod.py").write_text("x = 1\n")
        bl = tmp_path / "baseline.json"
        bl.write_text(json.dumps({"entries": [
            {"fingerprint": "lock-order::f::k", "justification": ""}]}))
        r = self._cli("--root", str(tmp_path), "--passes",
                      "concurrency", "--baseline", str(bl))
        assert r.returncode == 2
        assert "BASELINE ERROR" in r.stdout

    @pytest.mark.slow
    def test_cli_repo_clean_exit_0(self):
        """Acceptance: `python tools/check_static.py` exits 0 on the
        repo (the in-process gate test covers the same contract; this
        one pins the CLI surface)."""
        r = self._cli()
        assert r.returncode == 0, r.stdout + r.stderr
        assert "gate clean" in r.stdout

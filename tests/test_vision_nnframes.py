"""Vision pipeline + NNFrames tests (ref patterns: vision transformer
specs + NNEstimator/NNClassifier specs, SURVEY.md §4)."""

import io

import numpy as np
import pandas as pd
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.feature.vision import (
    AspectScale, CenterCrop, ChannelNormalize, ColorJitter, HFlip,
    ImageFeature, ImageFrame, ImageFrameToSample, MatToTensor,
    PixelBytesToMat, RandomCrop, RandomHFlip, Resize)
from bigdl_tpu.nn.module import set_seed
from bigdl_tpu.nnframes import NNClassifier, NNEstimator
from bigdl_tpu.optim.optim_method import Adam


def _png_bytes(h=32, w=48):
    from PIL import Image

    rs = np.random.RandomState(0)
    img = Image.fromarray(rs.randint(0, 255, (h, w, 3), dtype=np.uint8))
    buf = io.BytesIO()
    img.save(buf, format="PNG")
    return buf.getvalue()


class TestVisionPipeline:
    def test_decode_resize_crop_normalize_chain(self):
        feat = ImageFeature(data=_png_bytes(), label=1.0)
        pipeline = (PixelBytesToMat() >> Resize(40, 40)
                    >> CenterCrop(32, 32)
                    >> ChannelNormalize(123, 117, 104, 58, 57, 57)
                    >> MatToTensor() >> ImageFrameToSample())
        feat = pipeline(feat)
        sample = feat[ImageFeature.SAMPLE]
        assert sample.feature().shape == (3, 32, 32)
        assert float(sample.labels[0]) == 1.0
        assert abs(float(sample.feature().mean())) < 3.0  # normalized

    def test_aspect_scale_keeps_ratio(self):
        feat = ImageFeature(data=_png_bytes(h=100, w=200))
        feat = (PixelBytesToMat() >> AspectScale(50))(feat)
        h, w = feat[ImageFeature.MAT].shape[:2]
        assert h == 50 and w == 100

    def test_hflip_and_random_ops(self):
        mat = np.arange(2 * 4 * 3).reshape(2, 4, 3).astype(np.uint8)
        feat = ImageFeature()
        feat[ImageFeature.MAT] = mat
        flipped = HFlip()(feat)[ImageFeature.MAT]
        np.testing.assert_array_equal(flipped, mat[:, ::-1])
        feat2 = ImageFeature()
        feat2[ImageFeature.MAT] = np.zeros((8, 8, 3), np.uint8)
        out = (RandomCrop(4, 4, seed=0) >> RandomHFlip(seed=0))(feat2)
        assert out[ImageFeature.MAT].shape == (4, 4, 3)

    def test_color_jitter_stays_in_range(self):
        feat = ImageFeature(data=_png_bytes())
        feat = (PixelBytesToMat() >> ColorJitter(seed=0))(feat)
        mat = feat[ImageFeature.MAT]
        assert mat.min() >= 0 and mat.max() <= 255

    def test_image_frame_read_and_transform(self, tmp_path):
        p = tmp_path / "img0.png"
        p.write_bytes(_png_bytes())
        frame = ImageFrame.read(str(tmp_path / "*.png"))
        assert len(frame) == 1
        frame.transform(PixelBytesToMat() >> Resize(16, 16)
                        >> MatToTensor() >> ImageFrameToSample())
        samples = frame.to_samples()
        assert samples[0].feature().shape == (3, 16, 16)

    def test_failure_isolation(self):
        bad = ImageFeature(data=b"not an image")
        out = PixelBytesToMat()(bad)
        assert out.get("isValid") is False


class TestNNFrames:
    def test_nnclassifier_fit_transform(self):
        set_seed(0)
        rs = np.random.RandomState(0)
        x = rs.rand(128, 8).astype(np.float32)
        w = rs.randn(8, 3).astype(np.float32)
        labels = (x @ w).argmax(1) + 1.0  # 1-based like Spark ML
        df = pd.DataFrame({"features": [list(r) for r in x],
                           "label": labels})
        model = (nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU())
                 .add(nn.Linear(16, 3)).add(nn.LogSoftMax()))
        clf = (NNClassifier(model, nn.ClassNLLCriterion())
               .set_batch_size(32).set_max_epoch(30)
               .set_optim_method(Adam(learning_rate=0.01)))
        fitted = clf.fit(df)
        out = fitted.transform(df)
        acc = float((out["prediction"].to_numpy() == labels).mean())
        assert acc > 0.9, acc

    def test_nnestimator_regression_feature_size(self):
        set_seed(1)
        rs = np.random.RandomState(1)
        x = rs.rand(96, 4).astype(np.float32)
        y = x.sum(1) * 2
        df = pd.DataFrame({"feat": [list(r) for r in x],
                           "target": [[v] for v in y]})
        from bigdl_tpu.optim.optim_method import SGD
        model = nn.Sequential().add(nn.Linear(4, 1))
        est = (NNEstimator(model, nn.MSECriterion(), feature_size=[4])
               .set_features_col("feat").set_label_col("target")
               .set_batch_size(16).set_max_epoch(60)
               .set_optim_method(SGD(learning_rate=0.3)))
        fitted = est.fit(df)
        res = fitted.transform(df)
        pred = np.stack(res["prediction"].to_numpy()).squeeze()
        assert float(np.mean((pred - y) ** 2)) < 0.05

    def test_nn_image_reader(self, tmp_path):
        from bigdl_tpu.nnframes import NNImageReader

        (tmp_path / "a.png").write_bytes(_png_bytes(16, 16))
        df = NNImageReader.read_images(str(tmp_path / "*.png"))
        assert len(df) == 1
        assert df["image"][0].shape == (16, 16, 3)

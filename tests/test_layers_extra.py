"""Layer-zoo expansion tests: volumetric family, locally-connected,
misc table/reduce/distance layers, and the sparse stack — golden parity
vs torch / numpy (the reference's per-layer spec pattern, SURVEY.md §4).
"""

import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import set_seed
from bigdl_tpu.tensor import SparseTensor
from bigdl_tpu.utils.table import T


class TestVolumetric:
    def test_conv3d_matches_torch(self):
        torch = pytest.importorskip("torch")
        set_seed(0)
        layer = nn.VolumetricConvolution(3, 5, 3, 3, 3, d_t=2, d_w=1,
                                         d_h=1, pad_t=1, pad_w=1, pad_h=1)
        rs = np.random.RandomState(0)
        x = rs.randn(2, 3, 6, 8, 8).astype(np.float32)
        w = np.asarray(layer.parameters_dict()["weight"])
        b = np.asarray(layer.parameters_dict()["bias"])
        ref = torch.nn.functional.conv3d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=(2, 1, 1), padding=(1, 1, 1)).numpy()
        out = np.asarray(layer.forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_transposed_conv3d_matches_torch(self):
        torch = pytest.importorskip("torch")
        set_seed(0)
        layer = nn.VolumetricFullConvolution(3, 4, 2, 2, 2, d_t=2,
                                             d_w=2, d_h=2)
        rs = np.random.RandomState(1)
        x = rs.randn(1, 3, 4, 5, 5).astype(np.float32)
        w = np.asarray(layer.parameters_dict()["weight"])
        b = np.asarray(layer.parameters_dict()["bias"])
        ref = torch.nn.functional.conv_transpose3d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=(2, 2, 2)).numpy()
        out = np.asarray(layer.forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_avg_pool3d_matches_torch(self):
        torch = pytest.importorskip("torch")
        layer = nn.VolumetricAveragePooling(2, 2, 2)
        rs = np.random.RandomState(2)
        x = rs.randn(1, 2, 4, 6, 6).astype(np.float32)
        ref = torch.nn.functional.avg_pool3d(torch.tensor(x), 2).numpy()
        out = np.asarray(layer.forward(jnp.asarray(x)))
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-6)

    def test_crop_and_upsample_roundtrip(self):
        x = jnp.asarray(np.arange(2 * 1 * 2 * 2 * 2, dtype=np.float32)
                        .reshape(2, 1, 2, 2, 2))
        up = nn.UpSampling3D((2, 2, 2)).forward(x)
        assert up.shape == (2, 1, 4, 4, 4)
        crop = nn.Cropping3D((1, 1), (1, 1), (1, 1)).forward(up)
        assert crop.shape == (2, 1, 2, 2, 2)


class TestLocallyConnected2D:
    def test_matches_explicit_loop(self):
        set_seed(1)
        layer = nn.LocallyConnected2D(2, 5, 6, 3, 2, 2)
        rs = np.random.RandomState(3)
        x = rs.randn(2, 2, 5, 6).astype(np.float32)
        out = np.asarray(layer.forward(jnp.asarray(x)))
        w = np.asarray(layer.parameters_dict()["weight"])
        b = np.asarray(layer.parameters_dict()["bias"])
        oh, ow = layer.oh, layer.ow
        ref = np.zeros((2, 3, oh, ow), np.float32)
        for i in range(oh):
            for j in range(ow):
                patch = x[:, :, i:i + 2, j:j + 2].reshape(2, -1)
                ref[:, :, i, j] = patch @ w[i * ow + j].T + b[:, i, j]
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)


class TestMiscLayers:
    def test_reduce_layers(self):
        rs = np.random.RandomState(0)
        x = rs.randn(4, 6).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(nn.Max(dim=2).forward(jnp.asarray(x))),
            x.max(1), rtol=1e-6)
        np.testing.assert_allclose(
            np.asarray(nn.Mean(2).forward(jnp.asarray(x))),
            x.mean(1), rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(nn.Sum(1).forward(jnp.asarray(x))),
            x.sum(0), rtol=1e-5)

    def test_distance_layers(self):
        rs = np.random.RandomState(1)
        a = rs.randn(4, 6).astype(np.float32)
        b = rs.randn(4, 6).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(nn.DotProduct().forward(T(a, b))),
            (a * b).sum(1), rtol=1e-5)
        cos = (a * b).sum(1) / (np.linalg.norm(a, axis=1)
                                * np.linalg.norm(b, axis=1) + 1e-12)
        np.testing.assert_allclose(
            np.asarray(nn.CosineDistance().forward(T(a, b))), cos,
            rtol=1e-5)
        np.testing.assert_allclose(
            np.asarray(nn.PairwiseDistance().forward(T(a, b))),
            np.linalg.norm(a - b, axis=1), rtol=1e-5)

    def test_mm_mv_index(self):
        rs = np.random.RandomState(2)
        a = rs.randn(2, 3, 4).astype(np.float32)
        b = rs.randn(2, 4, 5).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(nn.MM().forward(T(a, b))), a @ b, rtol=1e-5)
        v = rs.randn(2, 4).astype(np.float32)
        np.testing.assert_allclose(
            np.asarray(nn.MV().forward(T(a, v))),
            np.einsum("bij,bj->bi", a, v), rtol=1e-5)
        t = rs.randn(5, 3).astype(np.float32)
        idx = np.array([1, 4])
        np.testing.assert_allclose(
            np.asarray(nn.Index(1).forward(T(t, idx))), t[[0, 3]],
            rtol=1e-6)

    def test_maxout_srelu_highway_shapes_and_grads(self):
        import jax
        set_seed(2)
        x = jnp.asarray(np.random.RandomState(4)
                        .randn(4, 6).astype(np.float32))
        for layer in (nn.Maxout(6, 3, 4), nn.SReLU((6,)), nn.Highway(6)):
            y = layer.forward(x)
            assert np.isfinite(np.asarray(y)).all()
            params = layer.parameters_dict()

            def loss(p):
                out, _ = layer.apply(p, layer.states_dict(), x,
                                     training=False, rng=None)
                return jnp.sum(out ** 2)

            g = jax.grad(loss)(params)
            assert all(np.isfinite(np.asarray(l)).all()
                       for l in jax.tree_util.tree_leaves(g))

    def test_time_distributed_equals_per_step(self):
        set_seed(3)
        inner = nn.Linear(6, 3)
        td = nn.TimeDistributed(inner)
        # share the inner layer's weights
        td.load_parameters_dict({"layer": inner.parameters_dict()})
        rs = np.random.RandomState(5)
        x = rs.randn(2, 5, 6).astype(np.float32)
        out = np.asarray(td.forward(jnp.asarray(x)))
        for t in range(5):
            step = np.asarray(inner.forward(jnp.asarray(x[:, t])))
            np.testing.assert_allclose(out[:, t], step, rtol=1e-5,
                                       atol=1e-6)


class TestSparseStack:
    def test_sparse_tensor_roundtrip_and_bcoo(self):
        d = np.array([[1., 0, 2], [0, 0, 3]], np.float32)
        st = SparseTensor.from_dense(d)
        assert st.nnz == 3
        np.testing.assert_allclose(np.asarray(st.to_dense()), d)
        bc = st.to_bcoo()
        st2 = SparseTensor.from_bcoo(bc)
        np.testing.assert_allclose(np.asarray(st2.to_dense()), d)

    def test_sparse_linear_matches_dense(self):
        set_seed(4)
        sl = nn.SparseLinear(8, 5)
        rs = np.random.RandomState(6)
        d = rs.randn(4, 8).astype(np.float32)
        d[rs.rand(4, 8) < 0.6] = 0.0
        out = np.asarray(sl.forward(SparseTensor.from_dense(d)))
        w = np.asarray(sl.parameters_dict()["weight"])
        b = np.asarray(sl.parameters_dict()["bias"])
        np.testing.assert_allclose(out, d @ w.T + b, rtol=1e-4,
                                   atol=1e-5)

    def test_lookup_table_sparse_combiners(self):
        set_seed(5)
        ids = np.array([[1, 2, 0], [3, 0, 0]])
        for combiner in ("sum", "mean", "sqrtn"):
            layer = nn.LookupTableSparse(10, 4, combiner=combiner)
            w = np.asarray(layer.parameters_dict()["weight"])
            out = np.asarray(layer.forward(ids))
            row0 = w[0] + w[1]
            row1 = w[2]
            if combiner == "mean":
                row0 = row0 / 2
            elif combiner == "sqrtn":
                row0 = row0 / np.sqrt(2)
            np.testing.assert_allclose(out[0], row0, rtol=1e-5,
                                       atol=1e-6)
            np.testing.assert_allclose(out[1], row1, rtol=1e-5,
                                       atol=1e-6)

    def test_sparse_join_table(self):
        a = SparseTensor.from_dense(np.array([[1., 0], [0, 2.]]))
        b = SparseTensor.from_dense(np.array([[0., 3.], [4., 0]]))
        joined = nn.SparseJoinTable(2).forward(T(a, b))
        assert joined.shape == (2, 4)
        np.testing.assert_allclose(
            np.asarray(joined.to_dense()),
            [[1, 0, 0, 3], [0, 2, 4, 0]])


class TestExtra2Layers:
    def test_reverse_tile_pack(self):
        import numpy as np
        import bigdl_tpu.nn as nn
        x = np.arange(6, dtype=np.float32).reshape(2, 3)
        np.testing.assert_allclose(
            np.asarray(nn.Reverse(2).forward(x)), x[:, ::-1])
        np.testing.assert_allclose(
            np.asarray(nn.Tile(1, 2).forward(x)),
            np.tile(x, (2, 1)))
        np.testing.assert_allclose(
            np.asarray(nn.Pack(1).forward([x, x + 1])),
            np.stack([x, x + 1], 0))

    def test_masked_fill_and_narrow_table(self):
        import numpy as np
        import bigdl_tpu.nn as nn
        x = np.ones((2, 3), np.float32)
        m = np.array([[1, 0, 1], [0, 0, 1]], bool)
        out = np.asarray(nn.MaskedFill(-9.0).forward([x, m]))
        np.testing.assert_allclose(out, np.where(m, -9.0, 1.0))
        t = [np.zeros(2), np.ones(2), np.full(2, 2.0)]
        picked = nn.NarrowTable(2, 1).forward(t)
        np.testing.assert_allclose(np.asarray(picked), 1.0)

    def test_mixture_table(self):
        import numpy as np
        import bigdl_tpu.nn as nn
        gates = np.array([[0.25, 0.75]], np.float32)
        e1 = np.full((1, 4), 1.0, np.float32)
        e2 = np.full((1, 4), 3.0, np.float32)
        out = np.asarray(nn.MixtureTable().forward([gates, [e1, e2]]))
        np.testing.assert_allclose(out, 0.25 * 1 + 0.75 * 3)

    def test_gradient_reversal(self):
        import jax
        import jax.numpy as jnp
        import numpy as np
        import bigdl_tpu.nn as nn
        layer = nn.GradientReversal(the_lambda=2.0)

        def f(x):
            y = layer._apply(None, None, x, training=True, rng=None)
            return jnp.sum(y * y)

        x = jnp.asarray([1.0, -2.0])
        g = jax.grad(f)(x)
        np.testing.assert_allclose(np.asarray(g), [-4.0, 8.0])  # -λ·2x

    def test_contrastive_normalization_zero_mean(self):
        import numpy as np
        import bigdl_tpu.nn as nn
        rs = np.random.RandomState(0)
        x = rs.randn(2, 3, 12, 12).astype(np.float32) * 5 + 10
        y = np.asarray(nn.SpatialSubtractiveNormalization(3).forward(x))
        # local mean removed: per-image mean shrinks dramatically
        assert abs(y.mean()) < abs(x.mean()) * 0.1
        z = np.asarray(nn.SpatialContrastiveNormalization(3).forward(x))
        assert np.isfinite(z).all()

    def test_conv_lstm_shapes_and_determinism(self):
        import numpy as np
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn.module import set_seed
        set_seed(0)
        layer = nn.ConvLSTMPeephole(2, 4, 3)
        x = np.random.RandomState(0).randn(1, 5, 2, 6, 6) \
            .astype(np.float32)
        y = np.asarray(layer.forward(x))
        assert y.shape == (1, 5, 4, 6, 6)
        assert np.isfinite(y).all()
        # later steps depend on earlier input (recurrence is real)
        x2 = x.copy(); x2[0, 0] += 1.0
        y2 = np.asarray(layer.forward(x2))
        assert np.abs(y2[0, -1] - y[0, -1]).max() > 1e-6

    def test_l1_penalty_records(self):
        import numpy as np
        import bigdl_tpu.nn as nn
        layer = nn.L1Penalty(l1weight=0.1)
        x = np.array([[1.0, -2.0]], np.float32)
        layer.training()
        y = layer.forward(x)
        np.testing.assert_allclose(np.asarray(y), x)
        np.testing.assert_allclose(float(layer.last_penalty), 0.3)

"""Model-zoo tests (ref pattern: models/* specs — forward shape checks plus
small-scale convergence, SURVEY.md §4)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.models import autoencoder, inception, resnet, rnn, vgg
from bigdl_tpu.nn.module import set_seed


def _jit_forward(model, x):
    params = model.parameters_dict()
    states = model.states_dict()

    @jax.jit
    def fwd(p, s, xi):
        y, _ = model.apply(p, s, xi, training=False, rng=None)
        return y

    return np.asarray(fwd(params, states, jnp.asarray(x)))


class TestForwardShapes:
    def test_resnet_cifar(self):
        set_seed(0)
        m = resnet.resnet_cifar(depth=20, class_num=10)
        y = _jit_forward(m, np.random.rand(2, 3, 32, 32).astype(np.float32))
        assert y.shape == (2, 10)
        np.testing.assert_allclose(np.exp(y).sum(1), 1.0, rtol=1e-3)

    def test_resnet50_imagenet(self):
        set_seed(0)
        m = resnet.resnet_imagenet(depth=50, class_num=1000)
        y = _jit_forward(m, np.random.rand(1, 3, 64, 64).astype(np.float32))
        assert y.shape == (1, 1000)

    def test_resnet18_imagenet(self):
        set_seed(0)
        m = resnet.resnet_imagenet(depth=18, class_num=100)
        y = _jit_forward(m, np.random.rand(1, 3, 64, 64).astype(np.float32))
        assert y.shape == (1, 100)

    def test_inception_v1(self):
        set_seed(0)
        m = inception.inception_v1(class_num=1000)
        y = _jit_forward(m, np.random.rand(1, 3, 224, 224).astype(np.float32))
        assert y.shape == (1, 1000)

    def test_vgg_cifar(self):
        set_seed(0)
        m = vgg.vgg_cifar(class_num=10)
        y = _jit_forward(m, np.random.rand(2, 3, 32, 32).astype(np.float32))
        assert y.shape == (2, 10)

    def test_autoencoder(self):
        set_seed(0)
        m = autoencoder.build_model(32)
        y = _jit_forward(m, np.random.rand(4, 28 * 28).astype(np.float32))
        assert y.shape == (4, 28 * 28)

    @pytest.mark.parametrize("cell", ["rnn", "lstm", "gru"])
    def test_rnn_lm(self, cell):
        set_seed(0)
        m = rnn.build_model(50, 16, 50, cell=cell)
        tokens = np.random.randint(1, 51, size=(3, 7)).astype(np.int32)
        y = _jit_forward(m, tokens)
        assert y.shape == (3, 7, 50)


class TestConvergence:
    def test_resnet_cifar_overfits_tiny_batch(self):
        """The reference's per-model train mains are smoke-level; here:
        8 samples must be memorized in a few hundred steps."""
        set_seed(5)
        m = resnet.resnet_cifar(depth=8, class_num=4)
        crit = nn.ClassNLLCriterion()
        from bigdl_tpu.optim.optim_method import Adam
        optim = Adam(learning_rate=3e-3)
        rs = np.random.RandomState(0)
        x = jnp.asarray(rs.rand(8, 3, 16, 16).astype(np.float32))
        t = jnp.asarray((np.arange(8) % 4 + 1).astype(np.int32))
        params = m.parameters_dict()
        states = m.states_dict()
        opt_state = optim.init_state(params)

        @jax.jit
        def step(p, s, o, rng):
            def loss_fn(pp):
                y, s2 = m.apply(pp, s, x, training=True, rng=rng)
                return crit.apply_loss(y, t), s2
            (loss, s2), g = jax.value_and_grad(loss_fn, has_aux=True)(p)
            p2, o2 = optim.step(p, g, o, 3e-3)
            return p2, s2, o2, loss

        key = jax.random.PRNGKey(0)
        loss = None
        for i in range(150):
            key, sub = jax.random.split(key)
            params, states, opt_state, loss = step(params, states,
                                                   opt_state, sub)
        assert float(loss) < 0.1, f"final loss {float(loss)}"

"""Legacy checkpoint importers (ref: CaffeLoader / TensorflowLoader /
torch loaders under S:dllib/utils — SURVEY.md §2.3 serialization row)."""

import struct

import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import set_seed
from bigdl_tpu.utils.importers import (
    CaffeLoader, load_tf_checkpoint, load_torch_state_dict)


class TestTorchImport:
    def test_state_dict_by_name_mapping_and_shape(self):
        torch = pytest.importorskip("torch")
        tmodel = torch.nn.Sequential(
            torch.nn.Linear(8, 16), torch.nn.ReLU(),
            torch.nn.Linear(16, 4))
        set_seed(0)
        ours = (nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU())
                .add(nn.Linear(16, 4)))
        n = load_torch_state_dict(ours, tmodel.state_dict())
        assert n == 4    # 2 weights + 2 biases
        x = np.random.RandomState(0).randn(3, 8).astype(np.float32)
        with torch.no_grad():
            ref = tmodel(torch.tensor(x)).numpy()
        got = np.asarray(ours.forward(x))
        np.testing.assert_allclose(got, ref, rtol=1e-4, atol=1e-5)

    def test_file_roundtrip_weights_only(self, tmp_path):
        torch = pytest.importorskip("torch")
        tmodel = torch.nn.Linear(5, 3)
        p = str(tmp_path / "w.pt")
        torch.save(tmodel.state_dict(), p)
        set_seed(0)
        ours = nn.Linear(5, 3)
        assert load_torch_state_dict(ours, p) == 2
        np.testing.assert_allclose(
            np.asarray(ours.parameters_dict()["weight"]),
            tmodel.weight.detach().numpy(), rtol=1e-6)


class TestTFImport:
    def test_tf2_checkpoint_variables(self, tmp_path):
        tf = pytest.importorskip("tensorflow")
        tf.keras.utils.set_random_seed(0)
        dense = tf.keras.layers.Dense(4)
        dense.build((None, 6))
        ckpt = tf.train.Checkpoint(w=dense.kernel, b=dense.bias)
        path = ckpt.write(str(tmp_path / "ck"))
        set_seed(0)
        ours = nn.Linear(6, 4)
        n = load_tf_checkpoint(ours, path)
        assert n == 2
        # TF kernel (in, out) was transposed into our (out, in)
        np.testing.assert_allclose(
            np.asarray(ours.parameters_dict()["weight"]),
            dense.kernel.numpy().T, rtol=1e-6)


def _varint(v):
    out = b""
    while True:
        b7 = v & 0x7F
        v >>= 7
        if v:
            out += bytes([b7 | 0x80])
        else:
            return out + bytes([b7])


def _ld(field, payload):     # length-delimited field
    return _varint((field << 3) | 2) + _varint(len(payload)) + payload


class TestCaffeLoader:
    def _blob(self, arr):
        shape = b"".join(_varint((1 << 3) | 0) + _varint(d)
                         for d in arr.shape)
        data = _ld(5, arr.astype("<f4").tobytes())
        return _ld(7, shape) + data

    def test_parse_synthetic_caffemodel(self, tmp_path):
        """Hand-encode a NetParameter with one conv layer (weights +
        bias blobs) and parse it back."""
        w = np.arange(24, dtype=np.float32).reshape(2, 3, 2, 2)
        b = np.array([0.5, -0.5], np.float32)
        layer = (_ld(1, b"conv1") + _ld(2, b"Convolution")
                 + _ld(7, self._blob(w)) + _ld(7, self._blob(b)))
        net = _ld(1, b"testnet") + _ld(100, layer)
        p = tmp_path / "net.caffemodel"
        p.write_bytes(net)
        layers = CaffeLoader.load(str(p))
        assert "conv1" in layers
        np.testing.assert_allclose(layers["conv1"][0], w)
        np.testing.assert_allclose(layers["conv1"][1], b)

    def test_v2_param_spec_field_not_a_blob(self, tmp_path):
        """V2 field 6 is repeated ParamSpec (lr_mult etc.), NOT blobs —
        it must not shift the blob0=weight/blob1=bias convention."""
        w = np.ones((2, 2), np.float32)
        b = np.full(2, 7.0, np.float32)
        param_spec = _ld(1, b"shared_w")        # ParamSpec.name = 1
        layer = (_ld(1, b"ip1") + _ld(2, b"InnerProduct")
                 + _ld(6, param_spec)           # would misparse as blob
                 + _ld(7, self._blob(w)) + _ld(7, self._blob(b)))
        p = tmp_path / "v2.caffemodel"
        p.write_bytes(_ld(100, layer))
        layers = CaffeLoader.load(str(p))
        assert len(layers["ip1"]) == 2
        np.testing.assert_allclose(layers["ip1"][0], w)
        np.testing.assert_allclose(layers["ip1"][1], b)

    def test_v1_layer_name_and_blobs(self, tmp_path):
        """V1LayerParameter: bottom=2, top=3, name=4, blobs=6."""
        w = np.arange(6, dtype=np.float32).reshape(2, 3)
        b = np.array([1.0, 2.0], np.float32)
        layer = (_ld(2, b"data") + _ld(3, b"fc1_out") + _ld(4, b"fc1")
                 + _ld(6, self._blob(w)) + _ld(6, self._blob(b)))
        p = tmp_path / "v1.caffemodel"
        p.write_bytes(_ld(2, layer))            # NetParameter.layers = 2
        layers = CaffeLoader.load(str(p))
        assert "fc1" in layers
        np.testing.assert_allclose(layers["fc1"][0], w)
        np.testing.assert_allclose(layers["fc1"][1], b)

    def test_load_into_model(self, tmp_path):
        w = np.random.RandomState(0).randn(4, 3, 3, 3).astype(np.float32)
        b = np.zeros(4, np.float32)
        layer = (_ld(1, b"conv1") + _ld(2, b"Convolution")
                 + _ld(7, self._blob(w)) + _ld(7, self._blob(b)))
        p = tmp_path / "m.caffemodel"
        p.write_bytes(_ld(100, layer))
        set_seed(0)
        model = nn.Sequential().add(
            nn.SpatialConvolution(3, 4, 3, 3, name="conv1"))
        n = CaffeLoader.load_into(model, str(p))
        assert n == 2
        got = np.asarray(model.parameters_dict()["0"]["weight"])
        np.testing.assert_allclose(got, w, rtol=1e-6)

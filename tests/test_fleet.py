"""Elastic serving fleet (ISSUE 15): graceful drain with KV handoff,
the drain-aware prober/router path, the autoscaler's control loop, the
worker-provider lifecycle, shutdown-during-drain hygiene, scale-in
under pipelining, the load generator, and the disabled-mode
structural-absence contract for ``bigdl.llm.fleet.enabled``.

The soak with mid-drain kills lives in ``tools/chaos_check.py
--fleet``; these tests pin each mechanism in isolation."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu import reliability as rel
from bigdl_tpu.llm.failover import HealthProber
from bigdl_tpu.llm.fleet import (DrainCoordinator, FleetController,
                                 LocalWorkerProvider, WorkerProvider,
                                 fleet_enabled)
from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import LLMServer
from bigdl_tpu.llm.worker import LLMRouter, LLMWorker
from bigdl_tpu.utils.conf import conf

pytestmark = pytest.mark.fleet


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=128)


@pytest.fixture()
def sync_tier():
    """Inline (synchronous) host-tier migrations for deterministic
    spills/fetches; conf restored exactly."""
    with conf._lock:
        prev = conf._set_layer.get("bigdl.llm.kvtier.sync")
    conf.set("bigdl.llm.kvtier.sync", "true")
    yield
    if prev is None:
        conf.unset("bigdl.llm.kvtier.sync")
    else:
        conf.set("bigdl.llm.kvtier.sync", prev)


@pytest.fixture()
def faults_armed():
    was = rel.enabled()
    if not was:
        rel.enable()
    yield
    rel.set_plan(None)
    if not was:
        rel.disable()


def _generate(model, p, n):
    return list(map(int, model.generate(np.asarray(p)[None],
                                        max_new_tokens=n)[0, len(p):]))


def _req(addr, method, path, body=None, timeout=120):
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        payload = json.dumps(body) if body is not None else None
        conn.request(method, path, payload,
                     {"Content-Type": "application/json"}
                     if body is not None else {})
        r = conn.getresponse()
        return r.status, json.loads(r.read().decode()), \
            dict(r.getheaders())
    finally:
        conn.close()


def _mk_server(model, **kw):
    args = dict(max_batch=2, max_seq_len=64, page_size=8, num_pages=24,
                kvcache=True, kvtier=True, host_pages=64)
    args.update(kw)
    return LLMServer(model, **args)


def _wait(cond, timeout=30.0, interval=0.01):
    t0 = time.time()
    while time.time() - t0 < timeout:
        if cond():
            return True
        time.sleep(interval)
    return False


# ---------------------------------------------------------------------------
# engine drain primitives + warm-chain enumeration
# ---------------------------------------------------------------------------

class TestEngineDrain:
    def test_begin_cancel_drain_and_idle(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8).start()
        try:
            assert not srv.draining and srv.engine_idle()
            srv.begin_drain()
            assert srv.draining
            with pytest.raises(rel.OverloadError, match="draining"):
                srv.submit(np.arange(6, dtype=np.int32),
                           max_new_tokens=2)
            srv.cancel_drain()
            assert not srv.draining
            r = srv.submit(np.arange(6, dtype=np.int32),
                           max_new_tokens=2)
            assert len(r.get(timeout=300)) == 2
        finally:
            srv.stop()

    def test_warm_chains_maximal_and_disabled(self, model, sync_tier):
        srv = _mk_server(model).start()
        try:
            rs = np.random.RandomState(0)
            shared = rs.randint(0, 250, 16).astype(np.int32)
            p1 = np.concatenate([shared,
                                 rs.randint(0, 250, 8).astype(np.int32)])
            srv.submit(shared, max_new_tokens=2).get(timeout=300)
            srv.submit(p1, max_new_tokens=2).get(timeout=300)
            chains = srv.warm_chains()
            assert chains, "no warm chains after two indexed requests"
            keys = [tuple(c) for c in chains]
            # maximal only: no chain is a prefix of another
            for a in keys:
                for b in keys:
                    if a is not b:
                        assert b[:len(a)] != a, \
                            f"chain {a} is a prefix of {b}"
            # every chain is full pages
            assert all(len(c) % 8 == 0 for c in keys)
        finally:
            srv.stop()
        off = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8)
        assert off.warm_chains() == []


class TestDrainCoordinator:
    def test_drain_migrates_chains_to_survivor(self, model, sync_tier):
        a = _mk_server(model).start()
        b = _mk_server(model).start()
        wa = LLMWorker(a, role="decode", fleet=True).start()
        wb = LLMWorker(b, role="decode", fleet=True).start()
        try:
            rs = np.random.RandomState(1)
            p = rs.randint(0, 250, 24).astype(np.int32)
            golden = _generate(model, p, 2)
            assert list(map(int, a.submit(p, max_new_tokens=2)
                            .get(timeout=300))) == golden
            st, body, _ = _req(wa.address, "POST", "/worker_drain",
                               {"action": "begin",
                                "peers": [list(wb.address)],
                                "timeout": 30.0})
            assert st == 200, body
            assert _wait(lambda: wa._drain.status()["state"]
                         == "drained"), wa._drain.status()
            stt = wa._drain.status()
            assert stt["migrated_chains"] >= 1 and \
                stt["migrated_pages"] >= 1, stt
            # healthz reports draining (503) once the drain holds
            st, hz, _ = _req(wa.address, "GET", "/healthz")
            assert st == 503 and hz["status"] == "draining"
            # new work sheds with the draining marker
            st, shed, _ = _req(wa.address, "POST", "/worker_generate",
                               {"prompt_ids": [int(t) for t in p],
                                "max_new_tokens": 2})
            assert st == 503 and shed.get("draining") is True, shed
            # the survivor's arena holds the chains and serves a
            # prefix hit for the same prompt
            assert b._tier.arena.used() >= 1
            before = b._kv.prefix_tokens_reused
            assert list(map(int, b.submit(p, max_new_tokens=2)
                            .get(timeout=300))) == golden
            assert b._kv.prefix_tokens_reused > before, \
                "survivor served no prefix hit from migrated chains"
            # drain GET status endpoint mirrors the coordinator
            st, got, _ = _req(wa.address, "GET", "/worker_drain")
            assert st == 200 and got["state"] == "drained"
        finally:
            wa.stop()
            wb.stop()
            a.stop(drain=False)
            b.stop()

    def test_drain_finishes_inflight_first(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8, kvcache=True).start()
        w = LLMWorker(srv, role="decode", fleet=True).start()
        try:
            p = np.arange(8, dtype=np.int32)
            golden = _generate(model, p, 12)
            r = srv.submit(p, max_new_tokens=12)
            assert w._drain.begin([], timeout=60.0)
            # the accepted request finishes with the full answer
            assert list(map(int, r.get(timeout=300))) == golden
            assert _wait(lambda: w._drain.status()["state"]
                         == "drained")
        finally:
            w.stop()
            srv.stop(drain=False)

    def test_double_begin_conflicts_and_cancel_resumes(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8).start()
        w = LLMWorker(srv, role="decode", fleet=True).start()
        try:
            r = srv.submit(np.arange(6, dtype=np.int32),
                           max_new_tokens=8)   # keeps phase 1 waiting
            assert w._drain.begin([], timeout=60.0)
            st, body, _ = _req(w.address, "POST", "/worker_drain",
                               {"action": "begin"})
            assert st == 409, body
            st, body, _ = _req(w.address, "POST", "/worker_drain",
                               {"action": "cancel"})
            assert st == 200
            assert not srv.draining, \
                "cancel must resume admission"
            r.get(timeout=300)
            srv.submit(np.arange(6, dtype=np.int32),
                       max_new_tokens=1).get(timeout=300)
        finally:
            w.stop()
            srv.stop()

    def test_worker_stop_during_active_drain(self, model, sync_tier,
                                             faults_armed):
        """Shutdown mid-drain (satellite): the drain thread is joined,
        no migration posts are orphaned, no arena slots stay pinned on
        either side."""
        a = _mk_server(model).start()
        b = _mk_server(model).start()
        wa = LLMWorker(a, role="decode", fleet=True).start()
        wb = LLMWorker(b, role="decode", fleet=True).start()
        try:
            rs = np.random.RandomState(2)
            for j in range(3):
                a.submit(rs.randint(0, 250, 16 + 8 * j)
                         .astype(np.int32),
                         max_new_tokens=2).get(timeout=300)
            plan = rel.FaultPlan(seed=0)
            plan.add("worker.drain", "delay", times=None, delay=0.1)
            rel.set_plan(plan)
            assert wa._drain.begin([list(wb.address)], timeout=60.0)
            assert _wait(lambda: wa._drain.status()["state"]
                         in ("migrating", "drained"), timeout=10.0)
            wa.stop()       # mid-migration shutdown
            assert not wa._drain.active(), \
                "stop() left the drain thread running"
            assert not [t for t in threading.enumerate()
                        if t.name == "bigdl-fleet-drain"]
            assert a._tier.arena.pinned() == 0
            assert b._tier.arena.pinned() == 0
            # shutdown path keeps admission closed
            assert a.draining
        finally:
            rel.set_plan(None)
            wb.stop()
            a.stop(drain=False)
            b.stop()


# ---------------------------------------------------------------------------
# prober + router drain awareness (satellite: DRAINING != dead)
# ---------------------------------------------------------------------------

class TestDrainAwareRouting:
    def test_prober_state_distinguishes_draining_dead(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8).start()
        w = LLMWorker(srv, role="decode", fleet=True).start()
        dead_addr = ("127.0.0.1", 1)      # nothing listens there
        prober = HealthProber(
            lambda: [(tuple(w.address), "decode"),
                     (dead_addr, "decode")], interval=60.0)
        assert prober.state(tuple(w.address)) == "ok"   # unprobed
        prober.probe_now()
        assert prober.state(tuple(w.address)) == "ok"
        assert prober.state(dead_addr) == "dead"
        assert not prober.healthy(dead_addr)
        srv.begin_drain()
        prober.probe_now()
        assert prober.state(tuple(w.address)) == "draining"
        assert not prober.healthy(tuple(w.address))
        # out-of-band marks (the router's bounce / abandoned drain)
        prober.mark(tuple(w.address), "ok")
        assert prober.healthy(tuple(w.address))
        states = prober.states()
        assert states[f"{dead_addr[0]}:{dead_addr[1]}"] == "dead"
        w.stop()
        srv.stop()

    def test_drain_bounces_without_tripping_breaker(self, model):
        """Regression (satellite): a draining backend must NEVER trip
        the circuit breaker or count as a failover — the request
        re-routes to a live backend and succeeds."""
        s1 = LLMServer(model, max_batch=2, max_seq_len=64,
                       page_size=8, kvcache=True).start()
        s2 = LLMServer(model, max_batch=2, max_seq_len=64,
                       page_size=8, kvcache=True).start()
        w1 = LLMWorker(s1, role="decode", fleet=True).start()
        w2 = LLMWorker(s2, role="decode", fleet=True).start()
        router = LLMRouter([], [w1.address, w2.address], failover=True,
                           start_prober=False).start()
        try:
            p = np.arange(10, dtype=np.int32)
            golden = _generate(model, p, 3)
            # round-robin starts at w1, which is draining: the dispatch
            # bounces and must land on w2
            s1.begin_drain()
            st, body, _ = _req(router.address, "POST",
                               "/worker_generate",
                               {"prompt_ids": [int(t) for t in p],
                                "max_new_tokens": 3})
            assert st == 200, body
            assert body["output_ids"] == golden
            b1 = router._breakers[tuple(w1.address)]
            assert b1.state == "closed", \
                "a drain shed tripped the circuit breaker"
            assert router.failovers == 0, \
                "a drain bounce was counted as a failover"
            assert router._prober.state(tuple(w1.address)) == "draining"
            # in-flight work on the draining backend still completes:
            # the engine keeps decoding what it accepted
            r = None
            s1.cancel_drain()
            r = s1.submit(p, max_new_tokens=3)
            s1.begin_drain()
            assert list(map(int, r.get(timeout=300))) == golden
        finally:
            router.stop()
            w1.stop()
            w2.stop()
            s1.stop(drain=False)
            s2.stop()

    def test_all_draining_sheds_with_retry_after(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8).start()
        w = LLMWorker(srv, role="decode", fleet=True).start()
        router = LLMRouter([], [w.address], failover=True,
                           start_prober=False).start()
        try:
            srv.begin_drain()
            st, body, hdrs = _req(
                router.address, "POST", "/worker_generate",
                {"prompt_ids": list(range(6)), "max_new_tokens": 1})
            assert st == 503
            assert "Retry-After" in hdrs
        finally:
            router.stop()
            w.stop()
            srv.stop(drain=False)


# ---------------------------------------------------------------------------
# scale-in under pipelining (satellite)
# ---------------------------------------------------------------------------

class TestScaleInUnderPipelining:
    def test_drain_with_inflight_fences_and_parked_fetch(
            self, model, faults_armed):
        """Drain a depth-4 worker holding multiple in-flight fences AND
        a parked (delayed) kvtier fetch: everything finishes, outputs
        are bit-identical, and the page/budget ledger returns to
        idle."""
        num_pages = 24
        a = _mk_server(model, pipeline_depth=4,
                       num_pages=num_pages).start()
        b = _mk_server(model).start()
        wa = LLMWorker(a, role="decode", fleet=True).start()
        wb = LLMWorker(b, role="decode", fleet=True).start()
        try:
            rs = np.random.RandomState(3)
            warm = rs.randint(0, 250, 24).astype(np.int32)
            others = [rs.randint(0, 250, 10 + 2 * j).astype(np.int32)
                      for j in range(2)]
            goldens = {tuple(map(int, p)): _generate(model, p, 4)
                       for p in [warm] + others}
            # plant warm's chain in A's ARENA (import via handoff from
            # B, so the next admission on A must FETCH it)
            b.submit(warm, max_new_tokens=1).get(timeout=300)
            blob = b.export_chain(warm)
            assert a.import_chain(blob) >= 1
            # park the fetch: every kvtier.fetch is delayed, so the
            # warm admission waits while decode requests pipeline
            plan = rel.FaultPlan(seed=0)
            plan.add("kvtier.fetch", "delay", times=None, delay=0.3)
            rel.set_plan(plan)
            reqs = [a.submit(p, max_new_tokens=4) for p in others]
            rwarm = a.submit(warm, max_new_tokens=4)
            assert wa._drain.begin([list(wb.address)], timeout=60.0)
            for p, r in zip(others + [warm], reqs + [rwarm]):
                assert list(map(int, r.get(timeout=300))) == \
                    goldens[tuple(map(int, p))]
            assert _wait(lambda: wa._drain.status()["state"]
                         == "drained"), wa._drain.status()
            # ledger idle: every charge returned, nothing pinned
            assert a.engine_idle()
            assert a._budget_avail == num_pages - 1
            assert a._tier.arena.pinned() == 0
            assert not a._inflight
        finally:
            rel.set_plan(None)
            wa.stop()
            wb.stop()
            a.stop(drain=False)
            b.stop()

    def test_kill_pipelined_worker_resumes_bit_identical(
            self, model, faults_armed):
        """KILL (not drain) a depth-4 worker mid-stream through the
        failover router: the journal resumes on the survivor with
        bit-identical greedy output."""
        s1 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                       kvcache=True, pipeline_depth=4).start()
        s2 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                       kvcache=True).start()
        w1 = LLMWorker(s1, role="decode").start()
        w2 = LLMWorker(s2, role="decode").start()
        router = LLMRouter([], [w1.address, w2.address], failover=True,
                           failover_attempts=6,
                           start_prober=False).start()
        try:
            p = np.arange(12, dtype=np.int32)
            golden = _generate(model, p, 6)
            # warm both engines on every shape the resume will hit
            for srv in (s1, s2):
                srv.submit(p, max_new_tokens=1).get(timeout=300)
                srv.submit(p, max_new_tokens=1).get(timeout=300)
            plan = rel.FaultPlan(seed=0)
            plan.add("llm.step", "delay", times=None, delay=0.02)
            rel.set_plan(plan)
            holder = {}

            def call():
                holder["resp"] = _req(
                    router.address, "POST", "/worker_generate",
                    {"prompt_ids": [int(t) for t in p],
                     "max_new_tokens": 6})
            t = threading.Thread(target=call, daemon=True)
            t.start()
            # kill w1 once the stream is live (w1 is the round-robin
            # first pick)
            assert _wait(lambda: any(r is not None
                                     for r in s1._slots)
                         or holder.get("resp"), timeout=30.0)
            w1.stop()
            s1.stop(drain=False)
            t.join(timeout=600)
            st, body, _ = holder["resp"]
            assert st == 200, body
            assert body["output_ids"] == golden
        finally:
            rel.set_plan(None)
            router.stop()
            w2.stop()
            s2.stop()


# ---------------------------------------------------------------------------
# the autoscaler control loop
# ---------------------------------------------------------------------------

class _FakeRouter:
    def __init__(self, addrs):
        self._pool_lock = threading.RLock()
        self.decode_workers = list(addrs)
        self._journal = None
        self._prober = None
        self._collector = None
        self.removed = []

    def _admin_backends(self, body):
        addr = (body["host"], int(body["port"]))
        if body["action"] == "add":
            self.decode_workers.append(addr)
        else:
            if len(self.decode_workers) == 1:
                raise ValueError("refusing to remove the last backend")
            self.decode_workers.remove(addr)
            self.removed.append(addr)
        return 200, {}


class _FakeProvider(WorkerProvider):
    def __init__(self):
        self.launched = []
        self.terminated = []
        self._n = 0

    def launch(self):
        self._n += 1
        addr = ("127.0.0.1", 40000 + self._n)
        self.launched.append(addr)
        return addr

    def terminate(self, addr):
        self.terminated.append(tuple(addr))


class TestFleetController:
    def _controller(self, router, provider, **kw):
        args = dict(min_workers=1, max_workers=3, interval=60.0,
                    cooldown=0.0, sustain=2, queue_high=1.0,
                    idle_low=0.0, drain_timeout=5.0)
        args.update(kw)
        return FleetController(router, provider=provider, **args)

    def test_scale_out_needs_sustained_pressure(self):
        router = _FakeRouter([("127.0.0.1", 39000)])
        provider = _FakeProvider()
        fc = self._controller(router, provider, sustain=3)
        fc.signals = lambda: {"workers": len(router.decode_workers),
                              "queue": 10.0, "active": 2.0,
                              "inflight": 0, "sheds": 0.0,
                              "occupancy_max": 0.0,
                              "source": "fake"}
        fc.tick()
        fc.tick()
        assert not provider.launched, \
            "scaled out before the sustain threshold"
        fc.tick()
        assert len(provider.launched) == 1
        assert len(router.decode_workers) == 2
        assert fc.scale_outs == 1

    def test_cooldown_and_max_bound(self):
        router = _FakeRouter([("127.0.0.1", 39000)])
        provider = _FakeProvider()
        fc = self._controller(router, provider, sustain=1,
                              cooldown=3600.0, max_workers=2)
        fc.signals = lambda: {"workers": len(router.decode_workers),
                              "queue": 10.0, "active": 0.0,
                              "inflight": 0, "sheds": 0.0,
                              "occupancy_max": 0.0, "source": "fake"}
        fc.tick()
        assert len(provider.launched) == 1
        for _ in range(5):
            fc.tick()
        assert len(provider.launched) == 1, \
            "cooldown did not damp repeated scale-outs"
        fc.cooldown = 0.0
        for _ in range(5):
            fc.tick()
        assert len(router.decode_workers) == 2, \
            "max bound was exceeded"

    def test_shed_delta_counts_as_pressure(self):
        router = _FakeRouter([("127.0.0.1", 39000)])
        provider = _FakeProvider()
        fc = self._controller(router, provider, sustain=1)
        sheds = {"v": 100.0}
        fc.signals = lambda: {"workers": len(router.decode_workers),
                              "queue": 0.0, "active": 1.0,
                              "inflight": 0, "sheds": sheds["v"],
                              "occupancy_max": 0.0, "source": "fake"}
        fc.tick()      # establishes the shed baseline, no pressure
        assert not provider.launched
        sheds["v"] = 103.0
        fc.tick()      # sheds grew -> pressure
        assert len(provider.launched) == 1

    def test_no_provider_records_event_instead_of_acting(self):
        router = _FakeRouter([("127.0.0.1", 39000)])
        fc = self._controller(router, None, sustain=1)
        fc.signals = lambda: {"workers": 1, "queue": 10.0,
                              "active": 0.0, "inflight": 0,
                              "sheds": 0.0, "occupancy_max": 0.0,
                              "source": "fake"}
        fc.tick()
        assert [e["action"] for e in fc.events] == ["no_provider"]
        assert len(router.decode_workers) == 1

    def test_min_bound_blocks_scale_in(self):
        router = _FakeRouter([("127.0.0.1", 39000)])
        provider = _FakeProvider()
        fc = self._controller(router, provider, sustain=1)
        fc.signals = lambda: {"workers": 1, "queue": 0.0,
                              "active": 0.0, "inflight": 0,
                              "sheds": 0.0, "occupancy_max": 0.0,
                              "source": "fake"}
        for _ in range(4):
            fc.tick()
        assert fc._draining is None and not router.removed

    def test_autoscaler_end_to_end(self, model):
        """Integration: spike -> scale-out -> idle -> graceful drain ->
        remove + terminate -> converged pool, against live workers."""
        provider = LocalWorkerProvider(
            model, server_kwargs=dict(max_batch=2, max_seq_len=64,
                                      page_size=8, kvcache=True,
                                      max_queue=8))
        router = None
        try:
            seed_addr = provider.launch()
            srv = provider.servers()[seed_addr]
            p = np.arange(10, dtype=np.int32)
            golden = _generate(model, p, 2)
            srv.submit(p, max_new_tokens=2).get(timeout=300)
            router = LLMRouter(
                [], [seed_addr], failover=True, start_prober=False,
                fleet=True, provider=provider, start_fleet=False,
                fleet_opts=dict(min_workers=1, max_workers=2,
                                interval=0.05, cooldown=0.0, sustain=1,
                                queue_high=0.5, idle_low=0.0,
                                drain_timeout=20.0)).start()
            fleet = router._fleet
            results = []

            def call():
                results.append(_req(
                    router.address, "POST", "/worker_generate",
                    {"prompt_ids": [int(t) for t in p],
                     "max_new_tokens": 2}))
            threads = [threading.Thread(target=call, daemon=True)
                       for _ in range(6)]
            for t in threads:
                t.start()
            assert _wait(lambda: (fleet.tick() or
                                  len(router.decode_workers) >= 2),
                         timeout=30.0), fleet.signals()
            for t in threads:
                t.join(timeout=600)
            assert all(st == 200 and body["output_ids"] == golden
                       for st, body, _ in results), results
            assert fleet.scale_outs >= 1
            # idle -> drain -> converge
            assert _wait(lambda: (fleet.tick() or
                                  (fleet.scale_ins >= 1 and
                                   len(router.decode_workers) == 1)),
                         timeout=60.0), fleet.status()
            assert provider.terminations >= 1
            st, status, _ = _req(router.address, "GET",
                                 "/fleet/autoscaler")
            assert st == 200
            assert status["scale_outs"] >= 1
            assert status["scale_ins"] >= 1
            assert any(e["action"] == "scale_in"
                       for e in status["events"])
        finally:
            if router is not None:
                router.stop()
            provider.stop_all()

    def test_router_stop_cancels_inflight_scale_in(self, model):
        """Satellite: router shutdown during an active drain cancels
        it — the victim resumes admission, no drain thread leaks."""
        provider = LocalWorkerProvider(
            model, server_kwargs=dict(max_batch=2, max_seq_len=64,
                                      page_size=8))
        router = None
        try:
            a1 = provider.launch()
            a2 = provider.launch()
            router = LLMRouter(
                [], [a1, a2], failover=True, start_prober=False,
                fleet=True, provider=provider, start_fleet=False,
                fleet_opts=dict(min_workers=1, max_workers=2,
                                interval=0.05, cooldown=0.0, sustain=1,
                                drain_timeout=30.0)).start()
            fleet = router._fleet
            # a request keeps the victim's phase-1 wait alive so the
            # drain is guaranteed still active at stop()
            victim_srv = provider.servers()[a2]
            r = victim_srv.submit(np.arange(6, dtype=np.int32),
                                  max_new_tokens=10)
            fleet._begin_scale_in(fleet.signals())
            assert fleet._draining is not None
            router.stop()
            router = None
            assert fleet._draining is None
            r.get(timeout=300)
            assert _wait(lambda: not victim_srv.draining, timeout=10.0), \
                "cancelled drain left the victim refusing work"
            assert not [t for t in threading.enumerate()
                        if t.name == "bigdl-fleet-drain"]
            victim_srv.submit(np.arange(6, dtype=np.int32),
                              max_new_tokens=1).get(timeout=300)
        finally:
            if router is not None:
                router.stop()
            provider.stop_all()


# ---------------------------------------------------------------------------
# load generator
# ---------------------------------------------------------------------------

class TestLoadgen:
    def test_run_load_zero_lost_and_parity(self, model):
        from tools.loadgen import gen_prompts, run_load
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8, max_queue=8).start()
        w = LLMWorker(srv, role="decode").start()
        try:
            prompts = gen_prompts(4, seed=0)
            goldens = [_generate(model, p, 3) for p in prompts]
            res = run_load(w.address, prompts, max_new_tokens=3,
                           qps=50.0, concurrency=2)
            assert res["lost"] == 0, res["errors"]
            assert res["ok"] == 4
            assert res["outputs"] == goldens
            assert res["latency_p99_ms"] is not None
        finally:
            w.stop()
            srv.stop()

    def test_sketch_window_isolates_the_soak(self):
        from bigdl_tpu.observability.sketch import QuantileSketch
        from tools.loadgen import sketch_window
        sk = QuantileSketch()
        for v in (1.0, 1.0, 1.0):
            sk.observe(v)
        before = sk.to_snapshot()
        for v in (100.0, 100.0, 100.0):
            sk.observe(v)
        win = sketch_window(before, sk.to_snapshot(), qs=(0.5,))
        assert win[0.5] == pytest.approx(100.0, rel=0.05), \
            "the window leaked pre-soak samples"
        assert sketch_window(before, before, qs=(0.5,))[0.5] is None
        assert sketch_window(None, None, qs=(0.5,))[0.5] is None


# ---------------------------------------------------------------------------
# disabled mode: bigdl.llm.fleet.enabled=false is structurally absent
# ---------------------------------------------------------------------------

class TestFleetDisabled:
    def test_structural_absence(self, model):
        # the gate defaults OFF
        assert conf.get_bool("bigdl.llm.fleet.enabled", False) is False
        assert fleet_enabled() is False
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8)
        srv.start()
        w = LLMWorker(srv, role="decode").start()
        before = set(obs.render().splitlines()) if obs.enabled() \
            else set()
        router = LLMRouter([], [w.address], failover=True,
                           start_prober=False).start()
        try:
            assert w._drain is None, \
                "bigdl.llm.fleet.enabled=false built a drain"
            assert router._fleet is None, \
                "bigdl.llm.fleet.enabled=false built a controller"
            st, _, _ = _req(w.address, "GET", "/worker_drain")
            assert st == 404
            st, _, _ = _req(w.address, "POST", "/worker_drain",
                            {"action": "begin"})
            assert st == 404
            st, _, _ = _req(router.address, "GET", "/fleet/autoscaler")
            assert st == 404
            # serving a request mints no fleet series
            st, body, _ = _req(router.address, "POST",
                               "/worker_generate",
                               {"prompt_ids": list(range(6)),
                                "max_new_tokens": 2})
            assert st == 200, body
            if obs.enabled():
                grown = "\n".join(
                    set(obs.render().splitlines()) - before)
                assert "bigdl_fleet_" not in grown, grown
            assert not [t for t in threading.enumerate()
                        if t.name.startswith("bigdl-fleet")], \
                "disabled fleet started a thread"
        finally:
            router.stop()
            w.stop()
            srv.stop()

    def test_fleet_router_requires_failover(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8)
        w = LLMWorker(srv, role="decode")
        with pytest.raises(ValueError, match="failover"):
            LLMRouter([], [w.address], failover=False, fleet=True)
        w.stop()
        srv.stop(drain=False)

"""Ragged in-place prefill through the ENGINE (ISSUE 8): greedy
bit-parity ragged vs the dense-staging path vs the plain ``generate``
golden — pipeline depths 1/2/4, prefix cache on/off, the COW tail fork,
tier re-prefills — plus the compile-grid regression the ragged path
exists to buy: partial-prefill signatures are O(suffix-buckets),
independent of how many prefix-page buckets the traffic mixes.
(Kernel-level interpret parity lives in tests/test_paged_attention.py.)
"""

import numpy as np
import pytest

from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import LLMServer

pytestmark = pytest.mark.kernels

PAGE = 8


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=128)


def _generate(model, p, n):
    return model.generate(np.asarray(p)[None], max_new_tokens=n)[0, len(p):]


def _serve(model, prompts, lens, *, ragged, replay=1, max_seq_len=64,
           **kw):
    """Run the workload ``replay`` times through one server; return the
    LAST pass's outputs plus the staging/prefix counters."""
    srv = LLMServer(model, max_batch=2, max_seq_len=max_seq_len,
                    page_size=PAGE, ragged_prefill=ragged, **kw).start()
    try:
        for _ in range(replay):
            got = [r.get(timeout=600) for r in
                   [srv.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, lens)]]
        return got, srv.prefill_dense_staged_tokens, srv
    finally:
        srv.stop()


def _workload():
    rs = np.random.RandomState(8)
    shared = rs.randint(0, 250, 20).astype(np.int32)      # 2.5 pages:
    prompts = [np.concatenate(                            # COW tail fork
        [shared, rs.randint(0, 250, 1 + j).astype(np.int32)])
        for j in range(4)]
    prompts.append(rs.randint(0, 250, 7).astype(np.int32))  # disjoint
    return prompts, [4, 3, 5, 2, 4]


# computed once and shared across the parametrized matrix (dense-engine
# behavior does not vary with the ragged flag, and its depth coverage
# already lives in tests/test_kvcache.py / test_llm_serving.py — only
# the RAGGED side needs the full depth sweep here)
_REF_CACHE = {}


def _references(model, kvcache):
    if kvcache not in _REF_CACHE:
        prompts, lens = _workload()
        golden = [_generate(model, p, n) for p, n in zip(prompts, lens)]
        dense, staged_dense, _ = _serve(
            model, prompts, lens, ragged=False, replay=2,
            kvcache=kvcache, pipeline_depth=1)
        assert staged_dense > 0        # the sandwich really staged
        _REF_CACHE[kvcache] = (golden, dense)
    return _REF_CACHE[kvcache]


class TestEngineParity:
    """The acceptance matrix: ragged outputs must be bit-identical to
    the dense-staging engine AND the plain generate golden, and the
    ragged path must stage ZERO tokens through a dense temp cache."""

    # tier-1 keeps the full depth sweep with the cache ON (the ragged
    # path's reason to exist) plus the cache-off representative at
    # depth 1; the cache-off × pipelined corners ride the slow suite
    @pytest.mark.parametrize("kvcache,depth", [
        pytest.param(True, 1), pytest.param(True, 2),
        pytest.param(True, 4), pytest.param(False, 1),
        pytest.param(False, 2, marks=pytest.mark.slow),
        pytest.param(False, 4, marks=pytest.mark.slow)])
    def test_parity_vs_dense_and_golden(self, model, depth, kvcache):
        prompts, lens = _workload()
        want, dense = _references(model, kvcache)
        rag, staged_rag, srv = _serve(
            model, prompts, lens, ragged=True, replay=2,
            kvcache=kvcache, pipeline_depth=depth)
        for j, (r, d, w) in enumerate(zip(rag, dense, want)):
            np.testing.assert_array_equal(np.asarray(r), np.asarray(d),
                                          err_msg=f"request {j}")
            np.testing.assert_array_equal(np.asarray(r), w,
                                          err_msg=f"request {j}")
        assert staged_rag == 0         # the ragged path never stages
        if kvcache:
            assert srv._kv.hits > 0    # replay actually hit the prefix
            assert srv.prefix_tokens_saved > 0

    # one family in tier-1 guards the nonzero-offset layer-scan shape;
    # the second rides the slow suite (same structure, MQA/wpe variant)
    @pytest.mark.parametrize("family", [
        "gptneox", pytest.param("starcoder", marks=pytest.mark.slow)])
    def test_family_partial_offset_parity(self, family):
        """The hand-written NeoX/StarCoder ragged layer scans at a
        NONZERO runtime offset — mid-page prefix (COW tail fork),
        position-dependent math (partial rotary / learned wpe) past the
        offset: ragged must match the facade golden with zero dense
        staging (dense == golden for these families is already held by
        test_kvcache's family test, so only the ragged side runs)."""
        if family == "gptneox":
            from bigdl_tpu.llm.models.gptneox import (
                GptNeoXConfig as C, GptNeoXForCausalLM as M)
        else:
            from bigdl_tpu.llm.models.starcoder import (
                StarCoderConfig as C, StarCoderForCausalLM as M)
        fam_model = M.from_config(C.tiny(), seed=0, max_cache_len=64)
        rs = np.random.RandomState(5)
        shared = rs.randint(0, 250, 20).astype(np.int32)  # 2.5 pages
        prompts = [np.concatenate(
            [shared, rs.randint(0, 250, 2 + j).astype(np.int32)])
            for j in range(2)]
        lens = [3, 3]
        want = [_generate(fam_model, p, n)
                for p, n in zip(prompts, lens)]
        rag, staged_rag, srv = _serve(
            fam_model, prompts, lens, ragged=True, replay=2,
            kvcache=True, max_seq_len=48)
        for j, (r, w) in enumerate(zip(rag, want)):
            np.testing.assert_array_equal(np.asarray(r), w,
                                          err_msg=f"request {j}")
        assert srv._kv.hits > 0          # offsets were really nonzero
        assert staged_rag == 0

    def test_tier_reprefill_parity(self, model):
        """ISSUE 6 composition: chains spilled to the host arena are
        re-adopted by admission and attended WHERE THEY LAND — the tier
        re-prefill rides the same ragged path (zero dense staging) and
        stays bit-exact."""
        from bigdl_tpu.utils.conf import conf
        rs = np.random.RandomState(23)
        groups = [rs.randint(0, 250, 16).astype(np.int32)
                  for _ in range(4)]
        prompts = [np.concatenate(
            [groups[j % 4], rs.randint(0, 250, 1 + j % 4)
             .astype(np.int32)]) for j in range(8)]
        lens = [int(rs.randint(1, 5)) for _ in prompts]
        want = [_generate(model, p, n) for p, n in zip(prompts, lens)]
        conf.set("bigdl.llm.kvtier.sync", "true")
        try:
            got, staged, srv = _serve(
                model, prompts, lens, ragged=True, num_pages=9,
                kvcache=True, kvtier=True, host_pages=32)
            spills, fetches = srv._tier.spills, srv._tier.fetches
        finally:
            conf.unset("bigdl.llm.kvtier.sync")
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        assert spills > 0 and fetches > 0   # the tier actually cycled
        assert staged == 0


class TestAutoResolution:
    def test_auto_is_dense_off_tpu_overrides_win(self, model):
        """`bigdl.llm.prefill.ragged=auto` (default) resolves by
        backend — dense here (CPU: the XLA twin would gather the full
        worst-case table per layer under jit); an explicit ctor arg or
        conf true/false forces the path."""
        from bigdl_tpu.utils.conf import conf
        kw = dict(max_batch=2, max_seq_len=64, page_size=PAGE,
                  kvcache=True)
        srv = LLMServer(model, **kw)
        assert srv._ragged is False               # auto, cpu backend
        srv.stop()
        srv = LLMServer(model, ragged_prefill=True, **kw)
        assert srv._ragged is True                # ctor override
        srv.stop()
        conf.set("bigdl.llm.prefill.ragged", "true")
        try:
            srv = LLMServer(model, **kw)
            assert srv._ragged is True            # conf override
            srv.stop()
        finally:
            conf.unset("bigdl.llm.prefill.ragged")


class TestCompileGrid:
    def test_partial_prefill_signatures_o_suffix_buckets(self, model):
        """The logarithmic-compile invariant (prefill.py docstring),
        post-ISSUE 8: prefix length is runtime block-table data, so a
        mixed-prefix replay adds ZERO new partial-prefill programs once
        the suffix buckets are warm — while the dense path compiles one
        program per (prefix-page-bucket, suffix-bucket) pair. Guarded
        via the PR 3 compile recorder + the engine's step cache."""
        from bigdl_tpu import observability as obs
        from bigdl_tpu.llm import serving as sv
        rs = np.random.RandomState(42)
        # prefix chains at 1/2/3/4 pages (n_pp buckets 1, 2, 4, 4);
        # every tail is 1..4 tokens -> ONE suffix bucket (PAGE)
        chains = [rs.randint(0, 250, PAGE * (1 + j)).astype(np.int32)
                  for j in range(4)]
        def tails(seed):
            r2 = np.random.RandomState(seed)
            return [np.concatenate(
                [c, r2.randint(0, 250, 1 + r2.randint(0, 4))
                 .astype(np.int32)]) for c in chains]

        def keys(tag):
            return {k for k in sv._PAGED_STEP_CACHE if tag in k}

        def ragged_compiles():
            return sum(s["compiles"] for s in obs.compile_stats()
                       if s["fn"] == "llm/prefill_ragged")

        was = obs.enabled()
        obs.enable()
        ragged_before = keys("prefill_ragged")
        # pool roomy enough that no chain ever evicts: a miss would
        # reroute to FULL prefill and understate the dense grid below
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, num_pages=40, kvcache=True,
                        ragged_prefill=True).start()
        try:
            # warmup: seed the chains (full prefill) + one partial each
            for p in list(chains) + tails(0):
                srv.submit(p, max_new_tokens=2).get(timeout=600)
            warm_keys = keys("prefill_ragged")
            warm_compiles = ragged_compiles()
            # mixed-prefix replay: every chain length again, new tails
            for seed in (1, 2, 3):
                for p in tails(seed):
                    srv.submit(p, max_new_tokens=2).get(timeout=600)
            assert keys("prefill_ragged") == warm_keys
            assert ragged_compiles() == warm_compiles
            # the whole grid is the suffix buckets: this workload's
            # are {8, 16, 32} (seeding fulls + the partial bucket), so
            # at most 3 NEW programs exist no matter how many prefix-
            # page buckets the chains span (the step cache is process-
            # global, hence the delta + subset form)
            assert len(warm_keys - ragged_before) <= 3
            assert {k[-1] for k in warm_keys - ragged_before} <= \
                {8, 16, 32}
        finally:
            srv.stop()
            if not was:
                obs.disable()
        # the dense path's grid: same traffic, one (n_pp, bucket)
        # program per prefix-page bucket on TOP of the full-prefill
        # buckets — this is exactly what the ragged path deleted
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=PAGE, num_pages=40, kvcache=True,
                        ragged_prefill=False).start()
        try:
            for p in list(chains) + tails(0):
                srv.submit(p, max_new_tokens=2).get(timeout=600)
        finally:
            srv.stop()
        # one program per (n_pp, bucket) pair — the key tail is
        # (..., "prefill_partial", n_pp, bucket) — so at the single
        # PAGE-sized suffix bucket the dense grid spans >= 3 n_pp
        # buckets for the 1/2/3/4-page chains, where the ragged grid
        # holds ONE partial program no matter the prefix mix
        dense_npp = {k[-2] for k in keys("prefill_partial")
                     if k[-1] == PAGE}
        assert len(dense_npp) >= 3

"""HTTP serving front-ends (ref: scala/serving Akka-HTTP frontend +
the bigdl-llm FastChat worker, SURVEY.md §3.6 / §2.8 — VERDICT r3
missing #4)."""

import http.client
import json

import numpy as np
import pytest

import bigdl_tpu.nn as nn


def _post(addr, path, obj):
    conn = http.client.HTTPConnection(*addr, timeout=120)
    conn.request("POST", path, json.dumps(obj),
                 {"Content-Type": "application/json"})
    r = conn.getresponse()
    body = r.read()
    conn.close()
    return r.status, json.loads(body)


def _get(addr, path):
    conn = http.client.HTTPConnection(*addr, timeout=30)
    conn.request("GET", path)
    r = conn.getresponse()
    body = json.loads(r.read())
    conn.close()
    return r.status, body


def _get_text(addr, path):
    conn = http.client.HTTPConnection(*addr, timeout=30)
    conn.request("GET", path)
    r = conn.getresponse()
    body = r.read().decode()
    ctype = r.getheader("Content-Type", "")
    conn.close()
    return r.status, body, ctype


class TestServingFrontend:
    def test_predict_roundtrip(self):
        from bigdl_tpu.serving.cluster_serving import ClusterServing
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        from bigdl_tpu.serving.inference_model import InferenceModel

        model = (nn.Sequential().add(nn.Linear(4, 3))
                 .add(nn.SoftMax()))
        im = InferenceModel().load_bigdl(model=model)
        stream = "http_test_stream"
        job = ClusterServing(im, stream_name=stream).start()
        fe = ServingFrontend(stream_name=stream).start()
        try:
            x = np.arange(4, dtype=np.float32)[None]
            code, out = _post(fe.address, "/predict",
                              {"inputs": {"input": x.tolist()}})
            assert code == 200, out
            want = im.predict(x)
            np.testing.assert_allclose(np.asarray(out["result"]),
                                       np.asarray(want), rtol=1e-5)
            # legacy JSON blob moved to /metrics.json (ISSUE 1 satellite)
            code, metrics = _get(fe.address, "/metrics.json")
            assert code == 200 and metrics["served"] == 1
            # /metrics is now Prometheus text exposition
            code, text, ctype = _get_text(fe.address, "/metrics")
            assert code == 200 and ctype.startswith("text/plain")
            from bigdl_tpu.observability import parse_prometheus
            parsed = parse_prometheus(text)
            assert parsed["bigdl_serving_served_total"][()] >= 1
            assert parsed["bigdl_serving_request_seconds_count"][()] >= 1
        finally:
            fe.stop()
            job.stop()

    def test_bad_request(self):
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        fe = ServingFrontend(stream_name="http_bad_stream").start()
        try:
            code, out = _post(fe.address, "/predict", {"nope": 1})
            assert code == 400
            code, _ = _post(fe.address, "/other", {})
            assert code == 404
        finally:
            fe.stop()


class TestLLMWorker:
    @pytest.fixture(scope="class")
    def served(self):
        from bigdl_tpu.llm.models.llama import (LlamaConfig,
                                                LlamaForCausalLM)
        from bigdl_tpu.llm.serving import LLMServer
        from bigdl_tpu.llm.worker import LLMWorker

        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                             max_cache_len=64)
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        worker = LLMWorker(srv).start()
        yield model, srv, worker
        worker.stop()
        srv.stop()

    def test_generate_matches_model(self, served):
        model, srv, worker = served
        ids = [3, 1, 4, 1, 5]
        want = model.generate(np.asarray(ids)[None],
                              max_new_tokens=6)[0, 5:]
        code, out = _post(worker.address, "/worker_generate",
                          {"prompt_ids": ids, "max_new_tokens": 6})
        assert code == 200, out
        np.testing.assert_array_equal(out["output_ids"], want)
        assert out["finish_reason"] == "length"

    def test_generate_stream(self, served):
        model, srv, worker = served
        ids = [2, 7, 1]
        want = model.generate(np.asarray(ids)[None],
                              max_new_tokens=5)[0, 3:]
        conn = http.client.HTTPConnection(*worker.address, timeout=120)
        conn.request("POST", "/worker_generate_stream",
                     json.dumps({"prompt_ids": ids,
                                 "max_new_tokens": 5}),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        lines = [json.loads(ln) for ln in r.read().decode().splitlines()
                 if ln.strip()]
        conn.close()
        assert lines, "no stream chunks"
        assert lines[-1]["done"] is True
        np.testing.assert_array_equal(lines[-1]["output_ids"], want)
        # deltas grow monotonically
        for a, b in zip(lines, lines[1:]):
            assert len(b["output_ids"]) >= len(a["output_ids"])

    def test_status_and_validation(self, served):
        model, srv, worker = served
        code, st = _get(worker.address, "/worker_get_status")
        assert code == 200 and st["model"] == "bigdl-tpu-llm"
        code, out = _post(worker.address, "/worker_generate",
                          {"prompt_ids": list(range(40)),
                           "max_new_tokens": 20})
        assert code == 422   # exceeds max_seq_len

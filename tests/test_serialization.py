"""Stable checkpoint format + foreign-checkpoint interop
(ref: S:dllib/utils/serializer/ — protobuf ModuleSerializer round-trip
specs, SURVEY.md §4 "Serialization round-trip tests")."""

import dataclasses
import json
import os

import jax.numpy as jnp
import numpy as np
import pytest

from bigdl_tpu.utils.checkpoint import (
    FORMAT_VERSION, load_checkpoint, save_checkpoint)


class TestCheckpointFormat:
    def test_roundtrip_nested(self, tmp_path):
        tree = {
            "params": {"w": np.arange(6, dtype=np.float32).reshape(2, 3),
                       "layers": [{"b": np.ones(4, np.int32)},
                                  {"b": np.zeros(4, np.int32)}]},
            "meta": {"lr": 0.1, "name": "m", "flag": True, "none": None},
            "tup": (np.float32(2.5), "x"),
        }
        save_checkpoint(str(tmp_path / "ck"), tree, metadata={"k": "v"})
        back, meta = load_checkpoint(str(tmp_path / "ck"), to_jax=False)
        assert meta == {"k": "v"}
        np.testing.assert_array_equal(back["params"]["w"],
                                      tree["params"]["w"])
        np.testing.assert_array_equal(back["params"]["layers"][0]["b"],
                                      np.ones(4, np.int32))
        assert back["meta"] == tree["meta"]
        assert isinstance(back["tup"], tuple) and back["tup"][1] == "x"

    def test_bf16_roundtrip(self, tmp_path):
        tree = {"w": jnp.asarray([[1.5, -2.25]], jnp.bfloat16)}
        save_checkpoint(str(tmp_path / "ck"), tree)
        back, _ = load_checkpoint(str(tmp_path / "ck"))
        assert back["w"].dtype == jnp.bfloat16
        np.testing.assert_array_equal(np.asarray(back["w"], np.float32),
                                      [[1.5, -2.25]])

    def test_newer_version_rejected(self, tmp_path):
        save_checkpoint(str(tmp_path / "ck"), {"a": np.zeros(1)})
        mpath = tmp_path / "ck" / "manifest.json"
        m = json.loads(mpath.read_text())
        m["version"] = FORMAT_VERSION + 1
        mpath.write_text(json.dumps(m))
        with pytest.raises(ValueError, match="newer"):
            load_checkpoint(str(tmp_path / "ck"))

    def test_no_code_execution_surface(self, tmp_path):
        """The weights file must be loadable with safetensors alone —
        no pickle anywhere in the stable surface."""
        from safetensors.numpy import load_file
        save_checkpoint(str(tmp_path / "ck"),
                        {"w": np.ones((2, 2), np.float32)})
        arrays = load_file(str(tmp_path / "ck" / "arrays.safetensors"))
        np.testing.assert_array_equal(arrays["w"], np.ones((2, 2)))


class TestModulePersistence:
    def _model(self):
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn.module import set_seed
        set_seed(0)
        return nn.Sequential()\
            .add(nn.Linear(6, 8)).add(nn.ReLU()).add(nn.Linear(8, 3))

    def test_save_module_directory_format(self, tmp_path):
        from bigdl_tpu.nn.module import Module
        m = self._model()
        x = jnp.asarray(np.random.RandomState(0)
                        .rand(4, 6).astype(np.float32))
        ref = np.asarray(m.forward(x))
        path = str(tmp_path / "model_ck")
        m.save_module(path)
        assert os.path.exists(os.path.join(path, "manifest.json"))
        assert os.path.exists(os.path.join(path, "arrays.safetensors"))
        m2 = Module.load_module(path)
        np.testing.assert_allclose(np.asarray(m2.forward(x)), ref,
                                   rtol=1e-6)
        # saving must not corrupt the live module
        np.testing.assert_allclose(np.asarray(m.forward(x)), ref, rtol=1e-6)

    def test_save_load_weights_into_fresh_model(self, tmp_path):
        m = self._model()
        x = jnp.asarray(np.random.RandomState(1)
                        .rand(4, 6).astype(np.float32))
        ref = np.asarray(m.forward(x))
        m.save_weights(str(tmp_path / "w"))
        m2 = self._model()
        # perturb so the test proves load_weights does the work
        import jax
        m2.load_parameters_dict(jax.tree_util.tree_map(
            lambda a: np.asarray(a) * 0.0, m2.parameters_dict()))
        m2.load_weights(str(tmp_path / "w"))
        np.testing.assert_allclose(np.asarray(m2.forward(x)), ref,
                                   rtol=1e-6)


class TestHFSafetensorsInterop:
    """End-to-end: a real HF checkpoint on disk → our loader → logits
    parity vs the independent torch implementation (the reference's
    golden-parity pattern, SURVEY.md §4)."""

    @pytest.fixture(scope="class")
    def hf_ckpt(self, tmp_path_factory):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        path = str(tmp_path_factory.mktemp("hf") / "tiny-llama")
        hf_cfg = transformers.LlamaConfig(
            vocab_size=96, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, max_position_embeddings=64,
            rms_norm_eps=1e-5, rope_theta=10000.0, tie_word_embeddings=False)
        torch.manual_seed(0)
        hf_model = transformers.LlamaForCausalLM(hf_cfg)
        hf_model.save_pretrained(path, safe_serialization=True)
        ids = np.array([[3, 17, 42, 9, 60, 21]], np.int64)
        with torch.no_grad():
            ref = hf_model(torch.tensor(ids)).logits.float().numpy()
        return path, ids, ref

    def test_dense_load_matches_hf(self, hf_ckpt):
        from bigdl_tpu.llm.transformers import AutoModelForCausalLM
        path, ids, ref = hf_ckpt
        assert any(f.endswith(".safetensors") for f in os.listdir(path))
        model = AutoModelForCausalLM.from_pretrained(path, max_cache_len=32)
        logits, _ = model(jnp.asarray(ids, jnp.int32))
        ours = np.asarray(logits)
        # bf16 params vs fp32 torch
        np.testing.assert_allclose(ours, ref, rtol=0.1, atol=0.1)
        # ranking agreement on the next-token head
        assert (np.argmax(ours[:, -1], -1)
                == np.argmax(ref[:, -1], -1)).all()

    def test_quantize_on_load_generates(self, hf_ckpt):
        from bigdl_tpu.llm.transformers import AutoModelForCausalLM
        path, ids, ref = hf_ckpt
        model = AutoModelForCausalLM.from_pretrained(
            path, load_in_4bit=True, max_cache_len=32)
        # quantize-on-load emits the fused-projection layout (r4)
        lp = model.params["layers"]["qkv_proj"]
        assert "q" in lp and "scale" in lp and "w" not in lp
        assert "q_proj" not in model.params["layers"]
        out = model.generate(ids.astype(np.int32), max_new_tokens=8)
        assert out.shape == (1, ids.shape[1] + 8)
        # q4 logits still rank like fp32 on the first next token
        logits, _ = model(jnp.asarray(ids, jnp.int32))
        ours = np.asarray(logits)
        top5 = np.argsort(-ref[0, -1])[:5]
        assert np.argmax(ours[0, -1]) in top5

"""parallel/ package tests on the virtual 8-device CPU mesh (the analog of
the reference's local[N]-Spark distributed tests, SURVEY.md §4)."""

import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

from bigdl_tpu.utils.jax_compat import shard_map

from bigdl_tpu.parallel import (
    all_gather, all_reduce, compressed_all_reduce, create_mesh,
    mesh_axis_size, reduce_scatter, ring_attention, shard_batch,
    ulysses_attention, PipelineModule, dp_train_step,
)


def _ref_attention(q, k, v, causal=False):
    d = q.shape[-1]
    logits = np.einsum("bqhd,bkhd->bhqk", q, k) / np.sqrt(d)
    if causal:
        s = q.shape[1]
        mask = np.tril(np.ones((s, s), bool))
        logits = np.where(mask[None, None], logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)
    return np.einsum("bhqk,bkhd->bqhd", p, v)


class TestMesh:
    def test_create_mesh_dict(self, devices):
        mesh = create_mesh({"data": 4, "model": 2})
        assert mesh.shape["data"] == 4 and mesh.shape["model"] == 2

    def test_create_mesh_infer(self, devices):
        mesh = create_mesh({"data": -1, "model": 2})
        assert mesh.shape["data"] == 4

    def test_axis_size_missing(self, devices):
        mesh = create_mesh({"data": 8})
        assert mesh_axis_size(mesh, "model") == 1

    def test_shard_batch(self, devices):
        mesh = create_mesh({"data": 8})
        x = shard_batch(np.ones((16, 3)), mesh)
        assert x.sharding.spec == P("data")


class TestCollectives:
    def test_all_reduce_and_compressed(self, devices):
        mesh = create_mesh({"data": 8})

        def body(x):
            return (all_reduce(x, "data"),
                    compressed_all_reduce(x, "data"))

        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        f = shard_map(body, mesh=mesh, in_specs=P("data"),
                      out_specs=P("data"))
        full, comp = f(x)
        np.testing.assert_allclose(np.asarray(full), 28.0 * np.ones((8, 1)))
        np.testing.assert_allclose(np.asarray(comp), 28.0 * np.ones((8, 1)),
                                   rtol=1e-2)

    def test_reduce_scatter_gather_roundtrip(self, devices):
        mesh = create_mesh({"data": 8})
        x = np.random.RandomState(0).rand(8, 8).astype(np.float32)

        def body(xl):
            rs = reduce_scatter(xl, "data", axis=0)   # sum then scatter
            return all_gather(rs, "data", axis=0)

        f = shard_map(body, mesh=mesh, in_specs=P(None, "data"),
                      out_specs=P(None, "data"))
        out = np.asarray(f(x))
        # device d holds column d; rs gives it row-sum d; gather+out_spec
        # tiles the row-sum vector across all 8 columns
        np.testing.assert_allclose(
            out, np.tile(x.sum(1, keepdims=True), (1, 8)), rtol=1e-5)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, devices, causal):
        mesh = create_mesh({"seq": 8})
        rs = np.random.RandomState(1)
        b, s, h, d = 2, 32, 4, 8
        q, k, v = (rs.randn(b, s, h, d).astype(np.float32) for _ in range(3))
        out = np.asarray(ring_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh,
            axis="seq", causal=causal, batch_axis=None))
        ref = _ref_attention(q, k, v, causal)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)

    def test_2d_mesh_data_and_seq(self, devices):
        mesh = create_mesh({"data": 2, "seq": 4})
        rs = np.random.RandomState(2)
        b, s, h, d = 4, 16, 2, 4
        q, k, v = (rs.randn(b, s, h, d).astype(np.float32) for _ in range(3))
        out = np.asarray(ring_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh,
            axis="seq", causal=True))
        ref = _ref_attention(q, k, v, True)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


class TestUlysses:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, devices, causal):
        mesh = create_mesh({"seq": 4})
        rs = np.random.RandomState(3)
        b, s, h, d = 2, 16, 8, 4
        q, k, v = (rs.randn(b, s, h, d).astype(np.float32) for _ in range(3))
        out = np.asarray(ulysses_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mesh,
            axis="seq", causal=causal, batch_axis=None))
        ref = _ref_attention(q, k, v, causal)
        np.testing.assert_allclose(out, ref, rtol=2e-4, atol=2e-4)


class TestPipeline:
    def test_stacked_linear_stages(self, devices):
        mesh = create_mesh({"pipe": 4})
        n_stages, n_micro, mb, dim = 4, 8, 2, 6
        rs = np.random.RandomState(4)
        w = rs.randn(n_stages, dim, dim).astype(np.float32) * 0.3
        b = rs.randn(n_stages, dim).astype(np.float32) * 0.1
        xs = rs.randn(n_micro, mb, dim).astype(np.float32)

        def stage_apply(p, x):
            return jnp.tanh(x @ p["w"].T + p["b"])

        pipe = PipelineModule(stage_apply, n_stages, mesh)
        params = pipe.place_params({"w": jnp.asarray(w), "b": jnp.asarray(b)})
        out = np.asarray(pipe(params, xs))

        ref = xs
        for i in range(n_stages):
            ref = np.tanh(ref @ w[i].T + b[i])
        np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


    def test_pipeline_training_loss_parity(self, devices):
        """GPipe training through the stage ring must track single-device
        training exactly (VERDICT r1: pipeline was forward-only)."""
        from bigdl_tpu.optim.optim_method import SGD
        from bigdl_tpu.parallel import (
            make_pipeline_train_step, split_microbatches)

        mesh = create_mesh({"pipe": 4})
        n_stages, n_micro, mb, dim = 4, 8, 4, 6
        rs = np.random.RandomState(7)
        w0 = rs.randn(n_stages, dim, dim).astype(np.float32) * 0.4
        b0 = rs.randn(n_stages, dim).astype(np.float32) * 0.1
        x = rs.randn(n_micro * mb, dim).astype(np.float32)
        t = np.tanh(x @ rs.randn(dim, dim).astype(np.float32))

        def stage_apply(p, xb):
            return jnp.tanh(xb @ p["w"].T + p["b"])

        def loss_fn(outs, targets):
            return jnp.mean((outs - targets) ** 2)

        optim = SGD(learning_rate=0.2)

        # -- pipeline run ---------------------------------------------------
        pipe = PipelineModule(stage_apply, n_stages, mesh, remat=True)
        params = pipe.place_params(
            {"w": jnp.asarray(w0), "b": jnp.asarray(b0)})
        opt_state = optim.init_state(params)
        step = make_pipeline_train_step(pipe, loss_fn, optim, lr=0.2)
        micro_x = split_microbatches(jnp.asarray(x), n_micro)
        micro_t = split_microbatches(jnp.asarray(t), n_micro)
        pipe_losses = []
        for _ in range(10):
            params, opt_state, loss = step(params, opt_state,
                                           micro_x, micro_t)
            pipe_losses.append(float(loss))

        # -- single-device reference ---------------------------------------
        ref_params = {"w": jnp.asarray(w0), "b": jnp.asarray(b0)}
        ref_opt = optim.init_state(ref_params)

        def ref_loss(p):
            h = jnp.asarray(x)
            for i in range(n_stages):
                h = jnp.tanh(h @ p["w"][i].T + p["b"][i])
            return jnp.mean((h - jnp.asarray(t)) ** 2)

        ref_losses = []
        for _ in range(10):
            l, g = jax.value_and_grad(ref_loss)(ref_params)
            ref_params, ref_opt = optim.step(ref_params, g, ref_opt, 0.2)
            ref_losses.append(float(l))

        np.testing.assert_allclose(pipe_losses, ref_losses,
                                   rtol=1e-4, atol=1e-5)
        assert pipe_losses[-1] < pipe_losses[0] * 0.9, "did not learn"


class TestDpTrainStep:
    def test_linear_regression_converges_sharded(self, devices):
        from bigdl_tpu.optim.optim_method import SGD

        mesh = create_mesh({"data": 8})
        rs = np.random.RandomState(5)
        w_true = rs.randn(3).astype(np.float32)
        x = rs.randn(64, 3).astype(np.float32)
        y = x @ w_true

        def apply_fn(p, s, xb, rng):
            return xb @ p["w"], s

        def loss_fn(pred, t):
            return jnp.mean((pred - t) ** 2)

        optim = SGD(learning_rate=0.1)
        step = dp_train_step(apply_fn, loss_fn, optim, mesh)
        params = {"w": jax.device_put(jnp.zeros(3),
                                      NamedSharding(mesh, P()))}
        opt_state = optim.init_state(params)
        xs = shard_batch(x, mesh)
        ys = shard_batch(y, mesh)
        loss = None
        for _ in range(200):
            params, _, opt_state, loss = step(
                params, {}, opt_state, xs, ys, 0.1, jax.random.PRNGKey(0))
        assert float(loss) < 1e-4
        np.testing.assert_allclose(np.asarray(params["w"]), w_true,
                                   atol=1e-2)


class TestQuantizedAllReduce:
    def test_matches_exact_allreduce(self, devices):
        """EQuARX-style int8 wire allreduce over the 8-device mesh must
        approximate the exact psum within the per-block quantization
        bound."""
        import functools
        from jax.sharding import Mesh
        from bigdl_tpu.parallel import quantized_all_reduce

        mesh = Mesh(np.asarray(devices), ("d",))
        rs = np.random.RandomState(0)
        x = rs.randn(8, 64, 37).astype(np.float32)

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("d"), out_specs=P("d"))
        def qar(xs):
            return quantized_all_reduce(xs[0], "d")[None]

        out = np.asarray(jax.jit(qar)(x))
        exact = x.sum(axis=0)
        # every shard holds the same (approximate) sum
        for i in range(8):
            err = np.abs(out[i] - exact).max()
            scale = np.abs(exact).max()
            assert err / scale < 0.05, err / scale

    def test_mean_and_dtype_roundtrip(self, devices):
        import functools
        from jax.sharding import Mesh
        from bigdl_tpu.parallel import quantized_all_reduce

        mesh = Mesh(np.asarray(devices), ("d",))
        x = np.ones((8, 130), np.float32) * 3.0   # non-multiple of block

        @functools.partial(
            shard_map, mesh=mesh, in_specs=P("d"), out_specs=P("d"))
        def qar(xs):
            t = {"g": xs[0].astype(jnp.bfloat16)}
            return quantized_all_reduce(t, "d", mean=True)["g"][None]

        out = np.asarray(jax.jit(qar)(x), np.float32)
        np.testing.assert_allclose(out, 3.0, rtol=0.02)

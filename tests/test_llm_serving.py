"""Continuous-batching LLM serving worker (ref: P:llm/serving — the
fastchat worker / vLLM integration row of SURVEY.md §2.8)."""

import numpy as np
import pytest

from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import LLMServer


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=64)


class TestLLMServer:
    def test_single_request_matches_generate(self, model):
        """A served request must produce exactly the model's own greedy
        continuation."""
        ids = np.array([3, 1, 4, 1, 5], np.int32)
        want = model.generate(ids[None], max_new_tokens=6)[0, 5:]
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        try:
            req = srv.submit(ids, max_new_tokens=6)
            got = req.get(timeout=120)
        finally:
            srv.stop()
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_continuous_batching_concurrent_requests(self, model):
        """Several overlapping requests of different lengths share the
        batch; each result equals its solo greedy continuation."""
        prompts = [np.array(p, np.int32) for p in
                   ([1, 2, 3], [7, 8], [9, 10, 11, 12], [5], [6, 4])]
        lens = [5, 3, 4, 6, 2]
        want = [model.generate(p[None], max_new_tokens=n)[0, len(p):]
                for p, n in zip(prompts, lens)]
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, lens)]
            got = [r.get(timeout=300) for r in reqs]
        finally:
            srv.stop()
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), w)
        # with max_batch=2 and 5 requests, slots must have been reused
        assert srv.steps >= max(lens)

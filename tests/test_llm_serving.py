"""Continuous-batching LLM serving worker (ref: P:llm/serving — the
fastchat worker / vLLM integration row of SURVEY.md §2.8)."""

import numpy as np
import pytest

from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import LLMServer


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=64)


class TestLLMServer:
    def test_single_request_matches_generate(self, model):
        """A served request must produce exactly the model's own greedy
        continuation."""
        ids = np.array([3, 1, 4, 1, 5], np.int32)
        want = model.generate(ids[None], max_new_tokens=6)[0, 5:]
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        try:
            req = srv.submit(ids, max_new_tokens=6)
            got = req.get(timeout=120)
        finally:
            srv.stop()
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_continuous_batching_concurrent_requests(self, model):
        """Several overlapping requests of different lengths share the
        batch; each result equals its solo greedy continuation."""
        prompts = [np.array(p, np.int32) for p in
                   ([1, 2, 3], [7, 8], [9, 10, 11, 12], [5], [6, 4])]
        lens = [5, 3, 4, 6, 2]
        want = [model.generate(p[None], max_new_tokens=n)[0, len(p):]
                for p, n in zip(prompts, lens)]
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, lens)]
            got = [r.get(timeout=300) for r in reqs]
        finally:
            srv.stop()
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), w)
        # with max_batch=2 and 5 requests, slots must have been reused
        assert srv.steps >= max(lens)

    def test_legacy_slot_static_mode(self, model):
        """paged=False keeps the round-3 slot-static cache path."""
        ids = np.array([3, 1, 4, 1, 5], np.int32)
        want = model.generate(ids[None], max_new_tokens=6)[0, 5:]
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        paged=False).start()
        try:
            got = srv.submit(ids, max_new_tokens=6).get(timeout=120)
        finally:
            srv.stop()
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_paged_16_mixed_length_requests(self, model):
        """The paged-cache north star (VERDICT r3 missing #1): 16
        concurrent mixed-length requests through 4 batch slots, each
        matching its solo greedy continuation, with KV HBM proportional
        to tokens in flight (pages, not slots × max_seq_len)."""
        rs = np.random.RandomState(7)
        prompts = [np.asarray(rs.randint(0, 250, rs.randint(1, 20)),
                              np.int32) for _ in range(16)]
        lens = [int(rs.randint(1, 10)) for _ in range(16)]
        want = [model.generate(p[None], max_new_tokens=n)[0, len(p):]
                for p, n in zip(prompts, lens)]
        srv = LLMServer(model, max_batch=4, max_seq_len=32,
                        page_size=16).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, lens)]
            got = [r.get(timeout=600) for r in reqs]
        finally:
            srv.stop()
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        # all requests done -> every page returned to the pool
        assert srv.pages_in_use == 0
        assert srv._budget_avail == srv._num_pages - 1
        assert sorted(srv._free) == list(range(1, srv._num_pages))

    def test_paged_budget_admission_small_pool(self, model):
        """A pool smaller than max_batch × worst case still serves every
        request: admission reserves page budgets and queues the rest."""
        prompts = [np.arange(1, 9, dtype=np.int32) for _ in range(6)]
        want = [model.generate(p[None], max_new_tokens=8)[0, len(p):]
                for p in prompts]
        # each request needs ceil(16/16) = 1..2 pages; pool of 4 usable
        srv = LLMServer(model, max_batch=4, max_seq_len=32,
                        page_size=16, num_pages=5).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=8) for p in prompts]
            got = [r.get(timeout=600) for r in reqs]
        finally:
            srv.stop()
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), w)

    def test_greedy_parity_under_concurrent_jax_load(self, model):
        """Regression for the round-3 flaky race: concurrent jax
        executions on OTHER threads let the async CPU runtime recycle
        the engine's just-dropped cache buffers while the step consuming
        them was still in flight (14/30 greedy-parity mismatches before
        the block_until_ready barrier in _prefill_slot/_decode_scatter;
        0/30 after). Hammer threads + randomized submit timing."""
        import threading
        import time

        import jax
        import jax.numpy as jnp

        ids = np.array([3, 1, 4, 1, 5], np.int32)
        want = model.generate(ids[None], max_new_tokens=6)[0, 5:]
        stop = threading.Event()

        def hammer():
            # input changes every call: some runtimes memoize identical
            # (program, args) executions, which would make a fixed-input
            # hammer generate zero real concurrent device traffic
            a = jax.random.normal(jax.random.PRNGKey(1), (256, 256))
            f = jax.jit(lambda x: jnp.tanh(x @ x) + 1e-6)
            while not stop.is_set():
                a = f(a).block_until_ready()

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for it in range(8):
                srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
                try:
                    time.sleep((it % 4) * 0.001)
                    req = srv.submit(ids, max_new_tokens=6)
                    got = np.asarray(req.get(timeout=120))
                finally:
                    srv.stop()
                np.testing.assert_array_equal(got, want,
                                              err_msg=f"iteration {it}")
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)

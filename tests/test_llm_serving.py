"""Continuous-batching LLM serving worker (ref: P:llm/serving — the
fastchat worker / vLLM integration row of SURVEY.md §2.8)."""

import numpy as np
import pytest

from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import LLMServer


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=64)


class TestLLMServer:
    def test_single_request_matches_generate(self, model):
        """A served request must produce exactly the model's own greedy
        continuation."""
        ids = np.array([3, 1, 4, 1, 5], np.int32)
        want = model.generate(ids[None], max_new_tokens=6)[0, 5:]
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        try:
            req = srv.submit(ids, max_new_tokens=6)
            got = req.get(timeout=120)
        finally:
            srv.stop()
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_continuous_batching_concurrent_requests(self, model):
        """Several overlapping requests of different lengths share the
        batch; each result equals its solo greedy continuation."""
        prompts = [np.array(p, np.int32) for p in
                   ([1, 2, 3], [7, 8], [9, 10, 11, 12], [5], [6, 4])]
        lens = [5, 3, 4, 6, 2]
        want = [model.generate(p[None], max_new_tokens=n)[0, len(p):]
                for p, n in zip(prompts, lens)]
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, lens)]
            got = [r.get(timeout=300) for r in reqs]
        finally:
            srv.stop()
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), w)
        # with max_batch=2 and 5 requests, slots must have been reused
        assert srv.steps >= max(lens)

    def test_legacy_slot_static_mode(self, model):
        """paged=False keeps the round-3 slot-static cache path."""
        ids = np.array([3, 1, 4, 1, 5], np.int32)
        want = model.generate(ids[None], max_new_tokens=6)[0, 5:]
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        paged=False).start()
        try:
            got = srv.submit(ids, max_new_tokens=6).get(timeout=120)
        finally:
            srv.stop()
        np.testing.assert_array_equal(np.asarray(got), want)

    def test_paged_16_mixed_length_requests(self, model):
        """The paged-cache north star (VERDICT r3 missing #1): 16
        concurrent mixed-length requests through 4 batch slots, each
        matching its solo greedy continuation, with KV HBM proportional
        to tokens in flight (pages, not slots × max_seq_len)."""
        rs = np.random.RandomState(7)
        prompts = [np.asarray(rs.randint(0, 250, rs.randint(1, 20)),
                              np.int32) for _ in range(16)]
        lens = [int(rs.randint(1, 10)) for _ in range(16)]
        want = [model.generate(p[None], max_new_tokens=n)[0, len(p):]
                for p, n in zip(prompts, lens)]
        srv = LLMServer(model, max_batch=4, max_seq_len=32,
                        page_size=16).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, lens)]
            got = [r.get(timeout=600) for r in reqs]
        finally:
            srv.stop()
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        # all requests done -> every page returned to the pool
        assert srv.pages_in_use == 0
        assert srv._budget_avail == srv._num_pages - 1
        assert sorted(srv._free) == list(range(1, srv._num_pages))

    def test_paged_budget_admission_small_pool(self, model):
        """A pool smaller than max_batch × worst case still serves every
        request: admission reserves page budgets and queues the rest."""
        prompts = [np.arange(1, 9, dtype=np.int32) for _ in range(6)]
        want = [model.generate(p[None], max_new_tokens=8)[0, len(p):]
                for p in prompts]
        # each request needs ceil(16/16) = 1..2 pages; pool of 4 usable
        srv = LLMServer(model, max_batch=4, max_seq_len=32,
                        page_size=16, num_pages=5).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=8) for p in prompts]
            got = [r.get(timeout=600) for r in reqs]
        finally:
            srv.stop()
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), w)

    @pytest.mark.parametrize("depth", [1, 4])
    def test_greedy_parity_under_concurrent_jax_load(self, model, depth):
        """Regression for the round-3 flaky race: concurrent jax
        executions on OTHER threads let the async CPU runtime recycle
        the engine's just-dropped cache buffers while the step consuming
        them was still in flight (14/30 greedy-parity mismatches before
        the block_until_ready barrier in _prefill_slot/_decode_scatter;
        0/30 after). Hammer threads + randomized submit timing. Re-run
        under pipelining (ISSUE 4): depth 4 replaces the per-step
        barrier with fence-pinned in-flight records, which must hold the
        same buffer-lifetime guarantee under the same load."""
        import threading
        import time

        import jax
        import jax.numpy as jnp

        ids = np.array([3, 1, 4, 1, 5], np.int32)
        want = model.generate(ids[None], max_new_tokens=6)[0, 5:]
        stop = threading.Event()

        def hammer():
            # input changes every call: some runtimes memoize identical
            # (program, args) executions, which would make a fixed-input
            # hammer generate zero real concurrent device traffic
            a = jax.random.normal(jax.random.PRNGKey(1), (256, 256))
            f = jax.jit(lambda x: jnp.tanh(x @ x) + 1e-6)
            while not stop.is_set():
                a = f(a).block_until_ready()

        threads = [threading.Thread(target=hammer, daemon=True)
                   for _ in range(4)]
        for t in threads:
            t.start()
        try:
            for it in range(6):
                srv = LLMServer(model, max_batch=2, max_seq_len=32,
                                pipeline_depth=depth).start()
                try:
                    time.sleep((it % 4) * 0.001)
                    req = srv.submit(ids, max_new_tokens=6)
                    got = np.asarray(req.get(timeout=120))
                finally:
                    srv.stop()
                np.testing.assert_array_equal(got, want,
                                              err_msg=f"iteration {it}")
        finally:
            stop.set()
            for t in threads:
                t.join(timeout=10)


class TestPipelinedEngine:
    """ISSUE 4: the async dispatch window must change THROUGHPUT, never
    tokens — greedy parity vs generate() at every depth, strict
    synchrony at depth 1, and budget/page invariants under speculative
    dispatch past data-dependent request ends."""

    @pytest.mark.parametrize("depth", [1, 2, 4])
    def test_greedy_parity_across_depths(self, model, depth):
        """Mixed-length overlapping requests through 2 slots at each
        pipeline depth: slot churn forces speculative steps for
        finished requests (their tokens must be discarded) and
        re-prefill into slots with steps still in flight."""
        prompts = [np.array(p, np.int32) for p in
                   ([1, 2, 3], [7, 8], [9, 10, 11, 12], [5], [6, 4])]
        lens = [5, 3, 4, 6, 2]
        want = [model.generate(p[None], max_new_tokens=n)[0, len(p):]
                for p, n in zip(prompts, lens)]
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        pipeline_depth=depth).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=n)
                    for p, n in zip(prompts, lens)]
            got = [r.get(timeout=300) for r in reqs]
        finally:
            srv.stop()
        for j, (g, w) in enumerate(zip(got, want)):
            np.testing.assert_array_equal(np.asarray(g), w,
                                          err_msg=f"request {j}")
        # every page returned despite speculative in-flight steps
        assert srv.pages_in_use == 0
        assert srv._budget_avail == srv._num_pages - 1
        assert sorted(srv._free) == list(range(1, srv._num_pages))
        assert not srv._inflight and not srv._pending_release

    def test_depth1_is_synchronous(self, model):
        """The acceptance contract: pipeline_depth=1 reproduces the
        synchronous engine — after every engine pass the in-flight
        window is empty and no pinned buffers survive, and with
        observability off no metric series exist at all."""
        from bigdl_tpu import observability as obs

        ids = np.array([3, 1, 4, 1, 5], np.int32)
        want = model.generate(ids[None], max_new_tokens=6)[0, 5:]
        obs.disable()
        try:
            before = len(obs.REGISTRY.collect())
            srv = LLMServer(model, max_batch=2, max_seq_len=32,
                            pipeline_depth=1)
            # drive the engine inline (no thread): inspect after passes
            req = srv.submit(ids, max_new_tokens=6)
            while not req.done.is_set():
                srv._admit()
                srv._step()
                assert len(srv._inflight) == 0      # drained every pass
                assert srv._pending_release == []   # nothing outlives it
            assert len(obs.REGISTRY.collect()) == before
        finally:
            obs.enable()
        np.testing.assert_array_equal(np.asarray(req.tokens), want)

    @pytest.mark.parametrize("depth", [2, 4])
    def test_slotted_engine_pipelined_parity(self, model, depth):
        """The legacy slot-static path under the same dispatch window
        (device-resident positions, non-donated cache pinned per
        record)."""
        ids = np.array([3, 1, 4, 1, 5], np.int32)
        want = model.generate(ids[None], max_new_tokens=6)[0, 5:]
        srv = LLMServer(model, max_batch=2, max_seq_len=32, paged=False,
                        pipeline_depth=depth).start()
        try:
            got = srv.submit(ids, max_new_tokens=6).get(timeout=120)
        finally:
            srv.stop()
        np.testing.assert_array_equal(np.asarray(got), want)
        assert not srv._inflight

    def test_small_pool_speculation_stays_inside_budget(self, model):
        """Speculative dispatch past a request's end must never allocate
        pages beyond the admission reserve: a pool barely larger than
        one request's worst case, deep pipeline, queued waiters — runs
        to completion (a budget overrun would IndexError the free list
        or deadlock admission) with exact greedy output."""
        prompts = [np.arange(1, 9, dtype=np.int32) for _ in range(6)]
        want = [model.generate(p[None], max_new_tokens=8)[0, len(p):]
                for p in prompts]
        srv = LLMServer(model, max_batch=4, max_seq_len=32,
                        page_size=16, num_pages=5,
                        pipeline_depth=4).start()
        try:
            reqs = [srv.submit(p, max_new_tokens=8) for p in prompts]
            got = [r.get(timeout=600) for r in reqs]
        finally:
            srv.stop()
        for g, w in zip(got, want):
            np.testing.assert_array_equal(np.asarray(g), w)
        assert srv._budget_avail == srv._num_pages - 1
        assert sorted(srv._free) == list(range(1, srv._num_pages))

    def test_pipeline_metrics_split(self, model):
        """The ISSUE 4 satellite's timing fix: decode time is reported
        as a host-scheduling slice and a fence-stall slice (plus the
        in-flight gauge), not one wall number hiding the barrier."""
        from bigdl_tpu import observability as obs

        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        pipeline_depth=2).start()
        try:
            srv.submit(np.array([3, 1, 4], np.int32),
                       max_new_tokens=5).get(timeout=120)
        finally:
            srv.stop()
        text = obs.render()
        assert "bigdl_llm_decode_host_seconds" in text
        assert "bigdl_llm_decode_stall_seconds" in text
        assert "bigdl_llm_pipeline_inflight" in text
        # the always-on accounting the microbench reads
        assert srv.host_seconds > 0.0
        assert srv.stall_seconds >= 0.0


class TestDecodeMicrobench:
    @pytest.mark.perf
    def test_microbench_runs_and_reports_split(self, model):
        """tools/microbench_decode.py end-to-end on the tiny model: one
        record per depth with the step/host/stall numbers bench.py's
        telemetry block embeds (values advisory — shared hosts)."""
        from tools.microbench_decode import run_microbench

        out = run_microbench(depths=(1, 2), batch=2, tokens=6,
                             warmup_tokens=2, model=model)
        for k in ("depth1", "depth2"):
            assert out[k]["steps"] > 0
            assert out[k]["step_ms"] > 0
            assert out[k]["host_ms_per_step"] >= 0
            assert out[k]["stall_ms_per_step"] >= 0
        assert "speedup_vs_depth1" in out

"""Serving / Friesian / Nano / PPML capability-layer tests."""

import threading

import numpy as np
import pandas as pd
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.nn.module import set_seed


def _mlp(in_dim=6, out_dim=3):
    set_seed(0)
    return (nn.Sequential()
            .add(nn.Linear(in_dim, 16)).add(nn.ReLU())
            .add(nn.Linear(16, out_dim)).add(nn.SoftMax()))


class TestServing:
    def test_inference_model_predict(self):
        from bigdl_tpu.serving import InferenceModel

        m = InferenceModel().load_bigdl(model=_mlp())
        m.aot_compile((4, 6))
        y = m.predict(np.random.rand(4, 6).astype(np.float32))
        assert y.shape == (4, 3)
        np.testing.assert_allclose(y.sum(1), 1.0, rtol=1e-4)

    def test_save_load_compiled_roundtrip(self, tmp_path):
        """The OpenVINO-artifact role (VERDICT r4 missing #4): serialize
        the COMPILED executable, reload it in a fresh InferenceModel,
        and predict without re-tracing. The reload must be numerically
        identical and skip compilation (cold start: artifact load is
        bounded well under a fresh jit of the same model)."""
        import time
        from bigdl_tpu.serving import InferenceModel

        m = InferenceModel().load_bigdl(model=_mlp())
        x = np.random.RandomState(0).rand(4, 6).astype(np.float32)
        want = m.predict(x)
        sizes = m.save_compiled(str(tmp_path / "art"), (4, 6))
        assert sizes["xla"] > 0 or sizes["hlo"] > 0

        m2 = InferenceModel().load_bigdl(model=_mlp())
        t0 = time.perf_counter()
        m2.load_compiled(str(tmp_path / "art"))
        got = m2.predict_compiled(x)
        cold_s = time.perf_counter() - t0
        np.testing.assert_allclose(got, want, rtol=1e-5)

        # fresh-jit control: trace+lower+compile the same model
        m3 = InferenceModel().load_bigdl(model=_mlp())
        t0 = time.perf_counter()
        m3.predict(x)
        fresh_s = time.perf_counter() - t0
        # the artifact path must not be slower than a fresh compile
        # (it skips trace+lower+XLA-compile; allow 2x slack for noise)
        assert cold_s < max(fresh_s * 2.0, 5.0), (cold_s, fresh_s)

    def test_load_compiled_requires_weights(self, tmp_path):
        from bigdl_tpu.serving import InferenceModel

        m = InferenceModel().load_bigdl(model=_mlp())
        m.save_compiled(str(tmp_path / "a"), (2, 6))
        with pytest.raises(RuntimeError, match="weights"):
            InferenceModel().load_compiled(str(tmp_path / "a"))

    def test_cluster_serving_roundtrip(self):
        from bigdl_tpu.serving import (
            ClusterServing, InferenceModel, InputQueue, OutputQueue)

        model = InferenceModel().load_bigdl(model=_mlp())
        serving = ClusterServing(model, stream_name="t1",
                                 batch_size=4).start()
        try:
            inq = InputQueue("t1")
            outq = OutputQueue("t1")
            xs = {f"r{i}": np.random.rand(1, 6).astype(np.float32)
                  for i in range(10)}
            for uri, x in xs.items():
                inq.enqueue(uri, input=x)
            for uri, x in xs.items():
                res = outq.query(uri, timeout=15)
                assert res.shape == (1, 3)
                direct = model.predict(x)
                np.testing.assert_allclose(res, direct, rtol=1e-4)
            assert serving.served == 10
        finally:
            serving.stop()


class TestFriesian:
    def test_encode_string_and_reuse_index(self):
        from bigdl_tpu.friesian import FeatureTable

        df = pd.DataFrame({"cat": ["a", "b", "a", "c"],
                           "v": [1.0, 2.0, 3.0, 4.0]})
        tbl = FeatureTable(df)
        enc, idx = tbl.encode_string("cat")
        assert enc.df["cat"].tolist() == [1, 2, 1, 3]
        df2 = pd.DataFrame({"cat": ["c", "zzz"], "v": [0.0, 0.0]})
        enc2, _ = FeatureTable(df2).encode_string("cat", indices=idx)
        assert enc2.df["cat"].tolist() == [3, 0]   # OOV -> 0

    def test_negative_sampling_and_cross(self):
        from bigdl_tpu.friesian import FeatureTable

        df = pd.DataFrame({"user": [1, 2], "item": [5, 7]})
        out = FeatureTable(df).add_negative_samples(
            item_size=100, item_col="item", neg_num=2)
        assert len(out.df) == 6
        assert (out.df["label"] == 1).sum() == 2
        crossed = out.cross_columns([["user", "item"]], [50])
        assert crossed.df["user_item"].between(0, 49).all()

    def test_hist_seq_and_pad(self):
        from bigdl_tpu.friesian import FeatureTable

        df = pd.DataFrame({"user": [1, 1, 1, 2, 2],
                           "item": [10, 11, 12, 20, 21],
                           "t": [1, 2, 3, 1, 2]})
        out = FeatureTable(df).gen_hist_seq("user", "item", sort_col="t",
                                            min_len=1, max_len=2)
        padded = out.pad("item_hist_seq", seq_len=3)
        for s in padded.df["item_hist_seq"]:
            assert len(s) == 3

    def test_brute_force_recall(self):
        from bigdl_tpu.friesian import BruteForceRecall

        rs = np.random.RandomState(0)
        items = rs.randn(100, 8).astype(np.float32)
        recall = BruteForceRecall(dim=8, metric="cosine").add(items)
        scores, idx = recall.search(items[17], k=5)
        assert idx[0, 0] == 17   # own nearest neighbor under cosine
        assert scores.shape == (1, 5)


class TestNano:
    def test_quantize_and_trace_agree(self):
        from bigdl_tpu.nano import InferenceOptimizer

        model = _mlp(in_dim=32)   # block quant needs K % 32 == 0
        x = np.random.rand(4, 32).astype(np.float32)
        base = InferenceOptimizer.trace(model, input_sample=x)
        ref = base(x)
        bf16 = InferenceOptimizer.quantize(model, "bf16")
        np.testing.assert_allclose(bf16(x), ref, atol=0.05)
        int8 = InferenceOptimizer.quantize(model, "int8")
        np.testing.assert_allclose(int8(x), ref, atol=0.05)

    def test_optimize_report_and_best(self):
        from bigdl_tpu.nano import InferenceOptimizer

        model = _mlp()
        x = np.random.rand(2, 6).astype(np.float32)
        report = InferenceOptimizer.optimize(model, x,
                                             latency_sample_num=3)
        assert report["original(jit)"]["status"] == "successful"
        best, name = InferenceOptimizer.get_best_model(report)
        assert best(x).shape == (2, 3)

    def test_save_load_optimized_pipeline(self, tmp_path):
        """Nano's deployable-artifact story (ref: P:nano
        InferenceOptimizer.save/load): an optimized pipeline round-trips
        through disk — module + quantization state + the serialized
        compiled executable — and predicts identically."""
        from bigdl_tpu.nano import InferenceOptimizer

        model = _mlp(in_dim=32)
        x = np.random.RandomState(0).rand(4, 32).astype(np.float32)
        pipe = InferenceOptimizer.quantize(model, "bf16")
        want = pipe(x)                     # traces; records example shape
        path = str(tmp_path / "nano_art")
        InferenceOptimizer.save(pipe, path)
        loaded = InferenceOptimizer.load(path)
        np.testing.assert_allclose(loaded(x), want, atol=1e-2)
        # the AOT artifact is wired when EITHER artifact round-trips
        import os
        assert os.path.exists(path + "/nano_meta.json")
        assert (loaded._aot is not None
                or not (os.path.exists(path + "/compiled.xla")
                        or os.path.exists(path + "/compiled.hlo")))
        # shape/dtype outside the compiled signature fall back to the
        # retracing jit path and must still be correct — and must NOT
        # poison the AOT gate for subsequent matching calls
        x8 = np.random.RandomState(1).rand(8, 32).astype(np.float32)
        y8 = loaded(x8)
        assert y8.shape == (8, 3)
        np.testing.assert_allclose(loaded(x8), y8, atol=1e-6)
        np.testing.assert_allclose(loaded(x), want, atol=1e-2)
        xi = x.astype(np.float64)
        assert loaded(xi).shape == (4, 3)   # dtype gate: jit fallback

    def test_trainer_fit(self):
        from bigdl_tpu.nano import Trainer

        rs = np.random.RandomState(0)
        x = rs.rand(64, 4).astype(np.float32)
        y = (x.sum(1, keepdims=True)).astype(np.float32)
        set_seed(1)
        from bigdl_tpu.optim.optim_method import SGD
        model = nn.Sequential().add(nn.Linear(4, 1))
        Trainer(max_epochs=30).fit(model, nn.MSECriterion(), x, y,
                                   batch_size=16,
                                   optim_method=SGD(learning_rate=0.3))
        pred = model.evaluate().forward(x)
        assert float(np.mean((np.asarray(pred) - y) ** 2)) < 0.05

    def test_trainer_multi_instance(self):
        """num_processes > 1: the reference's nano multi-instance
        training role on the RayContext spawn pool — sharded local SGD
        with per-epoch parameter averaging converges."""
        from bigdl_tpu.nano import Trainer
        from bigdl_tpu.optim.optim_method import SGD

        rs = np.random.RandomState(0)
        x = rs.rand(128, 4).astype(np.float32)
        y = (x.sum(1, keepdims=True)).astype(np.float32)
        set_seed(2)
        model = nn.Sequential().add(nn.Linear(4, 1))
        tr = Trainer(max_epochs=20, num_processes=2)
        # momentum exercises the carried-optimizer-state path (review
        # r4: slots must survive rounds, not reset every epoch)
        tr.fit(model, nn.MSECriterion(), x, y, batch_size=16,
               optim_method=SGD(learning_rate=0.2, momentum=0.9))
        assert len(tr.last_losses) == 20
        assert tr.last_losses[-1] < tr.last_losses[0]
        pred = model.evaluate().forward(x)
        assert float(np.mean((np.asarray(pred) - y) ** 2)) < 0.05


class TestPPML:
    def test_fedavg_two_parties(self):
        from bigdl_tpu.ppml import FLClient, FLEstimator, FLServer

        server = FLServer(client_num=2).build().start()
        try:
            rs = np.random.RandomState(0)
            w_true = rs.randn(4, 1).astype(np.float32)
            # two parties with disjoint data from the same distribution
            xs = [rs.rand(64, 4).astype(np.float32) for _ in range(2)]
            ys = [x @ w_true for x in xs]

            results = {}

            def party(pid):
                set_seed(42)   # same init on both parties (ref behavior)
                model = nn.Sequential().add(nn.Linear(4, 1))
                client = FLClient(f"p{pid}",
                                  f"127.0.0.1:{server.port}")
                est = FLEstimator(model, nn.MSECriterion(), client,
                                  lr=0.3)
                est.fit(xs[pid], ys[pid], rounds=15, local_epochs=3,
                        batch_size=16)
                results[pid] = est
                client.close()

            threads = [threading.Thread(target=party, args=(i,))
                       for i in range(2)]
            for t in threads:
                t.start()
            for t in threads:
                t.join(timeout=120)
            assert len(results) == 2
            # both parties converge to the same averaged model
            p0 = results[0].predict(xs[0])
            p1 = results[1].model.evaluate().forward(xs[0])
            np.testing.assert_allclose(p0, np.asarray(p1), atol=1e-5)
            mse = float(np.mean((p0 - ys[0]) ** 2))
            assert mse < 0.02, mse
        finally:
            server.stop()

    def test_psi_intersection(self):
        from bigdl_tpu.ppml import FLClient, FLServer

        server = FLServer(client_num=2).build().start()
        try:
            ids_a = ["alice", "bob", "carol", "dave"]
            ids_b = ["bob", "dave", "erin"]
            out = {}

            def party(name, ids):
                c = FLClient(name, f"127.0.0.1:{server.port}")
                salt = c.psi_get_salt()
                c.psi_upload_set(ids, salt)
                out[name] = c.psi_download_intersection()
                c.close()

            ts = [threading.Thread(target=party, args=("a", ids_a)),
                  threading.Thread(target=party, args=("b", ids_b))]
            for t in ts:
                t.start()
            for t in ts:
                t.join(timeout=60)
            assert out["a"] == ["bob", "dave"]
            assert out["b"] == ["bob", "dave"]
        finally:
            server.stop()


class TestFriesianServing:
    """Online serving pipeline (ref: friesian recall/feature/ranking/
    recommender gRPC services) — both in-process and over real TCP."""

    def _build(self):
        import bigdl_tpu.nn as nn
        from bigdl_tpu.friesian.serving import (
            FeatureService, RankingService, RecallService,
            RecommenderService)
        from bigdl_tpu.serving.inference_model import InferenceModel
        from bigdl_tpu.nn.module import set_seed

        rs = np.random.RandomState(0)
        dim = 8
        n_items = 64
        item_emb = rs.randn(n_items, dim).astype(np.float32)
        user_emb = rs.randn(4, dim).astype(np.float32)
        item_ids = np.arange(1000, 1000 + n_items)

        feature = FeatureService()
        feature.load_user_features(range(4), user_emb)
        feature.load_item_features(item_ids, item_emb)

        recall = RecallService(dim).add_items(item_emb)

        # ranking model: score = dot(user, item) computed by a linear net
        # with hand-set weights, through the real InferenceModel path
        set_seed(0)
        # x = [user || item]; score = sum(user * item) is not linear, so
        # use a score_fn computing it directly (the service contract) —
        # and a second service using InferenceModel for the model path
        ranking = RankingService(
            score_fn=lambda x: np.sum(x[:, :dim] * x[:, dim:], axis=1))
        rec = RecommenderService(feature, recall, ranking,
                                 item_ids=item_ids)
        return rec, user_emb, item_emb, item_ids

    def test_recommend_in_process(self):
        rec, user_emb, item_emb, item_ids = self._build()
        got = rec.recommend(user_id=2, k=5, candidate_num=20)
        # ground truth: top-5 items by dot product
        scores = item_emb @ user_emb[2]
        want = item_ids[np.argsort(-scores)[:5]].tolist()
        assert got == want

    def test_recommend_over_tcp(self):
        from bigdl_tpu.friesian.serving import (
            RecommenderService, ServiceClient)
        rec, user_emb, item_emb, item_ids = self._build()
        # re-compose the same backends as TCP services
        feature = rec._feature.start()
        recall = rec._recall.start()
        ranking = rec._ranking.start()
        try:
            rec2 = RecommenderService(feature.target, recall.target,
                                      ranking.target,
                                      item_ids=item_ids).start()
            client = ServiceClient(rec2.target)
            resp = client.call({"user_id": 1, "k": 4, "candidate_num": 16})
            got = np.asarray(resp["ids"]).tolist()
            scores = item_emb @ user_emb[1]
            want = item_ids[np.argsort(-scores)[:4]].tolist()
            assert got == want
            client.close()
            rec2.stop()
        finally:
            feature.stop()
            recall.stop()
            ranking.stop()

    def test_ranking_with_inference_model(self):
        import bigdl_tpu.nn as nn
        from bigdl_tpu.friesian.serving import RankingService
        from bigdl_tpu.serving.inference_model import InferenceModel
        from bigdl_tpu.nn.module import set_seed

        set_seed(3)
        dim = 6
        model = nn.Sequential().add(nn.Linear(2 * dim, 8)).add(nn.ReLU())\
            .add(nn.Linear(8, 1))
        im = InferenceModel()
        im.load_bigdl(model=model)
        svc = RankingService(inference_model=im)
        rs = np.random.RandomState(0)
        scores = svc.rank(rs.randn(dim).astype(np.float32),
                          rs.randn(10, dim).astype(np.float32))
        assert scores.shape == (10,)
        assert np.isfinite(scores).all()


class TestSparseTensorOps:
    """Expanded SparseTensor op surface (ref: S:dllib/tensor/SparseTensor
    .scala — VERDICT r2 weak #6: the 81-LoC sketch)."""

    def _st(self, d):
        from bigdl_tpu.tensor.sparse import SparseTensor
        return SparseTensor.from_dense(d)

    def test_add_and_coalesce(self):
        import numpy as np
        a = np.array([[1., 0], [0, 2]], np.float32)
        b = np.array([[3., 0], [4, 0]], np.float32)
        out = self._st(a).add(self._st(b))
        np.testing.assert_allclose(np.asarray(out.to_dense()), a + b)

    def test_mul_dense_and_scalar(self):
        import numpy as np
        a = np.array([[1., 0, 2], [0, 3, 0]], np.float32)
        d = np.arange(6, dtype=np.float32).reshape(2, 3)
        st = self._st(a)
        np.testing.assert_allclose(
            np.asarray(st.mul_dense(d).to_dense()), a * d)
        np.testing.assert_allclose(
            np.asarray(st.mul_scalar(2.5).to_dense()), a * 2.5)

    def test_transpose_narrow_concat(self):
        import numpy as np
        from bigdl_tpu.tensor.sparse import SparseTensor
        a = np.array([[1., 0, 2], [0, 3, 0], [4, 0, 5]], np.float32)
        st = self._st(a)
        np.testing.assert_allclose(np.asarray(st.transpose().to_dense()),
                                   a.T)
        np.testing.assert_allclose(
            np.asarray(st.narrow(0, 1, 2).to_dense()), a[1:3])
        np.testing.assert_allclose(
            np.asarray(st.narrow(1, 0, 2).to_dense()), a[:, :2])
        cat = SparseTensor.concat([st, st], dim=1)
        np.testing.assert_allclose(np.asarray(cat.to_dense()),
                                   np.concatenate([a, a], 1))

    def test_sum_apply(self):
        import numpy as np
        a = np.array([[1., 0], [0, -2]], np.float32)
        st = self._st(a)
        assert float(st.sum()) == -1.0
        np.testing.assert_allclose(
            np.asarray(st.apply(lambda v: v * v).to_dense()), a * a)


class TestInferenceOptimizerSweep:
    def test_optimize_reports_latency_and_metric(self):
        import numpy as np
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nano.inference_optimizer import InferenceOptimizer
        from bigdl_tpu.nn.module import set_seed

        set_seed(0)
        model = (nn.Sequential().add(nn.Linear(32, 64)).add(nn.ReLU())
                 .add(nn.Linear(64, 8)))
        x = np.random.RandomState(0).randn(4, 32).astype(np.float32)
        ref = np.asarray(model.forward(x))

        def mse(pred, y):
            return float(np.mean((pred - y) ** 2))

        report = InferenceOptimizer.optimize(
            model, x, latency_sample_num=2,
            validation_data=(x, ref), metric=mse)
        ok = [k for k, v in report.items() if v["status"] == "successful"]
        assert "original(jit)" in ok and "int8-conv" in ok
        assert report["int8-conv"]["metric"] < 1e-2
        best, name = InferenceOptimizer.get_best_model(report)
        assert name in ok
        table = InferenceOptimizer.summary(report)
        assert "pipeline" in table and "int8-conv" in table

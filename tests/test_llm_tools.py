"""LLM tooling tests: convert_model round-trip, llm-cli, langchain
wrappers (ref: P:llm convert/cli/langchain surfaces)."""

import numpy as np
import pytest

from bigdl_tpu.llm.convert_model import convert_model, load_model, save_model
from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM


@pytest.fixture(scope="module")
def converted_dir(tmp_path_factory):
    out = tmp_path_factory.mktemp("llm") / "model-q4"
    convert_model(LlamaConfig.tiny(), str(out), dtype="int4",
                  max_cache_len=64)
    return str(out)


class TestConvertModel:
    def test_roundtrip_preserves_generation(self, converted_dir):
        src = LlamaForCausalLM.from_config(
            LlamaConfig.tiny(), seed=0, load_in_low_bit="sym_int4",
            max_cache_len=64)
        loaded = load_model(converted_dir, max_cache_len=64)
        ids = np.array([[1, 2, 3]], np.int32)
        np.testing.assert_array_equal(
            src.generate(ids, max_new_tokens=6),
            loaded.generate(ids, max_new_tokens=6))

    def test_quantized_on_disk_size(self, converted_dir, tmp_path):
        import os

        dense_dir = tmp_path / "dense"
        save_model(LlamaForCausalLM.from_config(
            LlamaConfig.tiny(), seed=0, max_cache_len=64), str(dense_dir))
        q_size = os.path.getsize(os.path.join(converted_dir,
                                              "weights.npz"))
        d_size = os.path.getsize(os.path.join(dense_dir, "weights.npz"))
        assert q_size < d_size  # int4 payload beats dense storage

    def test_unknown_family_raises(self, tmp_path):
        with pytest.raises(NotImplementedError):
            convert_model(LlamaConfig.tiny(), str(tmp_path / "x"),
                          model_family="bloom")


class TestCLI:
    def test_llm_cli_main(self, converted_dir, capsys):
        from bigdl_tpu.llm.cli import main

        rc = main(["-m", converted_dir, "-p", "hello", "-n", "4",
                   "--ctx_size", "64"])
        assert rc == 0
        out = capsys.readouterr()
        assert "tok/s" in out.err


class TestLangchain:
    def test_llm_wrapper_invoke_and_stop(self, converted_dir):
        from bigdl_tpu.llm.langchain import BigdlTpuLLM

        llm = BigdlTpuLLM(converted_dir, max_new_tokens=6, ctx_size=64)
        text = llm.invoke("hi")
        assert isinstance(text, str)
        # stop sequence truncation
        if text:
            stopped = llm._call("hi", stop=[text[0]])
            assert not stopped.startswith(text[0]) or stopped == ""

    def test_embeddings_shapes(self, converted_dir):
        from bigdl_tpu.llm.langchain import BigdlTpuEmbeddings

        model = load_model(converted_dir, max_cache_len=64)
        emb = BigdlTpuEmbeddings(model)
        v = emb.embed_query("abc")
        assert len(v) == model.config.vocab_size  # tied-logit pooling dim
        vs = emb.embed_documents(["a", "b"])
        assert len(vs) == 2 and len(vs[0]) == len(v)

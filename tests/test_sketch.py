"""Mergeable quantile sketch (ISSUE 12 tentpole layer 1): the DDSketch
math (relative-error bound at every quantile), lossless merge vs the
pooled-sample sketch, snapshot round-trip, the `Sketch` registry
instrument (summary exposition, label children, re-declaration rules),
and the disabled-mode no-op contract."""

import json
import math

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu.observability.metrics import MetricRegistry
from bigdl_tpu.observability.sketch import QuantileSketch

pytestmark = pytest.mark.slo


def _exact_quantile(vals, q):
    s = sorted(vals)
    return s[max(int(math.ceil(q * len(s))) - 1, 0)]


class TestQuantileSketch:
    def test_relative_error_bound(self):
        rs = np.random.RandomState(0)
        # latencies spanning five orders of magnitude: µs stalls to
        # minute-long prefills in one sketch
        vals = np.concatenate([
            rs.lognormal(mean=-8, sigma=1.0, size=2000),
            rs.lognormal(mean=-2, sigma=1.5, size=2000),
            rs.uniform(10.0, 100.0, size=500)])
        sk = QuantileSketch(alpha=0.01)
        for v in vals:
            sk.observe(v)
        for q in (0.01, 0.1, 0.5, 0.9, 0.95, 0.99, 0.999):
            exact = _exact_quantile(vals, q)
            est = sk.quantile(q)
            assert abs(est - exact) / exact <= 0.0101, \
                f"q={q}: {est} vs {exact}"

    def test_count_sum_min_max(self):
        sk = QuantileSketch(alpha=0.02)
        for v in (0.5, 1.5, 3.0):
            sk.observe(v)
        assert sk.count == 3
        assert sk.sum == pytest.approx(5.0)
        assert sk.min == 0.5 and sk.max == 3.0

    def test_empty_and_zero_bucket(self):
        sk = QuantileSketch(alpha=0.01)
        assert sk.quantile(0.5) is None
        assert sk.min is None and sk.max is None
        sk.observe(0.0)
        sk.observe(0.0)
        sk.observe(1.0)
        assert sk.quantile(0.5) == 0.0          # rank 2 of 3 is a zero
        assert sk.quantile(1.0) == pytest.approx(1.0, rel=0.0101)
        assert sk.count == 3

    def test_nan_ignored(self):
        sk = QuantileSketch(alpha=0.01)
        sk.observe(float("nan"))
        assert sk.count == 0

    def test_merge_is_lossless(self):
        """The federation property: merging two shards is
        bucket-identical to sketching the pooled samples (sum differs
        only by float association order)."""
        rs = np.random.RandomState(7)
        vals = rs.lognormal(mean=-3, sigma=1.2, size=4000)
        pooled = QuantileSketch(alpha=0.01)
        a, b = QuantileSketch(alpha=0.01), QuantileSketch(alpha=0.01)
        for v in vals:
            pooled.observe(v)
        for v in vals[:1500]:
            a.observe(v)
        for v in vals[1500:]:
            b.observe(v)
        a.merge(b)
        sa, sp = a.to_snapshot(), pooled.to_snapshot()
        assert sa["buckets"] == sp["buckets"]
        assert sa["count"] == sp["count"] and sa["zero"] == sp["zero"]
        assert sa["sum"] == pytest.approx(sp["sum"])
        assert sa["min"] == sp["min"] and sa["max"] == sp["max"]
        # and therefore every quantile agrees exactly
        for q in (0.5, 0.95, 0.99):
            assert a.quantile(q) == pooled.quantile(q)

    def test_merged_p99_within_bound_of_pooled_raw(self):
        """The acceptance-criterion form: merged p99 vs the exact p99
        of the pooled RAW samples, within the stated alpha."""
        rs = np.random.RandomState(3)
        shard1 = rs.lognormal(mean=-4, sigma=1.0, size=3000)
        shard2 = rs.lognormal(mean=-3, sigma=1.5, size=2000)
        a, b = QuantileSketch(alpha=0.01), QuantileSketch(alpha=0.01)
        for v in shard1:
            a.observe(v)
        for v in shard2:
            b.observe(v)
        merged = QuantileSketch.merge_snapshots(
            [a.to_snapshot(), b.to_snapshot()])
        pooled = np.concatenate([shard1, shard2])
        for q in (0.5, 0.95, 0.99):
            exact = _exact_quantile(pooled, q)
            assert abs(merged.quantile(q) - exact) / exact <= 0.0101

    def test_merge_gamma_mismatch_raises(self):
        a, b = QuantileSketch(alpha=0.01), QuantileSketch(alpha=0.05)
        with pytest.raises(ValueError, match="gamma"):
            a.merge(b)

    def test_snapshot_roundtrip_through_json(self):
        sk = QuantileSketch(alpha=0.01)
        for v in (0.0, 1e-4, 0.5, 2.0, 300.0):
            sk.observe(v)
        wire = json.dumps(sk.to_snapshot())
        back = QuantileSketch.from_snapshot(json.loads(wire))
        assert back.to_snapshot() == sk.to_snapshot()
        assert back.quantile(0.5) == sk.quantile(0.5)

    def test_merge_snapshots_empty(self):
        assert QuantileSketch.merge_snapshots([]) is None


class TestSketchInstrument:
    def test_registry_declaration_and_render(self):
        reg = MetricRegistry()
        sk = reg.sketch("bigdl_test_latency_seconds", "test sketch")
        for v in (0.01, 0.02, 0.04):
            sk.observe(v)
        from bigdl_tpu.observability.metrics import render_prometheus
        text = render_prometheus(reg)
        assert "# TYPE bigdl_test_latency_seconds summary" in text
        assert 'bigdl_test_latency_seconds{quantile="0.99"}' in text
        assert "bigdl_test_latency_seconds_count 3" in text
        parsed = obs.parse_prometheus(text)
        assert parsed["bigdl_test_latency_seconds_count"][()] == 3
        p50 = parsed["bigdl_test_latency_seconds"][
            (("quantile", "0.5"),)]
        assert p50 == pytest.approx(0.02, rel=0.0101)

    def test_labeled_children(self):
        reg = MetricRegistry()
        sk = reg.sketch("bigdl_test_latency_seconds", "t",
                        labelnames=("stage",))
        sk.labels(stage="prefill").observe(0.1)
        sk.labels(stage="decode").observe(0.2)
        assert reg.sample_value("bigdl_test_latency_seconds",
                                stage="prefill") == 1

    def test_redeclare_same_returns_existing(self):
        reg = MetricRegistry()
        a = reg.sketch("bigdl_test_latency_seconds", "t")
        b = reg.sketch("bigdl_test_latency_seconds", "t")
        assert a is b

    def test_redeclare_alpha_mismatch_raises(self):
        reg = MetricRegistry()
        reg.sketch("bigdl_test_latency_seconds", "t", alpha=0.01)
        with pytest.raises(ValueError, match="alpha"):
            reg.sketch("bigdl_test_latency_seconds", "t", alpha=0.05)

    def test_redeclare_other_kind_raises(self):
        reg = MetricRegistry()
        reg.counter("bigdl_test_latency_seconds", "t")
        with pytest.raises(ValueError, match="already declared"):
            reg.sketch("bigdl_test_latency_seconds", "t")

    def test_disabled_mode_noop(self):
        reg = MetricRegistry()
        sk = reg.sketch("bigdl_test_latency_seconds", "t")
        sk.observe(1.0)
        assert sk.count == 1
        obs.disable()
        try:
            sk.observe(2.0)
            assert sk.count == 1    # nothing recorded
        finally:
            obs.enable()

    def test_empty_sketch_renders_nan(self):
        reg = MetricRegistry()
        reg.sketch("bigdl_test_latency_seconds", "t")
        from bigdl_tpu.observability.metrics import render_prometheus
        text = render_prometheus(reg)
        assert 'bigdl_test_latency_seconds{quantile="0.5"} NaN' in text
        assert "bigdl_test_latency_seconds_count 0" in text

"""The convergence benchmark's metric must be FALSIFIABLE (VERDICT r4
missing #2): the hard synthetic sets are Bayes-calibrated so a healthy
training run lands in a band below 1.0, and a deliberately-lamed
optimizer (lr=0) demonstrably fails the band — proving the metric can
catch a broken optimizer, unlike the saturated easy sets."""

import numpy as np

import bigdl_tpu.nn as nn
from bigdl_tpu.feature.dataset import DataSet
from bigdl_tpu.feature.mnist import (load_mnist, nearest_prototype_accuracy,
                                     normalize)
from bigdl_tpu.models import lenet
from bigdl_tpu.optim import (Adam, Evaluator, Optimizer, Top1Accuracy,
                             Trigger)


import functools


@functools.lru_cache(maxsize=None)
def _train_top1_cached(lr: float, epochs: int = 3) -> float:
    return _train_top1(lr, epochs)


def _train_top1(lr: float, epochs: int = 3) -> float:
    xtr, ytr = load_mnist(train=True, synthetic_size=2048, hard=True)
    xte, yte = load_mnist(train=False, synthetic_size=1024, hard=True)
    xtr = normalize(xtr).reshape(-1, 784)
    xte = normalize(xte).reshape(-1, 784)
    model = lenet.build_model(10)
    opt = Optimizer(model, DataSet.array(xtr, ytr),
                    nn.ClassNLLCriterion(), batch_size=256,
                    end_trigger=Trigger.max_epoch(epochs),
                    distributed=False)
    opt.set_optim_method(Adam(learning_rate=lr))
    trained = opt.optimize()
    acc = Evaluator(trained).evaluate((xte, yte), [Top1Accuracy()])[0]
    return float(acc.result)


class TestConvergenceFalsifiable:
    def test_hard_set_ceiling_is_calibrated(self):
        """Nearest-prototype (≈Bayes) on the hard test draw sits in the
        designed non-saturated band — NOT at 1.0."""
        xte, yte = load_mnist(train=False, synthetic_size=4096, hard=True)
        bayes = nearest_prototype_accuracy(xte, yte)
        assert 0.93 <= bayes <= 0.975, bayes

    def test_train_test_draws_disjoint(self):
        xtr, _ = load_mnist(train=True, synthetic_size=512, hard=True)
        xte, _ = load_mnist(train=False, synthetic_size=512, hard=True)
        assert not np.array_equal(xtr[:16], xte[:16])

    def test_lamed_control_fails_the_band(self):
        """lr=0 (the deliberately broken optimizer) must land near
        chance — the band [0.90, 0.99) catches it. This is the evidence
        that the benchmark metric CAN fail."""
        acc = _train_top1_cached(lr=0.0, epochs=1)
        assert acc < 0.35, f"lr=0 control scored {acc}: metric cannot fail"

    def test_healthy_short_run_beats_control(self):
        """A real (short) run clears the control by a wide margin on the
        same hard set — the band's lower edge is reachable."""
        acc = _train_top1(lr=1e-3, epochs=4)
        lamed = _train_top1_cached(lr=0.0, epochs=1)
        # 2048 samples x 4 epochs reaches ~0.7 on the hard set (the full
        # bench runs 8192 x 12); the test only pins healthy >> lamed
        assert acc > 0.6, f"healthy short run only reached {acc}"
        assert acc > lamed + 0.3

"""Fleet telemetry plane (ISSUE 12): registry snapshots, the
label-aware merge rules (counters sum, gauges gain an instance label,
sketches merge losslessly), the background collector's stale-marking
failure model, the member/fleet HTTP surfaces, per-request SLO
accounting on the live engine and the failover router, and the
disabled-mode structural-absence contract.

The acceptance merge-correctness test runs TWO LIVE WORKERS through a
federation-enabled router: the federated counter values must equal the
per-worker snapshot sums, and the merged sketch's p99 must agree with
a sketch built from the pooled per-worker states within the sketch's
stated relative-error bound."""

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu import reliability as rel
from bigdl_tpu.observability.federation import (
    FederationCollector, SnapshotServer, merge_snapshots,
    registry_snapshot, render_merged)
from bigdl_tpu.observability.metrics import MetricRegistry
from bigdl_tpu.observability.sketch import QuantileSketch
from bigdl_tpu.observability.slo import SLOAccount, itl_samples
from bigdl_tpu.utils.conf import conf

pytestmark = pytest.mark.slo


def _req(addr, method, path, body=None, timeout=120):
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        payload = json.dumps(body) if body is not None else None
        conn.request(method, path, payload,
                     {"Content-Type": "application/json"}
                     if body is not None else {})
        r = conn.getresponse()
        raw = r.read()
        try:
            data = json.loads(raw.decode())
        except ValueError:
            data = raw
        return r.status, data
    finally:
        conn.close()


def _make_registry(counter=0.0, gauge=None, sketch_vals=(),
                   hist_vals=()):
    reg = MetricRegistry()
    if counter:
        reg.counter("bigdl_llm_decode_tokens_total", "t").inc(counter)
    if gauge is not None:
        reg.gauge("bigdl_llm_active_slots", "t").set(gauge)
    if sketch_vals:
        sk = reg.sketch("bigdl_router_ttft_seconds", "t", alpha=0.01)
        for v in sketch_vals:
            sk.observe(v)
    if hist_vals:
        h = reg.histogram("bigdl_llm_prefill_seconds", "t")
        for v in hist_vals:
            h.observe(v)
    return reg


# ---------------------------------------------------------------------------
# snapshot + merge units
# ---------------------------------------------------------------------------

class TestMerge:
    def test_counters_sum(self):
        snaps = {
            "a": registry_snapshot(_make_registry(counter=10)),
            "b": registry_snapshot(_make_registry(counter=5))}
        merged = merge_snapshots(snaps)
        m = {d["name"]: d for d in merged["metrics"]}
        series = m["bigdl_llm_decode_tokens_total"]["series"]
        assert len(series) == 1 and series[0]["value"] == 15.0

    def test_gauges_gain_instance_label(self):
        snaps = {
            "a": registry_snapshot(_make_registry(gauge=2)),
            "b": registry_snapshot(_make_registry(gauge=3))}
        merged = merge_snapshots(snaps)
        m = {d["name"]: d for d in merged["metrics"]}
        g = m["bigdl_llm_active_slots"]
        assert g["labelnames"] == ["instance"]
        vals = {tuple(s["labels"]): s["value"] for s in g["series"]}
        assert vals == {("a",): 2.0, ("b",): 3.0}

    def test_histograms_sum_bucketwise(self):
        snaps = {
            "a": registry_snapshot(_make_registry(hist_vals=(0.01,))),
            "b": registry_snapshot(_make_registry(hist_vals=(0.02,
                                                             5.0)))}
        merged = merge_snapshots(snaps)
        m = {d["name"]: d for d in merged["metrics"]}
        s = m["bigdl_llm_prefill_seconds"]["series"][0]
        assert s["count"] == 3
        assert s["cum"][-1] == 3          # +Inf bucket
        assert s["sum"] == pytest.approx(5.03)

    def test_sketches_merge_losslessly(self):
        va, vb = (0.01, 0.02, 0.5), (0.03, 0.04)
        snaps = {
            "a": registry_snapshot(_make_registry(sketch_vals=va)),
            "b": registry_snapshot(_make_registry(sketch_vals=vb))}
        merged = merge_snapshots(snaps)
        m = {d["name"]: d for d in merged["metrics"]}
        sk = QuantileSketch.from_snapshot(
            m["bigdl_router_ttft_seconds"]["series"][0]["sketch"])
        pooled = QuantileSketch(alpha=0.01)
        for v in va + vb:
            pooled.observe(v)
        assert sk.count == 5
        assert sk.to_snapshot()["buckets"] == \
            pooled.to_snapshot()["buckets"]

    def test_sketch_alpha_mismatch_falls_back_to_instance(self):
        ra = MetricRegistry()
        ra.sketch("bigdl_router_ttft_seconds", "t",
                  alpha=0.01).observe(0.1)
        rb = MetricRegistry()
        rb.sketch("bigdl_router_ttft_seconds", "t",
                  alpha=0.05).observe(0.2)
        merged = merge_snapshots({"a": registry_snapshot(ra),
                                  "b": registry_snapshot(rb)})
        m = {d["name"]: d for d in merged["metrics"]}
        series = m["bigdl_router_ttft_seconds"]["series"]
        # both survive: one plain, one instance-tagged passthrough
        assert len(series) == 2
        total = sum(QuantileSketch.from_snapshot(s["sketch"]).count
                    for s in series)
        assert total == 2

    def test_render_merged_parses(self):
        snaps = {
            "a": registry_snapshot(_make_registry(
                counter=2, gauge=1, sketch_vals=(0.1, 0.2))),
            "b": registry_snapshot(_make_registry(counter=3))}
        text = render_merged(merge_snapshots(snaps))
        parsed = obs.parse_prometheus(text)
        assert parsed["bigdl_llm_decode_tokens_total"][()] == 5.0
        assert parsed["bigdl_llm_active_slots"][
            (("instance", "a"),)] == 1.0
        assert parsed["bigdl_router_ttft_seconds_count"][()] == 2


# ---------------------------------------------------------------------------
# collector: scraping, stale marking, lifecycle
# ---------------------------------------------------------------------------

class _StubMember:
    """Tiny member serving a fixed snapshot doc (its own registry)."""

    def __init__(self, registry):
        stub = self

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_GET(self):
                if self.path != "/metrics/snapshot":
                    self.send_response(404)
                    self.send_header("Content-Length", "0")
                    self.end_headers()
                    return
                body = json.dumps(registry_snapshot(
                    stub.registry)).encode()
                self.send_response(200)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.registry = registry
        self._httpd = ThreadingHTTPServer(("127.0.0.1", 0), Handler)
        self.address = self._httpd.server_address[:2]
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()

    def stop(self):
        self._httpd.shutdown()
        self._thread.join(timeout=5)
        self._httpd.server_close()


class TestCollector:
    def test_collects_and_merges(self):
        a = _StubMember(_make_registry(counter=7))
        b = _StubMember(_make_registry(counter=4))
        col = FederationCollector(
            lambda: [("a", a.address), ("b", b.address)],
            interval=3600)
        try:
            col.collect_now()
            merged = col.merged()
            m = {d["name"]: d for d in merged["metrics"]}
            assert m["bigdl_llm_decode_tokens_total"]["series"][0][
                "value"] == 11.0
            st = col.status()
            assert st["stale"] == 0
            assert set(st["members"]) == {"a", "b"}
        finally:
            a.stop()
            b.stop()

    def test_dead_member_goes_stale_not_fatal(self):
        a = _StubMember(_make_registry(counter=7))
        col = FederationCollector(
            lambda: [("a", a.address)], interval=3600)
        try:
            col.collect_now()
            assert col.status()["members"]["a"]["stale"] is False
            a.stop()
            col.collect_now()          # scrape fails: stale, not raise
            st = col.status()["members"]["a"]
            assert st["stale"] is True and st["failures"] >= 1
            # last-known snapshot keeps serving
            m = {d["name"]: d for d in col.merged()["metrics"]}
            assert m["bigdl_llm_decode_tokens_total"]["series"][0][
                "value"] == 7.0
        finally:
            try:
                a.stop()
            except Exception:
                pass

    def test_scrape_fault_site_marks_stale(self, ):
        was = rel.enabled()
        if not was:
            rel.enable()
        a = _StubMember(_make_registry(counter=7))
        col = FederationCollector(
            lambda: [("a", a.address)], interval=3600)
        try:
            plan = rel.FaultPlan(seed=0)
            plan.add("federation.scrape", "raise", times=1)
            rel.set_plan(plan)
            col.collect_now()
            assert col.status()["members"]["a"]["stale"] is True
            rel.set_plan(None)
            col.collect_now()          # recovery on the next sweep
            assert col.status()["members"]["a"]["stale"] is False
        finally:
            rel.set_plan(None)
            if not was:
                rel.disable()
            a.stop()

    def test_departed_member_dropped(self):
        a = _StubMember(_make_registry(counter=7))
        targets = [("a", a.address)]
        col = FederationCollector(lambda: list(targets), interval=3600)
        try:
            col.collect_now()
            assert "a" in col.status()["members"]
            targets.clear()
            col.collect_now()
            assert col.status()["members"] == {}
        finally:
            a.stop()

    def test_thread_lifecycle(self):
        col = FederationCollector(lambda: [], interval=3600)
        col.start()
        assert any(t.name == FederationCollector.THREAD_NAME
                   for t in threading.enumerate())
        col.stop()
        assert not any(t.name == FederationCollector.THREAD_NAME
                       for t in threading.enumerate())

    def test_snapshot_server(self):
        srv = SnapshotServer(instance="pidX").start()
        try:
            st, doc = _req(srv.address, "GET", "/metrics/snapshot")
            assert st == 200 and doc["instance"] == "pidX"
            st, _ = _req(srv.address, "GET", "/nope")
            assert st == 404
        finally:
            srv.stop()


# ---------------------------------------------------------------------------
# SLO accounting units
# ---------------------------------------------------------------------------

class TestSLOAccount:
    def test_if_enabled_gate(self):
        assert SLOAccount.if_enabled("engine") is None    # default off
        acct = SLOAccount.if_enabled("engine", enabled=True)
        assert acct is not None and acct.scope == "engine"

    def test_classification_and_burn_rate(self):
        acct = SLOAccount("router", ttft_ms=100.0, itl_ms=50.0,
                          window=4)
        before = {
            (v, s): obs.REGISTRY.sample_value(
                "bigdl_slo_requests_total", slo=s, verdict=v,
                scope="router") or 0.0
            for v in ("ok", "violated") for s in ("ttft", "itl")}
        acct.finish(0.05, 0.01)     # both ok
        acct.finish(0.25, 0.01)     # ttft violated
        acct.finish(0.05, 0.30)     # itl violated
        acct.finish(None, None)     # no token ever: ttft violated,
        #                             itl vacuously ok

        def delta(v, s):
            return (obs.REGISTRY.sample_value(
                "bigdl_slo_requests_total", slo=s, verdict=v,
                scope="router") or 0.0) - before[(v, s)]

        assert delta("ok", "ttft") == 2 and delta("violated",
                                                  "ttft") == 2
        assert delta("ok", "itl") == 3 and delta("violated",
                                                 "itl") == 1
        assert acct.burn_rates() == {"ttft": 0.5, "itl": 0.25}
        st = acct.status()
        assert st["requests"] == 4
        assert st["violations"] == {"ttft": 2, "itl": 1}

    def test_window_rolls(self):
        acct = SLOAccount("engine", ttft_ms=100.0, itl_ms=50.0,
                          window=2)
        acct.finish(1.0, None)      # violated
        acct.finish(0.01, None)     # ok
        acct.finish(0.01, None)     # ok — the violation rolled out
        assert acct.burn_rates()["ttft"] == 0.0

    def test_itl_samples_helper(self):
        assert itl_samples([1.0, 1.5, 1.6]) == \
            pytest.approx([0.5, 0.1])
        assert itl_samples([2.0]) == []


# ---------------------------------------------------------------------------
# live engine + router (the tentpole surfaces)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=128)


class TestEngineSLO:
    def test_engine_records_and_classifies(self, model):
        from bigdl_tpu.llm.serving import LLMServer
        before_ttft = obs.REGISTRY.sample_value(
            "bigdl_llm_ttft_seconds") or 0
        before_itl = obs.REGISTRY.sample_value(
            "bigdl_llm_itl_seconds") or 0
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                        slo=True).start()
        try:
            rs = np.random.RandomState(0)
            p = rs.randint(0, 250, 8).astype(np.int32)
            toks = srv.submit(p, max_new_tokens=4).get(timeout=600)
        finally:
            srv.stop()
        assert (obs.REGISTRY.sample_value("bigdl_llm_ttft_seconds")
                - before_ttft) == 1
        assert (obs.REGISTRY.sample_value("bigdl_llm_itl_seconds")
                - before_itl) == len(toks) - 1
        st = srv._slo.status()
        assert st["requests"] == 1 and st["scope"] == "engine"

    def test_disabled_engine_structurally_absent(self, model):
        from bigdl_tpu.llm.serving import LLMServer
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8).start()
        try:
            # the gate defaults off (gatecheck absence-test contract)
            assert conf.get_bool("bigdl.slo.enabled", False) is False
            assert srv._slo is None
            before = set(obs.render().splitlines())
            rs = np.random.RandomState(0)
            p = rs.randint(0, 250, 8).astype(np.int32)
            srv.submit(p, max_new_tokens=2).get(timeout=600)
            new = "\n".join(set(obs.render().splitlines()) - before)
            for name in ("bigdl_llm_ttft_seconds",
                         "bigdl_llm_itl_seconds",
                         "bigdl_slo_requests_total",
                         "bigdl_slo_burn_rate"):
                assert name not in new
        finally:
            srv.stop()


class TestLiveFleet:
    """The acceptance criterion: two live workers served through the
    router — federated counters equal the per-worker sums, merged
    sketch p99 within the stated relative-error bound of the pooled
    state."""

    def test_merge_correctness_two_live_workers(self, model):
        from bigdl_tpu.llm.serving import LLMServer
        from bigdl_tpu.llm.worker import LLMRouter, LLMWorker
        s1 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                       slo=True).start()
        s2 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                       slo=True).start()
        w1 = LLMWorker(s1, role="decode", federation=True).start()
        w2 = LLMWorker(s2, role="decode", federation=True).start()
        router = LLMRouter([], [w1.address, w2.address], failover=True,
                           slo=True, federation=True,
                           start_prober=False).start()
        try:
            rs = np.random.RandomState(0)
            base_ttft = obs.REGISTRY.sample_value(
                "bigdl_router_ttft_seconds") or 0
            base_itl = obs.REGISTRY.sample_value(
                "bigdl_router_itl_seconds") or 0
            total_toks = 0
            for j in range(4):
                p = rs.randint(0, 250, 8 + 2 * j).astype(np.int32)
                st, body = _req(router.address, "POST",
                                "/worker_generate",
                                {"prompt_ids": [int(t) for t in p],
                                 "max_new_tokens": 3})
                assert st == 200, body
                total_toks += len(body["output_ids"])
            # router-side SLO sketches: one TTFT sample per request,
            # tokens-1 ITL samples per request
            assert (obs.REGISTRY.sample_value(
                "bigdl_router_ttft_seconds") - base_ttft) == 4
            assert (obs.REGISTRY.sample_value(
                "bigdl_router_itl_seconds") - base_itl) == \
                total_toks - 4

            # member snapshots straight off each worker
            st1, snap1 = _req(w1.address, "GET", "/metrics/snapshot")
            st2, snap2 = _req(w2.address, "GET", "/metrics/snapshot")
            assert st1 == 200 and st2 == 200

            # federated counters == per-worker sums (exactly)
            merged = merge_snapshots({"w1": snap1, "w2": snap2})
            m = {d["name"]: d for d in merged["metrics"]}
            for name in ("bigdl_llm_decode_tokens_total",
                         "bigdl_llm_prefill_tokens_total"):
                per = []
                for snap in (snap1, snap2):
                    for d in snap["metrics"]:
                        if d["name"] == name:
                            per.append(sum(s["value"]
                                           for s in d["series"]))
                fed = sum(s["value"] for s in m[name]["series"])
                assert fed == pytest.approx(sum(per)), name

            # merged sketch p99 vs the sketch of the pooled state:
            # within the stated relative-error bound
            def member_sketch(snap, name):
                for d in snap["metrics"]:
                    if d["name"] == name:
                        return d["series"][0]["sketch"]
                return None
            snaps = [member_sketch(s, "bigdl_router_ttft_seconds")
                     for s in (snap1, snap2)]
            snaps = [s for s in snaps if s]
            pooled = QuantileSketch.merge_snapshots(snaps)
            fed_sk = QuantileSketch.from_snapshot(
                member_sketch(merged, "bigdl_router_ttft_seconds"))
            alpha = pooled.alpha
            p99_fed, p99_pooled = (fed_sk.quantile(0.99),
                                   pooled.quantile(0.99))
            assert abs(p99_fed - p99_pooled) <= \
                2 * alpha * max(p99_pooled, 1e-12)

            # fleet surfaces: collector sweep -> /fleet/status +
            # merged /metrics
            router._collector.collect_now()
            st, status = _req(router.address, "GET", "/fleet/status")
            assert st == 200
            assert set(status["members"]) == {
                f"{w1.address[0]}:{w1.address[1]}",
                f"{w2.address[0]}:{w2.address[1]}"}
            assert status["stale"] == 0
            st, text = _req(router.address, "GET", "/metrics")
            assert st == 200
            parsed = obs.parse_prometheus(text.decode())
            # three copies of the shared in-process registry (w1, w2,
            # router self): the federated counter triples the local one
            local = obs.REGISTRY.sample_value(
                "bigdl_llm_decode_tokens_total")
            assert parsed["bigdl_llm_decode_tokens_total"][()] == \
                pytest.approx(3 * local)
            # healthz carries the burn-rate block
            st, hz = _req(router.address, "GET", "/healthz")
            assert "slo" in hz and "burn_rate" in hz["slo"]
            st, hz = _req(w1.address, "GET", "/healthz")
            assert "slo" in hz and hz["slo"]["scope"] == "engine"
        finally:
            router.stop()
            w1.stop()
            w2.stop()
            s1.stop()
            s2.stop()

    def test_disabled_mode_structural_absence(self, model):
        from bigdl_tpu.llm.serving import LLMServer
        from bigdl_tpu.llm.worker import LLMRouter, LLMWorker
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8).start()
        w = LLMWorker(srv, role="decode").start()
        router = LLMRouter([], [w.address],
                           start_prober=False).start()
        try:
            assert router._collector is None and router._slo is None
            assert srv._slo is None
            st, _ = _req(w.address, "GET", "/metrics/snapshot")
            assert st == 404
            st, _ = _req(router.address, "GET", "/fleet/status")
            assert st == 404
            assert not any(
                t.name == FederationCollector.THREAD_NAME
                for t in threading.enumerate())
            # router /metrics stays the plain process registry
            st, text = _req(router.address, "GET", "/metrics")
            assert st == 200
        finally:
            router.stop()
            w.stop()
            srv.stop()


class TestElasticFederation:
    def test_supervisor_collects_agent_snapshots(self):
        from bigdl_tpu.elastic.agent import ElasticAgent
        from bigdl_tpu.elastic.supervisor import Supervisor
        conf.set("bigdl.observability.federation", "true")
        try:
            sup = Supervisor(expected=2).start()
            a1 = ElasticAgent(0, supervisor_address=sup.address).start()
            a2 = ElasticAgent(1, supervisor_address=sup.address).start()
            try:
                assert a1._metrics_server is not None
                a1.step_heartbeat(1)
                a2.step_heartbeat(2)
                a1.beat()
                a2.beat()
                sup._collector.collect_now()
                st, status = _req(sup.address, "GET", "/fleet/status")
                assert st == 200
                assert set(status["members"]) == {"pid0", "pid1"}
                st, text = _req(sup.address, "GET", "/metrics")
                assert st == 200
                assert b"bigdl_elastic_heartbeats_total" in text
            finally:
                a1.stop()
                a2.stop()
                sup.stop()
        finally:
            conf.unset("bigdl.observability.federation")

    def test_malformed_metrics_addr_is_422_and_unrecorded(self):
        from bigdl_tpu.elastic.supervisor import Supervisor
        sup = Supervisor(expected=1).start()
        try:
            st, body = _req(sup.address, "POST", "/elastic/heartbeat",
                            {"pid": 0, "metrics_addr": []})
            assert st == 422, body
            # the bad beat mutated nothing: the peer never registered
            assert sup.live_peers() == 0
            st, _ = _req(sup.address, "POST", "/elastic/heartbeat",
                         {"pid": 0,
                          "metrics_addr": ["127.0.0.1", "80"]})
            assert st == 200
        finally:
            sup.stop()

    def test_fleet_status_carries_member_addresses(self):
        """fleet_report --url re-fetches member snapshots from the
        advertised address — elastic members are named pidN, so the
        name alone is not a scrape target."""
        a = _StubMember(_make_registry(counter=1))
        col = FederationCollector(lambda: [("pid0", a.address)],
                                  interval=3600)
        try:
            col.collect_now()
            member = col.status()["members"]["pid0"]
            assert member["address"] == [a.address[0], a.address[1]]
        finally:
            a.stop()

    def test_disabled_supervisor_absent(self):
        from bigdl_tpu.elastic.agent import ElasticAgent
        from bigdl_tpu.elastic.supervisor import Supervisor
        sup = Supervisor(expected=1).start()
        agent = ElasticAgent(0, supervisor_address=sup.address).start()
        try:
            assert sup._collector is None
            assert agent._metrics_server is None
            st, _ = _req(sup.address, "GET", "/fleet/status")
            assert st == 404
            st, _ = _req(sup.address, "GET", "/metrics")
            assert st == 404
        finally:
            agent.stop()
            sup.stop()


class TestJournalTimestamps:
    def test_resumed_tokens_stamped_once(self):
        from bigdl_tpu.llm.failover import RequestJournal
        j = RequestJournal()
        ent = j.add([1, 2, 3], 6)
        ent.drained([10], 0)
        ent.drained([10, 11], 0)
        t2 = list(ent.token_times)
        # the failover resume: a new attempt REPLAYS the prefix
        # cumulatively from its base — stamps must not change
        ent.drained([12], 2)
        ent.drained([12, 13], 2)
        assert ent.tokens == [10, 11, 12, 13]
        assert len(ent.token_times) == 4
        assert ent.token_times[:2] == t2
        # a hedge-twin echo behind the winner is a no-op
        times = list(ent.token_times)
        ent.drained([12], 2)
        assert ent.token_times == times
        assert len(itl_samples(ent.token_times)) == 3

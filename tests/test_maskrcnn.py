"""Mask-RCNN + detection ops (ref: S:dllib/models/maskrcnn and its nn
support layers — RoiAlign, Nms, anchor/box utils; golden-parity against
independent numpy implementations per SURVEY.md §4)."""

import jax.numpy as jnp
import numpy as np

from bigdl_tpu.nn.layers.detection import (
    box_iou, decode_boxes, encode_boxes, generate_anchors, nms, roi_align)


class TestRoiAlign:
    def test_matches_numpy_bilinear(self):
        """One 2x2-bin ROI on a linear ramp: averaging bilinear samples
        of a linear function is exact, so the expected value is the
        function at the bin-center mean."""
        h = w = 8
        feat = (np.arange(h)[:, None] * 10.0
                + np.arange(w)[None, :]).astype(np.float32)
        feats = feat[None, :, :, None]                    # (1, 8, 8, 1)
        boxes = np.array([[1.0, 1.0, 5.0, 5.0]], np.float32)
        out = np.asarray(roi_align(jnp.asarray(feats), jnp.asarray(boxes),
                                   jnp.zeros(1, jnp.int32), output_size=2,
                                   sampling_ratio=2))[0, :, :, 0]
        # bins are 2x2 over [1, 5): centers at 2, 4. Continuous coord y
        # maps to pixel index y - 0.5 (torchvision ROIAlign convention),
        # so f(y, x) = 10*(y-0.5) + (x-0.5).
        expect = np.array([[(2 - .5) * 10 + (2 - .5),
                            (2 - .5) * 10 + (4 - .5)],
                           [(4 - .5) * 10 + (2 - .5),
                            (4 - .5) * 10 + (4 - .5)]], np.float32)
        np.testing.assert_allclose(out, expect, rtol=1e-5)

    def test_batch_index_selects_image(self):
        feats = np.stack([np.zeros((4, 4, 1)), np.ones((4, 4, 1))]) \
            .astype(np.float32)
        boxes = np.array([[0, 0, 4, 4], [0, 0, 4, 4]], np.float32)
        out = np.asarray(roi_align(jnp.asarray(feats), jnp.asarray(boxes),
                                   jnp.asarray([0, 1], jnp.int32),
                                   output_size=2))
        assert np.allclose(out[0], 0.0) and np.allclose(out[1], 1.0)


class TestNms:
    def test_matches_numpy_greedy(self):
        rs = np.random.RandomState(0)
        xy = rs.rand(24, 2) * 40
        wh = rs.rand(24, 2) * 20 + 4
        boxes = np.concatenate([xy, xy + wh], 1).astype(np.float32)
        scores = rs.rand(24).astype(np.float32)
        idx, valid = nms(jnp.asarray(boxes), jnp.asarray(scores),
                         iou_threshold=0.4, max_out=24)
        got = [int(i) for i, v in zip(np.asarray(idx), np.asarray(valid))
               if v]

        # independent numpy greedy reference
        iou = np.asarray(box_iou(jnp.asarray(boxes), jnp.asarray(boxes)))
        avail = scores.copy()
        want = []
        while True:
            b = int(np.argmax(avail))
            if avail[b] == -np.inf:
                break
            want.append(b)
            avail[iou[b] > 0.4] = -np.inf
            avail[b] = -np.inf
        assert got == want

    def test_static_output_shape(self):
        boxes = jnp.asarray([[0, 0, 10, 10], [0, 0, 10, 10.]],
                            jnp.float32)
        idx, valid = nms(boxes, jnp.asarray([0.9, 0.8]), 0.5, max_out=5)
        assert idx.shape == (5,) and valid.shape == (5,)
        assert int(np.asarray(valid).sum()) == 1  # duplicate suppressed


class TestBoxCodec:
    def test_roundtrip(self):
        rs = np.random.RandomState(1)
        anchors = np.abs(rs.rand(10, 2)) * 20
        anchors = np.concatenate([anchors, anchors + rs.rand(10, 2) * 30
                                  + 5], 1).astype(np.float32)
        boxes = anchors + rs.randn(10, 4).astype(np.float32)
        deltas = encode_boxes(jnp.asarray(anchors), jnp.asarray(boxes))
        back = decode_boxes(jnp.asarray(anchors), deltas)
        np.testing.assert_allclose(np.asarray(back), boxes, rtol=1e-4,
                                   atol=1e-4)

    def test_anchor_grid(self):
        a = generate_anchors(4, 4, 8, [32.0], (1.0,))
        assert a.shape == (16, 4)
        # centered on (stride/2 + i*stride)
        np.testing.assert_allclose(a[0], [-12, -12, 20, 20])


class TestMaskRCNNEndToEnd:
    def test_tiny_inference_shapes_and_masks(self):
        from bigdl_tpu.models.maskrcnn import MaskRCNN, MaskRCNNConfig

        cfg = MaskRCNNConfig.tiny()
        model = MaskRCNN(cfg, seed=0)
        imgs = np.random.RandomState(0).rand(
            2, cfg.image_size, cfg.image_size, 3).astype(np.float32)
        out = model(imgs)
        D = cfg.detections_per_img
        assert out["boxes"].shape == (2, D, 4)
        assert out["scores"].shape == (2, D)
        assert out["labels"].shape == (2, D)
        assert out["masks"].shape == (2, D, cfg.mask_size, cfg.mask_size)
        assert (out["labels"] >= 0).all() \
            and (out["labels"] < cfg.num_classes).all()
        assert np.isfinite(out["masks"]).all()
        assert (out["masks"] >= 0).all() and (out["masks"] <= 1).all()
        # boxes inside the image
        v = out["scores"] > 0
        if v.any():
            bx = out["boxes"][v]
            assert (bx >= 0).all() and (bx <= cfg.image_size).all()


class TestRoiAlignModule:
    def test_module_wrapper_table_input(self):
        from bigdl_tpu.nn.layers.detection import RoiAlign
        feats = np.ones((1, 4, 4, 2), np.float32)
        boxes = np.array([[0, 0, 4, 4]], np.float32)
        out = RoiAlign(output_size=2).forward(
            [jnp.asarray(feats), jnp.asarray(boxes),
             np.zeros(1, np.int64)])
        assert np.asarray(out).shape == (1, 2, 2, 2)
        np.testing.assert_allclose(np.asarray(out), 1.0, rtol=1e-6)

"""Orca tests (ref pattern: orca tests run local Ray / local[4] Spark,
SURVEY.md §4). BASELINE config 4 = Estimator BERT-base fine-tune (tiny
config here, as the reference's tests use)."""

import numpy as np
import pytest

from bigdl_tpu.orca import XShards, init_orca_context, stop_orca_context
from bigdl_tpu.orca.learn import Estimator


@pytest.fixture(autouse=True)
def orca_ctx():
    ctx = init_orca_context(cluster_mode="local-cpu", some_spark_arg=1)
    yield ctx
    stop_orca_context()


class TestXShards:
    def test_partition_and_collect(self):
        data = {"x": np.arange(20).reshape(10, 2),
                "y": np.arange(10)}
        shards = XShards.partition(data, num_shards=3)
        assert shards.num_partitions() == 3
        merged = shards.merged()
        np.testing.assert_array_equal(merged["x"], data["x"])

    def test_transform_and_repartition(self):
        shards = XShards.partition(np.arange(12), num_shards=4)
        doubled = shards.transform_shard(lambda a: a * 2)
        np.testing.assert_array_equal(doubled.merged(), np.arange(12) * 2)
        re = doubled.repartition(2)
        assert re.num_partitions() == 2

    def test_read_csv(self, tmp_path):
        import pandas as pd
        from bigdl_tpu.orca.data import read_csv

        df = pd.DataFrame({"a": range(10), "b": range(10)})
        p = tmp_path / "data.csv"
        df.to_csv(p, index=False)
        shards = read_csv(str(p), num_shards=2)
        assert shards.num_partitions() == 2
        assert sum(len(s) for s in shards.collect()) == 10


class TestBigDLEstimator:
    def test_fit_evaluate_predict(self):
        import bigdl_tpu.keras as K
        from bigdl_tpu.optim.optim_method import Adam

        rs = np.random.RandomState(0)
        x = rs.rand(128, 6).astype(np.float32)
        w = rs.randn(6, 2).astype(np.float32)
        y = (x @ w).argmax(1).astype(np.int32)

        model = K.Sequential()
        model.add(K.Dense(16, activation="relu", input_shape=(6,)))
        model.add(K.Dense(2, activation="softmax"))
        est = Estimator.from_bigdl(
            model=model, loss="sparse_categorical_crossentropy",
            optimizer=Adam(learning_rate=0.02), metrics=["accuracy"])
        shards = XShards.partition({"x": x, "y": y}, num_shards=4)
        est.fit(shards, epochs=25, batch_size=32)
        res = est.evaluate(shards)
        assert res[0].result > 0.9, res[0].result
        pred = est.predict(shards)
        assert pred.shape == (128, 2)


class TestTorchEstimator:
    def test_torch_regression_shards(self):
        torch = pytest.importorskip("torch")

        def model_creator(config):
            torch.manual_seed(0)
            return torch.nn.Sequential(
                torch.nn.Linear(4, 16), torch.nn.ReLU(),
                torch.nn.Linear(16, 1))

        def optim_creator(model, config):
            return torch.optim.Adam(model.parameters(),
                                    lr=config.get("lr", 1e-2))

        est = Estimator.from_torch(
            model_creator=model_creator, optimizer_creator=optim_creator,
            loss_creator=lambda cfg: torch.nn.MSELoss(),
            config={"lr": 5e-3}, backend="spark")

        rs = np.random.RandomState(1)
        x = rs.rand(200, 4).astype(np.float32)
        y = (x.sum(1, keepdims=True) * 1.5).astype(np.float32)
        shards = XShards.partition({"x": x, "y": y}, num_shards=4)
        est.fit(shards, epochs=30, batch_size=32)
        res = est.evaluate((x, y))
        assert res["MSE"] < 0.05, res

    def test_bert_tiny_finetune(self):
        """BASELINE config 4: BERT fine-tune through the Orca torch path
        (tiny random-init config; no network)."""
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        cfg = transformers.BertConfig(
            vocab_size=100, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=2, intermediate_size=64,
            max_position_embeddings=64, num_labels=2)

        class BertClassifier(torch.nn.Module):
            def __init__(self):
                super().__init__()
                self.bert = transformers.BertForSequenceClassification(cfg)

            def forward(self, ids):
                return self.bert(input_ids=ids).logits

        est = Estimator.from_torch(
            model_creator=lambda c: BertClassifier(),
            optimizer_creator=lambda m, c: torch.optim.Adam(
                m.parameters(), lr=5e-4),
            loss_creator=lambda c: torch.nn.CrossEntropyLoss())

        rs = np.random.RandomState(2)
        # learnable rule: label = first token > 50
        x = rs.randint(1, 100, (96, 12)).astype(np.int64)
        y = (x[:, 0] > 50).astype(np.int64)
        shards = XShards.partition({"x": x, "y": y}, num_shards=2)
        est.fit(shards, epochs=6, batch_size=16)
        res = est.evaluate((x, y))
        assert res["Accuracy"] > 0.8, res


class TestAutoML:
    def test_auto_estimator_random_search(self):
        from bigdl_tpu.chronos.forecaster import LSTMForecaster
        from bigdl_tpu.orca.automl import AutoEstimator, hp

        rs = np.random.RandomState(3)
        t = np.arange(200)
        series = np.sin(t * 0.3).astype(np.float32)
        x = np.stack([series[i:i + 12] for i in range(180)])[..., None]
        y = np.stack([series[i + 12:i + 13] for i in range(180)])[..., None]

        def builder(config):
            return LSTMForecaster(past_seq_len=12, input_feature_num=1,
                                  output_feature_num=1,
                                  hidden_dim=config["hidden_dim"],
                                  lr=config["lr"])

        auto = AutoEstimator(builder, metric="mse", mode="min")
        auto.fit((x, y), search_space={
            "hidden_dim": hp.grid_search([8, 16]),
            "lr": hp.loguniform(1e-3, 1e-2),
        }, epochs=4, batch_size=32)
        assert auto.get_best_config()["hidden_dim"] in (8, 16)
        assert auto.best_score < 0.05
        assert len(auto.trials) == 2

    def test_autots_pipeline(self):
        import pandas as pd
        from bigdl_tpu.chronos.autots import AutoTSEstimator
        from bigdl_tpu.chronos.data import TSDataset
        from bigdl_tpu.orca.automl import hp

        n = 260
        df = pd.DataFrame({
            "dt": pd.date_range("2025-01-01", periods=n, freq="h"),
            "value": np.sin(np.arange(n) * 0.25)})
        ts = TSDataset.from_pandas(df, "dt", "value")
        auto = AutoTSEstimator(
            model="lstm", past_seq_len=hp.choice([12, 16]),
            future_seq_len=1,
            search_space={"hidden_dim": hp.choice([16, 32]),
                          "lr": hp.choice([5e-3, 1e-2])})
        pipe = auto.fit(ts, n_sampling=2, epochs=8)
        mse = pipe.evaluate(ts, metrics=["mse"])[0]
        assert mse < 0.1, mse
        pred = pipe.predict(ts)
        assert pred.shape[1:] == (1, 1)


class TestTF2Estimator:
    def test_from_keras_tf2_trains_and_evaluates(self):
        """Hosted tf.keras training via creator functions (ref:
        P:orca/learn/tf2 Estimator) — loss must fall, accuracy rise."""
        tf = pytest.importorskip("tensorflow")
        from bigdl_tpu.orca.learn.estimator import Estimator

        def model_creator(config):
            tf.keras.utils.set_random_seed(0)
            m = tf.keras.Sequential([
                tf.keras.layers.Dense(32, activation="relu",
                                      input_shape=(10,)),
                tf.keras.layers.Dense(3, activation="softmax"),
            ])
            m.compile(optimizer=tf.keras.optimizers.Adam(config["lr"]),
                      loss=tf.keras.losses.SparseCategoricalCrossentropy())
            return m

        rs = np.random.RandomState(0)
        x = rs.randn(300, 10).astype(np.float32)
        w = rs.randn(10, 3)
        y = (x @ w).argmax(1).astype(np.int64)

        from bigdl_tpu.orca.data import XShards
        shards = XShards.partition({"x": x, "y": y}, num_shards=4)

        est = Estimator.from_keras(model_creator=model_creator,
                                   config={"lr": 5e-3}, backend="tf2")
        stats = est.fit(shards, epochs=8, batch_size=32)
        assert stats[-1] < stats[0]
        metrics = est.evaluate({"x": x, "y": y})
        assert metrics["Accuracy"] > 0.9, metrics


def _square(v):
    return v * v


class TestRayPool:
    """RayContext — the RayOnSpark worker-pool role on stdlib spawn
    processes (SURVEY §2.7 row 49; VERDICT r3 missing #6)."""

    def test_remote_map_and_errors(self):
        from bigdl_tpu.orca import RayContext, RemoteError

        with RayContext(num_workers=2) as ctx:
            ref = ctx.remote(_square)(7)
            assert ctx.get(ref, timeout=60) == 49
            assert ctx.map(_square, [1, 2, 3], timeout=60) == [1, 4, 9]
            # closures travel via cloudpickle like Ray remotes
            k = 10
            assert ctx.get(ctx.remote(lambda v: v + k)(5), timeout=60) == 15
            with pytest.raises(RemoteError, match="ValueError"):
                def boom(_):
                    raise ValueError("nope")
                ctx.get(ctx.remote(boom)(1), timeout=60)

    def test_parallel_automl_trials(self):
        from bigdl_tpu.orca import RayContext
        from bigdl_tpu.orca.automl import hp
        from bigdl_tpu.orca.automl.auto_estimator import AutoEstimator

        rs = np.random.RandomState(0)
        x = rs.rand(128, 4).astype(np.float32)
        y = (x @ np.array([1.0, -2.0, 0.5, 3.0], np.float32))[:, None]

        class Ridge:
            def __init__(self, config):
                self.lam = config["lam"]
                self.w = None

            def fit(self, data, epochs=1, batch_size=32):
                xx, yy = data
                a = xx.T @ xx + self.lam * np.eye(xx.shape[1])
                self.w = np.linalg.solve(a, xx.T @ yy)

            def evaluate(self, data, metrics=("mse",)):
                xx, yy = data
                return [float(np.mean((xx @ self.w - yy) ** 2))]

        est = AutoEstimator(lambda cfg: Ridge(cfg), metric="mse",
                            mode="min")
        with RayContext(num_workers=2) as ctx:
            est.fit((x, y), search_space={"lam": hp.grid_search(
                [10.0, 1.0, 1e-4])}, ray_ctx=ctx)
        assert est.get_best_config()["lam"] == 1e-4
        assert est.get_best_model() is not None
        assert len(est.trials) == 3

    def test_asha_scheduler_spends_fewer_epochs(self):
        from bigdl_tpu.orca.automl import hp
        from bigdl_tpu.orca.automl.auto_estimator import AutoEstimator

        spent = []

        class Slow:
            def __init__(self, config):
                self.q = config["q"]
                self.epochs = 0

            def fit(self, data, epochs=1, batch_size=32):
                self.epochs += epochs
                spent.append(epochs)

            def evaluate(self, data, metrics=("mse",)):
                # score improves with epochs; quality gap dominates
                return [self.q + 1.0 / (1 + self.epochs)]

        est = AutoEstimator(lambda cfg: Slow(cfg), metric="mse",
                            mode="min")
        est.fit(None, search_space={"q": hp.choice(
            [3.0, 2.0, 1.0, 0.0])}, epochs=8, scheduler="asha",
            grace_epochs=1, reduction_factor=2)
        assert est.get_best_config()["q"] == 0.0
        total = sum(spent)
        assert total < 4 * 8, total    # strictly below exhaustive budget

"""Test configuration: run everything on a virtual 8-device CPU mesh.

This is the TPU rebuild's analog of the reference's ``local[N]`` Spark test
pattern (SURVEY.md §4 "Distributed tests without a cluster"): XLA's host
platform is forced to expose 8 CPU devices, so mesh/pjit/collective logic is
exercised faithfully without TPU hardware.

Note: this image's sitecustomize registers an ``axon`` TPU plugin and pins
``jax_platforms`` before we run, so the env-var route (JAX_PLATFORMS=cpu) is
ineffective — ``jax.config.update`` after import is the override that works.
XLA_FLAGS must still be set before the first backend initialisation.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: excluded from the tier-1 gate (-m 'not slow')")
    config.addinivalue_line(
        "markers",
        "chaos: seeded fault-injection chaos runs (always also slow: "
        "tier-1 filters on 'not slow')")
    config.addinivalue_line(
        "markers",
        "perf: performance microbenchmarks (latency/throughput "
        "assertions are advisory on shared CI hosts; select with "
        "-m perf)")
    config.addinivalue_line(
        "markers",
        "kvcache: prefix-aware KV-cache subsystem tests (pool/radix "
        "units + engine parity; select with -m kvcache)")
    config.addinivalue_line(
        "markers",
        "kvtier: tiered KV-cache tests (host arena / migration / "
        "handoff units + spill-reload parity; select with -m kvtier)")
    config.addinivalue_line(
        "markers",
        "failover: request-level failover / hedged dispatch / engine "
        "watchdog tests (router journal+resume parity; select with "
        "-m failover)")
    config.addinivalue_line(
        "markers",
        "kernels: Pallas/Mosaic kernel family tests (paged decode + "
        "ragged prefill interpret-mode parity vs the XLA references; "
        "select with -m kernels)")
    config.addinivalue_line(
        "markers",
        "elastic: elastic multi-host training tests (supervisor state "
        "machine, peer heartbeats, collective-hang watchdog, snapshot "
        "ring, kill-and-recover; select with -m elastic)")
    config.addinivalue_line(
        "markers",
        "analysis: static-analysis suite tests (AST passes, baseline "
        "round-trip, lockwatch witness, repo gate; select with "
        "-m analysis)")
    config.addinivalue_line(
        "markers",
        "slo: fleet telemetry plane tests (quantile sketches, metric "
        "federation, per-request SLO accounting; select with -m slo)")
    config.addinivalue_line(
        "markers",
        "mixed: unified mixed prefill+decode dispatch tests (chunked "
        "admission parity, ledger rollback, compile grid; select with "
        "-m mixed)")
    config.addinivalue_line(
        "markers",
        "fleet: elastic serving fleet tests (autoscaler, graceful "
        "drain with KV migration, provider lifecycle; select with "
        "-m fleet)")
    config.addinivalue_line(
        "markers",
        "priority: SLO-class priority scheduling / lossless preemption "
        "tests (class-ordered admission, preempt-resume parity; select "
        "with -m priority)")
    config.addinivalue_line(
        "markers",
        "timeseries: time-series plane tests (windowed store, alert "
        "engine, fleet timelines; select with -m timeseries)")
    config.addinivalue_line(
        "markers",
        "spec: self-speculative decoding tests (greedy bit-parity "
        "matrix, adaptive-k, compile grid; select with -m spec)")
    config.addinivalue_line(
        "markers",
        "api: OpenAI-compatible gateway tests (translation, SSE "
        "framing, worker/router parity; select with -m api)")


@pytest.fixture(scope="session")
def devices():
    devs = jax.devices()
    assert len(devs) == 8, f"expected 8 virtual devices, got {len(devs)}"
    return devs


@pytest.fixture()
def rng():
    return np.random.RandomState(42)

"""Multi-host reality check (VERDICT r1 weak #7): two real OS processes
join via ``jax.distributed.initialize`` through ``Engine.init`` and run a
DistriOptimizer training step whose batches go through
``make_array_from_process_local_data`` — the analog of the reference's
``local[N]``-Spark-with-real-BlockManager distributed specs (SURVEY.md
§4), but across actual process boundaries.

Each subprocess exposes 4 virtual CPU devices → an 8-device global mesh,
2 processes × 4 local. Skipped gracefully if the jax build cannot do
loopback distributed init.
"""

import os
import socket
import subprocess
import sys
import textwrap

import pytest

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=4"
    import jax
    jax.config.update("jax_platforms", "cpu")
    import numpy as np

    port, pid = sys.argv[1], int(sys.argv[2])

    from bigdl_tpu.utils.engine import Engine
    Engine.init(coordinator_address=f"127.0.0.1:{port}",
                num_processes=2, process_id=pid)
    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 8, len(jax.devices())

    import bigdl_tpu.nn as nn
    from bigdl_tpu.nn.module import set_seed
    from bigdl_tpu.optim.optimizer import DistriOptimizer
    from bigdl_tpu.optim.optim_method import SGD
    from bigdl_tpu.optim.trigger import Trigger

    set_seed(0)   # identical init on both processes (ModelBroadcast role)
    model = nn.Sequential().add(nn.Linear(10, 16)).add(nn.ReLU())\\
        .add(nn.Linear(16, 2)).add(nn.LogSoftMax())

    # per-process HALF of the global batch (64 rows each, global 128):
    # rows are globally deterministic, sliced by process id
    rs = np.random.RandomState(0)
    x_all = rs.rand(128, 10).astype(np.float32)
    y_all = ((x_all.sum(1) > 5).astype(np.int32) + 1)
    lo, hi = pid * 64, (pid + 1) * 64
    opt = DistriOptimizer(model, (x_all[lo:hi], y_all[lo:hi]),
                          nn.ClassNLLCriterion(), batch_size=128,
                          end_trigger=Trigger.max_epoch(3))
    opt.set_optim_method(SGD(learning_rate=0.5))
    opt.optimize()
    final_w = np.asarray(
        jax.tree_util.tree_leaves(model.parameters_dict())[0])
    # all processes must agree on the trained weights bit-for-bit
    print("WSUM", float(np.abs(final_w).sum()))
""")


def test_two_process_distri_training(tmp_path):
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]

    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo_root + os.pathsep + env.get("PYTHONPATH", "")
    procs = [subprocess.Popen(
        [sys.executable, "-c", _WORKER, str(port), str(pid)],
        stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        env=env, cwd=repo_root) for pid in range(2)]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=240)
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            pytest.fail("multi-host worker timed out")
        outs.append((p.returncode, out, err))

    for rc, out, err in outs:
        if rc != 0 and ("DISTRIBUTED" in err.upper()
                        or "coordinator" in err.lower()
                        or "UNAVAILABLE" in err
                        or "Multiprocess computations" in err):
            # "Multiprocess computations aren't implemented on the CPU
            # backend": this jax build coordinates loopback processes
            # fine but cannot COMPUTE across them — same category as a
            # missing distributed service
            pytest.skip(f"loopback jax.distributed unsupported: {err[-200:]}")
        assert rc == 0, f"worker failed:\n{err[-2000:]}"

    wsums = [line.split()[1] for rc, out, _ in outs
             for line in out.splitlines() if line.startswith("WSUM")]
    assert len(wsums) == 2
    assert wsums[0] == wsums[1], f"replicas diverged: {wsums}"


@pytest.mark.slow
@pytest.mark.chaos
@pytest.mark.elastic
def test_two_process_kill_and_recover():
    """ISSUE 10 acceptance: a 2-process training job loses one process
    mid-epoch; the elastic supervisor restarts the worker set; the job
    finishes with final weights bit-identical to the clean run at the
    same world size. Real OS processes, real heartbeats, a real
    SIGKILL-grade death (``os._exit``) — the full chaos pass from
    tools/chaos_check.py, skipped gracefully where this jax build has
    no loopback distributed support at all."""
    import sys

    repo_root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    sys.path.insert(0, repo_root)
    from tools.chaos_check import ElasticUnsupported, run_elastic_chaos

    try:
        out = run_elastic_chaos(seed=0, smoke=True)
    except ElasticUnsupported as e:
        pytest.skip(str(e))
    assert out["match"], out
    assert out["kill"]["restarts"] >= 1
    assert out["clean"]["restarts"] == 0

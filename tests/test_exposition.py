"""ISSUE 16 satellite: Prometheus text-exposition compliance. A strict
pure-python parser (written against the text-format 0.0.4 grammar, no
external client library) is run over the live ``/metrics`` surfaces —
LLM worker, plain router, and the federation router's merged fleet view
— and over a direct registry render. Every line must parse, ``# HELP``
/ ``# TYPE`` must precede their family's samples and appear at most
once, sample names must belong to a declared family (histogram/summary
suffix rules), label names must be legal and label sets consistent
within a sample name, histogram bucket series must carry ``+Inf``, and
no duplicate (name, labelset) sample may appear."""

import http.client
import math
import re

import numpy as np
import pytest

from bigdl_tpu import observability as obs

METRIC_NAME_RE = re.compile(r"^[a-zA-Z_:][a-zA-Z0-9_:]*$")
LABEL_NAME_RE = re.compile(r"^[a-zA-Z_][a-zA-Z0-9_]*$")
EXPOSITION_TYPES = {"counter", "gauge", "histogram", "summary",
                    "untyped"}


def _parse_labels(body: str, where: str):
    """``k="v",k2="v2"`` → list of (name, value); strict on quoting and
    the \\\\ \\" \\n escape set."""
    labels = []
    i, n = 0, len(body)
    while i < n:
        j = i
        while j < n and body[j] not in "=":
            j += 1
        assert j < n, f"{where}: label without '=' in {body!r}"
        name = body[i:j]
        assert LABEL_NAME_RE.match(name), \
            f"{where}: bad label name {name!r}"
        assert j + 1 < n and body[j + 1] == '"', \
            f"{where}: unquoted label value for {name!r}"
        k = j + 2
        val = []
        while k < n and body[k] != '"':
            if body[k] == "\\":
                assert k + 1 < n and body[k + 1] in ('\\', '"', 'n'), \
                    f"{where}: bad escape in label {name!r}"
                val.append({"\\": "\\", '"': '"',
                            "n": "\n"}[body[k + 1]])
                k += 2
            else:
                val.append(body[k])
                k += 1
        assert k < n, f"{where}: unterminated label value for {name!r}"
        labels.append((name, "".join(val)))
        k += 1
        if k < n:
            assert body[k] == ",", \
                f"{where}: expected ',' between labels, got {body[k]!r}"
            k += 1
            assert k < n, f"{where}: trailing ',' in label set"
        i = k
    return labels


def _parse_value(tok: str, where: str) -> float:
    assert re.match(r"^[+-]?(\d|\.\d|Inf|NaN)", tok), \
        f"{where}: unparseable value {tok!r}"
    try:
        return float(tok)
    except ValueError:
        raise AssertionError(f"{where}: unparseable value {tok!r}")


def parse_exposition(text: str) -> dict:
    """Strict parse of one exposition document. Returns
    ``{family: {"help", "type", "samples": [(name, labels, value)]}}``
    and raises AssertionError (with the line) on any grammar or
    ordering violation."""
    assert text.endswith("\n"), "exposition must end with a newline"
    families = {}
    seen_keys = set()

    def family_of(name: str, where: str) -> dict:
        fam = families.get(name)
        if fam is not None:
            return fam
        for suffix in ("_bucket", "_sum", "_count"):
            if name.endswith(suffix):
                base = families.get(name[: -len(suffix)])
                if base is not None and \
                        base["type"] in ("histogram", "summary"):
                    if suffix == "_bucket":
                        assert base["type"] == "histogram", \
                            f"{where}: _bucket on a {base['type']}"
                    return base
        raise AssertionError(
            f"{where}: sample {name!r} has no declared family "
            "(HELP/TYPE must precede samples)")

    for lineno, line in enumerate(text.split("\n")[:-1], 1):
        where = f"line {lineno}"
        assert line == line.strip("\r"), f"{where}: CR in exposition"
        if not line:
            continue
        if line.startswith("#"):
            parts = line.split(None, 3)
            if len(parts) >= 2 and parts[1] in ("HELP", "TYPE"):
                assert len(parts) >= 3, f"{where}: bare # {parts[1]}"
                name = parts[2]
                assert METRIC_NAME_RE.match(name), \
                    f"{where}: bad metric name {name!r}"
                fam = families.setdefault(
                    name, {"help": None, "type": None, "samples": []})
                assert not fam["samples"], \
                    f"{where}: # {parts[1]} {name} after its samples"
                if parts[1] == "HELP":
                    assert fam["help"] is None, \
                        f"{where}: second HELP for {name}"
                    fam["help"] = parts[3] if len(parts) > 3 else ""
                else:
                    assert fam["type"] is None, \
                        f"{where}: second TYPE for {name}"
                    assert len(parts) == 4 and \
                        parts[3] in EXPOSITION_TYPES, \
                        f"{where}: bad TYPE line {line!r}"
                    fam["type"] = parts[3]
            continue                      # other comments are legal
        # sample line: name[{labels}] value [timestamp]
        m = re.match(r"^([a-zA-Z_:][a-zA-Z0-9_:]*)", line)
        assert m, f"{where}: unparseable sample {line!r}"
        name = m.group(1)
        rest = line[m.end():]
        labels = []
        if rest.startswith("{"):
            depth_end = None
            i, in_q, esc = 1, False, False
            while i < len(rest):
                c = rest[i]
                if esc:
                    esc = False
                elif c == "\\":
                    esc = True
                elif c == '"':
                    in_q = not in_q
                elif c == "}" and not in_q:
                    depth_end = i
                    break
                i += 1
            assert depth_end is not None, f"{where}: unclosed label set"
            labels = _parse_labels(rest[1:depth_end], where)
            rest = rest[depth_end + 1:]
        toks = rest.split()
        assert len(toks) in (1, 2), f"{where}: bad sample tail {rest!r}"
        value = _parse_value(toks[0], where)
        if len(toks) == 2:
            assert re.match(r"^-?\d+$", toks[1]), \
                f"{where}: bad timestamp {toks[1]!r}"
        fam = family_of(name, where)
        assert fam["help"] is not None and fam["type"] is not None, \
            f"{where}: sample {name!r} before HELP+TYPE"
        key = (name, tuple(sorted(labels)))
        assert key not in seen_keys, f"{where}: duplicate sample {key}"
        seen_keys.add(key)
        fam["samples"].append((name, labels, value))
    return families


def check_compliance(text: str):
    """The full satellite contract over one document."""
    families = parse_exposition(text)
    assert families, "empty exposition"
    # label-name sets consistent within each sample name
    keysets = {}
    for fam in families.values():
        for name, labels, _v in fam["samples"]:
            ks = frozenset(k for k, _ in labels)
            prev = keysets.setdefault(name, ks)
            assert prev == ks, \
                f"inconsistent label set for {name}: " \
                f"{sorted(prev)} vs {sorted(ks)}"
    # histogram invariants: per bucket group, cumulative counts are
    # non-decreasing and an le="+Inf" bucket exists
    for base, fam in families.items():
        if fam["type"] != "histogram":
            continue
        groups = {}
        for name, labels, v in fam["samples"]:
            if name != base + "_bucket":
                continue
            other = tuple(sorted((k, val) for k, val in labels
                                 if k != "le"))
            le = dict(labels)["le"]
            groups.setdefault(other, []).append((le, v))
        for other, buckets in groups.items():
            les = [b for b, _ in buckets]
            assert "+Inf" in les, f"{base}{dict(other)}: no +Inf bucket"
            ordered = sorted(
                (math.inf if b == "+Inf" else float(b), v)
                for b, v in buckets)
            counts = [v for _b, v in ordered]
            assert counts == sorted(counts), \
                f"{base}{dict(other)}: bucket counts not cumulative"
    return families


def _get(addr, path):
    conn = http.client.HTTPConnection(*addr, timeout=120)
    try:
        conn.request("GET", path)
        r = conn.getresponse()
        return r.status, r.read().decode()
    finally:
        conn.close()


def _post_generate(addr, prompt, tokens=3):
    import json
    conn = http.client.HTTPConnection(*addr, timeout=300)
    try:
        conn.request("POST", "/worker_generate",
                     json.dumps({"prompt_ids": [int(t) for t in prompt],
                                 "max_new_tokens": tokens}),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        body = json.loads(r.read().decode())
        assert r.status == 200, body
        return body
    finally:
        conn.close()


class TestParserRejectsMalformed:
    """The parser itself must have teeth, or the compliance pass is
    vacuous."""

    def test_accepts_minimal_valid(self):
        doc = ('# HELP x_total things\n# TYPE x_total counter\n'
               'x_total{a="1"} 3\n')
        fams = parse_exposition(doc)
        assert fams["x_total"]["samples"] == \
            [("x_total", [("a", "1")], 3.0)]

    @pytest.mark.parametrize("doc", [
        'x_total 1\n',                                    # no HELP/TYPE
        '# TYPE x_total counter\nx_total 1\n',            # no HELP
        '# HELP x_total h\n# TYPE x_total banana\nx 1\n',  # bad type
        '# HELP x h\n# TYPE x gauge\nx{1bad="v"} 1\n',    # label name
        '# HELP x h\n# TYPE x gauge\nx{a=unquoted} 1\n',  # quoting
        '# HELP x h\n# TYPE x gauge\nx nope\n',           # value
        '# HELP x h\n# TYPE x gauge\nx 1\nx 2\n',         # duplicate
        '# HELP x h\n# TYPE x gauge\nx 1\n# TYPE x gauge\n',  # 2nd TYPE
        '# HELP x h\n# TYPE x gauge\nx{a="v} 1\n',        # unterminated
    ])
    def test_rejects(self, doc):
        with pytest.raises(AssertionError):
            parse_exposition(doc)

    def test_rejects_inconsistent_label_sets(self):
        doc = ('# HELP x h\n# TYPE x gauge\n'
               'x{a="1"} 1\nx{b="2"} 2\n')
        with pytest.raises(AssertionError):
            check_compliance(doc)


class TestRegistryRender:
    def test_direct_render_complies(self):
        was = obs.enabled()
        obs.enable()
        try:
            # make sure every instrument shape is present: counter,
            # gauge, histogram (with labels), sketch summary
            obs.counter("expo_test_total", "c", labelnames=("k",)) \
                .labels(k="a").inc()
            obs.gauge("expo_test_gauge", "g").set(1.5)
            h = obs.histogram("expo_test_seconds", "h",
                              labelnames=("stage",))
            for v in (0.001, 0.1, 5.0):
                h.labels(stage="s").observe(v)
            sk = obs.sketch("expo_test_sketch_seconds", "q")
            for v in (0.01, 0.02, 0.3):
                sk.observe(v)
            fams = check_compliance(obs.render())
            assert fams["expo_test_seconds"]["type"] == "histogram"
            assert fams["expo_test_sketch_seconds"]["type"] == "summary"
        finally:
            if not was:
                obs.disable()


class TestLiveSurfaces:
    @pytest.fixture(scope="class")
    def fleet(self):
        """One federation worker + a plain worker behind a federation
        router — three /metrics surfaces (worker, router fleet view,
        plain decode traffic driving counters/sketches/histograms)."""
        from bigdl_tpu.llm.models.llama import (LlamaConfig,
                                                LlamaForCausalLM)
        from bigdl_tpu.llm.serving import LLMServer
        from bigdl_tpu.llm.worker import LLMRouter, LLMWorker

        was = obs.enabled()
        obs.enable()
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                             max_cache_len=64)
        s1 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                       kvcache=True, slo=True).start()
        w1 = LLMWorker(s1, role="decode", federation=True).start()
        router = LLMRouter([], [w1.address], slo=True, federation=True,
                           start_prober=False).start()
        rs = np.random.RandomState(0)
        for j in range(3):
            _post_generate(router.address,
                           rs.randint(0, 250, 8 + 2 * j)
                           .astype(np.int32))
        yield w1, router
        router.stop()
        w1.stop()
        s1.stop(drain=False)
        if not was:
            obs.disable()

    def test_worker_metrics_comply(self, fleet):
        w1, _router = fleet
        st, text = _get(w1.address, "/metrics")
        assert st == 200
        fams = check_compliance(text)
        assert "bigdl_llm_decode_tokens_total" in fams
        assert fams["bigdl_build_info"]["type"] == "gauge"

    def test_router_fleet_view_complies(self, fleet):
        w1, router = fleet
        router._collector.collect_now()     # deterministic scrape
        st, text = _get(router.address, "/metrics")
        assert st == 200
        fams = check_compliance(text)
        # the merged view carries worker series under instance labels
        assert any("instance" in dict(labels)
                   for fam in fams.values()
                   for _n, labels, _v in fam["samples"])

    def test_plain_router_metrics_comply(self):
        from bigdl_tpu.llm.models.llama import (LlamaConfig,
                                                LlamaForCausalLM)
        from bigdl_tpu.llm.serving import LLMServer
        from bigdl_tpu.llm.worker import LLMRouter, LLMWorker

        was = obs.enabled()
        obs.enable()
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                             max_cache_len=64)
        s = LLMServer(model, max_batch=2, max_seq_len=32).start()
        w = LLMWorker(s, role="decode").start()
        router = LLMRouter([], [w.address], start_prober=False).start()
        try:
            _post_generate(router.address,
                           np.arange(1, 9, dtype=np.int32))
            st, text = _get(router.address, "/metrics")
            assert st == 200
            check_compliance(text)
        finally:
            router.stop()
            w.stop()
            s.stop(drain=False)
            if not was:
                obs.disable()

"""BERT on the nn stack: fine-tune via DistriOptimizer on the 8-device
mesh (BASELINE config 4 on OUR stack, not a host-CPU torch loop) and
golden parity vs HF torch BERT (SURVEY.md §4 torch-parity pattern)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.models.bert import (
    BertConfig, BertModel, build_classifier, load_hf_bert_weights)
from bigdl_tpu.nn.module import set_seed


class TestBertModule:
    def test_forward_shapes(self):
        set_seed(0)
        cfg = BertConfig.tiny()
        model = BertModel(cfg)
        ids = jnp.asarray(np.random.RandomState(0)
                          .randint(0, cfg.vocab_size, (3, 12)), jnp.int32)
        model.evaluate()
        out = model.forward(ids)
        assert out["output"].shape == (3, 12, cfg.hidden_size)
        assert out["pooled"].shape == (3, cfg.hidden_size)

    def test_attention_mask_blocks_padding(self):
        """Padded positions must not influence unmasked outputs."""
        set_seed(0)
        cfg = BertConfig.tiny()
        model = BertModel(cfg)
        model.evaluate()
        rs = np.random.RandomState(1)
        ids = rs.randint(1, cfg.vocab_size, (1, 8)).astype(np.int32)
        mask = np.ones((1, 8), np.float32)
        mask[0, 6:] = 0.0
        out1 = model.forward((jnp.asarray(ids), None, jnp.asarray(mask)))
        ids2 = ids.copy()
        ids2[0, 6:] = rs.randint(1, cfg.vocab_size, 2)  # perturb padding
        out2 = model.forward((jnp.asarray(ids2), None, jnp.asarray(mask)))
        np.testing.assert_allclose(
            np.asarray(out1["output"])[:, :6],
            np.asarray(out2["output"])[:, :6], rtol=1e-4, atol=1e-5)

    def test_finetune_converges_on_mesh(self, devices):
        """BERT classification fine-tune through DistriOptimizer on the
        8-device CPU mesh — the round-1 gap: BASELINE config 4 on our
        stack, on the accelerator path."""
        from bigdl_tpu.optim.optimizer import Optimizer
        from bigdl_tpu.optim.optim_method import Adam
        from bigdl_tpu.optim.trigger import Trigger
        from bigdl_tpu.optim.validation import Top1Accuracy

        set_seed(0)
        cfg = BertConfig.tiny()
        model = build_classifier(cfg, num_labels=2)
        rs = np.random.RandomState(0)
        n, t = 256, 12
        ids = rs.randint(2, cfg.vocab_size, (n, t)).astype(np.int32)
        # learnable rule: class 2 iff token 3 appears in the sequence
        has = (ids == 3).any(axis=1)
        labels = has.astype(np.int32) + 1

        opt = Optimizer(model, (ids, labels), nn.ClassNLLCriterion(),
                        batch_size=64,
                        end_trigger=Trigger.max_epoch(12),
                        distributed=True)
        opt.set_optim_method(Adam(learning_rate=3e-3))
        opt.optimize()

        model.evaluate()
        pred = np.asarray(model.forward(jnp.asarray(ids))).argmax(-1) + 1
        acc = (pred == labels).mean()
        assert acc > 0.85, f"fine-tune did not converge: acc={acc}"


class TestBertHFParity:
    def test_matches_hf_bert_numerics(self, tmp_path):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")

        hf_cfg = transformers.BertConfig(
            vocab_size=97, hidden_size=32, num_hidden_layers=2,
            num_attention_heads=4, intermediate_size=64,
            max_position_embeddings=48, type_vocab_size=2,
            hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0)
        torch.manual_seed(0)
        hf = transformers.BertModel(hf_cfg)
        hf.eval()
        path = str(tmp_path / "hf-bert")
        hf.save_pretrained(path, safe_serialization=True)

        cfg = BertConfig(vocab_size=97, hidden_size=32,
                         num_hidden_layers=2, num_attention_heads=4,
                         intermediate_size=64, max_position_embeddings=48,
                         hidden_dropout_prob=0.0)
        set_seed(0)
        ours = BertModel(cfg)
        load_hf_bert_weights(ours, path)
        ours.evaluate()

        rs = np.random.RandomState(0)
        ids = rs.randint(0, 97, (2, 10)).astype(np.int64)
        with torch.no_grad():
            ref = hf(torch.tensor(ids))
        out = ours.forward(jnp.asarray(ids, jnp.int32))
        np.testing.assert_allclose(
            np.asarray(out["output"]),
            ref.last_hidden_state.numpy(), rtol=2e-3, atol=2e-3)
        np.testing.assert_allclose(
            np.asarray(out["pooled"]),
            ref.pooler_output.numpy(), rtol=2e-3, atol=2e-3)

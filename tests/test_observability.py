"""Unified telemetry subsystem (ISSUE 1): metric registry semantics,
Prometheus exposition (rendered AND parsed back), trace spans/Chrome
trace export, the instrumented hot paths (optimizer loop + serving
front-end), and the disabled-mode zero-overhead contract."""

import http.client
import json
import threading
import time

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu.observability.metrics import (
    MetricRegistry, parse_prometheus, render_prometheus)
from bigdl_tpu.observability.tracing import TraceBuffer


@pytest.fixture(autouse=True)
def _obs_clean():
    """Each test sees an enabled switch and an empty trace ring; the
    global registry is NOT cleared (live modules hold instrument refs) —
    tests read deltas or use a private registry."""
    was = obs.enabled()
    obs.enable()
    obs.TRACE.clear()
    yield
    obs.TRACE.clear()
    if was:
        obs.enable()
    else:
        obs.disable()


class TestMetricPrimitives:
    def test_counter_semantics(self):
        r = MetricRegistry()
        c = r.counter("bigdl_test_total", "help text")
        c.inc()
        c.inc(4)
        assert c.value == 5
        with pytest.raises(ValueError):
            c.inc(-1)
        # idempotent redeclaration returns the same instrument
        assert r.counter("bigdl_test_total", "help text") is c
        # conflicting redeclaration raises
        with pytest.raises(ValueError):
            r.gauge("bigdl_test_total")
        with pytest.raises(ValueError):
            r.counter("bigdl_test_total", labelnames=("x",))

    def test_gauge_semantics(self):
        g = MetricRegistry().gauge("bigdl_test_gauge", "g")
        g.set(10)
        g.inc(2.5)
        g.dec()
        assert g.value == 11.5

    def test_histogram_semantics(self):
        r = MetricRegistry()
        h = r.histogram("bigdl_test_seconds", "h", buckets=(0.1, 1, 10))
        for v in (0.05, 0.5, 5.0, 50.0):
            h.observe(v)
        assert h.count == 4
        assert h.sum == pytest.approx(55.55)
        cum, total, count = h._sole().snapshot()
        assert cum == [1, 2, 3, 4]           # cumulative incl. +Inf
        assert h.percentile(0.5) is not None
        # same buckets → same instrument; different buckets → conflict
        assert r.histogram("bigdl_test_seconds", "h",
                           buckets=(0.1, 1, 10)) is h
        with pytest.raises(ValueError):
            r.histogram("bigdl_test_seconds", "h", buckets=(1, 2))

    def test_labels(self):
        r = MetricRegistry()
        c = r.counter("bigdl_req_total", "reqs", labelnames=("code",))
        c.labels(code="200").inc(3)
        c.labels(code="500").inc()
        assert r.sample_value("bigdl_req_total", code="200") == 3
        assert r.sample_value("bigdl_req_total", code="500") == 1
        # same label values memoize to the same child
        assert c.labels(code="200") is c.labels(code="200")
        with pytest.raises(ValueError):
            c.labels(wrong="x")
        with pytest.raises(ValueError):
            c.inc()   # labeled instrument needs .labels()

    def test_thread_safety(self):
        c = MetricRegistry().counter("bigdl_mt_total", "")

        def work():
            for _ in range(10000):
                c.inc()

        ts = [threading.Thread(target=work) for _ in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert c.value == 80000


class TestPrometheusRendering:
    def test_render_and_parse_back(self):
        r = MetricRegistry()
        r.counter("bigdl_a_total", "a counter").inc(7)
        r.gauge("bigdl_b", "a gauge").set(-2.5)
        lab = r.counter("bigdl_c_total", "labeled",
                        labelnames=("op", "ok"))
        lab.labels(op="all_reduce", ok="true").inc(3)
        h = r.histogram("bigdl_lat_seconds", "latency",
                        buckets=(0.01, 0.1, 1))
        h.observe(0.005)
        h.observe(0.5)
        text = render_prometheus(r)
        # structure: HELP/TYPE lines present for each metric
        assert "# HELP bigdl_a_total a counter" in text
        assert "# TYPE bigdl_lat_seconds histogram" in text
        parsed = parse_prometheus(text)
        assert parsed["bigdl_a_total"][()] == 7
        assert parsed["bigdl_b"][()] == -2.5
        key = tuple(sorted((("op", "all_reduce"), ("ok", "true"))))
        assert parsed["bigdl_c_total"][key] == 3
        assert parsed["bigdl_lat_seconds_bucket"][(("le", "0.01"),)] == 1
        assert parsed["bigdl_lat_seconds_bucket"][(("le", "1"),)] == 2
        assert parsed["bigdl_lat_seconds_bucket"][(("le", "+Inf"),)] == 2
        assert parsed["bigdl_lat_seconds_count"][()] == 2
        assert parsed["bigdl_lat_seconds_sum"][()] == \
            pytest.approx(0.505)

    def test_escaping(self):
        r = MetricRegistry()
        c = r.counter("bigdl_esc_total", 'help with "quotes"\nnewline',
                      labelnames=("path",))
        # the r'C:\new' case: an escaped backslash before an 'n' must
        # not be misread as an escaped newline on parse-back
        values = ('a"b\\c', "C:\\new", "line\nbreak", "tail\\", 'x"')
        for value in values:
            c.labels(path=value).inc()
        parsed = parse_prometheus(render_prometheus(r))
        keys = {k[0][1] for k in parsed["bigdl_esc_total"]}
        assert keys == set(values)


class TestTracing:
    def test_span_nesting_and_export(self, tmp_path):
        with obs.span("outer", step=1):
            with obs.span("inner", detail="x"):
                time.sleep(0.002)
        spans = obs.TRACE.spans()
        names = [s["name"] for s in spans]
        assert names == ["inner", "outer"]     # completion order
        inner, outer = spans
        assert inner["args"]["parent"] == "outer"
        assert "parent" not in outer["args"]
        assert outer["dur"] >= inner["dur"] > 1000   # us; slept 2ms
        # chrome trace loads as JSON with the required event fields
        path = str(tmp_path / "trace.json")
        obs.export_chrome_trace(path)
        doc = json.load(open(path))
        assert {"name", "ph", "ts", "dur", "pid", "tid"} <= \
            set(doc["traceEvents"][0])
        assert all(e["ph"] == "X" for e in doc["traceEvents"])

    def test_ring_buffer_bounds(self):
        buf = TraceBuffer(capacity=4)
        for i in range(10):
            buf.append({"name": f"s{i}"})
        assert len(buf) == 4
        assert buf.dropped == 6
        assert [s["name"] for s in buf.spans()] == \
            ["s6", "s7", "s8", "s9"]
        buf.set_capacity(2)
        assert [s["name"] for s in buf.spans()] == ["s8", "s9"]

    def test_zero_capacity_disables_recording(self):
        buf = TraceBuffer(capacity=0)
        buf.append({"name": "x"})
        assert len(buf) == 0 and buf.dropped == 1
        full = TraceBuffer(capacity=2)
        full.append({"name": "a"})
        full.set_capacity(0)
        full.append({"name": "b"})
        assert full.spans() == []

    def test_threads_are_distinct(self):
        def work():
            with obs.span("worker"):
                pass

        t = threading.Thread(target=work)
        t.start()
        t.join()
        with obs.span("main"):
            pass
        tids = {s["tid"] for s in obs.TRACE.spans()}
        assert len(tids) == 2


class TestDisabledMode:
    def test_conf_set_applies_after_import(self):
        """conf.set of the kill switch must work post-import like every
        other config key (the _state module is refreshed on change)."""
        from bigdl_tpu.utils.conf import conf

        c = obs.counter("bigdl_conf_gate_total", "t")
        conf.set("bigdl.observability.enabled", "false")
        try:
            assert not obs.enabled()
            c.inc()
            assert c.value == 0
        finally:
            conf.unset("bigdl.observability.enabled")
        assert obs.enabled()
        c.inc()
        assert c.value == 1

    def test_unrelated_conf_key_keeps_runtime_override(self):
        """conf.set of another observability key must not clobber an
        explicit runtime disable()."""
        from bigdl_tpu.utils.conf import conf

        obs.disable()
        try:
            conf.set("bigdl.observability.trace.capacity",
                     obs.TRACE.capacity)
            assert not obs.enabled()
        finally:
            conf.unset("bigdl.observability.trace.capacity")
            obs.enable()

    def test_zero_entries(self):
        c = obs.counter("bigdl_disabled_total", "t")
        h = obs.histogram("bigdl_disabled_seconds", "t")
        obs.disable()
        try:
            c.inc(100)
            h.observe(1.0)
            with obs.span("off"):
                pass
        finally:
            obs.enable()
        assert c.value == 0
        assert h.count == 0
        assert len(obs.TRACE) == 0

    def test_disabled_training_run_records_nothing(self):
        """The acceptance bound: a disabled-mode training run adds ZERO
        telemetry entries — no spans, no registry samples, and the
        compiled step carries no telemetry outputs (so there are zero
        added host callbacks per step beyond the loop's own loss
        drain)."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        rs = np.random.RandomState(0)
        x = rs.rand(32, 6).astype(np.float32)
        y = (rs.randint(0, 2, 32) + 1).astype(np.int32)
        model = nn.Sequential().add(nn.Linear(6, 2)).add(nn.LogSoftMax())
        opt = LocalOptimizer(model, (x, y), nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_iteration(3))
        before = obs.REGISTRY.sample_value("bigdl_train_steps_total")
        obs.disable()
        try:
            opt.optimize()
        finally:
            obs.enable()
        assert len(obs.TRACE) == 0
        assert obs.REGISTRY.sample_value(
            "bigdl_train_steps_total") == before
        # the disabled-mode compiled step returns an EMPTY telemetry
        # pytree: nothing extra is computed or fetched per step
        assert opt._obs is False and opt._obs_ins is None
        # re-enabling and re-running rebuilds the step with the gauge
        # wired back in (the gate is baked at jit time, per run)
        opt.end_trigger = Trigger.max_iteration(6)
        opt.optimize()
        assert opt._obs is True
        assert obs.REGISTRY.sample_value("bigdl_train_grad_norm") > 0

    def test_runtime_enable_on_live_frontend(self):
        """obs.enable() must start recording on a server built while
        disabled (instruments declare lazily, not at construction)."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.serving.cluster_serving import ClusterServing
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        from bigdl_tpu.serving.inference_model import InferenceModel

        obs.disable()
        im = InferenceModel().load_bigdl(
            model=nn.Sequential().add(nn.Linear(4, 3)).add(nn.SoftMax()))
        job = ClusterServing(im, stream_name="late_enable_stream").start()
        fe = ServingFrontend(stream_name="late_enable_stream").start()
        try:
            before = obs.REGISTRY.sample_value(
                "bigdl_serving_served_total") or 0
            x = [[1.0, 2.0, 3.0, 4.0]]
            code, _ = _HTTP.post(fe.address, "/predict",
                                 {"inputs": {"input": x}})
            assert code == 200
            assert (obs.REGISTRY.sample_value(
                "bigdl_serving_served_total") or 0) == before
            obs.enable()
            code, _ = _HTTP.post(fe.address, "/predict",
                                 {"inputs": {"input": x}})
            assert code == 200
            assert obs.REGISTRY.sample_value(
                "bigdl_serving_served_total") == before + 1
        finally:
            obs.enable()
            fe.stop()
            job.stop()


class TestInstrumentedTraining:
    def test_train_run_produces_spans_and_metrics(self, tmp_path):
        """Acceptance: a short BaseOptimizer run yields a loadable
        Chrome-trace JSON with per-step spans, and the registry holds
        step/loss/grad-norm series."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        rs = np.random.RandomState(1)
        x = rs.rand(64, 8).astype(np.float32)
        y = (rs.randint(0, 3, 64) + 1).astype(np.int32)
        model = nn.Sequential().add(nn.Linear(8, 3)).add(nn.LogSoftMax())
        steps0 = obs.REGISTRY.sample_value("bigdl_train_steps_total") or 0
        opt = LocalOptimizer(model, (x, y), nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_epoch(2))
        opt.optimize()

        assert obs.REGISTRY.sample_value(
            "bigdl_train_steps_total") == steps0 + 8
        assert obs.REGISTRY.sample_value("bigdl_train_loss") is not None
        gn = obs.REGISTRY.sample_value("bigdl_train_grad_norm")
        assert gn is not None and gn > 0
        path = str(tmp_path / "train_trace.json")
        obs.export_chrome_trace(path)
        doc = json.load(open(path))
        step_spans = [e for e in doc["traceEvents"]
                      if e["name"] == "train/step"]
        epoch_spans = [e for e in doc["traceEvents"]
                       if e["name"] == "train/epoch"]
        assert len(step_spans) == 8 and len(epoch_spans) == 2
        assert all(e["args"]["parent"] == "train/epoch"
                   for e in step_spans)
        assert {e["args"]["step"] for e in step_spans} == set(range(1, 9))

    def test_summary_routes_through_registry(self, tmp_path):
        from bigdl_tpu.optim.summary import TrainSummary

        s = TrainSummary(str(tmp_path), "obs_app", flush_every=2)
        s.add_scalar("Loss", 0.5, 1)
        s.add_scalar("Loss", 0.25, 2)
        assert s.read_scalar("Loss") == [(1, 0.5), (2, 0.25)]
        assert obs.REGISTRY.sample_value(
            "bigdl_summary_scalar", app="obs_app", kind="train",
            tag="Loss") == 0.25
        s.close()

    def test_summary_pending_initialized(self, tmp_path):
        from bigdl_tpu.optim.summary import Summary

        s = Summary(str(tmp_path), "app", "train", flush_every=3)
        assert s._pending == 0           # eager init (ISSUE 1 satellite)
        s.add_scalar("t", 1.0, 1)
        assert s._pending == 1
        s.add_scalar("t", 1.0, 2)
        s.add_scalar("t", 1.0, 3)        # hits cadence → flushed
        assert s._pending == 0
        s.close()


class TestCollectiveTelemetry:
    def test_bytes_counted_at_trace_time(self, devices):
        import jax
        import jax.numpy as jnp
        from jax.sharding import PartitionSpec as P

        from bigdl_tpu.parallel import create_mesh
        from bigdl_tpu.parallel.collectives import all_reduce
        from bigdl_tpu.utils.jax_compat import shard_map

        mesh = create_mesh({"data": 8})
        before = obs.REGISTRY.sample_value(
            "bigdl_collective_traced_bytes_total", op="all_reduce") or 0

        def body(x):
            return all_reduce(x, "data")

        f = shard_map(body, mesh=mesh, in_specs=P("data"),
                      out_specs=P("data"))
        x = jnp.arange(64, dtype=jnp.float32)
        jax.jit(f)(x).block_until_ready()
        after = obs.REGISTRY.sample_value(
            "bigdl_collective_traced_bytes_total", op="all_reduce")
        # per-device shard is 8 f32 = 32 bytes at the traced call site
        assert after - before == 32


class _HTTP:
    @staticmethod
    def get(addr, path):
        conn = http.client.HTTPConnection(*addr, timeout=30)
        conn.request("GET", path)
        r = conn.getresponse()
        body = r.read().decode()
        ctype = r.getheader("Content-Type", "")
        conn.close()
        return r.status, body, ctype

    @staticmethod
    def post(addr, path, obj):
        conn = http.client.HTTPConnection(*addr, timeout=120)
        conn.request("POST", path, json.dumps(obj),
                     {"Content-Type": "application/json"})
        r = conn.getresponse()
        body = r.read()
        conn.close()
        return r.status, json.loads(body)


class TestServingMetricsEndpoint:
    def test_prometheus_exposition_on_live_frontend(self):
        """Acceptance: GET /metrics on a running ServingFrontend is valid
        Prometheus text including the request-latency histogram; the
        legacy JSON lives at /metrics.json."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.serving.cluster_serving import ClusterServing
        from bigdl_tpu.serving.http_frontend import ServingFrontend
        from bigdl_tpu.serving.inference_model import InferenceModel

        model = (nn.Sequential().add(nn.Linear(4, 3))
                 .add(nn.SoftMax()))
        im = InferenceModel().load_bigdl(model=model)
        stream = "obs_metrics_stream"
        job = ClusterServing(im, stream_name=stream).start()
        fe = ServingFrontend(stream_name=stream).start()
        try:
            served0 = obs.REGISTRY.sample_value(
                "bigdl_serving_served_total") or 0
            x = np.arange(4, dtype=np.float32)[None]
            for _ in range(3):
                code, out = _HTTP.post(fe.address, "/predict",
                                       {"inputs": {"input": x.tolist()}})
                assert code == 200, out
            code, text, ctype = _HTTP.get(fe.address, "/metrics")
            assert code == 200
            assert ctype.startswith("text/plain")
            assert "version=0.0.4" in ctype
            parsed = parse_prometheus(text)
            # request-latency histogram present, counted, consistent
            assert parsed["bigdl_serving_request_seconds_count"][()] >= 3
            inf_key = (("le", "+Inf"),)
            buckets = {k: v for k, v in
                       parsed["bigdl_serving_request_seconds_bucket"]
                       .items()}
            assert buckets[inf_key] == \
                parsed["bigdl_serving_request_seconds_count"][()]
            assert parsed["bigdl_serving_served_total"][()] == served0 + 3
            assert parsed["bigdl_serving_queue_depth"][()] == 0
            # batch-loop metrics flowed from the ClusterServing side
            assert parsed["bigdl_cluster_serving_records_total"][()] >= 3
            # legacy surface intact on the new path
            code, body, ctype = _HTTP.get(fe.address, "/metrics.json")
            assert code == 200 and json.loads(body)["pending"] == 0
        finally:
            fe.stop()
            job.stop()


class TestTelemetryReportTool:
    def test_scalars_and_trace_summaries(self, tmp_path):
        import sys
        sys.path.insert(0, "tools")
        try:
            from telemetry_report import (summarize_registry,
                                          summarize_scalars,
                                          summarize_trace)
        finally:
            sys.path.pop(0)

        scalars = tmp_path / "scalars.jsonl"
        t0 = 1000.0
        with open(scalars, "w") as f:
            for i in range(5):
                f.write(json.dumps({"tag": "Loss", "value": 1.0 / (i + 1),
                                    "step": i, "wall": t0 + 0.1 * i})
                        + "\n")
        s = summarize_scalars(str(scalars))
        assert s["tags"]["Loss"]["count"] == 5
        assert s["tags"]["Loss"]["last"] == pytest.approx(0.2)
        assert s["step_seconds"]["p50"] == pytest.approx(0.1, rel=1e-6)

        with obs.span("phase/a"):
            time.sleep(0.001)
        with obs.span("phase/a"):
            pass
        tr = summarize_trace(
            {"traceEvents": obs.TRACE.spans()})
        assert tr["spans"]["phase/a"]["count"] == 2

        reg = summarize_registry()
        assert isinstance(reg, dict)

    def test_cli(self, tmp_path, capsys):
        import subprocess
        import sys
        trace = tmp_path / "t.json"
        with obs.span("cli/span"):
            pass
        obs.export_chrome_trace(str(trace))
        out = subprocess.run(
            [sys.executable, "tools/telemetry_report.py", str(trace)],
            capture_output=True, text=True, timeout=120)
        assert out.returncode == 0
        assert "cli/span" in out.stdout

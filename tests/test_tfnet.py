"""TFNet: frozen TF graphs executed as jit-compiled jax (ref: orca
TFNet + S:dllib/nn/ops — golden parity vs TensorFlow's own execution,
the reference's independent-implementation test pattern)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from bigdl_tpu.nn.ops import TFNet  # noqa: E402


def _freeze(model, spec):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    fn = tf.function(lambda x: model(x))
    concrete = fn.get_concrete_function(tf.TensorSpec(spec, tf.float32))
    frozen = convert_variables_to_constants_v2(concrete)
    return frozen.graph.as_graph_def(), concrete


class TestTFNet:
    def test_mlp_matches_tf(self):
        tf.random.set_seed(0)
        model = tf.keras.Sequential([
            tf.keras.layers.Dense(16, activation="relu"),
            tf.keras.layers.Dense(8, activation="tanh"),
            tf.keras.layers.Dense(4),
            tf.keras.layers.Softmax(),
        ])
        model.build((None, 12))
        gd, concrete = _freeze(model, [None, 12])
        x = np.random.RandomState(0).rand(5, 12).astype(np.float32)
        ref = model(x).numpy()
        net = TFNet(gd)
        out = net.predict(x)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_cnn_matches_tf(self):
        tf.random.set_seed(1)
        model = tf.keras.Sequential([
            tf.keras.layers.Conv2D(4, 3, padding="same",
                                   activation="relu"),
            tf.keras.layers.MaxPooling2D(2),
            tf.keras.layers.Conv2D(8, 3, padding="valid"),
            tf.keras.layers.GlobalAveragePooling2D(),
            tf.keras.layers.Dense(3),
        ])
        model.build((None, 12, 12, 2))
        gd, _ = _freeze(model, [None, 12, 12, 2])
        x = np.random.RandomState(1).rand(2, 12, 12, 2)\
            .astype(np.float32)
        ref = model(x).numpy()
        out = TFNet(gd).predict(x)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_batchnorm_inference_matches_tf(self):
        tf.random.set_seed(2)
        model = tf.keras.Sequential([
            tf.keras.layers.Conv2D(4, 3),
            tf.keras.layers.BatchNormalization(),
            tf.keras.layers.ReLU(),
        ])
        model.build((None, 8, 8, 2))
        # shift running stats away from init so the BN math is exercised
        bn = model.layers[1]
        bn.moving_mean.assign(tf.random.normal([4]))
        bn.moving_variance.assign(tf.random.uniform([4], 0.5, 2.0))
        gd, _ = _freeze(model, [None, 8, 8, 2])
        x = np.random.RandomState(2).rand(2, 8, 8, 2).astype(np.float32)
        ref = model(x, training=False).numpy()
        out = TFNet(gd).predict(x)
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)

    def test_unsupported_op_raises_at_load(self):
        gd = tf.compat.v1.GraphDef()
        n = gd.node.add()
        n.name = "x"
        n.op = "Placeholder"
        n2 = gd.node.add()
        n2.name = "fancy"
        n2.op = "SomeExoticOp"
        n2.input.append("x")
        with pytest.raises(NotImplementedError, match="SomeExoticOp"):
            TFNet(gd)

    def test_explicit_outputs_and_multi_output(self):
        tf.random.set_seed(3)
        model = tf.keras.Sequential([
            tf.keras.layers.Dense(6, activation="relu"),
            tf.keras.layers.Dense(2),
        ])
        model.build((None, 4))
        gd, _ = _freeze(model, [None, 4])
        # pick an intermediate node as an extra output
        relu_nodes = [n.name for n in gd.node if n.op == "Relu"]
        final = [n.name for n in gd.node if n.op == "BiasAdd"][-1]
        net = TFNet(gd, outputs=[relu_nodes[0], final])
        x = np.random.RandomState(3).rand(3, 4).astype(np.float32)
        hid, out = net(x)
        assert np.asarray(hid).shape == (3, 6)
        assert np.asarray(out).shape == (3, 2)


def _freeze_and_compare(fn, *xs):
    """Freeze fn to a GraphDef, run through TFNet, compare vs TF."""
    specs = [tf.TensorSpec(x.shape, tf.float32) for x in xs]
    concrete = tf.function(fn).get_concrete_function(*specs)
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    gd = convert_variables_to_constants_v2(concrete).graph.as_graph_def()
    ref = np.asarray(fn(*[tf.constant(x) for x in xs]))
    out = TFNet(gd).predict(*xs)
    np.testing.assert_allclose(np.asarray(out), ref, rtol=1e-4,
                               atol=1e-5)


class TestWidenedOpSet:
    """Round-3 op-set widening (~36 -> ~100 ops, the reference's
    nn/ops + nn/tf op-count ballpark) — golden parity vs TF execution."""

    def _run(self, fn, *xs):
        _freeze_and_compare(fn, *xs)

    def test_elementwise_family(self):
        x = np.random.RandomState(0).rand(3, 5).astype(np.float32) + 0.5

        def f(x):
            y = tf.abs(-x) + tf.math.log1p(x) + tf.sqrt(x)
            y = tf.math.softplus(y) + tf.sin(x) * tf.cos(x)
            y = y + tf.math.erf(x) + tf.math.floordiv(x * 7.0, 2.0)
            return tf.math.squared_difference(y, x) + tf.pow(x, 2.0)

        self._run(f, x)

    def test_compare_select_family(self):
        x = np.random.RandomState(1).randn(4, 4).astype(np.float32)

        def f(x):
            m = tf.greater(x, 0.0)
            y = tf.where(m, x * 2.0, -x)
            return y + tf.cast(tf.logical_and(m, tf.less(x, 1.0)),
                               tf.float32)

        self._run(f, x)

    def test_shape_manipulation_family(self):
        x = np.random.RandomState(2).rand(2, 6, 4).astype(np.float32)

        def f(x):
            a = tf.tile(x[:, :2], [1, 3, 1])
            b = tf.slice(x, [0, 1, 0], [2, 2, 4])
            c = tf.strided_slice(x, [0, 0, 0], [2, 6, 4], [1, 2, 1])
            parts = tf.split(x, 2, axis=2)
            d = tf.stack([parts[0], parts[1]], axis=0)
            return (tf.reduce_sum(a) + tf.reduce_sum(b)
                    + tf.reduce_sum(c) + tf.reduce_prod(
                        tf.reduce_max(d, axis=[2, 3])))

        self._run(f, x)

    def test_matmul_resize_family(self):
        x = np.random.RandomState(3).rand(2, 3, 4).astype(np.float32)

        def f(x):
            y = tf.matmul(x, tf.transpose(x, [0, 2, 1]))   # BatchMatMul
            img = tf.reshape(tf.tile(tf.reduce_mean(y, -1,
                                                    keepdims=True),
                                     [1, 1, 8]), [2, 3, 8, 1])
            up = tf.image.resize(img, [6, 16], method="nearest")
            return tf.reduce_mean(up, axis=[1, 2, 3])

        self._run(f, x)

    def test_gather_range_fill(self):
        x = np.random.RandomState(4).rand(5, 4).astype(np.float32)

        def f(x):
            idx = tf.range(0, 4, 2)
            g = tf.gather(x, idx, axis=1)
            z = tf.fill([5, 2], 0.5)
            return g + z + tf.zeros_like(g) + tf.ones_like(g)

        self._run(f, x)


class TestRound4OpTail:
    """r4 op-set tail (Gather/GatherNd/OneHot/Cumsum/TopK/DepthToSpace/
    SpaceToDepth/L2Loss/...) — same golden-parity harness."""

    def _run(self, fn, *xs):
        _freeze_and_compare(fn, *xs)

    def test_gather_onehot_family(self):
        x = np.random.RandomState(0).rand(5, 4).astype(np.float32)

        def f(x):
            idx = tf.constant([3, 1, 0])
            g = tf.gather(x, idx)
            nd = tf.gather_nd(x, tf.constant([[0, 1], [4, 3]]))
            oh = tf.one_hot(tf.constant([1, 3]), 4, on_value=2.0,
                            off_value=-1.0)
            return (tf.reduce_sum(g) + tf.reduce_sum(nd)
                    + tf.reduce_sum(oh * x[:2]))

        self._run(f, x)

    def test_cumsum_topk_family(self):
        x = np.random.RandomState(1).rand(3, 6).astype(np.float32)

        def f(x):
            c1 = tf.cumsum(x, axis=1)
            c2 = tf.cumsum(x, axis=1, exclusive=True)
            c3 = tf.cumsum(x, axis=1, reverse=True)
            c4 = tf.cumsum(x, axis=1, exclusive=True, reverse=True)
            cp = tf.math.cumprod(x + 1.0, axis=0)
            cp2 = tf.math.cumprod(x + 0.5, axis=1, exclusive=True,
                                  reverse=True)
            vals, _ = tf.math.top_k(x, k=2)
            return (tf.reduce_sum(c1 + c2 + c3 + c4)
                    + tf.reduce_sum(cp) + tf.reduce_sum(cp2)
                    + tf.reduce_sum(vals) + tf.nn.l2_loss(x))

        self._run(f, x)

    def test_depth_space_family(self):
        x = np.random.RandomState(2).rand(2, 4, 4, 8).astype(np.float32)

        def f(x):
            up = tf.nn.depth_to_space(x, 2)
            down = tf.nn.space_to_depth(up, 2)
            return tf.reduce_sum(up) + tf.reduce_sum(down * x)

        self._run(f, x)

    def test_gather_nd_const_table_traced_indices(self):
        """Const tables stay host numpy in the executor env; GatherNd
        must promote before fancy-indexing with traced indices (review
        r4: raw numpy indexing concretized the tracer)."""
        table = tf.constant(np.arange(12, dtype=np.float32).reshape(4, 3))

        def f(x):
            return tf.reduce_sum(tf.gather_nd(table, tf.cast(x, tf.int32)))

        _freeze_and_compare(f, np.array([[0, 1], [3, 2]], np.float32))

    def test_cumsum_exclusive_inf_safe(self):
        """Exclusive cumsum is shift-based: inf inputs must not produce
        inf - inf = NaN (review r4)."""
        from bigdl_tpu.nn.ops.tfnet import _cumsum
        import jax.numpy as jnp
        out = np.asarray(_cumsum(jnp.asarray([np.inf, 1.0, 2.0]), 0,
                                 True, False))
        assert out[0] == 0.0 and np.isinf(out[1:]).all()

"""TFNet: frozen TF graphs executed as jit-compiled jax (ref: orca
TFNet + S:dllib/nn/ops — golden parity vs TensorFlow's own execution,
the reference's independent-implementation test pattern)."""

import numpy as np
import pytest

tf = pytest.importorskip("tensorflow")

from bigdl_tpu.nn.ops import TFNet  # noqa: E402


def _freeze(model, spec):
    from tensorflow.python.framework.convert_to_constants import (
        convert_variables_to_constants_v2)
    fn = tf.function(lambda x: model(x))
    concrete = fn.get_concrete_function(tf.TensorSpec(spec, tf.float32))
    frozen = convert_variables_to_constants_v2(concrete)
    return frozen.graph.as_graph_def(), concrete


class TestTFNet:
    def test_mlp_matches_tf(self):
        tf.random.set_seed(0)
        model = tf.keras.Sequential([
            tf.keras.layers.Dense(16, activation="relu"),
            tf.keras.layers.Dense(8, activation="tanh"),
            tf.keras.layers.Dense(4),
            tf.keras.layers.Softmax(),
        ])
        model.build((None, 12))
        gd, concrete = _freeze(model, [None, 12])
        x = np.random.RandomState(0).rand(5, 12).astype(np.float32)
        ref = model(x).numpy()
        net = TFNet(gd)
        out = net.predict(x)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-5)

    def test_cnn_matches_tf(self):
        tf.random.set_seed(1)
        model = tf.keras.Sequential([
            tf.keras.layers.Conv2D(4, 3, padding="same",
                                   activation="relu"),
            tf.keras.layers.MaxPooling2D(2),
            tf.keras.layers.Conv2D(8, 3, padding="valid"),
            tf.keras.layers.GlobalAveragePooling2D(),
            tf.keras.layers.Dense(3),
        ])
        model.build((None, 12, 12, 2))
        gd, _ = _freeze(model, [None, 12, 12, 2])
        x = np.random.RandomState(1).rand(2, 12, 12, 2)\
            .astype(np.float32)
        ref = model(x).numpy()
        out = TFNet(gd).predict(x)
        np.testing.assert_allclose(out, ref, rtol=1e-4, atol=1e-4)

    def test_batchnorm_inference_matches_tf(self):
        tf.random.set_seed(2)
        model = tf.keras.Sequential([
            tf.keras.layers.Conv2D(4, 3),
            tf.keras.layers.BatchNormalization(),
            tf.keras.layers.ReLU(),
        ])
        model.build((None, 8, 8, 2))
        # shift running stats away from init so the BN math is exercised
        bn = model.layers[1]
        bn.moving_mean.assign(tf.random.normal([4]))
        bn.moving_variance.assign(tf.random.uniform([4], 0.5, 2.0))
        gd, _ = _freeze(model, [None, 8, 8, 2])
        x = np.random.RandomState(2).rand(2, 8, 8, 2).astype(np.float32)
        ref = model(x, training=False).numpy()
        out = TFNet(gd).predict(x)
        np.testing.assert_allclose(out, ref, rtol=1e-3, atol=1e-4)

    def test_unsupported_op_raises_at_load(self):
        gd = tf.compat.v1.GraphDef()
        n = gd.node.add()
        n.name = "x"
        n.op = "Placeholder"
        n2 = gd.node.add()
        n2.name = "fancy"
        n2.op = "SomeExoticOp"
        n2.input.append("x")
        with pytest.raises(NotImplementedError, match="SomeExoticOp"):
            TFNet(gd)

    def test_explicit_outputs_and_multi_output(self):
        tf.random.set_seed(3)
        model = tf.keras.Sequential([
            tf.keras.layers.Dense(6, activation="relu"),
            tf.keras.layers.Dense(2),
        ])
        model.build((None, 4))
        gd, _ = _freeze(model, [None, 4])
        # pick an intermediate node as an extra output
        relu_nodes = [n.name for n in gd.node if n.op == "Relu"]
        final = [n.name for n in gd.node if n.op == "BiasAdd"][-1]
        net = TFNet(gd, outputs=[relu_nodes[0], final])
        x = np.random.RandomState(3).rand(3, 4).astype(np.float32)
        hid, out = net(x)
        assert np.asarray(hid).shape == (3, 6)
        assert np.asarray(out).shape == (3, 2)

"""ISSUE 10 elastic multi-host training: supervisor state machine, peer
heartbeats, collective-hang watchdog, snapshot ring, and snapshot-based
recovery.

Everything here is tier-1: the supervisor/agent state machines run on
fake clocks against recorded transports (zero sleeping, no sockets
except the two explicit HTTP round-trip cases), training cases use tiny
MLPs on the ring-only path, and the launcher cases spawn jax-free
subprocesses. The real two-process kill-and-recover run lives in
tests/test_multihost.py (slow-marked).
"""

import os
import sys
import threading

import numpy as np
import pytest

from bigdl_tpu import elastic
from bigdl_tpu import observability as obs
from bigdl_tpu import reliability as rel
from bigdl_tpu.elastic import ElasticAgent, ElasticRestart, SnapshotRing
from bigdl_tpu.elastic.supervisor import RESTARTING, RUNNING, Supervisor
from bigdl_tpu.utils.conf import conf

pytestmark = pytest.mark.elastic


@pytest.fixture(autouse=True)
def _clean_elastic_state():
    rel.enable()
    rel.set_plan(None)
    obs.reset()
    yield
    rel.set_plan(None)
    for key in ("bigdl.elastic.enabled", "bigdl.elastic.snapshot.every",
                "bigdl.elastic.snapshot.ring", "bigdl.elastic.step.timeout",
                "bigdl.elastic.heartbeat.interval",
                "bigdl.elastic.max.restarts",
                "bigdl.elastic.supervisor.address"):
        conf.unset(key)
    obs.reset()


class FakeClock:
    def __init__(self, t=100.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


def _counter_value(_metric, **labels):
    m = obs.REGISTRY.get(_metric)
    if m is None:
        return 0.0
    child = m.labels(**labels) if labels else m
    return child.value


# ---------------------------------------------------------------------------
# snapshot ring: take / evict / commit / rollback
# ---------------------------------------------------------------------------

class TestSnapshotRing:
    def _take(self, ring, step):
        return ring.take(step, {"w": np.full(2, step)}, {}, {"m": step},
                         {"seed": 0}, {"neval": step})

    def test_capacity_evicts_oldest(self):
        ring = SnapshotRing(capacity=2)
        for s in (5, 10, 15):
            self._take(ring, s)
        assert ring.steps() == [10, 15]
        assert ring.taken == 3

    def test_commit_marks_at_or_below_step(self):
        ring = SnapshotRing(capacity=4)
        for s in (5, 10, 15):
            self._take(ring, s)
        assert ring.newest_committed() is None
        flipped = ring.commit(10)
        assert flipped == 2
        assert ring.committed_steps() == [5, 10]
        assert ring.newest_committed().step == 10
        # idempotent: re-acking an old step flips nothing
        assert ring.commit(10) == 0

    def test_rollback_drops_uncommitted_younger_entries(self):
        ring = SnapshotRing(capacity=4)
        for s in (5, 10, 15):
            self._take(ring, s)
        ring.commit(10)
        ent = ring.rollback()
        assert ent.step == 10
        # the uncommitted 15 is gone: a second failure before the next
        # snapshot restores the same agreed-upon point
        assert ring.steps() == [5, 10]
        assert ring.rollback().step == 10

    def test_rollback_none_when_nothing_committed(self):
        ring = SnapshotRing(capacity=2)
        self._take(ring, 5)
        assert ring.rollback() is None
        assert len(ring) == 0          # uncommitted entries dropped

    def test_auto_commit_mode(self):
        ring = SnapshotRing(capacity=2, auto_commit=True)
        self._take(ring, 5)
        assert ring.newest_committed().step == 5
        assert ring.rollback().step == 5


# ---------------------------------------------------------------------------
# supervisor: membership, expiry, stall, commit floor, generations
# ---------------------------------------------------------------------------

class TestSupervisor:
    def test_heartbeats_register_and_commit_floor(self):
        clk = FakeClock()
        sup = Supervisor(expected=2, heartbeat_timeout=5.0, clock=clk)
        out = sup.heartbeat(pid=0, step=4, snap_step=3)
        assert out["directive"] == "ok"
        # only 1/2 peers present: no commit floor yet
        assert out["committed_step"] == -1
        out = sup.heartbeat(pid=1, step=5, snap_step=5)
        assert out["committed_step"] == 3   # min over the live world
        assert sup.live_peers() == 2
        assert sup.step_skew() == 1
        # the floor is monotonic
        sup.heartbeat(pid=0, step=8, snap_step=7)
        out = sup.heartbeat(pid=1, step=8, snap_step=7)
        assert out["committed_step"] == 7

    def test_heartbeat_expiry_fails_the_world(self):
        clk = FakeClock()
        sup = Supervisor(expected=2, heartbeat_timeout=5.0, clock=clk)
        sup.heartbeat(pid=0)
        sup.heartbeat(pid=1)
        assert sup.sweep() and sup.state == RUNNING
        clk.advance(3.0)
        sup.heartbeat(pid=0)           # peer 0 stays chatty
        clk.advance(3.0)               # peer 1 silent for 6s > 5s
        out = sup.heartbeat(pid=0)
        assert sup.state == RESTARTING
        assert out["directive"] == "abort"
        assert "expired" in out["reason"]
        assert sup.expiries == 1

    def test_stall_report_fails_the_world(self):
        clk = FakeClock()
        sup = Supervisor(expected=2, heartbeat_timeout=5.0, clock=clk)
        sup.heartbeat(pid=0)
        out = sup.heartbeat(pid=1, step=7, status="stall")
        assert sup.state == RESTARTING
        assert out["directive"] == "abort"
        assert "stalled" in out["reason"]
        assert sup.stalls == 1
        # the survivor's next beat is told to abort too
        assert sup.heartbeat(pid=0)["directive"] == "abort"

    def test_clean_leave_is_not_a_death(self):
        """A worker that finished and exited 0 must stop being a
        liveness obligation — its heartbeat going quiet must not
        restart the healthy remainder of the world."""
        clk = FakeClock()
        sup = Supervisor(expected=2, heartbeat_timeout=5.0, clock=clk)
        sup.heartbeat(pid=0)
        sup.heartbeat(pid=1)
        sup.leave(1)                   # launcher saw exit code 0
        assert sup.live_peers() == 1
        clk.advance(60.0)              # way past peer 1's last beat
        sup.heartbeat(pid=0)           # peer 0 still training
        assert sup.sweep()
        assert sup.state == RUNNING

    def test_commit_floor_keeps_moving_after_clean_leave(self):
        """A finished peer's snapshots stop constraining the floor —
        the survivors' later snapshots must still commit (and flush),
        or a late failure would lose far more than snapshot.every
        steps."""
        clk = FakeClock()
        sup = Supervisor(expected=2, heartbeat_timeout=5.0, clock=clk)
        sup.heartbeat(pid=0, snap_step=10)
        sup.heartbeat(pid=1, snap_step=10)
        assert sup.committed_step == 10
        sup.leave(1)
        out = sup.heartbeat(pid=0, snap_step=20)
        assert out["committed_step"] == 20

    def test_join_timeout_catches_prebeat_wedge(self):
        """A worker stuck BEFORE its first heartbeat never registers,
        so peer expiry can't see it — the join deadline must bound
        the hang."""
        clk = FakeClock()
        sup = Supervisor(expected=2, heartbeat_timeout=5.0,
                         join_timeout=30.0, clock=clk)
        sup.heartbeat(pid=0)           # peer 1 never arrives
        clk.advance(20.0)
        sup.heartbeat(pid=0)
        assert sup.state == RUNNING    # inside the join budget
        clk.advance(15.0)
        out = sup.heartbeat(pid=0)     # 35s > 30s
        assert sup.state == RESTARTING
        assert out["directive"] == "abort"
        assert "joined" in out["reason"]
        # a fresh generation restarts the join clock
        sup.begin_generation()
        assert sup.sweep() and sup.state == RUNNING

    def test_stale_generation_is_told_to_abort_without_joining(self):
        clk = FakeClock()
        sup = Supervisor(expected=2, heartbeat_timeout=5.0, clock=clk)
        sup.begin_generation()         # now generation 1
        out = sup.heartbeat(pid=0, generation=0)
        assert out["directive"] == "abort"
        assert "stale generation" in out["reason"]
        assert sup.live_peers() == 0   # ghosts never join the table

    def test_begin_generation_resets_membership_keeps_commit(self):
        clk = FakeClock()
        sup = Supervisor(expected=2, heartbeat_timeout=5.0, clock=clk)
        sup.heartbeat(pid=0, snap_step=9)
        sup.heartbeat(pid=1, snap_step=9)
        assert sup.committed_step == 9
        sup.fail("process 1 exited with code 17")
        assert sup.state == RESTARTING
        gen = sup.begin_generation()
        assert gen == 1 and sup.state == RUNNING
        assert sup.live_peers() == 0
        # the committed step survives: it names the resume point
        assert sup.committed_step == 9
        out = sup.heartbeat(pid=0, generation=1)
        assert out["directive"] == "ok"

    def test_http_round_trip_and_healthz(self):
        import http.client
        import json

        sup = Supervisor(expected=1, heartbeat_timeout=60.0).start()
        try:
            host, port = sup.address

            def call(method, path, body=None):
                c = http.client.HTTPConnection(host, port, timeout=5)
                try:
                    c.request(method, path,
                              json.dumps(body) if body else None)
                    r = c.getresponse()
                    return r.status, json.loads(r.read().decode())
                finally:
                    c.close()

            st, out = call("POST", "/elastic/heartbeat",
                           {"pid": 0, "step": 3, "snap_step": 2})
            assert st == 200 and out["directive"] == "ok"
            assert out["committed_step"] == 2
            st, out = call("GET", "/elastic/status")
            assert st == 200 and out["state"] == RUNNING
            assert out["peers"]["0"]["step"] == 3
            st, out = call("GET", "/healthz")
            assert st == 200 and out["ok"]
            sup.fail("test failure")
            st, out = call("GET", "/healthz")
            assert st == 503 and not out["ok"]
            st, out = call("POST", "/elastic/heartbeat", {"pid": "x"})
            assert st == 422
        finally:
            sup.stop()


# ---------------------------------------------------------------------------
# agent: step heartbeat, stall watchdog, beats, directives
# ---------------------------------------------------------------------------

class TestElasticAgent:
    def test_stall_detected_on_fake_clock_and_abort_armed(self):
        clk = FakeClock()
        agent = ElasticAgent(process_id=0, step_timeout=2.0,
                             heartbeat_interval=0.1, clock=clk)
        assert not agent.check_stall()     # no step seen: not live
        agent.step_heartbeat(5)
        clk.advance(1.0)
        assert not agent.check_stall()     # inside the budget
        clk.advance(1.5)
        assert agent.check_stall()         # 2.5s > 2.0s: wedged
        assert agent.should_abort()
        assert "stalled" in agent.abort_reason()
        assert agent.stalls == 1
        agent.check_stall()                # still stalled, counted once
        assert agent.stalls == 1
        assert _counter_value("bigdl_elastic_stalls_total") == 1

    def test_loop_idle_parks_the_watchdog(self):
        clk = FakeClock()
        agent = ElasticAgent(process_id=0, step_timeout=2.0,
                             heartbeat_interval=0.1, clock=clk)
        agent.step_heartbeat(5)
        agent.loop_idle()                  # epoch-boundary work
        clk.advance(60.0)
        assert not agent.check_stall()
        agent.step_heartbeat(6)            # next step re-arms
        clk.advance(3.0)
        assert agent.check_stall()

    def test_beat_payload_directives_and_ring_commit(self):
        clk = FakeClock()
        ring = SnapshotRing(capacity=4)
        ring.take(7, {}, {}, {}, {}, {"neval": 7})
        sent = []
        reply = {"directive": "ok", "generation": 0, "committed_step": 7}

        def transport(payload):
            sent.append(payload)
            return dict(reply)

        agent = ElasticAgent(process_id=3, ring=ring, transport=transport,
                             step_timeout=0, heartbeat_interval=0.1,
                             generation=0, clock=clk)
        agent.step_heartbeat(9)
        agent.note_snapshot(7)
        agent.beat()
        assert sent[-1] == {"pid": 3, "step": 9, "snap_step": 7,
                            "status": "ok", "generation": 0}
        # the acked commit landed on the ring
        assert ring.newest_committed().step == 7
        assert not agent.should_abort()
        reply = {"directive": "abort", "generation": 1,
                 "committed_step": 7, "reason": "world restarting"}
        agent.beat()
        assert agent.should_abort()
        assert "world restarting" in agent.abort_reason()
        assert agent.beats == 2
        assert _counter_value("bigdl_elastic_heartbeats_total") == 2

    def test_stalled_agent_reports_stall_status_upstream(self):
        clk = FakeClock()
        sent = []

        def transport(payload):
            sent.append(payload)
            return {"directive": "ok", "committed_step": -1}

        agent = ElasticAgent(process_id=0, transport=transport,
                             step_timeout=1.0, heartbeat_interval=0.1,
                             clock=clk)
        agent.step_heartbeat(4)
        clk.advance(5.0)
        agent.beat()
        assert sent[-1]["status"] == "stall"
        assert agent.should_abort()

    def test_heartbeat_fault_site_raises_through_beat(self):
        plan = rel.FaultPlan(seed=0)
        plan.add("elastic.heartbeat", "raise", times=1)
        rel.set_plan(plan)
        agent = ElasticAgent(process_id=0, transport=lambda p: {},
                             step_timeout=0, heartbeat_interval=0.1)
        with pytest.raises(rel.InjectedFault):
            agent.beat()
        rel.set_plan(None)
        assert agent.beats == 0            # the failed beat never sent

    def test_thread_lifecycle_and_failure_counting(self):
        calls = threading.Event()

        def transport(payload):
            calls.set()
            raise ConnectionError("supervisor gone")

        agent = ElasticAgent(process_id=0, transport=transport,
                             step_timeout=0, heartbeat_interval=0.01)
        agent.start()
        assert calls.wait(5.0)
        agent.stop()
        assert agent.beat_failures >= 1
        assert not [t for t in threading.enumerate()
                    if t.name == "bigdl-elastic-agent"]

    def test_threadless_when_nothing_to_do(self):
        agent = ElasticAgent(process_id=0, step_timeout=0,
                             heartbeat_interval=0.01)
        agent.start()
        assert agent._thread is None       # no supervisor, no watchdog


# ---------------------------------------------------------------------------
# optimizer integration: ring-only stall recovery, disabled mode
# ---------------------------------------------------------------------------

def _train(elastic_on=False, step_timeout="0.6", fault_plan=None,
           epochs=3, max_restarts=None):
    import bigdl_tpu.nn as nn
    from bigdl_tpu.feature.dataset import LocalDataSet
    from bigdl_tpu.nn.module import set_seed
    from bigdl_tpu.optim.optimizer import LocalOptimizer
    from bigdl_tpu.optim.trigger import Trigger

    set_seed(0)
    rs = np.random.RandomState(0)
    x = rs.randn(64, 8).astype(np.float32)
    t = (rs.randint(0, 4, 64) + 1).astype(np.int32)
    model = (nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU())
             .add(nn.Linear(16, 4)).add(nn.LogSoftMax()))
    opt = LocalOptimizer(model, LocalDataSet(x, t, shuffle=False),
                         nn.ClassNLLCriterion(), batch_size=16,
                         end_trigger=Trigger.max_epoch(epochs))
    if elastic_on:
        conf.set("bigdl.elastic.enabled", "true")
        conf.set("bigdl.elastic.snapshot.every", "2")
        conf.set("bigdl.elastic.step.timeout", step_timeout)
        conf.set("bigdl.elastic.heartbeat.interval", "0.05")
        if max_restarts is not None:
            conf.set("bigdl.elastic.max.restarts", str(max_restarts))
    if fault_plan is not None:
        rel.set_plan(fault_plan)
    try:
        opt.optimize()
    finally:
        rel.set_plan(None)
        if elastic_on:
            for k in ("bigdl.elastic.enabled",
                      "bigdl.elastic.snapshot.every",
                      "bigdl.elastic.step.timeout",
                      "bigdl.elastic.heartbeat.interval",
                      "bigdl.elastic.max.restarts"):
                conf.unset(k)
    import jax
    leaves = [np.asarray(l) for l in
              jax.tree_util.tree_leaves(opt.model.parameters_dict())]
    return opt, leaves


class TestOptimizerIntegration:
    def test_stall_recovery_is_bit_identical_to_clean_run(self):
        """The acceptance contract on the ring tier: one wedged step
        (an injected delay past the watchdog timeout) → stall detected
        → in-process rollback to the last committed snapshot → replay
        → final weights bit-identical to the uninterrupted run."""
        _, w_clean = _train(elastic_on=False)
        plan = rel.FaultPlan(seed=0)
        plan.add("elastic.step", "delay", times=1, after=6, delay=1.5)
        opt, w_el = _train(elastic_on=True, fault_plan=plan)
        assert plan.fired == [("elastic.step", "delay")]
        assert opt._elastic.agent.stalls == 1
        assert opt._elastic.ring.rollbacks == 1
        for a, b in zip(w_clean, w_el):
            np.testing.assert_array_equal(a, b)
        assert _counter_value("bigdl_elastic_restarts_total",
                              scope="in_process") == 1
        assert _counter_value("bigdl_elastic_snapshots_total") > 0

    def test_snapshot_cadence(self):
        opt, _ = _train(elastic_on=True, step_timeout="0")
        # 12 iterations at every=2 -> 6 snapshots, ring keeps newest 2
        assert opt._elastic.ring.taken == 6
        assert len(opt._elastic.ring) == 2
        assert opt._elastic.ring.newest_committed() is not None

    def test_flush_every_counts_commits_not_steps(self, tmp_path):
        """`snapshot.flush.every=2` means every SECOND committed
        snapshot reaches disk — observing the same pending commit
        across several iterations must not count repeatedly."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.feature.dataset import LocalDataSet
        from bigdl_tpu.nn.module import set_seed
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        set_seed(0)
        rs = np.random.RandomState(0)
        x = rs.randn(64, 8).astype(np.float32)
        t = (rs.randint(0, 4, 64) + 1).astype(np.int32)
        model = (nn.Sequential().add(nn.Linear(8, 4))
                 .add(nn.LogSoftMax()))
        opt = LocalOptimizer(model, LocalDataSet(x, t, shuffle=False),
                             nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=Trigger.max_epoch(3))
        # trigger far out of reach: every tag on disk is an elastic flush
        opt.set_checkpoint(str(tmp_path), Trigger.several_iteration(10**9))
        conf.set("bigdl.elastic.enabled", "true")
        conf.set("bigdl.elastic.snapshot.every", "2")
        conf.set("bigdl.elastic.step.timeout", "0")
        conf.set("bigdl.elastic.snapshot.flush.every", "2")
        try:
            opt.optimize()
        finally:
            for k in ("bigdl.elastic.enabled",
                      "bigdl.elastic.snapshot.every",
                      "bigdl.elastic.step.timeout",
                      "bigdl.elastic.snapshot.flush.every"):
                conf.unset(k)
        # 12 iterations -> 6 committed snapshots -> 3 durable flushes
        assert opt._elastic.ring.taken == 6
        assert _counter_value("bigdl_elastic_flushes_total") == 3

    def test_restart_budget_exhaustion_raises(self):
        plan = rel.FaultPlan(seed=0)
        # every step wedges: the budget (1) must run out and surface
        plan.add("elastic.step", "delay", times=None, delay=1.0)
        with pytest.raises(ElasticRestart):
            _train(elastic_on=True, fault_plan=plan, max_restarts=1)

    def test_elastic_auto_resume_without_reliability(self, tmp_path):
        """Elastic recovery must not silently depend on the unrelated
        reliability switch: a restarted generation with
        bigdl.reliability.enabled=false still resumes from the durable
        snapshot tier at the exact saved iteration."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.feature.dataset import LocalDataSet
        from bigdl_tpu.nn.module import set_seed
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger
        from bigdl_tpu.utils import checkpoint as ckpt

        def build(epochs):
            set_seed(0)
            rs = np.random.RandomState(0)
            x = rs.randn(32, 8).astype(np.float32)
            t = (rs.randint(0, 4, 32) + 1).astype(np.int32)
            model = (nn.Sequential().add(nn.Linear(8, 4))
                     .add(nn.LogSoftMax()))
            opt = LocalOptimizer(model, LocalDataSet(x, t, shuffle=False),
                                 nn.ClassNLLCriterion(), batch_size=16,
                                 end_trigger=Trigger.max_epoch(epochs))
            opt.set_checkpoint(str(tmp_path), Trigger.every_epoch())
            return opt

        build(1).optimize()            # seeds the durable tier
        saved = ckpt.latest(str(tmp_path), paired_prefix="model.")
        assert saved is not None
        conf.set("bigdl.elastic.enabled", "true")
        conf.set("bigdl.elastic.step.timeout", "0")
        rel.disable()
        try:
            opt2 = build(2)
            seen = {}
            orig = opt2._optimize_once

            def capture():
                seen["neval"] = opt2.state["neval"]
                return orig()

            opt2._optimize_once = capture
            opt2.optimize()
        finally:
            rel.enable()
        # resumed at the saved iteration, not from scratch
        assert seen["neval"] == int(saved.split(".")[1])

    def test_disabled_mode_structurally_absent(self):
        before = set(obs.render().splitlines())
        opt, _ = _train(elastic_on=False, epochs=1)
        assert opt._elastic is None
        assert not [t for t in threading.enumerate()
                    if t.name.startswith("bigdl-elastic")]
        grown = "\n".join(set(obs.render().splitlines()) - before)
        assert "bigdl_elastic_" not in grown


# ---------------------------------------------------------------------------
# world-size guard (satellite): resume must fail fast, not mis-shard
# ---------------------------------------------------------------------------

class TestWorldSizeGuard:
    def test_resume_into_changed_world_fails_fast(self, tmp_path):
        import bigdl_tpu.nn as nn
        from bigdl_tpu.feature.dataset import LocalDataSet
        from bigdl_tpu.nn.module import set_seed
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger
        from bigdl_tpu.utils import checkpoint as ckpt

        def build():
            set_seed(0)
            rs = np.random.RandomState(0)
            x = rs.randn(32, 8).astype(np.float32)
            t = (rs.randint(0, 4, 32) + 1).astype(np.int32)
            model = (nn.Sequential().add(nn.Linear(8, 4))
                     .add(nn.LogSoftMax()))
            opt = LocalOptimizer(model, LocalDataSet(x, t, shuffle=False),
                                 nn.ClassNLLCriterion(), batch_size=16,
                                 end_trigger=Trigger.max_epoch(1))
            opt.set_checkpoint(str(tmp_path), Trigger.every_epoch())
            return opt

        build().optimize()
        tag = ckpt.latest(str(tmp_path), paired_prefix="model.")
        assert tag is not None
        # the signature is recorded
        blob, _ = ckpt.load_checkpoint(
            str(tmp_path / f"optim.{tag}"), to_jax=False)
        assert blob["world"]["processes"] == 1
        # doctor the saved world: pretend a 4-process / 32-device run
        blob["world"] = {"processes": 4, "devices": 32}
        ckpt.save_checkpoint(str(tmp_path / f"optim.{tag}"), blob)

        opt2 = build()
        with pytest.raises(ValueError) as ei:
            opt2.resume_from_checkpoint(str(tmp_path), tag)
        msg = str(ei.value)
        assert "4 process(es)" in msg and "32 device(s)" in msg
        assert "1 process(es)" in msg      # saved vs current, by name
        # the rejected resume left the optimizer untouched
        assert opt2.state["neval"] == 1

    def test_legacy_blob_resets_stale_batch_in_epoch(self, tmp_path):
        """A pre-ISSUE-10 optim blob carries no batch_in_epoch; the
        live (possibly nonzero) value must not survive the resume, or
        the restored epoch silently skips that many batches."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.feature.dataset import LocalDataSet
        from bigdl_tpu.nn.module import set_seed
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger
        from bigdl_tpu.utils import checkpoint as ckpt

        set_seed(0)
        rs = np.random.RandomState(0)
        x = rs.randn(32, 8).astype(np.float32)
        t = (rs.randint(0, 4, 32) + 1).astype(np.int32)
        model = (nn.Sequential().add(nn.Linear(8, 4))
                 .add(nn.LogSoftMax()))
        opt = LocalOptimizer(model, LocalDataSet(x, t, shuffle=False),
                             nn.ClassNLLCriterion(), batch_size=16,
                             end_trigger=Trigger.max_epoch(1))
        opt.set_checkpoint(str(tmp_path), Trigger.every_epoch())
        opt.optimize()
        tag = ckpt.latest(str(tmp_path), paired_prefix="model.")
        blob, _ = ckpt.load_checkpoint(str(tmp_path / f"optim.{tag}"),
                                       to_jax=False)
        del blob["train_state"]["batch_in_epoch"]   # legacy layout
        ckpt.save_checkpoint(str(tmp_path / f"optim.{tag}"), blob)

        opt.state["batch_in_epoch"] = 7            # stale live value
        opt.resume_from_checkpoint(str(tmp_path), tag)
        assert opt.state["batch_in_epoch"] == 0

    def test_same_world_resume_still_works(self, tmp_path):
        """The guard must not break the normal preemption round-trip."""
        import bigdl_tpu.nn as nn
        from bigdl_tpu.feature.dataset import LocalDataSet
        from bigdl_tpu.nn.module import set_seed
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger
        from bigdl_tpu.utils import checkpoint as ckpt

        def build(epochs):
            set_seed(0)
            rs = np.random.RandomState(0)
            x = rs.randn(32, 8).astype(np.float32)
            t = (rs.randint(0, 4, 32) + 1).astype(np.int32)
            model = (nn.Sequential().add(nn.Linear(8, 4))
                     .add(nn.LogSoftMax()))
            opt = LocalOptimizer(model, LocalDataSet(x, t, shuffle=False),
                                 nn.ClassNLLCriterion(), batch_size=16,
                                 end_trigger=Trigger.max_epoch(epochs))
            opt.set_checkpoint(str(tmp_path), Trigger.every_epoch())
            return opt

        build(1).optimize()
        tag = ckpt.latest(str(tmp_path), paired_prefix="model.")
        opt2 = build(2)
        opt2.resume_from_checkpoint(str(tmp_path), tag)
        assert opt2.state["epoch"] == 2
        opt2.optimize()                    # trains epoch 2 and finishes
        assert opt2.state["epoch"] > 2


# ---------------------------------------------------------------------------
# Engine.init satellite: loud failure for explicit coordinators
# ---------------------------------------------------------------------------

class TestEngineInitFailures:
    @pytest.fixture(autouse=True)
    def _reset_engine(self):
        from bigdl_tpu.utils.engine import Engine
        Engine.reset()
        yield
        Engine.reset()

    def test_explicit_coordinator_failure_raises_and_counts(
            self, monkeypatch):
        import jax
        from bigdl_tpu.utils.engine import Engine

        def boom(**kw):
            raise RuntimeError("connection refused")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        with pytest.raises(RuntimeError) as ei:
            Engine.init(coordinator_address="127.0.0.1:1",
                        num_processes=2, process_id=0)
        assert "explicitly configured coordinator" in str(ei.value)
        assert "127.0.0.1:1" in str(ei.value)
        assert _counter_value("bigdl_engine_init_failures_total") == 1

    def test_env_autodetect_failure_is_best_effort(self, monkeypatch):
        import jax
        from bigdl_tpu.utils.engine import Engine

        def boom(**kw):
            raise RuntimeError("connection refused")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        monkeypatch.setenv("JAX_COORDINATOR_ADDRESS", "127.0.0.1:1")
        mesh = Engine.init()               # warns, continues standalone
        assert mesh is not None
        assert Engine.is_initialized()
        assert _counter_value("bigdl_engine_init_failures_total") == 1

    def test_already_initialized_is_not_a_failure(self, monkeypatch):
        import jax
        from bigdl_tpu.utils.engine import Engine

        def boom(**kw):
            raise RuntimeError(
                "jax.distributed.initialize was already called")

        monkeypatch.setattr(jax.distributed, "initialize", boom)
        mesh = Engine.init(coordinator_address="127.0.0.1:1")
        assert mesh is not None
        assert _counter_value("bigdl_engine_init_failures_total") == 0

    def test_reinit_distributed_tears_down_and_rejoins(self, monkeypatch):
        import jax
        from bigdl_tpu.utils.engine import Engine

        calls = []
        monkeypatch.setattr(jax.distributed, "shutdown",
                            lambda: calls.append("shutdown"))
        monkeypatch.setattr(
            jax.distributed, "initialize",
            lambda **kw: calls.append(("init", kw["coordinator_address"])))
        Engine.init()
        mesh = Engine.reinit_distributed("127.0.0.1:2222",
                                         num_processes=1, process_id=0)
        assert mesh is not None
        assert calls == ["shutdown", ("init", "127.0.0.1:2222")]
        assert Engine.is_initialized()

    def test_reinit_survives_wedged_shutdown(self, monkeypatch):
        import jax
        from bigdl_tpu.utils.engine import Engine

        def bad_shutdown():
            raise RuntimeError("client wedged on a dead peer")

        monkeypatch.setattr(jax.distributed, "shutdown", bad_shutdown)
        monkeypatch.setattr(jax.distributed, "initialize",
                            lambda **kw: None)
        mesh = Engine.reinit_distributed("127.0.0.1:2222",
                                         num_processes=1, process_id=0)
        assert mesh is not None


# ---------------------------------------------------------------------------
# launcher: jax-free worker sets (fast, real processes)
# ---------------------------------------------------------------------------

_EXIT_BY_GENERATION = (
    "import os, sys; "
    "sys.exit(0 if int(os.environ['BIGDL_TPU_ELASTIC_GENERATION']) >= %d "
    "else %d)")


class TestLauncher:
    def _launcher(self, code, **kw):
        from bigdl_tpu.elastic.launch import ElasticLauncher
        env = {k: v for k, v in os.environ.items()}
        return ElasticLauncher([sys.executable, "-c", code], nprocs=2,
                               poll_interval=0.05, grace=2.0, env=env,
                               **kw)

    def test_clean_set_completes_without_restart(self):
        rec = self._launcher("print('ok')",
                             max_restarts=1).run(timeout=60)
        assert rec["restarts"] == 0
        assert rec["exit_codes"] == [0, 0]
        assert rec["failures"] == []

    def test_failed_generation_is_restarted(self):
        # generation 0 exits 7; generation 1 exits 0
        rec = self._launcher(_EXIT_BY_GENERATION % (1, 7),
                             max_restarts=2).run(timeout=60)
        assert rec["restarts"] == 1
        assert rec["exit_codes"] == [0, 0]
        assert any("code 7" in f for f in rec["failures"])

    def test_restart_budget_exhaustion(self):
        from bigdl_tpu.elastic.launch import ElasticJobFailed
        with pytest.raises(ElasticJobFailed) as ei:
            self._launcher("import sys; sys.exit(3)",
                           max_restarts=1).run(timeout=60)
        assert "restart budget exhausted" in str(ei.value)
        assert ei.value.log_tails        # diagnostics attached

    def test_workers_see_the_elastic_env(self):
        code = ("import os; "
                "assert os.environ['BIGDL_TPU_ELASTIC_ENABLED'] == 'true'; "
                "assert ':' in os.environ["
                "'BIGDL_TPU_ELASTIC_SUPERVISOR_ADDRESS']; "
                "assert os.environ['BIGDL_TPU_NUM_PROCESSES'] == '2'; "
                "assert os.environ['BIGDL_TPU_PROCESS_ID'] in ('0', '1'); "
                "assert ':' in os.environ['BIGDL_TPU_COORDINATOR_ADDRESS']")
        rec = self._launcher(code, max_restarts=0).run(timeout=60)
        assert rec["exit_codes"] == [0, 0]

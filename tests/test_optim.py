"""Training-orchestration tests (ref test models: DistriOptimizerSpec runs
on local[N] Spark — here the 8-device CPU mesh plays that role, SURVEY.md §4).
"""

import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import bigdl_tpu.nn as nn
from bigdl_tpu.feature.dataset import DataSet, LocalDataSet, SampleToMiniBatch
from bigdl_tpu.feature.mnist import load_mnist, normalize
from bigdl_tpu.models import lenet
from bigdl_tpu.optim import (
    Adam, DistriOptimizer, Evaluator, LocalOptimizer, Optimizer, Predictor,
    SGD, Step, Top1Accuracy, TrainSummary, Trigger, validate)
from bigdl_tpu.utils.engine import Engine


def _toy_problem(n=256, d=8, classes=3, seed=0):
    rs = np.random.RandomState(seed)
    w = rs.randn(d, classes)
    x = rs.randn(n, d).astype(np.float32)
    y = (x @ w).argmax(1) + 1  # 1-based
    return x, y.astype(np.float32)


def _mlp(d=8, classes=3):
    return (nn.Sequential()
            .add(nn.Linear(d, 32)).add(nn.ReLU())
            .add(nn.Linear(32, classes)).add(nn.LogSoftMax()))


class TestOptimMethods:
    @pytest.mark.parametrize("method_cls", ["sgd", "sgdm", "adam", "adagrad",
                                            "rmsprop", "adadelta", "adamax",
                                            "ftrl"])
    def test_methods_reduce_loss(self, method_cls):
        from bigdl_tpu.optim import (Adadelta, Adagrad, Adam, Adamax, Ftrl,
                                     RMSprop)
        methods = {
            "sgd": SGD(learning_rate=0.5),
            "sgdm": SGD(learning_rate=0.2, momentum=0.9),
            "adam": Adam(learning_rate=0.05),
            "adagrad": Adagrad(learning_rate=0.3),
            "rmsprop": RMSprop(learning_rate=0.05),
            "adadelta": Adadelta(epsilon=1e-6),
            "adamax": Adamax(learning_rate=0.05),
            "ftrl": Ftrl(learning_rate=0.5),
        }
        method = methods[method_cls]
        # minimize ||p - 3||^2
        params = {"w": jnp.zeros((4,))}
        state = method.init_state(params)

        @jax.jit
        def step(p, s, lr):
            g = jax.grad(lambda q: jnp.sum((q["w"] - 3.0) ** 2))(p)
            return method.step(p, g, s, lr)

        # adadelta's unit-free updates start near sqrt(eps) — give it room
        iters = 4000 if method_cls == "adadelta" else 60
        loss0 = float(jnp.sum((params["w"] - 3.0) ** 2))
        for _ in range(iters):
            params, state = step(params, state, method.current_lr())
            method.host_state["eval_counter"] += 1
        loss1 = float(jnp.sum((params["w"] - 3.0) ** 2))
        assert loss1 < 0.2 * loss0, f"{method_cls}: {loss0} -> {loss1}"

    def test_lr_schedules(self):
        from bigdl_tpu.optim import Exponential, MultiStep, Poly
        sgd = SGD(learning_rate=1.0, learning_rate_schedule=Step(10, 0.5))
        sgd.host_state["eval_counter"] = 25
        assert abs(sgd.current_lr() - 0.25) < 1e-9
        sgd = SGD(learning_rate=1.0,
                  learning_rate_schedule=MultiStep([10, 20], 0.1))
        sgd.host_state["eval_counter"] = 15
        assert abs(sgd.current_lr() - 0.1) < 1e-9
        sgd = SGD(learning_rate=1.0,
                  learning_rate_schedule=Poly(2.0, 100))
        sgd.host_state["eval_counter"] = 50
        assert abs(sgd.current_lr() - 0.25) < 1e-9


class TestLocalOptimizer:
    def test_mlp_convergence_and_eval(self):
        x, y = _toy_problem()
        model = _mlp()
        opt = LocalOptimizer(model, DataSet.array(x, y),
                             nn.ClassNLLCriterion(), batch_size=32,
                             end_trigger=Trigger.max_epoch(30))
        opt.set_optim_method(Adam(learning_rate=0.01))
        trained = opt.optimize()
        res = Evaluator(trained).evaluate((x, y), [Top1Accuracy()])[0]
        assert res.result > 0.9, f"accuracy {res.result}"

    def test_predictor(self):
        x, y = _toy_problem()
        model = _mlp()
        preds = Predictor(model).predict(x)
        assert preds.shape == (256, 3)
        classes = Predictor(model).predict_class(x)
        assert classes.min() >= 1 and classes.max() <= 3

    def test_checkpoint_resume(self, tmp_path):
        x, y = _toy_problem()
        model = _mlp()
        opt = LocalOptimizer(model, DataSet.array(x, y),
                             nn.ClassNLLCriterion(), batch_size=64,
                             end_trigger=Trigger.max_epoch(2))
        opt.set_checkpoint(str(tmp_path), Trigger.every_epoch())
        opt.optimize()
        files = os.listdir(tmp_path)
        assert any(f.startswith("model.") for f in files)
        assert any(f.startswith("optim.") for f in files)

    def test_gradient_clipping(self):
        x, y = _toy_problem()
        model = _mlp()
        opt = LocalOptimizer(model, DataSet.array(x, y),
                             nn.ClassNLLCriterion(), batch_size=64,
                             end_trigger=Trigger.max_epoch(1))
        opt.set_gradient_clipping_by_l2_norm(0.1)
        opt.optimize()  # just must run
        assert np.isfinite(opt.state["loss"])

    def test_train_summary(self, tmp_path):
        x, y = _toy_problem()
        model = _mlp()
        summary = TrainSummary(str(tmp_path), "test_app")
        opt = LocalOptimizer(model, DataSet.array(x, y),
                             nn.ClassNLLCriterion(), batch_size=64,
                             end_trigger=Trigger.max_epoch(1))
        opt.set_train_summary(summary)
        opt.optimize()
        losses = summary.read_scalar("Loss")
        assert len(losses) == 4  # 256/64 iterations
        assert all(np.isfinite(v) for _, v in losses)


class TestDistriOptimizer:
    @pytest.mark.parametrize("mode", ["bf16", "int8"])
    def test_gradient_compression_converges_like_plain(self, devices, mode):
        """The FP16CompressedTensor analog (ref optim/parameters/): the
        compressed all-reduce runs inside a shard_map step, and training
        converges to the same accuracy as the plain-psum path."""
        Engine.reset()
        mesh = Engine.init(mesh_shape=(8,))
        x, y = _toy_problem(n=512)

        def train(compression):
            model = _mlp()
            opt = DistriOptimizer(model, DataSet.array(x, y),
                                  nn.ClassNLLCriterion(), batch_size=64,
                                  end_trigger=Trigger.max_epoch(15),
                                  mesh=mesh)
            opt.set_optim_method(Adam(learning_rate=0.01))
            if compression:
                opt.set_gradient_compression(compression)
            trained = opt.optimize()
            acc = Evaluator(trained).evaluate(
                (x, y), [Top1Accuracy()])[0].result
            return acc, opt.state["loss"]

        acc_c, loss_c = train(mode)
        acc_p, loss_p = train(None)
        assert np.isfinite(loss_c)
        assert acc_c > 0.9, f"{mode} compressed training failed: {acc_c}"
        assert abs(acc_c - acc_p) < 0.08, (acc_c, acc_p)

    def test_gradient_compression_rejects_unknown(self, devices):
        Engine.reset()
        mesh = Engine.init(mesh_shape=(8,))
        x, y = _toy_problem(n=64)
        opt = DistriOptimizer(_mlp(), DataSet.array(x, y),
                              nn.ClassNLLCriterion(), batch_size=64,
                              mesh=mesh)
        with pytest.raises(ValueError):
            opt.set_gradient_compression("fp8")

    def test_dp_training_on_mesh(self, devices):
        Engine.reset()
        mesh = Engine.init(mesh_shape=(8,))
        x, y = _toy_problem(n=512)
        model = _mlp()
        opt = DistriOptimizer(model, DataSet.array(x, y),
                              nn.ClassNLLCriterion(), batch_size=64,
                              end_trigger=Trigger.max_epoch(20), mesh=mesh)
        opt.set_optim_method(Adam(learning_rate=0.01))
        trained = opt.optimize()
        res = Evaluator(trained).evaluate((x, y), [Top1Accuracy()])[0]
        assert res.result > 0.9

    def test_dp_matches_local_first_step(self, devices):
        """One DP step over the mesh == one local step on the global batch
        (the correctness property AllReduceParameterSpec checks)."""
        Engine.reset()
        mesh = Engine.init(mesh_shape=(8,))
        x, y = _toy_problem(n=64)
        nn.set_seed(7)
        m1 = _mlp()
        nn.set_seed(7)
        m2 = _mlp()
        ds = DataSet.array(x, y, shuffle=False)
        local = LocalOptimizer(m1, ds, nn.ClassNLLCriterion(), batch_size=64,
                               end_trigger=Trigger.max_iteration(1))
        local.set_optim_method(SGD(learning_rate=0.1))
        distri = DistriOptimizer(m2, DataSet.array(x, y, shuffle=False),
                                 nn.ClassNLLCriterion(), batch_size=64,
                                 end_trigger=Trigger.max_iteration(1),
                                 mesh=mesh)
        distri.set_optim_method(SGD(learning_rate=0.1))
        local.optimize()
        distri.optimize()
        for a, b in zip(jax.tree_util.tree_leaves(m1.parameters_dict()),
                        jax.tree_util.tree_leaves(m2.parameters_dict())):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-5, atol=1e-6)

    def test_batch_not_divisible_raises(self, devices):
        Engine.reset()
        mesh = Engine.init(mesh_shape=(8,))
        x, y = _toy_problem(n=64)
        with pytest.raises(ValueError, match="divisible"):
            DistriOptimizer(_mlp(), DataSet.array(x, y),
                            nn.ClassNLLCriterion(), batch_size=30, mesh=mesh)


class TestLeNetMNIST:
    """BASELINE config 1: LeNet-5/MNIST hello-world convergence."""

    def test_lenet_mnist_convergence(self):
        x, y = load_mnist(synthetic_size=1024)
        x = normalize(x)
        model = lenet.build_model(10)
        opt = LocalOptimizer(model, DataSet.array(x, y),
                             nn.ClassNLLCriterion(), batch_size=128,
                             end_trigger=Trigger.max_epoch(6))
        opt.set_optim_method(Adam(learning_rate=0.003))
        xv, yv = load_mnist(synthetic_size=256, train=False)
        opt.set_validation(Trigger.every_epoch(), (normalize(xv), yv),
                           [Top1Accuracy()], batch_size=128)
        trained = opt.optimize()
        res = Evaluator(trained).evaluate(
            (normalize(xv), yv), [Top1Accuracy()], batch_size=128)[0]
        assert res.result > 0.9, f"LeNet MNIST accuracy {res.result}"


class TestReviewRegressions:
    def test_max_iteration_runs_exactly_n_steps(self):
        x, y = _toy_problem(n=64)
        model = _mlp()
        before = [np.asarray(p) for p in
                  jax.tree_util.tree_leaves(model.parameters_dict())]
        opt = LocalOptimizer(model, DataSet.array(x, y, shuffle=False),
                             nn.ClassNLLCriterion(), batch_size=64,
                             end_trigger=Trigger.max_iteration(1))
        opt.set_optim_method(SGD(learning_rate=0.5))
        opt.optimize()
        after = [np.asarray(p) for p in
                 jax.tree_util.tree_leaves(model.parameters_dict())]
        moved = any(not np.allclose(a, b) for a, b in zip(before, after))
        assert moved, "max_iteration(1) performed zero steps"
        assert opt.state["iteration_done"] == 1

    def test_resume_restores_opt_state(self, tmp_path):
        x, y = _toy_problem(n=128)
        model = _mlp()
        opt = LocalOptimizer(model, DataSet.array(x, y),
                             nn.ClassNLLCriterion(), batch_size=64,
                             end_trigger=Trigger.max_epoch(2))
        opt.set_optim_method(Adam(learning_rate=0.01))
        opt.set_checkpoint(str(tmp_path), Trigger.every_epoch())
        opt.optimize()
        tags = sorted(f.split("model.")[1] for f in os.listdir(tmp_path)
                      if f.startswith("model."))
        opt2 = LocalOptimizer(_mlp(), DataSet.array(x, y),
                              nn.ClassNLLCriterion(), batch_size=64,
                              end_trigger=Trigger.max_epoch(4))
        opt2.set_optim_method(Adam(learning_rate=0.01))
        opt2.resume_from_checkpoint(str(tmp_path), tags[-1])
        assert opt2._resume_opt_state is not None
        t_before = int(np.asarray(opt2._resume_opt_state["t"]))
        assert t_before > 0, "adam step counter not restored"
        opt2.optimize()
        assert opt2.state["epoch"] > 2  # resumed epoch counter

    def test_full_conv_impulse_stamps_kernel(self):
        deconv = nn.SpatialFullConvolution(1, 1, 3, 3, with_bias=False)
        k = np.arange(9, dtype=np.float32).reshape(1, 1, 3, 3)
        deconv.load_parameters_dict({"weight": k})
        x = np.zeros((1, 1, 3, 3), np.float32)
        x[0, 0, 1, 1] = 1.0
        y = np.asarray(deconv.forward(x))
        # impulse through transposed conv stamps the (unflipped) kernel
        np.testing.assert_allclose(y[0, 0, 1:4, 1:4], k[0, 0])

    def test_table_eq(self):
        from bigdl_tpu.utils.table import T
        import jax.numpy as jnp
        assert T(jnp.ones(3), 2.0) == T(jnp.ones(3), 2.0)
        assert T(jnp.ones(3)) != T(jnp.zeros(3))

    def test_eval_forward_cached(self):
        x, y = _toy_problem(n=64)
        model = _mlp()
        from bigdl_tpu.optim.optimizer import _forward_fn
        f1 = _forward_fn(model)
        f2 = _forward_fn(model)
        assert f1 is f2


class TestLBFGS:
    def test_quadratic_beats_sgd(self):
        """LBFGS on an ill-conditioned quadratic must converge far faster
        than SGD at comparable step counts (the reason the reference
        ships it for full-batch problems)."""
        import jax
        import jax.numpy as jnp
        from bigdl_tpu.optim.optim_method import LBFGS, SGD

        A = jnp.diag(jnp.asarray([1.0, 10.0, 100.0]))
        b = jnp.asarray([1.0, -2.0, 3.0])

        def loss(p):
            return 0.5 * p["x"] @ A @ p["x"] - b @ p["x"]

        def run(opt, lr, steps):
            params = {"x": jnp.zeros(3)}
            state = opt.init_state(params)
            for _ in range(steps):
                l, g = jax.value_and_grad(loss)(params)
                params, state = opt.step(params, g, state, lr)
            return float(loss(params))

        opt_val = float(-0.5 * b @ jnp.linalg.inv(A) @ b)
        l_lbfgs = run(LBFGS(history_size=5), 0.5, 25)
        l_sgd = run(SGD(), 0.009, 25)   # ~max stable lr for cond=100
        assert l_lbfgs - opt_val < 1e-3, l_lbfgs
        assert l_lbfgs < l_sgd - 1e-3

    def test_first_step_is_damped_gradient_descent(self):
        """No curvature yet: step = lr * min(1, 1/|g|_1) * g (the
        torch-lbfgs first-iteration damping the implementation mirrors)."""
        import jax.numpy as jnp
        from bigdl_tpu.optim.optim_method import LBFGS

        opt = LBFGS(history_size=3)
        params = {"a": jnp.asarray([1.0, 2.0]), "b": jnp.asarray(3.0)}
        grads = {"a": jnp.asarray([0.5, -0.5]), "b": jnp.asarray(1.0)}
        state = opt.init_state(params)
        new, state = opt.step(params, grads, state, 0.1)
        t = 0.1 * min(1.0, 1.0 / 2.0)   # |g|_1 = 2
        np.testing.assert_allclose(np.asarray(new["a"]),
                                   [1.0 - t * 0.5, 2.0 + t * 0.5],
                                   rtol=1e-6)
        np.testing.assert_allclose(float(new["b"]), 3.0 - t, rtol=1e-6)


class TestIterationRetry:
    def test_retry_resumes_from_checkpoint(self, tmp_path):
        """Inject a failure mid-training; with set_max_retry the driver
        must restore the newest checkpoint and finish (ref:
        DistriOptimizer maxRetry recovery)."""
        import jax
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn.module import set_seed
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        set_seed(0)
        model = (nn.Sequential().add(nn.Linear(8, 16)).add(nn.ReLU())
                 .add(nn.Linear(16, 4)).add(nn.LogSoftMax()))
        rs = np.random.RandomState(0)
        x = rs.randn(64, 8).astype(np.float32)
        t = (rs.randint(0, 4, 64) + 1).astype(np.int32)
        opt = LocalOptimizer(model, (x, t), nn.ClassNLLCriterion(),
                             batch_size=16,
                             end_trigger=Trigger.max_epoch(4))
        opt.set_checkpoint(str(tmp_path), Trigger.every_epoch())
        opt.set_max_retry(2)

        # sabotage epoch 3's first batch once via the batch placer
        orig = opt._place_batch
        fired = {"n": 0}

        def flaky(xb, tb):
            if opt.state["epoch"] == 3 and fired["n"] == 0:
                fired["n"] = 1
                raise RuntimeError("injected executor failure")
            return orig(xb, tb)

        opt._place_batch = flaky
        trained = opt.optimize()
        assert fired["n"] == 1          # the failure really happened
        assert opt.state["epoch"] >= 3  # and training still completed
        y = np.asarray(trained.evaluate().forward(x[:4]))
        assert y.shape == (4, 4)

    def test_retry_budget_exhausted_reraises(self):
        import bigdl_tpu.nn as nn
        from bigdl_tpu.nn.module import set_seed
        from bigdl_tpu.optim.optimizer import LocalOptimizer
        from bigdl_tpu.optim.trigger import Trigger

        set_seed(0)
        model = nn.Sequential().add(nn.Linear(4, 2)).add(nn.LogSoftMax())
        x = np.random.rand(8, 4).astype(np.float32)
        t = np.ones(8, np.int32)
        opt = LocalOptimizer(model, (x, t), nn.ClassNLLCriterion(),
                             batch_size=4,
                             end_trigger=Trigger.max_epoch(2))
        opt.set_max_retry(1)

        def always_fail(xb, tb):
            raise RuntimeError("permanent failure")

        opt._place_batch = always_fail
        with pytest.raises(RuntimeError, match="permanent failure"):
            opt.optimize()


class TestBatchPrefetcher:
    """ISSUE 4: double-buffered host→device staging must overlap batch
    N+1's placement with step N, change no numbers, and die cleanly."""

    class _FakeBatch:
        def __init__(self, i):
            self.i = i

        def get_input(self):
            return self.i

        def get_target(self):
            return -self.i

        def size(self):
            return 1

    def test_placement_overlaps_step(self):
        """Fake-clock overlap proof: a logical event counter (no real
        sleeps on the assert path) records that batch 2's placement
        happened BEFORE the consumer asked for it — i.e. while the
        consumer was still busy with step 1."""
        import threading

        from bigdl_tpu.optim.optimizer import BatchPrefetcher

        placed = {}
        placed_2 = threading.Event()
        clock = iter(range(1000))          # the fake clock: event order

        def place(x, t):
            placed[x] = next(clock)
            if x == 2:
                placed_2.set()
            return x, t

        pf = BatchPrefetcher((self._FakeBatch(i) for i in (1, 2, 3)),
                             place, depth=2)
        try:
            x, t, n = next(pf)             # consumer holds batch 1
            assert (x, t, n) == (1, -1, 1)
            # "step 1 running": without requesting batch 2, its
            # placement completes in the background
            assert placed_2.wait(timeout=10), \
                "batch 2 was not staged while batch 1 was outstanding"
            tick = next(clock)             # consumer's request time
            x, t, n = next(pf)
            assert (x, t, n) == (2, -2, 1)
            assert placed[2] < tick, \
                "batch 2 placed only after the consumer asked"
            assert next(pf)[0] == 3
            with pytest.raises(StopIteration):
                next(pf)
        finally:
            pf.close()

    def test_producer_error_surfaces_on_consumer(self):
        from bigdl_tpu.optim.optimizer import BatchPrefetcher

        def place(x, t):
            if x == 2:
                raise ValueError("bad batch")
            return x, t

        pf = BatchPrefetcher((self._FakeBatch(i) for i in (1, 2)),
                             place, depth=2)
        try:
            assert next(pf)[0] == 1
            with pytest.raises(ValueError, match="bad batch"):
                while True:
                    next(pf)
        finally:
            pf.close()

    def test_close_unblocks_abandoned_producer(self):
        """An abandoned epoch (early trigger fire) must not leave the
        producer thread blocked on a full queue forever."""
        from bigdl_tpu.optim.optimizer import BatchPrefetcher

        pf = BatchPrefetcher((self._FakeBatch(i) for i in range(100)),
                             lambda x, t: (x, t), depth=1)
        next(pf)                            # producer now refills + blocks
        pf.close()
        pf._thread.join(timeout=10)
        assert not pf._thread.is_alive()

    @pytest.mark.parametrize("prefetch", ["true", "false"])
    def test_training_matches_synchronous(self, prefetch):
        """bigdl.train.prefetch must change throughput only: identical
        batches in identical order → identical final loss and weights
        vs the inline-staging loop."""
        from bigdl_tpu.nn.module import set_seed
        from bigdl_tpu.utils.conf import conf

        x, y = _toy_problem(n=128)

        def train():
            set_seed(0)
            model = _mlp()
            opt = LocalOptimizer(model, DataSet.array(x, y),
                                 nn.ClassNLLCriterion(), batch_size=32,
                                 end_trigger=Trigger.max_epoch(3))
            opt.set_optim_method(SGD(learning_rate=0.1))
            trained = opt.optimize()
            return opt.state["loss"], trained.parameters_dict()

        conf.set("bigdl.train.prefetch", prefetch)
        try:
            loss, params = train()
        finally:
            conf.unset("bigdl.train.prefetch")
        loss_sync, params_sync = train()    # default-on reference run

        if prefetch == "false":
            # cross-check against the default (prefetch on) run
            assert loss == pytest.approx(loss_sync, rel=1e-6)
            jax.tree_util.tree_map(
                lambda a, b: np.testing.assert_allclose(
                    np.asarray(a), np.asarray(b), rtol=1e-6),
                params, params_sync)
        else:
            assert np.isfinite(loss)

"""Model-free self-speculative decoding (ISSUE 19): greedy bit-parity
vs the plain ``generate`` golden and the spec-off engine across pipeline
depths × prefix cache on/off × mixed dispatch on/off (including a spec
row sharing a radix prefix with a live chunked admission), the adaptive
draft-length backoff unit, zero-match degradation to plain decode, the
disabled-mode structural absence of the ``bigdl.llm.spec.enabled`` gate
and the O(k-buckets) compile-grid invariant over a replay.

The hard bar everything here leans on: acceptance is greedy EXACTNESS
(``kernels.sampling.spec_accept`` keeps only the draft prefix that
matches the verify chunk's own argmaxes), so speculative output must be
bit-identical to the non-speculative engine no matter how the proposer
behaves — a diverging token is a bug in the engine, never "speculation
noise".
"""

import numpy as np
import pytest

from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import LLMServer
from bigdl_tpu.llm.spec import NGramProposer

pytestmark = pytest.mark.spec

PAGE = 8


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=256)


def _generate(model, p, n):
    return list(map(int, model.generate(
        np.asarray(p)[None], max_new_tokens=n)[0, len(p):]))


def _serve(model, prompts, lens, *, spec, max_seq_len=128, **kw):
    srv = LLMServer(model, max_batch=2, max_seq_len=max_seq_len,
                    page_size=PAGE, ragged_prefill=True, spec=spec,
                    **kw).start()
    try:
        got = [list(map(int, r.get(timeout=600))) for r in
               [srv.submit(p, max_new_tokens=n)
                for p, n in zip(prompts, lens)]]
        return got, srv
    finally:
        srv.stop()


def _workload():
    """One prompt whose greedy CONTINUATION falls into a short cycle
    (seed 42 — what must repeat for prompt-lookup to draft is the
    output, not just the prompt) plus a short non-repetitive one, so
    every pass mixes a speculating row with a plain-decode row."""
    rs = np.random.RandomState(42)
    pattern = rs.randint(0, 250, 5).astype(np.int32)
    prompts = [np.tile(pattern, 6).astype(np.int32),     # 30 toks
               rs.randint(0, 250, 7).astype(np.int32)]
    return prompts, [24, 6]


# goldens computed once; the spec-off engine's own parity vs generate
# is the PR 4/8 proven matrix, so generate() is the single reference
_GOLDEN = {}


def _golden(model):
    if not _GOLDEN:
        prompts, lens = _workload()
        _GOLDEN["want"] = [_generate(model, p, n)
                           for p, n in zip(prompts, lens)]
    return _GOLDEN["want"]


class TestEngineParity:
    """The acceptance matrix: speculative outputs bit-identical to the
    golden with speculation genuinely engaged (drafts accepted, not
    just proposed)."""

    @pytest.mark.parametrize("kvcache,depth", [
        pytest.param(True, 1), pytest.param(True, 2),
        pytest.param(True, 4), pytest.param(False, 1),
        pytest.param(False, 2), pytest.param(False, 4)])
    def test_spec_parity_vs_golden(self, model, depth, kvcache):
        prompts, lens = _workload()
        want = _golden(model)
        got, srv = _serve(model, prompts, lens, spec=True, spec_k=8,
                          kvcache=kvcache, pipeline_depth=depth)
        for j, (g, w) in enumerate(zip(got, want)):
            assert g == w, f"request {j}: spec-on vs golden diverged"
        assert srv.spec_passes > 0, "speculation never engaged"
        assert srv.spec_accepted_total > 0, \
            "no draft ever accepted — the workload is not repetitive " \
            "enough to exercise the accept path"
        # the ledgers are consistent: every pass emits its bonus token
        # plus the accepted drafts, never more than it proposed
        assert srv.spec_emitted_total == \
            srv.spec_passes + srv.spec_accepted_total
        assert srv.spec_accepted_total <= srv.spec_proposed_total

    @pytest.mark.parametrize("depth", [1, 2])
    def test_spec_with_mixed_chunked_admission(self, model, depth):
        """A spec row sharing its radix prefix with a LIVE chunked
        admission: the long prompt extends the speculating row's chain
        in the radix index while that row is mid-flight, so chunk
        passes, COW adoption and speculative verifies interleave over
        the same pages — outputs must still match the goldens."""
        prompts, lens = _workload()
        rs = np.random.RandomState(7)
        long = np.concatenate(
            [prompts[0], rs.randint(0, 250, 17).astype(np.int32)])
        want = _golden(model) + [_generate(model, long, 4)]
        srv = LLMServer(model, max_batch=2, max_seq_len=128,
                        page_size=PAGE, ragged_prefill=True, spec=True,
                        spec_k=8, kvcache=True, mixed=True,
                        chunk_tokens=PAGE, num_pages=64,
                        pipeline_depth=depth).start()
        try:
            stream = srv.submit(prompts[0], max_new_tokens=lens[0])
            others = [srv.submit(p, max_new_tokens=n) for p, n in
                      [(prompts[1], lens[1]), (long, 4)]]
            got = [list(map(int, r.get(timeout=600)))
                   for r in [stream] + others]
            assert got == want
            assert srv.spec_passes > 0
            assert srv.prefill_chunks_total > 0, \
                "the long admission never chunked"
        finally:
            srv.stop()

    def test_zero_match_degrades_to_plain_decode(self, model):
        """A workload the proposer cannot draft for: spec-on output is
        bit-identical to spec-off, and passes that found no match paid
        nothing (plain decode ticks, no verify dispatches beyond what
        the generated history genuinely supported)."""
        rs = np.random.RandomState(1)
        prompts = [rs.randint(0, 250, 9).astype(np.int32),
                   rs.randint(0, 250, 13).astype(np.int32)]
        lens = [8, 8]
        off, _ = _serve(model, prompts, lens, spec=False,
                        pipeline_depth=2)
        on, srv = _serve(model, prompts, lens, spec=True, spec_k=8,
                         pipeline_depth=2)
        assert on == off
        # every speculative pass that DID run still reconciles
        assert srv.spec_emitted_total == \
            srv.spec_passes + srv.spec_accepted_total


class TestAdaptiveK:
    def test_backoff_halves_and_recovery_regrows(self):
        prop = NGramProposer(k=8, min_match=2, backoff=0.5)
        assert prop.k_live == 8
        # sustained rejection: EMA sinks below the backoff floor and
        # k_live halves per observation — but never below 2, because a
        # 1-token proposal carries zero drafts (the engine consumes
        # proposal[1:]) and speculation could never observe a recovery
        for _ in range(8):
            prop.observe(proposed=prop.k_live, accepted=0)
        assert prop.k_live == 2
        assert prop.acc_ema < 0.5
        # sustained acceptance: EMA recovers past the midpoint and
        # k_live climbs one step per verify back to the ceiling
        for _ in range(16):
            prop.observe(proposed=prop.k_live, accepted=prop.k_live)
        assert prop.k_live == 8
        assert prop.accept_rate < 1.0     # lifetime rate remembers both

    def test_propose_follows_the_cycle(self):
        prop = NGramProposer(k=4, min_match=2)
        ids = [1, 2, 3, 4, 1, 2, 3, 4, 1, 2]
        # suffix [1, 2] recurred at positions 4..5 -> draft what
        # followed there: [3, 4, 1, 2]
        assert prop.propose(ids) == [3, 4, 1, 2]
        assert prop.last_match >= 2
        assert prop.propose(ids, limit=2) == [3, 4]
        # a constant run: the most recent occurrence is one token from
        # the end with nothing after it — the proposer must fall back
        # to an earlier occurrence that can supply real drafts (two
        # are available in a run this short; a 1-token proposal would
        # be worthless, the engine consumes proposal[1:])
        assert prop.propose([9, 7, 7, 7, 7, 7]) == [7, 7]

    def test_propose_no_match_is_empty(self):
        prop = NGramProposer(k=4, min_match=2)
        assert prop.propose([1, 2, 3, 4, 5, 6, 7]) == []
        assert prop.propose([1, 2]) == []       # too short to match
        assert prop.propose([], limit=4) == []


class TestGateAbsence:
    def test_disabled_mode_structural_absence(self, model):
        """``bigdl.llm.spec.enabled`` defaults off: the default engine
        must carry NO speculative state — no proposer slots, no pending
        set entries, no spec step cache entries, and none of the
        ``bigdl_llm_spec_*`` series even with observability on."""
        from bigdl_tpu import observability as obs
        from bigdl_tpu.utils.conf import conf
        assert conf.get_bool("bigdl.llm.spec.enabled", True) is False, \
            "the bigdl.llm.spec.enabled gate must default off"
        prompts, lens = _workload()
        series_names = ("bigdl_llm_spec_proposed_tokens_total",
                        "bigdl_llm_spec_accepted_tokens_total",
                        "bigdl_llm_spec_passes_total")

        def samples(text, name):
            return sorted(l for l in text.splitlines()
                          if l.startswith(name + "{")
                          or l.startswith(name + " "))

        was = obs.enabled()
        obs.enable()
        try:
            before = obs.render()   # process-global registry: other
            # tests may have minted the series — the absence contract
            # is a ZERO DELTA from this server
            srv = LLMServer(model, max_batch=2, max_seq_len=64,
                            page_size=PAGE, ragged_prefill=True,
                            kvcache=True).start()
            try:
                assert srv._spec_active is False
                assert srv._spec_state is None
                assert srv._spec_pending == set()
                for p in prompts:
                    srv.submit(p, max_new_tokens=3).get(timeout=600)
                assert srv.spec_passes == 0
                assert srv.spec_proposed_total == 0
            finally:
                srv.stop()
            after = obs.render()
            for series in series_names:
                assert samples(after, series) == samples(before, series)
        finally:
            if not was:
                obs.disable()

    def test_spec_is_greedy_and_paged_only(self, model):
        with pytest.raises(ValueError, match="greedy-only"):
            LLMServer(model, max_batch=1, max_seq_len=64,
                      page_size=PAGE, ragged_prefill=True, spec=True,
                      temperature=0.7)
        with pytest.raises(ValueError, match="page-pool only"):
            LLMServer(model, max_batch=1, max_seq_len=64, paged=False,
                      spec=True)


class TestCompileGrid:
    def test_spec_replay_compiles_zero_new_programs(self, model):
        """The spec step's compile grid is O(k-buckets): verify chunks
        pad to the pow2 bucket of ``n_draft + 1``, and the row index,
        offset, drafts and block tables are runtime data — so replaying
        the same workload (fresh request, fresh proposer, identical
        deterministic trajectory at depth 1) adds ZERO new programs
        once the buckets are warm."""
        from bigdl_tpu import observability as obs
        from bigdl_tpu.llm import serving as sv
        prompts, lens = _workload()

        def keys(tag):
            return {k for k in sv._PAGED_STEP_CACHE if tag in k}

        def compiles(fn_name):
            return sum(s["compiles"] for s in obs.compile_stats()
                       if s["fn"] == fn_name)

        was = obs.enabled()
        obs.enable()
        spec_before = keys("spec")
        srv = LLMServer(model, max_batch=2, max_seq_len=128,
                        page_size=PAGE, ragged_prefill=True, spec=True,
                        spec_k=8, pipeline_depth=1).start()
        try:
            for p, n in zip(prompts, lens):
                srv.submit(p, max_new_tokens=n).get(timeout=600)
            assert srv.spec_passes > 0
            warm_keys = keys("spec")
            warm_compiles = compiles("llm/step_spec")
            passes0 = srv.spec_passes
            for p, n in zip(prompts, lens):
                srv.submit(p, max_new_tokens=n).get(timeout=600)
            assert srv.spec_passes > passes0    # it speculated again
            assert keys("spec") == warm_keys
            assert compiles("llm/step_spec") == warm_compiles
            # the whole grid is the pow2 draft-bucket ladder: with
            # k=8 that is at most {2, 4, 8, 16} wide
            assert len(warm_keys - spec_before) <= 4
        finally:
            srv.stop()
            if not was:
                obs.disable()

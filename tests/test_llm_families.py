"""Round-5 model families — Bloom (ALiBi), StarCoder (MQA), ChatGLM/GLM
(interleaved partial rotary on the Llama stack). The reference ships
five ggml families (P:llm/ggml/model/, SURVEY.md §2.8 row 65); with
these the repo covers all five plus the transformers-path lineages.
Each family gets (a) an HF numerics cross-check through the public
AutoModelForCausalLM facade and (b) a quantized-generate smoke."""

import numpy as np
import pytest

import jax.numpy as jnp


def _save_hf(tmp_path, hf_model, name):
    path = str(tmp_path / name)
    hf_model.eval()
    hf_model.save_pretrained(path, safe_serialization=True)
    return path


class TestBloom:
    def _tiny_hf(self):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        cfg = transformers.BloomConfig(
            vocab_size=97, hidden_size=32, n_layer=2, n_head=4,
            use_cache=False)
        torch.manual_seed(0)
        return torch, transformers.BloomForCausalLM(cfg)

    def test_matches_hf_bloom_numerics(self, tmp_path):
        torch, hf = self._tiny_hf()
        path = _save_hf(tmp_path, hf, "tiny-bloom")
        from bigdl_tpu.llm.models.bloom import BloomForCausalLM
        from bigdl_tpu.llm.transformers import AutoModelForCausalLM
        model = AutoModelForCausalLM.from_pretrained(path, max_cache_len=32)
        assert isinstance(model, BloomForCausalLM)
        ids = np.array([[3, 17, 42, 9, 60]], np.int64)
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.float().numpy()
        logits, _ = model(jnp.asarray(ids, jnp.int32))
        ours = np.asarray(logits)
        np.testing.assert_allclose(ours, ref, rtol=0.1, atol=0.1)
        assert (np.argmax(ours[:, -1], -1)
                == np.argmax(ref[:, -1], -1)).all()

    def test_alibi_slopes_match_hf(self):
        torch = pytest.importorskip("torch")
        from transformers.models.bloom.modeling_bloom import (
            build_alibi_tensor)
        from bigdl_tpu.llm.models.bloom import alibi_slopes
        for n in (4, 8, 6, 12):   # powers of 2 and not
            mask = torch.ones(1, 5)
            al = build_alibi_tensor(mask, n, torch.float32)
            # hf alibi (1*n, 1, 5): slope = al[h, 0, 1] (key index 1)
            hf_slopes = al.reshape(n, 5)[:, 1].numpy()
            np.testing.assert_allclose(alibi_slopes(n), hf_slopes,
                                       rtol=1e-6)

    def test_quantized_generate(self):
        from bigdl_tpu.llm.models.bloom import (BloomConfig,
                                                BloomForCausalLM)
        import dataclasses
        cfg = dataclasses.replace(BloomConfig.tiny(), hidden_size=256,
                                  num_attention_heads=2)
        model = BloomForCausalLM.from_config(cfg, seed=0,
                                             load_in_low_bit="sym_int4",
                                             max_cache_len=32)
        lp = model.params["layers"]["q_proj"]
        assert "q" in lp and "scale" in lp
        out = model.generate(np.array([[1, 5, 9]], np.int32),
                             max_new_tokens=6)
        assert out.shape == (1, 9)

    def test_prefill_decode_consistency(self):
        """ALiBi positions must agree between one-shot prefill and
        step-wise decode (the shift-invariant bias form)."""
        from bigdl_tpu.llm.models.bloom import (BloomConfig, forward,
                                                init_cache, init_params)
        cfg = BloomConfig.tiny()
        params = init_params(cfg, seed=0, dtype=jnp.float32)
        toks = np.array([[5, 9, 3, 7]], np.int32)
        cache = init_cache(cfg, 1, 16, dtype=jnp.float32)
        pos = jnp.arange(4)[None, :]
        full, _ = forward(params, cfg, jnp.asarray(toks), cache, pos)
        cache = init_cache(cfg, 1, 16, dtype=jnp.float32)
        outs = []
        for t in range(4):
            lg, cache = forward(params, cfg,
                                jnp.asarray(toks[:, t:t + 1]), cache,
                                jnp.asarray([[t]]))
            outs.append(np.asarray(lg[:, 0]))
        np.testing.assert_allclose(np.asarray(full), np.stack(outs, 1),
                                   rtol=2e-2, atol=2e-2)


class TestStarCoder:
    def test_matches_hf_gpt_bigcode_numerics(self, tmp_path):
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        cfg = transformers.GPTBigCodeConfig(
            vocab_size=97, n_embd=32, n_layer=2, n_head=4,
            n_positions=64, multi_query=True, use_cache=False)
        torch.manual_seed(0)
        hf = transformers.GPTBigCodeForCausalLM(cfg)
        path = _save_hf(tmp_path, hf, "tiny-bigcode")
        from bigdl_tpu.llm.models.starcoder import StarCoderForCausalLM
        from bigdl_tpu.llm.transformers import AutoModelForCausalLM
        model = AutoModelForCausalLM.from_pretrained(path, max_cache_len=32)
        assert isinstance(model, StarCoderForCausalLM)
        assert model.config.num_key_value_heads == 1   # MQA
        ids = np.array([[3, 17, 42, 9, 60]], np.int64)
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.float().numpy()
        logits, _ = model(jnp.asarray(ids, jnp.int32))
        ours = np.asarray(logits)
        np.testing.assert_allclose(ours, ref, rtol=0.1, atol=0.1)
        assert (np.argmax(ours[:, -1], -1)
                == np.argmax(ref[:, -1], -1)).all()

    def test_quantized_generate(self):
        from bigdl_tpu.llm.models.starcoder import (StarCoderConfig,
                                                    StarCoderForCausalLM)
        import dataclasses
        cfg = dataclasses.replace(StarCoderConfig.tiny(), hidden_size=256,
                                  intermediate_size=256,
                                  num_attention_heads=2)
        model = StarCoderForCausalLM.from_config(
            cfg, seed=0, load_in_low_bit="sym_int4", max_cache_len=32)
        assert "q" in model.params["layers"]["q_proj"]
        # MQA k/v (head_dim=128, h) quantize too at this size
        assert "q" in model.params["layers"]["k_proj"]
        out = model.generate(np.array([[1, 5, 9]], np.int32),
                             max_new_tokens=6)
        assert out.shape == (1, 9)


class TestFamilyServing:
    """Round-5 tail: the paged continuous-batching LLMServer dispatches
    per family — GPT-NeoX and StarCoder get their own paged decode
    steps (same read-only-pool scan structure); Bloom is rejected with
    a clear error (ALiBi has no paged-kernel bias hook yet)."""

    @pytest.mark.parametrize("family", ["gptneox", "gptneox-seq",
                                        "starcoder"])
    def test_paged_server_greedy_parity(self, family):
        import dataclasses
        from bigdl_tpu.llm.serving import LLMServer
        if family.startswith("gptneox"):
            from bigdl_tpu.llm.models import (GptNeoXConfig as C,
                                              GptNeoXForCausalLM as M)
            cfg = C.tiny()
            if family == "gptneox-seq":
                # sequential-residual NeoX (early StableLM lineage):
                # pins the use_parallel_residual=False paged branch
                cfg = dataclasses.replace(cfg,
                                          use_parallel_residual=False)
        else:
            from bigdl_tpu.llm.models import (StarCoderConfig as C,
                                              StarCoderForCausalLM as M)
            cfg = C.tiny()
        model = M.from_config(cfg, seed=0, max_cache_len=64)
        prompt = [7, 3, 11, 2]
        want = model.generate(np.asarray([prompt], np.int32),
                              max_new_tokens=8)[0, len(prompt):]
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        try:
            got = srv.submit(prompt, max_new_tokens=8).get(180)
            # a second, different-length request through the same server
            got2 = srv.submit([5, 9], max_new_tokens=4).get(180)
        finally:
            srv.stop()
        assert list(got) == list(map(int, want))
        want2 = model.generate(np.asarray([[5, 9]], np.int32),
                               max_new_tokens=4)[0, 2:]
        assert list(got2) == list(map(int, want2))

    def test_bloom_serving_rejected_with_clear_error(self):
        from bigdl_tpu.llm.models import BloomConfig, BloomForCausalLM
        from bigdl_tpu.llm.serving import LLMServer
        model = BloomForCausalLM.from_config(BloomConfig.tiny(), seed=0,
                                             max_cache_len=32)
        with pytest.raises(NotImplementedError, match="paged decode"):
            LLMServer(model)


class TestChatGLM:
    def test_matches_hf_glm_numerics(self, tmp_path):
        """GLM-4 (HF ``glm``) is the transformers-native ChatGLM lineage:
        interleaved partial rotary + GQA + qkv biases + fused gate_up —
        implemented as a LlamaConfig rope_mode='glm' variant."""
        torch = pytest.importorskip("torch")
        transformers = pytest.importorskip("transformers")
        cfg = transformers.GlmConfig(
            vocab_size=97, hidden_size=32, intermediate_size=64,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, head_dim=8, partial_rotary_factor=0.5,
            attention_bias=True, max_position_embeddings=64,
            tie_word_embeddings=False, use_cache=False,
            pad_token_id=0, eos_token_id=1)
        torch.manual_seed(0)
        hf = transformers.GlmForCausalLM(cfg)
        path = _save_hf(tmp_path, hf, "tiny-glm")
        from bigdl_tpu.llm.models.llama import LlamaForCausalLM
        from bigdl_tpu.llm.transformers import AutoModelForCausalLM
        model = AutoModelForCausalLM.from_pretrained(path, max_cache_len=32)
        assert isinstance(model, LlamaForCausalLM)
        assert model.config.rope_mode == "glm"
        assert model.config.partial_rotary_factor == 0.5
        ids = np.array([[3, 17, 42, 9, 60]], np.int64)
        with torch.no_grad():
            ref = hf(torch.tensor(ids)).logits.float().numpy()
        logits, _ = model(jnp.asarray(ids, jnp.int32))
        ours = np.asarray(logits)
        np.testing.assert_allclose(ours, ref, rtol=0.1, atol=0.1)
        assert (np.argmax(ours[:, -1], -1)
                == np.argmax(ref[:, -1], -1)).all()

    def test_glm_serves_on_the_paged_server(self):
        """The GLM rotary variant must ride the paged continuous-batching
        server unchanged (rope_cfg dispatch inside paged_decode_step):
        served greedy tokens == generate() greedy tokens."""
        from bigdl_tpu.llm.models.llama import (LlamaConfig,
                                                LlamaForCausalLM)
        from bigdl_tpu.llm.serving import LLMServer
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny_glm(),
                                             seed=0, max_cache_len=64)
        prompt = [7, 3, 11, 2]
        want = model.generate(np.asarray([prompt], np.int32),
                              max_new_tokens=8)[0, len(prompt):]
        srv = LLMServer(model, max_batch=2, max_seq_len=32).start()
        try:
            got = srv.submit(prompt, max_new_tokens=8).get(120)
        finally:
            srv.stop()
        assert list(got) == list(want)

    def test_quantized_generate(self):
        from bigdl_tpu.llm.models.llama import (LlamaConfig,
                                                LlamaForCausalLM)
        import dataclasses
        cfg = dataclasses.replace(LlamaConfig.tiny_glm(), hidden_size=256,
                                  intermediate_size=256,
                                  num_attention_heads=2,
                                  num_key_value_heads=2)
        model = LlamaForCausalLM.from_config(
            cfg, seed=0, load_in_low_bit="sym_int4", max_cache_len=32)
        out = model.generate(np.array([[1, 5, 9]], np.int32),
                             max_new_tokens=6)
        assert out.shape == (1, 9)

"""Request-level failover, hedged dispatch, and the engine watchdog
(ISSUE 7): journal/prober/hedge units, `LLMRouter._pick` edge cases
(the satellite matrix: breaker skipping, all-open shed, single-backend
pools, live pool mutation), deadline re-derivation on retries, live
mid-stream failover parity, and the disabled-mode structural-absence
contract.

Live-engine tests pre-warm every compiled shape before arming faults:
an XLA compile is indistinguishable from a hung step host-side, so an
unwarmed engine under a tight watchdog would trip on the compile, not
the injected stall (see LLMServer._watchdog_loop)."""

import http.client
import json
import threading
import time
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np
import pytest

from bigdl_tpu import observability as obs
from bigdl_tpu import reliability as rel
from bigdl_tpu.llm.failover import (Canceller, HealthProber, HedgePolicy,
                                    JournalEntry, LatencyTracker,
                                    RequestJournal, run_hedged)
from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
from bigdl_tpu.llm.serving import LLMServer
from bigdl_tpu.llm.worker import LLMRouter, LLMWorker
from bigdl_tpu.utils.conf import conf

pytestmark = pytest.mark.failover


@pytest.fixture(scope="module")
def model():
    return LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                        max_cache_len=128)


@pytest.fixture()
def faults_armed():
    """Reliability enabled for the test, restored after — later suites
    rely on the process-global default (plain ``disable()`` here would
    silently no-op every later ``set_plan``)."""
    was = rel.enabled()
    if not was:
        rel.enable()
    yield
    rel.set_plan(None)
    if not was:
        rel.disable()


def _generate(model, p, n):
    return model.generate(np.asarray(p)[None], max_new_tokens=n)[0, len(p):]


def _req(addr, method, path, body=None, headers=None, timeout=120):
    conn = http.client.HTTPConnection(*addr, timeout=timeout)
    try:
        payload = json.dumps(body) if body is not None else None
        conn.request(method, path, payload,
                     dict(headers or {},
                          **({"Content-Type": "application/json"}
                             if body is not None else {})))
        r = conn.getresponse()
        data = json.loads(r.read().decode())
        return r.status, data, dict(r.getheaders())
    finally:
        conn.close()


# ---------------------------------------------------------------------------
# units: journal, latency tracker, hedge policy, run_hedged, canceller
# ---------------------------------------------------------------------------

class TestRequestJournal:
    def test_entry_resume_state(self):
        j = RequestJournal()
        ent = j.add([1, 2, 3], max_new_tokens=5)
        assert ent.remaining == 5
        ent.drained([10, 11])
        assert ent.remaining == 3
        # cumulative re-delivery (a hedge twin behind the winner) is a
        # no-op, never a duplicate append
        ent.drained([10])
        ent.drained([10, 11])
        assert ent.tokens == [10, 11]
        # the re-dispatch prompt: original prompt + everything drained
        assert ent.resume_prompt() == [1, 2, 3, 10, 11]
        assert j.inflight() == 1
        j.record_failover(ent)
        assert j.failovers == 1 and j.tokens_resumed == 2
        j.complete(ent)
        assert j.inflight() == 0 and j.completed == 1
        # snapshot of an empty journal is empty (healthz body)
        assert j.snapshot() == []

    def test_snapshot_fields(self):
        j = RequestJournal()
        ent = j.add([1], 4)
        ent.drained([9])
        (snap,) = j.snapshot()
        assert snap["tokens_drained"] == 1
        assert snap["prompt_tokens"] == 1


class TestLatencyTracker:
    def test_quantile_empty_and_window(self):
        t = LatencyTracker(maxlen=4)
        assert t.quantile() is None
        for v in (1.0, 2.0, 3.0, 4.0, 100.0):   # 1.0 rolls out
            t.record(v)
        assert len(t) == 4
        assert t.quantile(0.95) == 100.0
        assert t.quantile(0.0) == 2.0


class TestHedgePolicy:
    def test_disabled_never_allows(self):
        p = HedgePolicy(enabled=False)
        assert not p.allow()

    def test_budget_caps_hedges(self):
        p = HedgePolicy(enabled=True, budget=0.5)
        p.note_request()
        p.note_request()
        # cap = 0.5 * 2 + 1 = 2 hedges
        assert p.allow()
        p.note_hedge()
        assert p.allow()
        p.note_hedge()
        assert not p.allow()

    def test_delay_pinned_vs_p95(self):
        t = LatencyTracker()
        pinned = HedgePolicy(enabled=True, delay_ms=7.0)
        assert pinned.delay_for(t) == pytest.approx(0.007)
        derived = HedgePolicy(enabled=True, min_delay_ms=50.0)
        # no samples -> the floor
        assert derived.delay_for(t) == pytest.approx(0.05)
        t.record(0.2)
        assert derived.delay_for(t) == pytest.approx(0.2)
        # observed p95 under the floor -> floored
        t2 = LatencyTracker()
        t2.record(0.001)
        assert derived.delay_for(t2) == pytest.approx(0.05)


class TestRunHedged:
    def test_fast_primary_never_hedges(self):
        launched = []
        out, outcome = run_hedged(
            lambda c: "fast", lambda c: launched.append(1) or "hedge",
            delay=0.2)
        assert out == "fast" and outcome == "primary"
        assert not launched

    def test_hedge_wins_and_primary_cancelled(self):
        release = threading.Event()
        cancelled = []

        def slow_primary(c):
            cancelled.append(c)
            release.wait(5.0)
            return "slow"

        out, outcome = run_hedged(slow_primary, lambda c: "hedge",
                                  delay=0.01)
        assert out == "hedge" and outcome == "hedge_won"
        assert cancelled[0].cancelled   # the straggler was cancelled
        release.set()

    def test_primary_won_after_hedge_launched(self):
        gate = threading.Event()

        def primary(c):
            gate.wait(5.0)
            return "primary"

        def hedge(c):
            gate.set()            # primary finishes the moment we start
            time.sleep(0.2)
            return "hedge"

        out, outcome = run_hedged(primary, hedge, delay=0.01)
        assert out == "primary" and outcome == "primary_won"

    def test_fast_failure_is_not_hedged(self):
        """A primary that FAILS before the delay propagates: hedging
        tames stragglers, failover handles failures."""
        launched = []

        def bad(c):
            raise RuntimeError("boom")

        with pytest.raises(RuntimeError, match="boom"):
            run_hedged(bad, lambda c: launched.append(1) or "x",
                       delay=0.5)
        assert not launched

    def test_both_fail_raises_last(self):
        def bad(c):
            time.sleep(0.05)
            raise RuntimeError("dead")

        with pytest.raises(RuntimeError, match="dead"):
            run_hedged(bad, bad, delay=0.01)

    def test_both_fail_prefers_verdict_errors(self):
        """A backend's relay-worthy verdict (4xx/shed, modeled here
        as ValueError) must not be masked by the twin's LATER
        transport error — the router relays verdicts but burns
        failover attempts on transport errors."""
        def fatal_fast(c):
            raise ValueError("403 from backend")

        def transport_slow(c):
            time.sleep(0.1)
            raise RuntimeError("conn torn")

        with pytest.raises(ValueError, match="403"):
            run_hedged(transport_slow, fatal_fast, delay=0.0,
                       prefer=(ValueError,))
        # without prefer= the temporally-last error still wins
        with pytest.raises(RuntimeError, match="torn"):
            run_hedged(transport_slow, fatal_fast, delay=0.0)

    def test_hedge_callback_fires(self):
        fired = []
        out, outcome = run_hedged(
            lambda c: time.sleep(0.1) or "a", lambda c: "b",
            delay=0.01, on_hedge=lambda: fired.append(1))
        assert fired == [1]
        assert outcome in ("primary_won", "hedge_won")


class TestCanceller:
    class _Conn:
        closed = False

        def close(self):
            self.closed = True

    def test_cancel_closes_attached(self):
        c = Canceller()
        conn = self._Conn()
        c.attach(conn)
        c.cancel()
        assert conn.closed and c.cancelled

    def test_attach_after_cancel_closes_immediately(self):
        c = Canceller()
        c.cancel()
        conn = self._Conn()
        c.attach(conn)
        assert conn.closed


# ---------------------------------------------------------------------------
# derived Retry-After (satellite)
# ---------------------------------------------------------------------------

class TestRetryAfter:
    def test_scales_with_depth_and_clamps(self):
        import random
        rng = random.Random(0)
        conf.set("bigdl.llm.retry_after.jitter", "0")
        try:
            assert rel.retry_after_seconds(0, rng) == "1"
            assert rel.retry_after_seconds(8, rng) == "3"   # 1 + .25*8
            assert rel.retry_after_seconds(10_000, rng) == "30"  # cap
        finally:
            conf.unset("bigdl.llm.retry_after.jitter")

    def test_jitter_bounded_and_depth0_compat(self):
        import random
        # depth 0 with default knobs must still render "1" for every
        # jitter draw (base 1.0 stretched < 1.2 rounds to 1): existing
        # clients see no change until pressure builds
        for seed in range(20):
            assert rel.retry_after_seconds(0, random.Random(seed)) == "1"
        vals = {int(rel.retry_after_seconds(8, random.Random(s)))
                for s in range(20)}
        assert vals <= {3, 4} and len(vals) >= 1   # jittered upward only

    def test_cap_jitters_downward(self):
        """At saturation the jitter spreads BELOW the cap — stretching
        upward and clamping would hand every shed client exactly the
        cap, re-synchronizing the herd at the deepest backlog."""
        import random
        vals = {int(rel.retry_after_seconds(10_000, random.Random(s)))
                for s in range(30)}
        assert max(vals) <= 30
        assert min(vals) >= 24          # cap * (1 - jitter)
        assert len(vals) > 1            # the herd actually spreads


# ---------------------------------------------------------------------------
# health prober
# ---------------------------------------------------------------------------

class TestHealthProber:
    def test_probe_live_and_dead(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        page_size=8).start()
        w = LLMWorker(srv).start()
        dead = ("127.0.0.1", 1)
        seen = []
        try:
            prober = HealthProber(
                lambda: [(w.address, "decode"), (dead, "decode")],
                timeout=2.0,
                on_probe=lambda a, r, h, b: seen.append((a, h)))
            # unprobed backends default healthy (a just-added member
            # must be routable before the first sweep)
            assert prober.healthy(w.address) and prober.healthy(dead)
            prober.probe_now()
            assert prober.healthy(w.address)
            assert not prober.healthy(dead)
            assert prober.status()[f"{dead[0]}:{dead[1]}"] is False
            assert dict(seen)[w.address] is True
            prober.forget(dead)
            assert prober.healthy(dead)   # back to the default
        finally:
            w.stop()
            srv.stop()


# ---------------------------------------------------------------------------
# LLMRouter._pick edge cases (satellite)
# ---------------------------------------------------------------------------

def _open_breaker(router, addr):
    b = router._breakers[addr]
    while b.state != "open":
        b.record_failure()


class TestRouterPick:
    def _router(self, n_decode=3, **kw):
        decode = [("127.0.0.1", 10_000 + i) for i in range(n_decode)]
        return LLMRouter([], decode, start_prober=False, **kw)

    def test_round_robin_skips_open_breakers(self):
        r = self._router(3)
        try:
            a, b, c = r.decode_workers
            _open_breaker(r, b)
            picks = [r._pick("decode") for _ in range(4)]
            assert b not in picks
            assert picks == [a, c, a, c]   # rotation continues past b
        finally:
            r.stop()

    def test_all_open_returns_none(self):
        r = self._router(2)
        try:
            for addr in r.decode_workers:
                _open_breaker(r, addr)
            assert r._pick("decode") is None
        finally:
            r.stop()

    def test_single_backend_pool(self):
        r = self._router(1)
        try:
            (only,) = r.decode_workers
            assert r._pick("decode") == only
            assert r._pick("decode") == only
            _open_breaker(r, only)
            assert r._pick("decode") is None
            # empty prefill pool never yields a backend
            assert r._pick("prefill") is None
        finally:
            r.stop()

    def test_exclude_is_soft(self):
        """Excluding every live backend must fall back to retrying
        them, not fail the request outright."""
        r = self._router(2)
        try:
            a, b = r.decode_workers
            assert r._pick("decode", exclude={a}) == b
            assert r._pick("decode", exclude={a, b}) in (a, b)
        finally:
            r.stop()

    def test_prober_unhealthy_skipped(self):
        r = self._router(2, failover=True)
        try:
            a, b = r.decode_workers
            with r._prober._lock:
                r._prober._status[a] = False
            assert r._pick("decode") == b
            assert r._pick("decode") == b
            with r._prober._lock:
                r._prober._status[a] = True
            assert a in {r._pick("decode"), r._pick("decode")}
        finally:
            r.stop()

    def test_pool_mutation_mid_stream(self):
        """The admin surface mutates pools under _pick's lock: a new
        member is picked immediately, a removed one never again, and
        the last decode backend is protected."""
        r = self._router(1, failover=True)
        try:
            (orig,) = r.decode_workers
            added = ("127.0.0.1", 10_099)
            code, out = r._admin_backends(
                {"action": "add", "role": "decode",
                 "host": added[0], "port": added[1]})
            assert code == 200 and len(out["decode_workers"]) == 2
            assert added in r._breakers
            picks = {r._pick("decode") for _ in range(4)}
            assert picks == {orig, added}
            code, _ = r._admin_backends(
                {"action": "remove", "role": "decode",
                 "host": orig[0], "port": orig[1]})
            assert code == 200
            assert all(r._pick("decode") == added for _ in range(3))
            assert orig not in r._breakers   # breaker GC'd with it
            with pytest.raises(ValueError, match="last"):
                r._admin_backends(
                    {"action": "remove", "role": "decode",
                     "host": added[0], "port": added[1]})
        finally:
            r.stop()

    def test_admin_validates(self):
        r = self._router(1, failover=True)
        try:
            with pytest.raises(ValueError):
                r._admin_backends({"action": "nope", "role": "decode"})
            with pytest.raises(ValueError):
                r._admin_backends({"action": "add", "role": "router"})
        finally:
            r.stop()


# ---------------------------------------------------------------------------
# router HTTP surfaces: all-open shed, healthz body, admin endpoint
# ---------------------------------------------------------------------------

class TestRouterSurfaces:
    def test_all_backends_open_sheds_503_with_retry_after(self):
        dead = [("127.0.0.1", 1), ("127.0.0.1", 2)]
        r = LLMRouter([], dead, start_prober=False).start()
        try:
            for addr in dead:
                _open_breaker(r, addr)
            st, body, hdrs = _req(r.address, "POST", "/worker_generate",
                                  {"prompt_ids": [1, 2],
                                   "max_new_tokens": 2})
            assert st == 503
            assert int(hdrs["Retry-After"]) >= 1
            # healthz mirrors the dead pool BEFORE any request fails
            # (satellite): per-backend breaker states in the body
            st, hz, _ = _req(r.address, "GET", "/healthz")
            assert st == 503
            assert set(hz["backends"].values()) == {"open"}
        finally:
            r.stop()

    def test_healthz_includes_prober_and_journal(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        page_size=8).start()
        w = LLMWorker(srv, role="decode").start()
        r = LLMRouter([], [w.address], failover=True,
                      start_prober=False).start()
        try:
            r._prober.probe_now()
            st, hz, _ = _req(r.address, "GET", "/healthz")
            assert st == 200
            key = f"{w.address[0]}:{w.address[1]}"
            assert hz["backends"][key] == "closed"
            assert hz["prober"][key] is True
            assert hz["journal_inflight"] == 0
            assert hz["failovers"] == 0
        finally:
            r.stop()
            w.stop()
            srv.stop()

    def test_admin_endpoint_requires_failover(self):
        r = LLMRouter([], [("127.0.0.1", 1)], start_prober=False).start()
        try:
            st, _, _ = _req(r.address, "POST", "/backends",
                            {"action": "add", "role": "decode",
                             "host": "127.0.0.1", "port": 2})
            assert st == 404   # PR 6 router had no such surface
        finally:
            r.stop()

    def test_admin_endpoint_over_http(self):
        r = LLMRouter([], [("127.0.0.1", 1)], failover=True,
                      start_prober=False).start()
        try:
            st, out, _ = _req(r.address, "POST", "/backends",
                              {"action": "add", "role": "decode",
                               "host": "127.0.0.1", "port": 2})
            assert st == 200 and len(out["decode_workers"]) == 2
            st, ws, _ = _req(r.address, "GET", "/worker_get_status")
            assert len(ws["decode_pool"]) == 2
        finally:
            r.stop()


# ---------------------------------------------------------------------------
# deadline re-derivation on retries (satellite)
# ---------------------------------------------------------------------------

class _RecordingBackend:
    """Stub decode worker: records each attempt's deadline header,
    burns a little budget, then fails the stream so the router
    retries."""

    def __init__(self):
        self.deadlines = []
        backend = self

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                backend.deadlines.append(
                    self.headers.get(rel.DEADLINE_HEADER))
                time.sleep(0.05)          # burn budget between attempts
                body = json.dumps({"error": "injected 500"}).encode()
                self.send_response(500)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.address = self.httpd.server_address
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TestDeadlineRederivation:
    def test_each_attempt_sees_remaining_budget(self):
        be = _RecordingBackend()
        r = LLMRouter([], [be.address], failover=True,
                      failover_attempts=3, start_prober=False,
                      breaker_threshold=10).start()
        try:
            st, body, _ = _req(
                r.address, "POST", "/worker_generate",
                {"prompt_ids": [1, 2], "max_new_tokens": 2},
                headers={rel.DEADLINE_HEADER: "5000"})
            assert st == 502    # every attempt failed
            got = [int(d) for d in be.deadlines]
            assert len(got) == 3
            # strictly shrinking, never the original value relayed
            assert got[0] <= 5000
            assert got[1] < got[0] and got[2] < got[1]
            assert got[0] - got[2] >= 90   # two 50 ms sleeps burned
        finally:
            r.stop()
            be.stop()

    def test_expired_deadline_stops_routing(self):
        be = _RecordingBackend()
        r = LLMRouter([], [be.address], failover=True,
                      failover_attempts=10, start_prober=False,
                      breaker_threshold=100).start()
        try:
            st, body, _ = _req(
                r.address, "POST", "/worker_generate",
                {"prompt_ids": [1], "max_new_tokens": 2},
                headers={rel.DEADLINE_HEADER: "120"})
            assert st in (502, 504)
            if st == 504:
                assert "deadline" in body["error"]
            # the 120 ms budget permits at most ~2 of the 10 attempts
            assert len(be.deadlines) <= 3
        finally:
            r.stop()
            be.stop()


class _TimeoutStreamBackend:
    """Stub decode worker whose stream ends in a ``finish_reason:
    "timeout"`` terminal chunk — the silent-truncation verdict a worker
    emits when its stream wait expires on a wedged engine."""

    def __init__(self, tokens=()):
        self.hits = 0
        backend = self
        payload = (json.dumps(
            {"output_ids": list(tokens), "done": True,
             "finish_reason": "timeout"}) + "\n").encode()

        class H(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"

            def log_message(self, *a):
                pass

            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                self.rfile.read(n)
                backend.hits += 1
                self.send_response(200)
                self.send_header("Content-Length", str(len(payload)))
                self.end_headers()
                self.wfile.write(payload)

        self.httpd = ThreadingHTTPServer(("127.0.0.1", 0), H)
        self.address = self.httpd.server_address
        threading.Thread(target=self.httpd.serve_forever,
                         daemon=True).start()

    def stop(self):
        self.httpd.shutdown()
        self.httpd.server_close()


class TestTimeoutChunkFailsOver:
    def test_timeout_terminal_chunk_is_retriable(self, model):
        """A backend answering ``finish_reason: "timeout"`` (stream
        wait expired on a wedged engine) must be failed over, not
        relayed as a 200 with truncated/empty output — that silent
        empty answer is exactly the stalled-worker case the journal
        exists for."""
        prompt = list(range(5, 17))
        want = list(map(int, _generate(model, np.asarray(prompt,
                                                         np.int32), 4)))
        stub = _TimeoutStreamBackend()
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8).start()
        w = LLMWorker(srv, role="decode").start()
        r = LLMRouter([], [stub.address, w.address], failover=True,
                      start_prober=False).start()
        try:
            st, body, _ = _req(r.address, "POST", "/worker_generate",
                               {"prompt_ids": prompt,
                                "max_new_tokens": 4})
            assert stub.hits == 1           # round-robin hit the stub
            assert st == 200
            assert body["output_ids"] == want
            assert body["finish_reason"] != "timeout"
            assert r.failovers == 1
        finally:
            r.stop()
            w.stop()
            srv.stop()
            stub.stop()


# ---------------------------------------------------------------------------
# live failover: mid-stream worker death -> resume parity (tentpole)
# ---------------------------------------------------------------------------

class TestLiveFailover:
    def test_midstream_failure_resumes_bit_identical(self, model,
                                                     faults_armed):
        prompt = list(range(1, 21))
        want = list(map(int, _generate(model, np.asarray(prompt,
                                                         np.int32), 6)))
        s1 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                       kvcache=True).start()
        s2 = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                       kvcache=True).start()
        w1 = LLMWorker(s1, role="decode").start()
        w2 = LLMWorker(s2, role="decode").start()
        r = LLMRouter([], [w1.address, w2.address], failover=True,
                      start_prober=False).start()
        try:
            # failover-path routing with no faults armed
            st, body, _ = _req(r.address, "POST", "/worker_generate",
                               {"prompt_ids": prompt,
                                "max_new_tokens": 6})
            assert st == 200 and body["output_ids"] == want
            assert r.failovers == 0

            # mid-stream kill: the dispatch site raises after chunks
            # drained (llm.step slowed so chunks arrive one token at a
            # time -> the kill lands mid-generation deterministically)
            plan = rel.FaultPlan(seed=0)
            plan.add("router.dispatch", "raise", times=1, after=2)
            plan.add("llm.step", "delay", times=None, delay=0.03)
            rel.set_plan(plan)
            try:
                st, body, _ = _req(r.address, "POST",
                                   "/worker_generate",
                                   {"prompt_ids": prompt,
                                    "max_new_tokens": 6})
            finally:
                rel.set_plan(None)
            assert st == 200
            assert body["output_ids"] == want    # bit-identical resume
            assert r.failovers >= 1
            assert r.tokens_resumed >= 1         # resumed, not restarted
            st, hz, _ = _req(r.address, "GET", "/healthz")
            assert hz["failovers"] == r.failovers
        finally:
            r.stop()
            w1.stop()
            w2.stop()
            s1.stop()
            s2.stop()

    def test_hedged_decode_parity(self, model):
        """Hedge armed with a tiny pinned delay: the duplicate races
        the primary on the twin backend; greedy parity holds no matter
        which side wins, and the hedge counters move."""
        prompt = list(range(30, 45))
        want = list(map(int, _generate(model, np.asarray(prompt,
                                                         np.int32), 5)))
        s1 = LLMServer(model, max_batch=2, max_seq_len=64,
                       page_size=8).start()
        s2 = LLMServer(model, max_batch=2, max_seq_len=64,
                       page_size=8).start()
        w1 = LLMWorker(s1, role="decode").start()
        w2 = LLMWorker(s2, role="decode").start()
        r = LLMRouter([], [w1.address, w2.address], failover=True,
                      hedge=True, hedge_delay_ms=1.0,
                      start_prober=False).start()
        try:
            st, body, _ = _req(r.address, "POST", "/worker_generate",
                               {"prompt_ids": prompt,
                                "max_new_tokens": 5})
            assert st == 200 and body["output_ids"] == want
            assert r.hedges_issued >= 1
        finally:
            r.stop()
            w1.stop()
            w2.stop()
            s1.stop()
            s2.stop()


class TestStreamEosWindow:
    def test_chunk_ending_in_eos_is_always_terminal(self, model,
                                                    faults_armed):
        """A stream chunk whose cumulative tokens end in EOS must carry
        done:true. A done:false chunk with EOS would let a mid-stream
        failover journal the EOS and resume PAST it on another backend,
        generating spurious tokens — the bit-identical contract dies."""
        prompt = np.arange(1, 13, dtype=np.int32)
        toks = list(map(int, _generate(model, prompt, 6)))
        eos = toks[2]          # greedy run hits "EOS" mid-generation
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                        eos_token_id=eos).start()
        w = LLMWorker(srv, role="decode").start()
        plan = rel.FaultPlan(seed=0)
        # one token per chunk: widens the EOS->done.set() window the
        # handler must mask
        plan.add("llm.step", "delay", times=None, delay=0.03)
        rel.set_plan(plan)
        try:
            conn = http.client.HTTPConnection(*w.address, timeout=120)
            try:
                conn.request("POST", "/worker_generate_stream",
                             json.dumps({"prompt_ids":
                                         [int(t) for t in prompt],
                                         "max_new_tokens": 6}),
                             {"Content-Type": "application/json"})
                resp = conn.getresponse()
                assert resp.status == 200
                chunks = []
                while True:
                    line = resp.readline()
                    if not line:
                        break
                    line = line.strip()
                    if line:
                        chunks.append(json.loads(line.decode()))
                    if chunks and chunks[-1].get("done"):
                        break
            finally:
                conn.close()
            for c in chunks:
                ids = c.get("output_ids", [])
                if ids and ids[-1] == eos:
                    assert c["done"], \
                        "non-terminal chunk carried the EOS token"
            assert chunks[-1]["done"]
            assert chunks[-1]["finish_reason"] == "stop"
            assert chunks[-1]["output_ids"] == toks[:3]
        finally:
            rel.set_plan(None)
            w.stop()
            srv.stop()


# ---------------------------------------------------------------------------
# engine watchdog
# ---------------------------------------------------------------------------

class TestWatchdog:
    def test_stall_fails_pending_retriably_then_recovers(self, model,
                                                         faults_armed):
        prompt = np.arange(1, 13, dtype=np.int32)
        srv = LLMServer(model, max_batch=2, max_seq_len=64, page_size=8,
                        watchdog_timeout=0.25).start()
        try:
            assert srv.watchdog_enabled
            assert srv._watchdog_thread is not None
            # warm every shape the test will hit: a compile stalls the
            # heartbeat exactly like a hung step (see _watchdog_loop)
            srv.submit(prompt, max_new_tokens=2).get(timeout=600)
            trips0 = srv.watchdog_trips
            plan = rel.FaultPlan(seed=0)
            plan.add("worker.stall", "delay", times=1, delay=1.2)
            rel.set_plan(plan)
            try:
                req = srv.submit(prompt, max_new_tokens=8)
                with pytest.raises(RuntimeError, match="watchdog"):
                    req.get(timeout=30)
                assert req.cancel_requested
                assert srv.watchdog_trips > trips0
                # recovery: the heartbeat resumes once the stalled pass
                # completes, the tripped flag clears, service resumes
                deadline = time.monotonic() + 10
                while srv.watchdog_tripped and \
                        time.monotonic() < deadline:
                    time.sleep(0.05)
                assert not srv.watchdog_tripped
            finally:
                rel.set_plan(None)
            out = srv.submit(prompt, max_new_tokens=2).get(timeout=600)
            assert len(out) == 2
        finally:
            srv.stop()

    def test_submit_while_tripped_fails_fast_retriably(self, model):
        """While the episode lasts, new submits must not queue behind
        the wedged pass (they would hang until the stream wait expires
        and surface as a silent 200 timeout) — they fail immediately
        with the same retriable verdict as the trip sweep. Unstarted
        server: no monitor loop to race the manually-set flag. The
        gate needs BOTH the flag and a currently-stale heartbeat —
        the flag alone lags recovery by up to one monitor tick."""
        srv = LLMServer(model, max_batch=2, max_seq_len=32, page_size=8,
                        watchdog_timeout=30.0)
        try:
            srv.watchdog_tripped = True
            srv._hb = time.monotonic() - 60.0   # wedged mid-pass now
            req = srv.submit(np.arange(1, 9, dtype=np.int32),
                             max_new_tokens=4)
            assert req.done.is_set()        # failed fast, never queued
            assert srv._queue.empty()
            with pytest.raises(RuntimeError, match="retriable"):
                req.get(timeout=1)
        finally:
            srv.stop()

    def test_tripped_engine_flips_worker_healthz(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=32, page_size=8,
                        watchdog_timeout=30.0).start()
        w = LLMWorker(srv, role="decode").start()
        try:
            st, hz, _ = _req(w.address, "GET", "/healthz")
            assert st == 200
            assert hz["watchdog"]["tripped"] is False
            srv.watchdog_tripped = True     # what a trip sets
            st, hz, _ = _req(w.address, "GET", "/healthz")
            assert st == 503 and hz["status"] == "stalled"
            # the prober drains a stalled worker out of the pool
            prober = HealthProber(lambda: [(w.address, "decode")])
            prober.probe_now()
            assert not prober.healthy(w.address)
            srv.watchdog_tripped = False
            prober.probe_now()
            assert prober.healthy(w.address)
        finally:
            w.stop()
            srv.stop()

    def test_disabled_watchdog_structurally_absent(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=32,
                        page_size=8).start()
        w = LLMWorker(srv).start()
        try:
            assert not srv.watchdog_enabled
            assert srv._watchdog_thread is None   # no monitor thread
            st, hz, _ = _req(w.address, "GET", "/healthz")
            assert st == 200
            assert "watchdog" not in hz   # healthz body byte-compat
        finally:
            w.stop()
            srv.stop()


# ---------------------------------------------------------------------------
# chaos: the acceptance kill-storm (slow-marked; tier-1 skips it)
# ---------------------------------------------------------------------------

@pytest.mark.chaos
@pytest.mark.slow
def test_chaos_kill_storm_loses_zero_requests():
    """tools/chaos_check.py --failover: seeded mid-stream worker kills
    plus a watchdog-tripping engine stall must complete every request
    with greedy outputs bit-identical to the clean run."""
    from tools.chaos_check import run_failover_chaos

    out = run_failover_chaos(seed=0)
    assert out["match"] and out["lost_requests"] == 0
    assert out["failovers"] > 0


# ---------------------------------------------------------------------------
# disabled mode: the PR 6 router, structurally
# ---------------------------------------------------------------------------

class TestDisabledStructurallyAbsent:
    def test_no_journal_no_prober_no_series(self, model):
        srv = LLMServer(model, max_batch=2, max_seq_len=64,
                        page_size=8).start()
        w = LLMWorker(srv, role="decode").start()
        before = set(obs.render().splitlines()) if obs.enabled() else set()
        r = LLMRouter([], [w.address], start_prober=False).start()
        try:
            # the gates themselves default off (the gatecheck pass's
            # absence-test contract names the conf keys explicitly)
            assert conf.get_bool("bigdl.llm.failover.enabled",
                                 False) is False
            assert conf.get_bool("bigdl.llm.hedge.enabled",
                                 False) is False
            assert not r._active and not r.failover_enabled
            assert r._journal is None
            assert r._prober is None
            assert r._hedge is None and r._latency is None
            st, body, _ = _req(r.address, "POST", "/worker_generate",
                               {"prompt_ids": list(range(1, 9)),
                                "max_new_tokens": 2})
            assert st == 200 and len(body["output_ids"]) == 2
            # no failover/hedge/journal/prober series appeared from
            # serving through the disabled router
            if obs.enabled():
                new = "\n".join(set(obs.render().splitlines()) - before)
                for name in ("bigdl_router_failovers_total",
                             "bigdl_router_hedges_total",
                             "bigdl_router_journal_inflight",
                             "bigdl_router_backend_healthy"):
                    assert name not in new
            # healthz has no journal/prober keys (PR 6 body shape)
            st, hz, _ = _req(r.address, "GET", "/healthz")
            assert st == 200
            for key in ("journal_inflight", "failovers",
                        "hedges_issued", "prober"):
                assert key not in hz
            # and no prober thread is running for this router
            assert not [t for t in threading.enumerate()
                        if t.name == "bigdl-router-prober"]
        finally:
            r.stop()
            w.stop()
            srv.stop()

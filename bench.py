"""Benchmark entry point — prints ONE JSON line for the driver.

Current headline: LeNet-5/MNIST synchronous training throughput (BASELINE
config 1 — the canonical BigDL hello-world) on whatever accelerator jax
exposes (one real TPU chip under the driver; CPU elsewhere).

The reference published no harvestable numbers this round (BASELINE.md):
``vs_baseline`` is reported against the baseline anchor when one exists,
else ``null``. As the build widens this script upgrades to the north-star
metrics (ResNet-50 images/sec/chip, Llama-2-7B INT4 tokens/sec).
"""

from __future__ import annotations

import json
import time

import numpy as np


def bench_lenet_train(batch_size: int = 512, warmup: int = 5,
                      iters: int = 30) -> dict:
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models import lenet
    from bigdl_tpu.nn import ClassNLLCriterion
    from bigdl_tpu.optim.optim_method import SGD

    model = lenet.build_model(10)
    criterion = ClassNLLCriterion()
    optim = SGD(learning_rate=0.05)
    params = jax.tree_util.tree_map(jnp.asarray, model.parameters_dict())
    states = jax.tree_util.tree_map(jnp.asarray, model.states_dict())
    opt_state = jax.tree_util.tree_map(jnp.asarray, optim.init_state(params))

    def train_step(params, states, opt_state, x, t, rng):
        def loss_fn(p):
            y, s2 = model.apply(p, states, x, training=True, rng=rng)
            return criterion.apply_loss(y, t), s2

        (loss, new_states), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt = optim.step(params, grads, opt_state, 0.05)
        return new_params, new_states, new_opt, loss

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    rs = np.random.RandomState(0)
    x = jnp.asarray(rs.rand(batch_size, 28 * 28).astype(np.float32))
    t = jnp.asarray((rs.randint(0, 10, batch_size) + 1).astype(np.int32))
    key = jax.random.PRNGKey(0)

    for _ in range(warmup):
        key, sub = jax.random.split(key)
        params, states, opt_state, loss = step(params, states, opt_state,
                                               x, t, sub)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        key, sub = jax.random.split(key)
        params, states, opt_state, loss = step(params, states, opt_state,
                                               x, t, sub)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    imgs_per_sec = batch_size * iters / dt
    return {
        "metric": "lenet_mnist_train_throughput",
        "value": round(imgs_per_sec, 1),
        "unit": "images/sec/chip",
        "vs_baseline": None,  # no reference number harvestable (BASELINE.md)
        "extra": {
            "batch_size": batch_size,
            "iters": iters,
            "backend": jax.default_backend(),
            "final_loss": float(loss),
        },
    }


if __name__ == "__main__":
    import os
    import sys

    if "--cpu" in sys.argv or os.environ.get("BIGDL_TPU_BENCH_CPU"):
        # sitecustomize pins the axon TPU platform; env JAX_PLATFORMS is
        # ineffective — the in-process config update is the working override
        import jax
        jax.config.update("jax_platforms", "cpu")
    print(json.dumps(bench_lenet_train()))

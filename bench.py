"""Benchmark entry point — prints ONE JSON line for the driver.

Headline: ResNet-50 ImageNet-shape synchronous training throughput in
images/sec/chip (the BASELINE.json north-star metric) in bf16 on whatever
accelerator jax exposes (one real TPU chip under the driver). ``--llama``
reports the second north-star, Llama-2-7B q4_0 decode tokens/sec.

The reference published no harvestable numbers this round (BASELINE.md):
``vs_baseline`` is ``null``. ``--quick`` shrinks configs for CPU smoke
runs and prefixes the metric name with ``smoke_`` so dashboards never
ingest smoke numbers as flagship results; ``--cpu`` forces the CPU
backend (the env-var route is ineffective under this image's
sitecustomize).
"""

from __future__ import annotations

import json
import time

import numpy as np


def _bench_train(model, make_batch, metric: str, batch_size: int,
                 warmup: int, iters: int, lr: float, optim,
                 extra: dict) -> dict:
    """Shared train-step timing harness: jit+donate, warmup, timed loop."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn import ClassNLLCriterion

    criterion = ClassNLLCriterion()
    params = jax.tree_util.tree_map(jnp.asarray, model.parameters_dict())
    states = jax.tree_util.tree_map(jnp.asarray, model.states_dict())
    opt_state = jax.tree_util.tree_map(jnp.asarray,
                                       optim.init_state(params))

    def train_step(params, states, opt_state, x, t, rng):
        def loss_fn(p):
            y, s2 = model.apply(p, states, x, training=True, rng=rng)
            return criterion.apply_loss(y.astype(jnp.float32), t), s2

        (loss, new_states), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt = optim.step(params, grads, opt_state, lr)
        return new_params, new_states, new_opt, loss

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))
    x, t = make_batch()
    key = jax.random.PRNGKey(0)

    for _ in range(warmup):
        key, sub = jax.random.split(key)
        params, states, opt_state, loss = step(params, states, opt_state,
                                               x, t, sub)
    jax.block_until_ready(loss)

    t0 = time.perf_counter()
    for _ in range(iters):
        key, sub = jax.random.split(key)
        params, states, opt_state, loss = step(params, states, opt_state,
                                               x, t, sub)
    jax.block_until_ready(loss)
    dt = time.perf_counter() - t0

    import jax as _jax
    return {
        "metric": metric,
        "value": round(batch_size * iters / dt, 2),
        "unit": "images/sec/chip",
        "vs_baseline": None,  # no reference number harvestable (BASELINE.md)
        "extra": {**extra, "batch_size": batch_size, "iters": iters,
                  "backend": _jax.default_backend(),
                  "final_loss": float(loss)},
    }


def bench_lenet_train(batch_size: int = 512, warmup: int = 5,
                      iters: int = 30) -> dict:
    import jax.numpy as jnp

    from bigdl_tpu.models import lenet
    from bigdl_tpu.optim.optim_method import SGD

    rs = np.random.RandomState(0)

    def make_batch():
        x = jnp.asarray(rs.rand(batch_size, 28 * 28).astype(np.float32))
        t = jnp.asarray((rs.randint(0, 10, batch_size) + 1)
                        .astype(np.int32))
        return x, t

    return _bench_train(lenet.build_model(10), make_batch,
                        "lenet_mnist_train_throughput", batch_size,
                        warmup, iters, 0.05, SGD(learning_rate=0.05),
                        extra={})


def bench_resnet50_train(batch_size: int = 32, warmup: int = 3,
                         iters: int = 10, image: int = 224,
                         depth: int = 50, classes: int = 1000,
                         smoke: bool = False) -> dict:
    """North-star: ResNet train-step throughput, bf16 params/compute."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models import resnet
    from bigdl_tpu.optim.optim_method import SGD

    model = resnet.resnet_imagenet(depth=depth, class_num=classes)
    rs = np.random.RandomState(0)

    def make_batch():
        x = jnp.asarray(rs.rand(batch_size, 3, image, image), jnp.bfloat16)
        t = jnp.asarray((rs.randint(0, classes, batch_size) + 1)
                        .astype(np.int32))
        return x, t

    # bf16 params: the MXU-native dtype
    model.load_parameters_dict(jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        model.parameters_dict()))
    name = "resnet50_imagenet_train_throughput"
    return _bench_train(model, make_batch,
                        ("smoke_" + name) if smoke else name,
                        batch_size, warmup, iters, 0.1,
                        SGD(learning_rate=0.1, momentum=0.9),
                        extra={"image": image, "depth": depth,
                               "dtype": "bfloat16"})


def _synthetic_q4_llama_params(cfg, seed: int = 0):
    """Random already-quantized params, built directly on device — avoids
    materializing 28 GB of fp32 host weights for the 7B benchmark (the
    values don't matter for throughput)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.llm.ggml.quantize import QK
    from bigdl_tpu.llm.models.llama import _LAYER_LINEARS, linear_shapes

    key = jax.random.PRNGKey(seed)
    h = cfg.hidden_size
    shapes = linear_shapes(cfg)
    L = cfg.num_hidden_layers
    layers = {}
    for name in _LAYER_LINEARS:
        n, k = shapes[name]
        key, k1, k2 = jax.random.split(key, 3)
        layers[name] = {
            "q": jax.random.randint(k1, (L, n, k // 2), 0, 256, jnp.uint8),
            "scale": (jax.random.uniform(k2, (L, n, k // QK),
                                         jnp.float32, 0.001, 0.02)
                      .astype(jnp.float16)),
        }
    layers["input_layernorm"] = jnp.ones((L, h), jnp.bfloat16)
    layers["post_attention_layernorm"] = jnp.ones((L, h), jnp.bfloat16)
    key, k1, k2 = jax.random.split(key, 3)
    return {
        "embed_tokens": (jax.random.normal(k1, (cfg.vocab_size, h),
                                           jnp.float32) * 0.02
                         ).astype(jnp.bfloat16),
        "norm": jnp.ones((h,), jnp.bfloat16),
        "layers": layers,
        "lm_head": {"w": (jax.random.normal(k2, (cfg.vocab_size, h),
                                            jnp.float32) * 0.02
                          ).astype(jnp.bfloat16)},
    }


def bench_llama_int4_decode(model_size: str = "7b", batch: int = 1,
                            prompt_len: int = 128, decode_tokens: int = 64,
                            max_cache: int = 256,
                            smoke: bool = False) -> dict:
    """North-star 2: Llama q4_0 decode throughput — prefill runs OUTSIDE
    the timed window; only the autoregressive decode loop is measured."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.llm.models.llama import (
        LlamaConfig, LlamaForCausalLM, init_cache)

    cfg = {"7b": LlamaConfig.llama2_7b,
           "8b": LlamaConfig.llama3_8b,
           "tiny": LlamaConfig.tiny}[model_size]()
    limit = min(max_cache, cfg.max_position_embeddings)
    prompt_len = min(prompt_len, limit - decode_tokens - 1)
    params = _synthetic_q4_llama_params(cfg)
    model = LlamaForCausalLM(cfg, params, max_cache_len=limit)

    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, prompt_len)),
                      jnp.int32)

    def decode_loop(logits, cache, n):
        last = logits[:, -1]
        for _ in range(n):
            nxt = jnp.argmax(last, axis=-1).astype(jnp.int32)[:, None]
            logits, cache = model(nxt, cache)
            last = logits[:, -1]
        jax.block_until_ready(last)
        return logits, cache

    # prefill + decode-step compile happen before the timer
    logits, cache = model(ids)
    logits, cache = decode_loop(logits, cache, 2)

    t0 = time.perf_counter()
    decode_loop(logits, cache, decode_tokens)
    dt = time.perf_counter() - t0

    name = "llama2_7b_int4_decode_throughput"
    return {
        "metric": ("smoke_" + name) if smoke else name,
        "value": round(decode_tokens * batch / dt, 2),
        "unit": "tokens/sec",
        "vs_baseline": None,  # no reference number harvestable (BASELINE.md)
        "extra": {
            "model": model_size, "batch": batch, "prompt_len": prompt_len,
            "decode_tokens": decode_tokens, "qtype": "sym_int4",
            "backend": jax.default_backend(),
        },
    }


if __name__ == "__main__":
    import os
    import sys

    if "--cpu" in sys.argv or os.environ.get("BIGDL_TPU_BENCH_CPU"):
        # sitecustomize pins the axon TPU platform; env JAX_PLATFORMS is
        # ineffective — the in-process config update is the working override
        import jax
        jax.config.update("jax_platforms", "cpu")
    quick = "--quick" in sys.argv or bool(os.environ.get(
        "BIGDL_TPU_BENCH_QUICK"))
    if "--lenet" in sys.argv:
        print(json.dumps(bench_lenet_train()))
    elif "--llama" in sys.argv:
        if quick:
            print(json.dumps(bench_llama_int4_decode(
                model_size="tiny", smoke=True)))
        else:
            print(json.dumps(bench_llama_int4_decode()))
    elif quick:
        print(json.dumps(bench_resnet50_train(
            batch_size=4, warmup=1, iters=3, image=64, depth=18,
            classes=100, smoke=True)))
    else:
        print(json.dumps(bench_resnet50_train()))
"""Benchmark entry point — prints ONE JSON line for the driver.

Headline: ResNet-50 ImageNet-shape synchronous training throughput in
images/sec/chip (BASELINE.json north-star config 2) in bf16, with MFU
computed from XLA's compiled cost analysis and asserted ``<= 1.0`` —
round 1 recorded a physically impossible number (~196% MFU) because the
timed window trusted ``block_until_ready`` over a 10-iteration async
dispatch; this harness instead closes every timed window with a literal
device-to-host fetch of a value that data-depends on the whole loop
(donated params chain each step to the next), which cannot complete
before the compute has actually run.

The default run also folds in the second north star (BASELINE config 5,
Llama-2-7B q4_0 decode tokens/sec) plus an int4-vs-dense matmul kernel
micro-bench under ``extra``, so one driver invocation records all of it.

The reference published no harvestable numbers (BASELINE.md):
``vs_baseline`` is ``null``. ``--quick`` shrinks configs for CPU smoke
runs and prefixes metric names with ``smoke_`` so dashboards never ingest
smoke numbers as flagship results; ``--cpu`` forces the CPU backend (the
env-var route is ineffective under this image's sitecustomize).
"""

from __future__ import annotations

import functools
import json
import time

import numpy as np

# Peak dense bf16 FLOP/s per chip by PJRT device_kind (public spec sheets).
# Matched by substring, lowercased. Used only for the MFU sanity number.
_PEAK_BF16_FLOPS = [
    ("v6", 918e12),           # Trillium / v6e
    ("v5p", 459e12),
    ("v5", 197e12),           # v5e / "TPU v5 lite"
    ("v4", 275e12),
    ("v3", 123e12),
    ("v2", 45e12),
]


def _peak_flops(device) -> float | None:
    kind = getattr(device, "device_kind", "").lower()
    if "tpu" not in kind and device.platform != "tpu":
        return None
    for key, peak in _PEAK_BF16_FLOPS:
        if key in kind:
            return peak
    return None


def _cost_analysis(compiled) -> dict:
    """XLA cost analysis as a plain dict (version-tolerant)."""
    try:
        ca = compiled.cost_analysis()
    except Exception:
        return {}
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else {}
    return ca or {}


def _flops_of(compiled) -> float | None:
    flops = _cost_analysis(compiled).get("flops")
    return float(flops) if flops else None


def _bench_train(model, make_batch, metric: str, batch_size: int,
                 warmup: int, iters: int, lr: float, optim,
                 extra: dict, unit: str = "images/sec/chip",
                 n_batches: int = 4) -> dict:
    """Shared train-step timing harness: jit+donate, warmup, timed loop.

    The timed window ends with a host fetch of the final loss scalar; the
    loss of iteration i depends (via donated params) on every iteration
    before it, so the fetch bounds the true wall-clock of all ``iters``
    steps regardless of how the runtime implements readiness.
    """
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.nn import ClassNLLCriterion

    criterion = ClassNLLCriterion()
    params = jax.tree_util.tree_map(jnp.asarray, model.parameters_dict())
    states = jax.tree_util.tree_map(jnp.asarray, model.states_dict())
    opt_state = jax.tree_util.tree_map(jnp.asarray,
                                       optim.init_state(params))

    def train_step(params, states, opt_state, x, t, rng):
        def loss_fn(p):
            y, s2 = model.apply(p, states, x, training=True, rng=rng)
            return criterion.apply_loss(y.astype(jnp.float32), t), s2

        (loss, new_states), grads = jax.value_and_grad(
            loss_fn, has_aux=True)(params)
        new_params, new_opt = optim.step(params, grads, opt_state, lr)
        return new_params, new_states, new_opt, loss

    # ISSUE 3: the flight-recorder wrapper records this step's compile
    # time and cost/memory analysis under bench/<metric> in the
    # telemetry block, so the MFU below is attributed to the executable
    # that actually ran (the bigdl_xla_* gauges carry the same numbers)
    from bigdl_tpu import observability as obs
    step = obs.compiled(train_step, name=f"bench/{metric}",
                        donate_argnums=(0, 1, 2))
    # rotate over several distinct batches so the loop is not single-batch
    # memorization (VERDICT r1 weak #10)
    batches = [make_batch() for _ in range(n_batches)]
    from bigdl_tpu.utils.engine import train_rng_key
    key = train_rng_key(0)   # hardware RBG on TPU: threefry dropout
    # masks alone cost ~40% of a BERT step (see engine.train_rng_key)

    for i in range(warmup):
        key, sub = jax.random.split(key)
        x, t = batches[i % n_batches]
        params, states, opt_state, loss = step(params, states, opt_state,
                                               x, t, sub)
    float(loss)  # full sync before the timed window opens

    t0 = time.perf_counter()
    for i in range(iters):
        key, sub = jax.random.split(key)
        x, t = batches[i % n_batches]
        params, states, opt_state, loss = step(params, states, opt_state,
                                               x, t, sub)
    final_loss = float(loss)  # host fetch closes the window
    dt = time.perf_counter() - t0

    # per-step latency, synchronously (separate from the pipelined window)
    sync_times = []
    for _ in range(min(10, iters)):
        key, sub = jax.random.split(key)
        s0 = time.perf_counter()
        params, states, opt_state, loss = step(params, states, opt_state,
                                               *batches[0], sub)
        float(loss)
        sync_times.append(time.perf_counter() - s0)

    # cost analysis comes from the flight recorder's ledger — i.e. from
    # the very executable the loop above dispatched (attributed, and no
    # duplicate compile). Manual lower+compile only as the fallback when
    # the recorder saw nothing (observability disabled).
    entry = {}
    stats_fn = getattr(step, "stats", None)
    if stats_fn is not None:
        hist = stats_fn()["history"]
        entry = hist[0] if hist else {}
    flops_per_step = entry.get("flops")
    bytes_per_step = entry.get("bytes_accessed")
    if flops_per_step is None:
        key, sub = jax.random.split(key)
        ca = _cost_analysis(step.lower(params, states, opt_state,
                                       *batches[0], sub).compile())
        flops_per_step = float(ca.get("flops") or 0) or None
        bytes_per_step = float(ca.get("bytes accessed") or 0) or None

    dev = jax.devices()[0]
    peak = _peak_flops(dev)
    mfu = None
    if peak and flops_per_step:
        mfu = flops_per_step * iters / dt / peak
        assert mfu <= 1.0, (
            f"measured MFU {mfu:.2%} exceeds hardware peak — the timing is "
            f"broken (flops/step={flops_per_step:.3e}, steps/s={iters/dt:.2f}, "
            f"peak={peak:.3e} FLOP/s on {dev.device_kind}); refusing to "
            f"report an impossible number")

    return {
        "metric": metric,
        "value": round(batch_size * iters / dt, 2),
        "unit": unit,
        "vs_baseline": None,  # no reference number harvestable (BASELINE.md)
        "extra": {**extra, "batch_size": batch_size, "iters": iters,
                  "step_ms": round(dt / iters * 1e3, 3),
                  "step_ms_sync_median": round(
                      float(np.median(sync_times)) * 1e3, 3),
                  "flops_per_step": flops_per_step,
                  "bytes_per_step": bytes_per_step,
                  "implied_hbm_gbs": (round(
                      bytes_per_step * iters / dt / 1e9, 1)
                      if bytes_per_step else None),
                  "achieved_tflops": (round(flops_per_step * iters / dt / 1e12,
                                            2) if flops_per_step else None),
                  "mfu": round(mfu, 4) if mfu is not None else None,
                  "peak_flops": peak,
                  "device_kind": getattr(dev, "device_kind", str(dev)),
                  "backend": jax.default_backend(),
                  "final_loss": final_loss},
    }


def bench_lenet_train(batch_size: int = 512, warmup: int = 5,
                      iters: int = 50) -> dict:
    import jax.numpy as jnp

    from bigdl_tpu.models import lenet
    from bigdl_tpu.optim.optim_method import SGD

    rs = np.random.RandomState(0)

    def make_batch():
        x = jnp.asarray(rs.rand(batch_size, 28 * 28).astype(np.float32))
        t = jnp.asarray((rs.randint(0, 10, batch_size) + 1)
                        .astype(np.int32))
        return x, t

    return _bench_train(lenet.build_model(10), make_batch,
                        "lenet_mnist_train_throughput", batch_size,
                        warmup, iters, 0.05, SGD(learning_rate=0.05),
                        extra={})


def bench_resnet50_train(batch_size: int = 256, warmup: int = 5,
                         iters: int = 40, image: int = 224,
                         depth: int = 50, classes: int = 1000,
                         smoke: bool = False,
                         format: str = "NHWC",
                         remat: bool = False) -> dict:
    """North-star: ResNet train-step throughput, bf16 params/compute.

    Default NHWC (channels on the TPU lane dim) at batch 256. The step
    is HBM-traffic-bound (cost analysis: ~43 GB accessed / 3.0 TFLOP at
    batch 128 — the byte roofline, not the MXU, sets the ceiling), so
    the wins came from single-pass f32 BN stats + fused scale/shift BN
    (bigdl_tpu.nn BatchNormalization) and batch size; remat=True trades
    FLOPs for bytes but measured net-negative on this model, so it
    stays opt-in.

    Round-5 close-out of the bytes diet (VERDICT r4 item 7): the batch
    sweep is complete — 256 → 2545-2559 img/s (768-773 GB/s implied,
    94% of the 819 GB/s spec); 288 → 2343; 320 → 2378; 384 → 2451;
    512 → 2402. Non-256 batches tile worse, every activation is
    already bf16, BN is a single fused pass, and remat is
    net-negative, so the residual ~6% between implied and spec
    bandwidth is scheduling overhead XLA owns, not removable bytes.
    The ~2550 img/s figure is this model/chip's measured ceiling."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models import resnet
    from bigdl_tpu.optim.optim_method import SGD

    model = resnet.resnet_imagenet(depth=depth, class_num=classes,
                                   format=format, remat=remat)
    rs = np.random.RandomState(0)
    shape = ((batch_size, 3, image, image) if format == "NCHW"
             else (batch_size, image, image, 3))

    def make_batch():
        x = jnp.asarray(rs.rand(*shape), jnp.bfloat16)
        t = jnp.asarray((rs.randint(0, classes, batch_size) + 1)
                        .astype(np.int32))
        return x, t

    # bf16 params: the MXU-native dtype
    model.load_parameters_dict(jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16) if a.dtype == jnp.float32 else a,
        model.parameters_dict()))
    name = "resnet50_imagenet_train_throughput"
    return _bench_train(model, make_batch,
                        ("smoke_" + name) if smoke else name,
                        batch_size, warmup, iters, 0.1,
                        SGD(learning_rate=0.1, momentum=0.9),
                        extra={"image": image, "depth": depth,
                               "dtype": "bfloat16", "format": format,
                               "remat": remat})


def bench_bert_finetune(batch_size: int = 64, seq_len: int = 128,
                        warmup: int = 5, iters: int = 50,
                        smoke: bool = False) -> dict:
    """BASELINE config 4: BERT-base fine-tune step throughput on OUR nn
    stack (not a host torch loop), bf16 params. Batch sweep closed out
    in r5: 64 → 1514-1554 samples/s (MFU 0.52-0.53), 96 → 1489,
    128 → 1438 — 64 is the measured optimum."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.models.bert import BertConfig, build_classifier
    from bigdl_tpu.nn.module import set_seed
    from bigdl_tpu.optim.optim_method import AdamWeightDecay

    set_seed(0)
    cfg = BertConfig.tiny() if smoke else BertConfig.base()
    model = build_classifier(cfg, num_labels=2)
    model.load_parameters_dict(jax.tree_util.tree_map(
        lambda a: a.astype(jnp.bfloat16)
        if a.dtype == jnp.float32 else a, model.parameters_dict()))
    rs = np.random.RandomState(0)
    sl = min(seq_len, cfg.max_position_embeddings)

    def make_batch():
        x = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch_size, sl)),
                        jnp.int32)
        t = jnp.asarray((rs.randint(0, 2, batch_size) + 1), jnp.int32)
        return x, t

    name = "bert_base_finetune_throughput"
    return _bench_train(model, make_batch,
                        ("smoke_" + name) if smoke else name,
                        batch_size, warmup, iters, 2e-5,
                        AdamWeightDecay(learning_rate=2e-5),
                        extra={"seq_len": sl, "dtype": "bfloat16"},
                        unit="samples/sec/chip")


def bench_lenet_convergence(epochs: int = 16, batch: int = 256,
                            lr: float = 1e-3) -> dict:
    """BASELINE config 1 as a TRAINING TARGET with a FALSIFIABLE metric
    (VERDICT r4 missing #2): LeNet-5 through the full Optimizer facade
    on the Bayes-calibrated hard synthetic set — nearest-prototype
    (≈Bayes) tops out at ~0.96 by construction, so a healthy run lands
    in [0.90, 0.99) and a subtly broken optimizer/loss/init falls out
    of the band (the lr=0 lamed control is asserted failing in
    tests/test_convergence_falsifiable.py). Real MNIST is read from
    disk when present; this environment has no network."""
    from bigdl_tpu.feature.dataset import DataSet
    from bigdl_tpu.feature.mnist import (load_mnist,
                                         nearest_prototype_accuracy,
                                         normalize)
    from bigdl_tpu.models import lenet
    from bigdl_tpu.optim import (Adam, Optimizer, Top1Accuracy, Trigger)
    import bigdl_tpu.nn as nn

    xtr, ytr = load_mnist(train=True, synthetic_size=16384, hard=True)
    xte, yte = load_mnist(train=False, synthetic_size=2048, hard=True)
    bayes_ref = nearest_prototype_accuracy(xte, yte)
    xtr = normalize(xtr).reshape(-1, 784)
    xte = normalize(xte).reshape(-1, 784)
    model = lenet.build_model(10)
    opt = Optimizer(model, DataSet.array(xtr, ytr),
                    nn.ClassNLLCriterion(), batch_size=batch,
                    end_trigger=Trigger.max_epoch(epochs),
                    distributed=False)
    opt.set_optim_method(Adam(learning_rate=lr))
    t0 = time.perf_counter()
    trained = opt.optimize()
    dt = time.perf_counter() - t0
    from bigdl_tpu.optim import Evaluator
    acc = Evaluator(trained).evaluate((xte, yte), [Top1Accuracy()])[0]
    val = round(float(acc.result), 4)
    band = [0.90, 0.99]
    return {"metric": "lenet_convergence_top1", "value": val,
            "unit": "accuracy", "vs_baseline": None,
            "extra": {"epochs": epochs, "train_s": round(dt, 1),
                      "train_size": len(xtr), "test_size": len(xte),
                      "dataset": "synthetic-mnist-hard (Bayes-calibrated "
                                 "sigma, ceiling ~0.96; no network)",
                      "bayes_ref_top1": round(bayes_ref, 4),
                      "band": band,
                      "in_band": bool(band[0] <= val < band[1]),
                      "final_loss": opt.state["loss"]}}


def bench_cifar_convergence(epochs: int = 12, batch: int = 256,
                            lr: float = 2e-3) -> dict:
    """BASELINE config 2's cheap accuracy twin: ResNet-20/CIFAR through
    the Optimizer facade on the Bayes-calibrated hard synthetic set
    (same falsifiable-band design as bench_lenet_convergence; test draw
    is disjoint from train — seed+1)."""
    from bigdl_tpu.feature.cifar import (load_cifar,
                                         nearest_prototype_accuracy)
    from bigdl_tpu.feature.dataset import DataSet
    from bigdl_tpu.models import resnet
    from bigdl_tpu.optim import (Adam, Evaluator, Optimizer, Top1Accuracy,
                                 Trigger)
    import bigdl_tpu.nn as nn

    xtr, ytr = load_cifar(train=True, synthetic_size=8192, hard=True)
    xte, yte = load_cifar(train=False, synthetic_size=2048, hard=True)
    bayes_ref = nearest_prototype_accuracy(xte, yte)
    model = resnet.resnet_cifar(depth=20, class_num=10)
    opt = Optimizer(model, DataSet.array(xtr, ytr),
                    nn.ClassNLLCriterion(), batch_size=batch,
                    end_trigger=Trigger.max_epoch(epochs),
                    distributed=False)
    opt.set_optim_method(Adam(learning_rate=lr))
    t0 = time.perf_counter()
    trained = opt.optimize()
    dt = time.perf_counter() - t0
    acc = Evaluator(trained).evaluate((xte, yte), [Top1Accuracy()])[0]
    val = round(float(acc.result), 4)
    band = [0.90, 0.99]
    return {"metric": "cifar_resnet20_convergence_top1", "value": val,
            "unit": "accuracy", "vs_baseline": None,
            "extra": {"epochs": epochs, "train_s": round(dt, 1),
                      "train_size": len(xtr), "test_size": len(xte),
                      "dataset": "synthetic-cifar-hard (Bayes-calibrated "
                                 "sigma, ceiling ~0.96; no network)",
                      "bayes_ref_top1": round(bayes_ref, 4),
                      "band": band,
                      "in_band": bool(band[0] <= val < band[1]),
                      "final_loss": opt.state["loss"]}}


def _synthetic_q4_llama_params(cfg, seed: int = 0):
    """Random already-quantized params, built directly on device — avoids
    materializing 28 GB of fp32 host weights for the 7B benchmark (the
    values don't matter for throughput)."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.llm.ggml.quantize import QK
    from bigdl_tpu.llm.models.llama import _LAYER_LINEARS, linear_shapes

    key = jax.random.PRNGKey(seed)
    h = cfg.hidden_size
    shapes = linear_shapes(cfg)
    L = cfg.num_hidden_layers
    layers = {}
    for name in _LAYER_LINEARS:
        n, k = shapes[name]
        key, k1, k2 = jax.random.split(key, 3)
        # k-major TPU kernel layout: q (L, K/2, N), scale (L, G, N) f32
        layers[name] = {
            "q": jax.random.randint(k1, (L, k // 2, n), 0, 256, jnp.uint8),
            "scale": jax.random.uniform(k2, (L, k // QK, n),
                                        jnp.float32, 0.001, 0.02),
        }
    layers["input_layernorm"] = jnp.ones((L, h), jnp.bfloat16)
    layers["post_attention_layernorm"] = jnp.ones((L, h), jnp.bfloat16)
    key, k1, k2, k3 = jax.random.split(key, 4)
    # lm_head quantized too (q4 streams 66 MB instead of 262 MB per token)
    params = {
        "embed_tokens": (jax.random.normal(k1, (cfg.vocab_size, h),
                                           jnp.float32) * 0.02
                         ).astype(jnp.bfloat16),
        "norm": jnp.ones((h,), jnp.bfloat16),
        "layers": layers,
        "lm_head": {
            "q": jax.random.randint(k2, (h // 2, cfg.vocab_size), 0, 256,
                                    jnp.uint8),
            "scale": jax.random.uniform(k3, (h // QK, cfg.vocab_size),
                                        jnp.float32, 0.001, 0.02)},
    }
    # fused qkv + gate_up: 4 weight-streaming matmuls per layer, not 7
    from bigdl_tpu.llm.models.llama import fuse_decoder_params
    return fuse_decoder_params(params)


def _q4_param_bytes(cfg) -> int:
    """On-device bytes of the quantized decoder weights that each decoded
    token must stream from HBM (q nibbles + f32 scales), for the
    bandwidth-roofline sanity number."""
    from bigdl_tpu.llm.ggml.quantize import QK
    from bigdl_tpu.llm.models.llama import _LAYER_LINEARS, linear_shapes

    shapes = linear_shapes(cfg)
    L = cfg.num_hidden_layers
    total = 0
    for name in _LAYER_LINEARS:
        n, k = shapes[name]
        total += L * (n * k // 2 + n * (k // QK) * 4)
    # lm_head quantized too
    h = cfg.hidden_size
    total += cfg.vocab_size * h // 2 + cfg.vocab_size * (h // QK) * 4
    return total


def bench_llama_int4_decode(model_size: str = "7b", batch: int = 1,
                            prompt_len: int = 128, decode_tokens: int = 96,
                            max_cache: int = 512,
                            smoke: bool = False) -> dict:
    """North-star 2: Llama q4_0 decode throughput.

    The token loop is llama.decode_scan — ONE compiled program per
    window, donated kv cache. This runtime's device<->host roundtrip
    costs ~100 ms and its executor memoizes identical (program, args)
    calls, so the harness (a) decodes two windows of different lengths
    and reports the SLOPE (per-token time net of fixed dispatch/fetch
    overhead), and (b) threads the rng key + cache through so no two
    scan calls see identical arguments."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.llm.models.llama import (
        LlamaConfig, LlamaForCausalLM)

    cfg = {"7b": LlamaConfig.llama2_7b,
           "8b": LlamaConfig.llama3_8b,
           "tiny": LlamaConfig.tiny}[model_size]()
    limit = min(max_cache, cfg.max_position_embeddings)
    n_small = max(decode_tokens // 4, 8)
    need = 2 * (decode_tokens + n_small) + 4
    prompt_len = max(8, min(prompt_len, limit - need))
    params = _synthetic_q4_llama_params(cfg)
    model = LlamaForCausalLM(cfg, params, max_cache_len=limit)

    rs = np.random.RandomState(0)
    ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, prompt_len)),
                      jnp.int32)

    # prefill throughput as a SLOPE between two prompt lengths, netting
    # out the ~100 ms fixed dispatch/fetch roundtrip exactly like the
    # decode windows below (distinct tokens dodge result memoization)
    def prefill_time(plen):
        pids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, plen)),
                           jnp.int32)
        lg, ch = model(pids)            # compile for this length
        int(np.asarray(jnp.argmax(lg[0, -1])))
        pids = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch, plen)),
                           jnp.int32)
        t0 = time.perf_counter()
        lg, ch = model(pids)
        int(np.asarray(jnp.argmax(lg[0, -1])))
        return time.perf_counter() - t0, lg, ch

    p_small = max(prompt_len // 4, 8)
    t_small, _, _ = prefill_time(p_small)
    t_full, logits, cache = prefill_time(prompt_len)
    # wall number includes the ~100 ms dispatch/fetch roundtrip AND the
    # one-off 4 GB weight stream; the marginal slope shows the per-token
    # cost once weights are flowing (prefill is weight-stream-bound at
    # these lengths, so the two differ by orders of magnitude)
    prefill_tok_s = batch * prompt_len / max(t_full, 1e-9)
    marginal = (batch * (prompt_len - p_small) / (t_full - t_small)
                if t_full > t_small else None)
    prefill_s = t_full

    key = jax.random.PRNGKey(0)
    last = logits[:, -1]
    temp = jnp.float32(1.0)

    if model.paged_decode:
        # the product-default decode path (round 5): dense prefill
        # bridged into the paged token loop — attention reads live
        # pages, not the max_cache window
        from bigdl_tpu.llm.models.llama import pageify_cache
        kp, vp, bt = pageify_cache(cache, page=model.page_size)
        state = [kp, vp, cache["pos"], last, key]
        del cache

        def window(n):
            kp, vp, pos, last, key = state
            t0 = time.perf_counter()
            toks, kp, vp, pos, last, key, _ = model._decode_scan_paged(
                model.params, kp, vp, bt, pos, last, key, temp,
                page=model.page_size, num_tokens=n, do_sample=True,
                top_k=0, eos_token_id=None)
            int(np.asarray(toks)[0, -1])  # host fetch closes the window
            state[:] = [kp, vp, pos, last, key]
            return time.perf_counter() - t0

        decode_mode = "paged_scan"
    else:
        state = [cache, last, key]

        def window(n):
            cache, last, key = state
            t0 = time.perf_counter()
            toks, cache, last, key, _ = model._decode_scan(
                model.params, cache, last, key, temp, num_tokens=n,
                do_sample=True, top_k=0, eos_token_id=None)
            int(np.asarray(toks)[0, -1])
            state[:] = [cache, last, key]
            return time.perf_counter() - t0

        decode_mode = "fused_scan"

    # compile both window sizes before timing
    for n in (n_small, decode_tokens):
        window(n)
    t_small = window(n_small)
    t_big = window(decode_tokens)

    per_tok = (t_big - t_small) / (decode_tokens - n_small)
    if per_tok <= 0:  # noisy tenancy: fall back to the big-window mean
        per_tok = t_big / decode_tokens
    tok_s = batch / per_tok
    weight_bytes = _q4_param_bytes(cfg)
    hbm_gbs = tok_s * weight_bytes / 1e9  # lower bound: weights re-read/token

    name = "llama2_7b_int4_decode_throughput"
    return {
        "metric": ("smoke_" + name) if smoke else name,
        "value": round(tok_s, 2),
        "unit": "tokens/sec",
        "vs_baseline": None,  # no reference number harvestable (BASELINE.md)
        "extra": {
            "model": model_size, "batch": batch, "prompt_len": prompt_len,
            "decode_tokens": decode_tokens, "qtype": "sym_int4",
            "step_ms": round(per_tok * 1e3, 3),
            "window_s": [round(t_small, 3), round(t_big, 3)],
            "weight_bytes": weight_bytes,
            "implied_hbm_gbs": round(hbm_gbs, 1),
            "prefill_tokens_per_s": round(prefill_tok_s, 1),
            "prefill_marginal_tokens_per_s": (round(marginal, 1)
                                              if marginal else None),
            "prefill_s": round(prefill_s, 3),
            "decode_mode": decode_mode,
            "matmuls_per_layer": 4,     # qkv, o, gate_up, down (fused)
            "layer_scan_unroll": 1,     # rolled scan measured fastest
            # measured in-context matmul-only floor on v5e: 28.6 ms/tok
            # (34.9 tok/s) — the m=1 kernel is dequant-rate-bound at
            # ~200 GB/s packed (see int4_matmul.py header); fusion and
            # unrolling are perf-neutral/negative within tenancy noise
            "matmul_floor_ms": 28.6,
            "backend": jax.default_backend(),
        },
    }


def bench_llama_longctx_prefill(prompt_len: int = 4096,
                                model_size: str = "7b") -> dict:
    """Long-context north star: 7B q4_0 prefill at 4k on one chip via
    the blockwise online-softmax attention path (the (T, S) score
    matrix never materializes past one attn_block_size column — what
    lets 4k+ fit beside 4.1 GB of weights). Throughput reported as the
    slope between half- and full-length prompts so the fixed
    dispatch/fetch roundtrip cancels."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM

    cfg = {"7b": LlamaConfig.llama2_7b,
           "tiny": LlamaConfig.tiny}[model_size]()
    limit = min(prompt_len, cfg.max_position_embeddings)
    params = _synthetic_q4_llama_params(cfg)
    model = LlamaForCausalLM(cfg, params, max_cache_len=limit)
    rs = np.random.RandomState(0)

    def run(plen):
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, plen)),
                          jnp.int32)
        lg, _ = model(ids)              # compile
        int(np.asarray(jnp.argmax(lg[0, -1])))
        ids = jnp.asarray(rs.randint(0, cfg.vocab_size, (1, plen)),
                          jnp.int32)
        t0 = time.perf_counter()
        lg, _ = model(ids)
        int(np.asarray(jnp.argmax(lg[0, -1])))
        return time.perf_counter() - t0

    t_half = run(limit // 2)
    t_full = run(limit)
    marginal = ((limit - limit // 2) / (t_full - t_half)
                if t_full > t_half else None)   # dispatch-dominated:
    # a noise-driven slope would print nonsense throughput
    name = f"llama_{model_size}_int4_prefill_{limit}"
    return {"metric": ("llama2_7b_int4_prefill_4k"
                       if model_size == "7b" and limit == 4096
                       else name),
            "value": round(limit / t_full, 1),
            "unit": "tokens/sec",
            "vs_baseline": None,
            "extra": {"prompt_len": limit,
                      "wall_s": round(t_full, 3),
                      "marginal_tokens_per_s": (round(marginal, 1)
                                                if marginal else None),
                      "attn_block_size": cfg.attn_block_size,
                      "backend": jax.default_backend()}}


def bench_paged_decode_step(batch: int = 8, ctx_len: int = 256,
                            page_size: int = 16,
                            model_size: str = "7b") -> dict:
    """Paged-KV serving decode at 7B scale ON CHIP — EXACTLY the step
    LLMServer compiles (serving.paged_decode_step: rolled layer scan,
    read-only pools inside the scan, one post-scan scatter), timed as K
    greedy-feedback steps inside one jit (the live server is
    host-synchronous per token by design, which on this tunneled runtime
    would measure the ~100 ms roundtrip, not the device).

    Round-4's version python-unrolled 32 layers inside the fori body —
    the compile alone outran a 20-minute budget and the structure was
    the ledger's measured -18% shape (int4_matmul.py header). The shared
    scanned step compiles in seconds and pipelines the weight stream
    like the fused-scan path; ``compile_s`` is reported so the warm-up
    cost is itself evidence."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.llm.kernels.paged_attention import LANE
    from bigdl_tpu.llm.models.llama import LlamaConfig
    from bigdl_tpu.llm.serving import paged_decode_step

    cfg = {"7b": LlamaConfig.llama2_7b,
           "tiny": LlamaConfig.tiny}[model_size]()
    params = _synthetic_q4_llama_params(cfg)
    ppb = LANE // page_size
    cap = -(-(ctx_len + 160) // page_size)
    pages_cap = -(-cap // ppb) * ppb
    num_pages = 1 + batch * pages_cap
    nl, hkv, hd = (cfg.num_hidden_layers, cfg.num_key_value_heads,
                   cfg.head_dim)
    # pools built directly on device (host randn at 7B scale costs
    # minutes and ~9 GB of host RAM for values that don't matter)
    kk, kv = jax.random.split(jax.random.PRNGKey(1))
    shape = (nl, num_pages, hkv, page_size, hd)
    k_pages = jax.random.normal(kk, shape, jnp.bfloat16) * 0.1
    v_pages = jax.random.normal(kv, shape, jnp.bfloat16) * 0.1
    rs = np.random.RandomState(0)
    # each row owns a disjoint page run (the allocator's layout)
    bt = np.zeros((batch, pages_cap), np.int32)
    for b in range(batch):
        bt[b] = 1 + b * pages_cap + np.arange(pages_cap)
    bt = jnp.asarray(bt)
    lens0 = jnp.full((batch,), ctx_len, jnp.int32)
    toks0 = jnp.asarray(rs.randint(0, cfg.vocab_size, (batch,)), jnp.int32)

    # params/bt are explicit jit ARGS, not closures: a closure capture
    # lowers 4.4 GB of weights as HLO *constants*, which the remote
    # compile endpoint must serialize — a large share of round-4's
    # >20-minute compile wall
    @functools.partial(jax.jit, static_argnames=("steps",),
                       donate_argnums=(1, 2))
    def run(params, kp, vp, bt, lens, toks, steps: int):
        def body(i, carry):
            kp, vp, lens, toks = carry
            logits, kp, vp = paged_decode_step(params, cfg, kp, vp, bt,
                                               lens, toks, page=page_size)
            return (kp, vp, lens + 1,
                    jnp.argmax(logits, -1).astype(jnp.int32))
        return jax.lax.fori_loop(0, steps, body, (kp, vp, lens, toks))

    def window(n, kp, vp):
        t0 = time.perf_counter()
        kp, vp, lens, toks = run(params, kp, vp, bt, lens0, toks0, n)
        int(np.asarray(toks)[0])
        return time.perf_counter() - t0, kp, vp

    t0 = time.perf_counter()
    for n in (8, 32):
        _, k_pages, v_pages = window(n, k_pages, v_pages)
    compile_s = time.perf_counter() - t0
    t_small, k_pages, v_pages = window(8, k_pages, v_pages)
    t_big, k_pages, v_pages = window(32, k_pages, v_pages)
    per = (t_big - t_small) / 24
    if per <= 0:
        per = t_big / 32
    pool_gb = 2 * k_pages.nbytes / 1e9
    return {"metric": f"llama_{model_size}_paged_decode_step",
            "value": round(batch / per, 2),
            "unit": "tokens/sec",
            "vs_baseline": None,
            "extra": {"batch": batch, "ctx_len": ctx_len,
                      "page_size": page_size,
                      "step_ms": round(per * 1e3, 3),
                      "compile_s": round(compile_s, 1),
                      "kv_pool_gb": round(pool_gb, 2),
                      "num_pages": num_pages,
                      "decode_mode": "shared_scan_readonly_pool",
                      "attn_kernel": "page_major",
                      "backend": jax.default_backend()}}


def bench_int4_kernel_micro(m: int = 1, k: int = 4096, n: int = 11008,
                            iters: int = 2000) -> dict:
    """Kernel roofline check: Pallas q4_0 matmul vs dense bf16 matmul at a
    7B ffn shape. Decode (m=1) should be HBM-bound, so int4 at ~4.5
    bits/weight targets >2.5x the dense bf16 step time.

    Timing is a device-side fori_loop whose carry data-depends on every
    kernel output (the runtime memoizes identical dispatches and its
    block_until_ready is unreliable — only a host fetch of a loop-final
    scalar bounds real compute), reported as the slope between two loop
    lengths so fixed dispatch/fetch overhead cancels."""
    import jax
    import jax.numpy as jnp

    from bigdl_tpu.llm.ggml.quantize import QK
    from bigdl_tpu.llm.models.llama import _linear

    key = jax.random.PRNGKey(0)
    k1, k2, k3, k4 = jax.random.split(key, 4)
    x0 = jax.random.normal(k1, (m, k), jnp.bfloat16)
    q = jax.random.randint(k2, (k // 2, n), 0, 256, jnp.uint8)
    scale = jax.random.uniform(k3, (k // QK, n), jnp.float32, 0.001, 0.02)
    w_dense = jax.random.normal(k4, (k, n), jnp.bfloat16)

    # distinct input buffers per timed call: the runtime memoizes
    # repeated identical dispatches
    xs = [x0 * (1.0 + 1e-3 * i) for i in range(8)]
    xs = [jnp.asarray(v) for v in jax.block_until_ready(xs)]

    def slope_time(fn, weights):
        def loop_for(n_it):
            @jax.jit
            def loop(x, *ws):
                def body(i, carry):
                    x, acc = carry
                    y = fn(x, *ws)
                    return (x + y.sum().astype(x.dtype)
                            * jnp.asarray(1e-30, x.dtype), acc + y.sum())
                return jax.lax.fori_loop(0, n_it, body,
                                         (x, jnp.float32(0)))
            return loop
        pts, xi = [], 0
        for n_it in (iters // 4, iters):
            loop = loop_for(n_it)
            float(loop(xs[xi], *weights)[1])  # compile + warm
            best = 1e9
            for rep in range(3):
                xi += 1
                t0 = time.perf_counter()
                float(loop(xs[xi % len(xs)], *weights)[1])
                best = min(best, time.perf_counter() - t0)
            pts.append((n_it, best))
        (a1, b1), (a2, b2) = pts
        sl = (b2 - b1) / (a2 - a1)
        return sl if sl > 0 else b2 / a2

    # same dispatch the model uses: Pallas q4_0 kernel on TPU, dequant
    # matmul elsewhere
    t_int4 = slope_time(
        lambda x, qq, ss: _linear({"q": qq, "scale": ss}, x), (q, scale))
    t_dense = slope_time(lambda x, w: (x @ w).astype(jnp.bfloat16),
                         (w_dense,))
    packed_gb = (q.size + scale.size * 4) / 1e9
    return {
        "shape": [m, k, n], "iters": iters,
        "int4_us": round(t_int4 * 1e6, 1),
        "dense_bf16_us": round(t_dense * 1e6, 1),
        "int4_speedup_vs_dense": round(t_dense / t_int4, 2),
        "int4_packed_gbs": round(packed_gb / t_int4, 1),
        "dense_gbs": round(w_dense.nbytes / 1e9 / t_dense, 1),
    }


def _compact_northstar(out: dict) -> dict:
    """A SMALL final record duplicating the north-star numbers. The
    driver keeps only the output tail, and BENCH_r04's single huge JSON
    line was truncated from the HEAD — losing the ResNet and b1 records
    (VERDICT r4 weak #4). The last printed line is this compact one, so
    whatever survives tail-capture always contains the headlines."""
    ex = out.get("extra", {})

    def g(key, *fields):
        d = ex.get(key) or {}
        if "error" in d:
            return {"error": str(d["error"])[:80]}
        r = {"v": d.get("value"), "unit": d.get("unit")}
        for f in fields:
            r[f] = (d.get("extra") or {}).get(f)
        return r

    ns = {
        "resnet_img_s": out.get("value"),
        "resnet_mfu": ex.get("mfu"),
        "resnet_hbm_gbs": ex.get("implied_hbm_gbs"),
        "llama_b1": g("llama_int4_decode", "step_ms"),
        "llama_b8": g("llama_int4_decode_b8", "step_ms"),
        "paged_b8": g("paged_decode", "step_ms", "compile_s",
                      "kv_pool_gb"),
        "bert": g("bert_finetune", "mfu"),
        "prefill_4k": g("llama_longctx_prefill"),
        "lenet_top1": g("lenet_convergence", "bayes_ref_top1", "in_band"),
        "cifar_top1": g("cifar_convergence", "bayes_ref_top1", "in_band"),
    }
    # ISSUE 4: per-depth live-engine decode step time (host overlap win)
    mb = ((ex.get("telemetry") or {}).get("microbench_decode") or {})
    if "error" in mb:
        ns["decode_pipeline"] = {"error": str(mb["error"])[:80]}
    else:
        ns["decode_pipeline"] = {
            k: (v or {}).get("step_ms") for k, v in mb.items()
            if k.startswith("depth")}
        if mb.get("speedup_vs_depth1") is not None:
            ns["decode_pipeline"]["speedup"] = mb["speedup_vs_depth1"]
    # ISSUE 5: prefix-cache headline — TTFT off/on + prefill tokens the
    # radix cache deleted on the shared-prompt workload
    pb = ((ex.get("telemetry") or {}).get("microbench_prefix") or {})
    if "error" in pb:
        ns["prefix_cache"] = {"error": str(pb["error"])[:80]}
    else:
        ns["prefix_cache"] = {
            "ttft_off_ms": (pb.get("cache_off") or {}).get("ttft_ms"),
            "ttft_on_ms": (pb.get("cache_on") or {}).get("ttft_ms"),
            "tokens_saved": pb.get("prefill_tokens_saved"),
            "speedup": pb.get("ttft_speedup"),
        }
    # ISSUE 6: host-tier headline — evicted chains served from the
    # arena instead of re-prefilled on the oversized working set
    tb = ((ex.get("telemetry") or {}).get("microbench_tier") or {})
    if "error" in tb:
        ns["kvtier"] = {"error": str(tb["error"])[:80]}
    else:
        ns["kvtier"] = {
            "ttft_off_ms": (tb.get("tier_off") or {}).get("ttft_ms"),
            "ttft_on_ms": (tb.get("tier_on") or {}).get("ttft_ms"),
            "tokens_saved": tb.get("prefill_tokens_saved_vs_off"),
            "fetches": (tb.get("tier_on") or {}).get("fetches"),
            "hit_rate": (tb.get("tier_on") or {}).get("hit_rate"),
        }
    # ISSUE 8: ragged-prefill headline — partial-prefill TTFT dense vs
    # in-place, and the dense-staging volume the ragged path deleted
    # (staged_on must stay 0)
    rb = ((ex.get("telemetry") or {}).get("microbench_ragged") or {})
    if "error" in rb:
        ns["ragged_prefill"] = {"error": str(rb["error"])[:80]}
    else:
        ns["ragged_prefill"] = {
            "ttft_off_ms": (rb.get("ragged_off") or {}).get("ttft_ms"),
            "ttft_on_ms": (rb.get("ragged_on") or {}).get("ttft_ms"),
            "staged_off": rb.get("dense_staged_tokens_off"),
            "staged_on": rb.get("dense_staged_tokens_on"),
            "speedup": rb.get("ttft_speedup"),
        }
    # ISSUE 14: unified-dispatch headline — the decode stream's p99
    # inter-token gap while a long prompt is admitted, split vs mixed
    # (the spike the chunked admission deletes), plus the TTFT trade
    xb = ((ex.get("telemetry") or {}).get("mixed_dispatch") or {})
    if "error" in xb:
        ns["mixed_dispatch"] = {"error": str(xb["error"])[:80]}
    else:
        ns["mixed_dispatch"] = {
            "itl_p99_off_ms": (xb.get("mixed_off") or {}).get(
                "itl_p99_ms"),
            "itl_p99_on_ms": (xb.get("mixed_on") or {}).get(
                "itl_p99_ms"),
            "ttft_off_ms": (xb.get("mixed_off") or {}).get("ttft_ms"),
            "ttft_on_ms": (xb.get("mixed_on") or {}).get("ttft_ms"),
            "chunks": (xb.get("mixed_on") or {}).get("chunks"),
            "p99_ratio": xb.get("itl_p99_ratio_off_on"),
        }
    # ISSUE 19: self-speculative decoding headline — batch-1 tok/s with
    # drafts verified in bulk vs plain decode, the accepted-tokens-per-
    # tick the ROADMAP bar is stated in, and the bit-parity verdict
    sb = ((ex.get("telemetry") or {}).get("spec_decode") or {})
    if "error" in sb:
        ns["spec_decode"] = {"error": str(sb["error"])[:80]}
    else:
        ns["spec_decode"] = {
            "tok_s_off": (sb.get("spec_off") or {}).get("tokens_per_s"),
            "tok_s_on": (sb.get("spec_on") or {}).get("tokens_per_s"),
            "accepted_per_tick": sb.get("accepted_tokens_per_tick"),
            "accept_rate": sb.get("accept_rate"),
            "speedup": sb.get("tokens_per_s_ratio"),
            "bit_identical": sb.get("bit_identical"),
        }
    # ISSUE 20: OpenAI-gateway headline — streaming TTFT through the
    # SSE leg vs the native stream, the gateway's added latency, and
    # the parity tally (must stay 0)
    ab = ((ex.get("telemetry") or {}).get("openai_api") or {})
    if "error" in ab:
        ns["api"] = {"error": str(ab["error"])[:80]}
    else:
        ns["api"] = {
            "ttft_direct_ms": ab.get("ttft_direct_p50_ms"),
            "ttft_gateway_ms": ab.get("ttft_gateway_p50_ms"),
            "overhead_ms": ab.get("gateway_overhead_ms"),
            "mismatches": ab.get("output_mismatches"),
        }
    return {"metric": out["metric"], "value": out["value"],
            "unit": out["unit"], "vs_baseline": out.get("vs_baseline"),
            "extra": {"northstar_summary": ns,
                      "note": "compact tail record; full record printed "
                              "on the line above"}}


def _telemetry_block() -> dict:
    """Snapshot of the observability registry + span distributions after
    the benches ran (the convergence benches drive the instrumented
    BaseOptimizer loop, so step-time histograms and loss/grad-norm
    gauges land here; see tools/telemetry_report.py). Also folds in one
    seeded chaos smoke run (tools/chaos_check.py): injected faults must
    recover to the clean run's final loss, and its reliability counters
    land in the same registry snapshot."""
    from bigdl_tpu import observability as obs
    from tools.telemetry_report import (summarize_registry,
                                        summarize_trace)
    # snapshot the bench telemetry FIRST: the chaos smoke trains its own
    # tiny model through the instrumented loop and must not pollute the
    # step-time/loss numbers this block reports for the benches
    out = {
        "metrics": summarize_registry(),
        "spans": summarize_trace(
            {"traceEvents": obs.TRACE.spans()})["spans"],
        # ISSUE 3 flight recorder: per-jit-entry-point compile history
        # (count, seconds, cost/memory analysis, recompile signatures)
        # — the MFU numbers above are attributed to these executables
        "compiles": obs.compile_stats(),
    }
    # ISSUE 16: arm the decision-event flight recorder for the
    # serving-driven microbenches below — the same gate enables the
    # per-dispatch wall-time sampler whose join with the obs.compiled
    # cost analyses yields the live roofline block captured at the end.
    # Restored before return so the gate stays default-off elsewhere.
    from bigdl_tpu.observability import utilization
    from bigdl_tpu.utils.conf import conf as _conf
    _flight_prior = _conf.get("bigdl.observability.flight.enabled")
    _conf.set("bigdl.observability.flight.enabled", "true")
    try:
        # ISSUE 7 satellite: every chaos suite in one block — train
        # recovery, kvcache eviction races, kvtier migration faults,
        # and the router kill-storm (zero lost requests, bit-identical
        # resume). One record per pass; a failing pass lands as an
        # error entry without hiding the others.
        from tools.chaos_check import run_all_chaos
        out["chaos_all"] = run_all_chaos(seed=0)
    except Exception as e:  # never lose the telemetry to the chaos run
        out["chaos_all"] = {"error": repr(e)}
    try:
        # ISSUE 11: the static-analysis gate summary — finding counts
        # by rule, zero-unbaselined verdict, baseline hygiene — lands
        # in every bench round (+ one PROGRESS.jsonl breadcrumb) so
        # finding-count drift across PRs is visible in telemetry
        out["static_analysis"] = _static_analysis_block()
    except Exception as e:
        out["static_analysis"] = {"error": repr(e)}
    try:
        # ISSUE 4: live-engine decode latency across pipeline depths —
        # the host-overlap win (and its host/stall attribution) lands in
        # every bench round next to the device-side decode numbers
        from tools.microbench_decode import run_microbench
        out["microbench_decode"] = run_microbench(
            depths=(1, 2, 4), batch=4, tokens=24)
    except Exception as e:
        out["microbench_decode"] = {"error": repr(e)}
    try:
        # ISSUE 5: shared-system-prompt replay with the prefix cache
        # off/on — TTFT and prefill-tokens-saved (bench_regress diffs
        # the ttft_ms pair across rounds)
        from tools.microbench_prefix import run_prefix_bench
        out["microbench_prefix"] = run_prefix_bench()
    except Exception as e:
        out["microbench_prefix"] = {"error": repr(e)}
    try:
        # ISSUE 6: working set sized past the HBM pool, tier off/on —
        # host-arena fetches must reappear as deleted prefill tokens
        # (bench_regress diffs the ttft_ms pair and the savings)
        from tools.microbench_tier import run_tier_bench
        out["microbench_tier"] = run_tier_bench()
    except Exception as e:
        out["microbench_tier"] = {"error": repr(e)}
    try:
        # ISSUE 8: partial-prefill TTFT + dense-staging volume with the
        # ragged in-place prefill off/on across prefix/suffix ratios —
        # the ragged path must stage ZERO tokens through a dense temp
        # cache (bench_regress diffs the ttft_ms pair and the staged
        # tally)
        from tools.microbench_ragged import run_ragged_bench
        out["microbench_ragged"] = run_ragged_bench()
    except Exception as e:
        out["microbench_ragged"] = {"error": repr(e)}
    try:
        # ISSUE 14: mixed-load microbench — steady decode streams with
        # a long admission mid-run, unified dispatch off/on. The p99
        # inter-token spike the split engine pays for the admission
        # must be gone in the on mode (bench_regress diffs
        # mixed.itl_p99_ms / mixed.ttft_ms and the off/on pairs)
        from tools.microbench_mixed import run_mixed_bench
        out["mixed_dispatch"] = run_mixed_bench(
            prompt_len=192, stream_tokens=24)
    except Exception as e:
        out["mixed_dispatch"] = {"error": repr(e)}
    try:
        # ISSUE 19: self-speculative decoding on/off — batch-1 tok/s on
        # a repetitive-suffix workload, accepted-tokens/tick and the
        # bit-parity verdict (bench_regress diffs spec.tokens_per_s /
        # spec.accept_rate and the off/on itl_p99 pair)
        from tools.microbench_decode import run_spec_bench
        out["spec_decode"] = run_spec_bench(tokens=48)
    except Exception as e:
        out["spec_decode"] = {"error": repr(e)}
    try:
        # ISSUE 12: the fleet telemetry plane — two live workers behind
        # a federation+SLO router; merged sketch percentiles
        # (ttft_p50/p95/p99_ms, itl_p99_ms — bench_regress diffs them)
        # plus the counter-additivity verdict
        from tools.fleet_report import run_fleet_micro
        out["fleet"] = run_fleet_micro()
    except Exception as e:
        out["fleet"] = {"error": repr(e)}
    try:
        # ISSUE 15: the elastic-fleet soak — spike -> autoscaler
        # scale-out -> graceful drain-and-scale-in, fault-free. The
        # numbers the fleet is judged on land in every round: p99
        # TTFT/ITL under soak (SLO sketch windows), requests lost
        # (must stay 0) and the scale-event counts (bench_regress
        # diffs fleet_elastic.*; the killing variant runs inside
        # chaos_all above)
        from tools.loadgen import run_fleet_soak
        out["fleet_elastic"] = run_fleet_soak()
    except Exception as e:
        out["fleet_elastic"] = {"error": repr(e)}
    try:
        # ISSUE 20: the OpenAI gateway — client-visible streaming TTFT
        # through /v1/completions SSE vs the native stream on the same
        # seeded prompts, and the gateway's added latency. The
        # output_mismatches tally must pin at 0 (bench_regress diffs
        # api.ttft_gateway_p50_ms / api.gateway_overhead_ms)
        from tools.loadgen import run_openai_bench
        out["openai_api"] = run_openai_bench()
    except Exception as e:
        out["openai_api"] = {"error": repr(e)}
    try:
        # ISSUE 18: the time-series plane — windowed-store sampling
        # cost over the live post-bench registry (every series the
        # benches above created, so the number tracks real cardinality)
        # plus one default-rule evaluation pass. bench_regress lifts
        # ts.sample_overhead_us / alerts.transitions: overhead creeping
        # up means snapshot cost regressed; transitions going nonzero
        # means the bench round itself tripped an SLO page
        out["alerts"] = _alerts_block()
    except Exception as e:
        out["alerts"] = {"error": repr(e)}
    try:
        # ISSUE 16: the live roofline — per-dispatch wall time sampled
        # while the serving microbenches above ran, joined with the
        # XLA cost analyses into achieved GB/s, MFU and bandwidth
        # utilization plus the per-program table (bench_regress lifts
        # util.mfu / util.hbm_bw_gbps; on real TPU the headline
        # hbm_bw_gbps should land near the decode bench's
        # implied_hbm_gbs weight-stream lower bound)
        out["utilization"] = utilization.snapshot()
    except Exception as e:
        out["utilization"] = {"error": repr(e)}
    finally:
        if _flight_prior is None:
            _conf.unset("bigdl.observability.flight.enabled")
        else:
            _conf.set("bigdl.observability.flight.enabled", _flight_prior)
    return out


def _alerts_block() -> dict:
    """ISSUE 18 micro-measurement: periodic-sampler overhead against
    the full live registry and one alert-engine pass over the built-in
    burn-rate rules. The gate is raised only for the measurement and
    restored on the way out (the plane stays default-off elsewhere)."""
    from bigdl_tpu.observability import alerts as _alerts
    from bigdl_tpu.observability import timeseries as _ts
    from bigdl_tpu.utils.conf import conf as _conf
    keys = ("bigdl.observability.timeseries.enabled",
            "bigdl.observability.timeseries.interval")
    prior = {k: _conf.get(k) for k in keys}
    _conf.set("bigdl.observability.timeseries.enabled", "true")
    # park the background thread: the synchronous samples below are the
    # measurement, a concurrent wall-clock tick would just add noise
    _conf.set("bigdl.observability.timeseries.interval", "3600")
    try:
        st = _ts.acquire()
        if st is None:
            return {"error": "store unavailable (observability off?)"}
        overheads = []
        for _ in range(8):
            st.sample_now()
            overheads.append(st.last_overhead_us)
        eng = _alerts.engine()
        if eng is not None:
            eng.evaluate(st.clock())
        status = st.status()
        overheads.sort()
        return {
            "sample_overhead_us": round(
                overheads[len(overheads) // 2], 1),
            "sample_overhead_max_us": round(overheads[-1], 1),
            "samples": status["samples"],
            "rules": len(eng.rules) if eng is not None else 0,
            "evaluations": eng.evaluations if eng is not None else 0,
            "transitions": eng.transitions if eng is not None else 0,
            "firing": eng.firing() if eng is not None else [],
        }
    finally:
        _ts.release()
        for k in keys:
            if prior[k] is None:
                _conf.unset(k)
            else:
                _conf.set(k, prior[k])


def _static_analysis_block() -> dict:
    """Run the ISSUE 11 analyzer over the repo and compress its record
    to the counts worth tracking round-over-round; append one
    breadcrumb line to PROGRESS.jsonl (the bench_regress idiom)."""
    import json as _json
    import os
    import time as _time
    from bigdl_tpu.analysis import check as static_check
    root = os.path.dirname(os.path.abspath(__file__))
    sa = static_check(root)
    block = {"ok": sa["ok"], "by_rule": sa["by_rule"],
             # per-pass finding counts (ISSUE 13): bench_regress diffs
             # these so a finding-count regression in any one pass
             # (donation/gatecheck/httpdrift included) is a visible
             # delta in PROGRESS.jsonl, not a buried by_rule reshuffle
             "by_pass": sa.get("by_pass", {}),
             "new": len(sa["new"]), "suppressed": sa["suppressed"],
             "stale_baseline": len(sa["stale_baseline"]),
             "baseline_errors": len(sa["baseline_errors"])}
    try:
        with open(os.path.join(root, "PROGRESS.jsonl"), "a") as f:
            f.write(_json.dumps({"ts": _time.time(),
                                 "kind": "static_analysis",
                                 **block}) + "\n")
    except OSError:
        pass                      # the breadcrumb never fails the bench
    return block


def _regress_block() -> dict:
    """Optional north-star regression diff (ISSUE 3 satellite): compare
    the newest two driver-recorded BENCH_r*.json rounds and flag moves
    past the warn threshold; one compact breadcrumb line is appended to
    PROGRESS.jsonl. Never fails the bench."""
    import os
    root = os.path.dirname(os.path.abspath(__file__))
    try:
        from tools.bench_regress import compare_latest
        out = compare_latest(
            root, progress_path=os.path.join(root, "PROGRESS.jsonl"))
        if out is None:
            return {"note": "fewer than two BENCH_r*.json rounds"}
        # compact: the full per-metric table is reproducible offline via
        # tools/bench_regress.py; the record keeps only the verdict
        return {"base": out["base"], "head": out["head"],
                "warn_pct": out["warn_pct"],
                "metrics": len(out["deltas"]), "warned": out["warned"]}
    except Exception as e:
        return {"error": repr(e)}


def _default_run(quick: bool) -> dict:
    """The driver-captured output: resnet headline + llama decode +
    kernel micro-bench folded into one JSON object."""
    from bigdl_tpu import observability as obs
    if quick:
        with obs.span("bench/resnet"):
            out = bench_resnet50_train(batch_size=4, warmup=1, iters=5,
                                       image=64, depth=18, classes=100,
                                       smoke=True, format="NCHW",
                                       remat=False)
        try:
            with obs.span("bench/llama_int4_decode"):
                out["extra"]["llama_int4_decode"] = \
                    bench_llama_int4_decode(model_size="tiny", smoke=True)
        except Exception as e:  # never lose the headline to a side metric
            out["extra"]["llama_int4_decode"] = {"error": repr(e)}
        try:
            with obs.span("bench/paged_decode"):
                out["extra"]["paged_decode"] = bench_paged_decode_step(
                    model_size="tiny", batch=2, ctx_len=32)
        except Exception as e:
            out["extra"]["paged_decode"] = {"error": repr(e)}
        try:
            out["extra"]["telemetry"] = _telemetry_block()
        except Exception as e:
            out["extra"]["telemetry"] = {"error": repr(e)}
        out["extra"]["regress"] = _regress_block()
        return out
    out = bench_resnet50_train()
    try:
        out["extra"]["llama_int4_decode"] = bench_llama_int4_decode()
    except Exception as e:
        out["extra"]["llama_int4_decode"] = {"error": repr(e)}
    try:
        out["extra"]["llama_int4_decode_b8"] = bench_llama_int4_decode(
            batch=8)
    except Exception as e:
        out["extra"]["llama_int4_decode_b8"] = {"error": repr(e)}
    try:
        out["extra"]["paged_decode"] = bench_paged_decode_step()
    except Exception as e:
        out["extra"]["paged_decode"] = {"error": repr(e)}
    try:
        out["extra"]["int4_kernel_micro"] = bench_int4_kernel_micro()
    except Exception as e:
        out["extra"]["int4_kernel_micro"] = {"error": repr(e)}
    try:
        out["extra"]["bert_finetune"] = bench_bert_finetune()
    except Exception as e:
        out["extra"]["bert_finetune"] = {"error": repr(e)}
    try:
        out["extra"]["llama_longctx_prefill"] = bench_llama_longctx_prefill()
    except Exception as e:
        out["extra"]["llama_longctx_prefill"] = {"error": repr(e)}
    try:
        out["extra"]["lenet_convergence"] = bench_lenet_convergence()
    except Exception as e:
        out["extra"]["lenet_convergence"] = {"error": repr(e)}
    try:
        out["extra"]["cifar_convergence"] = bench_cifar_convergence()
    except Exception as e:
        out["extra"]["cifar_convergence"] = {"error": repr(e)}
    try:
        out["extra"]["telemetry"] = _telemetry_block()
    except Exception as e:
        out["extra"]["telemetry"] = {"error": repr(e)}
    out["extra"]["regress"] = _regress_block()
    return out


if __name__ == "__main__":
    import os
    import sys

    if "--cpu" in sys.argv or os.environ.get("BIGDL_TPU_BENCH_CPU"):
        # sitecustomize pins the axon TPU platform; env JAX_PLATFORMS is
        # ineffective — the in-process config update is the working override
        import jax
        jax.config.update("jax_platforms", "cpu")
    if "--profile" in sys.argv:
        import jax
        jax.profiler.start_trace("/tmp/bigdl_tpu_trace")
    quick = "--quick" in sys.argv or bool(os.environ.get(
        "BIGDL_TPU_BENCH_QUICK"))
    if "--tpu-smoke" in sys.argv:
        # on-hardware Pallas kernel smoke suite (tests_tpu/): real Mosaic
        # lowering with production tile sizes — see tests_tpu/conftest.py
        import subprocess
        root = os.path.dirname(os.path.abspath(__file__))
        env = dict(os.environ)
        env["PYTHONPATH"] = root + os.pathsep + env.get("PYTHONPATH", "")
        rc = subprocess.call(
            [sys.executable, "-m", "pytest",
             os.path.join(root, "tests_tpu"), "-q"], env=env)
        print(json.dumps({"metric": "tpu_smoke_suite",
                          "value": 1 if rc == 0 else 0,
                          "unit": "pass", "vs_baseline": None,
                          "extra": {"pytest_rc": rc}}))
        if "--profile" in sys.argv:
            import jax
            jax.profiler.stop_trace()
        sys.exit(rc)
    if "--lenet" in sys.argv:
        print(json.dumps(bench_lenet_train()))
    elif "--paged" in sys.argv:
        if quick:
            print(json.dumps(bench_paged_decode_step(
                model_size="tiny", batch=2, ctx_len=32)))
        else:
            print(json.dumps(bench_paged_decode_step()))
    elif "--llama" in sys.argv:
        if quick:
            print(json.dumps(bench_llama_int4_decode(
                model_size="tiny", smoke=True)))
        else:
            print(json.dumps(bench_llama_int4_decode()))
    elif "--kernels" in sys.argv:
        print(json.dumps(bench_int4_kernel_micro()))
    elif "--bert" in sys.argv:
        print(json.dumps(bench_bert_finetune(smoke=quick)))
    else:
        res = _default_run(quick)
        print(json.dumps(res))
        print(json.dumps(_compact_northstar(res)))
    if "--profile" in sys.argv:
        import jax
        jax.profiler.stop_trace()

"""Static-analysis CI gate (ISSUE 11 + 13): run the six AST passes over
``bigdl_tpu/`` and fail on any finding the checked-in baseline does not
suppress.

Usage:
    python tools/check_static.py                  # the gate: 0 = clean
    python tools/check_static.py --json           # machine-readable
    python tools/check_static.py --only donation  # one pass (triage)
    python tools/check_static.py --passes hotpath,gatecheck
    python tools/check_static.py --sarif          # SARIF 2.1.0 -> stdout
    python tools/check_static.py --sarif-out f.sarif
    python tools/check_static.py --write-baseline --justify "..."
                                                  # absorb current NEW
                                                  # findings (triage!)
    python tools/check_static.py --prune          # drop stale entries
    python tools/check_static.py --dump-graph     # static lock graph
    python tools/check_static.py --strict         # stale baseline fails

Exit codes: 0 clean; 1 unbaselined findings; 2 baseline hygiene errors
(missing justification / duplicates); 3 stale baseline under --strict.

The analyzer imports nothing from the analyzed code — this script
loads ``bigdl_tpu/analysis`` as a standalone package, so the gate runs
without jax in a few seconds (CI pre-commit friendly; all six passes
share one parsed-AST index, see ``analysis.run_analysis``). The SARIF
output carries rule ids, file:line regions, the stable fingerprint and
— for baselined findings — a suppression with the triage justification,
so CI can annotate diffs.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
# load the analysis package WITHOUT importing bigdl_tpu/__init__ (which
# pulls jax): the package uses relative imports precisely for this
sys.path.insert(0, os.path.join(_ROOT, "bigdl_tpu"))
import analysis                                        # noqa: E402
from analysis.baseline import Baseline                 # noqa: E402


def main():
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--root", default=_ROOT)
    ap.add_argument("--baseline", default=None,
                    help="baseline path (default "
                         "bigdl_tpu/analysis/baseline.json)")
    ap.add_argument("--passes", default=",".join(analysis.PASSES),
                    help="comma-separated subset of "
                         f"{analysis.PASSES}")
    ap.add_argument("--only", default=None, metavar="PASS",
                    help="run a single pass (triage shorthand for "
                         "--passes PASS)")
    ap.add_argument("--json", action="store_true",
                    help="print the full summary record as JSON")
    ap.add_argument("--sarif", action="store_true",
                    help="print SARIF 2.1.0 to stdout instead of the "
                         "human summary")
    ap.add_argument("--sarif-out", default=None, metavar="PATH",
                    help="also write SARIF 2.1.0 to PATH")
    ap.add_argument("--write-baseline", action="store_true",
                    help="add every currently-NEW finding to the "
                         "baseline (requires --justify)")
    ap.add_argument("--justify", default="",
                    help="justification string for --write-baseline")
    ap.add_argument("--prune", action="store_true",
                    help="rewrite the baseline without stale entries")
    ap.add_argument("--strict", action="store_true",
                    help="stale baseline entries fail the gate")
    ap.add_argument("--dump-graph", action="store_true",
                    help="print the static lock-order graph "
                         "(adjacency JSON) and exit")
    args = ap.parse_args()

    if args.dump_graph:
        from analysis.concurrency import lock_graph
        idx = analysis.build_index(args.root)
        print(json.dumps(lock_graph(idx), indent=1))
        return 0

    if args.only:
        if args.only not in analysis.PASSES:
            print(f"--only {args.only!r}: unknown pass "
                  f"(choose from {analysis.PASSES})", file=sys.stderr)
            return 2
        passes = (args.only,)
    else:
        passes = tuple(p.strip() for p in args.passes.split(",")
                       if p.strip())
    baseline_path = args.baseline or os.path.join(
        args.root, analysis.BASELINE_RELPATH)

    if args.write_baseline:
        if not args.justify.strip():
            print("--write-baseline requires --justify 'why these are "
                  "acceptable' (triage, don't bulk-silence)",
                  file=sys.stderr)
            return 2
        findings = analysis.run_analysis(args.root, passes=passes)
        bl = Baseline.load(baseline_path)
        new, _, _ = bl.split(findings)
        bl.add_findings(new, args.justify.strip())
        bl.save(baseline_path)
        print(f"baselined {len(new)} finding(s) -> {baseline_path}")
        return 0

    findings = None
    if args.sarif or args.sarif_out:
        # one analysis run feeds both the summary and the SARIF view
        findings = analysis.run_analysis(args.root, passes=passes)
    out = analysis.check(args.root, baseline_path=baseline_path,
                         passes=passes, findings=findings)

    if args.prune and out["stale_baseline"]:
        bl = Baseline.load(baseline_path)
        bl.prune(out["stale_baseline"])
        bl.save(baseline_path)
        print(f"pruned {len(out['stale_baseline'])} stale baseline "
              f"entr(y/ies)")
        out["stale_baseline"] = []

    if args.sarif or args.sarif_out:
        doc = _sarif(args.root, passes, baseline_path, findings)
        if args.sarif_out:
            with open(args.sarif_out, "w") as f:
                json.dump(doc, f, indent=1)
                f.write("\n")
        if args.sarif:
            print(json.dumps(doc, indent=1))
    if args.json:
        print(json.dumps(out, indent=1))
    elif not args.sarif:
        _print_human(out)

    if out["baseline_errors"]:
        return 2
    if out["new"]:
        return 1
    if args.strict and out["stale_baseline"]:
        return 3
    return 0


def _sarif(root: str, passes, baseline_path: str,
           findings=None) -> dict:
    """Minimal SARIF 2.1.0: one run, one result per finding. Baselined
    findings carry a ``suppressions`` entry whose justification is the
    triage note from baseline.json — CI diff annotators can show new
    findings loud and suppressed ones dimmed."""
    if findings is None:
        findings = analysis.run_analysis(root, passes=passes)
    bl = Baseline.load(baseline_path)
    rules = sorted({f.rule for f in findings} |
                   {r for p in passes
                    for r in analysis.PASS_RULES.get(p, ())})
    results = []
    for f in findings:
        entry = bl.entries.get(f.fingerprint)
        res = {
            "ruleId": f.rule,
            "level": "note" if entry else "warning",
            "message": {"text": f.message},
            "locations": [{
                "physicalLocation": {
                    "artifactLocation": {"uri": f.file},
                    "region": {"startLine": max(f.line, 1)},
                }}],
            "fingerprints": {"bigdlAnalysis/v1": f.fingerprint},
        }
        if entry:
            res["suppressions"] = [{
                "kind": "external",
                "justification": entry.justification}]
        results.append(res)
    return {
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "version": "2.1.0",
        "runs": [{
            "tool": {"driver": {
                "name": "bigdl-tpu-check-static",
                "informationUri": "docs/ANALYSIS.md",
                "rules": [{"id": r,
                           "properties": {
                               "pass": analysis.RULE_TO_PASS.get(r, "")}}
                          for r in rules],
            }},
            "results": results,
        }],
    }


def _print_human(out: dict):
    print(f"check_static: {out['total']} finding(s) total, "
          f"{out['suppressed']} baselined, {len(out['new'])} NEW")
    if out.get("by_pass"):
        print("  per pass: " + "  ".join(
            f"{p}={n}" for p, n in out["by_pass"].items()))
    if out["by_rule"]:
        width = max(len(r) for r in out["by_rule"])
        for rule, n in out["by_rule"].items():
            print(f"  {rule:<{width}}  {n}")
    for f in out["new"]:
        print(f"NEW {f['rule']}: {f['file']}:{f['line']}: "
              f"{f['message']}")
    for err in out["baseline_errors"]:
        print(f"BASELINE ERROR: {err}")
    for fp in out["stale_baseline"]:
        print(f"stale baseline entry (no longer fires): {fp}")
    if out["new"]:
        print("\nFix the finding, or triage it into "
              f"{out['baseline_path']} with a justification "
              "(tools/check_static.py --write-baseline --justify ...).")
    else:
        print("gate clean: zero unbaselined findings")


if __name__ == "__main__":
    sys.exit(main())

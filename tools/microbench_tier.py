#!/usr/bin/env python
"""Tiered-KV microbench (ISSUE 6 satellite): replay a shared-prefix
working set sized PAST the HBM page pool, tier off vs on.

The workload prefix caching alone cannot save: ``n_groups`` distinct
system prompts whose chains together exceed the device pool, served in
two passes. Pass 1 seeds every chain (later groups LRU-evict earlier
ones); pass 2 re-requests each group with a fresh user tail. With the
tier **off** the evicted chains are gone — pass 2 re-prefills them.
With it **on** they spilled to the host arena and pass 2 admissions
fetch them back asynchronously. What it reports per mode:

- ``ttft_ms`` / ``ttft_p50_ms`` on the replay pass (the always-on
  ``Request.t_submit``/``t_first_token`` stamps);
- ``prefill_tokens`` on the replay pass — the compute the tier deleted;
- ``hit_rate`` (admission hits / requests) on the replay pass;
- tier on only: ``spills``/``fetches``/``fetch_failures`` and
  ``prefill_tokens_saved`` (must be > 0 for the tier to have mattered —
  the acceptance assertion rides these numbers).

Wired into ``bench.py``'s telemetry block (``telemetry.
microbench_tier``) and the compact northstar line (``kvtier``);
``tools/bench_regress.py`` diffs the ``ttft_ms`` pair across rounds.
Standalone:

    python tools/microbench_tier.py                  # tiny model
    python tools/microbench_tier.py --groups 8 --shared-len 64 --json
"""

from __future__ import annotations

import json
import os
import sys
from typing import Dict, Optional

# runnable both as `python tools/microbench_tier.py` and as an import
sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))


def run_tier_bench(n_groups: int = 5, shared_len: int = 32,
                   tail_len: int = 4, new_tokens: int = 3,
                   page_size: int = 8, pipeline_depth: int = 2,
                   model=None) -> Dict:
    """Two-pass shared-prefix replay over a pool sized for ~2 of the
    ``n_groups`` chains, tier off vs on. One untimed warmup request per
    mode absorbs the compile cost of each prefill bucket."""
    import numpy as np

    from bigdl_tpu.llm.models.llama import LlamaConfig, LlamaForCausalLM
    from bigdl_tpu.llm.serving import LLMServer

    if model is None:
        model = LlamaForCausalLM.from_config(LlamaConfig.tiny(), seed=0,
                                             max_cache_len=256)
    rs = np.random.RandomState(0)
    vocab = model.config.vocab_size
    groups = [rs.randint(0, vocab, shared_len).astype(np.int32)
              for _ in range(n_groups)]

    def prompt(g, j):
        return np.concatenate([groups[g],
                               rs.randint(0, vocab, tail_len)
                               .astype(np.int32)])

    max_seq = min(shared_len + tail_len + new_tokens + 2,
                  model.config.max_position_embeddings)
    per_chain = -(-(shared_len + tail_len + new_tokens) // page_size)
    # the crux: room for ~2 chains, so n_groups > 2 forces eviction —
    # the tier-on run must spill instead of dropping
    num_pages = 1 + 2 * per_chain + 2
    out: Dict = {"groups": n_groups, "shared_len": shared_len,
                 "tail_len": tail_len, "new_tokens": new_tokens,
                 "page_size": page_size, "num_pages": num_pages}
    for mode, key in ((False, "tier_off"), (True, "tier_on")):
        srv = LLMServer(model, max_batch=2, max_seq_len=max_seq,
                        page_size=page_size, num_pages=num_pages,
                        kvcache=True, kvtier=mode,
                        host_pages=4 * num_pages if mode else None,
                        pipeline_depth=pipeline_depth).start()
        try:
            # warmup compiles every bucket both passes will touch
            srv.submit(prompt(0, -1),
                       max_new_tokens=new_tokens).get(timeout=600)
            # pass 1: seed every group's chain (evictions happen here)
            for g in range(n_groups):
                srv.submit(prompt(g, 0),
                           max_new_tokens=new_tokens).get(timeout=600)
            if mode:
                # let in-flight spills land, then run ONE untimed
                # fetch-path replay: the partial-prefill bucket a
                # host-tier hit compiles (suffix length × fetched-page
                # count) first appears here, and the timed pass must
                # not carry that compile
                srv._tier.migrator.drain()
                srv.submit(prompt(0, -2),
                           max_new_tokens=new_tokens).get(timeout=600)
                srv._tier.migrator.drain()
            tokens0 = srv.prefill_tokens_total
            hits0 = srv._kv.hits
            saved0 = srv.prefix_tokens_saved
            # pass 2: replay each group with a fresh tail
            ttfts = []
            for g in range(n_groups):
                req = srv.submit(prompt(g, 1),
                                 max_new_tokens=new_tokens)
                req.get(timeout=600)
                ttfts.append((req.t_first_token - req.t_submit) * 1e3)
            d = {
                "ttft_ms": round(float(np.mean(ttfts)), 3),
                "ttft_p50_ms": round(float(np.median(ttfts)), 3),
                "prefill_tokens": srv.prefill_tokens_total - tokens0,
                "hit_rate": round((srv._kv.hits - hits0) / n_groups, 3),
                "evictions": srv._kv.evictions,
            }
            if mode:
                d["spills"] = srv._tier.spills
                d["fetches"] = srv._tier.fetches
                d["fetch_failures"] = srv._tier.fetch_failures
                d["host_pages_used"] = srv._tier.arena.used()
                out["prefill_tokens_saved"] = (srv.prefix_tokens_saved
                                               - saved0)
            out[key] = d
        finally:
            srv.stop()
    off, on = out["tier_off"], out["tier_on"]
    out["prefill_tokens_saved_vs_off"] = (off["prefill_tokens"]
                                          - on["prefill_tokens"])
    if on["ttft_ms"]:
        out["ttft_speedup"] = round(off["ttft_ms"] / on["ttft_ms"], 3)
    return out


def main(argv) -> int:
    def flag(name: str, default: Optional[str] = None):
        if name in argv:
            return argv[argv.index(name) + 1]
        return default

    out = run_tier_bench(
        n_groups=int(flag("--groups", "5")),
        shared_len=int(flag("--shared-len", "32")),
        tail_len=int(flag("--tail-len", "4")),
        new_tokens=int(flag("--new-tokens", "3")),
        page_size=int(flag("--page-size", "8")),
        pipeline_depth=int(flag("--depth", "2")))
    if "--json" in argv:
        print(json.dumps(out))
        return 0
    print(f"tier microbench: {out['groups']} groups, shared "
          f"{out['shared_len']} + tail {out['tail_len']} tokens, "
          f"pool {out['num_pages']} pages")
    for key in ("tier_off", "tier_on"):
        d = out[key]
        extra = (f"  spills={d['spills']} fetches={d['fetches']}"
                 if "spills" in d else "")
        print(f"  {key:<9} ttft={d['ttft_ms']:>8.3f} ms  "
              f"(p50 {d['ttft_p50_ms']:.3f})  "
              f"prefill_tokens={d['prefill_tokens']}  "
              f"hit_rate={d['hit_rate']}{extra}")
    print(f"  prefill tokens saved vs off: "
          f"{out['prefill_tokens_saved_vs_off']}  "
          f"ttft speedup: {out.get('ttft_speedup', 'n/a')}x")
    return 0


if __name__ == "__main__":
    sys.exit(main(sys.argv[1:]))
